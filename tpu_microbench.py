"""Pallas-on-real-TPU microbenchmark.

Proves Mosaic lowering of the two product pallas kernels
(``fused_moments`` and ``bin_matrix``, parallel/pallas_kernels.py) on an
actual chip and records wall-clocks vs their jitted-jnp fallbacks at the
scale the round-1 commit claimed (1M x 512).  Prints ONE JSON line.

Run via tpu_probe.py when the axon tunnel is healthy; safe to run by hand.
"""
from __future__ import annotations

import json
import os
import sys
import time


def _timeit(fn, *args, reps: int = 5, **kw) -> float:
    """Median wall-clock of fn(*args) with block_until_ready, after one
    warmup call (compilation excluded)."""
    import jax

    out = fn(*args, **kw)
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def main() -> int:
    t_start = time.time()
    import jax
    import jax.numpy as jnp

    from transmogrifai_tpu.parallel import pallas_kernels as pk

    dev = jax.devices()[0]
    try:
        import subprocess as _sp

        _git = _sp.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        bench_commit = _git.stdout.strip() or "unknown"
    except Exception:
        bench_commit = "unknown"
    result = {
        "metric": "pallas_microbench",
        "bench_commit": bench_commit,
        "platform": jax.default_backend(),
        "device": str(getattr(dev, "device_kind", dev)),
        "n_devices": jax.device_count(),
        "unit": "seconds",
    }
    on_tpu = result["platform"] == "tpu"
    result["mosaic_lowering"] = on_tpu  # interpret=False only on real tpu

    # TPU-sized; off-chip runs shrink to a smoke test of the same paths
    # (the recorded artifact only matters when platform == tpu)
    n, d = (1_000_000, 512) if on_tpu else (100_000, 128)
    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (n, d), jnp.float32)
    y = (jax.random.uniform(ky, (n,)) > 0.5).astype(jnp.float32)
    jax.block_until_ready((x, y))
    result["rows"] = n
    result["dims"] = d

    # -- bandwidth calibration: a pure one-pass read of x -----------------
    # (sanity anchor: no moments measurement can beat this; if one does,
    # the timing methodology is broken, not the kernel fast)
    sum_fn = jax.jit(lambda a: jnp.sum(a, dtype=jnp.float32))
    t_read = _timeit(sum_fn, x, reps=11)
    result["read_sum_s"] = round(t_read, 6)
    result["read_gbps"] = round(n * d * 4 / t_read / 1e9, 1)

    # -- fused_moments: pallas vs fused-jnp fallback ----------------------
    t_pallas = _timeit(pk.fused_moments, x, y, True, reps=11)
    t_jnp = _timeit(pk.fused_moments, x, y, False, reps=11)
    # parity check on device (sums agree to float32 tolerance)
    mp = pk.fused_moments(x, y, True)
    mj = pk.fused_moments(x, y, False)
    import numpy as np

    mom_err = max(
        float(np.max(np.abs((np.asarray(a) - np.asarray(b))
                            / (np.abs(np.asarray(b)) + 1.0))))
        for a, b in zip(mp, mj)
    )
    # soundness: a moments pass reads x exactly once, so NEITHER timing
    # may imply more bandwidth than the pure-read anchor (r3's capture
    # recorded 1387 GB/s "achieved" on a chip whose HBM tops out lower -
    # both its timings were invalid).  15% grace for timer noise.
    gbps_pallas = n * d * 4 / t_pallas / 1e9
    gbps_jnp = n * d * 4 / t_jnp / 1e9
    sound = (
        gbps_pallas <= result["read_gbps"] * 1.15
        and gbps_jnp <= result["read_gbps"] * 1.15
    )
    result.update(
        moments_pallas_s=round(t_pallas, 6),
        moments_jnp_s=round(t_jnp, 6),
        moments_speedup=round(t_jnp / t_pallas, 3),
        moments_rel_err=float(f"{mom_err:.3e}"),
        # one HBM pass over x: n*d*4 bytes / wall = achieved bandwidth
        moments_gbps=round(gbps_pallas, 1),
        moments_jnp_gbps=round(gbps_jnp, 1),
        moments_timing_sound=sound,
        # the shipped default is the measured winner (fused_moments
        # defaults to jnp until a SOUND capture shows pallas ahead)
        moments_winner=("pallas" if t_pallas < t_jnp else "jnp"),
    )

    # -- bin_matrix: pallas vs jnp comparison-count fallback --------------
    n_edges = 63
    qs = jnp.linspace(0.0, 1.0, n_edges + 2)[1:-1]
    edges = jnp.quantile(x[:65536], qs, axis=0).T  # [d, E]
    jax.block_until_ready(edges)
    t_bpallas = _timeit(pk.bin_matrix, x, edges, True)
    t_bjnp = _timeit(pk.bin_matrix, x, edges, False)
    bp = pk.bin_matrix(x[:65536], edges, True)
    bj = pk.bin_matrix(x[:65536], edges, False)
    result.update(
        bin_pallas_s=round(t_bpallas, 6),
        bin_jnp_s=round(t_bjnp, 6),
        bin_speedup=round(t_bjnp / t_bpallas, 3),
        bin_parity=bool((np.asarray(bp) == np.asarray(bj)).all()),
        bin_rows_per_s=round(n / t_bpallas, 1),
        # binning reads x once and writes [n, d] ids: implied traffic must
        # stay under ~2 passes of the read anchor
        bin_timing_sound=bool(
            (n * d * 8 / t_bpallas / 1e9) <= result["read_gbps"] * 2.3
        ),
        bin_winner=("pallas" if t_bpallas < t_bjnp else "jnp"),
    )

    # -- packed vs vmap batched LR fit (the round-4 MXU packing) ----------
    # (models/packed_newton.py: the CV fan-out Gram as [d,n]@[n,B*d]
    # packed matmuls vs the [B,d,d] batched-vmap form - this records the
    # on-chip speedup behind the synth_cv_mfu target)
    try:
        from transmogrifai_tpu.models.logistic_regression import (
            _lr_fit_batched,
        )
        from transmogrifai_tpu.models.packed_newton import (
            lr_fit_batched_packed,
        )

        ln = 2_000_000 if on_tpu else 50_000
        ld, lB, liters = 39, 24, 5
        lk = jax.random.split(key, 3)
        lX = jax.random.normal(lk[0], (ln, ld), jnp.float32)
        ly = (jax.random.uniform(lk[1], (ln,)) > 0.5).astype(jnp.float32)
        lW = (jax.random.uniform(lk[2], (lB, ln)) > 0.25).astype(jnp.float32)
        lregs = jnp.tile(jnp.asarray([0.001, 0.01, 0.1, 0.2] * 2), 3)
        lens = jnp.full((lB,), 0.1, jnp.float32)
        jax.block_until_ready((lX, ly, lW))
        hess_bf16 = on_tpu
        t_packed = _timeit(
            lambda: lr_fit_batched_packed(
                lX, ly, lW, lregs, lens, iters=liters, hess_bf16=hess_bf16
            ), reps=3,
        )
        t_vmap = _timeit(
            lambda: _lr_fit_batched(lX, ly, lW, lregs, lens, liters),
            reps=3,
        )
        bp, ip = lr_fit_batched_packed(
            lX, ly, lW, lregs, lens, iters=liters, hess_bf16=hess_bf16
        )
        bv, iv = _lr_fit_batched(lX, ly, lW, lregs, lens, liters)
        par = float(np.max(np.abs(np.asarray(bp) - np.asarray(bv))))
        lr_flops = lB * liters * (2.0 * ln * ld * ld + 4.0 * ln * ld)
        result.update(
            lrpack_rows=ln,
            lrpack_packed_s=round(t_packed, 4),
            lrpack_vmap_s=round(t_vmap, 4),
            lrpack_speedup=round(t_vmap / t_packed, 3),
            lrpack_packed_tflops_per_s=round(
                lr_flops / t_packed / 1e12, 3
            ),
            lrpack_vmap_tflops_per_s=round(lr_flops / t_vmap / 1e12, 3),
            lrpack_coef_maxdiff=float(f"{par:.3e}"),
        )
    except Exception as e:
        result["lrpack_error"] = f"{type(e).__name__}: {e}"

    # -- tree level-histogram: scatter block size + bin dtype sweep -------
    # (VERDICT r4 prep: the 2^23 default block was sized from compile-time
    # HBM bounds, not throughput; sweep it on the chip and record the
    # winner.  int8 vs int32 bins measures the HBM saving of the
    # bins_device_dtype cast on the dominant per-level read.)
    try:
        import os as _os

        from transmogrifai_tpu.models import tree_kernel as tk

        hn = 4_000_000 if on_tpu else 100_000  # CPU: smoke the path only
        hd, hC, hL, hB = 39, 3, 32, 64
        hk = jax.random.split(key, 4)
        hbins32 = jax.random.randint(hk[0], (hn, hd), 0, hB, jnp.int32)
        hbins8 = hbins32.astype(jnp.int8)
        hnode = jax.random.randint(hk[1], (hn,), 0, hL, jnp.int32)
        hstats = jax.random.uniform(hk[2], (hn, hC), jnp.float32)
        jax.block_until_ready((hbins32, hbins8, hnode, hstats))
        hist_jit = jax.jit(
            lambda b, nr, sw: tk._level_hist(b, nr, sw, hL, hB)
        )
        sweep = {}
        best = (None, float("inf"))
        for log2cap in ((21, 22, 23, 24, 25) if on_tpu else (21, 23)):
            _os.environ["TX_TREE_HIST_SCATTER_ELEMS"] = str(1 << log2cap)
            jax.clear_caches()  # cap is read at trace time
            t_h = _timeit(hist_jit, hbins32, hnode, hstats, reps=3)
            sweep[f"2^{log2cap}"] = round(t_h, 4)
            if t_h < best[1]:
                best = (log2cap, t_h)
        _os.environ["TX_TREE_HIST_SCATTER_ELEMS"] = str(1 << best[0])
        jax.clear_caches()
        t_h8 = _timeit(hist_jit, hbins8, hnode, hstats, reps=3)
        h32 = hist_jit(hbins32, hnode, hstats)
        h8 = hist_jit(hbins8, hnode, hstats)
        _os.environ.pop("TX_TREE_HIST_SCATTER_ELEMS", None)
        jax.clear_caches()
        result.update(
            hist_rows=hn,
            hist_block_sweep_s=sweep,
            hist_best_block_log2=best[0],
            hist_best_s=round(best[1], 4),
            hist_scatter_elems_per_s=round(hn * hd * hC / best[1], 1),
            hist_int8_s=round(t_h8, 4),
            hist_int8_speedup=round(best[1] / t_h8, 3),
            hist_int8_parity=bool(
                np.allclose(np.asarray(h32), np.asarray(h8), atol=1e-3)
            ),
        )
    except Exception as e:
        result["hist_sweep_error"] = f"{type(e).__name__}: {e}"

    result["value"] = result["moments_pallas_s"]
    result["total_wall_s"] = round(time.time() - t_start, 1)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
