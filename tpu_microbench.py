"""Pallas-on-real-TPU microbenchmark.

Proves Mosaic lowering of the two product pallas kernels
(``fused_moments`` and ``bin_matrix``, parallel/pallas_kernels.py) on an
actual chip and records wall-clocks vs their jitted-jnp fallbacks at the
scale the round-1 commit claimed (1M x 512).  Prints ONE JSON line.

Run via tpu_probe.py when the axon tunnel is healthy; safe to run by hand.
"""
from __future__ import annotations

import json
import sys
import time


def _timeit(fn, *args, reps: int = 5, **kw) -> float:
    """Median wall-clock of fn(*args) with block_until_ready, after one
    warmup call (compilation excluded)."""
    import jax

    out = fn(*args, **kw)
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def main() -> int:
    t_start = time.time()
    import jax
    import jax.numpy as jnp

    from transmogrifai_tpu.parallel import pallas_kernels as pk

    dev = jax.devices()[0]
    result = {
        "metric": "pallas_microbench",
        "platform": jax.default_backend(),
        "device": str(getattr(dev, "device_kind", dev)),
        "n_devices": jax.device_count(),
        "unit": "seconds",
    }
    on_tpu = result["platform"] == "tpu"
    result["mosaic_lowering"] = on_tpu  # interpret=False only on real tpu

    n, d = 1_000_000, 512
    key = jax.random.PRNGKey(0)
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (n, d), jnp.float32)
    y = (jax.random.uniform(ky, (n,)) > 0.5).astype(jnp.float32)
    jax.block_until_ready((x, y))
    result["rows"] = n
    result["dims"] = d

    # -- bandwidth calibration: a pure one-pass read of x -----------------
    # (sanity anchor: no moments measurement can beat this; if one does,
    # the timing methodology is broken, not the kernel fast)
    sum_fn = jax.jit(lambda a: jnp.sum(a, dtype=jnp.float32))
    t_read = _timeit(sum_fn, x, reps=11)
    result["read_sum_s"] = round(t_read, 6)
    result["read_gbps"] = round(n * d * 4 / t_read / 1e9, 1)

    # -- fused_moments: pallas vs fused-jnp fallback ----------------------
    t_pallas = _timeit(pk.fused_moments, x, y, True, reps=11)
    t_jnp = _timeit(pk.fused_moments, x, y, False, reps=11)
    # parity check on device (sums agree to float32 tolerance)
    mp = pk.fused_moments(x, y, True)
    mj = pk.fused_moments(x, y, False)
    import numpy as np

    mom_err = max(
        float(np.max(np.abs((np.asarray(a) - np.asarray(b))
                            / (np.abs(np.asarray(b)) + 1.0))))
        for a, b in zip(mp, mj)
    )
    result.update(
        moments_pallas_s=round(t_pallas, 6),
        moments_jnp_s=round(t_jnp, 6),
        moments_speedup=round(t_jnp / t_pallas, 3),
        moments_rel_err=float(f"{mom_err:.3e}"),
        # one HBM pass over x: n*d*4 bytes / wall = achieved bandwidth
        moments_gbps=round(n * d * 4 / t_pallas / 1e9, 1),
    )

    # -- bin_matrix: pallas vs jnp comparison-count fallback --------------
    n_edges = 63
    qs = jnp.linspace(0.0, 1.0, n_edges + 2)[1:-1]
    edges = jnp.quantile(x[:65536], qs, axis=0).T  # [d, E]
    jax.block_until_ready(edges)
    t_bpallas = _timeit(pk.bin_matrix, x, edges, True)
    t_bjnp = _timeit(pk.bin_matrix, x, edges, False)
    bp = pk.bin_matrix(x[:65536], edges, True)
    bj = pk.bin_matrix(x[:65536], edges, False)
    result.update(
        bin_pallas_s=round(t_bpallas, 6),
        bin_jnp_s=round(t_bjnp, 6),
        bin_speedup=round(t_bjnp / t_bpallas, 3),
        bin_parity=bool((np.asarray(bp) == np.asarray(bj)).all()),
        bin_rows_per_s=round(n / t_bpallas, 1),
    )

    result["value"] = result["moments_pallas_s"]
    result["total_wall_s"] = round(time.time() - t_start, 1)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
