"""Stage-library unit tests (mirrors reference: core/src/test/.../impl/
feature/* specs - each op's expected outputs + metadata)."""
import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.ops.bucketizers import (
    DecisionTreeNumericBucketizer,
    NumericBucketizer,
)
from transmogrifai_tpu.ops.categorical import OneHotVectorizer, StringIndexer
from transmogrifai_tpu.ops.collections import (
    FilterMap,
    IsotonicRegressionCalibrator,
    ScalerTransformer,
    DescalerTransformer,
    ToOccurTransformer,
)
from transmogrifai_tpu.ops.dates import DateVectorizer
from transmogrifai_tpu.ops.maps import MapVectorizer
from transmogrifai_tpu.ops.numeric import RealVectorizer
from transmogrifai_tpu.ops.text import SmartTextVectorizer, TextTokenizer, tokenize
from transmogrifai_tpu.ops.text_analysis import (
    EmailToPickList,
    JaccardSimilarity,
    LangDetector,
    MimeTypeDetector,
    NGramSimilarity,
    NameEntityRecognizer,
    PhoneNumberParser,
    TextLenTransformer,
    detect_mime_type,
    is_valid_phone,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import (
    ListColumn,
    MapColumn,
    NumericColumn,
    TextColumn,
    VectorColumn,
)
from transmogrifai_tpu.utils.hashing import hashing_tf, murmur3_32


def _ds(**cols):
    data, types = {}, {}
    for name, (vals, t) in cols.items():
        data[name], types[name] = vals, t
    return Dataset.from_pylists(data, types)


def _fit_transform(stage, ds, *features):
    stage.set_input(*features)
    from transmogrifai_tpu.stages.base import Estimator

    model = stage.fit(ds) if isinstance(stage, Estimator) else stage
    return model.transform(ds)[model.output_name]


def test_murmur3_reference_vectors():
    # murmur3_x86_32 known-answer tests (seed 0)
    assert murmur3_32(b"", seed=0) == 0
    assert murmur3_32(b"hello", seed=0) == 0x248BFA47
    assert murmur3_32(b"hello, world", seed=0) == 0x149BBB7F


def test_hashing_tf_deterministic():
    out = hashing_tf([["a", "b", "a"], ["c"]], 16)
    assert out.shape == (2, 16)
    assert out[0].sum() == 3.0 and out[0].max() == 2.0


def test_real_vectorizer_mean_impute_and_nulls():
    ds = _ds(x=([1.0, None, 3.0], ft.Real))
    f = FeatureBuilder(ft.Real, "x").as_predictor()
    out = _fit_transform(RealVectorizer(), ds, f)
    assert isinstance(out, VectorColumn)
    np.testing.assert_allclose(
        out.values, [[1.0, 0.0], [2.0, 1.0], [3.0, 0.0]]
    )
    assert out.metadata.columns[1].is_null_indicator


def test_one_hot_top_k_other_null():
    vals = ["a"] * 5 + ["b"] * 3 + ["c"] * 1 + [None]
    ds = _ds(x=(vals, ft.PickList))
    f = FeatureBuilder(ft.PickList, "x").as_predictor()
    out = _fit_transform(OneHotVectorizer(top_k=2, min_support=2), ds, f)
    labels = [m.indicator_value for m in out.metadata.columns]
    assert labels == ["a", "b", "OTHER", "NullIndicatorValue"]
    assert out.values[0].tolist() == [1, 0, 0, 0]
    assert out.values[8].tolist() == [0, 0, 1, 0]  # "c" below min_support
    assert out.values[9].tolist() == [0, 0, 0, 1]


def test_smart_text_pivots_low_cardinality_hashes_high(rng):
    low = [f"cat{i % 3}" for i in range(100)]
    high = [f"txt unique {i}" for i in range(100)]
    ds = _ds(lo=(low, ft.Text), hi=(high, ft.Text))
    flo = FeatureBuilder(ft.Text, "lo").as_predictor()
    fhi = FeatureBuilder(ft.Text, "hi").as_predictor()
    st = SmartTextVectorizer(max_cardinality=10, hash_dims=32)
    out = _fit_transform(st, ds, flo, fhi)
    # lo pivoted (3 + OTHER + null), hi hashed (32 + null)
    assert out.width == 5 + 33


def test_tokenizer():
    assert tokenize("Hello, World! 123") == ["hello", "world", "123"]
    ds = _ds(t=(["A b", None], ft.Text))
    f = FeatureBuilder(ft.Text, "t").as_predictor()
    out = TextTokenizer().set_input(f).transform(ds)
    col = out[TextTokenizer().set_input(f).output_name] if False else list(out.columns().values())[-1]
    assert isinstance(col, ListColumn)


def test_date_vectorizer_circular():
    ms_per_day = 24 * 3600 * 1000
    ds = _ds(d=([0.0, ms_per_day / 2], ft.Date))
    f = FeatureBuilder(ft.Date, "d").as_predictor()
    out = _fit_transform(DateVectorizer(periods=("HourOfDay",)), ds, f)
    # midnight: sin 0 cos 1; noon: sin ~0 cos -1
    np.testing.assert_allclose(out.values[0, :2], [0.0, 1.0], atol=1e-6)
    np.testing.assert_allclose(out.values[1, :2], [0.0, -1.0], atol=1e-6)


def test_map_vectorizer_numeric_and_pivot():
    maps = [{"a": 1.0, "b": 2.0}, {"a": 3.0}, {}]
    ds = _ds(m=(maps, ft.RealMap))
    f = FeatureBuilder(ft.RealMap, "m").as_predictor()
    out = _fit_transform(MapVectorizer(), ds, f)
    # keys a, b each: value + null indicator
    assert out.width == 4
    np.testing.assert_allclose(out.values[:, 0], [1.0, 3.0, 2.0])  # a mean=2

    tmaps = [{"k": "x"}, {"k": "y"}, {"k": "x"}]
    ds2 = _ds(m=(tmaps, ft.TextMap))
    f2 = FeatureBuilder(ft.TextMap, "m").as_predictor()
    out2 = _fit_transform(MapVectorizer(min_support=1, top_k=5), ds2, f2)
    labels = [m.indicator_value for m in out2.metadata.columns]
    assert "x" in labels and "y" in labels


def test_numeric_bucketizer():
    ds = _ds(x=([1.0, 5.0, 9.0, None], ft.Real))
    f = FeatureBuilder(ft.Real, "x").as_predictor()
    out = NumericBucketizer(splits=[4.0, 8.0]).set_input(f).transform(ds)
    col = list(out.columns().values())[-1]
    assert col.values[:, :3].argmax(axis=1).tolist()[:3] == [0, 1, 2]
    assert col.values[3, 3] == 1.0  # null indicator


def test_decision_tree_bucketizer_finds_signal_split(rng):
    x = rng.uniform(0, 10, 500)
    y = (x > 5.0).astype(float)
    ds = _ds(y=(y.tolist(), ft.RealNN), x=(x.tolist(), ft.Real))
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fx = FeatureBuilder(ft.Real, "x").as_predictor()
    stage = DecisionTreeNumericBucketizer(min_info_gain=0.01)
    model = stage.set_input(fy, fx).fit(ds)
    splits = stage.metadata["splits"]
    assert splits, "expected at least one split"
    assert any(abs(s - 5.0) < 0.8 for s in splits)


def test_decision_tree_bucketizer_no_split_on_noise(rng):
    x = rng.uniform(0, 10, 500)
    y = (rng.rand(500) > 0.5).astype(float)
    ds = _ds(y=(y.tolist(), ft.RealNN), x=(x.tolist(), ft.Real))
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fx = FeatureBuilder(ft.Real, "x").as_predictor()
    stage = DecisionTreeNumericBucketizer(min_info_gain=0.05)
    stage.set_input(fy, fx).fit(ds)
    assert not stage.metadata["should_split"]


def test_text_len_lang_ner_mime_phone():
    ds = _ds(t=(["hello world", None], ft.Text))
    f = FeatureBuilder(ft.Text, "t").as_predictor()
    out = TextLenTransformer().set_input(f).transform(ds)
    col = list(out.columns().values())[-1]
    assert col.values[0] == 11.0

    # NANP: exchange code must be [2-9]XX, so "123" is invalid (matches
    # libphonenumber's judgment) while "253" passes
    assert is_valid_phone("(650) 253-4567") is True
    assert is_valid_phone("(650) 123-4567") is False
    assert is_valid_phone("123") is False
    assert is_valid_phone(None) is None
    assert is_valid_phone("+44 7911 123456", "GB") is True

    import base64

    png = base64.b64encode(b"\x89PNG\r\n\x1a\n....").decode()
    assert detect_mime_type(png) == "image/png"
    assert detect_mime_type(base64.b64encode(b"plain text here").decode()) == "text/plain"

    from transmogrifai_tpu.ops.text_analysis import detect_language

    scores = detect_language("the quick brown fox jumps over the lazy dog")
    assert next(iter(scores)) == "en"


def test_ner_extracts_names():
    ds = _ds(t=(["Braund, Mr. Owen Harris", "nothing here"], ft.Text))
    f = FeatureBuilder(ft.Text, "t").as_predictor()
    model = NameEntityRecognizer().set_input(f)
    col = list(model.transform(ds).columns().values())[-1]
    assert "owen" in col.values[0] and "braund" in col.values[0]


def test_similarities():
    ds = _ds(a=(["kitten", None], ft.Text), b=(["sitting", "x"], ft.Text))
    fa = FeatureBuilder(ft.Text, "a").as_predictor()
    fb = FeatureBuilder(ft.Text, "b").as_predictor()
    col = list(
        NGramSimilarity().set_input(fa, fb).transform(ds).columns().values()
    )[-1]
    assert 0 < col.values[0] < 1
    assert col.values[1] == 0.0

    ds2 = _ds(
        a=([["x", "y"], []], ft.MultiPickList), b=([["x"], []], ft.MultiPickList)
    )
    fa2 = FeatureBuilder(ft.MultiPickList, "a").as_predictor()
    fb2 = FeatureBuilder(ft.MultiPickList, "b").as_predictor()
    col2 = list(
        JaccardSimilarity().set_input(fa2, fb2).transform(ds2).columns().values()
    )[-1]
    assert col2.values[0] == 0.5
    assert col2.values[1] == 1.0


def test_filter_map_and_to_occur():
    ds = _ds(m=([{"a": "1", "b": "2"}, {"b": "3"}], ft.TextMap))
    f = FeatureBuilder(ft.TextMap, "m").as_predictor()
    col = list(
        FilterMap(block_keys=["b"]).set_input(f).transform(ds).columns().values()
    )[-1]
    assert col.values == [{"a": "1"}, {}]

    ds2 = _ds(x=([1.0, 0.0, None], ft.Real))
    f2 = FeatureBuilder(ft.Real, "x").as_predictor()
    col2 = list(
        ToOccurTransformer().set_input(f2).transform(ds2).columns().values()
    )[-1]
    assert col2.values.tolist() == [1.0, 0.0, 0.0]


def test_scaler_descaler_roundtrip():
    ds = _ds(x=([1.0, 2.0, 3.0], ft.Real))
    f = FeatureBuilder(ft.Real, "x").as_predictor()
    scaler = ScalerTransformer(scaling_type="linear", slope=2.0, intercept=1.0)
    scaled_f = scaler.set_input(f).get_output()
    ds2 = scaler.transform(ds)
    descaler = DescalerTransformer().set_input(scaled_f, scaled_f)
    col = list(descaler.transform(ds2).columns().values())[-1]
    np.testing.assert_allclose(col.values, [1.0, 2.0, 3.0])


def test_isotonic_calibrator(rng):
    n = 200
    score = np.sort(rng.rand(n))
    y = (rng.rand(n) < score).astype(float)
    ds = _ds(y=(y.tolist(), ft.RealNN), s=(score.tolist(), ft.Real))
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fs = FeatureBuilder(ft.Real, "s").as_predictor()
    model = IsotonicRegressionCalibrator().set_input(fy, fs).fit(ds)
    col = list(model.transform(ds).columns().values())[-1]
    assert (np.diff(col.values[np.argsort(score)]) >= -1e-9).all()  # monotone


def test_string_indexer_and_email_domain():
    ds = _ds(t=(["b", "a", "b", None], ft.Text))
    f = FeatureBuilder(ft.Text, "t").as_predictor()
    model = StringIndexer().set_input(f).fit(ds)
    col = list(model.transform(ds).columns().values())[-1]
    # b most frequent -> 0; None stays MISSING (masked), never a phantom
    # class; an unseen non-null string would get the tail index instead
    assert col.values.tolist() == [0.0, 1.0, 0.0, 0.0]
    assert col.mask.tolist() == [True, True, True, False]
    ds_unseen = _ds(t=(["b", "zz"], ft.Text))
    col_u = list(model.transform(ds_unseen).columns().values())[-1]
    assert col_u.values.tolist() == [0.0, 2.0]  # 'zz' -> tail bucket
    assert col_u.mask.tolist() == [True, True]

    ds2 = _ds(e=(["joe@corp.COM", "bad"], ft.Email))
    f2 = FeatureBuilder(ft.Email, "e").as_predictor()
    col2 = list(
        EmailToPickList().set_input(f2).transform(ds2).columns().values()
    )[-1]
    assert col2.values[0] == "corp.com" and col2.values[1] is None


def test_transmogrify_label_aware_bucketize(rng):
    """transmogrify(label=...) adds per-numeric decision-tree bucket
    columns alongside the filled vectorizer output (reference:
    Transmogrifier.scala:155,175 -> RichNumericFeature.vectorize label
    branch)."""
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    n = 300
    x = rng.randn(n)
    y = (x > 0.3).astype(float)  # a clean split at 0.3
    noise = rng.randn(n)
    data = {"y": y.tolist(), "x": x.tolist(), "noise": noise.tolist()}
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fx = FeatureBuilder(ft.Real, "x").as_predictor()
    fn = FeatureBuilder(ft.Real, "noise").as_predictor()

    plain = transmogrify([fx, fn])
    labeled = transmogrify([fx, fn], label=fy)
    wf = OpWorkflow().set_result_features(plain, labeled)
    model = wf.set_input_dataset(data).train()
    scored = model.score(data)
    w_plain = scored[plain.name].width
    w_lab = scored[labeled.name].width
    assert w_lab > w_plain  # bucket columns appended
    names = scored[labeled.name].metadata.column_names()
    assert any("[" in nm and "x" in nm for nm in names)  # bucket ranges


def test_isotonic_pava_properties(rng):
    """PAVA invariants (reference IsotonicRegressionCalibrator.scala via
    Spark IsotonicRegression): fitted values are monotone, match an
    independent reference implementation (repeated full relaxation
    passes), reproduce already-monotone data exactly, and the antitonic
    mode mirrors the isotonic fit under negation."""
    import numpy as np

    from transmogrifai_tpu.ops.collections import (
        IsotonicRegressionCalibrator,
    )
    from transmogrifai_tpu.types.columns import NumericColumn
    from transmogrifai_tpu.types.dataset import Dataset as _DS

    def fit_values(x, y, isotonic=True):
        label = FeatureBuilder(ft.RealNN, "y").as_response()
        score = FeatureBuilder(ft.Real, "x").as_predictor()
        est = IsotonicRegressionCalibrator(isotonic=isotonic)
        est.set_input(label, score)
        ds = _DS({
            "y": NumericColumn(np.asarray(y, float), np.ones(len(y), bool),
                               ft.RealNN),
            "x": NumericColumn(np.asarray(x, float), np.ones(len(x), bool),
                               ft.Real),
        })
        model = est.fit(ds)
        out = model.transform(ds)[model.output_name]
        return np.asarray(out.values)

    def ref_pava(y):
        # independent O(n^2) relaxation: repeatedly pool adjacent
        # violating blocks until monotone
        blocks = [[float(v), 1.0] for v in y]
        changed = True
        while changed:
            changed = False
            i = 0
            while i < len(blocks) - 1:
                if blocks[i][0] > blocks[i + 1][0] + 1e-12:
                    v = (blocks[i][0] * blocks[i][1]
                         + blocks[i + 1][0] * blocks[i + 1][1])
                    w = blocks[i][1] + blocks[i + 1][1]
                    blocks[i] = [v / w, w]
                    del blocks[i + 1]
                    changed = True
                else:
                    i += 1
        out = []
        for v, w in blocks:
            out.extend([v] * int(round(w)))
        return np.array(out)

    n = 60
    x = np.sort(rng.rand(n) * 10)
    y = np.clip(0.1 * x + 0.8 * rng.randn(n), -3, 4)
    got = fit_values(x, y)
    # monotone in score order
    order = np.argsort(x)
    assert (np.diff(got[order]) >= -1e-9).all()
    # matches the independent reference fit (same score ordering)
    np.testing.assert_allclose(got[order], ref_pava(y[order]), atol=1e-9)
    # already-monotone data reproduces exactly
    ym = np.sort(rng.rand(n))
    np.testing.assert_allclose(fit_values(x, ym)[order], ym, atol=1e-12)
    # antitonic == negated isotonic of negated labels
    anti = fit_values(x, y, isotonic=False)
    np.testing.assert_allclose(anti[order], -ref_pava(-y[order]), atol=1e-9)


def test_vectorizer_meta_memo_identity_and_staleness():
    """cached_metas must return the SAME meta objects across transforms
    (the single-row serving win - identity turns the staleness compare
    into short-circuits) yet rebuild when the fitted state it derives
    from changes (round-5 serving memo)."""
    from transmogrifai_tpu.ops.text import SmartTextModel
    from transmogrifai_tpu.types.columns import TextColumn

    m = SmartTextModel(
        plans=[{"mode": "hash"}], hash_dims=8, track_nulls=True,
        clean_text=True,
    )

    class F:
        name = "t"

        class ftype:
            @staticmethod
            def type_name():
                return "Text"

    m.input_features = (F,)
    col = TextColumn(["a b", None], np.array([True, False]))
    _, ms1 = m.blocks_for(col, 0)
    _, ms2 = m.blocks_for(col, 0)
    assert ms1 is ms2  # identical objects, not equal copies
    assert len(ms1) == 9  # 8 hash dims + null tracker
    m.hash_dims = 4  # post-fit mutation must invalidate the memo
    _, ms3 = m.blocks_for(col, 0)
    assert ms3 is not ms1 and len(ms3) == 5


def test_pivot_helper_cache_staleness():
    """The pivot-mode helper cache must honor the same post-fit-mutation
    contract as cached_metas: flipping track_nulls rebuilds the helper
    (review r5 - a stale helper kept emitting the null column)."""
    from transmogrifai_tpu.ops.text import SmartTextModel
    from transmogrifai_tpu.types.columns import TextColumn

    m = SmartTextModel(
        plans=[{"mode": "pivot", "labels": ["a", "b"]}], hash_dims=8,
        track_nulls=True, clean_text=True,
    )

    class F:
        name = "t"

        class ftype:
            @staticmethod
            def type_name():
                return "PickList"

    m.input_features = (F,)
    col = TextColumn(["a", None], np.array([True, False]))
    arr1, ms1 = m.blocks_for(col, 0)
    assert arr1.shape[1] == 4  # 2 labels + OTHER + null
    m.track_nulls = False
    arr2, ms2 = m.blocks_for(col, 0)
    assert arr2.shape[1] == 3  # null column gone after mutation
