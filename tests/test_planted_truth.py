"""Planted-truth synthetic gate (examples/synthetic.py PLANTED).

The scale benchmark must be CORRECT, not just fast: the generator plants
known coefficients and has an analytically-pinned observable-Bayes AuROC
(0.7493, 5x4M-draw MC, std 3e-4).  A logistic fit on the design matrix
must recover the planted structure within the attenuation window and land
within tolerance of the Bayes ceiling.
"""
import numpy as np

from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.examples.synthetic import (
    BAYES_AUROC_OBSERVED,
    planted_truth_report,
    synthetic_design_matrix,
)
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.types.columns import PredictionColumn


def test_lr_recovers_planted_coefficients_and_bayes_auroc():
    X, y, meta = synthetic_design_matrix(150_000, text_dims=8)
    est = OpLogisticRegression(reg_param=1e-3, max_iter=25)
    params = est.fit_arrays(np.asarray(X, np.float64), y)
    pred, raw, prob = est.predict_arrays(params, np.asarray(X, np.float64))
    m = OpBinaryClassificationEvaluator().evaluate_arrays(
        y, PredictionColumn(pred, raw, prob)
    )
    report = planted_truth_report(params["beta"], meta, float(m.AuROC))
    assert report["ok"], report
    # the planted signal, attenuated ~4-7% by the unobservable noise term
    assert 0.025 <= report["age_coef"] <= 0.032
    assert -0.022 <= report["height_coef"] <= -0.016
    assert 1.40 <= report["female_vs_male"] <= 1.60
    # nuisance coefficients vanish despite weight-height correlation
    assert abs(report["weight_coef"]) < 0.005
    assert abs(report["other_vs_male"]) < 0.05
    # within noise of the Bayes ceiling, and never above it beyond MC noise
    assert abs(report["auroc_gap"]) < 0.012


def test_bayes_ceiling_is_not_beatable():
    """A fit must not report AuROC meaningfully ABOVE the observable Bayes
    bound - that would mean the generator or evaluator is broken."""
    X, y, meta = synthetic_design_matrix(150_000, text_dims=0)
    est = OpLogisticRegression(reg_param=1e-4, max_iter=25)
    params = est.fit_arrays(np.asarray(X, np.float64), y)
    pred, raw, prob = est.predict_arrays(params, np.asarray(X, np.float64))
    m = OpBinaryClassificationEvaluator().evaluate_arrays(
        y, PredictionColumn(pred, raw, prob)
    )
    assert float(m.AuROC) <= BAYES_AUROC_OBSERVED + 0.008


def test_report_flags_wrong_coefficients():
    X, y, meta = synthetic_design_matrix(20_000, text_dims=0)
    bogus = np.zeros(meta.size)
    report = planted_truth_report(bogus, meta, 0.5)
    assert not report["ok"]
