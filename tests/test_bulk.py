"""Exactly-once bulk scoring drills (ISSUE 18; bulk/).

The acceptance matrix for the checkpointed, kill-survivable
batch-inference job: a SIGKILL parameterized across EVERY journal state
boundary (all-pending, scored-not-committed, assigned, committed, and
the output-durable-but-unreceipted window) must resume to output bytes
identical to an uninterrupted run with the double-entry ledger exactly
balanced; a torn journal primary recovers from ``.last-good``; a
corrupted committed output shard is caught by its checksum and
re-scored; one trace id spans plan -> score -> commit -> resume.  The
satellites ride along: the ``tx bulk status`` CLI, the ``bulk``
workflow run type, the ``tx_bulk_*`` metrics view, and the fleet-mode
replica-death drill (at-least-once failover under an exactly-once
journal).

All drills are seeded: the drill pipeline's data seed and the fault
specs (``on=``/``times=`` triggers) pin every run to the same schedule.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from transmogrifai_tpu.bulk import (
    OUTPUT_DIR,
    STATE_COMMITTED,
    BulkJournal,
    BulkScoringJob,
    TornJournalError,
    concatenated_output,
)
from transmogrifai_tpu.bulk.journal import output_name
from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.obs import trace as obs_trace
from transmogrifai_tpu.serialization.model_io import LAST_GOOD_SUFFIX
from transmogrifai_tpu.testkit.drills import (
    BULK_KILL_CHILD_TEMPLATE,
    drill_env,
    tiny_drill_pipeline,
    write_shard_csv,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ROWS = 120
N_SHARDS = 3
ROWS_PER_SHARD = 40
POISON_INDEX = 45  # row 5 of shard 1: a non-numeric cell -> quarantine
CHUNK_ROWS = 16


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every drill arms injection explicitly; none may leak."""
    faults.reset()
    yield
    faults.reset()


def _drill_rows():
    """(workflow, rows): the tiny drill pipeline plus its 120 input
    rows with ONE poisoned numeric cell (the quarantine the ledger
    must account exactly).

    Stage uids are reset first: the kill drills compare output BYTES
    against a fresh child process (uid counters at zero), and the
    scored rows' column names embed those uids."""
    from transmogrifai_tpu.utils.uid import reset_uids

    reset_uids()
    wf, data, _records, _pred = tiny_drill_pipeline(n=N_ROWS, seed=0)
    rows = [{"y": data["y"][i], "a": data["a"][i], "c": data["c"][i]}
            for i in range(N_ROWS)]
    rows[POISON_INDEX] = dict(rows[POISON_INDEX], a="not-a-number")
    return wf, rows


def _write_shards(dirpath: str, rows) -> list:
    shards = []
    for k in range(N_SHARDS):
        p = os.path.join(dirpath, f"in-{k}.csv")
        write_shard_csv(p, rows[k * ROWS_PER_SHARD:(k + 1) * ROWS_PER_SHARD])
        shards.append(p)
    return shards


@pytest.fixture(scope="module")
def bulk_env(tmp_path_factory):
    """One trained model, three 40-row input shards (one quarantined
    cell), and an uninterrupted reference run's concatenated output -
    the byte-identity oracle every resume drill compares against."""
    base = str(tmp_path_factory.mktemp("bulk"))
    wf, rows = _drill_rows()
    model = wf.train()
    shards = _write_shards(base, rows)
    ref_dir = os.path.join(base, "ref")
    summary = BulkScoringJob(model, ref_dir, shards,
                             chunk_rows=CHUNK_ROWS).run()
    assert summary["ledger"]["balanced"], "reference run must balance"
    return {
        "model": model, "rows": rows, "shards": shards,
        "ref_dir": ref_dir, "ref": concatenated_output(ref_dir),
        "ref_summary": summary,
    }


# ---------------------------------------------------------------------------
# the clean path: planning, scoring, the ledger, determinism
# ---------------------------------------------------------------------------

def test_fresh_job_scores_every_shard_and_balances(bulk_env):
    s = bulk_env["ref_summary"]
    assert s["resumed"] is False
    assert s["shards"] == N_SHARDS
    assert s["shards_scored_this_run"] == N_SHARDS
    led = s["ledger"]
    assert led["complete"] and led["balanced"]
    assert led["rows_in"] == N_ROWS
    assert led["rows_quarantined"] == 1
    assert led["rows_out"] == N_ROWS - 1
    # the poisoned cell landed in shard 1, and ONLY there
    assert led["shards"]["1"]["rows_quarantined"] == 1
    assert led["shards"]["0"]["rows_quarantined"] == 0
    j = BulkJournal.load(bulk_env["ref_dir"])
    assert j.states()[STATE_COMMITTED] == N_SHARDS
    assert all(j.verify_output(sid) for sid in j.shard_ids())
    # the output is real scored rows, one JSON object per line
    lines = bulk_env["ref"].decode("utf-8").splitlines()
    assert len(lines) == N_ROWS - 1
    assert all(isinstance(json.loads(ln), dict) for ln in lines)


def test_second_clean_run_is_byte_identical(bulk_env, tmp_path):
    jd = str(tmp_path / "job")
    s = BulkScoringJob(bulk_env["model"], jd, bulk_env["shards"],
                       chunk_rows=CHUNK_ROWS).run()
    assert s["resumed"] is False
    assert concatenated_output(jd) == bulk_env["ref"]


def test_columnar_feed_matches_record_scoring(bulk_env):
    """The direct chunk->env feed must produce the SAME rows as
    scoring the per-record dicts through the scorer's batch path."""
    from transmogrifai_tpu.local.scorer import LocalScorer

    clean = [r for i, r in enumerate(bulk_env["rows"])
             if i != POISON_INDEX]
    records = [{"a": float(r["a"]), "c": r["c"]} for r in clean]
    scorer = LocalScorer(bulk_env["model"], fused=True)
    want = [json.dumps(r, sort_keys=True, separators=(",", ":"),
                       default=str)
            for r in scorer.score_batch(records)]
    assert bulk_env["ref"].decode("utf-8").splitlines() == want


# ---------------------------------------------------------------------------
# the tentpole drill: SIGKILL at every journal state boundary
# ---------------------------------------------------------------------------

# the journal commit sequence for 3 shards is create(1), then per shard
# assigned/scored/committed (2..10); on=N walks the kill across each
# distinct boundary, and bulk.output_crash:on=2 lands in the canonical
# "output durable, receipt lost" window of the SECOND shard
KILL_FAULTS = (
    "bulk.commit_crash:on=1",   # planned: every shard still pending
    "bulk.commit_crash:on=3",   # shard 0 scored, not yet committed
    "bulk.commit_crash:on=5",   # shard 1 assigned, scoring in flight
    "bulk.commit_crash:on=7",   # shard 1 committed, shard 2 pending
    "bulk.output_crash:on=2",   # shard 1 output written, unreceipted
)

@pytest.mark.parametrize("fault", KILL_FAULTS)
def test_sigkill_at_state_boundary_resumes_byte_identical(
        bulk_env, tmp_path, fault):
    jd = str(tmp_path / "job")
    script = tmp_path / "child.py"
    script.write_text(BULK_KILL_CHILD_TEMPLATE.format(
        repo=REPO, fault=fault, n=N_ROWS, job_dir=jd,
        shards=bulk_env["shards"], chunk=CHUNK_ROWS))
    proc = subprocess.run([sys.executable, str(script)],
                          env=drill_env(), timeout=300)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT  # really killed
    # the kill left a loadable journal with unfinished work
    j = BulkJournal.load(jd)
    assert j.states()[STATE_COMMITTED] < N_SHARDS
    # resume in THIS process with the same (deterministically trained)
    # model: no inputs passed - the journal is the plan
    s = BulkScoringJob(bulk_env["model"], jd).run()
    assert s["resumed"] is True
    assert concatenated_output(jd) == bulk_env["ref"]
    led = s["ledger"]
    assert led["complete"] and led["balanced"]
    assert led["rows_in"] == N_ROWS
    assert led["rows_out"] == N_ROWS - 1
    assert led["rows_quarantined"] == 1
    (resume,) = s["resumes"]
    assert resume["pid"] == os.getpid()
    assert resume["from_last_good"] is False
    j2 = BulkJournal.load(jd)
    assert j2.states()[STATE_COMMITTED] == N_SHARDS
    assert all(j2.verify_output(sid) for sid in j2.shard_ids())


def test_output_crash_resume_rescores_the_unreceipted_shard(
        bulk_env, tmp_path):
    """The exactly-once window in detail: the output shard is durable
    but the journal still says ``assigned`` - the resume must treat
    the untrusted bytes as garbage and re-score exactly that shard."""
    jd = str(tmp_path / "job")
    script = tmp_path / "child.py"
    script.write_text(BULK_KILL_CHILD_TEMPLATE.format(
        repo=REPO, fault="bulk.output_crash:on=1", n=N_ROWS, job_dir=jd,
        shards=bulk_env["shards"], chunk=CHUNK_ROWS))
    proc = subprocess.run([sys.executable, str(script)],
                          env=drill_env(), timeout=300)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT
    j = BulkJournal.load(jd)
    assert j.shard(0)["state"] == "assigned"
    assert os.path.exists(os.path.join(jd, OUTPUT_DIR, output_name(0)))
    s = BulkScoringJob(bulk_env["model"], jd).run()
    (resume,) = s["resumes"]
    assert resume["recovered_states"]["0"] == "assigned"
    assert 0 in resume["rescored_shards"]
    assert concatenated_output(jd) == bulk_env["ref"]
    assert s["ledger"]["balanced"]


def test_rerun_of_a_completed_job_is_a_noop_resume(bulk_env, tmp_path):
    jd = str(tmp_path / "job")
    BulkScoringJob(bulk_env["model"], jd, bulk_env["shards"],
                   chunk_rows=CHUNK_ROWS).run()
    s = BulkScoringJob(bulk_env["model"], jd, bulk_env["shards"]).run()
    assert s["resumed"] is True
    assert s["shards_scored_this_run"] == 0
    last = s["resumes"][-1]
    assert last["recovered_states"] == {}
    assert last["rescored_shards"] == []
    assert concatenated_output(jd) == bulk_env["ref"]


def test_job_dir_refuses_a_different_input_set(bulk_env, tmp_path):
    other = str(tmp_path / "other.csv")
    write_shard_csv(other, [{"y": 1.0, "a": 0.5, "c": "u"}])
    with pytest.raises(ValueError, match="different input set"):
        BulkScoringJob(bulk_env["model"], bulk_env["ref_dir"],
                       [other]).run()


# ---------------------------------------------------------------------------
# journal durability: torn primary, torn both, corrupted outputs
# ---------------------------------------------------------------------------

def test_torn_primary_recovers_from_last_good(bulk_env, tmp_path):
    jd = str(tmp_path / "job")
    BulkScoringJob(bulk_env["model"], jd, bulk_env["shards"],
                   chunk_rows=CHUNK_ROWS).run()
    faults.configure("bulk.journal_torn:times=1")
    j = BulkJournal.load(jd)
    assert j.recovered_from_last_good is True
    # .last-good is exactly one commit behind the final state
    assert j.states()[STATE_COMMITTED] == N_SHARDS - 1
    # a full resume THROUGH the torn primary: the verified scored
    # shard rolls forward to committed without re-scoring
    faults.configure("bulk.journal_torn:times=1")
    s = BulkScoringJob(bulk_env["model"], jd).run()
    last = s["resumes"][-1]
    assert last["from_last_good"] is True
    assert last["rescored_shards"] == []
    assert last["recovered_states"] == {"2": "scored"}
    assert concatenated_output(jd) == bulk_env["ref"]
    assert s["ledger"]["balanced"]


def test_torn_primary_and_fallback_is_loud(bulk_env, tmp_path):
    jd = str(tmp_path / "job")
    BulkScoringJob(bulk_env["model"], jd, bulk_env["shards"],
                   chunk_rows=CHUNK_ROWS).run()
    primary = os.path.join(jd, "journal.json")
    for path in (primary, primary + LAST_GOOD_SUFFIX):
        with open(path, "r+b") as f:
            f.truncate(30)
    assert BulkJournal.exists(jd)
    with pytest.raises(TornJournalError):
        BulkJournal.load(jd)


def test_corrupted_committed_output_is_caught_and_rescored(
        bulk_env, tmp_path):
    jd = str(tmp_path / "job")
    BulkScoringJob(bulk_env["model"], jd, bulk_env["shards"],
                   chunk_rows=CHUNK_ROWS).run()
    # a partial write nobody journaled: truncate shard 1's output
    with open(os.path.join(jd, OUTPUT_DIR, output_name(1)), "r+b") as f:
        f.truncate(10)
    j = BulkJournal.load(jd)
    assert j.verify_output(0) and not j.verify_output(1)
    s = BulkScoringJob(bulk_env["model"], jd).run()
    last = s["resumes"][-1]
    assert last["recovered_states"] == {"1": "committed"}
    assert last["rescored_shards"] == [1]
    assert concatenated_output(jd) == bulk_env["ref"]
    assert s["ledger"]["balanced"]


def test_empty_shard_commits_with_zero_rows(bulk_env, tmp_path):
    import csv

    empty = str(tmp_path / "empty.csv")
    with open(empty, "w", newline="") as f:
        csv.DictWriter(f, fieldnames=["y", "a", "c"]).writeheader()
    jd = str(tmp_path / "job")
    s = BulkScoringJob(bulk_env["model"], jd,
                       [bulk_env["shards"][0], empty],
                       chunk_rows=CHUNK_ROWS).run()
    assert s["ledger"]["balanced"]
    j = BulkJournal.load(jd)
    rec = j.shard(1)
    assert rec["state"] == STATE_COMMITTED
    assert rec["rows_in"] == 0 and rec["rows_out"] == 0
    assert os.path.getsize(j.output_path(1)) == 0
    assert j.verify_output(1)


# ---------------------------------------------------------------------------
# one trace across plan -> score -> commit -> resume
# ---------------------------------------------------------------------------

def test_one_trace_spans_plan_to_resume(bulk_env, tmp_path):
    from transmogrifai_tpu.obs.trace import reset_tracer, tracer

    jd = str(tmp_path / "job")
    reset_tracer()
    try:
        BulkScoringJob(bulk_env["model"], jd, bulk_env["shards"],
                       chunk_rows=CHUNK_ROWS).run()
        ctx = BulkJournal.load(jd).doc["trace_context"]
        assert ctx, "planning must stamp its trace context"
        trace_id = ctx.split(":")[0]
        # a FRESH tracer (= a new process after the kill) must adopt
        # the planning trace when it resumes
        reset_tracer()
        BulkScoringJob(bulk_env["model"], jd).run()
        names = {r["name"] for r in tracer().spans(trace_id)}
        assert "bulk.run" in names and "bulk.resume" in names
        # the journal still carries the ORIGINAL planning context
        assert BulkJournal.load(jd).doc["trace_context"] == ctx
    finally:
        reset_tracer()


# ---------------------------------------------------------------------------
# satellites: the metrics view, the CLI, the workflow run type
# ---------------------------------------------------------------------------

def test_bulk_metrics_view_rides_the_scrape(bulk_env, tmp_path):
    from transmogrifai_tpu.obs.metrics import (
        metrics_registry,
        reset_metrics_registry,
    )

    reset_metrics_registry()
    try:
        job = BulkScoringJob(bulk_env["model"], str(tmp_path / "job"),
                             bulk_env["shards"], chunk_rows=CHUNK_ROWS)
        job.run()
        text = metrics_registry().prometheus_text()
        samples = {}
        for line in text.splitlines():
            if line.startswith("tx_bulk_"):
                name = line.split("{", 1)[0]
                samples[name] = float(line.rsplit(" ", 1)[1])
        assert samples["tx_bulk_shards_total"] == N_SHARDS
        assert samples["tx_bulk_shards_committed"] == N_SHARDS
        assert samples["tx_bulk_shards_pending"] == 0
        assert samples["tx_bulk_rows_out"] == N_ROWS - 1
        assert samples["tx_bulk_rows_quarantined"] == 1
        assert samples["tx_bulk_rows_per_s"] > 0
    finally:
        reset_metrics_registry()


def test_cli_bulk_status_prints_the_journal(bulk_env, capsys):
    from transmogrifai_tpu.cli import main

    rc = main(["bulk", "status", bulk_env["ref_dir"]])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["n_shards"] == N_SHARDS
    assert doc["states"][STATE_COMMITTED] == N_SHARDS
    assert doc["ledger"]["balanced"] is True
    assert doc["ledger"]["rows_quarantined"] == 1
    assert doc["trace_context"]


def test_cli_bulk_status_torn_journal_exits_1(tmp_path, capsys):
    from transmogrifai_tpu.cli import main

    jd = str(tmp_path / "job")
    os.makedirs(jd)
    with open(os.path.join(jd, "journal.json"), "w") as f:
        f.write("{ torn")
    rc = main(["bulk", "status", jd])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["error"].startswith("TornJournalError")


def test_runner_bulk_run_type(tmp_path):
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    wf, rows = _drill_rows()
    mloc = str(tmp_path / "model")
    OpWorkflowRunner(wf).run("train", OpParams(model_location=mloc))
    shards = _write_shards(str(tmp_path), rows)
    wf2, _ = _drill_rows()
    params = OpParams(
        model_location=mloc,
        write_location=str(tmp_path / "out"),
        metrics_location=str(tmp_path / "metrics"),
        custom_params={"bulk_inputs": shards,
                       "bulk_chunk_rows": CHUNK_ROWS},
    )
    r = OpWorkflowRunner(wf2).run("bulk", params)
    assert r.run_type == "bulk"
    assert r.metrics["ledger"]["balanced"] is True
    assert r.metrics["ledger"]["rows_in"] == N_ROWS
    jd = os.path.join(str(tmp_path / "out"), "bulk")
    assert BulkJournal.load(jd).states()[STATE_COMMITTED] == N_SHARDS
    with open(tmp_path / "metrics" / "bulk_metrics.json") as f:
        saved = json.load(f)
    assert saved["run_type"] == "bulk"
    assert saved["ledger"]["balanced"] is True


# ---------------------------------------------------------------------------
# fleet mode: a replica dies mid-shard; the journal keeps exactly-once
# ---------------------------------------------------------------------------

def test_fleet_replica_death_midshard_keeps_output_exactly_once(tmp_path):
    from transmogrifai_tpu.fleet import FleetController
    from transmogrifai_tpu.registry import ModelRegistry

    wf, rows = _drill_rows()
    model = wf.train()
    root = str(tmp_path / "registry")
    ModelRegistry(root).publish(model, stage="stable")
    shards = _write_shards(str(tmp_path), rows)
    with FleetController(
        root, "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline",
        n_replicas=2, work_dir=str(tmp_path / "fleet"),
        ship_interval_s=0.15, max_restarts=0,
        router_kw={"max_in_flight_per_replica": 2, "max_queue": 64},
        # replica-1 dies on its FIRST bulk chunk; the router must
        # reassign the in-flight batch to the survivor
        worker_env_overrides={
            "replica-1": {"TX_FAULTS": "bulk.replica_die_midshard:on=1"},
        },
    ) as fc:
        jd = str(tmp_path / "job")
        s = BulkScoringJob(model, jd, shards, router=fc.router,
                           chunk_rows=CHUNK_ROWS, max_in_flight=4).run()
        led = s["ledger"]
        assert led["complete"] and led["balanced"]
        assert led["rows_in"] == N_ROWS
        assert led["rows_out"] == N_ROWS - 1
        snap = fc.router.snapshot()
        assert snap["replica_deaths"] == 1
        assert snap["retries"] >= 1  # the victim died holding a chunk
        got = concatenated_output(jd)
        assert len(got.splitlines()) == N_ROWS - 1
        # a clean run on the surviving fleet is byte-identical: the
        # failover duplicated WORK (at-least-once), never OUTPUT
        jd2 = str(tmp_path / "job2")
        s2 = BulkScoringJob(model, jd2, shards, router=fc.router,
                            chunk_rows=CHUNK_ROWS, max_in_flight=4).run()
        assert s2["ledger"]["balanced"]
        assert concatenated_output(jd2) == got
