"""Golden-corruption coverage for the crash-consistent model artifact.

ISSUE 2 satellites: a truncated arrays.npz or a bit-flipped model.json
must be REFUSED by load_model with the checksum/manifest error (never a
deep traceback from json/zipfile), a model.json referencing an npz key
that is not there must surface as ModelLoadError naming the stage path
and the artifact file (not a raw KeyError), and a corrupted primary with
an intact ``.last-good`` predecessor must recover silently.
"""
import json
import os
import shutil

import numpy as np
import pytest

from transmogrifai_tpu.serialization.model_io import (
    ARRAYS_NPZ,
    LAST_GOOD_SUFFIX,
    MANIFEST_JSON,
    MODEL_JSON,
    ModelIntegrityError,
    ModelLoadError,
    load_model,
    verify_artifact,
)
from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline


def _build(n=100, seed=1):
    wf, data, _records, _name = tiny_drill_pipeline(n=n, seed=seed)
    return wf, data


@pytest.fixture(scope="module")
def saved(tmp_path_factory):
    """One trained+saved artifact per module; tests copy it, never
    mutate it."""
    root = tmp_path_factory.mktemp("golden")
    wf, data = _build()
    model = wf.train()
    path = str(root / "m")
    model.save(path)
    return path, data


def _fresh_copy(saved_path, tmp_path):
    dst = str(tmp_path / "m")
    shutil.copytree(saved_path, dst)
    return dst


def test_truncated_npz_refused(saved, tmp_path):
    path = _fresh_copy(saved[0], tmp_path)
    npz = os.path.join(path, ARRAYS_NPZ)
    size = os.path.getsize(npz)
    with open(npz, "r+b") as f:
        f.truncate(size // 2)
    err = verify_artifact(path)
    assert err is not None and "truncated" in err
    wf, _ = _build()
    with pytest.raises(ModelIntegrityError, match="truncated"):
        load_model(path, wf)


def test_bitflipped_model_json_refused(saved, tmp_path):
    path = _fresh_copy(saved[0], tmp_path)
    jpath = os.path.join(path, MODEL_JSON)
    with open(jpath, "r+b") as f:
        f.seek(os.path.getsize(jpath) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x40]))  # same length, different bytes
    err = verify_artifact(path)
    assert err is not None and "SHA-256" in err
    wf, _ = _build()
    with pytest.raises(ModelIntegrityError, match="SHA-256"):
        load_model(path, wf)


def test_missing_npz_key_names_stage_and_file(saved, tmp_path):
    """A checksum-VALID artifact whose arrays.npz lacks a key model.json
    references (mismatched pair) raises ModelLoadError naming both - the
    raw-KeyError satellite fix."""
    import hashlib

    path = _fresh_copy(saved[0], tmp_path)
    npz_path = os.path.join(path, ARRAYS_NPZ)
    with np.load(npz_path, allow_pickle=False) as npz:
        arrays = {k: npz[k] for k in npz.files}
    dropped = sorted(arrays)[0]
    arrays.pop(dropped)
    np.savez_compressed(npz_path, **arrays)
    # recompute the manifest so ONLY the key mismatch remains detectable
    with open(npz_path, "rb") as f:
        data = f.read()
    mpath = os.path.join(path, MANIFEST_JSON)
    manifest = json.load(open(mpath))
    manifest["files"][ARRAYS_NPZ] = {
        "sha256": hashlib.sha256(data).hexdigest(), "bytes": len(data),
    }
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    wf, _ = _build()
    with pytest.raises(ModelLoadError) as exc:
        load_model(path, wf)
    msg = str(exc.value)
    assert dropped in msg and ARRAYS_NPZ in msg
    assert "KeyError" not in msg


def test_corrupt_primary_recovers_from_last_good(saved, tmp_path):
    """Primary fails checksum, .last-good intact -> load transparently
    recovers and the recovered model scores."""
    path = _fresh_copy(saved[0], tmp_path)
    shutil.copytree(path, path + LAST_GOOD_SUFFIX)
    npz = os.path.join(path, ARRAYS_NPZ)
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) - 10)
    wf, data = _build()
    model = load_model(path, wf)
    scored = model.score(data)
    assert len(next(iter(scored.columns().values()))) == len(data["y"])


def test_both_artifacts_corrupt_is_loud(saved, tmp_path):
    path = _fresh_copy(saved[0], tmp_path)
    shutil.copytree(path, path + LAST_GOOD_SUFFIX)
    for p in (path, path + LAST_GOOD_SUFFIX):
        npz = os.path.join(p, ARRAYS_NPZ)
        with open(npz, "r+b") as f:
            f.truncate(os.path.getsize(npz) // 2)
    wf, _ = _build()
    with pytest.raises(ModelIntegrityError, match="last-good"):
        load_model(path, wf)


def test_missing_manifest_is_legacy_tolerated(saved, tmp_path):
    """Pre-manifest artifacts (older saves) still load - with a warning,
    without verification - so the format change is not a breaking one."""
    path = _fresh_copy(saved[0], tmp_path)
    os.remove(os.path.join(path, MANIFEST_JSON))
    assert verify_artifact(path) is None
    wf, data = _build()
    model = load_model(path, wf)
    assert model is not None


def test_legacy_corrupt_npz_still_raises_model_load_error(saved, tmp_path):
    """Manifest-less (legacy) + truncated npz: verification is skipped,
    so np.load/decompress fails - but as ModelLoadError, never a raw
    zipfile/zlib traceback."""
    path = _fresh_copy(saved[0], tmp_path)
    os.remove(os.path.join(path, MANIFEST_JSON))
    npz = os.path.join(path, ARRAYS_NPZ)
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)
    wf, _ = _build()
    with pytest.raises(ModelLoadError):
        load_model(path, wf)


def test_crashed_save_tempdirs_are_reaped(saved, tmp_path):
    """Tempdirs leaked by a DEAD writer are removed by the next save;
    a live writer's tempdir (concurrent save to a shared path) is left
    alone."""
    import subprocess
    import sys

    wf, _ = _build()
    model = wf.train()
    path = str(tmp_path / "m")
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    dead = f"{path}.tmp-{proc.pid}"        # provably dead pid
    live = f"{path}.tmp-{os.getppid()}"    # provably live pid (pytest's parent)
    for d in (dead, live):
        os.makedirs(d)
        open(os.path.join(d, "model.json"), "w").close()
    model.save(path)
    assert not os.path.isdir(dead)
    assert os.path.isdir(live)  # concurrent writer NOT clobbered
    assert verify_artifact(path) is None


def test_publish_by_copy_fallback_produces_verified_artifact(saved, tmp_path):
    """The non-atomic publish path (taken when rename(2) refuses, e.g. a
    volume mounted at the artifact dir) still yields a checksum-valid
    artifact, snapshots the predecessor to last-good, and removes the
    tempdir."""
    from transmogrifai_tpu.serialization import model_io

    path = _fresh_copy(saved[0], tmp_path)
    tmp = path + ".tmp-12345"
    shutil.copytree(saved[0], tmp)
    model_io._publish_by_copy(tmp, path, path + LAST_GOOD_SUFFIX,
                              reason="drill")
    assert verify_artifact(path) is None
    assert verify_artifact(path + LAST_GOOD_SUFFIX) is None
    assert not os.path.isdir(tmp)


def test_swap_save_carries_colocated_extras(saved, tmp_path):
    """Non-artifact files living in the model directory (the runner's
    summary.json, user-kept reports) must survive a re-save, not vanish
    into last-good."""
    wf, _ = _build()
    model = wf.train()
    path = str(tmp_path / "m")
    model.save(path)
    with open(os.path.join(path, "summary.json"), "w") as f:
        f.write('{"kept": true}')
    model.save(path)  # swap must carry the extra forward
    assert os.path.exists(os.path.join(path, "summary.json"))
    assert verify_artifact(path) is None


def test_roundtrip_scores_match_after_swap_save(saved, tmp_path):
    """The atomic-swap save changes the write path, not the format:
    scores from the restored model match the original exactly."""
    wf, data = _build()
    model = wf.train()
    path = str(tmp_path / "m")
    model.save(path)
    before = model.score(data)[model.result_features[0].name].to_list()
    wf2, _ = _build()
    m2 = load_model(path, wf2)
    after = m2.score(data)[m2.result_features[0].name].to_list()
    assert before == after
