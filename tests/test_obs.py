"""Unified observability plane drills (ISSUE 7, obs/).

Pins the acceptance criteria:
* one trace id demonstrably spans a full ingest -> train -> save ->
  publish -> swap -> serve run, walked from the EXPORTED span tree;
* Prometheus exposition parses and round-trips every numeric series the
  four legacy telemetry snapshots report;
* the shared percentile helper is THE implementation (utils.tracing
  aliases it, quantiles pinned);
* telemetry survives >=4-thread hammering with a hot-swap mid-run -
  no lost updates, torn snapshots, or exceptions;
* a broken mesh-event feed counts obs.events_dropped and surfaces it;
* the tail sampler retains full span trees only for slow outliers;
* observability-on serving costs within the CPU-time floor of
  observability-off (the 3%% wall target is proven by bench.py --obs;
  the tier-1 floor is the loose, non-flaky version of the same claim).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.obs import (
    MetricsRegistry,
    SpanProfiler,
    build_trees,
    export_obs,
    metrics_registry,
    prometheus_text_from_json,
    reset_metrics_registry,
    reset_tracer,
    set_enabled,
    tracer,
)
from transmogrifai_tpu.obs.metrics import _numeric_leaves, percentiles
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.readers.csv_reader import CSVReader
from transmogrifai_tpu.serving import compile_endpoint, records_from_dataset
from transmogrifai_tpu.serving.telemetry import ServingTelemetry
from transmogrifai_tpu.types import feature_types as ft


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Every test gets its own registry + tracer (and leaves a fresh
    pair behind so later test modules scrape their own state)."""
    reset_metrics_registry()
    reset_tracer()
    yield
    reset_metrics_registry()
    reset_tracer()


def _small_csv(tmp_path, n=120) -> str:
    rng = np.random.RandomState(0)
    path = os.path.join(str(tmp_path), "data.csv")
    with open(path, "w") as f:
        f.write("label,a,b,kind\n")
        for _ in range(n):
            a, b = rng.rand(), rng.rand()
            kind = ("x", "y", "z")[int(rng.randint(3))]
            f.write(f"{int(a + b > 1.0)},{a:.4f},{b:.4f},{kind}\n")
    return path


def _small_workflow(csv_path):
    label = FeatureBuilder(ft.RealNN, "label").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    kind = FeatureBuilder(ft.PickList, "kind").as_predictor()
    vec = transmogrify([a, b, kind])
    checked = label.sanity_check(vec, remove_bad_features=True)
    pred = OpLogisticRegression().set_input(label, checked).get_output()
    return (
        OpWorkflow()
        .set_result_features(pred)
        .set_reader(CSVReader(csv_path))
    )


# ---------------------------------------------------------------------------
# acceptance: one trace id across the full lifecycle
# ---------------------------------------------------------------------------
def test_one_trace_id_spans_full_lifecycle(tmp_path):
    """ingest -> fit -> save -> publish -> swap -> serve under ONE trace
    id, pinned by walking the EXPORTED span tree (JSONL round trip, not
    the in-memory buffer)."""
    from transmogrifai_tpu.registry import (
        DeploymentController,
        ModelRegistry,
    )

    tr = tracer()
    wf = _small_workflow(_small_csv(tmp_path))
    with tr.span("e2e_run") as root:
        model = wf.train()
        model.save(os.path.join(str(tmp_path), "model"))
        registry = ModelRegistry(os.path.join(str(tmp_path), "registry"))
        version = registry.publish(model)
        registry.promote(version.version, to="stable")
        controller = DeploymentController(registry=registry)
        controller.deploy(model, version=version.version)
        records = records_from_dataset(
            wf.generate_raw_data(), model.raw_features
        )
        results = controller.score_batch(records[:32])
    assert len(results) == 32

    jsonl = os.path.join(str(tmp_path), "spans.jsonl")
    n = tr.export_jsonl(jsonl, trace_id=root.trace_id)
    assert n > 0
    with open(jsonl) as f:
        records_out = [json.loads(line) for line in f if line.strip()]
    assert {r["trace"] for r in records_out} == {root.trace_id}

    trees = build_trees(records_out)
    assert len(trees) == 1 and trees[0]["name"] == "e2e_run"

    def walk(node):
        yield node
        for c in node.get("children", ()):
            yield from walk(c)

    names = {nd["name"] for nd in walk(trees[0])}
    required = {
        "workflow.train", "workflow.ingest", "ingest.read", "stage.fit",
        "stage.transform", "model.save", "registry.publish",
        "deploy.swap", "serve.batch", "score.batch",
    }
    assert required <= names, f"missing spans: {required - names}"
    # the serve batch names its bucket + fused status (ISSUE 7 tagging)
    serve = next(nd for nd in walk(trees[0])
                 if nd["name"] == "serve.batch")
    assert "bucket" in serve["attrs"] and "fused" in serve["attrs"]
    # and every span wall is perf_counter-derived and non-negative
    assert all(nd["wall_ms"] >= 0.0 for nd in walk(trees[0]))


# ---------------------------------------------------------------------------
# acceptance: Prometheus exposition round-trips the legacy snapshots
# ---------------------------------------------------------------------------
_PROM_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$"
)
_PROM_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def _parse_prometheus(text: str) -> dict:
    """Strict parse of the text exposition: every non-comment line must
    be ``name{labels} value``; returns {(name, labels): float}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_SAMPLE.match(line)
        assert m is not None, f"unparseable exposition line: {line!r}"
        labels = tuple(sorted(_PROM_LABEL.findall(m.group(2) or "")))
        out[(m.group(1), labels)] = float(m.group(3))
    return out


def test_prometheus_round_trips_all_four_legacy_snapshots():
    """Every finite numeric series the four legacy telemetry snapshots
    report appears in the Prometheus text with the same value."""
    from transmogrifai_tpu.parallel.resilience import MeshTelemetry
    from transmogrifai_tpu.schema.quarantine import DataTelemetry
    from transmogrifai_tpu.utils.tracing import AppMetrics, StageMetrics

    reg = metrics_registry()
    serving = ServingTelemetry()
    serving.record_request(0.002, "ok")
    serving.record_request(0.004, "failed")
    serving.record_batch(32, 32, 0.01, fused=True)
    serving.record_breaker_transition("open")
    serving.set_model_version("v001", generation=3)
    mesh = MeshTelemetry()
    mesh.record_step("fit", 0.5)
    mesh.record_detection("fit", 1.0, "straggler", 1.2, [])
    data = DataTelemetry()
    data.record_read("a.csv", 100, 97)
    app = AppMetrics()
    app.record(StageMetrics("uid1", "OpX", "fit", 0.25, 100))

    # ONE document: live views tick (wall_s, rows_per_s), so the parse
    # target must be the exposition of the SAME snapshot it checks
    doc = reg.to_json()
    kinds = {k.split("/")[0] for k in doc["views"]}
    assert {"serving", "mesh", "data", "stage"} <= kinds

    from transmogrifai_tpu.obs import process_instance

    inst = process_instance()
    samples = _parse_prometheus(prometheus_text_from_json(doc))
    missing, wrong = [], []
    for key, snap in doc["views"].items():
        kind, _, idx = key.partition("/")
        for path, value in _numeric_leaves(snap):
            from transmogrifai_tpu.obs import sanitize_metric_name

            name = sanitize_metric_name(kind + "_" + "_".join(path))
            got = samples.get(
                (name, (("instance", inst), ("view", idx))))
            if got is None:
                missing.append(name)
            elif abs(got - float(value)) > 1e-9:
                wrong.append((name, got, value))
    assert not missing, f"series missing from exposition: {missing[:10]}"
    assert not wrong, f"series value mismatch: {wrong[:10]}"

    # every sample names the process it came from (ISSUE 11 satellite:
    # the instance label is a stable pid+nonce identity, never empty)
    def _lbl(view):
        return (("instance", inst), ("view", view))

    # spot-pin a few load-bearing ones end to end
    assert samples[("tx_serving_rows_scored", _lbl("0"))] == 1.0
    assert samples[("tx_serving_generation", _lbl("0"))] == 3.0
    assert samples[("tx_mesh_detections", _lbl("0"))] == 1.0
    assert samples[("tx_data_rows_quarantined", _lbl("0"))] == 3.0


def test_prometheus_renderer_shared_with_saved_json(tmp_path, capsys):
    """tx obs metrics renders a SAVED metrics.json through the SAME
    renderer a live scrape uses: the CLI output is byte-identical to
    prometheus_text_from_json of the saved document."""
    reg = metrics_registry()
    reg.counter("obs.events_dropped", help="drops").inc(4)
    serving = ServingTelemetry()
    serving.record_request(0.001, "ok")
    out = export_obs(str(tmp_path / "obs"))
    assert out["series"]["obs.events_dropped"]["value"] == 4
    with open(tmp_path / "obs" / "metrics.json") as f:
        saved = json.load(f)
    # the .prom file written next to it came from the same document
    with open(tmp_path / "obs" / "metrics.prom") as f:
        assert f.read() == prometheus_text_from_json(saved)

    from transmogrifai_tpu import cli

    rc = cli.main(["obs", "metrics", "--path", str(tmp_path / "obs"),
                   "--format", "prometheus"])
    assert rc == 0
    assert capsys.readouterr().out == prometheus_text_from_json(saved)


# ---------------------------------------------------------------------------
# satellite: one percentile implementation
# ---------------------------------------------------------------------------
def test_percentiles_single_implementation_and_pinned():
    from transmogrifai_tpu.utils import tracing

    # the alias IS the function, not a fork
    assert tracing.percentiles is percentiles
    vals = [5.0, 1.0, 3.0, 2.0, 4.0]
    got = percentiles(vals, (50.0, 95.0, 99.0))
    assert got["p50"] == 3.0
    assert got["p95"] == pytest.approx(4.8)
    assert got["p99"] == pytest.approx(4.96)
    # numpy's linear-interpolation quantile is the independent oracle
    for q in (50.0, 95.0, 99.0):
        assert percentiles(vals, (q,))[f"p{q:g}"] == pytest.approx(
            float(np.percentile(vals, q))
        )
    # empty input: NaN, never an exception (snapshot paths rely on it)
    empty = percentiles([], (50.0,))
    assert empty["p50"] != empty["p50"]


# ---------------------------------------------------------------------------
# satellite: events_dropped self-metric
# ---------------------------------------------------------------------------
def test_broken_mesh_event_feed_is_counted_and_surfaced():
    from transmogrifai_tpu.utils import tracing

    old = tracing._mesh_events_source

    def _broken(since_epoch=None):
        raise RuntimeError("event feed wedged")

    try:
        tracing.register_mesh_events_source(_broken)
        assert tracing.mesh_events() == []  # still never raises
        assert tracing.mesh_events_dropped() == 1
        app = tracing.AppMetrics()
        doc = app.to_json()  # calls mesh_events again -> second drop
        assert doc["obs_events_dropped"] >= 2
        # and the scrape sees the self-metric (instance-labeled)
        from transmogrifai_tpu.obs import process_instance

        samples = _parse_prometheus(metrics_registry().prometheus_text())
        assert samples[("tx_obs_events_dropped",
                        (("instance", process_instance()),))] >= 2
    finally:
        tracing.register_mesh_events_source(old)


# ---------------------------------------------------------------------------
# satellite: telemetry under concurrency (>=4 threads + hot-swap)
# ---------------------------------------------------------------------------
def test_serving_telemetry_concurrent_no_lost_updates():
    tel = ServingTelemetry()
    tel.set_model_version("v001", generation=1)
    n_threads, per_thread = 6, 2000
    errors: list = []
    start = threading.Barrier(n_threads + 2)

    def hammer(tid: int) -> None:
        try:
            start.wait(timeout=10)
            for i in range(per_thread):
                tel.record_request(0.001 * (i % 7), "ok")
                tel.record_batch(4, 8, 0.0001, fused=bool(i % 2))
                if i % 5 == 0:
                    tel.record_request(0.002, "failed")
        except Exception as e:  # noqa: BLE001 - the assertion itself
            errors.append(e)

    def swap() -> None:
        # hot-swap mid-run: generation tagging must never tear a
        # snapshot or lose counts
        try:
            start.wait(timeout=10)
            for g in range(2, 40):
                tel.set_model_version(f"v{g:03d}", generation=g)
                tel.record_lifecycle({"event": "swap", "generation": g})
                time.sleep(0.001)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)
    ] + [threading.Thread(target=swap)]
    for t in threads:
        t.start()
    start.wait(timeout=10)
    seen_rows = 0
    deadline = time.monotonic() + 120
    while any(t.is_alive() for t in threads):
        assert time.monotonic() < deadline, "concurrency drill wedged"
        snap = tel.snapshot()  # concurrent snapshots must not tear
        assert snap["rows_scored"] >= seen_rows  # monotonic, no lost inc
        seen_rows = snap["rows_scored"]
        assert snap["rows_scored"] <= n_threads * per_thread
        time.sleep(0.02)  # snapshot copies bounded reservoirs under
        # the lock; an unthrottled loop starves the writers it drills
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors
    final = tel.snapshot()
    assert final["rows_scored"] == n_threads * per_thread
    assert final["rows_failed"] == n_threads * ((per_thread + 4) // 5)
    assert final["batches"] == n_threads * per_thread
    assert final["rows_batched"] == n_threads * per_thread * 4
    assert final["generation"] == 39
    assert final["model_version"] == "v039"


def test_metrics_registry_concurrent_no_lost_updates():
    reg = MetricsRegistry()
    c = reg.counter("hammer.count")
    h = reg.histogram("hammer.ms")
    n_threads, per_thread = 5, 8000
    errors: list = []

    def hammer(tid: int) -> None:
        try:
            for i in range(per_thread):
                c.inc()
                h.observe(float(i % 100))
                if i % 1000 == 0:
                    reg.prometheus_text()  # concurrent scrape
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=hammer, args=(t,))
        for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * per_thread


# ---------------------------------------------------------------------------
# profiler: tail sampler
# ---------------------------------------------------------------------------
def test_tail_sampler_retains_only_slow_outlier_trees():
    prof = SpanProfiler(exemplar_capacity=8, min_samples=50,
                        threshold_refresh=10)
    for i in range(500):
        prof.observe("serve.batch", 1.0,
                     tree={"trace": f"t{i}", "wall_ms": 1.0})
    snap = prof.snapshot()
    assert snap["tail"]["exemplars_retained"] == 0  # no tail, no hoard
    prof.observe("serve.batch", 250.0, tree={
        "trace": "slow", "wall_ms": 250.0,
        "children": [{"name": "score.batch", "wall_ms": 249.0}],
    })
    snap = prof.snapshot()
    assert snap["tail"]["exemplars_retained"] == 1
    ex = prof.exemplars()[0]
    assert ex["trace"] == "slow" and ex["wall_ms"] == 250.0
    # the FULL tree rode along: the stage-level breakdown is right there
    assert ex["tree"]["children"][0]["name"] == "score.batch"
    # stats: ewma tracks recency, histogram quantiles are finite
    st = snap["spans"]["serve.batch"]
    assert st["count"] == 501
    assert st["p99_ms"] is not None and st["max_ms"] == 250.0


def test_span_ring_buffer_bounded_and_eviction_counted():
    tr = reset_tracer(capacity=64)
    for _ in range(200):
        with tr.span("tick"):
            pass
    snap = tr.snapshot()
    assert snap["spans_retained"] == 64
    assert snap["spans_recorded"] == 200
    assert snap["spans_evicted"] == 136


def test_disabled_tracer_records_nothing():
    tr = reset_tracer(enabled=False)
    with tr.span("off") as sp:
        sp.set_attr("ignored", 1)  # the null span accepts the calls
    assert tr.spans() == []
    set_enabled(True)
    with tr.span("on"):
        pass
    assert [r["name"] for r in tr.spans()] == ["on"]


# ---------------------------------------------------------------------------
# acceptance: CPU-time overhead floor (the bench proves the 3% wall bar)
# ---------------------------------------------------------------------------
def test_observability_on_within_cpu_floor_of_off(tmp_path):
    """Obs-on fused serving must stay within 1.25x the CPU time of
    obs-off (min-of-3, interleaved arms).  The bench (OBS_BENCH.json)
    pins the tight 3%% wall-clock claim; this floor is the loose,
    CI-stable tier-1 version - a per-row or per-value span regression
    blows straight past it."""
    wf = _small_workflow(_small_csv(tmp_path, n=240))
    model = wf.train()
    records = records_from_dataset(
        wf.generate_raw_data(), model.raw_features
    )
    endpoint = compile_endpoint(model, batch_buckets=(1, 8, 32, 128))
    endpoint.score_batch(records)  # warm both arms' caches

    def cpu_pass() -> float:
        t0 = time.process_time()
        for _ in range(4):
            out = endpoint.score_batch(records)
        assert len(out) == len(records)
        return max(time.process_time() - t0, 1e-9)

    on_c = off_c = float("inf")
    for _ in range(3):
        set_enabled(True)
        on_c = min(on_c, cpu_pass())
        set_enabled(False)
        off_c = min(off_c, cpu_pass())
    set_enabled(True)
    assert on_c <= off_c * 1.25 + 0.01, (
        f"observability overhead too high: on={on_c:.4f}s "
        f"off={off_c:.4f}s cpu"
    )


# ---------------------------------------------------------------------------
# runner knob + CLI trace view
# ---------------------------------------------------------------------------
def test_runner_metrics_path_knob_exports_plane(tmp_path):
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    wf = _small_workflow(_small_csv(tmp_path))
    runner = OpWorkflowRunner(wf)
    out_dir = str(tmp_path / "obs_out")
    result = runner.run("train", OpParams(
        model_location=str(tmp_path / "model"),
        custom_params={"metrics_path": out_dir},
    ))
    assert result.model is not None
    for name in ("metrics.json", "metrics.prom", "spans.jsonl"):
        assert os.path.exists(os.path.join(out_dir, name)), name
    with open(os.path.join(out_dir, "metrics.json")) as f:
        doc = json.load(f)
    assert "views" in doc and any(
        k.startswith("stage/") for k in doc["views"]
    )
    # exposition file parses
    with open(os.path.join(out_dir, "metrics.prom")) as f:
        _parse_prometheus(f.read())
    # the spans JSONL reconstructs to a tree containing the train run
    from transmogrifai_tpu import cli

    rc = cli.main(["obs", "trace", "--path", out_dir, "--slowest", "3"])
    assert rc == 0
    with open(os.path.join(out_dir, "spans.jsonl")) as f:
        names = {json.loads(line)["name"] for line in f if line.strip()}
    assert {"run.train", "workflow.train", "ingest.read"} <= names


# ---------------------------------------------------------------------------
# ISSUE 11 satellites: instance identity + truncated-JSONL tolerance
# ---------------------------------------------------------------------------
def test_instance_label_stable_and_overridable():
    """The exposition `instance` label is a stable per-process identity
    (pid + start nonce), overridable for replica names, and a caller
    override beats the document stamp."""
    from transmogrifai_tpu.obs import (
        process_instance,
        prometheus_text_from_json,
        set_process_instance,
    )

    inst = process_instance()
    assert inst == process_instance()  # stable for the process lifetime
    assert inst.split("-")[0] == str(os.getpid())
    try:
        set_process_instance("replica-7")
        reg = metrics_registry()
        reg.counter("x.c").inc()
        text = reg.prometheus_text()
        assert 'tx_x_c{instance="replica-7"} 1' in text
        doc = dict(reg.to_json(), instance="replica-7")
        t2 = prometheus_text_from_json(doc, instance="other")
        assert 'instance="other"' in t2 and "replica-7" not in t2
    finally:
        set_process_instance(None)


def test_trace_cli_skips_truncated_jsonl_lines(tmp_path, capsys):
    """A process killed mid-export truncates the LAST spans.jsonl line;
    ``tx obs trace --slowest`` must skip-and-count it, not fail the
    whole read (ISSUE 11 satellite - the pre-fix behavior returned an
    error for the entire file)."""
    tr = tracer()
    with tr.span("whole"):
        pass
    p = str(tmp_path / "spans.jsonl")
    tr.export_jsonl(p)
    with open(p) as f:
        content = f.read()
    with open(p, "w") as f:
        f.write(content)
        f.write('{"trace": "t", "span": 1, "name": "torn mid-wri')
    from transmogrifai_tpu import cli

    rc = cli.main(["obs", "trace", "--path", p, "--slowest", "3"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["lines_skipped"] == 1
    assert out["spans"] == 1
    assert out["trees"][0]["name"] == "whole"
