"""Reader / evaluator / metadata edge-case depth (VERDICT r4 Weak #7:
the reference's test mass concentrates exactly here - reader corner
cases, metadata semantics, evaluator degeneracies).  Each case cites the
behavior it pins rather than a happy path."""
import csv as _csv
import io

import numpy as np
import pytest

from transmogrifai_tpu.evaluators.binary import (
    OpBinaryClassificationEvaluator,
    OpBinScoreEvaluator,
)
from transmogrifai_tpu.evaluators.multiclass import (
    OpMultiClassificationEvaluator,
)
from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
from transmogrifai_tpu.readers import fast_csv
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import PredictionColumn
from transmogrifai_tpu.types.vector_metadata import (
    VectorColumnMeta,
    VectorMetadata,
)


def _write(tmp_path, text, name="t.csv", encoding="utf-8"):
    p = tmp_path / name
    p.write_bytes(text.encode(encoding) if isinstance(text, str) else text)
    return str(p)


# -- CSV reader corner cases -------------------------------------------------

def test_csv_utf8_bom_does_not_corrupt_first_header(tmp_path):
    """A UTF-8 BOM before the header must not leak into the first column
    name (Excel exports lead with one)."""
    path = _write(tmp_path, b"\xef\xbb\xbfid,name\n1,alice\n")
    cols = fast_csv.read_csv_columnar(
        path, {"id": ft.Integral, "name": ft.Text}
    )
    assert len(cols["id"]) == 1
    assert cols["name"].values[0] == "alice"


def test_csv_multibyte_utf8_survives_chunk_boundaries(tmp_path):
    """Multi-byte sequences sliced by the scanner's read chunks must
    reassemble: force tiny chunks over rows of emoji + CJK text."""
    rows = [f"{i},héllo wörld 日本語 {i} 🎉" for i in range(200)]
    path = _write(tmp_path, "id,txt\n" + "\n".join(rows) + "\n")
    cols = fast_csv.read_csv_columnar(
        path, {"id": ft.Integral, "txt": ft.Text}, chunk_bytes=64
    )
    assert len(cols["txt"]) == 200
    assert cols["txt"].values[199] == "héllo wörld 日本語 199 🎉"


def test_csv_quoted_empty_vs_bare_empty(tmp_path):
    """Both '' and "" parse as missing - for numerics AND for text (the
    scanner folds a quoted empty cell to null, Spark's emptyValue-as-null
    default).  Pinned so a change to present-empty-string semantics is a
    deliberate one."""
    path = _write(tmp_path, 'a,b\n,""\n"",x\n')
    cols = fast_csv.read_csv_columnar(path, {"a": ft.Real, "b": ft.Text})
    assert not cols["a"].mask[0] and not cols["a"].mask[1]
    vals = cols["b"].to_list()
    assert vals[0] is None  # quoted empty -> null, same as bare empty
    assert vals[1] == "x"


def test_csv_field_of_only_quotes_and_doubled_quotes(tmp_path):
    path = _write(tmp_path, 'a\n""""\n"a""b"\n')
    cols = fast_csv.read_csv_columnar(path, {"a": ft.Text})
    assert cols["a"].values[0] == '"'
    assert cols["a"].values[1] == 'a"b'


def test_csv_long_row_exceeding_any_single_chunk(tmp_path):
    """One field larger than the chunk size must still parse whole."""
    big = "x" * 10_000
    path = _write(tmp_path, f'a,b\n1,"{big}"\n')
    cols = fast_csv.read_csv_columnar(
        path, {"a": ft.Integral, "b": ft.Text}, chunk_bytes=512
    )
    assert cols["b"].values[0] == big


def test_csv_numeric_junk_masks_not_raises(tmp_path):
    """Unparseable numerics mask out like Spark's permissive read, they
    must not abort the scan."""
    path = _write(tmp_path, "a\n1.5\nnot-a-number\n2.5\n")
    cols = fast_csv.read_csv_columnar(path, {"a": ft.Real})
    assert list(cols["a"].mask) == [True, False, True]
    assert cols["a"].values[2] == 2.5


# -- evaluator degeneracies --------------------------------------------------

def _pred(scores):
    scores = np.asarray(scores, float)
    prob = np.stack([1 - scores, scores], axis=1)
    raw = np.stack([-scores, scores], axis=1)
    return PredictionColumn((scores > 0.5).astype(float), raw, prob)


def test_binary_eval_single_class_labels_do_not_crash():
    """All-positive (or all-negative) validation folds happen under
    stratification edge cases; AuROC is undefined - the evaluator must
    return a finite default, not divide by zero (reference
    OpBinaryClassificationEvaluator guards the same)."""
    ev = OpBinaryClassificationEvaluator()
    y = np.ones(50)
    m = ev.evaluate_arrays(y, _pred(np.linspace(0.1, 0.9, 50)))
    assert np.isfinite(m.AuROC)
    assert m.TP + m.FN == 50 and m.TN == 0 and m.FP == 0
    y0 = np.zeros(50)
    m0 = ev.evaluate_arrays(y0, _pred(np.linspace(0.1, 0.9, 50)))
    assert np.isfinite(m0.AuROC) and m0.TP == 0


def test_binary_eval_all_tied_scores_auroc_is_half():
    """Constant scores rank nothing: AuROC must be exactly 0.5 (the
    pair-counting definition with ties counted half)."""
    ev = OpBinaryClassificationEvaluator()
    y = np.r_[np.ones(30), np.zeros(30)]
    m = ev.evaluate_arrays(y, _pred(np.full(60, 0.4)))
    assert m.AuROC == pytest.approx(0.5)


def test_binary_threshold_curve_endpoints():
    """The threshold sweep's extremes must recover the trivial
    classifiers: everything-positive at the lowest threshold (recall 1)
    and everything-negative at the highest (precision conventionally
    finite, recall 0)."""
    ev = OpBinaryClassificationEvaluator()
    rng = np.random.RandomState(0)
    y = (rng.rand(200) < 0.4).astype(float)
    m = ev.evaluate_arrays(y, _pred(rng.rand(200)))
    rec = m.recall_by_threshold
    assert rec[0] == pytest.approx(1.0)
    assert rec[-1] == pytest.approx(0.0, abs=1e-12)


def test_binscore_brier_identities():
    """BinScore: perfectly-calibrated constant predictor's Brier score
    equals p(1-p); a perfect 0/1 predictor scores 0."""
    ev = OpBinScoreEvaluator(num_bins=10)
    y = np.r_[np.ones(500), np.zeros(500)]
    perfect = ev.evaluate_arrays(y, _pred(y))
    assert perfect.brier_score == pytest.approx(0.0, abs=1e-12)
    const = ev.evaluate_arrays(y, _pred(np.full(1000, 0.5)))
    assert const.brier_score == pytest.approx(0.25, abs=1e-9)


def test_multiclass_eval_missing_class_in_fold():
    """A fold that never sees one class must still produce finite
    macro metrics (empty-class precision/recall treated as 0, not NaN)."""
    ev = OpMultiClassificationEvaluator()
    y = np.r_[np.zeros(30), np.ones(30)]  # class 2 absent
    prob = np.zeros((60, 3))
    prob[np.arange(60), y.astype(int)] = 1.0
    pred = PredictionColumn(y.copy(), np.log(prob + 1e-9), prob)
    m = ev.evaluate_arrays(y, pred)
    assert np.isfinite(m.F1) and np.isfinite(m.Error)
    assert m.Error == pytest.approx(0.0)


def test_regression_eval_constant_target_r2():
    """R^2 against a constant target divides by zero variance; the
    evaluator must return a finite value for the exact-fit case."""
    ev = OpRegressionEvaluator()
    y = np.full(40, 3.14)
    m = ev.evaluate_arrays(y, PredictionColumn(y.copy(), None, None))
    assert m.RootMeanSquaredError == pytest.approx(0.0, abs=1e-12)
    assert np.isfinite(m.R2)


# -- vector-metadata semantics ----------------------------------------------

def _meta(feat, **kw):
    return VectorColumnMeta(
        parent_feature_name=feat, parent_feature_type="Text", **kw
    )


def test_metadata_reindex_idempotent_and_names_stable():
    vm = VectorMetadata("out", (
        _meta("a", indicator_value="x", grouping="a"),
        _meta("a", indicator_value="y", grouping="a"),
        _meta("b"),
    )).reindexed()
    once = vm.column_names()
    again = vm.reindexed().column_names()
    assert once == again  # idempotent
    assert len(set(once)) == 3  # names unique


def test_metadata_select_preserves_provenance_and_json_roundtrip():
    vm = VectorMetadata("out", tuple(
        _meta("f", indicator_value=str(i), grouping="f") for i in range(5)
    )).reindexed()
    sel = vm.select([4, 2])
    assert [m.indicator_value for m in sel.columns] == ["4", "2"]
    back = VectorMetadata.from_json(sel.to_json())
    assert back.column_names() == sel.column_names()
    assert [m.indicator_value for m in back.columns] == ["4", "2"]


def test_metadata_combine_offsets_and_grouping_indices():
    a = VectorMetadata("a", (_meta("a"), _meta("a", indicator_value="n",
                                               grouping="a")))
    b = VectorMetadata("b", (_meta("b"),))
    vm = VectorMetadata.combine("out", [a, b])
    assert vm.size == 3
    gi = vm.grouping_indices()
    assert gi[("a", "a")] == [1]


def test_python_csvreader_strips_bom_too(tmp_path):
    from transmogrifai_tpu.readers.csv_reader import CSVReader

    p = tmp_path / "bom.csv"
    p.write_bytes(b"\xef\xbb\xbfid,name\n1,alice\n")
    raw = CSVReader(str(p)).read_raw()
    assert "id" in raw and raw["name"] == ["alice"]


def test_csv_bom_headerless_numeric_first_cell(tmp_path):
    """Headerless BOM files never pass through _parse_header: the data
    path must strip the BOM or the first numeric cell reads as
    '\\ufeff1' and masks out (fast path would then disagree with the
    utf-8-sig python fallback) - review r5."""
    p = tmp_path / "nb.csv"
    p.write_bytes(b"\xef\xbb\xbf1,2.5\n3,4.5\n")
    cols = fast_csv.read_csv_columnar(
        str(p), {"c0": ft.Real, "c1": ft.Real}, has_header=False
    )
    assert bool(cols["c0"].mask[0]) and cols["c0"].values[0] == 1.0


def test_geolocation_column_validates_ranges():
    """The reference validates coordinates at construction
    (Geolocation.scala:50); (95, 200) must raise with the offending rows
    named, masked rows are exempt, and boundary values pass."""
    from transmogrifai_tpu.types.columns import GeolocationColumn

    with pytest.raises(ValueError, match="rows \\[1\\]"):
        GeolocationColumn(
            np.array([[45.0, -120.0, 1.0], [95.0, 200.0, 1.0]]),
            np.array([True, True]),
        )
    # masked garbage is fine (missing rows carry placeholder zeros)
    GeolocationColumn(
        np.array([[999.0, 999.0, 0.0], [45.0, -120.0, 1.0]]),
        np.array([False, True]),
    )
    GeolocationColumn(
        np.array([[90.0, 180.0, 1.0], [-90.0, -180.0, 0.0]]),
        np.array([True, True]),
    )


def test_avro_reader_gnarly_schema(tmp_path):
    """Hand-built OCF with the schema shapes the easy tests skip: nested
    records, enum decoding to symbols, a THREE-branch union
    (null|double|string), fixed, and map-of-arrays - byte-level encoding
    written here independently of the reader under test."""
    import io
    import json
    import struct

    from transmogrifai_tpu.readers.avro_reader import read_avro_records

    schema = {
        "type": "record", "name": "R", "fields": [
            {"name": "id", "type": "long"},
            {"name": "tag", "type": {"type": "enum", "name": "E",
                                     "symbols": ["A", "B", "C"]}},
            {"name": "val", "type": ["null", "double", "string"]},
            {"name": "inner", "type": {
                "type": "record", "name": "Inner",
                "fields": [
                    {"name": "x", "type": "float"},
                    {"name": "ys",
                     "type": {"type": "array", "items": "int"}},
                ]}},
            {"name": "fx",
             "type": {"type": "fixed", "name": "F", "size": 4}},
            {"name": "m", "type": {"type": "map",
                                   "values": {"type": "array",
                                              "items": "string"}}},
        ],
    }

    def zz(n):
        n = (n << 1) ^ (n >> 63)
        out = b""
        while True:
            b7 = n & 0x7F
            n >>= 7
            if n:
                out += bytes([b7 | 0x80])
            else:
                return out + bytes([b7])

    def enc_str(s):
        b = s.encode()
        return zz(len(b)) + b

    def enc_rec(i):
        out = zz(i)
        out += zz(i % 3)
        if i % 3 == 0:
            out += zz(0)
        elif i % 3 == 1:
            out += zz(1) + struct.pack("<d", i * 1.5)
        else:
            out += zz(2) + enc_str(f"s{i}")
        out += struct.pack("<f", i * 0.5)
        out += zz(2) + zz(i) + zz(i + 1) + zz(0)
        out += bytes([i % 256] * 4)
        out += zz(1) + enc_str("k") + (
            zz(1) + enc_str(f"v{i}") + zz(0)
        ) + zz(0)
        return out

    sync = b"S" * 16
    block = b"".join(enc_rec(i) for i in range(5))
    buf = io.BytesIO()
    buf.write(b"Obj\x01")
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"null"}
    buf.write(zz(len(meta)))
    for k, v in meta.items():
        buf.write(enc_str(k))
        buf.write(zz(len(v)) + v)
    buf.write(zz(0))
    buf.write(sync)
    buf.write(zz(5))
    buf.write(zz(len(block)))
    buf.write(block)
    buf.write(sync)
    path = str(tmp_path / "gnarly.avro")
    with open(path, "wb") as f:
        f.write(buf.getvalue())

    _, recs = read_avro_records(path)
    assert len(recs) == 5
    assert recs[0]["val"] is None
    assert recs[1]["val"] == 1.5
    assert recs[2]["val"] == "s2"
    assert recs[3]["inner"]["ys"] == [3, 4]
    assert recs[4]["tag"] == "B"
    assert recs[1]["m"]["k"] == ["v1"]
    assert recs[2]["fx"] == b"\x02\x02\x02\x02"


def test_workflow_survives_all_null_feature(rng):
    """A 100% null predictor must flow through transmogrification +
    SanityChecker + fit without crashing (the checker drops or zeroes it;
    reference SanityCheckerTest covers the same degeneracy)."""
    import transmogrifai_tpu.dsl  # noqa: F401
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify

    n = 200
    data = {"y": (rng.rand(n) > 0.5).astype(float).tolist(),
            "a": [None] * n,
            "b": rng.randn(n).tolist()}
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fa = FeatureBuilder(ft.Real, "a").as_predictor()
    fb = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([fa, fb])
    checked = fy.sanity_check(vec)
    pred = (
        OpLogisticRegression(reg_param=0.01)
        .set_input(fy, checked).get_output()
    )
    model = (
        OpWorkflow().set_result_features(pred)
        .set_input_dataset(data).train()
    )
    out = model.score(data)
    pcol = [c for c in out.columns().values()
            if hasattr(c, "prediction")][0]
    assert np.isfinite(np.asarray(pcol.prediction)).all()


def test_selector_survives_single_positive_label(rng):
    """One positive among 200 rows through the balancer + 2-fold CV must
    train without crashing (folds may see zero positives; metrics stay
    finite - reference DataBalancer handles the same edge)."""
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector.factories import (
        BinaryClassificationModelSelector,
    )

    n = 200
    y2 = np.zeros(n)
    y2[0] = 1.0
    data = {"y": y2.tolist(), "b": rng.randn(n).tolist()}
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fb = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([fb])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        models_and_parameters=[(OpLogisticRegression(max_iter=5), [{}])],
    )
    pred = sel.set_input(fy, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred)
        .set_input_dataset(data).train()
    )
    ins = model.model_insights()
    assert ins.label_summary["distribution"]["type"] == "discrete"


def test_kitchen_sink_workflow_save_load(tmp_path, rng):
    """One workflow combining the round-5 surfaces - multinomial softmax
    winner, language detection over the widened profile set, NER with the
    surname carry - saved and reloaded with bit-identical probabilities
    (the graph re-pairing must survive multi-output workflows, not just
    single-prediction ones)."""
    import transmogrifai_tpu.dsl  # noqa: F401
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

    n = 180
    centers = np.array([[2.5, 0.0], [-2.5, 1.0], [0.0, -3.0]])
    yv = np.repeat(np.arange(3.0), n // 3)
    texts = [
        "Dr. Okonkwo met the board in Nairobi last week. Okonkwo was "
        "pleased.",
        "La banque centrale a relevé ses taux ce jeudi à Paris.",
        "میری بہن ہسپتال میں کام کرتی ہے اور روز ٹرین سے شہر جاتی ہے۔",
    ] * (n // 3)
    data = {
        "y": yv.tolist(),
        "a": (centers[yv.astype(int), 0] + 0.4 * rng.randn(n)).tolist(),
        "b": (centers[yv.astype(int), 1] + 0.4 * rng.randn(n)).tolist(),
        "txt": texts[:n],
    }

    def build():
        fy = FeatureBuilder(ft.RealNN, "y").as_response()
        fa = FeatureBuilder(ft.Real, "a").as_predictor()
        fb = FeatureBuilder(ft.Real, "b").as_predictor()
        ftxt = FeatureBuilder(ft.Text, "txt").as_predictor()
        langs = ftxt.detect_languages()
        ents = ftxt.recognize_entities()
        vec = transmogrify([fa, fb])
        pred = (
            OpLogisticRegression(reg_param=0.01)
            .set_input(fy, vec).get_output()
        )
        return (
            OpWorkflow()
            .set_result_features(pred, langs, ents)
            .set_input_dataset(data)
        )

    m1 = build().train()
    assert m1.stages[-1].model_params["family"] == "multinomial"
    m1.save(str(tmp_path / "ks"))
    m2 = OpWorkflowModel.load(str(tmp_path / "ks"), build())
    s1, s2 = m1.score(data), m2.score(data)
    p1 = [c for c in s1.columns().values() if hasattr(c, "prediction")][0]
    p2 = [c for c in s2.columns().values() if hasattr(c, "prediction")][0]
    np.testing.assert_array_equal(
        np.asarray(p1.probability), np.asarray(p2.probability)
    )
    langs_out = [v for k, v in s2.columns().items()
                 if "lang" in k.lower()][0]
    assert max(langs_out.values[0], key=langs_out.values[0].get) == "en"
    assert max(langs_out.values[1], key=langs_out.values[1].get) == "fr"
    assert max(langs_out.values[2], key=langs_out.values[2].get) == "ur"
    ner_out = [v for k, v in s2.columns().items()
               if "ner" in k.lower() or "entit" in k.lower()][0]
    assert "okonkwo" in ner_out.values[0]


def test_index_deindex_unseen_semantics(rng):
    """StringIndexer reserves the tail slot for unseen values (NoFilter
    scoring semantics); deindexing that reserved index yields null, and
    missing values stay missing through the round trip."""
    import transmogrifai_tpu.dsl  # noqa: F401
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow

    data = {"c": ["a", "b", "a", "c", "b", "a"]}
    f = FeatureBuilder(ft.PickList, "c").as_predictor()
    idx = f.indexed()
    back = idx.deindexed(["a", "b", "c"])
    model = (
        OpWorkflow().set_result_features(idx, back)
        .set_input_dataset(data).train()
    )
    out = model.score({"c": ["a", "zzz", None, "b"]})
    assert out[idx.name].to_list() == [0.0, 3.0, None, 1.0]
    assert list(out[back.name].values) == ["a", None, None, "b"]
