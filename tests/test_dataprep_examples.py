"""End-to-end tests for the two data-prep example apps (reference:
helloworld/.../dataprep/{JoinsAndAggregates,ConditionalAggregation}.scala)
- the user-visible proof that aggregate/conditional/joined readers compose
into real workflows.  Expected values are hand-derived from the embedded
event tables using the reference's cutoff comparisons
(FeatureAggregator.scala:114-123: predictors strictly before the cutoff,
responses from it, windows inclusive at the far edge)."""

from transmogrifai_tpu.examples.conditional_aggregation import (
    conditional_aggregation_workflow,
)
from transmogrifai_tpu.examples.joins_and_aggregates import (
    joins_and_aggregates_workflow,
)


def test_joins_and_aggregates_end_to_end():
    wf, feats = joins_and_aggregates_workflow()
    model = wf.train()
    scored = model.score()
    cols = scored.columns()
    keys = wf._reader.left.row_keys()
    assert keys == ["u1", "u2", "u3"]

    by = {f.name: cols[f.name].to_list() for f in feats if f.name in cols}
    # u1: 2 clicks yesterday, 1 tomorrow, 2 sends last week, ctr 2/(2+1)
    # u2: no clicks in the yday window (Mar 8 is out), 1 tomorrow, 1 send
    # u3: no click rows at all (left join null side), 1 send in window
    assert by["numClicksYday"] == [2.0, None, None]
    assert by["numClicksTomorrow"] == [1.0, 1.0, None]
    assert by["numSendsLastWeek"] == [2.0, 1.0, 1.0]
    assert [round(v, 4) for v in by["ctr"]] == [0.6667, 0.0, 0.0]


def test_conditional_aggregation_end_to_end():
    wf, feats = conditional_aggregation_workflow()
    model = wf.train()
    scored = model.score()
    cols = scored.columns()
    keys = wf._reader.row_keys()
    # dan never lands on the target page -> dropped
    assert keys == ["ann", "bob", "cat"]

    by = {f.name: cols[f.name].to_list() for f in feats}
    # ann: 3 browse visits strictly before her landing; purchase 30 min
    # after it.  bob: landed with no prior visits (the landing itself is
    # response-side); bought next morning.  cat: 1 prior visit; purchase
    # 3 days later falls OUTSIDE the 1-day response window.
    assert by["numVisitsWeekPrior"] == [3.0, None, 1.0]
    assert by["numPurchasesNextDay"] == [1.0, 1.0, None]


def test_conditional_scoring_reuses_fitted_model():
    wf, feats = conditional_aggregation_workflow()
    model = wf.train()
    first = model.score().columns()
    second = model.score().columns()
    for name in first:
        assert first[name].to_list() == second[name].to_list()
