"""Scale-out serving fleet drills (ISSUE 14).

The three acceptance drills - zero-drop rolling deploy across >= 3
replicas, one replica SIGKILLed mid-run with exact row conservation on
survivors, and router backpressure with every replica full (shed,
never hang) - plus the satellites: per-tenant quotas on the admission
controller, the fleet-aggregated SLO/rollback loop, the one-scrape
fleet Prometheus exposition, the router-overhead CPU floor, the
``tx fleet`` CLI, and the autotune report over an aggregation dir.

All drills are seeded: the drill pipeline's data seed, the fault specs
(``on=``/``every=`` triggers), and the deterministic canary hash split
pin every run to the same schedule.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time

import pytest

from transmogrifai_tpu.fleet import (
    FleetController,
    FleetRouter,
    encode_records,
    merge_serving_snapshots,
)
from transmogrifai_tpu.registry import ModelRegistry
from transmogrifai_tpu.serving import TenantQuotaError
from transmogrifai_tpu.serving.admission import AdmissionController
from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline

WORKFLOW_SPEC = "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline"


# ---------------------------------------------------------------------------
# shared registry: one tiny trained model published as three versions
# (v1 stable; v2, v3 candidates for the rolling-deploy / canary drills)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def fleet_registry(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("fleet-registry"))
    wf, _data, records, pred_name = tiny_drill_pipeline()
    model = wf.train()
    reg = ModelRegistry(root)
    v1 = reg.publish(model, stage="stable")
    v2 = reg.publish(model)
    v3 = reg.publish(model)
    return {
        "root": root, "records": records, "pred_name": pred_name,
        "v1": v1.version, "v2": v2.version, "v3": v3.version,
    }


def _controller(fleet_registry, tmp_path, n_replicas, **kw):
    kw.setdefault("router_kw", {})
    kw["router_kw"].setdefault("max_in_flight_per_replica", 2)
    kw["router_kw"].setdefault("max_queue", 64)
    return FleetController(
        fleet_registry["root"], WORKFLOW_SPEC,
        n_replicas=n_replicas, work_dir=str(tmp_path / "fleet"),
        ship_interval_s=0.15, **kw,
    )


# ---------------------------------------------------------------------------
# satellite: per-tenant quotas on the admission controller
# ---------------------------------------------------------------------------
def test_tenant_quota_bounds_one_tenant_not_the_rest():
    ac = AdmissionController(max_queue=10, tenant_quota=0.3)
    assert ac.tenant_limit == 3
    for _ in range(3):
        ac.admit({"r": 1}, tenant="hog")
    with pytest.raises(TenantQuotaError) as ei:
        ac.admit({"r": 1}, tenant="hog")
    assert ei.value.tenant == "hog" and ei.value.limit == 3
    # other tenants (and the anonymous pool) still admit
    ac.admit({"r": 1}, tenant="polite")
    ac.admit({"r": 1})
    assert ac.tenants_held() == {"hog": 3, "polite": 1, None: 1}
    # dequeue releases the hog's slots: it can admit again
    live, shed = ac.take(10)
    assert len(live) == 5 and not shed
    assert ac.tenants_held() == {}
    ac.admit({"r": 1}, tenant="hog")


def test_tenant_quota_is_off_by_default():
    ac = AdmissionController(max_queue=4)
    for _ in range(4):
        ac.admit({"r": 1}, tenant="only")
    assert ac.tenant_limit is None


def test_scheduler_counts_shed_quota_and_scrapes_it():
    from transmogrifai_tpu.obs import prometheus_text_from_json
    from transmogrifai_tpu.serving import (
        MicroBatchScheduler,
        ServingTelemetry,
        compile_endpoint,
    )

    wf, _data, records, _pred = tiny_drill_pipeline(n=40)
    model = wf.train()
    telemetry = ServingTelemetry()
    endpoint = compile_endpoint(model, telemetry=telemetry,
                                batch_buckets=(1, 8, 32))
    with MicroBatchScheduler(endpoint, start=False, max_queue=10,
                             tenant_quota=0.2,
                             telemetry=telemetry) as sched:
        for _ in range(2):
            sched.submit(records[0], tenant="hog")
        with pytest.raises(TenantQuotaError):
            sched.submit(records[0], tenant="hog")
        sched.submit(records[0], tenant="other")  # unaffected
        sched.run_once()
    snap = telemetry.snapshot()
    assert snap["shed_quota"] == 1
    assert snap["rows_scored"] == 3
    from transmogrifai_tpu.obs import metrics_registry

    text = prometheus_text_from_json(metrics_registry().to_json())
    assert "tx_serving_shed_quota" in text


# ---------------------------------------------------------------------------
# satellite: merged fleet rollback snapshots
# ---------------------------------------------------------------------------
def test_merge_serving_snapshots_sums_counters_maxes_tails():
    a = {"rows_scored": 10, "rows_failed": 1,
         "breaker": {"opens": 1, "rows_nonfinite": 2},
         "latency_ms": {"p99": 5.0},
         "data_contract": {"drift_js_max": 0.1},
         "model_version": "v1", "generation": 1}
    b = {"rows_scored": 20, "rows_failed": 2,
         "breaker": {"opens": 0, "rows_nonfinite": 1},
         "latency_ms": {"p99": 9.0},
         "data_contract": {"drift_js_max": 0.05}}
    merged = merge_serving_snapshots([a, b])
    assert merged["rows_scored"] == 30
    assert merged["rows_failed"] == 3
    assert merged["breaker"]["opens"] == 1
    assert merged["breaker"]["rows_nonfinite"] == 3
    assert merged["latency_ms"]["p99"] == 9.0
    assert merged["data_contract"]["drift_js_max"] == 0.1
    assert merged["replicas"] == 2
    assert merged["model_version"] == "v1"


# ---------------------------------------------------------------------------
# channel: bounded waits, closed-peer detection
# ---------------------------------------------------------------------------
def test_channel_roundtrip_idle_and_peer_death():
    import socket as socket_mod

    from transmogrifai_tpu.fleet.channel import (
        OP_SCORE,
        ChannelClosedError,
        FleetChannel,
    )

    a, b = socket_mod.socketpair(socket_mod.AF_UNIX,
                                 socket_mod.SOCK_STREAM)
    ca, cb = FleetChannel(a), FleetChannel(b)
    payload = encode_records([{"x": 1.0}] * 8)
    ca.send(OP_SCORE, 7, {"tenant": None, "n_rows": 8}, payload)
    op, rid, meta, got = cb.recv()
    assert (op, rid, meta["n_rows"], got) == (OP_SCORE, 7, 8, payload)
    # idle recv hands back within ~one quantum, never blocks
    t0 = time.perf_counter()
    assert cb.recv(idle_return=True) is None
    assert time.perf_counter() - t0 < 1.0
    # peer death surfaces as ChannelClosedError, not a hang
    ca.close()
    with pytest.raises(ChannelClosedError):
        cb.recv()


def test_router_with_no_replicas_fails_loudly_not_hanging():
    router = FleetRouter(max_queue=4)
    try:
        from transmogrifai_tpu.fleet import FleetError

        req = router.submit(records=[{"x": 1}])
        with pytest.raises(FleetError):
            req.wait(5.0)
    finally:
        router.close()


# ---------------------------------------------------------------------------
# drill 1: zero-drop rolling deploy across 3 replicas
# ---------------------------------------------------------------------------
def test_rolling_deploy_zero_drop_three_replicas(fleet_registry,
                                                 tmp_path):
    records = fleet_registry["records"]
    batch = records[:40]
    with _controller(fleet_registry, tmp_path, 3) as fc:
        fc.router.score_batch(batch, timeout_s=60.0)  # warm
        results: list = []
        errors: list = []
        stop = threading.Event()

        def pump() -> None:
            while not stop.is_set():
                try:
                    req = fc.router.submit(records=batch)
                    res = req.wait(60.0)
                    results.append(res)
                except Exception as e:  # noqa: BLE001 - the drill counts
                    errors.append(repr(e))

        threads = [threading.Thread(target=pump) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        report = fc.rolling_deploy(fleet_registry["v2"])
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=60.0)

        # the roll covered every replica, one at a time
        assert [s["instance"] for s in report] == [
            "replica-0", "replica-1", "replica-2"]
        # zero dropped: every submitted request came back scored
        assert errors == []
        assert all(res.n_rows == len(batch) for res in results)
        # zero mixed-generation responses: each response names exactly
        # one (version, generation) pair, and both generations served
        versions = {res.version for res in results}
        assert all(res.version is not None
                   and res.generation is not None for res in results)
        assert versions <= {fleet_registry["v1"], fleet_registry["v2"]}
        assert fleet_registry["v2"] in versions
        # after the roll every replica serves v2
        for h in fc.router.live_replicas():
            doc = fc.router.control(h.instance, "status")
            assert doc["version"] == fleet_registry["v2"]
        # registry agrees: v2 is the stable pointer
        assert fc.registry.stable == fleet_registry["v2"]
        # exact conservation, double-entry: the router's delivered-rows
        # ledger equals the client-side sum, split by generation
        snap = fc.router.snapshot()
        assert snap["rows_ok"] == sum(r.n_rows for r in results) \
            + len(batch)  # + the warm batch
        assert sum(snap["rows_by_generation"].values()) \
            == snap["rows_ok"]

        # acceptance: ONE Prometheus scrape of the aggregation dir
        # covers the whole fleet - every replica under its own instance
        # label plus the fleet rollup
        time.sleep(0.4)  # one shipper beat
        text = fc.aggregator.prometheus_text()
        for i in range(3):
            assert f'instance="replica-{i}"' in text
        assert 'instance="fleet",agg="sum"' in text
        assert "tx_serving_rows_scored" in text

        # `tx fleet status` renders the controller's one consistent doc
        from transmogrifai_tpu.cli import main as cli_main

        rc = cli_main(["fleet", "status", "--path", fc.control_dir])
        assert rc == 0
        status_doc = json.load(open(
            os.path.join(fc.control_dir, "fleet_status.json")))
        assert set(status_doc["replicas"]) == {
            "replica-0", "replica-1", "replica-2"}
        for rep in status_doc["replicas"].values():
            assert rep["running"] is True

        # satellite: a deployment controller pointed at the published
        # status document carries the SAME one fleet view in its
        # summary (not N shard re-reads)
        from transmogrifai_tpu.registry import DeploymentController

        ctl = DeploymentController()
        ctl.fleet_status_source = os.path.join(fc.control_dir,
                                               "fleet_status.json")
        summary = ctl.summary_json()
        assert set(summary["fleet"]["replicas"]) == {
            "replica-0", "replica-1", "replica-2"}
        for rep in summary["fleet"]["replicas"].values():
            assert "generation" in rep and "heartbeat_age_s" in rep \
                and "in_flight" in rep


# ---------------------------------------------------------------------------
# drill 2: one replica SIGKILLed mid-run, exact conservation on survivors
# ---------------------------------------------------------------------------
def test_replica_sigkill_conserves_every_accepted_request(
        fleet_registry, tmp_path):
    records = fleet_registry["records"]
    batch = records[:30]
    # slow batches keep every replica busy so the victim dies with
    # requests genuinely in flight; no restarts - survivors carry the
    # load (the controller restart path is drilled separately)
    with _controller(
        fleet_registry, tmp_path, 3, max_restarts=0,
        worker_env={"TX_FAULTS": "serving.slow_batch:every=1:delay=0.05"},
    ) as fc:
        fc.router.score_batch(batch, timeout_s=60.0)  # warm
        delivered: list = []
        errors: list = []
        submitted = 60

        def pump(k: int) -> None:
            for _ in range(k):
                try:
                    res = fc.router.submit(records=batch).wait(120.0)
                    delivered.append(res.n_rows)
                except Exception as e:  # noqa: BLE001 - the drill counts
                    errors.append(repr(e))

        threads = [threading.Thread(target=pump, args=(submitted // 4,))
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # the fleet is saturated (2 in flight each)
        victim = fc._replicas["replica-1"]
        os.kill(victim.proc.pid, signal.SIGKILL)
        for t in threads:
            t.join(timeout=180.0)

        # EXACT conservation: every accepted request was answered on a
        # survivor - nothing lost, nothing double-delivered
        assert errors == []
        assert len(delivered) == submitted
        assert sum(delivered) == submitted * len(batch)
        snap = fc.router.snapshot()
        assert snap["replica_deaths"] == 1
        assert snap["retries"] >= 1  # the victim died holding work
        assert snap["rows_ok"] == submitted * len(batch) + len(batch)
        # the survivors are intact and still serving
        live = {h.instance for h in fc.router.live_replicas()}
        assert live == {"replica-0", "replica-2"}
        post = fc.router.score_batch(batch, timeout_s=60.0)
        assert len(post) == len(batch)


# ---------------------------------------------------------------------------
# drill 3: router backpressure - every replica full -> shed, never hang
# ---------------------------------------------------------------------------
def test_router_backpressure_sheds_never_hangs(fleet_registry,
                                               tmp_path):
    records = fleet_registry["records"]
    batch = records[:20]
    with _controller(
        fleet_registry, tmp_path, 1,
        router_kw={"max_in_flight_per_replica": 1, "max_queue": 3},
        worker_env={"TX_FAULTS": "serving.slow_batch:every=1:delay=0.3"},
    ) as fc:
        from transmogrifai_tpu.serving import QueueFullError

        fc.router.score_batch(batch, timeout_s=60.0)  # warm
        pending = []
        sheds = 0
        t0 = time.perf_counter()
        # the single replica sustains ~3 batches/s; flood it: 1 in
        # flight + 3 queued saturate, everything beyond MUST shed fast
        for _ in range(12):
            try:
                pending.append(fc.router.submit(records=batch))
            except QueueFullError:
                sheds += 1
        submit_wall = time.perf_counter() - t0
        assert sheds >= 6, "a full fleet must shed at the front door"
        assert submit_wall < 2.0, "shedding must be fast, not a hang"
        assert fc.router.snapshot()["shed_queue_full"] == sheds
        # everything actually admitted completes; nothing hangs
        for req in pending:
            res = req.wait(60.0)
            assert res.n_rows == len(batch)


# ---------------------------------------------------------------------------
# fleet-wide canary: aggregated signals + firing SLO roll back everywhere
# ---------------------------------------------------------------------------
def test_fleet_slo_and_signals_roll_canary_back_everywhere(
        fleet_registry, tmp_path):
    from transmogrifai_tpu.obs.slo import SLObjective

    records = fleet_registry["records"]
    batch = records[:40]
    # the fleet-level SLO: any NaN-guard refusal across the fleet blows
    # the objective (threshold over the merged docs' MAX)
    slo = SLObjective(
        name="fleet-nonfinite", kind="threshold",
        metric="serving.breaker.rows_nonfinite", objective=0.5,
        windows_s=(30.0, 5.0),
    )
    with _controller(
        fleet_registry, tmp_path, 2, slo_objectives=[slo],
        worker_env={"TX_FAULTS": "canary.regression:every=1"},
    ) as fc:
        out = fc.start_canary(fleet_registry["v3"], fraction=0.5)
        assert all(doc.get("ok") for doc in out.values())
        assert fc.registry.canary == fleet_registry["v3"]
        # pump traffic: the deterministic hash split sends ~half the
        # rows to the canary on EVERY replica, where the armed
        # canary.regression fault poisons live outputs through the real
        # NaN-guard accounting
        for _ in range(6):
            fc.router.score_batch(batch, timeout_s=60.0)
        time.sleep(0.5)  # shards ship the poisoned canary telemetry
        decision = fc.check_canary()
        assert decision is not None and decision.rollback
        signals = {r["signal"] for r in decision.reasons}
        assert "nonfinite_rows" in signals
        assert any(s.startswith("slo:fleet-nonfinite")
                   for s in signals), signals
        # the rollback reached EVERY replica and the registry
        assert fc.canary_version is None
        for h in fc.router.live_replicas():
            doc = fc.router.control(h.instance, "status")
            assert doc["canary_version"] is None
        assert fc.registry.get(
            fleet_registry["v3"]).stage == "rolled_back"
        # serving continues on stable after the rollback
        post = fc.router.score_batch(batch, timeout_s=60.0)
        assert len(post) == len(batch)


# ---------------------------------------------------------------------------
# CPU floor: router overhead <= 10% of direct endpoint scoring
# ---------------------------------------------------------------------------
def test_router_cpu_overhead_within_floor_of_direct(tmp_path):
    """The dispatch layer must never become the fleet's bottleneck:
    the router process's OWN CPU per routed row (framing via one
    sendmsg gather call, least-loaded pick, single-buffer recv_into,
    response ledger - the wire payload passes through encoded, decoded
    lazily by the caller) stays <= 10% of what scoring a row directly
    on an in-process endpoint costs.  Measured at the REAL fleet
    workload (the full mixed-type serving pipeline the fleet bench
    drives) and an AMORTIZING wire batch (8192 rows): the router's
    per-request fixed cost - thread wakeups, syscalls, whose kernel
    accounting swings hundreds of us per message on this host - is
    designed to amortize, and the per-ROW cost is the floor's
    question.  Best-of-3 on CPU time so wall noise cannot flake it -
    process_time excludes the blocked waits, which is exactly the
    router-overhead question."""
    from collections import deque

    from transmogrifai_tpu.serving import compile_endpoint
    from transmogrifai_tpu.testkit.drills import serving_fleet_workflow

    wf, records = serving_fleet_workflow()
    model = wf.train()
    root = str(tmp_path / "registry")
    ModelRegistry(root).publish(model, stage="stable")
    buckets = (1, 8, 32, 128, 512, 2048, 8192)
    n_rows = 8192
    batch = (records * (n_rows // len(records) + 1))[:n_rows]
    endpoint = compile_endpoint(model, batch_buckets=buckets)
    endpoint.score_batch(batch)  # warm
    n_iters = 8
    direct_cpu_per_row = float("inf")
    for _ in range(3):
        t0 = time.process_time()
        for _ in range(n_iters):
            endpoint.score_batch(batch)
        direct_cpu_per_row = min(
            direct_cpu_per_row,
            (time.process_time() - t0) / (n_iters * n_rows))
    with FleetController(
        root, "transmogrifai_tpu.testkit.drills:serving_fleet_workflow",
        n_replicas=1, work_dir=str(tmp_path / "fleet"),
        monitor_interval_s=5.0,
        router_kw={"max_in_flight_per_replica": 3, "max_queue": 64},
        worker_args=["--buckets", ",".join(str(b) for b in buckets)],
    ) as fc:
        payload = encode_records(batch)
        fc.router.submit(payload=payload, n_rows=n_rows).wait(60.0)
        router_cpu_per_row = float("inf")
        # the routed window runs MORE iterations than the direct one:
        # the router's per-row CPU is ~30x smaller, and the window must
        # still span many scheduler jiffies for process_time to resolve
        # the ratio honestly
        n_routed = 4 * n_iters
        for _ in range(3):
            rows = 0
            pend: deque = deque()
            t0 = time.process_time()
            for _ in range(n_routed):
                pend.append(fc.router.submit(payload=payload,
                                             n_rows=n_rows))
                if len(pend) >= 3:
                    rows += pend.popleft().wait(60.0).n_rows
            while pend:
                res = pend.popleft()
                rows += res.wait(60.0).n_rows
            router_cpu_per_row = min(
                router_cpu_per_row, (time.process_time() - t0) / rows)
            assert rows == n_routed * n_rows
        # decode outside the measured window proves the payload is real
        assert len(res.wait(1.0).results) == n_rows
    assert router_cpu_per_row <= 0.10 * direct_cpu_per_row, (
        f"router overhead {router_cpu_per_row * 1e6:.2f}us/row vs "
        f"direct {direct_cpu_per_row * 1e6:.2f}us/row"
    )


# ---------------------------------------------------------------------------
# operator surfaces over saved artifacts (no live fleet needed)
# ---------------------------------------------------------------------------
def test_fleet_status_cli_over_agg_dir_and_drain_command(tmp_path,
                                                         capsys):
    from transmogrifai_tpu.cli import main as cli_main
    from transmogrifai_tpu.obs import metrics_registry, ship_now

    agg = tmp_path / "obs"
    metrics_registry().counter("drill.fleet_cli").inc()
    ship_now(str(agg), instance="replica-9",
             extra={"fleet": {"generation": 3, "version": "v7",
                              "rows_scored": 123}})
    rc = cli_main(["fleet", "status", "--path", str(agg)])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["replicas"]["replica-9"]["fleet"]["version"] == "v7"
    assert doc["replicas"]["replica-9"]["heartbeat_age_s"] is not None
    # drain queues an atomic command file the controller consumes
    control = tmp_path / "control"
    rc = cli_main(["fleet", "drain", "--path", str(control),
                   "--replica", "replica-9"])
    assert rc == 0
    cmd = json.load(open(control / "commands" / "replica-9.json"))
    assert cmd == {"replica": "replica-9", "drain": True,
                   "t": pytest.approx(cmd["t"])}
    rc = cli_main(["fleet", "drain", "--path", str(control),
                   "--replica", "replica-9", "--undrain"])
    assert rc == 0
    cmd = json.load(open(control / "commands" / "replica-9.json"))
    assert cmd["drain"] is False
    # status on garbage fails loudly with exit 2
    rc = cli_main(["fleet", "status", "--path", str(tmp_path / "nope")])
    assert rc == 2


def test_autotune_report_over_aggregation_dir(tmp_path):
    from transmogrifai_tpu.autotune import report_from_path
    from transmogrifai_tpu.obs import metrics_registry, ship_now
    from transmogrifai_tpu.serving import ServingTelemetry

    tel = ServingTelemetry()
    tel.set_tuned_knobs({"max_batch_size": 256}, source="autotune")
    metrics_registry().counter("autotune.observations").inc(3)
    agg = tmp_path / "obs"
    ship_now(str(agg), instance="replica-0")
    doc = report_from_path(str(agg))
    rep = doc["replicas"]["replica-0"]
    assert "autotune.observations" in rep["series"]
    knob_views = list(rep["serving_knobs"].values())
    assert any(v["knob_source"] == "autotune"
               and v["tuned_knobs"].get("max_batch_size") == 256.0
               for v in knob_views)
    assert doc["fleet"]["shards_live"] == 1


def test_router_reads_observed_throughput_from_shards():
    """Satellite: dispatch weights follow the shards' observed
    batch_rows_per_s (a fast replica reads as a shorter expected
    wait)."""
    router = FleetRouter(start=False)
    try:
        from transmogrifai_tpu.fleet.channel import FleetChannel
        import socket as socket_mod

        a, _b = socket_mod.socketpair(socket_mod.AF_UNIX,
                                      socket_mod.SOCK_STREAM)
        from transmogrifai_tpu.fleet.router import ReplicaHandle

        fast = ReplicaHandle("replica-0", FleetChannel(a))
        slow = ReplicaHandle("replica-1", FleetChannel(_b))
        router._handles = {"replica-0": fast, "replica-1": slow}
        docs = [
            {"instance": "replica-0",
             "views": {"serving/0": {"batch_rows_per_s": 100000.0,
                                     "latency_ms": {"p99": 4.0},
                                     "queue_depth": {},
                                     "rows_scored": 10}}},
            {"instance": "replica-1",
             "views": {"serving/0": {"batch_rows_per_s": 10000.0,
                                     "latency_ms": {"p99": 40.0},
                                     "queue_depth": {},
                                     "rows_scored": 10}}},
        ]
        assert router.refresh_from_shards(docs) == 2
        assert fast.expected_wait_s(512) < slow.expected_wait_s(512)
        assert router._pick(512) is fast
    finally:
        router.close()
