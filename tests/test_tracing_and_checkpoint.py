"""Tracing metrics + CV checkpoint/resume tests (aux subsystems; reference:
utils/.../spark/OpSparkListener.scala for metrics; checkpointing is the
TPU-pod preemption gap called out in SURVEY §5.3)."""
import json
import os

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.selector.validator import OpCrossValidation
from transmogrifai_tpu.types import feature_types as ft


def test_stage_metrics_collected(rng):
    n = 100
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    vec = transmogrify([a])
    pred = OpLogisticRegression().set_input(y, vec).get_output()
    model = OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    sm = model.summary_json()["stageMetrics"]
    ops = {s["operation"] for s in sm["stages"]}
    assert "OpLogisticRegression" in ops
    phases = {s["phase"] for s in sm["stages"]}
    assert phases == {"fit", "transform"}
    assert all(s["wall_s"] >= 0 for s in sm["stages"])
    assert sm["by_operation"]


def test_cv_checkpoint_resume(tmp_path, rng):
    n, d = 200, 4
    X = rng.randn(n, d)
    y = (rng.rand(n) > 0.5).astype(float)
    grid = [{"max_iter": 10, "reg_param": r} for r in (0.001, 0.1)]
    path = str(tmp_path / "cv.json")
    cv = OpCrossValidation(
        num_folds=2, evaluator=OpBinaryClassificationEvaluator(),
        checkpoint_path=path,
    )
    r1 = cv.validate([(OpLogisticRegression(), grid)], X, y)
    assert os.path.exists(path)
    saved = json.load(open(path))
    assert len(saved) == 2

    # resume: poison fit so any recomputation would crash -> must come
    # entirely from the checkpoint
    class Boom(OpLogisticRegression):
        def fit_arrays(self, *a, **k):
            raise AssertionError("should not refit: checkpoint resume")

        fit_arrays_batched = property()

    cv2 = OpCrossValidation(
        num_folds=2, evaluator=OpBinaryClassificationEvaluator(),
        checkpoint_path=path,
    )
    r2 = cv2.validate([(Boom(), grid)], X, y)
    assert r2.best_metric == pytest.approx(r1.best_metric)
    assert r2.best_params == r1.best_params
