"""Chunked native CSV ingestion (readers/fast_csv.py).

Parity with the python csv module on RFC-4180 quoting, chunk-boundary
alignment (including quoted embedded newlines), numeric parsing semantics,
and the double-buffered device ingest.  Reference contract:
readers/.../DataReader.scala:173 generateDataFrame.
"""
import csv as _csv
import io
import os

import numpy as np
import pytest

from transmogrifai_tpu.readers import fast_csv
from transmogrifai_tpu.types import feature_types as ft

pytestmark = pytest.mark.skipif(
    not fast_csv.fast_path_available(), reason="native CSV kernels unavailable"
)


def _write(tmp_path, text, name="t.csv"):
    p = tmp_path / name
    p.write_bytes(text.encode("utf-8"))
    return str(p)


def test_basic_parity_with_python_reader(tmp_path, rng):
    n = 500
    rows = []
    for i in range(n):
        age = "" if i % 7 == 0 else f"{rng.rand() * 80:.3f}"
        name = f"name {i}" if i % 5 else f'quo"ted, {i}'
        rows.append([str(i), age, name])
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["id", "age", "name"])
    w.writerows(rows)
    path = _write(tmp_path, buf.getvalue())

    cols = fast_csv.read_csv_columnar(
        path, {"id": ft.Integral, "age": ft.Real, "name": ft.Text}
    )
    assert len(cols["id"]) == n
    assert np.array_equal(cols["id"].values, np.arange(n, dtype=float))
    # empty numeric -> masked out
    assert not cols["age"].mask[0] and cols["age"].mask[1]
    expect_age = [None if i % 7 == 0 else float(f"{r[1]}")
                  for i, r in enumerate(rows)]
    got_age = cols["age"].to_list()
    for e, g in zip(expect_age, got_age):
        assert (e is None) == (g is None)
        if e is not None:
            assert abs(e - g) < 1e-9
    # quoted cells incl. escaped quotes and embedded commas
    assert cols["name"].values[6] == "name 6"
    assert cols["name"].values[5] == 'quo"ted, 5'
    assert cols["name"].values[0] == 'quo"ted, 0'


def test_quoted_newline_across_chunk_boundary(tmp_path):
    # rows large enough that a tiny chunk size forces boundaries inside
    # quoted multi-line cells
    rows = []
    for i in range(50):
        rows.append([str(i), f'line1 {i}\nline2 "{i}" end', f"{i * 1.5}"])
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["k", "blob", "x"])
    w.writerows(rows)
    path = _write(tmp_path, buf.getvalue())

    cols = fast_csv.read_csv_columnar(
        path, {"k": ft.Integral, "blob": ft.Text, "x": ft.Real},
        chunk_bytes=64,
    )
    assert len(cols["k"]) == 50
    assert np.array_equal(cols["k"].values, np.arange(50, dtype=float))
    assert cols["blob"].values[7] == 'line1 7\nline2 "7" end'
    assert np.allclose(cols["x"].values, np.arange(50) * 1.5)


def test_crlf_and_no_trailing_newline(tmp_path):
    path = _write(tmp_path, "a,b\r\n1,x\r\n2,y")
    cols = fast_csv.read_csv_columnar(path, {"a": ft.Real, "b": ft.Text})
    assert np.array_equal(cols["a"].values, [1.0, 2.0])
    assert list(cols["b"].values) == ["x", "y"]


def test_numeric_parse_python_float_semantics(tmp_path):
    """The native parser must match float(raw): whitespace-padded numbers
    parse, trailing garbage invalidates, long cells parse in full."""
    long_num = "1." + "1" * 80  # 82-char cell: no silent 63-byte prefix
    path = _write(
        tmp_path,
        "a\n 1.5 \n1 x\n" + long_num + "\nnan\n2e3\n",
    )
    cols = fast_csv.read_csv_columnar(path, {"a": ft.Real})
    vals, mask = cols["a"].values, cols["a"].mask
    assert mask[0] and vals[0] == 1.5        # "  1.5  " ok like float()
    assert not mask[1]                        # "1 x" invalid like float()
    assert mask[2] and vals[2] == float(long_num)
    assert not mask[3] and vals[3] == 0.0  # "nan" -> missing (python parity)
    assert mask[4] and vals[4] == 2000.0


def test_numeric_parse_hex_and_underscores(tmp_path):
    """float() parity corners: no C99 hex floats; PEP-515 underscores
    strip only between digits."""
    path = _write(tmp_path, "a\n0x10\n1_000\n_1\n1_\n1__0\ninf\n-2.5\n")
    cols = fast_csv.read_csv_columnar(path, {"a": ft.Real})
    vals, mask = cols["a"].values, cols["a"].mask
    assert not mask[0]                      # float("0x10") raises
    assert mask[1] and vals[1] == 1000.0    # float("1_000") == 1000.0
    assert not mask[2] and not mask[3]      # leading/trailing underscore
    assert not mask[4]                      # doubled underscore
    assert mask[5] and np.isinf(vals[5])
    assert mask[6] and vals[6] == -2.5


def test_empty_and_header_only_files(tmp_path):
    import pytest as _pytest

    empty = _write(tmp_path, "", name="empty.csv")
    with _pytest.raises(KeyError):
        fast_csv.read_csv_columnar(empty, {"a": ft.Real})
    header_only = _write(tmp_path, "a,b,c", name="h.csv")  # no newline
    cols = fast_csv.read_csv_columnar(header_only, {"a": ft.Real})
    assert len(cols["a"]) == 0


def test_short_rows_pad_missing(tmp_path):
    path = _write(tmp_path, "a,b,c\n1,x\n2,y,3\n")
    cols = fast_csv.read_csv_columnar(
        path, {"a": ft.Real, "b": ft.Text, "c": ft.Real}
    )
    assert cols["c"].to_list() == [None, 3.0]


def test_csvreader_uses_fast_path_same_result(tmp_path, rng):
    """CSVReader.generate_dataset fast output == python-path output."""
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.readers.csv_reader import CSVReader

    n = 300
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["y", "x", "c"])
    for i in range(n):
        w.writerow([i % 2, "" if i % 11 == 0 else f"{rng.randn():.6f}",
                    ["u", "v", ""][i % 3]])
    path = _write(tmp_path, buf.getvalue())

    y = FeatureBuilder(ft.RealNN, "y").as_response()
    x = FeatureBuilder(ft.Real, "x").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    feats = [y, x, c]

    fast_ds = CSVReader(path).generate_dataset(feats)

    slow = CSVReader(path)
    raw = slow.read_raw()
    from transmogrifai_tpu.readers.csv_reader import _parse_cell
    from transmogrifai_tpu.types.columns import column_from_list
    from transmogrifai_tpu.types.dataset import Dataset

    slow_ds = Dataset({
        f.name: column_from_list(
            [_parse_cell(v, f.ftype) for v in raw[f.name]], f.ftype
        )
        for f in feats
    })
    for f in feats:
        a, b = fast_ds[f.name], slow_ds[f.name]
        assert a.to_list() == b.to_list(), f.name


def test_device_ingest_double_buffered(tmp_path, rng):
    n = 2000
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["x1", "x2", "skip", "x3"])
    M = rng.randn(n, 3)
    for i in range(n):
        w.writerow([f"{M[i,0]:.6f}", "" if i == 17 else f"{M[i,1]:.6f}",
                    "text", f"{M[i,2]:.6f}"])
    path = _write(tmp_path, buf.getvalue())

    ingest = fast_csv.DeviceCSVIngest(
        path, ["x1", "x2", "x3"],
        {"x1": ft.Real, "x2": ft.Real, "x3": ft.Real},
        chunk_bytes=4096,  # force many chunks through the double buffer
    )
    X, mask, rows = ingest.to_device()
    assert rows == n and X.shape == (n, 3)
    Xh = np.asarray(X)
    assert np.allclose(Xh[~np.isnan(M @ np.ones(3))][:, 0],
                       M[:, 0], atol=1e-5)
    assert not bool(mask[17, 1]) and float(X[17, 1]) == 0.0
    assert np.allclose(Xh[16, :], M[16, :], atol=1e-5)


def test_titanic_through_fast_reader():
    """The real Titanic CSV (headerless) parses identically via the fast
    path inside the example workflow's reader."""
    from transmogrifai_tpu.examples.titanic import TITANIC_CSV
    from transmogrifai_tpu.readers.csv_reader import CSVReader

    if not os.path.exists(TITANIC_CSV):
        pytest.skip("titanic csv not available on this host")

    headers = ["id", "survived", "pClass", "name", "sex", "age", "sibSp",
               "parCh", "ticket", "fare", "cabin", "embarked"]
    schema = {"survived": ft.RealNN, "age": ft.Real, "sex": ft.PickList,
              "name": ft.Text, "fare": ft.Real}
    cols = fast_csv.read_csv_columnar(
        TITANIC_CSV, schema, headers=headers, has_header=False
    )
    r = CSVReader(TITANIC_CSV, headers=headers, has_header=False)
    raw = r.read_raw()
    assert len(cols["survived"]) == len(raw["survived"])
    surv = [float(v) for v in raw["survived"]]
    assert np.array_equal(cols["survived"].values, surv)
    ages = [None if v is None else float(v) for v in raw["age"]]
    assert cols["age"].to_list() == ages
    assert list(cols["name"].values) == [v for v in raw["name"]]


def test_random_adversarial_parity_with_python_csv(tmp_path):
    """Random cells - embedded commas, escaped quotes, newlines inside
    quoted fields, unicode, blanks - must parse identically to python's
    csv module at chunk sizes that split rows, quotes, and multi-byte
    characters across chunk boundaries."""
    rng = np.random.RandomState(77)
    pieces = ["plain", 'quo"te', "comma,inside", "new\nline", "Ünïcødé…",
              "", "  spaced  ", "'single'", '""', "end\"quote"]
    n = 300
    rows = []
    for i in range(n):
        cells = [str(i)]
        for _ in range(3):
            k = int(rng.randint(len(pieces)))
            cells.append(pieces[k] + (str(rng.randint(10)) if rng.rand() < 0.5 else ""))
        rows.append(cells)
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["id", "a", "b", "c"])
    w.writerows(rows)
    text = buf.getvalue()
    path = _write(tmp_path, text)

    expect = list(_csv.reader(io.StringIO(text)))[1:]
    schema = {"id": ft.Integral, "a": ft.Text, "b": ft.Text, "c": ft.Text}
    for chunk in (37, 256, 4096, fast_csv.DEFAULT_CHUNK_BYTES):
        cols = fast_csv.read_csv_columnar(path, schema, chunk_bytes=chunk)
        assert len(cols["id"]) == n, chunk
        for j, name in enumerate(("a", "b", "c"), start=1):
            got = cols[name].to_list()
            for i in range(n):
                want = expect[i][j] or None  # blank text cell -> null
                assert got[i] == want, (chunk, name, i, got[i], want)


def test_unicode_digit_cells_match_python_float(tmp_path):
    """python float() accepts unicode decimal digits; the native path
    must agree with the python reader on such cells (masked-cell retry)
    while pure-ASCII junk stays masked."""
    rows = [["1", "١٢٣", "x"], ["2", "4.5", "Ünïcødé"],
            ["3", "junk", "y"], ["4", "٢٫٥", "z"], ["5", "", "w"]]
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["id", "v", "t"])
    w.writerows(rows)
    path = _write(tmp_path, buf.getvalue())
    cols = fast_csv.read_csv_columnar(
        path, {"id": ft.Integral, "v": ft.Real, "t": ft.Text},
        chunk_bytes=64,  # the unicode cell must survive chunking too
    )
    vals, mask = cols["v"].values, cols["v"].mask
    assert mask.tolist() == [True, True, False, False, False]
    assert vals[0] == 123.0 and vals[1] == 4.5
    # ("٢٫٥" uses the Arabic decimal separator, which float() rejects -
    # stays masked like the python path)
    assert cols["t"].values[1] == "Ünïcødé"


def test_device_ingest_unicode_digit_parity(tmp_path):
    """The double-buffered device ingest route applies the same float()
    retry as the columnar path."""
    import jax

    rows = [["1.5", "١٢٣"], ["2.5", "7"], ["3.5", "junk"]]
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["a", "b"])
    w.writerows(rows)
    path = _write(tmp_path, buf.getvalue())
    ing = fast_csv.DeviceCSVIngest(
        path, ["a", "b"], {"a": ft.Real, "b": ft.Real}
    )
    X, mask, _rows = ing.to_device()
    X, mask = np.asarray(X), np.asarray(mask)
    assert X[:, 1].tolist() == [123.0, 7.0, 0.0]
    assert mask[:, 1].tolist() == [True, True, False]
