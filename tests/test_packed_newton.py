"""Packed-Gram batched kernels must match the vmap kernels to f32
fixed-point tolerance, and both must match per-replica single fits.

The packed rewrite (models/packed_newton.py) changes only the MACHINE
layout of the CV fan-out - every replica's per-row math is identical to
the vmapped kernel - so coefficients agree to float-reduction noise.
The pin here is the contract VERDICT r3 item 2 requires: packing is a
performance transform, not a numerics change.
"""
import os

import numpy as np
import pytest

from transmogrifai_tpu.models.linear_regression import (
    OpLinearRegression,
    _linreg_fit_batched,
)
from transmogrifai_tpu.models.linear_svc import OpLinearSVC, _svc_fit_batched
from transmogrifai_tpu.models.logistic_regression import (
    OpLogisticRegression,
    _lr_fit_batched,
)
from transmogrifai_tpu.models.packed_newton import (
    lr_fit_batched_packed,
    linreg_fit_batched_packed,
    packed_weighted_gram,
    svc_fit_batched_packed,
    use_packed,
)

import jax.numpy as jnp


@pytest.fixture
def problem():
    rng = np.random.default_rng(7)
    n, d, k, g = 900, 13, 3, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[:, 0] *= 40.0  # un-standardized scale to exercise the folded algebra
    truth = rng.normal(size=d)
    y = (X @ truth / np.linalg.norm(truth) + rng.normal(size=n) > 0).astype(
        np.float32
    )
    masks = np.ones((k, n), np.float32)
    for f in range(k):
        masks[f, f::k] = 0.0  # CV train masks
    W = np.repeat(masks, g, axis=0)  # [k*g, n]
    regs = np.tile(np.asarray([0.001, 0.01, 0.1, 0.2], np.float32), k)
    ens = np.tile(np.asarray([0.0, 0.1, 0.5, 0.0], np.float32), k)
    return X, y, W, regs, ens


def test_packed_gram_matches_einsum(problem):
    X, _, W, _, _ = problem
    G = np.asarray(packed_weighted_gram(jnp.asarray(X), jnp.asarray(W.T)))
    ref = np.einsum("nd,bn,ne->bde", X, W, X)
    np.testing.assert_allclose(G, ref, rtol=2e-5, atol=1e-2)


def test_packed_gram_chunked_matches_single_shot(problem, monkeypatch):
    X, _, W, _, _ = problem
    whole = np.asarray(packed_weighted_gram(jnp.asarray(X), jnp.asarray(W.T)))
    # force chunking with a ragged tail (900 rows -> 256-row chunks + pad)
    monkeypatch.setenv("TX_PACKED_GRAM_ELEMS", str(256 * W.shape[0] * X.shape[1]))
    from transmogrifai_tpu.models.packed_newton import _gram_chunk_rows

    assert _gram_chunk_rows(X.shape[0], W.shape[0], X.shape[1]) < X.shape[0]
    chunked = np.asarray(
        packed_weighted_gram(jnp.asarray(X), jnp.asarray(W.T))
    )
    np.testing.assert_allclose(chunked, whole, rtol=1e-5, atol=1e-4)


def test_lr_packed_matches_vmap(problem):
    X, y, W, regs, ens = problem
    bp, ip = lr_fit_batched_packed(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
        jnp.asarray(regs), jnp.asarray(ens), iters=25, hess_bf16=False,
    )
    bv, iv = _lr_fit_batched(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
        jnp.asarray(regs), jnp.asarray(ens), iters=25,
    )
    np.testing.assert_allclose(np.asarray(bp), np.asarray(bv), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ip), np.asarray(iv), atol=1e-5)


def test_lr_packed_bf16_close_to_f32(problem):
    """bf16 Gram steers only the Newton path: the f32 gradient fixed point
    keeps packed-bf16 coefficients near the f32 answer (same contract the
    vmap kernel pins on TPU)."""
    X, y, W, regs, ens = problem
    b16, i16 = lr_fit_batched_packed(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
        jnp.asarray(regs), jnp.asarray(ens), iters=25, hess_bf16=True,
    )
    b32, i32 = lr_fit_batched_packed(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
        jnp.asarray(regs), jnp.asarray(ens), iters=25, hess_bf16=False,
    )
    np.testing.assert_allclose(np.asarray(b16), np.asarray(b32), atol=5e-3)
    np.testing.assert_allclose(np.asarray(i16), np.asarray(i32), atol=5e-3)


def test_svc_packed_matches_vmap(problem):
    X, y, W, regs, _ = problem
    bp, ip = svc_fit_batched_packed(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(W), jnp.asarray(regs),
        iters=20, hess_bf16=False,
    )
    bv, iv = _svc_fit_batched(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(W), jnp.asarray(regs),
        iters=20,
    )
    np.testing.assert_allclose(np.asarray(bp), np.asarray(bv), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ip), np.asarray(iv), atol=1e-5)


def test_linreg_packed_matches_vmap(problem):
    X, y, W, regs, ens = problem
    target = (X @ np.linspace(-1, 1, X.shape[1])).astype(np.float32)
    bp, ip = linreg_fit_batched_packed(
        jnp.asarray(X), jnp.asarray(target), jnp.asarray(W),
        jnp.asarray(regs), jnp.asarray(ens),
    )
    bv, iv = _linreg_fit_batched(
        jnp.asarray(X), jnp.asarray(target), jnp.asarray(W),
        jnp.asarray(regs), jnp.asarray(ens),
    )
    np.testing.assert_allclose(np.asarray(bp), np.asarray(bv), atol=1e-4)
    np.testing.assert_allclose(np.asarray(ip), np.asarray(iv), atol=1e-4)


def test_fit_arrays_batched_routes_packed_and_matches_single(problem, monkeypatch):
    """The public entry point must (a) take the packed route when forced
    (on-TPU default; CPU hosts default to vmap - the packing measured
    0.5x there) and (b) still agree with the unbatched per-replica fit."""
    X, y, W, regs, ens = problem
    assert not use_packed(jnp.asarray(X), jnp.asarray(W))  # cpu default
    monkeypatch.setenv("TX_PACKED_GRAM", "1")
    assert use_packed(jnp.asarray(X), jnp.asarray(W))
    est = OpLogisticRegression(max_iter=25)
    betas, b0s = est.fit_arrays_batched(X, y, W, regs, ens)
    for b in (0, 5, 11):
        est_b = OpLogisticRegression(
            reg_param=float(regs[b]), elastic_net_param=float(ens[b]),
            max_iter=25,
        )
        single = est_b.fit_arrays(X, y, W[b])
        np.testing.assert_allclose(betas[b], single["beta"], atol=2e-5)
        np.testing.assert_allclose(b0s[b], single["intercept"], atol=2e-5)


def test_env_override_forces_vmap(problem, monkeypatch):
    X, y, W, regs, ens = problem
    monkeypatch.setenv("TX_PACKED_GRAM", "0")
    assert not use_packed(jnp.asarray(X), jnp.asarray(W))
    est = OpLinearSVC(max_iter=20)
    betas, b0s = est.fit_arrays_batched(X, y, W, regs, ens)
    monkeypatch.setenv("TX_PACKED_GRAM", "1")
    bp, ip = OpLinearSVC(max_iter=20).fit_arrays_batched(X, y, W, regs, ens)
    np.testing.assert_allclose(bp, betas, atol=1e-5)
    np.testing.assert_allclose(ip, b0s, atol=1e-5)


def test_linreg_entry_parity(problem):
    X, _, W, regs, ens = problem
    target = (X @ np.linspace(-1, 1, X.shape[1]) + 0.5).astype(np.float32)
    est = OpLinearRegression()
    betas, b0s = est.fit_arrays_batched(X, target, W, regs, ens)
    single = OpLinearRegression(
        reg_param=float(regs[2]), elastic_net_param=float(ens[2])
    ).fit_arrays(X, target, W[2])
    np.testing.assert_allclose(betas[2], single["beta"], atol=1e-4)
    np.testing.assert_allclose(b0s[2], single["intercept"], atol=1e-4)


def test_packed_gram_wide_design_matrix(monkeypatch):
    """Hashing caps vectorized width at 16k dims (Transmogrifier.scala:
    55-56); at B=24 replicas the packed Gram's N dimension spans ~384k
    columns and the chunker must shrink rows accordingly.  Pin a scaled
    stand-in (d=512, tight budget -> multi-chunk) against the einsum."""
    rng = np.random.default_rng(13)
    n, d, B = 700, 512, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    W = (rng.random((B, n)) > 0.3).astype(np.float32)
    monkeypatch.setenv("TX_PACKED_GRAM_ELEMS", str(160 * B * d))
    from transmogrifai_tpu.models.packed_newton import _gram_chunk_rows

    c = _gram_chunk_rows(n, B, d)
    assert 128 <= c < n  # multi-chunk with the floor respected
    G = np.asarray(packed_weighted_gram(jnp.asarray(X), jnp.asarray(W.T)))
    ref = np.einsum("nd,bn,ne->bde", X, W, X)
    np.testing.assert_allclose(G, ref, rtol=3e-5, atol=5e-2)


def test_full_cv_selection_parity_packed_vs_vmap(monkeypatch):
    """Validator-level integration: the whole fold x grid CV flow must
    pick the same candidate with the same metric through the packed and
    vmap routes (the exact flow the on-chip bench runs)."""
    import jax

    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )
    from transmogrifai_tpu.selector.factories import lr_grid
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    rng = np.random.default_rng(2)
    n, d = 6000, 13
    X = rng.normal(size=(n, d)).astype(np.float32)
    truth = rng.normal(size=d)
    y = (
        X @ truth / np.linalg.norm(truth) + 0.5 * rng.normal(size=n) > 0
    ).astype(np.float64)

    def run():
        cv = OpCrossValidation(
            num_folds=3, evaluator=OpBinaryClassificationEvaluator(),
            stratify=True, seed=0,
        )
        return cv.validate([(OpLogisticRegression(), lr_grid())], X, y)

    monkeypatch.setenv("TX_PACKED_GRAM", "1")
    packed = run()
    monkeypatch.setenv("TX_PACKED_GRAM", "0")
    jax.clear_caches()
    vmap = run()
    assert packed.best_params == vmap.best_params
    assert abs(packed.best_metric - vmap.best_metric) < 1e-4


# -- mesh composition (round 5: shard_map Gram over the 'data' axis) --------

def _mesh_24():
    from transmogrifai_tpu.parallel.mesh import make_mesh

    return make_mesh(axis_names=("replica", "data"), shape=(2, 4))


def _shard_problem(problem, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    X, y, W, regs, ens = problem
    n = X.shape[0] - (X.shape[0] % mesh.shape["data"])
    X, y, W = X[:n], y[:n], W[:, :n]
    Xs = jax.device_put(X, NamedSharding(mesh, P("data", None)))
    ys = jax.device_put(y, NamedSharding(mesh, P("data")))
    Ws = jax.device_put(W, NamedSharding(mesh, P("replica", "data")))
    rs = jax.device_put(
        jnp.asarray(regs), NamedSharding(mesh, P("replica"))
    )
    es = jax.device_put(jnp.asarray(ens), NamedSharding(mesh, P("replica")))
    return (X, y, W, regs, ens), (Xs, ys, Ws, rs, es)


def test_packed_gram_mesh_matches_unsharded(problem):
    """Each device packs its local row shard; psum('data') must reproduce
    the single-device packed Gram to f32 reduction-order noise."""
    mesh = _mesh_24()
    (X, _, W, _, _), (Xs, _, Ws, _, _) = _shard_problem(problem, mesh)
    G_ref = np.asarray(
        packed_weighted_gram(jnp.asarray(X), jnp.asarray(W.T))
    )
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    Wts = jax.device_put(
        jnp.asarray(W.T), NamedSharding(mesh, P("data", "replica"))
    )
    G_mesh = np.asarray(packed_weighted_gram(Xs, Wts, mesh))
    np.testing.assert_allclose(G_mesh, G_ref, rtol=2e-5, atol=1e-2)


def test_packed_kernels_sharded_match_unsharded(problem):
    """Coefficient parity for all three packed kernels between the
    shard_map mesh route and the single-device route (VERDICT r4 #2:
    sharded == unsharded on an 8-device CPU mesh)."""
    mesh = _mesh_24()
    (X, y, W, regs, ens), (Xs, ys, Ws, rs, es) = _shard_problem(
        problem, mesh
    )
    Xj, yj, Wj = jnp.asarray(X), jnp.asarray(y), jnp.asarray(W)
    rj, ej = jnp.asarray(regs), jnp.asarray(ens)

    b0, i0 = lr_fit_batched_packed(Xj, yj, Wj, rj, ej, iters=8,
                                   hess_bf16=False)
    b1, i1 = lr_fit_batched_packed(Xs, ys, Ws, rs, es, iters=8,
                                   hess_bf16=False, mesh=mesh)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), atol=5e-5)
    np.testing.assert_allclose(np.asarray(i1), np.asarray(i0), atol=5e-5)

    b0, i0 = svc_fit_batched_packed(Xj, yj, Wj, rj, iters=8,
                                    hess_bf16=False)
    b1, i1 = svc_fit_batched_packed(Xs, ys, Ws, rs, iters=8,
                                    hess_bf16=False, mesh=mesh)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), atol=5e-5)
    np.testing.assert_allclose(np.asarray(i1), np.asarray(i0), atol=5e-5)

    b0, i0 = linreg_fit_batched_packed(Xj, yj, Wj, rj, ej)
    b1, i1 = linreg_fit_batched_packed(Xs, ys, Ws, rs, es, mesh=mesh)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), atol=5e-5)
    np.testing.assert_allclose(np.asarray(i1), np.asarray(i0), atol=5e-5)


def test_packed_mesh_detection_and_use_packed(problem, monkeypatch):
    """Mesh-sharded inputs must KEEP the packed route (round 4 excluded
    them): packed_mesh_or_none finds the validator's mesh from the array
    shardings and use_packed no longer refuses multi-device."""
    from transmogrifai_tpu.models.packed_newton import packed_mesh_or_none

    mesh = _mesh_24()
    _, (Xs, _, Ws, _, _) = _shard_problem(problem, mesh)
    assert packed_mesh_or_none(Xs, Ws) is mesh
    monkeypatch.setenv("TX_PACKED_GRAM", "1")
    assert use_packed(Xs, Ws)
    # single-host numpy arrays have no mesh: plain body
    assert packed_mesh_or_none(np.ones((4, 2))) is None


def test_packed_gram_mesh_indivisible_falls_back(problem):
    """Shapes the mesh does not divide must still produce the right Gram
    (guard falls back to the GSPMD-lowered plain body)."""
    mesh = _mesh_24()
    X, _, W, _, _ = problem
    n = (X.shape[0] // mesh.shape["data"]) * mesh.shape["data"] - 1
    X, W = X[:n], W[:5, :n]  # B=5 not divisible by replica=2 either
    G = np.asarray(
        packed_weighted_gram(jnp.asarray(X), jnp.asarray(W.T), mesh)
    )
    ref = np.einsum("nd,bn,ne->bde", X, W, X)
    np.testing.assert_allclose(G, ref, rtol=2e-5, atol=1e-2)


def test_full_cv_mesh_selection_parity_packed_vs_vmap(monkeypatch):
    """End-to-end: the validator's mesh branch (8 virtual CPU devices ->
    rows on 'data', fold x grid on 'replica') with the packed route forced
    must select the same candidate as the vmap route - the v5e-8 BASELINE
    shape the round-4 packed kernels excluded."""
    import jax

    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )
    from transmogrifai_tpu.selector.factories import lr_grid
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    rng = np.random.default_rng(3)
    n, d = 4000, 11
    X = rng.normal(size=(n, d)).astype(np.float32)
    truth = rng.normal(size=d)
    y = (
        X @ truth / np.linalg.norm(truth) + 0.5 * rng.normal(size=n) > 0
    ).astype(np.float64)

    def run():
        cv = OpCrossValidation(
            num_folds=3, evaluator=OpBinaryClassificationEvaluator(),
            stratify=True, seed=0,
        )
        return cv.validate([(OpLogisticRegression(), lr_grid())], X, y)

    monkeypatch.setenv("TX_PACKED_GRAM", "1")
    packed = run()
    monkeypatch.setenv("TX_PACKED_GRAM", "0")
    jax.clear_caches()
    vmap = run()
    assert packed.best_params == vmap.best_params
    assert abs(packed.best_metric - vmap.best_metric) < 1e-4


def test_packed_mesh_or_none_rejects_indivisible_shapes(problem):
    """Shapes the mesh does not divide must NOT take the packed route (the
    dynamic_slice fallback under GSPMD row sharding is the exact layout
    conflict the vmap kernels avoid) - review r5.  jax.device_put itself
    refuses indivisible NamedSharding placement, so the guard is exercised
    through duck-typed stand-ins (the shapes a non-validator caller could
    hand over after jit with uneven outputs)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from transmogrifai_tpu.models.packed_newton import packed_mesh_or_none

    mesh = _mesh_24()

    class FakeArr:
        def __init__(self, shape):
            self.shape = shape
            self.sharding = NamedSharding(mesh, P("data", None))

    d = 13
    assert packed_mesh_or_none(FakeArr((899, d)), FakeArr((8, 899))) is None
    assert packed_mesh_or_none(FakeArr((904, d)), FakeArr((5, 904))) is None
    assert (
        packed_mesh_or_none(FakeArr((904, d)), FakeArr((8, 904))) is mesh
    )


@pytest.mark.parametrize("shape", [(1, 8), (2, 4), (4, 2), (8, 1)])
def test_packed_mesh_parity_across_mesh_shapes(problem, shape):
    """Sharded == unsharded LR coefficients for every (replica, data)
    factorization of the 8 virtual devices - the driver may hand any of
    these to the dryrun, and cv_mesh_or_none picks different r per grid
    size."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from transmogrifai_tpu.parallel.mesh import make_mesh

    r, nd = shape
    mesh = make_mesh(axis_names=("replica", "data"), shape=shape)
    X, y, W, regs, ens = problem
    n = X.shape[0] - (X.shape[0] % nd)
    B = W.shape[0] - (W.shape[0] % r)
    X, y, W, regs, ens = X[:n], y[:n], W[:B, :n], regs[:B], ens[:B]
    Xs = jax.device_put(X, NamedSharding(mesh, P("data", None)))
    ys = jax.device_put(y, NamedSharding(mesh, P("data")))
    Ws = jax.device_put(W, NamedSharding(mesh, P("replica", "data")))
    rs = jax.device_put(jnp.asarray(regs), NamedSharding(mesh, P("replica")))
    es = jax.device_put(jnp.asarray(ens), NamedSharding(mesh, P("replica")))
    b0, i0 = lr_fit_batched_packed(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(W),
        jnp.asarray(regs), jnp.asarray(ens), iters=6, hess_bf16=False,
    )
    b1, i1 = lr_fit_batched_packed(
        Xs, ys, Ws, rs, es, iters=6, hess_bf16=False, mesh=mesh,
    )
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0), atol=5e-5)
    np.testing.assert_allclose(np.asarray(i1), np.asarray(i0), atol=5e-5)
