"""Whole-pipeline fused serving compilation (local/fused.py, ISSUE 6).

Pins the compile-to-kernel seam end to end:
* fused vs interpreted parity - EXACT result dicts (1-ULP tolerated for
  float heads) for every lowerable model family over a mixed-type
  pipeline with missing values, including batch-of-1 and empty batches
* stage-level lowering parity for every lowerable vectorizer/feature
  stage (lowered array fn vs transform_columns on the same data)
* per-pipeline fallback: a non-lowerable stage leaves the scorer on the
  interpreted path for life, with the reason recorded and surfaced in
  serving telemetry
* robustness machinery sits unchanged on the fused path: poison rows
  fall back per row, the NaN/Inf output guard refuses non-finite
  scores, the circuit breaker opens on injected batch failures
* per-shape-bucket compile times land in telemetry
* tier-1 throughput floor: the fused program must beat the interpreted
  DAG walk by >= 2x (CPU-time measured, interleaved).  The ISSUE-6
  target of 3x was set against the SEED interpreted path (~11k rows/s
  endpoint); the same PR's interpreted-path speedups (shared decoder +
  columnar assembly) make the fallback itself ~6x faster, so 2x against
  the CURRENT interpreted path exceeds the original intent (>10x vs
  seed) while staying robust to shared-host noise.
"""
import math
import time

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.local import FusionError, LocalScorer
from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.linear_svc import OpLinearSVC
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.mlp import OpMultilayerPerceptronClassifier
from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
from transmogrifai_tpu.models.trees import (
    OpGBTClassifier,
    OpGBTRegressor,
    OpRandomForestClassifier,
    OpRandomForestRegressor,
)
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.serving import (
    RowScoringError,
    ServingTelemetry,
    compile_endpoint,
)
from transmogrifai_tpu.types import feature_types as ft


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _mixed_pipeline(est, n=160, seed=3, classification=True):
    """Small full pipeline exercising every lowerable stage family:
    numeric chains (fill-mean -> z-normalize), real/integral
    vectorizers, one-hot picklists, combiner, sanity checker, and the
    predictor head.  Returns (model, records, pred_name)."""
    rng = np.random.RandomState(seed)
    y = (
        (rng.rand(n) > 0.5).astype(float)
        if classification else rng.randn(n) * 2.0
    )
    data = {
        "y": y.tolist(),
        "a": [float(v) if rng.rand() > 0.2 else None
              for v in rng.randn(n)],
        "b": rng.uniform(0, 10, n).round(3).tolist(),
        "k": rng.randint(0, 5, n).astype(float).tolist(),
        "c": [("u", "v", "w", None)[rng.randint(4)] for _ in range(n)],
    }
    yf = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    k = FeatureBuilder(ft.Integral, "k").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a.fill_missing_with_mean().z_normalize(), b, k, c])
    checked = yf.sanity_check(vec, remove_bad_features=True)
    pred = est.set_input(yf, checked).get_output()
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    records = [
        {nm: data[nm][i] for nm in ("a", "b", "k", "c")} for i in range(n)
    ]
    return model, records, pred.name


def _assert_rows_equal(fused_rows, interp_rows):
    """Result-dict equality with 1-ULP float tolerance (the regressor
    heads' existing tolerance); everything else must match exactly."""
    assert len(fused_rows) == len(interp_rows)
    for rf, ri in zip(fused_rows, interp_rows):
        assert rf.keys() == ri.keys()
        for name in rf:
            df, di = rf[name], ri[name]
            if not isinstance(df, dict):
                assert df == di, name
                continue
            assert df.keys() == di.keys(), name
            for kk, vf in df.items():
                vi = di[kk]
                if isinstance(vf, float) and isinstance(vi, float):
                    assert vf == vi or (
                        math.isfinite(vf)
                        and abs(vf - vi)
                        <= abs(np.nextafter(vi, vf) - vi)
                    ), (name, kk, vf, vi)
                else:
                    assert vf == vi, (name, kk)


CLS_FAMILIES = [
    ("lr", lambda: OpLogisticRegression(reg_param=0.01)),
    ("rf", lambda: OpRandomForestClassifier(num_trees=8, max_depth=4)),
    ("gbt", lambda: OpGBTClassifier(num_trees=6, max_depth=3)),
    ("nb", lambda: OpNaiveBayes()),
    ("svc", lambda: OpLinearSVC()),
    ("mlp", lambda: OpMultilayerPerceptronClassifier(
        hidden_layers=(4,), max_iter=15)),
]
REG_FAMILIES = [
    ("linreg", lambda: OpLinearRegression()),
    ("rf_reg", lambda: OpRandomForestRegressor(num_trees=8, max_depth=4)),
    ("gbt_reg", lambda: OpGBTRegressor(num_trees=6, max_depth=3)),
    ("glm", lambda: OpGeneralizedLinearRegression()),
]


@pytest.mark.parametrize(
    "name,make", CLS_FAMILIES, ids=[f[0] for f in CLS_FAMILIES]
)
def test_fused_parity_classifier_families(name, make):
    model, records, _ = _mixed_pipeline(make())
    fused = LocalScorer(model, drift_policy=None, fused=True)
    interp = LocalScorer(model, drift_policy=None, fused=False)
    assert fused.fused is not None, fused.fused_reason
    _assert_rows_equal(fused.score_batch(records),
                       interp.score_batch(records))
    # batch-of-1 through the same fused program
    _assert_rows_equal([fused(records[0])], [interp(records[0])])


@pytest.mark.parametrize(
    "name,make", REG_FAMILIES, ids=[f[0] for f in REG_FAMILIES]
)
def test_fused_parity_regressor_families(name, make):
    model, records, _ = _mixed_pipeline(make(), classification=False)
    fused = LocalScorer(model, drift_policy=None, fused=True)
    interp = LocalScorer(model, drift_policy=None, fused=False)
    assert fused.fused is not None, fused.fused_reason
    _assert_rows_equal(fused.score_batch(records),
                       interp.score_batch(records))


def test_fused_records_with_missing_keys_decode_as_missing():
    """A record that omits a feature KEY entirely must decode exactly
    like an explicit None - through both the itemgetter fast path and
    its KeyError fallback, including the single-feature decoder."""
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    fused = LocalScorer(model, drift_policy=None, fused=True)
    interp = LocalScorer(model, drift_policy=None, fused=False)
    stripped = [
        {k: v for k, v in r.items() if k not in ("a", "c")}
        for r in records[:20]
    ]
    explicit = [dict(r, a=None, c=None) for r in stripped]
    _assert_rows_equal(fused.score_batch(stripped),
                       interp.score_batch(stripped))
    _assert_rows_equal(fused.score_batch(stripped),
                       fused.score_batch(explicit))
    # single-raw-feature pipeline: itemgetter returns bare values and
    # the fallback must not wrap them into 1-tuples
    rng = np.random.RandomState(11)
    n = 80
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
    }
    yf = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    vec = transmogrify([a])
    pred = OpLogisticRegression().set_input(yf, vec).get_output()
    m1 = OpWorkflow().set_result_features(pred).set_input_dataset(
        data).train()
    f1 = LocalScorer(m1, drift_policy=None, fused=True)
    i1 = LocalScorer(m1, drift_policy=None, fused=False)
    assert f1.fused is not None, f1.fused_reason
    mixed = [{"a": data["a"][0]}, {}, {"a": None}]
    _assert_rows_equal(f1.score_batch(mixed), i1.score_batch(mixed))


def test_fused_mapping_subtypes_and_str_subclass_parity():
    """Two decode edge cases pinned from review:

    * a ``defaultdict`` record must decode like a plain dict - its
      ``__missing__`` must never fabricate a present value for an
      absent key, and scoring must never INSERT keys into the caller's
      record (``itemgetter`` on a defaultdict does both);
    * ``np.str_("")`` (a str subclass, e.g. lifted out of a numpy
      object array) must map to missing exactly like ``""`` does in
      ``TextColumn.from_list`` - train/serve skew otherwise."""
    from collections import defaultdict

    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    fused = LocalScorer(model, drift_policy=None, fused=True)
    interp = LocalScorer(model, drift_policy=None, fused=False)
    assert fused.fused is not None, fused.fused_reason

    plain = [{k: v for k, v in r.items() if k != "a"}
             for r in records[:10]]
    dd = [defaultdict(float, r) for r in plain]
    _assert_rows_equal(fused.score_batch(dd), interp.score_batch(plain))
    assert all("a" not in r for r in dd), "scoring mutated caller records"

    empt = [dict(r, c=np.str_("")) for r in records[:10]]
    none = [dict(r, c=None) for r in records[:10]]
    _assert_rows_equal(fused.score_batch(empt), fused.score_batch(none))
    _assert_rows_equal(interp.score_batch(empt), interp.score_batch(none))


def test_fused_nan_valued_inputs_match_from_list_semantics():
    """NumericColumn.from_list treats None and python-float NaN as
    MISSING (mean-fillable), but a NaN-valued input of any OTHER type
    (str "nan", np.float32 NaN) as PRESENT with value NaN - junk that
    must surface as a non-finite score for the output guard, never be
    silently mean-filled.  Both fused decode paths (the batched env
    decode and per-feature decode_numeric) must agree with the
    interpreted column path."""
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    fused = LocalScorer(model, drift_policy=None, fused=True)
    interp = LocalScorer(model, drift_policy=None, fused=False)
    assert fused.fused is not None, fused.fused_reason
    base = dict(records[0])
    rows = [
        dict(base, a=float("nan")),       # missing: fills, scores finite
        dict(base, a="nan"),              # present NaN
        dict(base, a=np.float32("nan")),  # present NaN
        base,
    ]
    fr, ir = fused.score_batch(rows), interp.score_batch(rows)
    _assert_rows_equal([fr[0], fr[3]], [ir[0], ir[3]])
    for got in (fr, ir):
        assert all(math.isfinite(v) for v in got[0].popitem()[1].values())
        for junk in (got[1], got[2]):
            assert any(
                not math.isfinite(v) for v in junk.popitem()[1].values()
            ), "NaN-valued present input scored finite"


def test_fused_empty_batch_is_empty_list():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    fused = LocalScorer(model, drift_policy=None, fused=True)
    assert fused.fused is not None
    assert fused.score_batch([]) == []
    endpoint = compile_endpoint(model, batch_buckets=(4,))
    assert endpoint.score_batch([]) == []


# -- stage-level lowering parity --------------------------------------------

def _stage_parity(stage, ds, env):
    """A fitted stage's lowered fn must reproduce transform_columns'
    arrays bit for bit."""
    from transmogrifai_tpu.stages.base import MASK_SUFFIX
    from transmogrifai_tpu.types.columns import (
        NumericColumn,
        PredictionColumn,
        VectorColumn,
    )

    lowering = stage.lower()
    assert lowering is not None, type(stage).__name__
    produced = lowering.fn(dict(env))
    col = stage.transform_columns(
        [ds[f.name] for f in stage.input_features], ds
    )
    out = stage.output_name
    if isinstance(col, VectorColumn):
        np.testing.assert_array_equal(produced[out], col.values)
    elif isinstance(col, NumericColumn):
        np.testing.assert_array_equal(produced[out], col.values)
        np.testing.assert_array_equal(produced[out + MASK_SUFFIX], col.mask)
    elif isinstance(col, PredictionColumn):
        np.testing.assert_array_equal(produced[out], col.prediction)
    else:  # pragma: no cover
        raise AssertionError(f"unhandled column {type(col).__name__}")
    return produced


def test_stage_lowering_parity_vectorizers_and_scalers(rng):
    from transmogrifai_tpu.ops.categorical import (
        OneHotVectorizer,
        StringIndexer,
    )
    from transmogrifai_tpu.ops.numeric import (
        BinaryVectorizer,
        IntegralVectorizer,
        RealVectorizer,
    )
    from transmogrifai_tpu.ops.scalers import (
        FillMissingWithMean,
        OpScalarStandardScaler,
        PercentileCalibrator,
    )
    from transmogrifai_tpu.stages.base import MASK_SUFFIX
    from transmogrifai_tpu.types.dataset import Dataset
    from transmogrifai_tpu.types.columns import column_from_list

    n = 60
    vals = [float(v) if rng.rand() > 0.25 else None for v in rng.randn(n)]
    ints = [float(rng.randint(0, 4)) if rng.rand() > 0.2 else None
            for _ in range(n)]
    bins = [bool(rng.rand() > 0.5) if rng.rand() > 0.2 else None
            for _ in range(n)]
    txts = [("x", "y", "zz", None)[rng.randint(4)] for _ in range(n)]
    ds = Dataset({
        "r": column_from_list(vals, ft.Real),
        "i": column_from_list(ints, ft.Integral),
        "bl": column_from_list([None if b is None else float(b)
                                for b in bins], ft.Binary),
        "t": column_from_list(txts, ft.PickList),
    })
    r = FeatureBuilder(ft.Real, "r").as_predictor()
    i = FeatureBuilder(ft.Integral, "i").as_predictor()
    bl = FeatureBuilder(ft.Binary, "bl").as_predictor()
    t = FeatureBuilder(ft.PickList, "t").as_predictor()
    env = {
        "r": ds["r"].values, "r" + MASK_SUFFIX: ds["r"].mask,
        "i": ds["i"].values, "i" + MASK_SUFFIX: ds["i"].mask,
        "bl": ds["bl"].values, "bl" + MASK_SUFFIX: ds["bl"].mask,
        "t": list(ds["t"].values),
    }
    stages = [
        RealVectorizer().set_input(r),
        IntegralVectorizer().set_input(i),
        BinaryVectorizer().set_input(bl),
        OneHotVectorizer(top_k=3, min_support=1).set_input(t),
        OpScalarStandardScaler().set_input(r),
        FillMissingWithMean(default=0.5).set_input(r),
        PercentileCalibrator(buckets=10).set_input(r),
        StringIndexer().set_input(t),
    ]
    for est in stages:
        fitted = est.fit(ds)
        _stage_parity(fitted, ds, env)


def test_stage_lowering_parity_onehot_multipicklist(rng):
    from transmogrifai_tpu.ops.categorical import OneHotVectorizer
    from transmogrifai_tpu.types.dataset import Dataset
    from transmogrifai_tpu.types.columns import column_from_list

    n = 50
    pools = (("p", "q"), ("q",), ("p", "r", "s"), ())
    raw = [pools[rng.randint(len(pools))] for _ in range(n)]
    ds = Dataset({"m": column_from_list(raw, ft.MultiPickList)})
    m = FeatureBuilder(ft.MultiPickList, "m").as_predictor()
    fitted = OneHotVectorizer(top_k=3, min_support=1).set_input(m).fit(ds)
    env = {"m": np.array(ds["m"].values, dtype=object)}
    _stage_parity(fitted, ds, env)


def test_stage_lowering_parity_combiner_and_alias(rng):
    from transmogrifai_tpu.ops.combiner import (
        AliasTransformer,
        VectorsCombiner,
    )
    from transmogrifai_tpu.types.dataset import Dataset
    from transmogrifai_tpu.types.columns import VectorColumn
    from transmogrifai_tpu.types.vector_metadata import (
        VectorColumnMeta,
        VectorMetadata,
    )

    n = 40
    v1 = np.asarray(rng.randn(n, 3), dtype=np.float32)
    v2 = np.asarray(rng.randn(n, 2), dtype=np.float32)
    meta1 = VectorMetadata("v1", tuple(
        VectorColumnMeta("v1", "Real") for _ in range(3)))
    meta2 = VectorMetadata("v2", tuple(
        VectorColumnMeta("v2", "Real") for _ in range(2)))
    ds = Dataset({"v1": VectorColumn(v1, meta1),
                  "v2": VectorColumn(v2, meta2)})
    f1 = FeatureBuilder(ft.OPVector, "v1").as_predictor()
    f2 = FeatureBuilder(ft.OPVector, "v2").as_predictor()
    env = {"v1": v1, "v2": v2}
    _stage_parity(VectorsCombiner().set_input(f1, f2), ds, env)
    _stage_parity(AliasTransformer("renamed").set_input(f1), ds, env)


# -- per-pipeline fallback ---------------------------------------------------

def _lambda_pipeline(n=120, seed=5):
    """A pipeline with a row-lambda stage (map_values) that cannot
    lower: the whole pipeline must serve interpreted."""
    rng = np.random.RandomState(seed)
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
    }
    yf = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    doubled = a.map_values(
        lambda v: None if v is None else 2.0 * v, ft.Real
    )
    vec = transmogrify([doubled])
    pred = OpLogisticRegression().set_input(yf, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    records = [{"a": data["a"][i]} for i in range(n)]
    return model, records


def test_non_lowerable_stage_falls_back_per_pipeline():
    model, records = _lambda_pipeline()
    scorer = LocalScorer(model, drift_policy=None, fused=True)
    assert scorer.fused is None
    assert "lower" in scorer.fused_reason
    # the interpreted path still serves, and the endpoint surfaces the
    # per-pipeline choice + reason in telemetry
    tel = ServingTelemetry()
    endpoint = compile_endpoint(model, batch_buckets=(8,), telemetry=tel)
    out = endpoint.score_batch(records[:8])
    assert not any(isinstance(r, RowScoringError) for r in out)
    snap = tel.snapshot()["fused"]
    assert snap["enabled"] is False
    assert "lower" in snap["reason"]
    assert snap["batches_fused"] == 0


def test_fused_disabled_by_caller_records_reason():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    scorer = LocalScorer(model, drift_policy=None, fused=False)
    assert scorer.fused is None
    assert scorer.fused_reason == "disabled by caller"


# -- robustness machinery on the fused path ---------------------------------

def test_poison_row_falls_back_per_row_on_fused_endpoint():
    model, records, pred_name = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model, batch_buckets=(8,))
    assert endpoint.fused
    batch = [dict(r) for r in records[:6]]
    batch[2]["b"] = "not-a-number"  # poisons the numeric decode
    out = endpoint.score_batch(batch)
    assert isinstance(out[2], RowScoringError)
    good = [r for i, r in enumerate(out) if i != 2]
    assert all(isinstance(r, dict) and pred_name in r for r in good)
    assert endpoint.shape_misses == 1


def test_nan_guard_refuses_fused_nonfinite_scores():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    # poison the fitted head so the fused program emits NaN scores
    from transmogrifai_tpu.models.base import PredictorModel

    for layer in model._dag():
        for stage in layer:
            if isinstance(stage, PredictorModel):
                stage.model_params["beta"] = np.full_like(
                    stage.model_params["beta"], np.nan
                )
    tel = ServingTelemetry()
    endpoint = compile_endpoint(model, batch_buckets=(4,), telemetry=tel,
                                warm=False)
    assert endpoint.fused
    out = endpoint.score_batch(records[:4])
    assert all(isinstance(r, RowScoringError) for r in out)
    assert all("non-finite" in r.error for r in out)
    assert tel.snapshot()["breaker"]["rows_nonfinite"] == 4


def test_breaker_opens_on_fused_batch_failures():
    from transmogrifai_tpu.serving import CircuitBreaker

    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    breaker = CircuitBreaker(failure_threshold=2, cooldown_s=60.0)
    endpoint = compile_endpoint(model, batch_buckets=(4,), breaker=breaker)
    assert endpoint.fused
    faults.configure("serving.batch:every=1:times=2")
    for _ in range(2):
        out = endpoint.score_batch(records[:3])
        # batch path failed, rows still served via the row fallback
        assert not any(isinstance(r, RowScoringError) for r in out)
    assert breaker.state == "open"
    shed = endpoint.score_batch(records[:3])
    assert all(isinstance(r, RowScoringError) and r.shed for r in shed)


def test_fused_compile_times_per_bucket_in_telemetry():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    tel = ServingTelemetry()
    endpoint = compile_endpoint(model, batch_buckets=(1, 4, 16),
                                telemetry=tel)
    snap = tel.snapshot()["fused"]
    assert snap["enabled"] is True
    assert snap["reason"] is None
    # warm-up compiled every bucket; per-bucket wall times recorded
    assert set(snap["compile_ms_by_bucket"]) == {"1", "4", "16"}
    assert all(v >= 0.0 for v in snap["compile_ms_by_bucket"].values())
    # traffic counts fused batches
    endpoint.score_batch(records[:5])
    snap = tel.snapshot()["fused"]
    assert snap["batches_fused"] >= 1
    assert snap["rows_fused"] >= 5


def test_fused_plan_names_every_stage():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    scorer = LocalScorer(model, drift_policy=None, fused=True)
    plan = scorer.fused.plan
    assert len(plan) == len(scorer._steps)
    ops = {op for _, op, _, _, _ in plan}
    assert "VectorsCombiner" in ops or len(plan) > 3


# -- throughput floor (tier-1 regression gate) ------------------------------

def test_fused_throughput_floor_vs_interpreted():
    """The fused program must stay >= 2x the interpreted DAG walk on the
    scaled-down RF winner (CPU-time, interleaved best-of-N: immune to
    other-process noise).  A silent drop to the interpreted path also
    fails the explicit `scorer.fused is not None` assert first."""
    model, records, _ = _mixed_pipeline(
        OpRandomForestClassifier(num_trees=4, max_depth=3), n=320
    )
    fused = LocalScorer(model, drift_policy=None, fused=True)
    interp = LocalScorer(model, drift_policy=None, fused=False)
    assert fused.fused is not None, fused.fused_reason
    batch = (records * 2)[:256]
    # warm both paths (bucket compile + memo fills)
    fused.score_batch(batch)
    interp.score_batch(batch)
    # process_time ticks can be 10ms on this kernel: each timed block
    # must span many ticks, so inner is sized for ~100ms+ of fused work.
    # Heavy co-tenant load can still depress a whole measurement window
    # (cache contention is not CPU-time-neutral), so a failing ratio is
    # re-measured before it fails the gate - a TRUE regression to
    # interpreter speed fails every attempt.
    reps, inner = 4, 100
    ratio = best_f = best_i = None
    for _attempt in range(3):
        best_f = best_i = float("inf")
        for _ in range(reps):
            t0 = time.process_time()
            for _ in range(inner):
                fused.score_batch(batch)
            best_f = min(best_f, max(time.process_time() - t0, 1e-6))
            t0 = time.process_time()
            for _ in range(inner):
                interp.score_batch(batch)
            best_i = min(best_i, max(time.process_time() - t0, 1e-6))
        ratio = best_i / best_f
        if ratio >= 2.0:
            break
    assert ratio >= 2.0, (
        f"fused path only {ratio:.2f}x the interpreted path "
        f"(fused {256 * inner / best_f:.0f} rows/s vs interpreted "
        f"{256 * inner / best_i:.0f} rows/s) - the fused program "
        "regressed toward interpreter speed"
    )
