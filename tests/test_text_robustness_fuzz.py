"""Garbage-in robustness fuzz for every text-analysis function.

None of the detectors/parsers may raise on arbitrary input - random
bytes, lone surrogate-free unicode from hostile planes, control
characters, pathological lengths, malformed base64 - and outputs stay in
their contracted domains (probabilities, Optional[bool], domain strings,
similarity in [0, 1]).
"""
from __future__ import annotations

import base64
import string

import numpy as np
import pytest

from transmogrifai_tpu.ops.lang_data import detect
from transmogrifai_tpu.ops.ner import tag_entities
from transmogrifai_tpu.ops.text import tokenize
from transmogrifai_tpu.ops.text_analysis import (
    detect_mime_type,
    is_valid_phone,
    ngrams,
    parse_phone,
)


def _garbage_strings(rng, k=120):
    pools = [
        string.printable,
        "".join(chr(c) for c in range(0x20)),              # control chars
        "αβγδεζηθικλμνξοπρστυφχψω中文字符日本語한국어",        # multi-script
        "\U0001F600\U0001F4A9\U0001F680‍​﻿",  # emoji + ZWJ/BOM
        "ÀÈÌÒÙàèìòùÄÖÜäöüßÿñçœæ",
        "().,;:!?-_'\"@#$%^&*[]{}|\\/<>~`+=",
    ]
    out = [None, "", " ", "\n", "\t\t\t", "a" * 10_000, "\x00"]
    for _ in range(k):
        pool = pools[rng.randint(len(pools))]
        n = int(rng.randint(1, 60))
        out.append("".join(pool[rng.randint(len(pool))] for _ in range(n)))
    return out


@pytest.mark.parametrize("seed", [81, 82])
def test_detectors_never_raise_and_stay_in_domain(seed):
    rng = np.random.RandomState(seed)
    for s in _garbage_strings(rng):
        scores = detect(s or "")
        for lang, p in scores.items():
            assert isinstance(lang, str) and 0.0 <= p <= 1.0 + 1e-9
        ents = tag_entities(s)
        assert isinstance(ents, dict)
        v = is_valid_phone(s)
        assert v is None or isinstance(v, bool)
        parsed = parse_phone(s)
        assert parsed is None or isinstance(parsed, str)
        toks = tokenize(s)
        assert all(isinstance(t, str) for t in toks)
        g = ngrams(s or "")
        assert isinstance(g, set)


@pytest.mark.parametrize("seed", [83, 84])
def test_mime_detector_on_random_bytes(seed):
    rng = np.random.RandomState(seed)
    cases = [b"", b"\x00", bytes(rng.randint(0, 256, 4).tolist())]
    for _ in range(60):
        n = int(rng.randint(1, 4096))
        cases.append(bytes(rng.randint(0, 256, n).tolist()))
    # truncated real signatures: a PNG magic cut mid-way, a half ZIP
    cases += [b"\x89PN", b"PK\x03", b"%PD", b"GIF8", b"\xff\xd8"]
    for raw in cases:
        mt = detect_mime_type(base64.b64encode(raw).decode("ascii"))
        assert mt is None or (isinstance(mt, str) and "/" in mt)
    # non-base64 garbage must not raise either
    for junk in ("!!!", "%%%", "not base64 at all", "ab=cd=="):
        mt = detect_mime_type(junk)
        assert mt is None or isinstance(mt, str)
