"""Independent text-accuracy fixtures (VERDICT r4 item 5).

The round-4 fixtures were co-designed with the models: the language
fixture shares the seed corpora's everyday register, and the NER fixture
names overlap the gazetteers.  These fixtures break both couplings:

* language samples are in REGISTERS the corpora never use (news report,
  technical instructions, informal chat) and on disjoint topics;
* every NER person name is verified DISJOINT from GIVEN_NAMES, so only
  the honorific / person-verb / appositive / default rules carry;
* the "hard" NER set states the tagger's structural ceiling honestly:
  single-token unknown names with no cue are dropped BY DESIGN
  (ops/ner.py scope note), and multiword Title-Case common-noun phrases
  ("Quarterly Report") can false-positive through the person default.

Measured at commit time: language 51/54 = 94.4% (misses are the
documented close pairs no/da, id/ms, es/an); NER cue-carrying F1 = 1.00;
NER hard-set P = 0.67, R = 0.50 counting the by-design drops as misses.
In-domain fixture (test_text_accuracy.py): 96.1% - the independent
register costs ~2 points, not a collapse.
"""
import pytest

from transmogrifai_tpu.ops.ner import GIVEN_NAMES, tag_entities
from transmogrifai_tpu.ops.text_analysis import detect_language

LANG_INDEP = [
    # news register
    ("en", "The central bank raised interest rates by a quarter point on Thursday, citing persistent inflation in the services sector."),
    ("en", "Rescue teams pulled three survivors from the collapsed building overnight, officials confirmed."),
    ("de", "Die Zentralbank erhöhte am Donnerstag die Zinsen um einen Viertelpunkt und verwies auf die anhaltende Inflation im Dienstleistungssektor."),
    ("de", "Rettungskräfte bargen in der Nacht drei Überlebende aus dem eingestürzten Gebäude, wie Behörden bestätigten."),
    ("fr", "La banque centrale a relevé jeudi ses taux d'un quart de point, invoquant une inflation persistante dans le secteur des services."),
    ("fr", "Les équipes de secours ont extrait trois survivants de l'immeuble effondré pendant la nuit, ont confirmé les autorités."),
    ("es", "El banco central subió el jueves los tipos un cuarto de punto, alegando una inflación persistente en el sector servicios."),
    ("es", "Los equipos de rescate sacaron a tres supervivientes del edificio derrumbado durante la noche, confirmaron las autoridades."),
    ("it", "La banca centrale ha alzato giovedì i tassi di un quarto di punto, citando l'inflazione persistente nel settore dei servizi."),
    ("it", "Le squadre di soccorso hanno estratto tre superstiti dall'edificio crollato durante la notte, hanno confermato le autorità."),
    ("pt", "O banco central subiu os juros em um quarto de ponto na quinta-feira, citando a inflação persistente no setor de serviços."),
    ("pt", "As equipes de resgate retiraram três sobreviventes do prédio desabado durante a madrugada, confirmaram as autoridades."),
    ("nl", "De centrale bank verhoogde donderdag de rente met een kwart punt, onder verwijzing naar de aanhoudende inflatie in de dienstensector."),
    ("nl", "Reddingsteams haalden in de nacht drie overlevenden uit het ingestorte gebouw, bevestigden de autoriteiten."),
    ("pl", "Bank centralny podniósł w czwartek stopy procentowe o ćwierć punktu, powołując się na uporczywą inflację w sektorze usług."),
    ("pl", "Ekipy ratunkowe wyciągnęły w nocy trzech ocalałych z zawalonego budynku, potwierdziły władze."),
    ("ru", "Центральный банк в четверг повысил ставку на четверть пункта, сославшись на устойчивую инфляцию в секторе услуг."),
    ("ru", "Спасатели ночью извлекли троих выживших из обрушившегося здания, подтвердили власти."),
    ("uk", "Центральний банк у четвер підвищив ставку на чверть пункту, пославшись на стійку інфляцію в секторі послуг."),
    ("tr", "Merkez bankası perşembe günü faizleri çeyrek puan artırdı ve hizmet sektöründeki kalıcı enflasyona işaret etti."),
    ("sv", "Centralbanken höjde räntan med en kvarts procentenhet i torsdags med hänvisning till den ihållande inflationen i tjänstesektorn."),
    ("fi", "Keskuspankki nosti torstaina korkoja neljännespisteellä vedoten palvelualan sitkeään inflaatioon."),
    ("hu", "A jegybank csütörtökön negyed ponttal emelte a kamatot, a szolgáltatási szektor tartós inflációjára hivatkozva."),
    ("cs", "Centrální banka ve čtvrtek zvýšila sazby o čtvrt bodu s odkazem na přetrvávající inflaci v sektoru služeb."),
    ("ro", "Banca centrală a majorat joi dobânzile cu un sfert de punct, invocând inflația persistentă din sectorul serviciilor."),
    ("el", "Η κεντρική τράπεζα αύξησε την Πέμπτη τα επιτόκια κατά ένα τέταρτο της μονάδας, επικαλούμενη τον επίμονο πληθωρισμό στον τομέα των υπηρεσιών."),
    ("ar", "رفع البنك المركزي أسعار الفائدة ربع نقطة يوم الخميس مشيرا إلى استمرار التضخم في قطاع الخدمات."),
    ("fa", "بانک مرکزی روز پنجشنبه نرخ بهره را یک چهارم واحد افزایش داد و به تورم پایدار در بخش خدمات اشاره کرد."),
    ("he", "הבנק המרכזי העלה ביום חמישי את הריבית ברבע נקודה, בהצביעו על אינפלציה מתמשכת במגזר השירותים."),
    ("hi", "केंद्रीय बैंक ने गुरुवार को ब्याज दरों में चौथाई अंक की बढ़ोतरी की, सेवा क्षेत्र में लगातार महंगाई का हवाला देते हुए।"),
    ("ja", "中央銀行は木曜日、サービス部門の根強いインフレを理由に金利を0.25ポイント引き上げた。"),
    ("ko", "중앙은행은 목요일 서비스 부문의 지속적인 인플레이션을 이유로 금리를 0.25포인트 인상했다."),
    ("zh-cn", "中央银行周四将利率上调了四分之一个百分点，理由是服务业通胀持续。"),
    # technical-instruction register
    ("en", "Disconnect the power cable before removing the side panel, then loosen the four screws at the corners."),
    ("de", "Trennen Sie das Netzkabel, bevor Sie die Seitenabdeckung abnehmen, und lösen Sie dann die vier Schrauben an den Ecken."),
    ("fr", "Débranchez le câble d'alimentation avant de retirer le panneau latéral, puis desserrez les quatre vis aux coins."),
    ("es", "Desconecte el cable de alimentación antes de retirar el panel lateral y luego afloje los cuatro tornillos de las esquinas."),
    ("it", "Scollegare il cavo di alimentazione prima di rimuovere il pannello laterale, quindi allentare le quattro viti agli angoli."),
    ("pt", "Desligue o cabo de alimentação antes de remover o painel lateral e depois solte os quatro parafusos dos cantos."),
    ("nl", "Koppel de voedingskabel los voordat u het zijpaneel verwijdert en draai daarna de vier schroeven in de hoeken los."),
    ("da", "Tag strømkablet ud, før du fjerner sidepanelet, og løsn derefter de fire skruer i hjørnerne."),
    ("no", "Koble fra strømkabelen før du fjerner sidepanelet, og løsne deretter de fire skruene i hjørnene."),
    ("ru", "Отсоедините кабель питания перед снятием боковой панели, затем ослабьте четыре винта по углам."),
    ("tr", "Yan paneli çıkarmadan önce güç kablosunu çıkarın, ardından köşelerdeki dört vidayı gevşetin."),
    ("vi", "Ngắt cáp nguồn trước khi tháo tấm bên, sau đó nới lỏng bốn con vít ở các góc."),
    ("id", "Cabut kabel daya sebelum melepas panel samping, lalu kendurkan keempat sekrup di sudutnya."),
    # informal chat register
    ("en", "lol no way, she actually showed up two hours late and blamed the bus again"),
    ("de", "haha echt jetzt, er hat schon wieder sein handy im zug liegen lassen"),
    ("fr", "mdr sérieux, il a encore oublié son portefeuille chez lui, on a dû payer pour lui"),
    ("es", "jaja en serio, se le olvidaron las llaves otra vez y tuvimos que esperar fuera una hora"),
    ("it", "ahah davvero, ha perso di nuovo il portafoglio e abbiamo dovuto pagare noi"),
    ("pt", "kkk sério, ele esqueceu a carteira de novo e a gente teve que pagar tudo"),
    ("sv", "haha seriöst, hon missade tåget igen och fick vänta en timme på nästa"),
    ("pl", "haha serio, znowu zapomniał kluczy i czekaliśmy godzinę pod drzwiami"),
]


def test_lang_detect_independent_register_at_least_88pct():
    """Floor set 6 points under the measured 94.4% to absorb close-pair
    flutter; a drop toward the floor means register overfitting."""
    correct, misses = 0, []
    for lang, text in LANG_INDEP:
        got = next(iter(detect_language(text)), None)
        if got == lang:
            correct += 1
        else:
            misses.append((lang, got, text[:30]))
    acc = correct / len(LANG_INDEP)
    assert acc >= 0.88, f"accuracy {acc:.2%}; misses: {misses}"


# every person name below is asserted DISJOINT from GIVEN_NAMES
NER_CUE_CASES = [
    ("Dr. Okonkwo presented the findings to the committee yesterday.", ["okonkwo"]),
    ("Mrs. Vandermeer said the results were encouraging.", ["vandermeer"]),
    ("According to Professor Szymborski, the data was incomplete.", ["szymborski"]),
    ("Thandiwe Mabaso resigned from the board last week.", ["thandiwe mabaso"]),
    ("The award went to Mr. Quisenberry after a long deliberation.", ["quisenberry"]),
    ("Capt. Ostrowski explained that the route had changed.", ["ostrowski"]),
    ("Zydrunas Kavaliauskas married his longtime partner in June.", ["zydrunas kavaliauskas"]),
    ("Judge Abubakar noted that the appeal lacked merit.", ["abubakar"]),
    ("Ms. Thorvaldsen replied that the contract was void.", ["thorvaldsen"]),
    ("Sen. Okafor argued for the amendment on the floor.", ["okafor"]),
    ("Fenwick Attenborough died at the age of ninety.", ["fenwick attenborough"]),
    ("The book was written by Nnamdi Chukwuemeka, according to the preface.", ["nnamdi chukwuemeka"]),
    ("Gov. Palmqvist insisted the budget would balance.", ["palmqvist"]),
    ("Rev. Oyelaran laughed at the suggestion.", ["oyelaran"]),
    ("Wojciechowski shouted across the courtyard before the meeting.", ["wojciechowski"]),
]


def test_ner_names_are_disjoint_from_gazetteer():
    for _, names in NER_CUE_CASES:
        for name in names:
            for tok in name.split():
                assert tok not in GIVEN_NAMES, tok


def test_ner_context_rules_carry_unknown_names():
    """Honorific / person-verb / by-with rules must identify person names
    the gazetteer has never seen (measured F1 = 1.00; floor 0.9)."""
    tp = fp = fn = 0
    for text, expect in NER_CUE_CASES:
        got = set(tag_entities(text).get("person", []))
        exp = set(expect)
        tp += len(got & exp)
        fp += len(got - exp)
        fn += len(exp - got)
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    f1 = 2 * prec * rec / max(prec + rec, 1e-9)
    assert f1 >= 0.9, (prec, rec, f1)


def test_ner_structural_ceiling_is_honest():
    """The tagger's documented limits, pinned so they stay DOCUMENTED:
    single-token unknown names with no cue are dropped (by design), and
    a multiword Title-Case common-noun phrase can ride the person
    default (known false-positive class)."""
    # multiword no-cue names still default to person
    got = tag_entities("We met Oluwaseun Adeyemi at the conference.")
    assert got["person"] == ["oluwaseun adeyemi"]
    # by-design drop: lone unknown surname, no cue
    got = tag_entities("The committee thanked Okonjo for the contribution.")
    assert got["person"] == []
    # known false-positive class: capitalized common-noun phrase
    got = tag_entities(
        "The Monday meeting covered the Quarterly Report in detail."
    )
    assert got["person"] == ["quarterly report"]  # honest: this is wrong


def test_ner_document_level_surname_carry():
    """A lone surname with no cue of its own tags when an EARLIER
    strong-evidence person mention in the same text introduced it as
    their final token (round 5 - the trained-model behavior the
    gazetteer tagger lacked); a never-introduced lone token stays
    dropped, particles never carry, rule-6 default persons seed nothing,
    a later introduction does not retro-tag, and the person list keeps
    first-appearance order."""
    ents = tag_entities(
        "Thandiwe Mabaso resigned from the board last week. A day "
        "later Mabaso announced a new venture."
    )
    assert "thandiwe mabaso" in ents["person"]
    assert "mabaso" in ents["person"]
    # never-introduced single token still dropped (scope note intact)
    ents2 = tag_entities("The committee thanked Okonjo for the work.")
    assert ents2["person"] == []
    # the carry must not promote location/org tokens
    ents3 = tag_entities(
        "Dr. Okonkwo flew from Nairobi to Lagos. Nairobi was rainy."
    )
    assert "okonkwo" in ents3["person"]
    assert "nairobi" not in ents3["person"]
    # review r5: introduction must precede the lone mention
    r = tag_entities(
        "Mabaso was away. Thandiwe Mabaso resigned last week."
    )
    assert "mabaso" not in r["person"]
    # particles (non-final name tokens) never carry
    r = tag_entities("Ludwig van Beethoven resigned. Van went home.")
    assert "van" not in r["person"]
    # rule-6 default persons cannot seed carries
    r = tag_entities(
        "The Monday meeting covered the Quarterly Report in detail. "
        "Report authors were absent."
    )
    assert "report" not in r["person"]
    # first-appearance ordering survives the carry
    r = tag_entities(
        "Thandiwe Mabaso resigned. Mabaso left early. "
        "Priya Sharma resigned too."
    )
    assert r["person"] == ["thandiwe mabaso", "mabaso", "priya sharma"]


def test_ner_construction_coverage():
    """Common person constructions with gazetteer-disjoint names:
    appositives (both orders), role nouns, coordination under shared
    honorifics, and age insets all resolve; a LONE unknown token after
    'by' stays dropped by design (it is as likely an organization -
    'published by Penguin')."""
    cases = [
        ("The director, Thandiwe Mabaso, announced the merger.",
         "thandiwe mabaso"),
        ("According to spokeswoman Ingrid Haraldsdottir, sales rose.",
         "ingrid haraldsdottir"),
        ("The prize went to Dr. Okonkwo and Mrs. Vandermeer.",
         "okonkwo"),
        ("Thandiwe Mabaso, 54, retired on Friday.", "thandiwe mabaso"),
    ]
    for text, want in cases:
        assert want in tag_entities(text)["person"], text
    # shared-honorific coordination labels BOTH names
    got = tag_entities(
        "The prize went to Dr. Okonkwo and Mrs. Vandermeer."
    )["person"]
    assert "vandermeer" in got
    # by-design conservative drops
    assert tag_entities(
        "Okonkwo and Vandermeer signed the agreement."
    )["person"] == []
    assert tag_entities(
        "Interviewed by Chukwuemeka, the minister denied it."
    )["person"] == []
