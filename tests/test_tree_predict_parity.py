"""Cross-backend tree predict parity: native C++ vs jax predictors.

predict_arrays routes by SCORING batch size (sub-TX_TREE_NATIVE_ROWS
batches take the native predictor to skip device dispatch overhead), so
the same fitted model may score through either backend depending on
batch size.  That routing is only sound if the two predictors agree
EXACTLY - including at bin-threshold ties and NaN feature values, the
two places tree traversal could diverge (advisor r3 finding).  This pins
the equivalence.
"""
import numpy as np
import pytest

from transmogrifai_tpu.models import native_trees
from transmogrifai_tpu.models.tree_kernel import bin_data
from transmogrifai_tpu.models.trees import (
    OpGBTClassifier,
    OpRandomForestClassifier,
    OpRandomForestRegressor,
)


def _tricky_inputs(X_fit: np.ndarray, edges: np.ndarray, rng) -> np.ndarray:
    """Scoring rows that land EXACTLY on bin edges, far outside the fitted
    range, and NaN - the traversal tie/NaN cases the routing relies on."""
    n, d = 64, X_fit.shape[1]
    X = rng.normal(size=(n, d)).astype(np.float32)
    # rows 0..15: exact edge values (tie-breaking at the threshold)
    for i in range(16):
        j = i % d
        e = edges[j]
        X[i, j] = e[min(i % max(len(e), 1), len(e) - 1)] if len(e) else 0.0
    # rows 16..23: +/- inf-ish extremes
    X[16:20] = 1e30
    X[20:24] = -1e30
    # rows 24..31: NaNs scattered per-feature
    for i in range(24, 32):
        X[i, i % d] = np.nan
    return X


@pytest.mark.skipif(
    not native_trees.available(), reason="native tree kernels unavailable"
)
@pytest.mark.parametrize(
    "cls,kw",
    [
        (OpRandomForestClassifier, dict(num_trees=5, max_depth=4)),
        (OpRandomForestRegressor, dict(num_trees=5, max_depth=4)),
        (OpGBTClassifier, dict(num_trees=4, max_depth=3)),
    ],
)
def test_native_and_jax_predict_agree(cls, kw, monkeypatch):
    rng = np.random.default_rng(3)
    n, d = 400, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    est = cls(backend="jax", **kw)
    params = est.fit_arrays(X, y)
    Xs = _tricky_inputs(X, params["edges"], rng)

    monkeypatch.setitem(est.params, "backend", "native")
    monkeypatch.setenv("TX_TREE_NATIVE_ROWS", str(10**9))
    pred_n, raw_n, prob_n = est.predict_arrays(params, Xs)
    monkeypatch.setitem(est.params, "backend", "jax")
    pred_j, raw_j, prob_j = est.predict_arrays(params, Xs)

    if prob_n is None:
        # regressor means: native C++ and XLA may sum tree outputs in a
        # different order, so exact f32 bit equality over-asserts (a 1-ULP
        # 1.2e-7 difference was observed across hosts); class argmax
        # predictions below stay exactly equal
        np.testing.assert_allclose(pred_n, pred_j, rtol=1e-6, atol=1e-7)
    else:
        np.testing.assert_array_equal(pred_n, pred_j)
        np.testing.assert_allclose(prob_n, prob_j, atol=1e-6)


@pytest.mark.skipif(
    not native_trees.available(), reason="native tree kernels unavailable"
)
def test_bin_data_agrees_native_vs_python():
    """The two binners must assign identical bin ids, including exact-edge
    and NaN values (NaN routes to the last bin in both)."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(200, 4)).astype(np.float32)
    edges = [np.sort(rng.normal(size=7)).astype(np.float32) for _ in range(4)]
    X[0, 0] = edges[0][3]  # exact edge
    X[1, 1] = np.nan
    X[2, 2] = 1e30
    X[3, 3] = -1e30
    b_py = bin_data(X, edges)
    b_nat = native_trees.bin_data(X, edges)
    if b_nat is not None:
        np.testing.assert_array_equal(b_py, b_nat)


@pytest.mark.skipif(
    not native_trees.available(), reason="native tree kernels unavailable"
)
@pytest.mark.parametrize("seed", [11, 12, 13, 14, 15])
def test_native_jax_predict_parity_fuzz(seed, monkeypatch):
    """Random shapes/depths over TIE-HEAVY data (small integer grids make
    most cells land exactly on bin edges) plus constant and duplicated
    columns - the backends must agree on every row."""
    rng = np.random.default_rng(seed)
    n = 300
    d = int(rng.integers(3, 9))
    depth = int(rng.integers(2, 6))
    trees = int(rng.integers(2, 7))
    X = rng.integers(-3, 4, size=(n, d)).astype(np.float32)
    X[:, d - 1] = 1.0  # constant column: no splits available
    if d >= 4:
        X[:, d - 2] = X[:, 0]  # duplicated column
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    for cls, kw in (
        (OpRandomForestClassifier, dict(num_trees=trees, max_depth=depth)),
        (OpGBTClassifier, dict(num_trees=max(trees // 2, 2),
                               max_depth=max(depth - 1, 2))),
    ):
        est = cls(backend="jax", **kw)
        params = est.fit_arrays(X, y)
        Xs = _tricky_inputs(X, params["edges"], rng)
        # mixed scoring batch: fitted rows + tricky rows
        Xs = np.concatenate([X[:50], Xs], axis=0)
        monkeypatch.setitem(est.params, "backend", "native")
        monkeypatch.setenv("TX_TREE_NATIVE_ROWS", str(10**9))
        pred_n, _, prob_n = est.predict_arrays(params, Xs)
        monkeypatch.setitem(est.params, "backend", "jax")
        pred_j, _, prob_j = est.predict_arrays(params, Xs)
        np.testing.assert_array_equal(pred_n, pred_j)
        if prob_n is not None:
            np.testing.assert_allclose(prob_n, prob_j, atol=1e-6)
