"""Uniform per-stage contract tests.

Analog of the reference's OpTransformerSpec / OpEstimatorSpec library specs
(reference: features/src/main/scala/com/salesforce/op/test/
OpEstimatorSpec.scala:55, OpTransformerSpec.scala): EVERY public stage
class in ops/, models/ and preparators/ is driven through one shared
contract —

  construct -> wire testkit-generated inputs -> train -> score ->
  metadata presence -> deterministic re-transform -> save/load round-trip
  into a freshly built workflow -> bit-identical re-score -> copy isolation

A final coverage test asserts no public stage class escaped the
parametrization (estimator-produced Model classes are credited when an
estimator's contract run instantiates them).
"""
from __future__ import annotations

import base64
import importlib
import inspect
import pkgutil

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.stages.base import Estimator, PipelineStage
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import (
    GeolocationColumn,
    ListColumn,
    MapColumn,
    NumericColumn,
    PredictionColumn,
    TextColumn,
    VectorColumn,
)
from transmogrifai_tpu.utils.uid import reset_uids
from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

N = 80  # rows per contract dataset

# TX_CONTRACT_SEED offsets every generator seed so the whole harness can
# sweep data variations (default 0 = the pinned CI seeds)
import os as _os

_SEED_OFFSET = int(_os.environ.get("TX_CONTRACT_SEED", "0"))

# ---------------------------------------------------------------------------
# testkit-style typed value generation
# ---------------------------------------------------------------------------
_WORDS = ["alpha", "bravo", "charlie", "delta", "echo", "golf", "hotel"]
_PICKS = ["red", "green", "blue"]


def _scalar(t, rng):
    """One random value of feature type t (most-specific subtype first)."""
    if issubclass(t, ft.Binary):
        return bool(rng.rand() < 0.5)
    if issubclass(t, ft.Date):  # Date/DateTime (epoch millis)
        return int(1.5e12) + int(rng.randint(0, 10**9))
    if issubclass(t, ft.Integral):
        return int(rng.randint(0, 50))
    if issubclass(t, ft.Real):  # Real/RealNN/Percent/Currency
        return float(rng.randn())
    if issubclass(t, ft.Email):
        return f"{_WORDS[rng.randint(len(_WORDS))]}@example.com"
    if issubclass(t, ft.Phone):
        return f"650-{rng.randint(200, 999)}-{rng.randint(1000, 9999)}"
    if issubclass(t, ft.URL):
        return f"https://{_WORDS[rng.randint(len(_WORDS))]}.example.com/x"
    if issubclass(t, ft.Base64):
        payload = b"\x89PNG\r\n\x1a\n" + bytes(rng.randint(0, 256, 16).tolist())
        return base64.b64encode(payload).decode("ascii")
    if issubclass(t, ft.PickList) or issubclass(t, ft.ComboBox):
        return _PICKS[rng.randint(len(_PICKS))]
    if issubclass(t, ft.Country):
        return ["France", "Japan", "Brazil"][rng.randint(3)]
    if issubclass(t, ft.State):
        return ["CA", "NY", "TX"][rng.randint(3)]
    if issubclass(t, ft.PostalCode):
        return f"{rng.randint(10000, 99999)}"
    if issubclass(t, ft.Text):  # Text/TextArea/ID/City/Street
        k = rng.randint(1, 4)
        return " ".join(_WORDS[rng.randint(len(_WORDS))] for _ in range(k))
    if issubclass(t, ft.MultiPickList):
        k = rng.randint(0, 3)
        return frozenset(_PICKS[rng.randint(len(_PICKS))] for _ in range(k))
    if issubclass(t, ft.Geolocation):
        return (float(rng.uniform(-60, 60)), float(rng.uniform(-180, 180)), 5.0)
    if issubclass(t, ft.TextList):
        k = rng.randint(0, 4)
        return [_WORDS[rng.randint(len(_WORDS))] for _ in range(k)]
    if issubclass(t, ft.DateList):
        k = rng.randint(0, 3)
        return [int(1.5e12) + int(rng.randint(0, 10**9)) for _ in range(k)]
    raise TypeError(f"no generator for {t.__name__}")


def _values(t, n, rng, p_empty=0.1):
    """n optional values of type t (nullable types draw ~p_empty Nones)."""
    if issubclass(t, ft.OPMap):
        vt = t.value_type or ft.Text
        out = []
        for _ in range(n):
            if rng.rand() < p_empty:
                out.append({})
            else:
                out.append(
                    {k: _scalar(vt, rng) for k in ("k1", "k2", "k3")
                     if rng.rand() < 0.8}
                )
        return out
    if issubclass(t, ft.OPVector):
        return [rng.randn(4).tolist() for _ in range(n)]
    nullable = not t.non_nullable
    return [
        None if (nullable and rng.rand() < p_empty) else _scalar(t, rng)
        for _ in range(n)
    ]


def _raw(name, t, response=False):
    fb = FeatureBuilder(t, name)
    return fb.as_response() if response else fb.as_predictor()


# ---------------------------------------------------------------------------
# spec builders: each returns (result_feature, data_dict) for seeded rng
# ---------------------------------------------------------------------------
def _wire_simple(cls, in_types, ctor=None, data_fn=None):
    """Stage over raw features of in_types; ctor() builds the instance."""

    def build(n, rng):
        feats, data = [], {}
        for i, t in enumerate(in_types):
            name = f"in{i}"
            feats.append(_raw(name, t))
            data[name] = (data_fn or _values)(t, n, rng) if data_fn is None \
                else data_fn(i, t, n, rng)
        stage = cls() if ctor is None else ctor()
        stage.set_input(*feats)
        return stage.get_output(), data

    return build


def _wire_labeled(cls, x_type, ctor=None, binary_label=True):
    """Estimator over (RealNN label, x_type feature): label correlates with
    the input so fits are non-degenerate."""

    def build(n, rng):
        x = _values(x_type, n, rng)
        xv = np.array([0.0 if v is None else float(v) for v in x])
        noise = rng.randn(n) * 0.5
        y = (xv + noise > 0).astype(float) if binary_label else xv * 2 + noise
        lab = _raw("y", ft.RealNN, response=True)
        xf = _raw("x", x_type)
        stage = cls() if ctor is None else ctor()
        stage.set_input(lab, xf)
        return stage.get_output(), {"y": y.tolist(), "x": x}

    return build


def _predictor_data(n, rng, task):
    """3 Real predictors + label via a planted linear rule."""
    x1, x2, x3 = rng.randn(n), rng.randn(n), rng.randn(n)
    z = 1.2 * x1 - 0.8 * x2 + 0.3 * rng.randn(n)
    y = (z > 0).astype(float) if task == "clf" else z
    data = {"y": y.tolist(), "x1": x1.tolist(), "x2": x2.tolist(),
            "x3": x3.tolist()}
    return data


def _wire_predictor(cls, ctor=None, task="clf"):
    """(label, RealVectorizer([x1,x2,x3])) -> predictor -> Prediction."""
    from transmogrifai_tpu.ops.numeric import RealVectorizer

    def build(n, rng):
        data = _predictor_data(n, rng, task)
        y = _raw("y", ft.RealNN, response=True)
        xs = [_raw(f"x{i}", ft.Real) for i in (1, 2, 3)]
        vec = RealVectorizer().set_input(*xs).get_output()
        stage = cls() if ctor is None else ctor()
        stage.set_input(y, vec)
        return stage.get_output(), data

    return build


def _wire_vectorizer(cls, in_type, ctor=None, n_feats=2):
    """Variadic vectorizer over n_feats raw features of in_type."""

    def build(n, rng):
        feats, data = [], {}
        for i in range(n_feats):
            name = f"v{i}"
            feats.append(_raw(name, in_type))
            data[name] = _values(in_type, n, rng)
        stage = cls() if ctor is None else ctor()
        stage.set_input(*feats)
        return stage.get_output(), data

    return build


def _build_descaler(n, rng):
    from transmogrifai_tpu.ops.collections import (
        DescalerTransformer,
        ScalerTransformer,
    )

    a = _raw("a", ft.Real)
    scaled = ScalerTransformer(scaling_type="linear", slope=2.0,
                               intercept=1.0).set_input(a).get_output()
    out = DescalerTransformer().set_input(scaled, scaled).get_output()
    return out, {"a": _values(ft.Real, n, rng)}


def _build_prediction_descaler(n, rng):
    from transmogrifai_tpu.models.linear_regression import OpLinearRegression
    from transmogrifai_tpu.ops.collections import (
        PredictionDescaler,
        ScalerTransformer,
    )
    from transmogrifai_tpu.ops.numeric import RealVectorizer

    data = _predictor_data(n, rng, "reg")
    y = _raw("y", ft.RealNN, response=True)
    xs = [_raw(f"x{i}", ft.Real) for i in (1, 2, 3)]
    vec = RealVectorizer().set_input(*xs).get_output()
    scaled = ScalerTransformer(scaling_type="linear", slope=2.0,
                               intercept=1.0).set_input(y).get_output()
    pred = OpLinearRegression().set_input(scaled, vec).get_output()
    out = PredictionDescaler().set_input(pred, scaled).get_output()
    return out, data


def _build_dt_map_bucketizer(n, rng):
    from transmogrifai_tpu.ops.bucketizers import (
        DecisionTreeNumericMapBucketizer,
    )

    maps, ys = [], []
    for _ in range(n):
        v = float(rng.randn())
        m = {"k1": v}
        if rng.rand() < 0.7:
            m["k2"] = float(rng.randn())
        maps.append(m)
        ys.append(float(v + 0.3 * rng.randn() > 0))
    lab = _raw("y", ft.RealNN, response=True)
    xf = _raw("m", ft.RealMap)
    out = (DecisionTreeNumericMapBucketizer(max_depth=2)
           .set_input(lab, xf).get_output())
    return out, {"y": ys, "m": maps}


def _build_drop_indices(n, rng):
    from transmogrifai_tpu.ops.combiner import DropIndicesByTransformer
    from transmogrifai_tpu.ops.numeric import RealVectorizer

    a, b = _raw("a", ft.Real), _raw("b", ft.Real)
    vec = RealVectorizer().set_input(a, b).get_output()
    out = (
        DropIndicesByTransformer(predicate=_drop_null_indicators)
        .set_input(vec)
        .get_output()
    )
    return out, {"a": _values(ft.Real, n, rng), "b": _values(ft.Real, n, rng)}


def _drop_null_indicators(meta):  # module-level: survives workflow rebuild
    return meta.is_null_indicator


def _build_idf(n, rng):
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.ops.text import OpIDF

    a, b = _raw("a", ft.Real), _raw("b", ft.Real)
    vec = RealVectorizer().set_input(a, b).get_output()
    out = OpIDF(min_doc_freq=1).set_input(vec).get_output()
    return out, {"a": _values(ft.Real, n, rng), "b": _values(ft.Real, n, rng)}


def _build_vectors_combiner(n, rng):
    from transmogrifai_tpu.ops.combiner import VectorsCombiner
    from transmogrifai_tpu.ops.numeric import IntegralVectorizer, RealVectorizer

    a, b = _raw("a", ft.Real), _raw("b", ft.Integral)
    v1 = RealVectorizer().set_input(a).get_output()
    v2 = IntegralVectorizer().set_input(b).get_output()
    out = VectorsCombiner().set_input(v1, v2).get_output()
    return out, {"a": _values(ft.Real, n, rng),
                 "b": _values(ft.Integral, n, rng)}


def _build_sanity_checker(n, rng):
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.preparators.sanity_checker import SanityChecker

    data = _predictor_data(n, rng, "clf")
    y = _raw("y", ft.RealNN, response=True)
    xs = [_raw(f"x{i}", ft.Real) for i in (1, 2, 3)]
    vec = RealVectorizer().set_input(*xs).get_output()
    out = SanityChecker().set_input(y, vec).get_output()
    return out, data


def _build_deindexer(n, rng):
    from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.preparators.deindexer import PredictionDeIndexer

    data = _predictor_data(n, rng, "clf")
    data["ytext"] = ["yes" if v else "no" for v in data["y"]]
    y = _raw("y", ft.RealNN, response=True)
    ytext = _raw("ytext", ft.PickList)
    xs = [_raw(f"x{i}", ft.Real) for i in (1, 2, 3)]
    vec = RealVectorizer().set_input(*xs).get_output()
    pred = OpLogisticRegression(max_iter=5).set_input(y, vec).get_output()
    out = PredictionDeIndexer().set_input(ytext, pred).get_output()
    return out, data


def _build_lda(n, rng):
    from transmogrifai_tpu.models.unsupervised import OpLDA

    vec = _raw("counts", ft.OPVector)
    data = {"counts": [rng.poisson(2.0, 6).astype(float).tolist()
                       for _ in range(n)]}
    out = OpLDA(k=3, max_iter=5).set_input(vec).get_output()
    return out, data


def _build_word2vec(n, rng):
    from transmogrifai_tpu.models.unsupervised import OpWord2Vec

    tl = _raw("tokens", ft.TextList)
    data = {"tokens": [[_WORDS[rng.randint(len(_WORDS))]
                        for _ in range(rng.randint(2, 6))] for _ in range(n)]}
    out = (
        OpWord2Vec(vector_size=8, min_count=1, steps=50)
        .set_input(tl)
        .get_output()
    )
    return out, data


def _int_index_values(i, t, n, rng):
    return [float(rng.randint(0, 3)) for _ in range(n)]


def _lazy(module, name):
    def ctor_factory(**kw):
        cls = getattr(importlib.import_module(module), name)
        return cls(**kw)

    return ctor_factory


# ---------------------------------------------------------------------------
# the spec registry: class name -> build(n, rng) -> (result_feature, data)
# ---------------------------------------------------------------------------
def _specs():
    from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
    from transmogrifai_tpu.models.linear_regression import OpLinearRegression
    from transmogrifai_tpu.models.linear_svc import OpLinearSVC
    from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
    from transmogrifai_tpu.models.mlp import OpMultilayerPerceptronClassifier
    from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
    from transmogrifai_tpu.models import trees as tr
    from transmogrifai_tpu.ops import text_analysis as ta
    from transmogrifai_tpu.ops.bucketizers import (
        DecisionTreeNumericBucketizer,
        DecisionTreeNumericMapBucketizer,
        NumericBucketizer,
    )
    from transmogrifai_tpu.ops.categorical import (
        IndexToString,
        OneHotVectorizer,
        StringIndexer,
    )
    from transmogrifai_tpu.ops.collections import (
        FilterMap,
        IsotonicRegressionCalibrator,
        ScalerTransformer,
        ToOccurTransformer,
    )
    from transmogrifai_tpu.ops.combiner import AliasTransformer
    from transmogrifai_tpu.ops.dates import DateListVectorizer, DateVectorizer
    from transmogrifai_tpu.ops.geo import GeolocationVectorizer
    from transmogrifai_tpu.ops.maps import (
        MapVectorizer,
        TextMapLenEstimator,
        TextMapNullEstimator,
    )
    from transmogrifai_tpu.ops.numeric import (
        BinaryVectorizer,
        IntegralVectorizer,
        RealNNVectorizer,
        RealVectorizer,
    )
    from transmogrifai_tpu.ops.scalers import (
        FillMissingWithMean,
        OpScalarStandardScaler,
        PercentileCalibrator,
    )
    from transmogrifai_tpu.ops.text import (
        OpCountVectorizer,
        SmartTextVectorizer,
        TextListHashingVectorizer,
        TextListNullTransformer,
        TextTokenizer,
    )

    specs = {
        # -- plain transformers ------------------------------------------
        "NumericBucketizer": _wire_simple(
            NumericBucketizer, [ft.Real],
            ctor=lambda: NumericBucketizer(splits=[-np.inf, -1.0, 0.0, 1.0,
                                                   np.inf])),
        "IndexToString": (lambda n, rng: (
            IndexToString(labels=["a", "b", "c"])
            .set_input(_raw("idx", ft.Real)).get_output(),
            {"idx": _int_index_values(0, ft.Real, n, rng)})),
        "FilterMap": _wire_simple(
            FilterMap, [ft.TextMap],
            ctor=lambda: FilterMap(block_keys=["k2"])),
        "ScalerTransformer": _wire_simple(
            ScalerTransformer, [ft.Real],
            ctor=lambda: ScalerTransformer(scaling_type="linear", slope=2.0,
                                           intercept=1.0)),
        "DescalerTransformer": _build_descaler,
        "ToOccurTransformer": _wire_simple(ToOccurTransformer, [ft.Text]),
        "AliasTransformer": _wire_simple(
            AliasTransformer, [ft.Real],
            ctor=lambda: AliasTransformer(name="aliased")),
        "DropIndicesByTransformer": _build_drop_indices,
        "VectorsCombiner": _build_vectors_combiner,
        "OpIDF": _build_idf,
        "TextTokenizer": _wire_simple(TextTokenizer, [ft.Text]),
        "EmailToPickList": _wire_simple(ta.EmailToPickList, [ft.Email]),
        "JaccardSimilarity": _wire_simple(
            ta.JaccardSimilarity, [ft.MultiPickList, ft.MultiPickList]),
        "LangDetector": _wire_simple(ta.LangDetector, [ft.Text]),
        "BestLanguageDetector": _wire_simple(
            ta.BestLanguageDetector, [ft.Text]),
        "MimeTypeDetector": _wire_simple(ta.MimeTypeDetector, [ft.Base64]),
        "NGramSimilarity": _wire_simple(ta.NGramSimilarity, [ft.Text, ft.Text]),
        "SetNGramSimilarity": _wire_simple(
            ta.SetNGramSimilarity, [ft.MultiPickList, ft.MultiPickList]),
        "IsValidPhoneMapDefaultCountry": _wire_simple(
            ta.IsValidPhoneMapDefaultCountry, [ft.PhoneMap]),
        "MimeTypeMapDetector": _wire_simple(
            ta.MimeTypeMapDetector, [ft.Base64Map]),
        "TextListNullTransformer": _wire_vectorizer(
            TextListNullTransformer, ft.TextList),
        "PredictionDescaler": _build_prediction_descaler,
        "NameEntityRecognizer": _wire_simple(ta.NameEntityRecognizer, [ft.Text]),
        "PhoneNumberParser": _wire_simple(ta.PhoneNumberParser, [ft.Phone]),
        "TextLenTransformer": _wire_simple(ta.TextLenTransformer, [ft.Text]),
        "UrlToDomain": _wire_simple(ta.UrlToDomain, [ft.URL]),
        # -- label-free estimators ---------------------------------------
        "StringIndexer": _wire_simple(StringIndexer, [ft.PickList]),
        "OneHotVectorizer": _wire_vectorizer(
            OneHotVectorizer, ft.PickList,
            ctor=lambda: OneHotVectorizer(top_k=10, min_support=2)),
        "DateVectorizer": _wire_vectorizer(DateVectorizer, ft.Date),
        "DateListVectorizer": _wire_vectorizer(
            DateListVectorizer, ft.DateList,
            ctor=lambda: DateListVectorizer(
                pivot="SinceLast", reference_date_ms=1.6e12)),
        "GeolocationVectorizer": _wire_vectorizer(
            GeolocationVectorizer, ft.Geolocation),
        "MapVectorizer": (lambda n, rng: (
            MapVectorizer(top_k=10, min_support=2)
            .set_input(_raw("m1", ft.RealMap), _raw("m2", ft.PickListMap))
            .get_output(),
            {"m1": _values(ft.RealMap, n, rng),
             "m2": _values(ft.PickListMap, n, rng)})),
        "BinaryVectorizer": _wire_vectorizer(BinaryVectorizer, ft.Binary),
        "IntegralVectorizer": _wire_vectorizer(IntegralVectorizer, ft.Integral),
        "RealNNVectorizer": _wire_vectorizer(RealNNVectorizer, ft.RealNN),
        "RealVectorizer": _wire_vectorizer(RealVectorizer, ft.Real),
        "FillMissingWithMean": _wire_simple(FillMissingWithMean, [ft.Real]),
        "OpScalarStandardScaler": _wire_simple(OpScalarStandardScaler,
                                               [ft.Real]),
        "PercentileCalibrator": _wire_simple(
            PercentileCalibrator, [ft.Real],
            ctor=lambda: PercentileCalibrator(buckets=10)),
        "SmartTextVectorizer": _wire_vectorizer(
            SmartTextVectorizer, ft.Text,
            ctor=lambda: SmartTextVectorizer(max_cardinality=5, top_k=10,
                                             min_support=2, hash_dims=16)),
        "TextListHashingVectorizer": _wire_simple(
            TextListHashingVectorizer, [ft.TextList],
            ctor=lambda: TextListHashingVectorizer(hash_dims=16)),
        # -- labeled estimators ------------------------------------------
        "DecisionTreeNumericBucketizer": _wire_labeled(
            DecisionTreeNumericBucketizer, ft.Real,
            ctor=lambda: DecisionTreeNumericBucketizer(max_depth=2)),
        "DecisionTreeNumericMapBucketizer": _build_dt_map_bucketizer,
        "TextMapLenEstimator": _wire_vectorizer(TextMapLenEstimator,
                                                ft.TextMap),
        "TextMapNullEstimator": _wire_vectorizer(TextMapNullEstimator,
                                                 ft.TextMap),
        "OpCountVectorizer": _wire_simple(
            OpCountVectorizer, [ft.TextList],
            ctor=lambda: OpCountVectorizer(vocab_size=10)),
        "IsotonicRegressionCalibrator": _wire_labeled(
            IsotonicRegressionCalibrator, ft.Real),
        "SanityChecker": _build_sanity_checker,
        "PredictionDeIndexer": _build_deindexer,
        # -- predictors --------------------------------------------------
        "OpLogisticRegression": _wire_predictor(
            OpLogisticRegression, ctor=lambda: OpLogisticRegression(max_iter=5)),
        "OpLinearRegression": _wire_predictor(OpLinearRegression, task="reg"),
        "OpLinearSVC": _wire_predictor(
            OpLinearSVC, ctor=lambda: OpLinearSVC(max_iter=5)),
        "OpNaiveBayes": _wire_predictor(OpNaiveBayes),
        "OpMultilayerPerceptronClassifier": _wire_predictor(
            OpMultilayerPerceptronClassifier,
            ctor=lambda: OpMultilayerPerceptronClassifier(
                hidden_layers=(4,), max_iter=10)),
        "OpGeneralizedLinearRegression": _wire_predictor(
            OpGeneralizedLinearRegression,
            ctor=lambda: OpGeneralizedLinearRegression(max_iter=5),
            task="reg"),
        "OpRandomForestClassifier": _wire_predictor(
            tr.OpRandomForestClassifier,
            ctor=lambda: tr.OpRandomForestClassifier(num_trees=5, max_depth=3)),
        "OpRandomForestRegressor": _wire_predictor(
            tr.OpRandomForestRegressor,
            ctor=lambda: tr.OpRandomForestRegressor(num_trees=5, max_depth=3),
            task="reg"),
        "OpDecisionTreeClassifier": _wire_predictor(
            tr.OpDecisionTreeClassifier,
            ctor=lambda: tr.OpDecisionTreeClassifier(max_depth=3)),
        "OpDecisionTreeRegressor": _wire_predictor(
            tr.OpDecisionTreeRegressor,
            ctor=lambda: tr.OpDecisionTreeRegressor(max_depth=3), task="reg"),
        "OpGBTClassifier": _wire_predictor(
            tr.OpGBTClassifier,
            ctor=lambda: tr.OpGBTClassifier(num_trees=3)),
        "OpGBTRegressor": _wire_predictor(
            tr.OpGBTRegressor,
            ctor=lambda: tr.OpGBTRegressor(num_trees=3), task="reg"),
        "OpXGBoostClassifier": _wire_predictor(
            tr.OpXGBoostClassifier,
            ctor=lambda: tr.OpXGBoostClassifier(num_round=3)),
        "OpXGBoostRegressor": _wire_predictor(
            tr.OpXGBoostRegressor,
            ctor=lambda: tr.OpXGBoostRegressor(num_round=3), task="reg"),
        "OpLDA": _build_lda,
        "OpWord2Vec": _build_word2vec,
    }
    return specs


SPECS = _specs()

# classes with no standalone contract, with justification
EXCLUDED = {
    # abstract bases: concrete subclasses carry the contract
    "PredictorEstimator", "SequenceVectorizer", "SequenceVectorizerModel",
}

# classes instantiated during some estimator's contract run (filled at
# runtime; checked by test_zz_every_stage_class_is_covered)
_FITTED_SEEN: set[str] = set()


def _cols_equal(a, b) -> bool:
    if type(a) is not type(b) or len(a) != len(b):
        return False
    if isinstance(a, NumericColumn):
        return (np.array_equal(a.values, b.values)
                and np.array_equal(a.mask, b.mask))
    if isinstance(a, TextColumn):
        return list(a.values) == list(b.values)
    if isinstance(a, (ListColumn, MapColumn)):
        return a.values == b.values
    if isinstance(a, GeolocationColumn):
        return (np.array_equal(a.values, b.values)
                and np.array_equal(a.mask, b.mask))
    if isinstance(a, VectorColumn):
        return (np.array_equal(a.values, b.values)
                and a.metadata.column_names() == b.metadata.column_names())
    if isinstance(a, PredictionColumn):
        for x, y in ((a.prediction, b.prediction),
                     (a.raw_prediction, b.raw_prediction),
                     (a.probability, b.probability)):
            if (x is None) != (y is None):
                return False
            if x is not None and not np.array_equal(x, y):
                return False
        return True
    raise TypeError(f"unknown column type {type(a).__name__}")


@pytest.mark.parametrize("name", sorted(SPECS))
def test_stage_contract(name, tmp_path):
    build = SPECS[name]

    def mk():
        reset_uids()
        rng = np.random.RandomState(7 + _SEED_OFFSET)
        out, data = build(N, rng)
        wf = OpWorkflow().set_result_features(out)
        return wf, out, data

    wf, out, data = mk()
    wf.set_input_dataset(data)
    model = wf.train()
    _FITTED_SEEN.update(type(s).__name__ for s in model.stages)

    # 1. scoring produces a full-length column of the declared output kind
    col = model.score(data)[out.name]
    assert len(col) == N

    # 2. vector outputs carry coherent provenance metadata
    if isinstance(col, VectorColumn):
        assert col.metadata.size == col.width
        assert all(c.parent_feature_name for c in col.metadata.columns)

    # 3. deterministic re-transform
    col_b = model.score(data)[out.name]
    assert _cols_equal(col, col_b), "transform is not deterministic"

    # 4. save/load round-trip into a freshly built same-code workflow
    path = str(tmp_path / "model")
    model.save(path)
    wf2, out2, data2 = mk()
    model2 = OpWorkflowModel.load(path, wf2)
    col2 = model2.score(data2)[out2.name]
    assert _cols_equal(col, col2), "save/load round-trip changed outputs"

    # 5. round-trip equality must hold on UNSEEN data as well (catches
    #    fitted state that only looked right because training-data caches
    #    papered over it)
    _, data_new = build(N, np.random.RandomState(11 + _SEED_OFFSET))
    col_n1 = model.score(data_new)[out.name]
    col_n2 = model2.score(data_new)[out2.name]
    assert _cols_equal(col_n1, col_n2), (
        "loaded model diverges from original on unseen data"
    )

    # 6. copy isolation: mutating a copy's params never leaks back
    for s in model.stages:
        c = s.copy()
        c.set(__contract_probe__=1)
        assert "__contract_probe__" not in s.params


def _discover():
    found = {}
    for pkg in ("ops", "models", "preparators"):
        p = importlib.import_module(f"transmogrifai_tpu.{pkg}")
        for m in pkgutil.iter_modules(p.__path__):
            mn = f"transmogrifai_tpu.{pkg}.{m.name}"
            mod = importlib.import_module(mn)
            for cname, obj in vars(mod).items():
                if (inspect.isclass(obj) and issubclass(obj, PipelineStage)
                        and obj.__module__ == mn
                        and not cname.startswith("_")):
                    found[cname] = obj
    return found


def test_zz_every_stage_class_is_covered():
    """Coverage gate: every public stage class has a contract — directly
    parametrized, instantiated by an estimator's contract run, or
    explicitly excluded with justification."""
    found = _discover()
    missing = [
        n for n in found
        if n not in SPECS and n not in EXCLUDED and n not in _FITTED_SEEN
    ]
    assert not missing, f"stage classes with no contract coverage: {missing}"
