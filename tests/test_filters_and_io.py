"""RawFeatureFilter + model serialization tests (mirrors reference:
core/src/test/.../filters/RawFeatureFilterTest.scala,
OpWorkflowModelReaderWriterTest.scala)."""
import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401
from transmogrifai_tpu import Dataset, FeatureBuilder, OpWorkflow
from transmogrifai_tpu.filters.feature_distribution import compute_distribution
from transmogrifai_tpu.filters.raw_feature_filter import RawFeatureFilter
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import NumericColumn, TextColumn
from transmogrifai_tpu.utils.uid import reset_uids


def _mk_data(rng, n=300, leak=False):
    x1 = rng.randn(n)
    y = (x1 + 0.5 * rng.randn(n) > 0).astype(float)
    sparse = [None] * n  # nearly empty feature
    sparse[0] = 1.0
    x2 = rng.randn(n)
    null_leak = [float(v) if yy == 1 or not leak else None
                 for v, yy in zip(x2, y)]
    cat = [("a" if v > 0 else "b") for v in rng.randn(n)]
    return {
        "y": y.tolist(),
        "x1": x1.tolist(),
        "sparse": sparse,
        "leaky": null_leak,
        "cat": cat,
    }


def _features():
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    x1 = FeatureBuilder(ft.Real, "x1").as_predictor()
    sparse = FeatureBuilder(ft.Real, "sparse").as_predictor()
    leaky = FeatureBuilder(ft.Real, "leaky").as_predictor()
    cat = FeatureBuilder(ft.PickList, "cat").as_predictor()
    return y, [x1, sparse, leaky, cat]


def test_rff_drops_low_fill_and_leaky(rng):
    data = _mk_data(rng, leak=True)
    y, preds = _features()
    types = {"y": ft.RealNN, "x1": ft.Real, "sparse": ft.Real,
             "leaky": ft.Real, "cat": ft.PickList}
    ds = Dataset.from_pylists(data, types)
    rff = RawFeatureFilter(min_fill_rate=0.1, max_correlation=0.8)
    filtered = rff.filter_raw_data(ds, [y] + preds)
    dropped = {f.name for f in filtered.blacklisted_features}
    assert "sparse" in dropped       # fill rate ~0.003
    assert "leaky" in dropped        # null pattern predicts the label
    assert "x1" not in dropped and "cat" not in dropped
    assert "sparse" not in filtered.clean_data


def test_rff_js_divergence_drift(rng):
    n = 500
    train = Dataset.from_pylists(
        {"y": [0.0, 1.0] * (n // 2), "x": rng.randn(n).tolist()},
        {"y": ft.RealNN, "x": ft.Real},
    )
    score = Dataset.from_pylists(
        {"x": (rng.randn(n) + 10.0).tolist()}, {"x": ft.Real}
    )
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    x = FeatureBuilder(ft.Real, "x").as_predictor()
    rff = RawFeatureFilter(scoring_data=score, max_js_divergence=0.5)
    filtered = rff.filter_raw_data(train, [y, x])
    assert [f.name for f in filtered.blacklisted_features] == ["x"]


def test_rff_in_workflow_does_dag_surgery(rng):
    data = _mk_data(rng, leak=False)
    y, preds = _features()
    vec = transmogrify(preds)
    pred_stage = OpLogisticRegression(reg_param=0.01)
    prediction = pred_stage.set_input(y, vec).get_output()
    wf = (
        OpWorkflow()
        .set_result_features(prediction)
        .set_input_dataset(data)
        .with_raw_feature_filter(RawFeatureFilter(min_fill_rate=0.1))
    )
    model = wf.train()
    assert "sparse" in {f.name for f in wf.blacklisted_features}
    # vectorizer lost the blacklisted input
    scored = model.score(
        {k: v for k, v in data.items()}
    )
    assert prediction.name in scored


def test_distribution_monoid_merge(rng):
    col = NumericColumn.from_list(list(rng.randn(100)) + [None] * 20)
    d1 = compute_distribution("x", col.take(np.arange(60)), value_range=(-4, 4))
    d2 = compute_distribution("x", col.take(np.arange(60, 120)), value_range=(-4, 4))
    full = compute_distribution("x", col, value_range=(-4, 4))
    merged = d1.merge(d2)
    assert merged.count == full.count
    assert merged.nulls == full.nulls
    assert np.allclose(merged.histogram, full.histogram)


def test_model_save_load_roundtrip(tmp_path, rng):
    def build():
        reset_uids()
        y = FeatureBuilder(ft.RealNN, "y").as_response()
        a = FeatureBuilder(ft.Real, "a").as_predictor()
        c = FeatureBuilder(ft.PickList, "c").as_predictor()
        vec = transmogrify([a, c])
        checked = y.sanity_check(vec, remove_bad_features=False)
        pred = OpLogisticRegression(reg_param=0.01).set_input(y, checked).get_output()
        return OpWorkflow().set_result_features(pred), pred

    n = 200
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "c": [("u" if v > 0 else "v") for v in rng.randn(n)],
    }
    wf, pred = build()
    model = wf.train() if wf.set_input_dataset(data) else None
    scored1 = model.score(data)
    p1 = scored1[pred.name].probability

    model.save(str(tmp_path / "model"))

    wf2, pred2 = build()  # same code-defined workflow, fresh uids
    from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

    model2 = OpWorkflowModel.load(str(tmp_path / "model"), wf2)
    scored2 = model2.score(data)
    p2 = scored2[pred2.name].probability
    assert np.allclose(p1, p2, atol=1e-6)


def test_compute_data_up_to(tmp_path, rng):
    """computeDataUpTo parity (reference: OpWorkflowCore.scala:273-284):
    unfitted workflow fits only the upstream stages; fitted model reuses
    fitted state; the path variant saves Avro."""
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.readers.avro_reader import read_avro_records
    from transmogrifai_tpu.types import feature_types as ft

    n = 60
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "b": rng.randn(n).tolist(),
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = RealVectorizer().set_input(a, b).get_output()
    pred = OpLogisticRegression(reg_param=0.1).set_input(y, vec).get_output()

    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    up_to_pred = wf.compute_data_up_to(pred)
    # the vector column exists, the prediction column does NOT
    assert vec.name in up_to_pred
    assert pred.name not in up_to_pred

    avro_path = str(tmp_path / "upto.avro")
    model = wf.train()
    got = model.compute_data_up_to(pred, data=data, path=avro_path)
    assert vec.name in got and pred.name not in got
    np.testing.assert_allclose(
        np.asarray(got[vec.name].values),
        np.asarray(up_to_pred[vec.name].values), rtol=1e-6)
    schema, records = read_avro_records(avro_path)
    assert len(records) == n
    import pytest as _pytest

    with _pytest.raises(ValueError, match="needs data="):
        model.compute_data_up_to(pred)

    # a feature whose upstream stages the trained model never saw must
    # error loudly, not silently return raw columns
    vec2 = RealVectorizer().set_input(b, a).get_output()
    pred2 = OpLogisticRegression().set_input(y, vec2).get_output()
    with _pytest.raises(ValueError, match="not in .* DAG|not in"):
        model.compute_data_up_to(pred2, data=data)


@pytest.mark.parametrize("family", ["auto", "ovr"])
def test_multiclass_lr_save_load_roundtrip(tmp_path, rng, family):
    """Multiclass LR params (betas [K,d] / intercepts / classes / family)
    must survive the model writer and score identically after load - for
    both the round-5 multinomial softmax default and the OVR option."""
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

    n = 240
    yv = np.repeat(np.arange(3.0), n // 3)
    Xv = np.array([[2.0, 0], [-2, 1], [0, -2.5]])[yv.astype(int)]
    Xv = Xv + 0.5 * rng.randn(n, 2)
    data = {"y": yv.tolist(), "a": Xv[:, 0].tolist(), "b": Xv[:, 1].tolist()}

    def build():
        y = FeatureBuilder(ft.RealNN, "y").as_response()
        a = FeatureBuilder(ft.Real, "a").as_predictor()
        b = FeatureBuilder(ft.Real, "b").as_predictor()
        vec = transmogrify([a, b])
        pred = (
            OpLogisticRegression(reg_param=0.01, family=family)
            .set_input(y, vec).get_output()
        )
        return OpWorkflow().set_result_features(pred).set_input_dataset(data)

    m1 = build().train()
    expect_family = "multinomial" if family == "auto" else "ovr"
    assert m1.stages[-1].model_params["family"] == expect_family
    m1.save(str(tmp_path / "mc_model"))
    m2 = OpWorkflowModel.load(str(tmp_path / "mc_model"), build())
    assert m2.stages[-1].model_params["family"] == expect_family
    s1 = [c for c in m1.score(data).columns().values()
          if hasattr(c, "prediction")]
    s2 = [c for c in m2.score(data).columns().values()
          if hasattr(c, "prediction")]
    assert len(s1) == len(s2) == 1
    np.testing.assert_allclose(s1[0].prediction, s2[0].prediction)
    np.testing.assert_allclose(s1[0].probability, s2[0].probability,
                               atol=1e-12)


def test_feature_distribution_js_divergence_properties(rng):
    """JS divergence invariants (reference FeatureDistribution.
    jsDivergence): identity 0, symmetry, log2 bound of 1 on disjoint
    support, monoid merge commutes with divergence inputs."""
    from transmogrifai_tpu.filters.feature_distribution import (
        FeatureDistribution,
    )

    def dist(hist):
        h = np.asarray(hist, dtype=np.float64)
        return FeatureDistribution(
            name="f", key=None, count=int(h.sum()), nulls=0, histogram=h
        )

    a = dist(rng.randint(1, 50, 16))
    b = dist(rng.randint(1, 50, 16))
    assert a.js_divergence(a) == pytest.approx(0.0, abs=1e-12)
    assert a.js_divergence(b) == pytest.approx(b.js_divergence(a))
    assert 0.0 <= a.js_divergence(b) <= 1.0 + 1e-12
    # disjoint support saturates the log2 bound
    left = dist([10, 10, 0, 0])
    right = dist([0, 0, 7, 3])
    assert left.js_divergence(right) == pytest.approx(1.0)
    # scale invariance: divergence depends on shapes, not counts
    scaled = dist(np.asarray(a.histogram) * 7)
    assert a.js_divergence(b) == pytest.approx(scaled.js_divergence(b))
    # merge is the histogram monoid: merging equals summing
    m = a.merge(dist(a.histogram))
    assert m.count == 2 * a.count
    assert m.js_divergence(a) == pytest.approx(0.0, abs=1e-12)


def test_model_load_failure_modes_are_loud(tmp_path, rng):
    """Corrupted or mismatched saved models must raise clearly, never
    load partially: missing arrays.npz, truncated model.json, and a
    workflow whose stage set differs from the saved graph.  Since the
    crash-consistent artifact format (ISSUE 2) both corruptions are
    caught by manifest verification as ModelIntegrityError (naming the
    damage) instead of leaking FileNotFoundError/JSONDecodeError."""
    import shutil

    import numpy as np

    from transmogrifai_tpu.serialization.model_io import ModelIntegrityError

    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

    n = 80
    data = {"y": (rng.rand(n) > 0.5).astype(float).tolist(),
            "a": rng.randn(n).tolist()}

    def build(extra=False):
        fy = FeatureBuilder(ft.RealNN, "y").as_response()
        preds = [FeatureBuilder(ft.Real, "a").as_predictor()]
        if extra:
            preds.append(FeatureBuilder(ft.Real, "extra").as_predictor())
        vec = transmogrify(preds)
        pred = (
            OpLogisticRegression(reg_param=0.01)
            .set_input(fy, vec).get_output()
        )
        wf = OpWorkflow().set_result_features(pred)
        return wf.set_input_dataset(data) if not extra else wf

    m = build().train()
    base = tmp_path / "m"
    m.save(str(base))

    broken1 = tmp_path / "m1"
    shutil.copytree(base, broken1)
    (broken1 / "arrays.npz").unlink()
    with pytest.raises(ModelIntegrityError, match="arrays.npz"):
        OpWorkflowModel.load(str(broken1), build())

    broken2 = tmp_path / "m2"
    shutil.copytree(base, broken2)
    (broken2 / "model.json").write_text(
        (broken2 / "model.json").read_text()[:50]
    )
    with pytest.raises(ModelIntegrityError, match="truncated"):
        OpWorkflowModel.load(str(broken2), build())

    with pytest.raises(ValueError, match="same code-defined workflow"):
        OpWorkflowModel.load(str(base), build(extra=True))
