"""Property-style tests for the workflow engine over RANDOM feature DAGs
(VERDICT r3 item 8: aim the contract-harness style at workflow/dag.py).

A generator builds random graphs - 2-5 numeric predictors, random-depth
transformer chains, label-touching sanity checkers at random depths,
1-3 parallel selectors - and asserts cut_dag's structural invariants on
every one (partition, leakage-freedom, transitive refit closure,
downstream 'after' exactness).  A smaller seed set backs the invariants
with real training: fold refit counts, warm-start skip sets, and
computeDataUpTo prefix equivalence against a fully trained model.
"""
import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - activates the feature DSL
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.selector.factories import (
    BinaryClassificationModelSelector,
)
from transmogrifai_tpu.selector.splitters import DataSplitter
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow.dag import (
    _label_touching,
    compute_dag,
    cut_dag,
    flatten,
)


def _random_graph(rng, n_selectors=None, with_after=None):
    """Random feature DAG.  Returns (data, y, selectors, result_features,
    intermediates) where intermediates are features strictly upstream of
    the selectors (fair game for computeDataUpTo)."""
    n = 160
    n_pred = int(rng.randint(2, 6))
    data = {"y": (rng.rand(n) > 0.5).astype(float).tolist()}
    names = [f"x{i}" for i in range(n_pred)]
    for i, nm in enumerate(names):
        col = rng.randn(n)
        if i == 0:  # keep one informative column so fits converge
            col = col + 2.0 * np.asarray(data["y"])
        data[nm] = col.tolist()
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    preds = [FeatureBuilder(ft.Real, nm).as_predictor() for nm in names]

    # random transformer chains on a few predictors (non-label stages)
    chained = []
    for f in preds:
        depth = int(rng.randint(0, 3))
        for _ in range(depth):
            f = (f + float(rng.randn())) if rng.rand() < 0.5 else (
                f * float(1.0 + abs(rng.randn()))
            )
        chained.append(f)

    k = int(rng.randint(1, 4)) if n_selectors is None else n_selectors
    selectors, sel_preds, intermediates = [], [], []
    for si in range(k):
        lo = int(rng.randint(0, len(chained)))
        subset = chained[lo:] or chained
        vec = transmogrify(list(subset))
        intermediates.append(vec)
        # label-touching stage at random depth (or absent)
        branch = rng.rand()
        if branch < 0.6:
            vec = y.sanity_check(vec, remove_bad_features=False)
            intermediates.append(vec)
            if branch < 0.2:  # two chained label-touching stages
                vec = y.sanity_check(vec, remove_bad_features=False)
                intermediates.append(vec)
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=3,
            models_and_parameters=[
                (OpLogisticRegression(max_iter=6), [{"reg_param": 0.01}])
            ],
            splitter=DataSplitter(reserve_test_fraction=0.1),
        )
        pred = sel.set_input(y, vec).get_output()
        selectors.append(sel)
        sel_preds.append(pred)

    results = list(sel_preds)
    if with_after or (with_after is None and rng.rand() < 0.4):
        # a stage strictly downstream of a selector output
        results.append(sel_preds[0].alias(f"renamed_{rng.randint(10**6)}"))
    return data, y, selectors, results, intermediates


@pytest.mark.parametrize("seed", range(25))
def test_cut_dag_invariants_on_random_graphs(seed):
    rng = np.random.RandomState(seed)
    data, y, selectors, results, _ = _random_graph(rng)
    dag = compute_dag(results)
    before, during, after = cut_dag(dag, selectors)

    all_stages = set(flatten(dag))
    b = {s for layer in before for s in layer}
    d = set(during)
    a = {s for layer in after for s in layer}

    # 1. exact partition
    assert b | d | a == all_stages
    assert not (b & d) and not (b & a) and not (d & a)
    assert all(sel in d for sel in selectors)

    # per-selector upstream cones (stage -> in cone of selector?)
    cones = {}
    for sel in selectors:
        cone = {
            st for st in sel.get_output().parent_stages()
            if st is not sel and st in all_stages
        }
        cones[sel.uid] = cone

    # 2. leakage-freedom: no label-touching stage upstream of a selector
    #    ever stays in 'before'
    for sel in selectors:
        for st in cones[sel.uid]:
            if _label_touching(st):
                assert st in d, (
                    f"seed {seed}: label-touching {st.uid} left in before"
                )

    # 3. transitive closure: anything in a cone DOWNSTREAM of a during
    #    stage is during too (the round-2 single-hop bug regression)
    for sel in selectors:
        cone = cones[sel.uid]
        for st in cone:
            if st not in d:
                continue
            st_out = st.get_output().uid
            for other in cone:
                if any(p.uid == st_out for p in other.input_features):
                    assert other in d, (
                        f"seed {seed}: {other.uid} consumes during-stage "
                        f"{st.uid} output but is not during"
                    )

    # 4. 'after' is exactly the transitive downstream of selector outputs
    produced = {sel.get_output().uid for sel in selectors}
    expect_after = set()
    changed = True
    while changed:
        changed = False
        for st in all_stages - set(selectors) - expect_after:
            if any(p.uid in produced for p in st.input_features):
                expect_after.add(st)
                produced.add(st.get_output().uid)
                changed = True
    assert a == expect_after, f"seed {seed}"

    # 5. selectors with no label-touching cone stage contribute only
    #    themselves to 'during'
    for sel in selectors:
        if not any(_label_touching(st) for st in cones[sel.uid]):
            assert not (cones[sel.uid] & d), f"seed {seed}"


@pytest.mark.parametrize("seed", [3, 11])
def test_workflow_cv_fold_refit_counts_on_random_graphs(seed, monkeypatch):
    """Every label-touching SanityChecker upstream of a selector refits
    once per fold under with_workflow_cv - counted, not assumed."""
    from transmogrifai_tpu.preparators import sanity_checker as sc_mod

    rng = np.random.RandomState(seed)
    data, y, selectors, results, _ = _random_graph(
        rng, n_selectors=1, with_after=False
    )
    dag = compute_dag(results)
    _, during, _ = cut_dag(dag, selectors)
    n_checkers = sum(
        1 for s in during if isinstance(s, sc_mod.SanityChecker)
    )

    calls = {"n": 0}
    orig = sc_mod.SanityChecker.fit_model

    def counting(self, cols, ds):
        calls["n"] += 1
        return orig(self, cols, ds)

    monkeypatch.setattr(sc_mod.SanityChecker, "fit_model", counting)
    wf = (
        OpWorkflow().set_result_features(*results)
        .set_input_dataset(data).with_workflow_cv()
    )
    wf.train()
    # n_folds refits per during-checker + exactly one final full-data fit
    assert calls["n"] == 3 * n_checkers + n_checkers, (
        f"seed {seed}: {calls['n']} fits for {n_checkers} checkers"
    )


def _fit_uids(model):
    return {
        m["stage_uid"] for m in model.app_metrics.to_json()["stages"]
        if m["phase"] == "fit"
    }


@pytest.mark.parametrize("seed", [5, 17])
def test_warm_start_skip_sets_on_random_graphs(seed):
    """Extending a random trained graph with one new estimator and warm
    starting refits EXACTLY the new stages."""
    rng = np.random.RandomState(seed)
    data, y, selectors, results, intermediates = _random_graph(
        rng, n_selectors=1, with_after=False
    )
    wf1 = OpWorkflow().set_result_features(*results).set_input_dataset(data)
    m1 = wf1.train()
    fitted_once = _fit_uids(m1)
    assert fitted_once

    new_pred = (
        OpLogisticRegression(max_iter=6, reg_param=0.1)
        .set_input(y, intermediates[0])
        .get_output()
    )
    wf2 = (
        OpWorkflow()
        .set_result_features(*results, new_pred)
        .set_input_dataset(data)
        .with_model_stages(m1)
    )
    m2 = wf2.train()
    refit = _fit_uids(m2)
    assert not (refit & fitted_once), f"seed {seed}: re-fit {refit & fitted_once}"
    assert refit == {new_pred.origin_stage.uid}


@pytest.mark.parametrize("seed", [2, 9])
def test_compute_data_up_to_prefix_equivalence(seed):
    """computeDataUpTo on the workflow == the same columns from a fully
    trained model's computeDataUpTo (deterministic upstream stages)."""
    rng = np.random.RandomState(seed)
    data, y, selectors, results, intermediates = _random_graph(
        rng, n_selectors=1, with_after=False
    )
    target = intermediates[-1]
    wf = OpWorkflow().set_result_features(*results).set_input_dataset(data)
    ds_workflow = wf.compute_data_up_to(target)

    model = wf.train()
    ds_model = model.compute_data_up_to(target, data=data)
    for name, col in ds_workflow.columns().items():
        other = ds_model.columns().get(name)
        assert other is not None, f"seed {seed}: {name} missing from model side"
        va, vb = col.to_list(), other.to_list()
        assert len(va) == len(vb)
        for x, z in zip(va, vb):
            if isinstance(x, float) and isinstance(z, float):
                assert abs(x - z) < 1e-9
            else:
                assert x == z


@pytest.mark.parametrize("seed", [7, 21])
def test_training_is_deterministic(seed):
    """SURVEY §5.2: determinism is engineered (name-sorted layers, seeded
    samplers).  Two trains of the same random graph on the same data must
    produce IDENTICAL fitted parameters and scores."""
    rng = np.random.RandomState(seed)
    data, y, selectors, results, _ = _random_graph(
        rng, n_selectors=1, with_after=False
    )

    def train_once():
        wf = (
            OpWorkflow().set_result_features(*results)
            .set_input_dataset(data)
        )
        model = wf.train()
        scored = model.score()
        return {
            name: col.prediction if hasattr(col, "prediction")
            else col.values
            for name, col in scored.columns().items()
        }

    s1, s2 = train_once(), train_once()
    assert set(s1) == set(s2)
    for name in s1:
        v1, v2 = np.asarray(s1[name]), np.asarray(s2[name])
        if v1.dtype.kind in "fc":
            np.testing.assert_array_equal(v1, v2), name
        else:
            assert (v1 == v2).all(), name


@pytest.mark.parametrize("seed", range(15))
def test_blacklist_cascade_invariants_on_random_graphs(seed):
    """_apply_blacklist over random graphs + random raw blacklists:
    after surgery (reference setBlacklist semantics, cascade included)
    no surviving stage references a blacklisted feature, cascaded
    outputs are themselves blacklisted, and the workflow either trains
    clean on the reduced raw set or rejected the cut loudly."""
    rng = np.random.RandomState(1000 + seed)
    data, y, selectors, results, _ = _random_graph(rng)
    wf = OpWorkflow().set_result_features(*results)
    raw_preds = [f for f in wf.raw_features if not f.is_response]
    k = int(rng.randint(1, max(2, len(raw_preds))))
    cut = list(rng.choice(len(raw_preds), size=k, replace=False))
    wf.blacklisted_features = [raw_preds[i] for i in cut]
    try:
        wf._apply_blacklist()
    except ValueError:
        # legal only when the cut reaches a result feature
        return
    bl_uids = {f.uid for f in wf.blacklisted_features}
    dag = compute_dag(wf.result_features)
    for stage in flatten(dag):
        for f in stage.input_features:
            assert f.uid not in bl_uids, (
                f"stage {stage.uid} still reads blacklisted {f.name}"
            )
    # surviving raw set excludes every blacklisted raw
    raw_names = {f.name for f in wf.raw_features}
    for i in cut:
        assert raw_preds[i].name not in raw_names
    # the reduced workflow still trains and scores on the reduced data
    reduced = {k_: v for k_, v in data.items() if k_ in raw_names or k_ == "y"}
    model = wf.set_input_dataset(reduced).train()
    out = model.score(reduced)
    for rf in wf.result_features:
        assert rf.name in out
