"""Exact-calendar date semantics + DateList pivots.

The unit-circle encoder must use EXACT UTC calendar fields like the
reference's Joda lookups (reference: DateToUnitCircleTransformer.scala:
117-130) — day-of-month 1 at angle 0, ISO weekOfWeekyear — and the
DateList pivots mirror DateListVectorizer.scala:49-260 (SinceFirst/
SinceLast whole-day distances, modal-field one-hots with ties to the
smallest value, empty-list fill + null tracking).
"""
from __future__ import annotations

import datetime as _dt

import numpy as np
import pytest

from transmogrifai_tpu.features.feature_builder import FeatureBuilder
from transmogrifai_tpu.ops.dates import (
    DateListVectorizer,
    DateVectorizer,
    MS_PER_DAY,
    PERIOD_SIZES,
    day_of_month0,
    day_of_week0,
    day_of_year0,
    hour_of_day,
    iso_week_of_year,
    month_of_year0,
    period_fraction,
    period_value,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow.workflow import OpWorkflow


def _ms(y, m, d, h=0, mi=0):
    return _dt.datetime(y, m, d, h, mi, tzinfo=_dt.timezone.utc).timestamp() * 1000.0


# --- exact calendar fields, pinned against python's datetime/isocalendar ---


def test_reference_docstring_example():
    """'timestamp 01/01/2018 6:37 maps to angle 2*pi*6/24' — integer hour
    (DateToUnitCircleTransformer.scala:68-69)."""
    ts = np.array([_ms(2018, 1, 1, 6, 37)])
    assert hour_of_day(ts)[0] == 6
    assert period_fraction(ts, "HourOfDay")[0] == pytest.approx(6 / 24)


def test_first_of_month_is_angle_zero():
    for y, m in [(2018, 1), (2019, 2), (2020, 12), (1969, 7)]:
        ts = np.array([_ms(y, m, 1)])
        assert day_of_month0(ts)[0] == 0, (y, m)
        assert period_fraction(ts, "DayOfMonth")[0] == 0.0


@pytest.mark.parametrize("date", [
    (2018, 1, 1), (2019, 12, 31), (2020, 2, 29), (2021, 3, 14),
    (1970, 1, 1), (1969, 12, 31), (2000, 2, 29), (2024, 9, 30),
])
def test_calendar_fields_match_stdlib(date):
    y, m, d = date
    ts = np.array([_ms(y, m, d, 13)])
    py = _dt.date(y, m, d)
    assert day_of_month0(ts)[0] == d - 1
    assert month_of_year0(ts)[0] == m - 1
    assert day_of_week0(ts)[0] == py.weekday()  # Monday=0
    assert day_of_year0(ts)[0] == py.timetuple().tm_yday - 1
    assert iso_week_of_year(ts)[0] == py.isocalendar()[1]
    assert hour_of_day(ts)[0] == 13


def test_calendar_fields_random_sweep_vs_stdlib():
    """500 random dates 1950-2050: every exact field agrees with python's
    datetime/isocalendar (vectorized batch, one call per field)."""
    rng = np.random.RandomState(11)
    days = rng.randint(-7305, 29220, size=500)  # 1950..2050 in epoch days
    hours = rng.randint(0, 24, size=500)
    ts = days * MS_PER_DAY + hours * 3600_000.0
    dom = day_of_month0(ts)
    moy = month_of_year0(ts)
    dow = day_of_week0(ts)
    doy = day_of_year0(ts)
    week = iso_week_of_year(ts)
    hod = hour_of_day(ts)
    for i, (d, h) in enumerate(zip(days, hours)):
        py = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(d))
        assert dom[i] == py.day - 1
        assert moy[i] == py.month - 1
        assert dow[i] == py.weekday()
        assert doy[i] == py.timetuple().tm_yday - 1
        assert week[i] == py.isocalendar()[1]
        assert hod[i] == h


def test_iso_week_boundary_cases():
    """2019-12-30 (Mon) is week 1 of ISO year 2020; 2021-01-01 (Fri) is
    week 53 of ISO year 2020 — the Thursday rule."""
    assert iso_week_of_year(np.array([_ms(2019, 12, 30)]))[0] == 1
    assert iso_week_of_year(np.array([_ms(2021, 1, 1)]))[0] == 53
    assert iso_week_of_year(np.array([_ms(2016, 1, 1)]))[0] == 53


def test_week_of_month_reference_semantics():
    """weekOfWeekyear - weekOfWeekyear(first of month), raw difference
    (DateToUnitCircleTransformer.scala:125-126)."""
    ts = np.array([_ms(2021, 3, 14)])  # week 10; Mar 1 2021 is week 9
    assert period_value(ts, "WeekOfMonth")[0] == 1
    assert period_value(np.array([_ms(2021, 3, 1)]), "WeekOfMonth")[0] == 0


def test_period_sizes_match_reference():
    assert PERIOD_SIZES == {
        "HourOfDay": 24, "DayOfWeek": 7, "DayOfMonth": 31,
        "DayOfYear": 366, "MonthOfYear": 12, "WeekOfMonth": 6,
        "WeekOfYear": 53,
    }


def test_pre_epoch_dates_stay_in_range():
    ts = np.array([_ms(1969, 12, 31, 23)])
    assert hour_of_day(ts)[0] == 23
    assert day_of_week0(ts)[0] == 2  # Wednesday
    assert day_of_month0(ts)[0] == 30
    for p, size in PERIOD_SIZES.items():
        if p == "WeekOfMonth":
            continue  # raw difference, deliberately unbounded
        v = period_value(ts, p)[0]
        assert 0 <= v < size, (p, v)


def test_unit_circle_continuity_hour_wrap():
    """23:xx and 00:xx land adjacent on the circle (the encoding's whole
    point); integer-hour parity means the circle has 24 discrete points."""
    late = np.array([_ms(2018, 5, 5, 23)])
    early = np.array([_ms(2018, 5, 6, 0)])
    for f in (np.sin, np.cos):
        a = f(2 * np.pi * period_fraction(late, "HourOfDay"))
        b = f(2 * np.pi * period_fraction(early, "HourOfDay"))
        assert abs(a - b) < 2 * np.sin(np.pi / 24) + 1e-9


# --- DateList pivots -------------------------------------------------------


def _fit_datelist(values, **kw):
    f = FeatureBuilder(ft.DateList, "dates").as_predictor()
    vec = DateListVectorizer(**kw).set_input(f).get_output()
    data = {"dates": values}
    model = (
        OpWorkflow().set_result_features(vec).set_input_dataset(data).train()
    )
    return np.asarray(model.score(data)[vec.name].to_list(), dtype=float), model


REF = _ms(2021, 6, 15, 12)  # reference date for Since* pivots


def test_since_last_whole_days():
    vals = [
        [_ms(2021, 6, 1), _ms(2021, 6, 10)],   # last = Jun 10 -> 5 days
        [_ms(2021, 6, 14, 13)],                # 0 full days (23h)
        [],                                    # empty -> fill + null flag
    ]
    out, _ = _fit_datelist(vals, pivot="SinceLast", reference_date_ms=REF,
                           fill_value=-1.0)
    assert out.shape == (3, 2)  # days + null indicator
    assert out[0].tolist() == [5.0, 0.0]
    assert out[1].tolist() == [0.0, 0.0]
    assert out[2].tolist() == [-1.0, 1.0]


def test_since_first_and_future_events_negative():
    vals = [[_ms(2021, 6, 1), _ms(2021, 6, 10)],
            [_ms(2021, 6, 20)]]  # after the reference -> negative days
    out, _ = _fit_datelist(vals, pivot="SinceFirst", reference_date_ms=REF)
    assert out[0, 0] == 14.0
    assert out[1, 0] == -4.0


def test_mode_day_one_hot_with_tie_to_smallest():
    monday, tuesday = _ms(2021, 6, 14), _ms(2021, 6, 15)
    vals = [
        [monday, monday, tuesday],   # mode Monday
        [tuesday, monday],           # tie -> smallest (Monday)
        [],
    ]
    out, model = _fit_datelist(vals, pivot="ModeDay", reference_date_ms=REF)
    assert out.shape == (3, 8)  # 7 days + null
    assert out[0, :7].tolist() == [1, 0, 0, 0, 0, 0, 0]
    assert out[1, :7].tolist() == [1, 0, 0, 0, 0, 0, 0]
    assert out[2].tolist() == [0] * 7 + [1]
    # metadata names the day columns
    vec_name = model.result_features[0].name
    col = model.score({"dates": vals})[vec_name]
    assert [c.indicator_value for c in col.metadata.columns][:2] == [
        "Monday", "Tuesday"]


def test_mode_month_and_mode_hour():
    vals = [[_ms(2021, 3, 2), _ms(2021, 3, 9), _ms(2021, 4, 1)]]
    out, _ = _fit_datelist(vals, pivot="ModeMonth", reference_date_ms=REF,
                           track_nulls=False)
    assert out.shape == (1, 12)
    assert out[0, 2] == 1.0 and out.sum() == 1.0  # March
    vals = [[_ms(2021, 3, 2, 7), _ms(2021, 3, 9, 7), _ms(2021, 4, 1, 22)]]
    out, model = _fit_datelist(vals, pivot="ModeHour", reference_date_ms=REF,
                               track_nulls=False)
    assert out.shape == (1, 24)
    assert out[0, 7] == 1.0 and out.sum() == 1.0
    # hour columns are named like the reference: "0:00".."23:00"
    # (DateListVectorizer.scala:275)
    name = model.result_features[0].name
    col = model.score({"dates": vals})[name]
    assert col.metadata.columns[7].indicator_value == "7:00"


def test_scalar_date_vectorize_includes_days_since():
    """Scalar Date transmogrification combines the unit circles with the
    SinceLast days column (RichDateFeature.vectorize:97-110)."""
    f = FeatureBuilder(ft.Date, "d").as_predictor()
    vec = DateVectorizer(
        periods=("HourOfDay",), with_time_since=True,
        reference_date_ms=REF,
    ).set_input(f).get_output()
    data = {"d": [_ms(2021, 6, 10), None]}
    model = (
        OpWorkflow().set_result_features(vec).set_input_dataset(data).train()
    )
    col = model.score(data)[vec.name]
    out = np.asarray(col.to_list(), dtype=float)
    assert out.shape == (2, 4)  # sin, cos, days, null
    assert out[0, 2] == 5.0  # Jun 10 -> Jun 15 reference
    assert out[1].tolist() == [0.0, 0.0, 0.0, 1.0]
    descs = [c.descriptor_value for c in col.metadata.columns]
    assert descs[2] == "SinceLast"


def test_invalid_pivot_rejected():
    with pytest.raises(ValueError, match="pivot"):
        DateListVectorizer(pivot="SinceForever")


def test_transmogrify_routes_datelist():
    from transmogrifai_tpu.ops.transmogrifier import transmogrify

    f = FeatureBuilder(ft.DateList, "dates").as_predictor()
    r = FeatureBuilder(ft.Real, "x").as_predictor()
    vec = transmogrify([f, r])
    data = {"dates": [[_ms(2021, 6, 1)], []], "x": [1.0, 2.0]}
    model = (
        OpWorkflow().set_result_features(vec).set_input_dataset(data).train()
    )
    col = model.score(data)[vec.name]
    out = np.asarray(col.to_list(), dtype=float)
    assert out.shape[1] >= 4  # since-days + null + real + real-null
    assert any(c.descriptor_value == "SinceLast" for c in col.metadata.columns)


def test_datelist_save_load_roundtrip_pins_reference_date(tmp_path):
    vals = [[_ms(2021, 6, 1)], [_ms(2021, 6, 10)]]
    f = FeatureBuilder(ft.DateList, "dates").as_predictor()
    vec = DateListVectorizer(pivot="SinceLast").set_input(f).get_output()
    data = {"dates": vals}
    model = (
        OpWorkflow().set_result_features(vec).set_input_dataset(data).train()
    )
    before = model.score(data)[vec.name].to_list()
    model.save(str(tmp_path / "m"))
    from transmogrifai_tpu.serialization.model_io import load_model

    f2 = FeatureBuilder(ft.DateList, "dates").as_predictor()
    vec2 = DateListVectorizer(pivot="SinceLast").set_input(f2).get_output()
    wf2 = OpWorkflow().set_result_features(vec2).set_input_dataset(data)
    m2 = load_model(str(tmp_path / "m"), wf2)
    after = m2.score(data)[vec2.name].to_list()
    assert before == after  # captured now() must round-trip, not re-capture
