"""Avro/Parquet readers + LDA + Word2Vec tests."""
import os

import numpy as np
import pytest

from transmogrifai_tpu.features.feature_builder import FeatureBuilder
from transmogrifai_tpu.models.unsupervised import OpLDA, OpWord2Vec
from transmogrifai_tpu.readers.avro_reader import (
    AvroReader,
    ParquetReader,
    read_avro_records,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import ListColumn, VectorColumn
from transmogrifai_tpu.types.dataset import Dataset
from transmogrifai_tpu.types.vector_metadata import VectorMetadata

PASSENGER_AVRO = "/root/reference/test-data/PassengerDataAll.avro"
PASSENGER_PARQUET = (
    "/root/reference/test-data/BigPassengerWithHeader.parquet"
)


@pytest.mark.skipif(not os.path.exists(PASSENGER_AVRO), reason="no avro data")
def test_avro_reader_titanic():
    schema, records = read_avro_records(PASSENGER_AVRO)
    assert schema["name"] == "Passenger"
    assert len(records) == 891
    assert records[0]["Name"].startswith("Braund")
    surv = FeatureBuilder(ft.RealNN, "Survived").as_response()
    age = FeatureBuilder(ft.Real, "Age").as_predictor()
    sex = FeatureBuilder(ft.PickList, "Sex").as_predictor()
    ds = AvroReader(PASSENGER_AVRO).generate_dataset([surv, age, sex])
    assert len(ds) == 891
    assert set(v for v in ds["Sex"].values if v) == {"male", "female"}
    assert abs(np.nanmean([v for v in ds["Age"].to_list() if v]) - 29.7) < 0.5


@pytest.mark.skipif(
    not os.path.exists(PASSENGER_PARQUET), reason="no parquet data"
)
def test_parquet_reader():
    surv = FeatureBuilder(ft.RealNN, "survived").as_response()
    ds = ParquetReader(PASSENGER_PARQUET).generate_dataset([surv])
    assert len(ds) > 0


def test_lda_separates_topics(rng):
    # two disjoint vocab halves -> topics should specialize
    n, v, k = 60, 20, 2
    counts = np.zeros((n, v), dtype=np.float32)
    for i in range(n):
        half = i % 2
        idx = rng.randint(0, v // 2, size=20) + half * (v // 2)
        np.add.at(counts[i], idx, 1.0)
    ds = Dataset({"vec": VectorColumn(counts, VectorMetadata("vec", tuple()))})
    f = FeatureBuilder(ft.OPVector, "vec").as_predictor()
    model = OpLDA(k=k, max_iter=20).set_input(f).fit(ds)
    out = model.transform(ds)[model.output_name]
    theta = out.values
    assert theta.shape == (n, k)
    np.testing.assert_allclose(theta.sum(axis=1), 1.0, atol=1e-3)
    # same-parity docs should cluster on the same dominant topic
    dom = theta.argmax(axis=1)
    assert (dom[::2] == dom[0]).mean() > 0.9
    assert (dom[1::2] == dom[1]).mean() > 0.9
    assert dom[0] != dom[1]


def test_word2vec_embeds_cooccurring_words(rng):
    docs = []
    for i in range(200):
        if i % 2 == 0:
            docs.append(("cat", "dog", "pet", "animal"))
        else:
            docs.append(("car", "road", "drive", "engine"))
    ds = Dataset({"toks": ListColumn(docs, ft.TextList)})
    f = FeatureBuilder(ft.TextList, "toks").as_predictor()
    est = OpWord2Vec(vector_size=16, min_count=2, steps=800, batch=64)
    model = est.set_input(f).fit(ds)
    out = model.transform(ds)[model.output_name]
    assert out.values.shape == (200, 16)
    sims = dict(model.similar_words("cat", top_k=3))
    assert set(sims) & {"dog", "pet", "animal"}


def test_avro_writer_round_trips_through_reader(tmp_path):
    """write_avro_records -> read_avro_records is the identity for the
    supported schema subset, both codecs (the reader half is golden-tested
    against the reference's fixtures, so round-trip = spec conformance)."""
    from transmogrifai_tpu.readers.avro_reader import (
        read_avro_records,
        write_avro_records,
    )

    schema = {
        "type": "record", "name": "Row", "fields": [
            {"name": "id", "type": "long"},
            {"name": "name", "type": ["null", "string"]},
            {"name": "score", "type": ["null", "double"]},
            {"name": "flag", "type": "boolean"},
            {"name": "tags", "type": {"type": "array", "items": "string"}},
            {"name": "attrs", "type": {"type": "map", "values": "double"}},
            {"name": "nested", "type": ["null", {
                "type": "record", "name": "Inner", "fields": [
                    {"name": "a", "type": "long"}]}]},
        ],
    }
    records = [
        {"id": 1, "name": "ann", "score": 0.25, "flag": True,
         "tags": ["x", "y"], "attrs": {"k": 1.5}, "nested": {"a": 7}},
        {"id": -9, "name": None, "score": None, "flag": False,
         "tags": [], "attrs": {}, "nested": None},
        {"id": 2**40, "name": "bob", "score": -1e30, "flag": True,
         "tags": ["z"], "attrs": {"m": -2.0, "n": 0.0}, "nested": {"a": -1}},
    ]
    for codec in ("null", "deflate"):
        path = str(tmp_path / f"t_{codec}.avro")
        assert write_avro_records(path, schema, records, codec=codec) == 3
        got_schema, got = read_avro_records(path)
        assert got == records
        assert got_schema["fields"][0]["name"] == "id"


def test_csv_to_avro_matches_csv_reader(tmp_path):
    """csv_to_avro (reference: CSVToAvro.scala) writes an OCF whose
    AvroReader columns equal the CSVReader's own typed columns."""
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.examples.titanic import TITANIC_CSV, TITANIC_COLUMNS

    if not os.path.exists(TITANIC_CSV):
        pytest.skip("titanic csv not available on this host")
    from transmogrifai_tpu.readers.avro_reader import AvroReader, csv_to_avro
    from transmogrifai_tpu.readers.csv_reader import CSVReader
    from transmogrifai_tpu.types import feature_types as ft

    feats = [
        FeatureBuilder(ft.Real, "age").as_predictor(),
        FeatureBuilder(ft.Text, "name").as_predictor(),
        FeatureBuilder(ft.Integral, "pClass").as_predictor(),
    ]
    path = str(tmp_path / "titanic.avro")
    n = csv_to_avro(TITANIC_CSV, path, feats, has_header=False,
                    headers=TITANIC_COLUMNS)
    ds_csv = CSVReader(TITANIC_CSV, has_header=False,
                       headers=TITANIC_COLUMNS).generate_dataset(feats)
    assert n == len(ds_csv)
    ds_avro = AvroReader(path).generate_dataset(feats)
    import numpy as np

    for f in feats:
        a, c = ds_avro[f.name], ds_csv[f.name]
        if a.values.dtype == object:  # text columns
            assert list(a.values) == list(c.values)
        else:
            assert np.array_equal(a.mask, c.mask)
            assert np.allclose(a.values[a.mask], c.values[c.mask])


def test_avro_writer_randomized_round_trip(tmp_path, rng):
    """Seeded fuzz over the supported schema space: random nesting of
    primitives/unions/arrays/maps/records/enums/fixed must round-trip
    exactly through write_avro_records -> read_avro_records."""
    import string

    from transmogrifai_tpu.readers.avro_reader import (
        read_avro_records,
        write_avro_records,
    )

    names = iter(f"F{i}" for i in range(10_000))

    def rand_schema(depth=0):
        prims = ["boolean", "int", "long", "float", "double", "bytes",
                 "string", "null"]
        kinds = prims + (["array", "map", "record", "union", "enum", "fixed"]
                         if depth < 3 else [])
        k = kinds[rng.randint(len(kinds))]
        if k == "array":
            return {"type": "array", "items": rand_schema(depth + 1)}
        if k == "map":
            return {"type": "map", "values": rand_schema(depth + 1)}
        if k == "record":
            return {"type": "record", "name": next(names), "fields": [
                {"name": next(names), "type": rand_schema(depth + 1)}
                for _ in range(rng.randint(1, 4))
            ]}
        if k == "union":
            return ["null", rand_schema(depth + 1)]
        if k == "enum":
            return {"type": "enum", "name": next(names),
                    "symbols": ["A", "B", "C"]}
        if k == "fixed":
            return {"type": "fixed", "name": next(names), "size": 4}
        return k

    def rand_value(schema):
        if isinstance(schema, list):
            if rng.rand() < 0.4:
                return None
            branch = next(s for s in schema if s != "null")
            return rand_value(branch)
        if isinstance(schema, dict):
            t = schema["type"]
            if t == "array":
                return [rand_value(schema["items"])
                        for _ in range(rng.randint(0, 4))]
            if t == "map":
                return {
                    "".join(rng.choice(list(string.ascii_lowercase), 4)):
                        rand_value(schema["values"])
                    for _ in range(rng.randint(0, 3))
                }
            if t == "record":
                return {f["name"]: rand_value(f["type"])
                        for f in schema["fields"]}
            if t == "enum":
                return schema["symbols"][rng.randint(3)]
            if t == "fixed":
                return bytes(rng.randint(0, 256, schema["size"]).tolist())
            return rand_value(t)
        if schema == "null":
            return None
        if schema == "boolean":
            return bool(rng.rand() < 0.5)
        if schema in ("int", "long"):
            return int(rng.randint(-(2**40), 2**40))
        if schema == "float":
            import struct as _s
            # round-trip through f32 so equality is exact
            return _s.unpack("<f", _s.pack("<f", float(rng.randn())))[0]
        if schema == "double":
            return float(rng.randn())
        if schema == "bytes":
            return bytes(rng.randint(0, 256, rng.randint(0, 8)).tolist())
        if schema == "string":
            return "".join(rng.choice(list(string.ascii_letters), rng.randint(0, 9)))
        raise AssertionError(schema)

    for trial in range(8):
        schema = {"type": "record", "name": f"T{trial}", "fields": [
            {"name": next(names), "type": rand_schema()}
            for _ in range(rng.randint(1, 5))
        ]}
        records = [rand_value(schema) for _ in range(rng.randint(1, 12))]
        path = str(tmp_path / f"fz{trial}.avro")
        codec = ("null", "deflate")[trial % 2]
        assert write_avro_records(path, schema, records, codec=codec) \
            == len(records)
        _, got = read_avro_records(path)
        assert got == records
