"""Native C++ kernel tests: exact agreement with the python reference
implementations (host-side murmur3/tokenize/parse, native/txkernels.cpp)."""
import numpy as np
import pytest

from transmogrifai_tpu.ops.text import tokenize
from transmogrifai_tpu.utils.hashing import hashing_tf, murmur3_32
from transmogrifai_tpu.utils.native import (
    get_lib,
    murmur3_batch,
    parse_doubles,
    tokenize_hash_tf,
)

needs_native = pytest.mark.skipif(get_lib() is None, reason="no g++/native lib")


@needs_native
def test_native_murmur3_matches_python():
    values = ["", "a", "hello", "hello, world", "x" * 100, "émile zola"]
    out = murmur3_batch(values, seed=42)
    expected = [murmur3_32(v.encode("utf-8"), seed=42) for v in values]
    assert out.tolist() == expected


@needs_native
def test_native_tokenize_hash_matches_python_ascii():
    texts = [
        "Hello, World! This is TEXT number 42.",
        "the quick brown fox",
        None,
        "",
        "repeat repeat repeat",
    ]
    dims = 64
    native = tokenize_hash_tf(texts, dims, seed=42)
    py = hashing_tf([tokenize(t) for t in texts], dims, seed=42)
    np.testing.assert_array_equal(native, py)


@needs_native
def test_native_parse_doubles():
    vals = ["1.5", "", "abc", "-2e3", "  7 ", "0"]
    out, mask = parse_doubles(vals)
    assert mask.tolist() == [True, False, False, True, True, True]
    assert out[0] == 1.5 and out[3] == -2000.0 and out[4] == 7.0


@needs_native
def test_native_throughput_smoke():
    n = 20000
    texts = [f"user {i} bought item_{i % 97} at store {i % 13}" for i in range(n)]
    import time

    t0 = time.time()
    out = tokenize_hash_tf(texts, 512)
    dt = time.time() - t0
    assert out.shape == (n, 512)
    assert dt < 2.0  # native should chew 20k rows in well under 2s


def test_native_bridges_degenerate_inputs(rng):
    """The ctypes bridges must survive the inputs that crash naive C:
    empty batches, None rows, NUL bytes, 10k-char strings, single-row /
    constant-feature / two-value tree fits (round-5 robustness sweep)."""
    import numpy as np

    from transmogrifai_tpu.models import native_trees
    from transmogrifai_tpu.utils.native import tokenize_hash_tf

    for case, n_rows in (
        (["", None, "a" * 10000, "héllo wörld 日本語", "a,b;c|d"], 5),
        ([], 0),
        ([None], 1),
        (["\x00weird\x00bytes"], 1),
    ):
        out = tokenize_hash_tf(case, 16, seed=42)
        if out is not None:  # None = no native lib (python fallback)
            assert out.shape == (n_rows, 16)
            assert np.isfinite(out).all()

    if not native_trees.available():
        return
    from transmogrifai_tpu.models.trees import OpRandomForestClassifier

    est = OpRandomForestClassifier(num_trees=2, max_depth=2,
                                   backend="native")
    p = est.fit_arrays(np.array([[1.0, 2.0]]), np.array([1.0]))
    pred, _, _ = est.predict_arrays(p, np.array([[1.0, 2.0]]))
    assert pred[0] == 1.0
    X = np.ones((50, 3))
    y = (rng.rand(50) > 0.5).astype(float)
    p = est.fit_arrays(X, y)
    _, _, prob = est.predict_arrays(p, X)
    assert np.isfinite(prob).all()
    X2 = np.repeat([[1.0], [2.0]], 25, axis=0)
    y2 = np.r_[np.ones(25), np.zeros(25)]
    p2 = est.fit_arrays(X2, y2)
    pred2, _, _ = est.predict_arrays(p2, X2)
    assert (pred2 == y2).mean() == 1.0


def test_tokenize_hash_tf_unicode_parity_with_python():
    """The fused native path must hash EXACTLY like the python
    tokenizer+hasher on non-ASCII text (unicode lowercasing, emoji are
    not \\w, >4096-byte tokens) - cross-backend model portability.
    Before the routing fix the native kernel byte-lowercased ('Ü' stayed
    uppercase), kept emoji as tokens, and hashed truncated long tokens."""
    import numpy as np

    from transmogrifai_tpu.ops.text import tokenize
    from transmogrifai_tpu.utils.hashing import hashing_tf
    from transmogrifai_tpu.utils.native import tokenize_hash_tf

    rng = np.random.RandomState(9)
    texts = [
        "Ünïcødé tökens über alles", "emoji \U0001F600 in \U0001F600 text",
        "a" * 5000 + " tail", "mixed ASCII und Ümlaut wörter",
        "中文 分词 测试 中文", "pure ascii stays native", "", None,
    ]
    pools = "abc déf 中文 \U0001F600 xyz,;!"
    texts += [
        "".join(pools[rng.randint(len(pools))]
                for _ in range(rng.randint(1, 60)))
        for _ in range(100)
    ]
    nat = tokenize_hash_tf(texts, 64, seed=42)
    if nat is None:
        pytest.skip("native lib unavailable")
    py = hashing_tf([tokenize(t) for t in texts], 64, seed=42)
    np.testing.assert_array_equal(np.asarray(nat), np.asarray(py))
