"""Native C++ kernel tests: exact agreement with the python reference
implementations (host-side murmur3/tokenize/parse, native/txkernels.cpp)."""
import numpy as np
import pytest

from transmogrifai_tpu.ops.text import tokenize
from transmogrifai_tpu.utils.hashing import hashing_tf, murmur3_32
from transmogrifai_tpu.utils.native import (
    get_lib,
    murmur3_batch,
    parse_doubles,
    tokenize_hash_tf,
)

needs_native = pytest.mark.skipif(get_lib() is None, reason="no g++/native lib")


@needs_native
def test_native_murmur3_matches_python():
    values = ["", "a", "hello", "hello, world", "x" * 100, "émile zola"]
    out = murmur3_batch(values, seed=42)
    expected = [murmur3_32(v.encode("utf-8"), seed=42) for v in values]
    assert out.tolist() == expected


@needs_native
def test_native_tokenize_hash_matches_python_ascii():
    texts = [
        "Hello, World! This is TEXT number 42.",
        "the quick brown fox",
        None,
        "",
        "repeat repeat repeat",
    ]
    dims = 64
    native = tokenize_hash_tf(texts, dims, seed=42)
    py = hashing_tf([tokenize(t) for t in texts], dims, seed=42)
    np.testing.assert_array_equal(native, py)


@needs_native
def test_native_parse_doubles():
    vals = ["1.5", "", "abc", "-2e3", "  7 ", "0"]
    out, mask = parse_doubles(vals)
    assert mask.tolist() == [True, False, False, True, True, True]
    assert out[0] == 1.5 and out[3] == -2000.0 and out[4] == 7.0


@needs_native
def test_native_throughput_smoke():
    n = 20000
    texts = [f"user {i} bought item_{i % 97} at store {i % 13}" for i in range(n)]
    import time

    t0 = time.time()
    out = tokenize_hash_tf(texts, 512)
    dt = time.time() - t0
    assert out.shape == (n, 512)
    assert dt < 2.0  # native should chew 20k rows in well under 2s
