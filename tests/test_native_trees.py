"""Native C++ histogram tree learner: backend parity with the JAX kernels.

The C++ learner (native/txtrees.cpp) is the framework's libxgboost
equivalent (SURVEY §2.9 - reference's only native dependency is
ml.dmlc:xgboost4j-spark's JNI libxgboost, reference core/build.gradle:27).
Both backends emit the same flat-heap layout, so deterministic fits
(single tree, GBT: no bootstrap, no per-node feature subsets) must agree
exactly and stochastic forests must agree statistically.
"""
import numpy as np
import pytest

from transmogrifai_tpu.models import native_trees
from transmogrifai_tpu.models.trees import (
    OpDecisionTreeClassifier,
    OpDecisionTreeRegressor,
    OpGBTClassifier,
    OpGBTRegressor,
    OpRandomForestClassifier,
)

pytestmark = pytest.mark.skipif(
    not native_trees.available(), reason="native tree library unavailable"
)


def _data(seed=0, n=800, d=8):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.2 * rng.randn(n) > 0.3).astype(
        np.float64
    )
    yreg = (2 * X[:, 0] + X[:, 1] + 0.1 * rng.randn(n)).astype(np.float64)
    return X, y, yreg


@pytest.mark.parametrize("cls", [OpDecisionTreeClassifier, OpGBTClassifier])
def test_deterministic_classifier_parity(cls):
    X, y, _ = _data()
    kw = {"num_trees": 5} if cls is OpGBTClassifier else {}
    mj, mn = cls(backend="jax", **kw), cls(backend="native", **kw)
    pj, pn = mj.fit_arrays(X, y), mn.fit_arrays(X, y)
    pred_j = mj.predict_arrays(pj, X)[0]
    pred_n = mn.predict_arrays(pn, X)[0]
    assert (pred_j == pred_n).mean() == 1.0


@pytest.mark.parametrize("cls", [OpDecisionTreeRegressor, OpGBTRegressor])
def test_deterministic_regressor_parity(cls):
    X, _, yreg = _data()
    kw = {"num_trees": 5} if cls is OpGBTRegressor else {}
    mj, mn = cls(backend="jax", **kw), cls(backend="native", **kw)
    pj, pn = mj.fit_arrays(X, yreg), mn.fit_arrays(X, yreg)
    a = mj.predict_arrays(pj, X)[0]
    b = mn.predict_arrays(pn, X)[0]
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_single_tree_heap_identical():
    """Heap arrays themselves must match for a deterministic single tree."""
    X, y, _ = _data(seed=3)
    mj = OpDecisionTreeClassifier(backend="jax")
    mn = OpDecisionTreeClassifier(backend="native")
    pj, pn = mj.fit_arrays(X, y), mn.fit_arrays(X, y)
    hf_j, ht_j, hl_j, hv_j = (np.asarray(h) for h in pj["heaps"])
    hf_n, ht_n, hl_n, hv_n = pn["heaps"]
    np.testing.assert_array_equal(hf_j, hf_n)
    np.testing.assert_array_equal(ht_j, ht_n)
    np.testing.assert_array_equal(hl_j, hl_n)
    np.testing.assert_allclose(hv_j, hv_n, rtol=1e-4, atol=1e-3)


def test_forest_statistical_agreement():
    """Bootstrapped forests share boot weights but differ in per-node
    feature-subset RNG streams -> predictions agree on most rows."""
    X, y, _ = _data(seed=1, n=1200)
    mj = OpRandomForestClassifier(backend="jax", num_trees=20, max_depth=5)
    mn = OpRandomForestClassifier(backend="native", num_trees=20, max_depth=5)
    pj, pn = mj.fit_arrays(X, y), mn.fit_arrays(X, y)
    pred_j = mj.predict_arrays(pj, X)[0]
    pred_n = mn.predict_arrays(pn, X)[0]
    assert (pred_j == pred_n).mean() > 0.9
    assert (pred_n == y).mean() > 0.85


def test_native_bin_data_matches_searchsorted():
    rng = np.random.RandomState(7)
    X = rng.randn(500, 6).astype(np.float32)
    X[::17, 2] = np.nan  # NaN must sort last in both backends
    from transmogrifai_tpu.models.tree_kernel import quantile_bin_edges

    edges = quantile_bin_edges(X, 32)
    got = native_trees.bin_data(X, edges)
    want = np.empty_like(got)
    for j in range(X.shape[1]):
        want[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    np.testing.assert_array_equal(got, want)


def test_fold_weight_fan_out_native():
    """CV fold masks ride the weight vector through the native path too."""
    X, y, _ = _data(seed=5)
    n = len(y)
    rng = np.random.RandomState(0)
    fold = rng.randint(0, 3, size=n)
    W = np.stack([(fold != f).astype(np.float32) for f in range(3)])
    m = OpRandomForestClassifier(backend="native", num_trees=10, max_depth=4)
    models = m.fit_arrays_folds(X, y, W)
    assert len(models) == 3
    for f, params in enumerate(models):
        pred = m.predict_arrays(params, X[fold == f])[0]
        assert (pred == y[fold == f]).mean() > 0.75
