"""DSL enrichment surface (reference: core/.../dsl/Rich*Feature.scala).

The README experience: per-type .vectorize(...), numeric/scaling/bucketize
math, text/email/url/phone/base64 enrichments, set/vector/map methods -
all as Feature methods, executed through real workflows.
"""
import base64

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - patches Feature
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import (
    ListColumn,
    NumericColumn,
    TextColumn,
    VectorColumn,
)


def _train(out_features, data):
    wf = OpWorkflow().set_result_features(*out_features)
    wf.set_input_dataset(data)
    model = wf.train()
    return model.score(data)


def test_vectorize_dispatches_per_type(rng):
    n = 60
    data = {
        "r": rng.randn(n).tolist(),
        "i": [int(v) for v in rng.randint(0, 9, n)],
        "b": [bool(v) for v in rng.rand(n) > 0.5],
        "d": [int(1.5e12 + v) for v in rng.randint(0, 10**9, n)],
        "p": [("a", "b", "c")[i % 3] for i in range(n)],
        "m": [{"k1": float(rng.randn())} for _ in range(n)],
        "g": [(37.7, -122.4, 5.0)] * n,
        "tl": [["red", "blue"][: (i % 3)] for i in range(n)],
    }
    r = FeatureBuilder(ft.Real, "r").as_predictor()
    i = FeatureBuilder(ft.Integral, "i").as_predictor()
    b = FeatureBuilder(ft.Binary, "b").as_predictor()
    d = FeatureBuilder(ft.Date, "d").as_predictor()
    p = FeatureBuilder(ft.PickList, "p").as_predictor()
    m = FeatureBuilder(ft.RealMap, "m").as_predictor()
    g = FeatureBuilder(ft.Geolocation, "g").as_predictor()
    tl = FeatureBuilder(ft.TextList, "tl").as_predictor()

    outs = [
        r.vectorize(), i.vectorize(), b.vectorize(), d.vectorize(),
        p.vectorize(top_k=5, min_support=1), m.vectorize(min_support=1),
        g.vectorize(), tl.vectorize(hash_dims=8),
    ]
    scored = _train(outs, data)
    for out in outs:
        col = scored[out.name]
        assert isinstance(col, VectorColumn), out.name
        assert col.width > 0
        assert col.metadata.size == col.width


def test_numeric_enrichments_bucketize_scale_percentile(rng):
    n = 80
    x = rng.randn(n)
    y = (x + 0.3 * rng.randn(n) > 0).astype(float)
    data = {"x": x.tolist(), "y": y.tolist()}
    xf = FeatureBuilder(ft.Real, "x").as_predictor()
    yf = FeatureBuilder(ft.RealNN, "y").as_response()

    bucketed = xf.bucketize(splits=[-np.inf, 0.0, np.inf])
    auto = xf.auto_bucketize(yf, max_depth=2)
    scaled = xf.scale(slope=2.0, intercept=1.0)
    descaled = scaled.descale(scaled)
    pct = xf.to_percentile(buckets=10)
    iso = xf.to_isotonic_calibrated(yf)

    scored = _train([bucketed, auto, scaled, descaled, pct, iso], data)
    assert isinstance(scored[bucketed.name], VectorColumn)
    assert isinstance(scored[auto.name], VectorColumn)
    s = scored[scaled.name]
    assert np.allclose(s.values[s.mask], 2.0 * x[s.mask] + 1.0)
    ds = scored[descaled.name]
    assert np.allclose(ds.values[ds.mask], x[ds.mask], atol=1e-12)
    pv = scored[pct.name].values
    assert pv.min() >= 0.0 and pv.max() <= 100.0
    iv = scored[iso.name].values
    assert np.all(np.diff(iv[np.argsort(x)]) >= -1e-9)  # monotone in x


def test_text_enrichments_end_to_end(rng):
    n = 40
    data = {
        "t": ["Mr. John Smith went to Paris last spring"] * n,
        "e": ["alice@example.com" if i % 2 else None for i in range(n)],
        "u": ["https://docs.example.org/page"] * n,
        "ph": ["650-253-0000"] * n,
        "b64": [base64.b64encode(b"%PDF-1.7 more").decode()] * n,
        "other": ["Mr John Smyth visited Paris"] * n,
    }
    t = FeatureBuilder(ft.Text, "t").as_predictor()
    e = FeatureBuilder(ft.Email, "e").as_predictor()
    u = FeatureBuilder(ft.URL, "u").as_predictor()
    ph = FeatureBuilder(ft.Phone, "ph").as_predictor()
    b64 = FeatureBuilder(ft.Base64, "b64").as_predictor()
    other = FeatureBuilder(ft.Text, "other").as_predictor()

    outs = {
        "lang": t.detect_languages(),
        "ents": t.recognize_entities(),
        "len": t.text_len(),
        "sim": t.to_ngram_similarity(other),
        "edom": e.to_email_domain(),
        "epre": e.to_email_prefix(),
        "udom": u.to_domain(),
        "uproto": u.to_protocol(),
        "uvalid": u.is_valid_url(),
        "phv": ph.is_valid_phone("US"),
        "mime": b64.detect_mime_types(),
        "idx": t.indexed(),
        "toks": t.tokenize(remove_stopwords=True, language="en"),
    }
    scored = _train(list(outs.values()), data)
    lang_scores = scored[outs["lang"].name].values[0]
    # detect_languages returns the reference's RealMap of confidences
    assert max(lang_scores, key=lang_scores.get) == "en"
    assert abs(sum(lang_scores.values()) - 1.0) < 1e-6
    assert "smith" in scored[outs["ents"].name].values[0]
    assert scored[outs["len"].name].values[0] == len(data["t"][0])
    assert 0.0 < scored[outs["sim"].name].values[0] < 1.0
    assert scored[outs["edom"].name].values[1] == "example.com"
    assert scored[outs["epre"].name].values[1] == "alice"
    assert scored[outs["edom"].name].values[0] is None
    assert scored[outs["udom"].name].values[0] == "docs.example.org"
    assert scored[outs["uproto"].name].values[0] == "https"
    assert scored[outs["uvalid"].name].values[0] == 1.0
    assert scored[outs["phv"].name].values[0] == 1.0
    assert scored[outs["mime"].name].values[0] == "application/pdf"
    assert isinstance(scored[outs["idx"].name], NumericColumn)
    toks = scored[outs["toks"].name].values[0]
    assert "paris" in toks and "to" not in toks


def test_set_vector_map_enrichments(rng):
    n = 30
    data = {
        "s1": [frozenset(["a", "b"])] * n,
        "s2": [frozenset(["b", "c"])] * n,
        "a": rng.randn(n).tolist(),
        "bcol": rng.randn(n).tolist(),
        "m": [{"keep": 1.0, "drop": 2.0}] * n,
        "txt": ["hello world", None] * (n // 2),
    }
    s1 = FeatureBuilder(ft.MultiPickList, "s1").as_predictor()
    s2 = FeatureBuilder(ft.MultiPickList, "s2").as_predictor()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "bcol").as_predictor()
    m = FeatureBuilder(ft.RealMap, "m").as_predictor()
    txt = FeatureBuilder(ft.Text, "txt").as_predictor()

    jac = s1.jaccard_similarity(s2)
    combined = a.vectorize().combine(b.vectorize())
    dropped = combined.drop_indices_by(_is_null_ind)
    filtered = m.filter_map(block_keys=["drop"])
    occ = txt.to_occur()

    scored = _train([jac, combined, dropped, filtered, occ], data)
    assert scored[jac.name].values[0] == pytest.approx(1 / 3)
    cw = scored[combined.name].width
    assert scored[dropped.name].width < cw
    assert all(
        not c.is_null_indicator
        for c in scored[dropped.name].metadata.columns
    )
    assert list(scored[filtered.name].values[0]) == ["keep"]
    assert scored[occ.name].values[1] == 0.0
    assert scored[occ.name].values[0] == 1.0


def _is_null_ind(meta):
    return meta.is_null_indicator


def test_examples_are_dsl_only():
    """The example apps must read like the reference README: no direct
    ops-class imports (selector factories and DSL only)."""
    import os

    ex_dir = os.path.join(
        os.path.dirname(__file__), "..", "transmogrifai_tpu", "examples"
    )
    for fname in os.listdir(ex_dir):
        if not fname.endswith(".py") or fname == "__init__.py":
            continue
        src = open(os.path.join(ex_dir, fname)).read()
        assert "from ..ops." not in src.replace(
            "from ..ops.transmogrifier import transmogrify", ""
        ), f"{fname} imports ops classes directly"


def test_text_ml_sugar_tf_idf_lda_w2v(rng):
    """Round-4 DSL closure (reference RichTextFeature tf/idf/tfidf,
    countVec, lda, word + removeStopWords/tokenizeRegex)."""
    docs = [
        "the cat sat on the warm mat near the door",
        "dogs chase the cat around the garden every day",
        "stock markets fell sharply after the earnings report",
        "investors sold shares as the market dropped again",
    ] * 8
    data = {"t": docs, "y": [0.0, 0.0, 1.0, 1.0] * 8}
    t = FeatureBuilder(ft.Text, "t").as_predictor()
    toks = t.tokenize()
    tf_vec = toks.tf(num_features=64)
    tfidf_vec = toks.tfidf(num_features=64)
    counts = toks.count_vec(vocab_size=50, min_df=2.0)
    topics = counts.lda(k=2, max_iter=10)
    emb = toks.word2vec(vector_size=8, min_count=2)
    nostop = toks.remove_stop_words()
    rx = t.tokenize_regex(r"[^a-z]+")
    scored = _train(
        [tf_vec, tfidf_vec, counts, topics, emb, nostop, rx], data
    )
    tfv = scored[tf_vec.name].values
    tiv = scored[tfidf_vec.name].values
    assert tfv.shape == (32, 64) and (tfv.sum(axis=1) > 0).all()
    # idf rescales but never flips presence
    assert ((tfv != 0) >= (tiv != 0)).all()
    assert scored[topics.name].values.shape == (32, 2)
    assert scored[emb.name].values.shape == (32, 8)
    assert "the" not in set().union(*scored[nostop.name].values)
    assert scored[rx.name].values[0][0] == "the"


def test_functional_and_phone_sugar(rng):
    data = {
        "r": [1.0, -2.0, 3.0, None],
        "ph": ["650-253-0000", "not a phone", None, "+1 212 555 2368"],
    }
    r = FeatureBuilder(ft.Real, "r").as_predictor()
    ph = FeatureBuilder(ft.Phone, "ph").as_predictor()
    outs = {
        "pos": r.exists(lambda v: v > 0),
        "swap": r.replace_with(-2.0, 0.0),
        "kept": r.filter_values(lambda v: v > 0, default=0.0),
        "parsed": ph.parse_phone("US"),
    }
    scored = _train(list(outs.values()), data)
    assert list(scored[outs["pos"].name].to_list()) == [
        True, False, True, False]
    assert scored[outs["swap"].name].to_list()[1] == 0.0
    assert scored[outs["kept"].name].to_list()[:3] == [1.0, 0.0, 3.0]
    parsed = scored[outs["parsed"].name].to_list()
    assert parsed[0] == "+16502530000"
    assert parsed[1] is None and parsed[2] is None
    assert parsed[3] == "+12125552368"


def test_date_unit_circle_sugar(rng):
    import math

    hour_ms = 3600 * 1000
    data = {"d": [0, 6 * hour_ms, 12 * hour_ms, 18 * hour_ms]}
    d = FeatureBuilder(ft.Date, "d").as_predictor()
    circ = d.to_unit_circle("HourOfDay")
    scored = _train([circ], data)
    vals = scored[circ.name].values
    assert vals.shape[1] == 2
    # midnight -> angle 0 -> (sin, cos) in some order with unit norm
    norms = np.sqrt((vals**2).sum(axis=1))
    np.testing.assert_allclose(norms, 1.0, atol=1e-9)
    # noon is diametrically opposite midnight
    np.testing.assert_allclose(vals[2], -vals[0], atol=1e-9)


def test_to_date_list_and_to_multi_pick_list(rng):
    """Scalar Text -> 0/1-element set (the reference receiver shape,
    RichTextFeature.toMultiPickList:58 - NOT char-split); TextList ->
    distinct tokens; Date -> single-element DateList with epoch 0
    surviving (no falsy-zero trap)."""
    data = {
        "d": [0, 3600000, None],
        "t": ["red", "blue", None],
        "toks": ["a b a", "c", None],
    }
    d = FeatureBuilder(ft.Date, "d").as_predictor()
    t = FeatureBuilder(ft.Text, "t").as_predictor()
    toks = FeatureBuilder(ft.Text, "toks").as_predictor().tokenize()
    dl = d.to_date_list()
    scalar_set = t.to_multi_pick_list()
    token_set = toks.to_multi_pick_list()
    scored = _train([dl, scalar_set, token_set], data)
    assert scored[dl.name].to_list() == [[0.0], [3600000.0], []]
    assert list(scored[scalar_set.name].values) == [
        frozenset({"red"}), frozenset({"blue"}), frozenset()]
    assert list(scored[token_set.name].values) == [
        frozenset({"a", "b"}), frozenset({"c"}), frozenset()]


def test_prediction_descale_dispatch(rng):
    """prediction.descale(scaled_label) must route to PredictionDescaler
    (round 5 - the Real-only DescalerTransformer made the natural
    regression-on-scaled-label spelling a TypeError), and recover the
    raw-scale target."""
    import numpy as np

    from transmogrifai_tpu.models.linear_regression import (
        OpLinearRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify

    n = 100
    a_vals = rng.rand(n) * 10 + 1
    data = {"y": (a_vals * 3).tolist(), "a": a_vals.tolist()}
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    # NON-identity scaling: with the default slope=1/intercept=0 the
    # inverse is a no-op and the test could not catch a broken descale
    scaled = y.scale(slope=2.5, intercept=7.0)
    vec = transmogrify([a])
    pred = (
        OpLinearRegression(reg_param=0.001)
        .set_input(scaled, vec).get_output()
    )
    de = pred.descale(scaled)
    model = (
        OpWorkflow().set_result_features(de)
        .set_input_dataset(data).train()
    )
    dv = np.asarray(model.score(data)[de.name].values, dtype=float)
    target = a_vals * 3
    r2 = 1 - ((dv - target) ** 2).sum() / (
        (target - target.mean()) ** 2
    ).sum()
    assert r2 > 0.999


def test_feature_math_nonfinite_results_null_without_warnings(rng):
    """x/0 and overflow results become nulls with NO RuntimeWarning —
    the errstate must cover divide, invalid, AND over."""
    import warnings

    data = {"a": [1e200, 3.0, 5.0], "b": [1e200, 2.0, 0.0]}
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    prod = a * b   # 1e200 * 1e200 overflows
    ratio = a / b  # 5/0 divides by zero
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        model = (
            OpWorkflow().set_result_features(prod, ratio)
            .set_input_dataset(data).train()
        )
        scored = model.score(data)
    assert scored[prod.name].to_list() == [None, 6.0, 0.0]
    assert scored[ratio.name].to_list()[2] is None


def test_feature_division_null_divisor_propagates(rng):
    """a / b with a null b row yields a null output row, not 0 or inf."""
    n = 20
    data = {"a": (rng.rand(n) + 1).tolist(), "b": (rng.rand(n) + 1).tolist()}
    data["b"][3] = None
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    ratio = a / b
    model = (
        OpWorkflow().set_result_features(ratio)
        .set_input_dataset(data).train()
    )
    out = model.score(data)[ratio.name].to_list()
    assert out[3] is None
    assert abs(out[0] - data["a"][0] / data["b"][0]) < 1e-12
