"""Fused training programs (ISSUE 15): parity, donation safety, the AOT
executable cache, and the tier-1 CPU floor.

The contract under test (local/fused_train.py): each family's fold x
grid dispatch runs as a donate-buffers fit program + device scoring +
the exact metric program, and under the 'parity' runtime the selection
it produces is indistinguishable from the kernel-at-a-time dispatch -
same winner, metrics within 1e-9 (in practice: bit-level), betas
bit-equal - in EVERY configuration (one runtime): a warm refit
rehydrating executables from the ``train_xla_cache/`` compile cache
returns bit-identical metrics to a cold one.
"""
import json
import os
import time

import numpy as np
import pytest

from transmogrifai_tpu.evaluators.binary import (
    OpBinaryClassificationEvaluator,
)
from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
from transmogrifai_tpu.examples.synthetic import synthetic_design_matrix
from transmogrifai_tpu.local import fused_train
from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.linear_svc import OpLinearSVC
from transmogrifai_tpu.models.logistic_regression import (
    OpLogisticRegression,
)
from transmogrifai_tpu.models.trees import (
    OpGBTClassifier,
    OpRandomForestClassifier,
    OpRandomForestRegressor,
)
from transmogrifai_tpu.selector.factories import lr_grid
from transmogrifai_tpu.selector.validator import OpCrossValidation


@pytest.fixture(autouse=True)
def _single_process_mesh(monkeypatch):
    """Fused dispatches engage only without a CV mesh (the PR-3 guarded
    mesh route owns multi-device degradation); tier-1 forces 8 virtual
    CPU devices, so pin the product mesh off for these drills."""
    monkeypatch.setenv("TX_PRODUCT_MESH", "0")


def _binary_data(n=12_000, seed=0):
    X, y, _ = synthetic_design_matrix(n, text_dims=32, seed=seed)
    return np.asarray(X, np.float64), np.asarray(y)


def _regression_target(X, seed=0):
    rng = np.random.RandomState(seed)
    return X[:, 3] * 2.0 - X[:, 7] + 0.1 * rng.randn(X.shape[0])


def _validate(est, grid, X, y, ev, fused, stratify=True, cache_dir=None):
    cv = OpCrossValidation(num_folds=3, evaluator=ev, stratify=stratify)
    cv.train_fused = fused
    cv.train_cache_dir = cache_dir
    return cv.validate([(est, grid)], X, y)


def _metric_diffs(r0, r1):
    pairs = {
        json.dumps(r["params"], sort_keys=True): r["metric"]
        for r in r0.all_results
    }
    return [
        abs(pairs[json.dumps(r["params"], sort_keys=True)] - r["metric"])
        for r in r1.all_results
    ]


# ---------------------------------------------------------------------------
# Per-family parity: fused == existing dispatch
# ---------------------------------------------------------------------------
def test_lr_fused_parity_and_beta_bit_equality():
    X, y = _binary_data()
    est = OpLogisticRegression(max_iter=12)
    ev = OpBinaryClassificationEvaluator()
    grid = lr_grid()
    r0 = _validate(est, grid, X, y, ev, fused=False)
    r1 = _validate(est, grid, X, y, ev, fused=True)
    assert r1.train_fused["families"][est.model_type]["backend"] == "fused"
    assert r0.best_params == r1.best_params
    assert max(_metric_diffs(r0, r1)) <= 1e-9
    # betas: the fixed-point fit must be BIT-identical to the scan fit
    import jax.numpy as jnp

    from transmogrifai_tpu.selector.validator import (
        lr_grid_scalars,
        stratified_kfold_masks,
    )

    masks = stratified_kfold_masks(y, 3, 42, True)
    regs_g, ens_g = lr_grid_scalars(est, grid)
    regs, ens = np.tile(regs_g, 3), np.tile(ens_g, 3)
    Xd = jnp.asarray(X, jnp.float32)
    res = fused_train.run_linear(
        est, Xd, y, masks, np.ones(len(y)), False, regs, ens,
        len(grid), ev, "exact")
    W = jnp.repeat(jnp.asarray(masks).astype(jnp.float32), len(grid),
                   axis=0)
    betas_e, b0s_e = est.fit_arrays_batched(
        Xd, jnp.asarray(y, jnp.float32), W, regs, ens)
    assert np.array_equal(np.asarray(betas_e), res.betas)
    assert np.array_equal(np.asarray(b0s_e), res.b0s)


def test_svc_fused_parity():
    X, y = _binary_data()
    est = OpLinearSVC(max_iter=8)
    ev = OpBinaryClassificationEvaluator()
    grid = [{"reg_param": r} for r in (0.01, 0.1, 0.5)]
    r0 = _validate(est, grid, X, y, ev, fused=False)
    r1 = _validate(est, grid, X, y, ev, fused=True)
    assert r1.train_fused["families"][est.model_type]["backend"] == "fused"
    assert r0.best_params == r1.best_params
    assert max(_metric_diffs(r0, r1)) <= 1e-9


def test_linreg_fused_parity():
    X, y = _binary_data()
    yr = _regression_target(X)
    est = OpLinearRegression()
    ev = OpRegressionEvaluator()
    r0 = _validate(est, lr_grid(), X, yr, ev, fused=False, stratify=False)
    r1 = _validate(est, lr_grid(), X, yr, ev, fused=True, stratify=False)
    assert r1.train_fused["families"][est.model_type]["backend"] == "fused"
    assert r0.best_params == r1.best_params
    assert max(_metric_diffs(r0, r1)) <= 1e-9


@pytest.mark.parametrize("family", ["rf", "gbt", "rf_reg"])
def test_tree_fused_parity(monkeypatch, family):
    monkeypatch.setenv("TX_TREE_BACKEND", "jax")
    X, y = _binary_data(3_000)
    if family == "rf":
        est = OpRandomForestClassifier(num_trees=8, max_depth=4)
        ev, yy, strat = OpBinaryClassificationEvaluator(), y, True
        grid = [{"max_depth": 4, "min_info_gain": m} for m in (0.0, 0.01)]
    elif family == "gbt":
        est = OpGBTClassifier(num_trees=6, max_depth=3)
        ev, yy, strat = OpBinaryClassificationEvaluator(), y, True
        grid = [{"step_size": s} for s in (0.1, 0.3)]
    else:
        est = OpRandomForestRegressor(num_trees=8, max_depth=4)
        ev, yy, strat = OpRegressionEvaluator(), _regression_target(X), \
            False
        grid = [{"max_depth": 4, "min_info_gain": m} for m in (0.0, 0.01)]
    r0 = _validate(est, grid, X, yy, ev, fused=False, stratify=strat)
    r1 = _validate(est, grid, X, yy, ev, fused=True, stratify=strat)
    assert r1.train_fused["families"][est.model_type]["backend"] == "fused"
    assert r0.best_params == r1.best_params
    assert max(_metric_diffs(r0, r1)) <= 1e-9


def test_approx_mode_fused_parity(monkeypatch):
    """The 1024-bin device-metric arm (TPU's mode, forced on CPU via the
    TX_CV_RANK_METRICS knob): the fused path reuses the exact kernels of
    the existing approx arm on bit-identical betas, so the metrics are
    bit-equal, not merely close."""
    monkeypatch.setenv("TX_CV_RANK_METRICS", "approx")
    X, y = _binary_data()
    est = OpLogisticRegression(max_iter=12)
    ev = OpBinaryClassificationEvaluator()
    r0 = _validate(est, lr_grid(), X, y, ev, fused=False)
    r1 = _validate(est, lr_grid(), X, y, ev, fused=True)
    fam = r1.train_fused["families"][est.model_type]
    assert fam["backend"] == "fused" and fam["mode"] == "approx"
    assert r0.best_params == r1.best_params
    assert max(_metric_diffs(r0, r1)) == 0.0


# ---------------------------------------------------------------------------
# Exact device rank metrics == host evaluator
# ---------------------------------------------------------------------------
def test_exact_rank_metrics_match_host_evaluator():
    import jax
    import jax.numpy as jnp

    from transmogrifai_tpu.evaluators.binary import _roc_pr_areas

    rng = np.random.RandomState(7)
    n = 5000
    y = (rng.rand(n) > 0.6).astype(np.float64)
    # scores with heavy EXACT ties (saturated sigmoid analog) plus a
    # continuous region - the tie-grouping is the part worth pinning
    scores = np.where(rng.rand(n) < 0.2, 1.0,
                      rng.rand(n)).astype(np.float32)
    ok = rng.rand(n) > 0.1
    with jax.experimental.enable_x64():
        auroc, aupr = fused_train.exact_rank_metrics(
            jnp.asarray(scores[None, :]),
            jnp.asarray(y[None, :]),
            jnp.asarray(ok[None, :]),
        )
        auroc, aupr = float(auroc[0]), float(aupr[0])
    a_h, p_h = _roc_pr_areas(y[ok], scores[ok])
    assert abs(auroc - a_h) <= 1e-12
    assert abs(aupr - p_h) <= 1e-12


# ---------------------------------------------------------------------------
# Donation safety
# ---------------------------------------------------------------------------
def test_donation_safety_shared_buffers_survive_dispatch():
    """The fit program donates the per-call fold-weight block; the
    SHARED buffers (the hoisted design matrix) must never be donated -
    they are read again by the scoring stage, by later families, and by
    the caller.  Two dispatches over the same device X must succeed and
    agree exactly, and X must remain readable afterwards."""
    import jax.numpy as jnp

    X, y = _binary_data(6_000)
    est = OpLogisticRegression(max_iter=8)
    ev = OpBinaryClassificationEvaluator()
    from transmogrifai_tpu.selector.validator import (
        lr_grid_scalars,
        stratified_kfold_masks,
    )

    grid = lr_grid()
    masks = stratified_kfold_masks(y, 3, 42, True)
    regs_g, ens_g = lr_grid_scalars(est, grid)
    regs, ens = np.tile(regs_g, 3), np.tile(ens_g, 3)
    Xd = jnp.asarray(X, jnp.float32)
    r1 = fused_train.run_linear(
        est, Xd, y, masks, np.ones(len(y)), False, regs, ens,
        len(grid), ev, "exact")
    # the shared buffer is intact and reusable
    assert np.isfinite(np.asarray(Xd)).all()
    r2 = fused_train.run_linear(
        est, Xd, y, masks, np.ones(len(y)), False, regs, ens,
        len(grid), ev, "exact")
    assert np.array_equal(r1.metrics, r2.metrics)
    assert np.array_equal(r1.betas, r2.betas)


# ---------------------------------------------------------------------------
# AOT executable cache
# ---------------------------------------------------------------------------
_WARM_CHILD = r"""
import json, sys
import numpy as np
from transmogrifai_tpu.evaluators.binary import (
    OpBinaryClassificationEvaluator,
)
from transmogrifai_tpu.examples.synthetic import synthetic_design_matrix
from transmogrifai_tpu.models.logistic_regression import (
    OpLogisticRegression,
)
from transmogrifai_tpu.selector.factories import lr_grid
from transmogrifai_tpu.selector.validator import OpCrossValidation

X, y, _ = synthetic_design_matrix(8000, text_dims=32, seed=0)
X = np.asarray(X, np.float64)
cv = OpCrossValidation(
    num_folds=3, evaluator=OpBinaryClassificationEvaluator(),
    stratify=True)
cv.train_fused = True
cv.train_cache_dir = sys.argv[1]
r = cv.validate([(OpLogisticRegression(max_iter=8), lr_grid())], X,
                np.asarray(y))
print(json.dumps({
    "fam": r.train_fused["families"]["OpLogisticRegression"],
    "metrics": [x["metric"] for x in r.all_results],
    "best": r.best_params,
}))
"""


def test_aot_cache_warm_refit_loads_instead_of_retracing(tmp_path):
    """The warm-refit acceptance flow is the PR-12 pinned
    trainer-process -> cache -> fresh-process shape: a brand-new
    process (replica restart, rung worker) deserializes the cached
    executable instead of retracing.  (A SAME-process reload can hit
    jaxlib's process-uniquified entry-symbol collision - a counted
    retrace, never wrong results - so the deterministic cross-process
    flow is what gets pinned.)"""
    import subprocess
    import sys

    X, y = _binary_data(8_000)
    est = OpLogisticRegression(max_iter=8)
    ev = OpBinaryClassificationEvaluator()
    cache = str(tmp_path / "train_xla_cache")
    fused_train.reset_program_registry()
    r_cold = _validate(est, lr_grid(), X, y, ev, fused=True,
                       cache_dir=cache)
    fam_c = r_cold.train_fused["families"][est.model_type]
    assert fam_c["cache"] == "miss"
    assert fam_c["compile_ms"] > 0
    assert os.listdir(cache), "no executables cached"
    env = dict(os.environ, JAX_PLATFORMS="cpu", TX_PRODUCT_MESH="0")
    out = subprocess.run(
        [sys.executable, "-c", _WARM_CHILD, cache],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    child = json.loads(out.stdout.strip().splitlines()[-1])
    fam_w = child["fam"]
    assert fam_w["cache"] == "hit"
    assert fam_w["load_ms"] > 0 and fam_w["compile_ms"] == 0
    assert fam_w["load_ms"] < (fam_c["trace_ms"] + fam_c["compile_ms"])
    # warm metrics are bit-identical to cold (same executable bytes)
    assert child["metrics"] == [x["metric"] for x in r_cold.all_results]
    assert child["best"] == r_cold.best_params


def test_fingerprint_mismatch_is_counted_retrace_and_recache(
        tmp_path, monkeypatch):
    X, y = _binary_data(6_000)
    est = OpLogisticRegression(max_iter=8)
    ev = OpBinaryClassificationEvaluator()
    cache = str(tmp_path / "train_xla_cache")
    fused_train.reset_program_registry()
    _validate(est, lr_grid(), X, y, ev, fused=True, cache_dir=cache)
    n_before = len([n for n in os.listdir(cache)
                    if n.endswith(".txmeta.json")])
    # a new jaxlib/backend: every fingerprint changes, the logical key
    # does not - the reload must be a counted STALE retrace-and-recache,
    # never a foreign executable
    real = fused_train.runtime_fingerprint

    def fake_runtime():
        rt = dict(real())
        rt["jaxlib"] = "0.0.0-upgraded"
        return rt

    monkeypatch.setattr(fused_train, "runtime_fingerprint", fake_runtime)
    fused_train.reset_program_registry()
    r = _validate(est, lr_grid(), X, y, ev, fused=True, cache_dir=cache)
    fam = r.train_fused["families"][est.model_type]
    assert fam["cache"] == "stale"
    assert fam["compile_ms"] > 0  # really retraced
    assert r.train_fused["cache"]["stale"] >= 1
    # recached under the new fingerprint, superseded records reaped
    n_after = len([n for n in os.listdir(cache)
                   if n.endswith(".txmeta.json")])
    assert n_after == n_before


def test_corrupt_cache_entry_degrades_to_retrace(tmp_path):
    X, y = _binary_data(6_000)
    est = OpLogisticRegression(max_iter=8)
    ev = OpBinaryClassificationEvaluator()
    cache = str(tmp_path / "train_xla_cache")
    fused_train.reset_program_registry()
    r0 = _validate(est, lr_grid(), X, y, ev, fused=True, cache_dir=cache)
    for name in os.listdir(cache):
        if name.endswith(".txmeta.json"):
            continue
        p = os.path.join(cache, name)
        try:
            with open(p, "r+b") as f:
                f.seek(10)
                f.write(b"\xde\xad\xbe\xef")
        except OSError:
            continue
    fused_train.reset_program_registry()
    r1 = _validate(est, lr_grid(), X, y, ev, fused=True, cache_dir=cache)
    fam = r1.train_fused["families"][est.model_type]
    # the contract: corruption degrades to a working fresh compile (jax
    # warns on the unreadable entry and recompiles), never an error or
    # a wrong executable - selection identical to the cold run
    assert fam["backend"] == "fused"
    assert r0.best_params == r1.best_params
    assert all(
        a["metric"] == b["metric"]
        for a, b in zip(r0.all_results, r1.all_results)
    )


# ---------------------------------------------------------------------------
# Fallback reasons + trail shape
# ---------------------------------------------------------------------------
def test_unsupported_evaluator_falls_back_with_reason():
    from transmogrifai_tpu.evaluators.binary import OpBinScoreEvaluator

    X, y = _binary_data(4_000)
    est = OpLogisticRegression(max_iter=6)
    r = _validate(est, lr_grid()[:2], X, y, OpBinScoreEvaluator(),
                  fused=True)
    fam = r.train_fused["families"][est.model_type]
    assert fam["backend"] == "existing"
    assert fam["reason"] == "evaluator_unsupported"


def test_auto_gate_keeps_small_fits_on_existing_path():
    X, y = _binary_data(4_000)
    est = OpLogisticRegression(max_iter=6)
    ev = OpBinaryClassificationEvaluator()
    r = _validate(est, lr_grid()[:2], X, y, ev, fused=None)  # auto
    fam = r.train_fused["families"][est.model_type]
    assert fam["backend"] == "existing"
    assert fam["reason"] == "below_min_rows"


def test_trail_shape_mirrors_serving_telemetry():
    X, y = _binary_data(6_000)
    est = OpLogisticRegression(max_iter=6)
    ev = OpBinaryClassificationEvaluator()
    r = _validate(est, lr_grid()[:2], X, y, ev, fused=True)
    tf = r.train_fused
    assert tf["backend"] == "fused"
    assert set(tf["cache"]) == {"hits", "misses", "stale"}
    fam = tf["families"][est.model_type]
    for key in ("backend", "cache", "trace_ms", "compile_ms", "load_ms",
                "bucket", "mode"):
        assert key in fam, key


# ---------------------------------------------------------------------------
# Runner + report wiring (ISSUE 15 satellite)
# ---------------------------------------------------------------------------
def test_runner_train_fused_summary_cache_and_report(tmp_path):
    """The ``train_fused`` run knob end to end: the run summary and the
    saved summary.json carry the per-family dispatch trail
    (backend/cache mirroring the PR-12 serving telemetry shape), the
    AOT cache lands in ``train_xla_cache/`` NEXT TO the model, and
    ``tx autotune report`` renders the trail."""
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.autotune import report_from_path
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression as LR,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector.factories import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    rng = np.random.RandomState(0)
    n = 600
    a_v, b_v = rng.randn(n), rng.randn(n)
    data = {
        "y": ((a_v - b_v + 0.3 * rng.randn(n)) > 0)
        .astype(float).tolist(),
        "a": a_v.tolist(),
        "b": b_v.tolist(),
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([a, b])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        models_and_parameters=[
            (LR(max_iter=6),
             [{"reg_param": r, "elastic_net_param": 0.1}
              for r in (0.01, 0.1)]),
        ],
        splitter=None,
    )
    pred = selector.set_input(y, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    loc = str(tmp_path / "model")
    fused_train.reset_program_registry()
    r = OpWorkflowRunner(wf).run(
        "train",
        OpParams(model_location=loc,
                 custom_params={"train_fused": True}),
    )
    tf = r.summary["train_fused"]
    assert tf["backend"] == "fused"
    fam = tf["families"]["OpLogisticRegression"]
    # runner default: cache dir next to the model
    assert fam["cache"] == "miss"
    cache_dir = os.path.join(loc, "train_xla_cache")
    assert os.path.isdir(cache_dir) and os.listdir(cache_dir)
    with open(os.path.join(loc, "summary.json")) as f:
        assert json.load(f)["train_fused"]["backend"] == "fused"
    report = report_from_path(loc)
    assert report["train_fused"]["backend"] == "fused"
    assert (report["selection"][0]["train_fused"]["families"]
            ["OpLogisticRegression"]["bucket"])


# ---------------------------------------------------------------------------
# Tier-1 CPU floor
# ---------------------------------------------------------------------------
def test_fused_fold_grid_cpu_floor():
    """Fused fold x grid dispatch must not cost more CPU than the
    kernel-at-a-time path at a size where both are warm - proven
    COMPILED-FIRST (the first fused call pays trace+compile and is
    excluded), then best-of-2 process_time windows."""
    X, y = _binary_data(60_000, seed=3)
    est = OpLogisticRegression(max_iter=10)
    ev = OpBinaryClassificationEvaluator()
    grid = lr_grid()
    # warm both paths (compile + trace)
    r_f = _validate(est, grid, X, y, ev, fused=True)
    assert (r_f.train_fused["families"][est.model_type]["backend"]
            == "fused"), "floor would be vacuous: fused did not engage"
    _validate(est, grid, X, y, ev, fused=False)

    def cpu_of(fused):
        best = float("inf")
        for _ in range(3):
            t0 = time.process_time()
            _validate(est, grid, X, y, ev, fused=fused)
            best = min(best, time.process_time() - t0)
        return best

    c_fused = cpu_of(True)
    c_exist = cpu_of(False)
    # best-of-3 + small tolerance for scheduler noise (idle margin is
    # ~0.88x CPU / ~0.64x wall; process_time counts ALL XLA worker
    # threads, so shared-host contention can inflate the parallel
    # fused metric stage more than the host-side existing path)
    assert c_fused <= c_exist * 1.10, (c_fused, c_exist)
