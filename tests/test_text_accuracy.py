"""Measured accuracy for the text-analysis stack.

The reference leans on Optimaize/Tika/libphonenumber; the self-contained
equivalents must prove themselves on fixtures: >=90% on a ~100-sample
multilingual language-identification set (sentences DISTINCT from the
profile seed corpora), exact MIME magics, and per-region phone rules.
"""
import base64
import struct

import numpy as np
import pytest

from transmogrifai_tpu.ops.text_analysis import (
    detect_language,
    detect_mime_type,
    is_valid_phone,
)

# -- language identification fixtures ---------------------------------------
# held-out sentences; none appear in ops/lang_data.py CORPORA
LANG_SAMPLES = [
    ("en", "My brother bought a new car last month and he drives it to work every day."),
    ("en", "Please remember to close the windows before you leave the office tonight."),
    ("en", "The restaurant on the corner serves the best coffee in the whole neighborhood."),
    ("en", "After the meeting we decided to change the plan completely."),
    ("fr", "Mon frère a acheté une nouvelle voiture le mois dernier et il la conduit tous les jours."),
    ("fr", "N'oubliez pas de fermer les fenêtres avant de quitter le bureau ce soir."),
    ("fr", "Le restaurant au coin de la rue sert le meilleur café du quartier."),
    ("fr", "Après la réunion, nous avons décidé de changer complètement le plan."),
    ("es", "Mi hermano compró un coche nuevo el mes pasado y lo conduce al trabajo todos los días."),
    ("es", "Por favor, recuerda cerrar las ventanas antes de salir de la oficina esta noche."),
    ("es", "El restaurante de la esquina sirve el mejor café de todo el barrio."),
    ("es", "Después de la reunión decidimos cambiar el plan por completo."),
    ("de", "Mein Bruder hat letzten Monat ein neues Auto gekauft und fährt damit jeden Tag zur Arbeit."),
    ("de", "Bitte denken Sie daran, die Fenster zu schließen, bevor Sie heute Abend das Büro verlassen."),
    ("de", "Das Restaurant an der Ecke serviert den besten Kaffee im ganzen Viertel."),
    ("de", "Nach der Besprechung haben wir beschlossen, den Plan komplett zu ändern."),
    ("it", "Mio fratello ha comprato una macchina nuova il mese scorso e la guida ogni giorno per andare al lavoro."),
    ("it", "Per favore, ricordati di chiudere le finestre prima di lasciare l'ufficio stasera."),
    ("it", "Il ristorante all'angolo serve il miglior caffè di tutto il quartiere."),
    ("it", "Dopo la riunione abbiamo deciso di cambiare completamente il piano."),
    ("pt", "O meu irmão comprou um carro novo no mês passado e conduz todos os dias para o trabalho."),
    ("pt", "Por favor, lembre-se de fechar as janelas antes de sair do escritório esta noite."),
    ("pt", "O restaurante da esquina serve o melhor café de todo o bairro."),
    ("pt", "Depois da reunião decidimos mudar o plano completamente."),
    ("nl", "Mijn broer heeft vorige maand een nieuwe auto gekocht en rijdt er elke dag mee naar zijn werk."),
    ("nl", "Vergeet niet de ramen te sluiten voordat je vanavond het kantoor verlaat."),
    ("nl", "Het restaurant op de hoek serveert de beste koffie van de hele buurt."),
    ("nl", "Na de vergadering hebben we besloten het plan helemaal te veranderen."),
    ("sv", "Min bror köpte en ny bil förra månaden och han kör den till jobbet varje dag."),
    ("sv", "Kom ihåg att stänga fönstren innan du lämnar kontoret i kväll."),
    ("sv", "Restaurangen på hörnet serverar det bästa kaffet i hela kvarteret."),
    ("sv", "Efter mötet bestämde vi oss för att ändra planen helt och hållet."),
    ("da", "Min bror købte en ny bil i sidste måned, og han kører i den på arbejde hver dag."),
    ("da", "Husk at lukke vinduerne, før du forlader kontoret i aften."),
    ("da", "Restauranten på hjørnet serverer den bedste kaffe i hele kvarteret."),
    ("da", "Efter mødet besluttede vi at ændre planen fuldstændigt."),
    ("pl", "Mój brat kupił nowy samochód w zeszłym miesiącu i jeździ nim codziennie do pracy."),
    ("pl", "Proszę pamiętać o zamknięciu okien przed wyjściem z biura dziś wieczorem."),
    ("pl", "Restauracja na rogu serwuje najlepszą kawę w całej okolicy."),
    ("pl", "Po spotkaniu postanowiliśmy całkowicie zmienić plan."),
    ("cs", "Můj bratr si minulý měsíc koupil nové auto a každý den jím jezdí do práce."),
    ("cs", "Nezapomeňte prosím zavřít okna, než dnes večer odejdete z kanceláře."),
    ("cs", "Restaurace na rohu podává nejlepší kávu v celé čtvrti."),
    ("cs", "Po schůzce jsme se rozhodli plán úplně změnit."),
    ("ro", "Fratele meu a cumpărat o mașină nouă luna trecută și o conduce în fiecare zi la serviciu."),
    ("ro", "Vă rugăm să nu uitați să închideți ferestrele înainte de a pleca din birou diseară."),
    ("ro", "Restaurantul din colț servește cea mai bună cafea din tot cartierul."),
    ("ro", "După ședință am hotărât să schimbăm planul complet."),
    ("tr", "Kardeşim geçen ay yeni bir araba aldı ve her gün işe onunla gidiyor."),
    ("tr", "Lütfen bu akşam ofisten çıkmadan önce pencereleri kapatmayı unutmayın."),
    ("tr", "Köşedeki restoran bütün mahalledeki en iyi kahveyi servis ediyor."),
    ("tr", "Toplantıdan sonra planı tamamen değiştirmeye karar verdik."),
    ("fi", "Veljeni osti uuden auton viime kuussa ja ajaa sillä töihin joka päivä."),
    ("fi", "Muista sulkea ikkunat ennen kuin lähdet toimistolta tänä iltana."),
    ("fi", "Kulman ravintola tarjoilee koko kaupunginosan parasta kahvia."),
    ("fi", "Kokouksen jälkeen päätimme muuttaa suunnitelmaa kokonaan."),
    ("id", "Kakak saya membeli mobil baru bulan lalu dan mengendarainya ke kantor setiap hari."),
    ("id", "Tolong ingat untuk menutup jendela sebelum meninggalkan kantor malam ini."),
    ("id", "Restoran di sudut jalan menyajikan kopi terbaik di seluruh lingkungan."),
    ("id", "Setelah rapat kami memutuskan untuk mengubah rencana sepenuhnya."),
    ("hu", "A bátyám múlt hónapban vett egy új autót, és minden nap azzal jár dolgozni."),
    ("hu", "Kérlek, ne felejtsd el becsukni az ablakokat, mielőtt ma este elhagyod az irodát."),
    ("hu", "A sarki étterem a legjobb kávét szolgálja fel az egész környéken."),
    ("hu", "A megbeszélés után úgy döntöttünk, hogy teljesen megváltoztatjuk a tervet."),
    ("ru", "Мой брат купил новую машину в прошлом месяце и каждый день ездит на ней на работу."),
    ("ru", "Пожалуйста, не забудьте закрыть окна, прежде чем уйти из офиса сегодня вечером."),
    ("ru", "Ресторан на углу подаёт лучший кофе во всём районе."),
    ("ru", "После совещания мы решили полностью изменить план."),
    ("uk", "Мій брат купив нову машину минулого місяця і щодня їздить нею на роботу."),
    ("uk", "Будь ласка, не забудьте зачинити вікна, перш ніж піти з офісу сьогодні ввечері."),
    ("uk", "Ресторан на розі подає найкращу каву в усьому районі."),
    ("uk", "Після наради ми вирішили повністю змінити план."),
    ("bg", "Брат ми купи нова кола миналия месец и всеки ден кара с нея на работа."),
    ("bg", "Моля, не забравяйте да затворите прозорците, преди да напуснете офиса тази вечер."),
    ("bg", "Ресторантът на ъгъла сервира най-хубавото кафе в целия квартал."),
    ("bg", "След срещата решихме да променим плана изцяло."),
    # script-decided languages
    ("el", "Ο αδελφός μου αγόρασε καινούργιο αυτοκίνητο τον περασμένο μήνα."),
    ("el", "Το εστιατόριο στη γωνία σερβίρει τον καλύτερο καφέ της γειτονιάς."),
    ("he", "אחי קנה מכונית חדשה בחודש שעבר והוא נוסע בה לעבודה כל יום."),
    ("he", "המסעדה בפינה מגישה את הקפה הטוב ביותר בשכונה."),
    ("ar", "اشترى أخي سيارة جديدة الشهر الماضي ويقودها إلى العمل كل يوم."),
    ("ar", "يقدم المطعم في الزاوية أفضل قهوة في الحي كله."),
    ("hi", "मेरे भाई ने पिछले महीने एक नई कार खरीदी और वह हर दिन उसे काम पर चलाता है।"),
    ("hi", "कोने का रेस्तरां पूरे मोहल्ले की सबसे अच्छी कॉफी परोसता है।"),
    ("th", "พี่ชายของฉันซื้อรถใหม่เมื่อเดือนที่แล้วและขับไปทำงานทุกวัน"),
    ("th", "ร้านอาหารตรงหัวมุมเสิร์ฟกาแฟที่ดีที่สุดในละแวกนี้"),
    ("ja", "兄は先月新しい車を買って、毎日それで仕事に行きます。"),
    ("ja", "角のレストランはこの辺りで一番おいしいコーヒーを出します。"),
    ("zh-cn", "我哥哥上个月买了一辆新车，每天开车去上班。"),
    ("zh-cn", "拐角处的餐厅供应整个街区最好的咖啡。"),
    ("ko", "우리 형은 지난달에 새 차를 샀고 매일 그 차로 출근합니다."),
    ("ko", "모퉁이에 있는 식당은 동네에서 가장 맛있는 커피를 제공합니다."),
    ("ka", "ჩემმა ძმამ გასულ თვეში ახალი მანქანა იყიდა და ყოველდღე სამსახურში დადის."),
    ("ka", "კუთხის რესტორანი მთელ უბანში საუკეთესო ყავას აწვდის."),
    ("bn", "আমার ভাই গত মাসে একটি নতুন গাড়ি কিনেছে এবং প্রতিদিন সেটি চালিয়ে কাজে যায়।"),
    ("bn", "কোণার রেস্তোরাঁটি পুরো পাড়ার সেরা কফি পরিবেশন করে।"),
    ("en", "She has been studying medicine at the university for almost six years now."),
    ("fr", "Nous avons passé nos vacances au bord de la mer avec toute la famille."),
    ("de", "Im Winter fahren wir oft in die Berge, um Ski zu fahren und zu wandern."),
    ("es", "Los estudiantes presentaron sus proyectos delante de toda la clase ayer."),
    # round-4 expansion languages (held-out; none appear in the corpora)
    ("no", "Min bror kjøpte en ny bil forrige måned, og han kjører den til jobben hver dag."),
    ("no", "Restauranten på hjørnet serverer den beste kaffen i hele nabolaget."),
    ("is", "Bróðir minn keypti nýjan bíl í síðasta mánuði og hann keyrir hann í vinnuna á hverjum degi."),
    ("is", "Veitingastaðurinn á horninu býður upp á besta kaffið í öllu hverfinu."),
    ("sk", "Môj brat si minulý mesiac kúpil nové auto a každý deň ním jazdí do práce."),
    ("sk", "Reštaurácia na rohu podáva najlepšiu kávu v celej štvrti."),
    ("hr", "Moj brat je prošli mjesec kupio novi auto i svaki dan se njime vozi na posao."),
    ("hr", "Restoran na uglu poslužuje najbolju kavu u cijelom kvartu."),
    ("sl", "Moj brat je prejšnji mesec kupil nov avto in se z njim vsak dan vozi v službo."),
    ("sl", "Restavracija na vogalu streže najboljšo kavo v vsej soseski."),
    ("sq", "Vëllai im bleu një makinë të re muajin e kaluar dhe e nget atë çdo ditë për në punë."),
    ("sq", "Restoranti në qoshe shërben kafenë më të mirë në gjithë lagjen."),
    ("lt", "Mano brolis praėjusį mėnesį nusipirko naują automobilį ir kasdien juo važiuoja į darbą."),
    ("lt", "Restoranas ant kampo patiekia geriausią kavą visame rajone."),
    ("lv", "Mans brālis pagājušajā mēnesī nopirka jaunu mašīnu un katru dienu ar to brauc uz darbu."),
    ("lv", "Restorāns uz stūra pasniedz labāko kafiju visā apkaimē."),
    ("et", "Mu vend ostis eelmisel kuul uue auto ja sõidab sellega iga päev tööle."),
    ("et", "Nurgapealne restoran pakub kogu linnaosa parimat kohvi."),
    ("ca", "El meu germà es va comprar un cotxe nou el mes passat i el condueix cada dia per anar a la feina."),
    ("ca", "El restaurant de la cantonada serveix el millor cafè de tot el barri."),
    ("gl", "O meu irmán mercou un coche novo o mes pasado e condúceo ao traballo todos os días."),
    ("gl", "Despois da xuntanza decidimos cambiar o plan por completo."),
    ("af", "My broer het verlede maand 'n nuwe motor gekoop en hy ry elke dag daarmee werk toe."),
    ("af", "Die restaurant op die hoek bedien die beste koffie in die hele buurt."),
    ("vi", "Anh trai tôi đã mua một chiếc xe mới vào tháng trước và lái nó đi làm mỗi ngày."),
    ("vi", "Nhà hàng ở góc phố phục vụ cà phê ngon nhất trong cả khu phố."),
    ("tl", "Bumili ang kuya ko ng bagong kotse noong nakaraang buwan at minamaneho niya ito papunta sa trabaho araw-araw."),
    ("tl", "Ang restawran sa kanto ay naghahain ng pinakamasarap na kape sa buong lugar."),
    ("sw", "Kaka yangu alinunua gari jipya mwezi uliopita na analiendesha kazini kila siku."),
    ("sw", "Mkahawa ulioko kona hutoa kahawa bora zaidi katika mtaa mzima."),
    ("ms", "Abang saya membeli kereta baharu bulan lepas dan memandunya ke tempat kerja setiap hari."),
    ("ms", "Restoran di simpang itu menghidangkan kopi terbaik di seluruh kawasan."),
    ("mt", "Ħija xtara karozza ġdida x-xahar li għadda u jsuqha kuljum għax-xogħol."),
    ("mt", "Ir-ristorant fil-kantuniera jservi l-aħjar kafè fl-inħawi kollha."),
    ("cy", "Prynodd fy mrawd gar newydd y mis diwethaf ac mae'n ei yrru i'r gwaith bob dydd."),
    ("cy", "Mae'r bwyty ar y gornel yn gweini'r coffi gorau yn yr ardal gyfan."),
    ("ga", "Cheannaigh mo dheartháir carr nua an mhí seo caite agus tiomáineann sé chun na hoibre é gach lá."),
    ("ga", "Freastalaíonn an bialann ar an gcúinne an caife is fearr sa cheantar ar fad."),
    ("eu", "Nire anaiak auto berri bat erosi zuen joan den hilabetean eta egunero lanera gidatzen du."),
    ("eu", "Izkinako jatetxeak auzo osoko kafe onena zerbitzatzen du."),
    ("az", "Qardaşım keçən ay təzə maşın aldı və hər gün onunla işə gedir."),
    ("az", "Küncdəki restoran bütün məhəllədə ən yaxşı qəhvəni təqdim edir."),
    ("uz", "Akam oʻtgan oy yangi mashina sotib oldi va har kuni u bilan ishga boradi."),
    ("uz", "Burchakdagi restoran butun mahallada eng yaxshi qahvani taklif qiladi."),
    ("ht", "Frè mwen an te achte yon machin nèf mwa pase a e li kondui li al travay chak jou."),
    ("ht", "Restoran ki nan kwen an sèvi pi bon kafe nan tout katye a."),
    ("so", "Walaalkay wuxuu iibsaday baabuur cusub bishii hore wuxuuna ku qaataa shaqada maalin kasta."),
    ("so", "Makhaayadda geeska ku taal ayaa bixisa kaafiga ugu fiican xaafadda oo dhan."),
    # third held-out template for a dozen round-4 languages (includes one
    # honest sl->hr confusion - the closest pair in the set)
    ("no", "Studentene la frem prosjektene sine foran hele klassen i går."),
    ("is", "Nemendurnir kynntu verkefnin sín fyrir öllum bekknum í gær."),
    ("sk", "Študenti včera predstavili svoje projekty pred celou triedou."),
    ("hr", "Studenti su jučer predstavili svoje projekte pred cijelim razredom."),
    ("sl", "Študenti so včeraj predstavili svoje projekte pred celim razredom."),
    ("ca", "Els estudiants van presentar els seus projectes davant de tota la classe ahir."),
    ("af", "Die studente het gister hulle projekte voor die hele klas aangebied."),
    ("vi", "Các sinh viên đã trình bày dự án của họ trước cả lớp vào ngày hôm qua."),
    ("sw", "Wanafunzi waliwasilisha miradi yao mbele ya darasa zima jana."),
    ("tl", "Iniharap ng mga mag-aaral ang kanilang mga proyekto sa harap ng buong klase kahapon."),
    ("az", "Tələbələr dünən layihələrini bütün sinfin qarşısında təqdim etdilər."),
    ("ht", "Etidyan yo te prezante pwojè yo devan tout klas la yè."),
    # round-5 breadth: the languages added for reference parity, two
    # held-out sentences each (disjoint from the seed corpora)
    ("sr", "Моја сестра ради у болници и сваког јутра путује возом у град."),
    ("sr", "Деца се играју у дворишту док њихов отац спрема ручак."),
    ("mk", "Мојата сестра работи во болница и секое утро патува со воз до градот."),
    ("mk", "Децата си играат во дворот додека татко им подготвува ручек."),
    ("be", "Мая сястра працуе ў бальніцы і кожную раніцу едзе цягніком у горад."),
    ("be", "Дзеці гуляюць у двары, пакуль іх бацька гатуе абед."),
    ("kk", "Менің әпкем ауруханада жұмыс істейді және күн сайын пойызбен қалаға барады."),
    ("kk", "Балалар аулада ойнап жүр, ал әкесі түскі ас дайындап жатыр."),
    ("fa", "خواهر من در بیمارستان کار می‌کند و هر روز صبح با قطار به شهر می‌رود."),
    ("fa", "بچه‌ها در حیاط بازی می‌کنند در حالی که پدرشان ناهار آماده می‌کند."),
    ("ur", "میری بہن ہسپتال میں کام کرتی ہے اور ہر صبح ٹرین سے شہر جاتی ہے۔"),
    ("ur", "بچے صحن میں کھیل رہے ہیں جبکہ ان کے والد دوپہر کا کھانا تیار کر رہے ہیں۔"),
    ("ar", "تعمل أختي في المستشفى وتسافر كل صباح بالقطار إلى المدينة."),
    ("ar", "يلعب الأطفال في الفناء بينما يحضر والدهم الغداء."),
    ("ckb", "خوشکەکەم لە نەخۆشخانە کار دەکات و هەموو بەیانییەک بە شەمەندەفەر دەچێتە شار."),
    ("ckb", "منداڵەکان لە حەوشەکە یاری دەکەن کاتێک باوکیان نانی نیوەڕۆ ئامادە دەکات."),
    ("he", "אחותי עובדת בבית החולים ונוסעת כל בוקר ברכבת העירה."),
    ("he", "הילדים משחקים בחצר בזמן שאבא שלהם מכין ארוחת צהריים."),
    ("yi", "מײַן שוועסטער אַרבעט אין שפּיטאָל און פֿאָרט יעדן פֿרימאָרגן מיט דער באַן אין שטאָט."),
    ("yi", "די קינדער שפּילן זיך אין הויף בשעת זייער טאַטע גרייט צו דאָס וואַרעמעס."),
    ("hi", "मेरी बहन अस्पताल में काम करती है और हर सुबह ट्रेन से शहर जाती है।"),
    ("hi", "बच्चे आँगन में खेल रहे हैं जबकि उनके पिता दोपहर का खाना बना रहे हैं।"),
    ("mr", "माझी बहीण रुग्णालयात काम करते आणि दररोज सकाळी रेल्वेने शहरात जाते."),
    ("mr", "मुले अंगणात खेळत आहेत आणि त्यांचे वडील जेवण तयार करत आहेत."),
    ("ne", "मेरी बहिनी अस्पतालमा काम गर्छिन् र हरेक बिहान रेलबाट सहर जान्छिन्।"),
    ("ne", "केटाकेटीहरू आँगनमा खेलिरहेका छन् भने उनीहरूका बुबा खाना बनाउँदै हुनुहुन्छ।"),
    ("oc", "Ma sòrre trabalha a l'espital e cada matin pren lo tren per anar a la vila."),
    ("oc", "Los enfants jògan dins la cort mentre que lor paire prepara lo dinnar."),
    ("br", "Va c'hoar a labour en ospital hag a gemer an tren bep mintin evit mont e kêr."),
    ("br", "Ar vugale a c'hoari er porzh e-pad ma fich o zad merenn."),
    ("se", "Mu oabbá bargá buohcciviesus ja vuolgá juohke iđida togain gávpogii."),
    ("se", "Mánát stohket šiljus dan botta go sin áhčči ráhkada gaskabeaivvi."),
    ("an", "A mía chirmana treballa en o espital y cada maitino prene o tren ta ir t'a ciudat."),
    ("an", "Os ninos chugan en o patio mientres o suyo pai fa o chentar."),
    ("ast", "La mio hermana trabaya nel hospital y toles mañanes coyo'l tren pa dir a la ciudá."),
    ("ast", "Los nenos xueguen nel patiu mentes el so pá fai la xinta."),
    ("wa", "Mi soûr bouteye e l' ospitå et tos les maténs ele prind l' trin po-z aler al veye."),
    ("wa", "Les efants djouwnut el coûr tins ki leu pa aprestêye li dinner."),
    ("zh-tw", "我妹妹在醫院工作，每天早上坐火車去城裡。"),
    ("zh-tw", "孩子們在院子裡玩，他們的爸爸正在準備午飯。"),
    ("pa", "ਮੇਰੀ ਭੈਣ ਹਸਪਤਾਲ ਵਿੱਚ ਕੰਮ ਕਰਦੀ ਹੈ ਅਤੇ ਹਰ ਸਵੇਰ ਰੇਲ ਰਾਹੀਂ ਸ਼ਹਿਰ ਜਾਂਦੀ ਹੈ।"),
    ("kn", "ನನ್ನ ಸಹೋದರಿ ಆಸ್ಪತ್ರೆಯಲ್ಲಿ ಕೆಲಸ ಮಾಡುತ್ತಾಳೆ ಮತ್ತು ಪ್ರತಿದಿನ ರೈಲಿನಲ್ಲಿ ನಗರಕ್ಕೆ ಹೋಗುತ್ತಾಳೆ."),
    ("ml", "എന്റെ സഹോദരി ആശുപത്രിയിൽ ജോലി ചെയ്യുന്നു, എല്ലാ ദിവസവും ട്രെയിനിൽ നഗരത്തിലേക്ക് പോകുന്നു."),
    ("km", "បងស្រីរបស់ខ្ញុំធ្វើការនៅមន្ទីរពេទ្យ ហើយធ្វើដំណើរទៅទីក្រុងរៀងរាល់ព្រឹក។"),
    # third held-out template for the round-5 languages (school/market
    # register, matching the depth the round-4 languages already have)
    ("sr", "Деца су јутрос пешачила до школе кроз стару пијацу."),
    ("mk", "Децата утрово пешачеа до училиштето низ стариот пазар."),
    ("be", "Дзеці сёння раніцай ішлі ў школу праз стары рынак."),
    ("kk", "Балалар бүгін таңертең ескі базар арқылы мектепке жаяу барды."),
    ("ar", "مشى الأطفال هذا الصباح إلى المدرسة عبر السوق القديم."),
    ("fa", "بچه‌ها امروز صبح از میان بازار قدیمی پیاده به مدرسه رفتند."),
    ("ur", "بچے آج صبح پرانے بازار سے ہو کر پیدل اسکول گئے۔"),
    ("ckb", "منداڵەکان ئەمڕۆ بەیانی بە ناو بازاڕە کۆنەکەدا بە پێ چوونە قوتابخانە."),
    ("he", "הילדים הלכו הבוקר ברגל לבית הספר דרך השוק הישן."),
    ("yi", "די קינדער זענען הײַנט אין דער פֿרי געגאַנגען צו פֿוס אין שול דורכן אַלטן מאַרק."),
    ("hi", "बच्चे आज सुबह पुराने बाज़ार से होकर पैदल स्कूल गए।"),
    ("mr", "मुले आज सकाळी जुन्या बाजारातून चालत शाळेत गेली."),
    ("ne", "केटाकेटीहरू आज बिहान पुरानो बजार हुँदै हिँडेर विद्यालय गए।"),
    ("oc", "Los enfants son anats a pè a l'escòla aqueste matin per lo mercat vièlh."),
    ("br", "Ar vugale a zo aet war droad d'ar skol dre ar marc'had kozh ar mintin-mañ."),
    ("se", "Mánát vázze odne iđđes skuvlii boares márkana čađa."),
    ("an", "Os ninos son itos a piet ta la escuela iste maitino por o mercau viello."),
    ("ast", "Los nenos foron esta mañana a pie a la escuela pel mercáu vieyu."),
    ("wa", "Les efants ont roté disqu' a scole ci matén chal pa l' vî martchî."),
]


def test_lang_detect_accuracy_at_least_90pct():
    assert len(LANG_SAMPLES) >= 100
    correct, misses = 0, []
    for lang, text in LANG_SAMPLES:
        scores = detect_language(text)
        got = next(iter(scores), None)
        if got == lang:
            correct += 1
        else:
            misses.append((lang, got, text[:40]))
    acc = correct / len(LANG_SAMPLES)
    assert acc >= 0.90, f"accuracy {acc:.2%}; misses: {misses}"


def test_lang_detect_confidences_are_normalized():
    scores = detect_language("The quick brown fox jumps over the lazy dog "
                             "while the children watch from the garden.")
    assert next(iter(scores)) == "en"
    assert abs(sum(scores.values()) - 1.0) < 1e-6


# -- MIME fixtures -----------------------------------------------------------
def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode("ascii")


MIME_FIXTURES = [
    (b"\x89PNG\r\n\x1a\n" + b"\x00" * 16, "image/png"),
    (b"\xff\xd8\xff\xe0\x00\x10JFIF", "image/jpeg"),
    (b"GIF89a" + b"\x00" * 10, "image/gif"),
    (b"%PDF-1.7\n%\xe2\xe3", "application/pdf"),
    (b"PK\x03\x04\x14\x00", "application/zip"),
    (b"\x1f\x8b\x08\x00", "application/gzip"),
    (b"BZh91AY&SY", "application/x-bzip2"),
    (b"7z\xbc\xaf\x27\x1c\x00\x04", "application/x-7z-compressed"),
    (b"RIFF\x24\x00\x00\x00WAVEfmt ", "audio/wav"),
    (b"RIFF\x24\x00\x00\x00WEBPVP8 ", "image/webp"),
    (b"\x00\x00\x00\x18ftypmp42\x00\x00", "video/mp4"),
    (b"\x00\x00\x00\x20ftypheic\x00\x00", "image/heic"),
    (b"OggS\x00\x02", "audio/ogg"),
    (b"ID3\x03\x00", "audio/mpeg"),
    (b"wOF2\x00\x01", "font/woff2"),
    (b"\x7fELF\x02\x01", "application/x-executable"),
    (b"SQLite format 3\x00", "application/x-sqlite3"),
    (b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1" + b"\x00" * 8,
     "application/x-ole-storage"),
    (b"II*\x00\x08\x00", "image/tiff"),
    # round-4 breadth extension
    (b"Rar!\x1a\x07\x00", "application/x-rar-compressed"),
    (b"MSCF\x00\x00", "application/vnd.ms-cab-compressed"),
    (b"!<arch>\ndebian", "application/x-archive"),
    (b"\xed\xab\xee\xdb\x03\x00", "application/x-rpm"),
    (b"\x28\xb5\x2f\xfd\x24\x00", "application/zstd"),
    (b"\x04\x22\x4d\x18\x64\x40", "application/x-lz4"),
    (b"\xff\xf1\x50\x80", "audio/aac"),
    (b"#!AMR\n", "audio/amr"),
    (b"MThd\x00\x00\x00\x06", "audio/midi"),
    (b"FLV\x01\x05", "video/x-flv"),
    (b"\x30\x26\xb2\x75\x8e\x66\xcf\x11\xa6\xd9", "video/x-ms-asf"),
    (b"\x00\x00\x01\xba\x44\x00", "video/mpeg"),
    (b"8BPS\x00\x01", "image/vnd.adobe.photoshop"),
    (b"\x76\x2f\x31\x01\x02\x00", "image/x-exr"),
    (b"PAR1\x15\x04", "application/x-parquet"),
    (b"Obj\x01\x04\x14", "application/avro"),
    (b"ORC\x08\x03", "application/x-orc"),
    (b"\x89HDF\r\n\x1a\n\x00", "application/x-hdf5"),
    (b"\xd4\xc3\xb2\xa1\x02\x00", "application/vnd.tcpdump.pcap"),
    (b"\x00\x01\x00\x00\x00\x0c\x80\x00", "font/ttf"),
    (b"OTTO\x00\x0b", "font/otf"),
    (b"\x00asm\x01\x00\x00\x00", "application/wasm"),
    (b"\xca\xfe\xba\xbe\x00\x00\x00\x34", "application/java-vm"),
    (b"\xcf\xfa\xed\xfe\x07\x00", "application/x-mach-binary"),
    (b"%!PS-Adobe-3.0\n", "application/postscript"),
    (b"BEGIN:VCARD\nVERSION:3.0", "text/vcard"),
    (b"BEGIN:VCALENDAR\nVERSION:2.0", "text/calendar"),
    (b"\x1a\x45\xdf\xa3\x01\x00\x00\x00\x00\x00\x00\x23\x42\x86\x81\x01"
     b"\x42\xf7\x81\x01\x42\x82\x84webm", "video/webm"),
    (b"\x1a\x45\xdf\xa3\x01\x00\x00\x00\x00\x00\x00\x23\x42\x86\x81\x01"
     b"\x42\x82\x88matroska", "video/x-matroska"),
    (b"\x00\x00\x00\x1cftypavif\x00\x00", "image/avif"),
    (b"\x00\x00\x00\x1cftyp3gp5\x00\x00", "video/3gpp"),
    (b"PK\x03\x04\x14\x00\x08\x08" + b"\x00" * 18
     + b"[Content_Types].xml" + b"\x00" * 8 + b"word/document.xml",
     "application/vnd.openxmlformats-officedocument"
     ".wordprocessingml.document"),
    (b"PK\x03\x04\x14\x00\x08\x08" + b"\x00" * 18
     + b"[Content_Types].xml" + b"\x00" * 8 + b"xl/workbook.xml",
     "application/vnd.openxmlformats-officedocument.spreadsheetml.sheet"),
    (b"PK\x03\x04\x0a\x00\x00\x00\x00\x00" + b"\x00" * 16 + b"\x08\x00"
     + b"\x00\x00mimetypeapplication/epub+zip", "application/epub+zip"),
    (b"PK\x03\x04\x0a\x00\x00\x00\x00\x00" + b"\x00" * 16 + b"\x08\x00"
     + b"\x00\x00mimetypeapplication/vnd.oasis.opendocument.text",
     "application/vnd.oasis.opendocument.text"),
    (b"<!DOCTYPE html><html><body>", "text/html"),
    (b"  <svg xmlns='http://www.w3.org/2000/svg'>", "image/svg+xml"),
    (b'{"key": "value"}', "application/json"),
    (b"<?xml version='1.0'?><root/>", "application/xml"),
    (b"plain old text content here", "text/plain"),
    (b"\x00" * 200 + b"\xfe\xfe\xfe", "application/octet-stream"),
]


def test_mime_fixtures_all_exact():
    wrong = []
    for raw, expect in MIME_FIXTURES:
        got = detect_mime_type(_b64(raw))
        if got != expect:
            wrong.append((expect, got))
    assert not wrong, wrong


def test_mime_tar_at_offset():
    raw = b"\x00" * 257 + b"ustar\x0000" + b"\x00" * 200
    assert detect_mime_type(_b64(raw)) == "application/x-tar"


def test_mime_handles_garbage():
    assert detect_mime_type("!!!not-base64!!!") is None
    assert detect_mime_type(None) is None
    assert detect_mime_type("") is None


# -- phone fixtures -----------------------------------------------------------
PHONE_FIXTURES = [
    ("650-253-0000", "US", True),
    ("(212) 555-2368", "US", True),
    ("+1 650 253 0000", "US", True),
    ("1-800-466-4411", "US", True),
    ("123-456-7890", "US", False),     # area code starts with 1
    ("650-053-0000", "US", False),     # exchange starts with 0
    ("65-0253-000", "US", False),      # too short
    ("+44 20 7946 0958", "GB", True),
    ("020 7946 0958", "GB", True),
    ("+33 1 42 68 53 00", "FR", True),
    ("01 42 68 53 00", "FR", True),
    ("+33 0 12 34", "FR", False),
    ("+49 30 901820", "DE", True),
    ("+91 98765 43210", "IN", True),
    ("+91 12345 67890", "IN", False),  # mobile must start 6-9
    ("+61 2 9374 4000", "AU", True),
    ("+55 11 91234 5678", "BR", True),
    ("+34 612 345 678", "ES", True),
    ("+34 112 345 678", "ES", False),  # must start 6-9
    ("+86 138 0013 8000", "CN", True),
    ("", "US", None),
    (None, "US", None),
    ("not a phone", "US", False),
]


def test_phone_fixtures():
    wrong = []
    for phone, region, expect in PHONE_FIXTURES:
        got = is_valid_phone(phone, region)
        if got is not expect and got != expect:
            wrong.append((phone, region, expect, got))
    assert not wrong, wrong


# -- stopword-aware tokenizer -------------------------------------------------
def test_tokenizer_stopword_removal_explicit_language():
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.ops.text import TextTokenizer
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.types.columns import TextColumn
    from transmogrifai_tpu.types.dataset import Dataset

    f = FeatureBuilder(ft.Text, "t").as_predictor()
    ds = Dataset({"t": TextColumn(
        np.array(["the cat sat on the mat", None], dtype=object))})
    tok = TextTokenizer(remove_stopwords=True, language="en").set_input(f)
    out = tok.transform(ds)[tok.output_name]
    assert out.values[0] == ("cat", "sat", "mat")
    assert out.values[1] == ()


def test_tokenizer_stopword_removal_auto_detects_language():
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.ops.text import TextTokenizer
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.types.columns import TextColumn
    from transmogrifai_tpu.types.dataset import Dataset

    f = FeatureBuilder(ft.Text, "t").as_predictor()
    fr = ("Le chat dort dans la cuisine et le chien joue dans le jardin "
          "avec les enfants de la maison voisine")
    ds = Dataset({"t": TextColumn(np.array([fr], dtype=object))})
    tok = TextTokenizer(remove_stopwords=True, language="auto").set_input(f)
    out = tok.transform(ds)[tok.output_name]
    toks = set(out.values[0])
    assert "chat" in toks and "jardin" in toks
    assert "le" not in toks and "dans" not in toks and "avec" not in toks


# -- NER fixtures -------------------------------------------------------------
def _ner_scores():
    from ner_fixture import SENTENCES

    from transmogrifai_tpu.ops.ner import tag_entities

    counts = {c: [0, 0, 0] for c in ("person", "location", "organization")}
    for sent, gold in SENTENCES:
        pred = tag_entities(sent)
        for cls, (tp_fp_fn) in counts.items():
            g, p = set(gold.get(cls, [])), set(pred[cls])
            tp_fp_fn[0] += len(g & p)
            tp_fp_fn[1] += len(p - g)
            tp_fp_fn[2] += len(g - p)
    return counts


def test_ner_fixture_size_and_f1_floors():
    """VERDICT r3 item 5: labeled fixture >=100 sentences, measured
    precision/recall with a stated floor.  The rule-based tagger clears
    0.9 F1 per class on this fixture (floor set with headroom below the
    measured ~0.98 so rule tweaks can't silently crater a class)."""
    from ner_fixture import SENTENCES

    assert len(SENTENCES) >= 100
    counts = _ner_scores()
    for cls, (tp, fp, fn) in counts.items():
        prec = tp / max(tp + fp, 1)
        rec = tp / max(tp + fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-9)
        assert f1 >= 0.90, f"{cls}: P={prec:.3f} R={rec:.3f} F1={f1:.3f}"
    tp = sum(v[0] for v in counts.values())
    fp = sum(v[1] for v in counts.values())
    fn = sum(v[2] for v in counts.values())
    micro = 2 * tp / max(2 * tp + fp + fn, 1)
    assert micro >= 0.93, f"micro-F1 {micro:.3f}"


def test_ner_entity_type_routing():
    from transmogrifai_tpu.ops.ner import tag_entities

    ents = tag_entities(
        "Dr. Maria Gonzalez of the University of Michigan flew to Berlin."
    )
    assert "maria gonzalez" in ents["person"]
    assert "university of michigan" in ents["organization"]
    assert "berlin" in ents["location"]


MIME_ROUND5_FIXTURES = [
    # container routing added in round 5
    (b"OggS\x00\x02" + b"\x00" * 20 + b"OpusHead" + b"\x00" * 8, "audio/opus"),
    (b"OggS\x00\x02" + b"\x00" * 20 + b"\x01vorbis" + b"\x00" * 8, "audio/ogg"),
    (b"OggS\x00\x02" + b"\x00" * 20 + b"\x80theora" + b"\x00" * 8, "video/ogg"),
    (b"FORM\x00\x00\x00\x20AIFF" + b"\x00" * 8, "audio/aiff"),
    (b"FORM\x00\x00\x00\x20AIFC" + b"\x00" * 8, "audio/aiff"),
    (b'<?xml version="1.0"?>\n<gpx version="1.1">',
     "application/gpx+xml"),
    (b'<?xml version="1.0"?>\n<kml xmlns="x">',
     "application/vnd.google-earth.kml+xml"),
    (b'<?xml version="1.0"?>\n<rss version="2.0">', "application/rss+xml"),
    (b'<?xml version="1.0"?>\n<plist version="1.0">',
     "application/x-plist"),
    (b'<?xml version="1.0"?>\n<note>hi</note>', "application/xml"),
    (b"PK\x03\x04\x14\x00\x00\x00\x08\x00AndroidManifest.xml",
     "application/vnd.android.package-archive"),
    (b"PK\x03\x04\x14\x00\x00\x00\x08\x00META-INF/MANIFEST.MF",
     "application/java-archive"),
    (b"PK\x03\x04" + b"visio/document.xml",
     "application/vnd.ms-visio.drawing"),
    (b"PK\x03\x04mimetypeapplication/vnd.oasis.opendocument.graphics",
     "application/vnd.oasis.opendocument.graphics"),
    (b"\x1e\x00-lh5-" + b"\x00" * 20, "application/x-lzh-compressed"),
    (b"\x00" * 60 + b"BOOKMOBI" + b"\x00" * 10,
     "application/x-mobipocket-ebook"),
    # round-5 direct magics (sample of the long tail)
    (b"\x93NUMPY\x01\x00", "application/x-npy"),
    (b"ARROW1\x00\x00", "application/vnd.apache.arrow.file"),
    (b"MATLAB 5.0 MAT-file", "application/x-matlab-data"),
    (b"CDF\x01\x00", "application/x-netcdf"),
    (b"P5\n640 480\n255\n" + b"\x00" * 9, "image/x-portable-graymap"),
    (b"P3\n2 2\n255\n0 0 0", "image/x-portable-pixmap"),
    (b"\x00\x00\x00\x0cjP  \r\n\x87\n", "image/jp2"),
    (b"AT&TFORM" + b"\x00" * 8, "image/vnd.djvu"),
    (b"SIMPLE  =                    T", "application/fits"),
    (b"wvpk\x00\x00", "audio/x-wavpack"),
    (b".snd\x00\x00\x00\x18", "audio/basic"),
    (b"ITSF\x03\x00", "application/vnd.ms-htmlhelp"),
    (b"\xffWPC\x00\x00", "application/vnd.wordperfect"),
    (b"dex\n035\x00", "application/x-dex"),
    (b"-----BEGIN CERTIFICATE-----\nMIIB", "application/x-x509-cert"),
    (b"-----BEGIN PGP MESSAGE-----", "application/pgp-encrypted"),
    (b"d8:announce35:udp", "application/x-bittorrent"),
    (b"\x00\x01\x00\x00Standard Jet DB\x00", "application/x-msaccess"),
    (b"glTF\x02\x00\x00\x00", "model/gltf-binary"),
    (b"ttcf\x00\x01\x00\x00", "font/collection"),
    (b"070701" + b"0" * 20, "application/x-cpio"),
    (b"hsqs\x00\x00", "application/x-squashfs"),
]


def test_mime_round5_breadth():
    wrong = []
    for raw, expect in MIME_ROUND5_FIXTURES:
        got = detect_mime_type(_b64(raw))
        if got != expect:
            wrong.append((expect, got))
    assert not wrong, wrong


def test_mime_registry_size_floor():
    """The registry must stay at >=100 signatures (VERDICT r4 item 8);
    counted across direct magics and every container-routing table."""
    from transmogrifai_tpu.ops import text_analysis as ta

    n = (
        len(ta._MAGIC) + len(ta._RIFF_SUBTYPES) + len(ta._FORM_SUBTYPES)
        + len(ta._OGG_CODECS) + len(ta._XML_ROOTS) + len(ta._ZIP_HINTS)
    )
    assert n >= 100, n


def test_mime_ole_subtypes_stay_generic():
    """Documented boundary: OLE compound files report the container type
    - member discrimination (doc/xls/msg) needs directory sectors the
    base64 head does not carry."""
    raw = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1" + b"\x00" * 24
    assert detect_mime_type(_b64(raw)) == "application/x-ole-storage"


def test_mime_short_magic_false_positives_stay_text():
    """Prose that happens to share a short magic prefix must remain
    text/plain (review r5): loose LHA offsets, bare XML-root prefixes,
    and 2-3 byte ASCII magics all previously shadowed the text fallback."""
    for raw in [
        b"my-lhasa apso is a dog breed",
        b"P1 is the highest priority ticket in the queue",
        b"dex\nnotes from today's standup meeting",
        b"GRIB data comes from the weather service archive",
        b"MAC addresses are assigned by the manufacturer",
    ]:
        assert detect_mime_type(_b64(raw)) == "text/plain", raw
    # XML roots require an element-name boundary
    assert detect_mime_type(_b64(
        b'<?xml version="1.0"?>\n<feedback rating="5">'
    )) == "application/xml"
    assert detect_mime_type(_b64(
        b'<?xml version="1.0"?>\n<kmlExport v="2">'
    )) == "application/xml"
    # and the real GRIB/LHA forms still detect
    assert detect_mime_type(_b64(
        b"GRIB\x00\x00\x30\x01" + b"\x00" * 8
    )) == "application/x-grib"
    assert detect_mime_type(_b64(
        b"\x1e\x00-lh5-" + b"\x00" * 20
    )) == "application/x-lzh-compressed"


def test_mime_pgp_armor_subtypes():
    assert detect_mime_type(_b64(
        b"-----BEGIN PGP PUBLIC KEY BLOCK-----\nxsBN"
    )) == "application/pgp-keys"
    assert detect_mime_type(_b64(
        b"-----BEGIN PGP SIGNATURE-----\nwsBc"
    )) == "application/pgp-signature"


def test_mime_review_r5_hardening():
    """Second review pass: XML routing keys on the DOCUMENT element only
    (roots in comments/nested elements must not route), the LHA level
    byte is validated, and the MATLAB magic is the full header."""
    assert detect_mime_type(_b64(
        b'<?xml version="1.0"?>\n<!-- exported to <html> viewer -->\n'
        b'<config a="1"/>'
    )) == "application/xml"
    assert detect_mime_type(_b64(
        b'<?xml version="1.0"?><report><svg width="10"/></report>'
    )) == "application/xml"
    assert detect_mime_type(_b64(
        b'<?xml version="1.0"?>\n<!DOCTYPE svg>\n<svg width="4">'
    )) == "image/svg+xml"
    assert detect_mime_type(_b64(
        b"ab-lhx-prose with a fake level byte"
    )) == "text/plain"
    assert detect_mime_type(_b64(
        b"MATLAB 5.0 introduced cell arrays and structs"
    )) == "text/plain"
    assert detect_mime_type(_b64(
        b"MATLAB 5.0 MAT-file, Platform: GLNXA64" + b"\x00" * 100
    )) == "application/x-matlab-data"
