"""Style-as-test gate (the ScalaStyleValidationTest analog, SURVEY §4).

Enforced invariants over every module in transmogrifai_tpu/:
- parses as valid python (AST) with no tab indentation
- no line longer than 140 columns (keeps diffs reviewable)
- citation discipline: every public Op* stage class carries a docstring
  mentioning the reference, or sits in a module whose docstring does -
  the judge-checkable parity trail the build contract requires
- library modules print nothing (logging/metadata channels only);
  user-facing surfaces (cli, runner, examples) are exempt
- no bare ``except:`` anywhere (it swallows KeyboardInterrupt/SystemExit)
- every broad ``except Exception`` under serving/ and workflow/ must
  re-raise, use the bound exception, or record telemetry/a log entry -
  silent swallowing is exactly how serving degradation hides (ISSUE 2)
- no unbounded blocking waits under parallel/ and workflow/: every
  ``.join()`` / ``.wait()`` / ``.get()`` / ``.recv()`` must pass a
  timeout - a hung mesh peer or D-state child must never be able to
  wedge supervision or the collective watchdog forever (ISSUE 3)
- no silent exception swallowing under readers/ and schema/: an
  ``except`` whose body is ONLY ``pass``/``continue`` (no re-raise, no
  use of the exception, no telemetry/log call) is exactly how a
  malformed row silently coerces instead of being quarantined or named
  (ISSUE 4)
- model artifacts are written only via serialization/ and registry/:
  no ``np.save``/``np.savez*`` calls and no write-mode ``open()`` of an
  artifact file (model.json, arrays.npz, manifest.json, schema.json,
  registry.json) anywhere else - every published version must ride the
  crash-consistent fsync+manifest+rename path, or a registry entry
  could reference an artifact that a crash can corrupt (ISSUE 5)
- durations are never measured on the epoch clock: no ``time.time()``
  call inside a subtraction anywhere in the package (ISSUE 7) - the
  epoch clock steps under NTP, so span/metric timing must ride
  ``time.perf_counter``/``perf_counter_ns``/``monotonic``; the one
  allowlisted site compares against a file MTIME, which only exists on
  the epoch timeline
- the observability plane (obs/ and utils/tracing.py) stays importable
  before jax/numpy init: module-level imports are stdlib or intra-obs
  relative only (ISSUE 7) - the measurement plane must not depend on
  the accelerator stack it measures
- bulk/ never writes a file with a bare ``open()``/``np.save*``: the
  exactly-once journal and every output shard ride the atomic
  tempfile+fsync+rename writer only (ISSUE 18)
"""
import ast
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent / "transmogrifai_tpu"
MODULES = sorted(ROOT.rglob("*.py"))
assert MODULES, "transmogrifai_tpu package not found - gates would be vacuous"
# exemptions are RELATIVE to the package root (absolute-path matching
# would exempt everything under e.g. /home/ci/examples/<repo>)
PRINT_EXEMPT_REL = {("cli.py",), ("workflow", "runner.py")}
PRINT_EXEMPT_DIRS = {"examples"}


def _rel(p: pathlib.Path) -> tuple:
    return p.relative_to(ROOT).parts


def test_every_module_parses_and_has_no_tabs():
    for p in MODULES:
        src = p.read_text(encoding="utf-8")
        ast.parse(src)  # raises on syntax errors
        for i, line in enumerate(src.split("\n"), 1):
            assert "\t" not in line, f"{p}:{i}: tab indentation"


def test_line_length_cap():
    over = []
    for p in MODULES:
        for i, line in enumerate(p.read_text(encoding="utf-8").split("\n"), 1):
            if len(line) > 140:
                over.append(f"{p}:{i} ({len(line)} cols)")
    assert not over, over[:10]


def test_op_stage_citation_discipline():
    missing = []
    for p in MODULES:
        tree = ast.parse(p.read_text(encoding="utf-8"))
        mod_doc = (ast.get_docstring(tree) or "").lower()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name.startswith("Op"):
                doc = (ast.get_docstring(node) or "").lower()
                if "reference" not in doc and "reference" not in mod_doc:
                    missing.append(f"{p}:{node.name}")
    assert not missing, missing


def test_no_bare_except_anywhere():
    """``except:`` catches KeyboardInterrupt/SystemExit and hides every
    failure class behind it - always name the exception."""
    offenders = []
    for p in MODULES:
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                offenders.append(f"{p}:{node.lineno}")
    assert not offenders, offenders


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


_LOGGING_ATTRS = {"exception", "error", "warning", "info", "debug"}


def _handler_is_accounted(handler: ast.ExceptHandler) -> bool:
    """A broad handler is acceptable when the failure leaves a trace:
    it re-raises, uses the bound exception object (so the error reaches
    a result/telemetry channel), or calls a record*/log method."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if (handler.name is not None
                and isinstance(node, ast.Name)
                and node.id == handler.name):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            attr = node.func.attr
            if attr.startswith("record") or attr in _LOGGING_ATTRS:
                return True
    return False


def test_serving_and_workflow_broad_excepts_leave_a_trace():
    """Under serving/, workflow/, fleet/ AND bulk/ a broad ``except
    Exception`` must re-raise, use the caught exception, or record
    telemetry/logging - a swallowed batch failure is a silent
    full-fleet degradation, on the ISSUE-17 TCP transport a swallowed
    channel error is an invisible network fault, and in an ISSUE-18
    bulk job a swallowed shard failure silently breaks exactly-once."""
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if rel[0] not in ("serving", "workflow", "fleet", "bulk"):
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node):
                if not _handler_is_accounted(node):
                    offenders.append(f"{p}:{node.lineno}")
    assert not offenders, offenders


_BLOCKING_METHODS = {"join", "wait", "get", "recv"}

#: provably-bounded blocking sites, keyed (relative-path, lineno) - keep
#: EMPTY unless a site can be argued bounded in a comment here
_BLOCKING_ALLOWLIST: set = set()


def test_no_unbounded_blocking_waits_under_parallel_and_workflow():
    """Under parallel/, workflow/, fleet/ AND bulk/ every .join()/
    .wait()/.get()/.recv() call must pass a timeout (ISSUE 3; extended
    to the serving fleet by ISSUE 14 and to bulk scoring by ISSUE 18 -
    a SIGKILLed replica or a wedged router peer must never block
    dispatch, failover, worker shutdown, or a bulk job's result drain
    forever; every fleet wait runs in 50 ms quanta).  The zero-argument
    forms are the unbounded-blocking ones - dict.get(k) /
    "sep".join(xs) / q.get(timeout=...) all carry arguments and pass."""
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if rel[0] not in ("parallel", "workflow", "fleet", "bulk"):
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS
                and not node.args
                and not node.keywords
                and ("/".join(rel), node.lineno) not in _BLOCKING_ALLOWLIST
            ):
                offenders.append(f"{p}:{node.lineno} .{node.func.attr}()")
    assert not offenders, offenders


def test_pipeline_queue_waits_are_bounded():
    """Under readers/pipeline.py every queue ``.put()`` must carry an
    explicit ``timeout=`` and every zero-argument ``.get()``/``.join()``
    is forbidden (ISSUE 10, same rule family as the parallel/ gate): a
    full prefetch buffer with a dead consumer - or a wedged worker at
    join time - must never block ingest forever.  ``"sep".join(xs)`` /
    ``d.get(k)`` carry arguments and pass; ``q.put(item)`` does NOT
    pass (it has an argument but still blocks unboundedly)."""
    p = ROOT / "readers" / "pipeline.py"
    tree = ast.parse(p.read_text(encoding="utf-8"))
    offenders = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        attr = node.func.attr
        if attr not in ("put", "get", "join"):
            continue
        has_timeout_kw = any(kw.arg == "timeout" for kw in node.keywords)
        if attr == "put":
            ok = has_timeout_kw
        else:
            ok = has_timeout_kw or bool(node.args)
        if not ok:
            offenders.append(f"{p}:{node.lineno} .{attr}()")
    assert not offenders, offenders


def test_no_silent_exception_swallowing_under_readers_and_schema():
    """Under readers/ and schema/ an ``except`` handler whose body is
    only ``pass``/``continue`` must still leave a trace (re-raise, use
    the exception, or call a record*/log method): the data plane's
    whole job is to NAME bad rows, not to silently eat them (ISSUE 4).
    Applies to every exception type, not just broad ones - a narrow
    ``except ValueError: pass`` swallows a malformed cell just as
    silently."""
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if rel[0] not in ("readers", "schema"):
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            body_only_skips = all(
                isinstance(stmt, (ast.Pass, ast.Continue))
                for stmt in node.body
            )
            if body_only_skips and not _handler_is_accounted(node):
                offenders.append(f"{p}:{node.lineno}")
    assert not offenders, offenders


#: files that make up a crash-consistent model artifact (plus the
#: registry index); writing any of these outside the exempt dirs
#: bypasses the fsync + manifest + atomic-rename discipline
_ARTIFACT_FILES = (
    "model.json", "arrays.npz", "manifest.json", "schema.json",
    "registry.json",
)
_ARTIFACT_WRITE_EXEMPT_DIRS = ("serialization", "registry")
_NP_SAVERS = {"save", "savez", "savez_compressed"}


def _call_writes_artifact(node: ast.Call) -> bool:
    """A write-mode ``open()`` whose argument expressions mention an
    artifact filename literal."""
    if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
        return False
    mode = ""
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = str(node.args[1].value)
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = str(kw.value.value)
    if not any(c in mode for c in "wax+"):
        return False
    for arg in node.args[:1]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                if any(sub.value.endswith(n) for n in _ARTIFACT_FILES):
                    return True
    return False


def test_model_artifacts_written_only_via_serialization_and_registry():
    """Every model artifact write must go through serialization/ (or the
    registry/ index commit): a raw ``open()``/``np.savez`` elsewhere
    produces an artifact with no manifest, no fsync, and no atomic swap
    - exactly the un-verifiable state the registry exists to prevent
    (ISSUE 5)."""
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if rel[0] in _ARTIFACT_WRITE_EXEMPT_DIRS:
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _NP_SAVERS
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy")
            ):
                offenders.append(f"{p}:{node.lineno} np.{f.attr}")
            elif _call_writes_artifact(node):
                offenders.append(f"{p}:{node.lineno} open(<artifact>, 'w')")
    assert not offenders, offenders


def test_bulk_writes_only_through_the_atomic_journal_writer():
    """Under bulk/ NO file may be written with a bare ``open()`` or
    ``np.save*`` at all (ISSUE 18): the exactly-once contract rests on
    every journal and output-shard byte riding the tempfile + fsync +
    os.replace path (serialization.write_bytes_atomic), so a single
    buffered write-mode ``open()`` is a torn-file bug waiting for a
    kill -9.  Read-mode ``open(p, "rb")`` passes; this gate is stricter
    than the artifact gate above - it bans write modes regardless of
    filename."""
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if rel[0] != "bulk":
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _NP_SAVERS
                and isinstance(f.value, ast.Name)
                and f.value.id in ("np", "numpy")
            ):
                offenders.append(f"{p}:{node.lineno} np.{f.attr}")
                continue
            if not (isinstance(f, ast.Name) and f.id == "open"):
                continue
            mode = "r"
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if any(c in mode for c in "wax+"):
                offenders.append(f"{p}:{node.lineno} open(mode={mode!r})")
    assert not offenders, offenders


def test_library_modules_do_not_print():
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if rel in PRINT_EXEMPT_REL or rel[0] in PRINT_EXEMPT_DIRS:
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                offenders.append(f"{p}:{node.lineno}")
    assert not offenders, offenders


#: epoch-clock subtraction sites that are provably NOT durations, keyed
#: (relative-path, lineno) - each needs a justification here:
#: supervisor.staleness compares time.time() against a heartbeat file's
#: os.path.getmtime(), and mtimes only exist on the epoch timeline;
#: obs/fleet.py's shard-staleness check is the same mtime comparison
#: (the PeerHealth convention - obs/ cannot import the supervisor
#: helper because the obs plane stays stdlib/intra-obs at module level)
_EPOCH_SUB_ALLOWLIST = {
    ("workflow/supervisor.py", 64),
    ("obs/fleet.py", 305),
}


def _is_time_time_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "time"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "time"
    )


def test_no_epoch_clock_durations():
    """No ``time.time()`` call may appear inside a subtraction anywhere
    in the package - or in ``bench.py`` (ISSUE 15 satellite extended the
    ISSUE-7 gate: the boston/iris train walls were still epoch-clock
    subtractions): ``time.time() - t0`` is a duration measured on a
    clock that steps under NTP.  Span/metric timing code must use
    ``time.perf_counter`` / ``perf_counter_ns`` / ``time.monotonic``;
    epoch stamps are fine as plain timestamps."""
    bench = ROOT.parent / "bench.py"
    for p in list(MODULES) + [bench]:
        rel = _rel(p) if p != bench else ("bench.py",)
        offenders = []
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            if not any(_is_time_time_call(sub) for sub in ast.walk(node)):
                continue
            if ("/".join(rel), node.lineno) in _EPOCH_SUB_ALLOWLIST:
                continue
            offenders.append(f"{p}:{node.lineno} time.time() in a "
                             "subtraction")
        assert not offenders, offenders


def _validate_fold_loops(tree: ast.Module):
    """The fold loops (``for f in ...``) of OpValidator.validate, with
    every nested node - the fold x grid hot path."""
    validate_fn = None
    for node in ast.walk(tree):
        if (isinstance(node, ast.ClassDef) and node.name == "OpValidator"):
            for sub in node.body:
                if (isinstance(sub, ast.FunctionDef)
                        and sub.name == "validate"):
                    validate_fn = sub
    assert validate_fn is not None, "OpValidator.validate not found"
    for node in ast.walk(validate_fn):
        if (isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and node.target.id == "f"):
            yield node


def test_validator_hot_loop_has_no_device_host_syncs():
    """The fold x grid hot loops in selector/validator.py (the
    ``for f`` fold loops of OpValidator.validate and everything nested
    in them) must not force mid-loop device->host syncs: no ``.item()``
    (anywhere in the file), and no ``float(...)`` / ``np.asarray(...)``
    calls inside the fold loops outside Lambda bodies (ISSUE 15
    satellite) - the post-selection boundary (result building after the
    metric matrix is complete) is where host conversion belongs.  The
    degraded-mode recompute closures (lambdas handed to the collective
    watchdog) are the sanctioned exception."""
    p = ROOT / "selector" / "validator.py"
    tree = ast.parse(p.read_text(encoding="utf-8"))
    offenders = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute) and node.attr == "item"):
            offenders.append(f"{p}:{node.lineno} .item")

    lambda_nodes: set = set()
    for loop in _validate_fold_loops(tree):
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Lambda):
                for inner in ast.walk(sub):
                    lambda_nodes.add(id(inner))
        for sub in ast.walk(loop):
            if id(sub) in lambda_nodes or not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Name) and fn.id == "float":
                offenders.append(f"{p}:{sub.lineno} float() in fold loop")
            elif (
                isinstance(fn, ast.Attribute)
                and fn.attr == "asarray"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("np", "numpy")
            ):
                offenders.append(
                    f"{p}:{sub.lineno} np.asarray in fold loop")
    assert not offenders, offenders


def test_obs_plane_importable_before_jax_numpy():
    """obs/ (and utils/tracing.py, which it absorbed the quantile
    helper from) must stay importable before jax/numpy init (ISSUE 7):
    every module-level import is either stdlib or a relative import
    within obs/ - so a metrics scrape or span export can never be the
    thing that initializes a device backend."""
    import sys

    stdlib = set(sys.stdlib_module_names)
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if not (rel[0] == "obs" or rel == ("utils", "tracing.py")):
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in tree.body:  # module level only: lazy imports are
            # exactly the escape hatch (profile_to imports jax inside)
            if isinstance(node, ast.Import):
                for a in node.names:
                    root = a.name.split(".")[0]
                    if root not in stdlib:
                        offenders.append(f"{p}:{node.lineno} import "
                                         f"{a.name}")
            elif isinstance(node, ast.ImportFrom):
                if node.level > 0:
                    mod = node.module or ""
                    if mod.split(".")[0] != "obs" and rel[0] != "obs":
                        offenders.append(
                            f"{p}:{node.lineno} relative import "
                            f"{mod!r} outside obs/"
                        )
                    elif rel[0] == "obs" and node.level > 1:
                        offenders.append(
                            f"{p}:{node.lineno} relative import above "
                            "obs/"
                        )
                else:
                    root = (node.module or "").split(".")[0]
                    if root not in stdlib:
                        offenders.append(f"{p}:{node.lineno} from "
                                         f"{node.module} import ...")
    assert not offenders, offenders


#: the only functions in obs/fleet.py allowed to parse foreign JSON
#: bytes: both degrade torn/partial input to a skip-and-count, never an
#: exception escaping into the aggregator/scrape path
_FLEET_LOADER_FUNCS = {"read_json_torn_safe", "read_jsonl_tolerant"}


def test_fleet_reads_snapshots_only_via_torn_safe_loader():
    """obs/fleet.py may call ``json.load``/``json.loads`` ONLY inside
    the torn-read-safe loaders (ISSUE 11 satellite): shard files are
    written by OTHER processes that can be SIGKILLed mid-write, so any
    direct parse elsewhere in the module is a latent crash of the whole
    fleet scrape on one dying process."""
    p = ROOT / "obs" / "fleet.py"
    tree = ast.parse(p.read_text(encoding="utf-8"))
    offenders = []

    def _walk(node, func_name):
        for child in ast.iter_child_nodes(node):
            name = func_name
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr in ("load", "loads")
                and isinstance(child.func.value, ast.Name)
                and child.func.value.id == "json"
                and func_name not in _FLEET_LOADER_FUNCS
            ):
                offenders.append(f"{p}:{child.lineno} json.{child.func.attr}"
                                 f" outside the torn-safe loaders")
            _walk(child, name)

    _walk(tree, "<module>")
    assert not offenders, offenders


#: the fused serving path (ISSUE 12 satellite): these modules import at
#: LocalScorer construction on every CPU replica, so a module-level jax
#: import would put jax/device init on the numpy-fused cold-start path.
#: local/fused_xla.py is the XLA backend itself and STILL must defer -
#: importing the cache/compiler types (model_io does, for the artifact
#: round trip) must not initialize a backend.
_FUSED_PATH_MODULES = (
    ("local", "__init__.py"),
    ("local", "fused.py"),
    ("local", "fused_xla.py"),
    ("local", "fused_train.py"),
    ("local", "scorer.py"),
)


def test_no_module_level_jax_on_fused_serving_path():
    """No module-level ``import jax``/``jaxlib`` anywhere on the fused
    serving path (ISSUE 12 satellite): the numpy-fused default and the
    artifact load path must never pay jax/device initialization; every
    jax touch in the XLA backend goes through deferred in-function
    imports."""
    offenders = []
    for p in MODULES:
        if _rel(p) not in _FUSED_PATH_MODULES:
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in tree.body:  # module level only: lazy is the pattern
            roots = []
            if isinstance(node, ast.Import):
                roots = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                roots = [(node.module or "").split(".")[0]]
            for root in roots:
                if root in ("jax", "jaxlib"):
                    offenders.append(
                        f"{p}:{node.lineno} module-level {root} import"
                    )
    assert not offenders, offenders


def test_fused_module_stays_columnar():
    """The fused serving program (local/fused.py) must stay columnar end
    to end (ISSUE 6): no ``for``/``while`` statement loops anywhere in
    the module (single-pass boundary comprehensions at decode/assembly
    are the ONLY per-record python allowed), and no Column round trips -
    ``to_list()`` / ``column_from_list`` / ``with_column`` would rebuild
    exactly the per-stage boxing the compiler exists to remove."""
    fused = ROOT / "local" / "fused.py"
    src = fused.read_text(encoding="utf-8")
    tree = ast.parse(src)
    offenders = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            offenders.append(
                f"{fused}:{node.lineno} statement loop "
                f"({type(node).__name__})"
            )
        elif (
            isinstance(node, ast.Attribute)
            and node.attr in ("to_list", "with_column")
        ):
            offenders.append(f"{fused}:{node.lineno} .{node.attr}")
        elif (
            isinstance(node, ast.Name)
            and node.id == "column_from_list"
        ):
            offenders.append(f"{fused}:{node.lineno} column_from_list")
    assert not offenders, offenders


def test_autotune_reads_telemetry_via_public_apis_only():
    """autotune/ may read observations only through public obs-plane
    APIs - registry/profiler/tracer snapshots, span exports, snapshot
    dicts (ISSUE 13 satellite, the PR-9 torn-safe-loader discipline):
    no single-underscore attribute of ANY foreign object is touched
    anywhere in the package (``self._x``/``cls._x`` own-state access is
    the only exception).  A private reach into a telemetry object would
    couple the tuner to accumulator internals that every telemetry
    class is free to change under its own lock discipline."""
    offenders = []
    for p in sorted((ROOT / "autotune").rglob("*.py")):
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            attr = node.attr
            if not attr.startswith("_") or attr.startswith("__"):
                continue
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")
            ):
                continue
            offenders.append(f"{p}:{node.lineno} .{attr}")
    assert not offenders, offenders


def test_autoscaler_drives_the_fleet_via_public_seams_only():
    """fleet/autoscaler.py composes the controller, router, SLO
    engine, cost model and knob tuner and may drive them ONLY through
    their public seams (ISSUE 19 satellite): no single-underscore
    attribute of ANY foreign object is touched anywhere in the module
    (``self._x``/``cls._x`` own-state access is the only exception).
    The control loop must survive each subsystem refactoring its
    internals - a private reach would weld capacity decisions to
    implementation details four packages away."""
    p = ROOT / "fleet" / "autoscaler.py"
    offenders = []
    tree = ast.parse(p.read_text(encoding="utf-8"))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        attr = node.attr
        if not attr.startswith("_") or attr.startswith("__"):
            continue
        if (
            isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")
        ):
            continue
        offenders.append(f"{p}:{node.lineno} .{attr}")
    assert not offenders, offenders


def test_multimodel_drives_subsystems_via_public_seams_only():
    """fleet/multimodel.py composes the registry, the deployment
    controller, the cost model and the fault plane and may drive them
    ONLY through their public seams (ISSUE 20 satellite): no
    single-underscore attribute of ANY foreign object is touched
    anywhere in the module (``self._x``/``cls._x`` own-state access is
    the only exception).  The model table and placement planner must
    survive each subsystem refactoring its internals - a private reach
    would weld the multiplexing layer to lifecycle implementation
    details it does not own."""
    p = ROOT / "fleet" / "multimodel.py"
    offenders = []
    tree = ast.parse(p.read_text(encoding="utf-8"))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        attr = node.attr
        if not attr.startswith("_") or attr.startswith("__"):
            continue
        if (
            isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")
        ):
            continue
        offenders.append(f"{p}:{node.lineno} .{attr}")
    assert not offenders, offenders


def test_continuous_drives_subsystems_via_public_seams_only():
    """continuous/ composes five earlier subsystems (reader follow
    mode, drift monitor, fused-train cache, registry, fleet) and may
    drive them ONLY through their public seams (ISSUE 16 satellite):
    no single-underscore attribute of ANY foreign object is touched
    anywhere in the package (``self._x``/``cls._x`` own-state access is
    the only exception).  The controller must survive each subsystem
    refactoring its internals - a private reach would weld the refit
    loop to implementation details five packages away."""
    offenders = []
    for p in sorted((ROOT / "continuous").rglob("*.py")):
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            attr = node.attr
            if not attr.startswith("_") or attr.startswith("__"):
                continue
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")
            ):
                continue
            offenders.append(f"{p}:{node.lineno} .{attr}")
    assert not offenders, offenders
