"""Style-as-test gate (the ScalaStyleValidationTest analog, SURVEY §4).

Enforced invariants over every module in transmogrifai_tpu/:
- parses as valid python (AST) with no tab indentation
- no line longer than 140 columns (keeps diffs reviewable)
- citation discipline: every public Op* stage class carries a docstring
  mentioning the reference, or sits in a module whose docstring does -
  the judge-checkable parity trail the build contract requires
- library modules print nothing (logging/metadata channels only);
  user-facing surfaces (cli, runner, examples) are exempt
"""
import ast
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent / "transmogrifai_tpu"
MODULES = sorted(ROOT.rglob("*.py"))
assert MODULES, "transmogrifai_tpu package not found - gates would be vacuous"
# exemptions are RELATIVE to the package root (absolute-path matching
# would exempt everything under e.g. /home/ci/examples/<repo>)
PRINT_EXEMPT_REL = {("cli.py",), ("workflow", "runner.py")}
PRINT_EXEMPT_DIRS = {"examples"}


def _rel(p: pathlib.Path) -> tuple:
    return p.relative_to(ROOT).parts


def test_every_module_parses_and_has_no_tabs():
    for p in MODULES:
        src = p.read_text(encoding="utf-8")
        ast.parse(src)  # raises on syntax errors
        for i, line in enumerate(src.split("\n"), 1):
            assert "\t" not in line, f"{p}:{i}: tab indentation"


def test_line_length_cap():
    over = []
    for p in MODULES:
        for i, line in enumerate(p.read_text(encoding="utf-8").split("\n"), 1):
            if len(line) > 140:
                over.append(f"{p}:{i} ({len(line)} cols)")
    assert not over, over[:10]


def test_op_stage_citation_discipline():
    missing = []
    for p in MODULES:
        tree = ast.parse(p.read_text(encoding="utf-8"))
        mod_doc = (ast.get_docstring(tree) or "").lower()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name.startswith("Op"):
                doc = (ast.get_docstring(node) or "").lower()
                if "reference" not in doc and "reference" not in mod_doc:
                    missing.append(f"{p}:{node.name}")
    assert not missing, missing


def test_library_modules_do_not_print():
    offenders = []
    for p in MODULES:
        rel = _rel(p)
        if rel in PRINT_EXEMPT_REL or rel[0] in PRINT_EXEMPT_DIRS:
            continue
        tree = ast.parse(p.read_text(encoding="utf-8"))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                offenders.append(f"{p}:{node.lineno}")
    assert not offenders, offenders
