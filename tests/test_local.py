"""Local (engine-free) scoring: numpy predict parity + row scorer.

Mirrors the reference's local-scoring test (reference: local/src/test/scala/
com/salesforce/op/local/OpWorkflowModelLocalTest.scala): the compiled
dict->dict function must agree with batch scoring through the full engine.
"""
import os
import time

import numpy as np
import pytest

from transmogrifai_tpu.examples.titanic import TITANIC_CSV, titanic_workflow
from transmogrifai_tpu.local import LocalScorer, score_function
from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.mlp import OpMultilayerPerceptronClassifier
from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.trees import (
    OpGBTClassifier,
    OpGBTRegressor,
    OpRandomForestClassifier,
    OpRandomForestRegressor,
)

needs_data = pytest.mark.skipif(
    not os.path.exists(TITANIC_CSV), reason="titanic csv not available"
)


CLS_MODELS = [
    OpLogisticRegression(),
    OpRandomForestClassifier(num_trees=5, max_depth=3),
    OpGBTClassifier(num_trees=5, max_depth=3),
    OpNaiveBayes(),
    OpMultilayerPerceptronClassifier(hidden_layers=(4,), max_iter=20),
]
REG_MODELS = [
    OpLinearRegression(),
    OpRandomForestRegressor(num_trees=5, max_depth=3),
    OpGBTRegressor(num_trees=5, max_depth=3),
    OpGeneralizedLinearRegression(),
]


@pytest.mark.parametrize(
    "est", CLS_MODELS, ids=[type(m).__name__ for m in CLS_MODELS]
)
def test_numpy_predict_parity_classification(est, rng):
    X = rng.randn(200, 6)
    X[:, 3:] = np.abs(X[:, 3:])  # NB wants non-negative-ish inputs
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(200) > 0).astype(float)
    params = est.fit_arrays(X, y)
    pred_j, raw_j, prob_j = est.predict_arrays(params, X)
    pred_n, raw_n, prob_n = est.predict_arrays_np(params, X)
    np.testing.assert_allclose(pred_j, pred_n, atol=1e-5)
    if prob_j is not None:
        np.testing.assert_allclose(prob_j, prob_n, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize(
    "est", REG_MODELS, ids=[type(m).__name__ for m in REG_MODELS]
)
def test_numpy_predict_parity_regression(est, rng):
    X = rng.randn(200, 6)
    y = X[:, 0] - 2.0 * X[:, 1] + 0.1 * rng.randn(200)
    params = est.fit_arrays(X, y)
    pred_j, _, _ = est.predict_arrays(params, X)
    pred_n, _, _ = est.predict_arrays_np(params, X)
    np.testing.assert_allclose(pred_j, pred_n, rtol=1e-4, atol=1e-5)


@needs_data
def test_local_scorer_titanic_parity_and_latency():
    wf, survived, prediction = titanic_workflow(reserve_test_fraction=0.0)
    model = wf.train()

    import csv

    fields = ["id", "survived", "pClass", "name", "sex", "age", "sibSp",
              "parCh", "ticket", "fare", "cabin", "embarked"]
    with open(TITANIC_CSV) as f:
        rows = [dict(zip(fields, r)) for r in csv.reader(f)]

    def to_record(row):
        num = lambda v: None if v in (None, "") else float(v)
        return {
            "pClass": row["pClass"] or None,
            "name": row["name"] or None,
            "sex": row["sex"] or None,
            "age": num(row["age"]),
            "sibSp": num(row["sibSp"]),
            "parCh": num(row["parCh"]),
            "ticket": row["ticket"] or None,
            "fare": num(row["fare"]),
            "cabin": row["cabin"] or None,
            "embarked": row["embarked"] or None,
            "survived": num(row["survived"]),
        }

    records = [to_record(r) for r in rows[:50]]

    scorer = score_function(model)
    assert isinstance(scorer, LocalScorer)

    # batch parity vs the device engine path (model.score on the same
    # records); model.score_function() is now the LocalScorer itself
    assert isinstance(model.score_function(), LocalScorer)
    local_out = scorer.score_batch(records)
    batch_data = {
        f.name: [r.get(f.name) for r in records]
        for f in model.raw_features
    }
    engine_out = model.score(batch_data)[prediction.name].to_list()
    for eng, loc in zip(engine_out[:10], local_out[:10]):
        le = loc[prediction.name]
        assert le["prediction"] == eng["prediction"]
        assert abs(le["probability_1"] - eng["probability_1"]) < 1e-5

    # per-record call works and is fast enough for serving loops
    t0 = time.perf_counter()
    out = [scorer(r) for r in records]
    per_rec_ms = (time.perf_counter() - t0) / len(records) * 1e3
    assert len(out) == len(records)
    assert per_rec_ms < 100, f"local scoring too slow: {per_rec_ms:.1f}ms"

    # streaming path
    streamed = list(scorer.score_stream(iter(records), batch_size=16))
    assert len(streamed) == len(records)
    assert streamed[0][prediction.name]["prediction"] == local_out[0][
        prediction.name
    ]["prediction"]


def test_fitted_transform_metadata_is_memoized(rng):
    """Row-serving perf contract (round-4: 70 -> 316 rows/s on the
    Titanic pipeline came from metadata memoization): a fitted
    vectorizer / combiner / checker must return the IDENTICAL metadata
    object across repeated transforms, and caches must not leak into
    saved models."""
    import numpy as np

    import transmogrifai_tpu.dsl  # noqa: F401
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.serialization.model_io import stage_state
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.types.columns import VectorColumn

    n = 120
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "c": [("u", "v")[i % 2] for i in range(n)],
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a, c])
    checked = y.sanity_check(vec, remove_bad_features=True)
    pred = OpLogisticRegression(reg_param=0.01).set_input(y, checked).get_output()
    model = OpWorkflow().set_result_features(pred).set_input_dataset(data).train()

    s1 = model.score(data)
    s2 = model.score(data)
    metas1 = {k: v.metadata for k, v in s1.columns().items()
              if isinstance(v, VectorColumn)}
    metas2 = {k: v.metadata for k, v in s2.columns().items()
              if isinstance(v, VectorColumn)}
    assert metas1  # vector stages present
    for k in metas1:
        assert metas1[k] is metas2[k], f"{k} metadata rebuilt per call"

    # caches never persist into the model writer's state
    for stage in model.stages:
        state = stage_state(stage)
        assert "_meta_cache" not in state
        assert "_combine_cache" not in state
        assert "_select_cache" not in state


def test_multinomial_model_serves_single_rows(rng):
    """The round-5 softmax model must serve through BOTH single-row
    surfaces (full-DAG score_function and the engine-free local scorer)
    with jointly-normalized probabilities."""
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.local import score_function
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    n = 240
    centers = np.array([[2.5, 0.0], [-2.5, 1.0], [0.0, -3.0]])
    yv = np.repeat(np.arange(3.0), n // 3)
    data = {
        "y": yv.tolist(),
        "a": (centers[yv.astype(int), 0] + 0.4 * rng.randn(n)).tolist(),
        "b": (centers[yv.astype(int), 1] + 0.4 * rng.randn(n)).tolist(),
    }
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fa = FeatureBuilder(ft.Real, "a").as_predictor()
    fb = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([fa, fb])
    pred = OpLogisticRegression(reg_param=0.01).set_input(fy, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    assert model.stages[-1].model_params["family"] == "multinomial"

    for fn in (model.score_function(), score_function(model)):
        out = fn({"a": 2.5, "b": 0.0})
        pcol = next(
            v for v in out.values()
            if isinstance(v, dict) and "prediction" in v
        )
        probs = [v for k, v in sorted(pcol.items())
                 if k.startswith("probability")]
        assert len(probs) == 3
        assert abs(sum(probs) - 1.0) < 1e-9
        assert pcol["prediction"] == 0.0
