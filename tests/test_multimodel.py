"""Multi-model serving drills (ISSUE 20; fleet/multimodel.py).

The fleet as a model-multiplexed platform: the per-replica
:class:`ModelTable` (weighted LRU over AOT executables - evict cold,
rehydrate by deserialize, never retrace), the router's per-model
dispatch/quotas, the cost-model-driven :class:`PlacementPlanner`
re-planned on membership changes, and the per-model canary lifecycle
(two hosted models canary concurrently; one promotes while the other
rolls back).  The ``fleet.model_evict_storm`` fault proves eviction
thrash stays rate-bounded.

All drills are seeded: the drill pipeline's data seed and deterministic
placement ties pin every run to the same schedule.
"""
from __future__ import annotations

import json
import os
import time

import pytest

from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.fleet import (
    FleetController,
    FleetRouter,
    ModelQuotaError,
    ModelTable,
    MultiModelError,
    PlacementPlanner,
    UnhostedModelError,
    UnknownModelError,
    format_models_arg,
    parse_models_arg,
)
from transmogrifai_tpu.fleet.multimodel import artifact_cache_bytes
from transmogrifai_tpu.registry import ModelRegistry
from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline

WORKFLOW_SPEC = "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline"


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# shared registry: one tiny trained model published as three versions
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def mm_registry(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("mm-registry"))
    wf, _data, records, pred_name = tiny_drill_pipeline()
    model = wf.train()
    reg = ModelRegistry(root)
    v1 = reg.publish(model, stage="stable")
    v2 = reg.publish(model)
    v3 = reg.publish(model)
    return {
        "root": root, "records": records, "pred_name": pred_name,
        "v1": v1.version, "v2": v2.version, "v3": v3.version,
    }


def _fresh_workflow():
    return tiny_drill_pipeline()[0]


def _table(mm_registry, **kw):
    kw.setdefault("batch_buckets", (1, 8, 32))
    reg = ModelRegistry(mm_registry["root"], create=False)
    return ModelTable(reg, _fresh_workflow, **kw)


def _wait_status(fc, cond, timeout_s=45.0):
    """Poll the controller's status doc until ``cond(doc)`` holds (the
    per-model rows fold from obs shards shipped on an interval)."""
    deadline = time.monotonic() + timeout_s
    while True:
        doc = fc.status()
        if cond(doc):
            return doc
        if time.monotonic() >= deadline:
            return doc  # let the caller's assert show the state
        time.sleep(0.2)


def _controller(mm_registry, tmp_path, n_replicas, **kw):
    kw.setdefault("router_kw", {})
    kw["router_kw"].setdefault("max_in_flight_per_replica", 2)
    kw["router_kw"].setdefault("max_queue", 64)
    return FleetController(
        mm_registry["root"], WORKFLOW_SPEC,
        n_replicas=n_replicas, work_dir=str(tmp_path / "fleet"),
        ship_interval_s=0.15, **kw,
    )


# ---------------------------------------------------------------------------
# the --models grammar (worker argv and controller must never drift)
# ---------------------------------------------------------------------------
def test_models_arg_roundtrip_and_rejects_blanks():
    models = {"alpha": "v1", "beta": "v2"}
    assert parse_models_arg(format_models_arg(models)) == models
    assert parse_models_arg(" alpha = v1 , beta=v2 ,") == models
    with pytest.raises(ValueError):
        parse_models_arg("alpha")  # no '='
    with pytest.raises(ValueError):
        parse_models_arg("=v1")
    with pytest.raises(ValueError):
        parse_models_arg(",,")


def test_artifact_cache_bytes_weighs_published_versions(mm_registry):
    reg = ModelRegistry(mm_registry["root"], create=False)
    w = artifact_cache_bytes(reg, mm_registry["v1"])
    assert w > 0
    assert artifact_cache_bytes(reg, "v999") == 0  # unknown weighs 0


# ---------------------------------------------------------------------------
# PlacementPlanner: deterministic, replicated, harmonic capacities
# ---------------------------------------------------------------------------
def test_placement_replicates_and_is_deterministic():
    planner = PlacementPlanner(replication=2)
    models = [
        {"model_id": "a", "weight_bytes": 300, "rows_per_s": 1000.0},
        {"model_id": "b", "weight_bytes": 200, "rows_per_s": 4000.0},
        {"model_id": "c", "weight_bytes": 100, "rows_per_s": 2000.0},
    ]
    insts = ["replica-0", "replica-1", "replica-2"]
    plan = planner.plan(models, insts)
    # every model lands on exactly `replication` replicas
    for m in ("a", "b", "c"):
        assert len(plan.hosts(m)) == 2, plan.assignments
    # deterministic: a fresh planner over the same input re-derives the
    # same assignments (re-planning must not shuffle gratuitously)
    again = PlacementPlanner(replication=2).plan(models, insts)
    assert again.assignments == plan.assignments
    assert plan.rev == 1 and planner.plan(models, insts).rev == 2


def test_placement_capacity_is_the_harmonic_blend():
    planner = PlacementPlanner(replication=1)
    models = [
        {"model_id": "fast", "rows_per_s": 4000.0},
        {"model_id": "slow", "rows_per_s": 1000.0},
    ]
    plan = planner.plan(models, ["only"])
    # one replica hosting both: 2 / (1/4000 + 1/1000) = 1600, NOT the
    # arithmetic mean 2500 - the slow model drags the achievable rate
    assert plan.replica_capacity("only") == pytest.approx(1600.0)
    assert plan.mean_capacity() == pytest.approx(1600.0)
    doc = plan.to_json()
    assert doc["assignments"]["only"] == ["fast", "slow"]
    assert doc["model_rows_s"]["slow"] == 1000.0


def test_placement_respects_cache_budget_headroom():
    planner = PlacementPlanner(replication=1, cache_budget_bytes=250)
    models = [
        {"model_id": "big", "weight_bytes": 200, "rows_per_s": 100.0},
        {"model_id": "mid", "weight_bytes": 150, "rows_per_s": 100.0},
        {"model_id": "sml", "weight_bytes": 40, "rows_per_s": 100.0},
    ]
    plan = planner.plan(models, ["replica-0", "replica-1"])
    by_inst = plan.pressure_bytes
    # first-fit-decreasing under the budget: no replica takes big+mid
    assert max(by_inst.values()) <= 250
    assert sorted(plan.hosts("big") + plan.hosts("mid")) == [
        "replica-0", "replica-1"]


def test_placement_refuses_an_empty_fleet():
    with pytest.raises(ValueError):
        PlacementPlanner().plan([{"model_id": "a"}], [])


# ---------------------------------------------------------------------------
# ModelTable: weighted LRU over AOT executables
# ---------------------------------------------------------------------------
def test_table_eviction_and_rehydration_counters_exact(mm_registry):
    table = _table(mm_registry, max_resident=1,
                   evict_min_interval_s=0.0)
    records = mm_registry["records"][:8]
    table.host("alpha", mm_registry["v1"])
    table.host("beta", mm_registry["v2"])
    # max_resident=1: hosting beta evicted alpha (LRU), exactly once
    rows = {r["model_id"]: r for r in table.rows()}
    assert rows["beta"]["resident"] and not rows["alpha"]["resident"]
    assert table.evictions == 1 and table.rehydrations == 0
    # a hit on the evicted model rehydrates from the artifact's AOT
    # cache (deserialize, not retrace) and is measured
    results, info = table.score("alpha", records)
    assert len(results) == 8 and info["model_id"] == "alpha"
    assert info["cold_hit"] is True and info["rehydrate_ms"] > 0
    assert table.rehydrations == 1 and table.cold_hits == 1
    snap = table.snapshot()
    assert snap["rehydrate_ms"]["p99"] is not None
    assert snap["cold_hit_ms"]["p99"] is not None
    # the rehydrate pushed beta out in turn; a warm re-hit on alpha is
    # NOT a cold hit
    rows = {r["model_id"]: r for r in table.rows()}
    assert rows["alpha"]["resident"] and not rows["beta"]["resident"]
    _results, info = table.score("alpha", records)
    assert "cold_hit" not in info
    assert table.cold_hits == 1


def test_table_unknown_model_is_loud(mm_registry):
    table = _table(mm_registry)
    table.host("alpha", mm_registry["v1"])
    with pytest.raises(UnknownModelError):
        table.score("ghost", mm_registry["records"][:4])
    assert table.unknown_model_errors == 1


def test_table_canary_pins_model_against_eviction(mm_registry):
    table = _table(mm_registry, max_resident=1,
                   evict_min_interval_s=0.0)
    table.host("alpha", mm_registry["v1"])
    table.start_canary("alpha", mm_registry["v3"], fraction=0.5)
    table.host("beta", mm_registry["v2"])
    # pressure wants alpha out (LRU) but its in-flight canary pins it
    rows = {r["model_id"]: r for r in table.rows()}
    assert rows["alpha"]["resident"]
    assert rows["alpha"]["canary_version"] == mm_registry["v3"]
    assert table.evictions == 0
    with pytest.raises(MultiModelError):
        table.unhost("alpha")  # pinned models cannot be dropped either
    gen = table.promote_canary("alpha")
    assert gen.version == mm_registry["v3"]
    rows = {r["model_id"]: r for r in table.rows()}
    assert rows["alpha"]["version"] == mm_registry["v3"]
    # promotion releases the pin: the next pressure wave can evict
    table.host("beta", mm_registry["v2"])
    assert table.evictions >= 1


def test_evict_storm_fault_is_rate_bounded(mm_registry):
    """``fleet.model_evict_storm`` demands an eviction on EVERY cache
    decision; the rate bound must absorb the storm into denied-eviction
    counters instead of thrashing the executable cache."""
    table = _table(mm_registry, evict_min_interval_s=60.0)
    records = mm_registry["records"][:4]
    table.host("alpha", mm_registry["v1"])
    table.host("beta", mm_registry["v2"])
    evictions_before = table.evictions
    faults.configure("fleet.model_evict_storm:every=1")
    try:
        for _ in range(4):
            r1, _ = table.score("alpha", records)
            r2, _ = table.score("beta", records)
            assert len(r1) == 4 and len(r2) == 4
    finally:
        faults.reset()
    # at most ONE eviction landed inside the 60s window; every other
    # storm demand was denied and counted
    assert table.evictions - evictions_before <= 1
    assert table.evictions_denied >= 3


# ---------------------------------------------------------------------------
# router: per-model dispatch, hosting fold, quotas (unit, fake replicas)
# ---------------------------------------------------------------------------
def _fake_router(model_quotas=None):
    import socket as socket_mod

    from transmogrifai_tpu.fleet.channel import FleetChannel
    from transmogrifai_tpu.fleet.router import ReplicaHandle

    router = FleetRouter(start=False, model_quotas=model_quotas)
    socks = []
    for i in range(2):
        a, b = socket_mod.socketpair(socket_mod.AF_UNIX,
                                     socket_mod.SOCK_STREAM)
        socks.append(b)
        router._handles[f"replica-{i}"] = ReplicaHandle(
            f"replica-{i}", FleetChannel(a))
    return router, socks


def test_router_dispatch_filters_on_hosting():
    router, _socks = _fake_router()
    try:
        router.set_hosting({"replica-0": ["alpha"],
                            "replica-1": ["beta"]})
        assert router.hosting_map() == {"replica-0": ["alpha"],
                                        "replica-1": ["beta"]}
        h = router._pick(8, model_id="beta")
        assert h is not None and h.instance == "replica-1"
        assert router._pick(8, model_id="alpha").instance == "replica-0"
        assert router._pick(8) is not None  # unpinned: anyone
    finally:
        router.close()


def test_router_unhosted_model_sheds_loudly():
    router, _socks = _fake_router()
    try:
        router.set_hosting({"replica-0": ["alpha"], "replica-1": []})
        with pytest.raises(UnhostedModelError):
            router.submit(records=[{"a": 1.0}], model_id="ghost")
        assert router.snapshot()["unhosted_model_errors"] == 1
    finally:
        router.close()


def test_router_per_model_quota_bounds_in_flight_rows():
    router, _socks = _fake_router(model_quotas={"alpha": 8})
    try:
        router.set_hosting({"replica-0": ["alpha", "beta"],
                            "replica-1": ["alpha", "beta"]})
        records = [{"a": float(i)} for i in range(6)]
        router.submit(records=records, model_id="alpha")
        # 6 rows in flight (the fakes never answer); 6 + 6 > 8 -> shed
        with pytest.raises(ModelQuotaError):
            router.submit(records=records, model_id="alpha")
        # quota is per model: beta is unaffected
        router.submit(records=records, model_id="beta")
        snap = router.snapshot()
        assert snap["shed_model_quota"] == 1
    finally:
        router.close()


def test_refresh_from_shards_folds_hosting_from_replica_view():
    router, _socks = _fake_router()
    try:
        docs = [
            {"instance": "replica-0",
             "views": {
                 "serving/0": {"batch_rows_per_s": 1000.0,
                               "latency_ms": {"p99": 4.0},
                               "queue_depth": {}, "rows_scored": 10},
                 "fleet_replica/0": {
                     "models": [{"model_id": "alpha"},
                                {"model_id": "gamma"}]},
             }},
        ]
        router.refresh_from_shards(docs)
        h = router._handles["replica-0"]
        assert h.hosted_models == {"alpha", "gamma"}
    finally:
        router.close()


# ---------------------------------------------------------------------------
# end-to-end: a model-multiplexed fleet
# ---------------------------------------------------------------------------
def test_multimodel_fleet_dispatch_quota_and_status(mm_registry,
                                                    tmp_path):
    records = mm_registry["records"]
    models = {"alpha": mm_registry["v1"], "beta": mm_registry["v2"]}
    with _controller(
            mm_registry, tmp_path, 2, models=models,
            router_kw={"model_quotas": {"beta": 4096}}) as fc:
        assert fc.placement is not None and fc.placement.rev >= 1
        # both replicas host both models (replication=2, width 2)
        assert sorted(fc.model_hosts("alpha")) == sorted(
            fc.model_hosts("beta"))
        out_a = fc.router.score_batch(records[:24], model_id="alpha")
        out_b = fc.router.score_batch(records[:16], model_id="beta")
        assert len(out_a) == 24 and len(out_b) == 16
        assert all(isinstance(r, dict) for r in out_a + out_b)
        with pytest.raises(UnhostedModelError):
            fc.router.score_batch(records[:4], model_id="ghost")
        status = _wait_status(fc, lambda d: (
            d.get("models", {}).get("alpha", {}).get("rows_scored")
            == 24
            and len(d["models"]["alpha"].get("hosts", [])) == 2))
        rows = status["models"]
        assert set(rows) == {"alpha", "beta"}
        assert rows["alpha"]["version"] == mm_registry["v1"]
        assert rows["alpha"]["rows_delivered"] == 24
        assert rows["beta"]["rows_delivered"] == 16
        assert len(rows["alpha"]["hosts"]) == 2
        assert status["placement"]["rev"] == fc.placement.rev
        assert status["router"]["rows_by_model"] == {
            "alpha": 24, "beta": 16}
        # per-replica table rows summed per model
        assert rows["alpha"]["rows_scored"] == 24
        # the status doc is what fleet_status.json carries: the CLI's
        # per-model rows come straight from it
        fc._write_status()
        doc = json.load(open(os.path.join(
            fc.control_dir, "fleet_status.json")))
        assert set(doc["models"]) == {"alpha", "beta"}


def test_concurrent_canaries_one_promotes_one_rolls_back(mm_registry,
                                                         tmp_path):
    """Two hosted models run INDEPENDENT canary lifecycles at once:
    alpha's canary promotes while beta's rolls back, with zero dropped
    rows on either model throughout."""
    records = mm_registry["records"]
    models = {"alpha": mm_registry["v1"], "beta": mm_registry["v2"]}
    with _controller(mm_registry, tmp_path, 2, models=models) as fc:
        fc.start_model_canary("alpha", mm_registry["v3"], fraction=0.5)
        fc.start_model_canary("beta", mm_registry["v3"], fraction=0.5)
        assert fc.model_canaries == {"alpha": mm_registry["v3"],
                                     "beta": mm_registry["v3"]}
        for _ in range(3):
            assert len(fc.router.score_batch(
                records[:16], model_id="alpha")) == 16
            assert len(fc.router.score_batch(
                records[:16], model_id="beta")) == 16
        fc.promote_model_canary("alpha")
        fc.rollback_model_canary("beta", reason="drill")
        assert fc.models["alpha"] == mm_registry["v3"]
        assert fc.models["beta"] == mm_registry["v2"]
        assert fc.model_canaries == {}
        # both models keep serving after their (opposite) verdicts
        out_a = fc.router.score_batch(records[:8], model_id="alpha")
        out_b = fc.router.score_batch(records[:8], model_id="beta")
        assert len(out_a) == 8 and len(out_b) == 8
        rows = _wait_status(fc, lambda d: (
            d.get("models", {}).get("alpha", {}).get("version")
            == mm_registry["v3"]))["models"]
        assert rows["alpha"]["version"] == mm_registry["v3"]
        assert rows["beta"]["version"] == mm_registry["v2"]
        assert rows["alpha"]["canary_version"] is None
        assert rows["beta"]["canary_version"] is None


def test_scale_up_replans_placement_and_hosts_on_new_replica(
        mm_registry, tmp_path):
    records = mm_registry["records"]
    models = {"alpha": mm_registry["v1"], "beta": mm_registry["v2"]}
    with _controller(mm_registry, tmp_path, 1, models=models) as fc:
        rev0 = fc.placement.rev
        assert fc.model_hosts("alpha") == ["replica-0"]
        inst = fc.add_replica()
        assert fc.placement.rev > rev0
        assert inst in fc.placement.assignments
        # the new replica converged onto its assigned models: ask IT
        doc = fc.router.control(inst, "models", timeout_s=60.0)
        hosted = {r["model_id"] for r in doc["table"]["models"]}
        assert hosted == set(fc.placement.models_for(inst))
        assert len(fc.router.score_batch(records[:16],
                                         model_id="alpha")) == 16


# ---------------------------------------------------------------------------
# bulk scoring selects a hosted model (satellite)
# ---------------------------------------------------------------------------
def test_bulk_job_scores_one_hosted_model(mm_registry, tmp_path):
    from transmogrifai_tpu.bulk import BulkScoringJob
    from transmogrifai_tpu.testkit.drills import write_shard_csv
    from transmogrifai_tpu.utils.uid import reset_uids

    reset_uids()
    wf, data, _records, _pred = tiny_drill_pipeline(n=80, seed=0)
    model = wf.train()
    rows = [{"y": data["y"][i], "a": data["a"][i], "c": data["c"][i]}
            for i in range(80)]
    shards = []
    for k in range(2):
        p = str(tmp_path / f"in-{k}.csv")
        write_shard_csv(p, rows[k * 40:(k + 1) * 40])
        shards.append(p)
    models = {"alpha": mm_registry["v1"], "beta": mm_registry["v2"]}
    with _controller(mm_registry, tmp_path, 2, models=models) as fc:
        jd = str(tmp_path / "job")
        job = BulkScoringJob(model, jd, shards, router=fc.router,
                             model_id="alpha", chunk_rows=16, workers=1)
        summary = job.run()
        led = summary["ledger"]
        assert led["rows_in"] == 80 and led["rows_out"] == 80
        assert led["rows_in"] == led["rows_out"] + led["rows_quarantined"]
        # the journal records which model scored the job
        doc = json.load(open(os.path.join(jd, "journal.json")))
        assert doc["params"]["model_id"] == "alpha"
        # every delivered row was attributed to alpha
        assert fc.router.snapshot()["rows_by_model"].get("alpha") == 80
        # an unhosted model fails LOUDLY before any scoring
        job2 = BulkScoringJob(model, str(tmp_path / "job2"), shards,
                              router=fc.router, model_id="ghost",
                              chunk_rows=16, workers=1)
        with pytest.raises(UnhostedModelError):
            job2.run()
    with pytest.raises(ValueError):
        BulkScoringJob(model, str(tmp_path / "job3"), shards,
                       model_id="alpha")  # model_id needs a fleet


# ---------------------------------------------------------------------------
# observability: the model_id label rides the Prometheus exposition
# ---------------------------------------------------------------------------
def test_prometheus_exposition_carries_model_id_label():
    from transmogrifai_tpu.obs import prometheus_text_from_json
    from transmogrifai_tpu.serving import ServingTelemetry

    tel = ServingTelemetry()
    tel.set_model_id("alpha")
    tel.record_batch(8, 8, 0.002)
    doc = {"views": {"serving/0": tel.snapshot()}, "series": {}}
    text = prometheus_text_from_json(doc)
    lines = [ln for ln in text.splitlines()
             if "rows_scored" in ln and not ln.startswith("#")]
    assert lines and all('model_id="alpha"' in ln for ln in lines)


def test_autoscaler_sizes_from_heterogeneous_capacity_mix():
    """Satellite: with a placement plan the autoscaler sizes from the
    per-replica capacity MIX, not ceil(demand / one-capacity)."""
    from transmogrifai_tpu.fleet.autoscaler import FleetAutoscaler
    from transmogrifai_tpu.fleet.multimodel import PlacementPlan

    class _Ctl:
        placement = PlacementPlan(
            assignments={"replica-0": ["a"], "replica-1": ["b"]},
            capacity_rows_s={"replica-0": 3000.0, "replica-1": 1000.0},
            model_rows_s={"a": 3000.0, "b": 1000.0})
    scaler = FleetAutoscaler.__new__(FleetAutoscaler)
    scaler.controller = _Ctl()
    scaler.target_utilization = 0.5
    scaler.max_replicas = 8
    capacity = {"per_replica_rows_s": 2000.0}
    mix = scaler._capacity_mix(["replica-0", "replica-1"], [], capacity)
    # ratios follow the plan, anchored to the observed absolute level
    # (mean of the mix == the waterfall estimate)
    assert mix["replica-0"] == pytest.approx(3000.0)
    assert mix["replica-1"] == pytest.approx(1000.0)
    # demand 2500 at 50% target needs 5000 rows/s of capacity: the
    # 3000 replica + the 1000 replica + one assumed-mean addition
    n = scaler._sized_target({
        "demand_rows_s": 2500.0, "capacity_mix": mix,
        "capacity": {"per_replica_rows_s": 2000.0}})
    assert n == 3
    # homogeneous fallback (no plan): byte-for-byte the old rule
    n = scaler._sized_target({
        "demand_rows_s": 2500.0, "capacity_mix": {},
        "capacity": {"per_replica_rows_s": 2000.0}})
    assert n == 3  # ceil(2500 / (2000 * 0.5))
