"""Conditional + joined reader depth.

Reference semantics: ConditionalDataReader cuts each key at its first (or
last) event matching targetCondition, keys with no match are dropped
(DataReader.scala:283-345), responses confined to responseWindow after
the cutoff; JoinedDataReader inner/left/outer joins align on the
aggregation key with nulls for unmatched rows (JoinedDataReader.scala:
124-214).
"""
from __future__ import annotations

import pytest

from transmogrifai_tpu.features.feature_builder import FeatureBuilder
from transmogrifai_tpu.readers.events import (
    AggregateReader,
    ConditionalReader,
    JoinedReader,
)
from transmogrifai_tpu.types import feature_types as ft

EVENTS = [
    {"u": "a", "ts": 1.0, "page": "home", "spend": 1.0},
    {"u": "a", "ts": 5.0, "page": "buy", "spend": 10.0},   # condition
    {"u": "a", "ts": 6.0, "page": "home", "spend": 2.0},
    {"u": "a", "ts": 50.0, "page": "home", "spend": 4.0},  # beyond window
    {"u": "b", "ts": 2.0, "page": "home", "spend": 7.0},   # never converts
    {"u": "c", "ts": 3.0, "page": "buy", "spend": 5.0},    # converts at first event
    {"u": "c", "ts": 9.0, "page": "buy", "spend": 6.0},    # second match ignored (use_first)
]


def _features():
    pre = FeatureBuilder(ft.Real, "spend").extract(
        lambda r: r["spend"]
    ).as_predictor()
    post = FeatureBuilder(ft.Real, "spend_after").extract(
        lambda r: r["spend"]
    ).as_response()
    return pre, post


def _reader(**kw):
    return ConditionalReader(
        EVENTS, key_fn=lambda r: r["u"], time_fn=lambda r: r["ts"],
        target_condition=lambda r: r["page"] == "buy", **kw
    )


def test_conditional_drops_keys_without_condition():
    r = _reader()
    assert r.row_keys() == ["a", "c"]  # b never matched


def test_conditional_keeps_unmatched_keys_when_not_dropping():
    r = _reader(drop_if_no_condition=False)
    assert r.row_keys() == ["a", "b", "c"]
    pre, post = _features()
    ds = r.generate_dataset([pre, post])
    i = r.row_keys().index("b")
    # no cutoff for b: everything is both predictor- and response-side
    assert ds["spend"].values[i] == 7.0


def test_conditional_cutoff_splits_predictors_and_responses():
    pre, post = _features()
    r = _reader(response_window=10.0)
    ds = r.generate_dataset([pre, post])
    keys = r.row_keys()
    a = keys.index("a")
    # predictors strictly before ts=5 (the buy): only ts=1 -> 1.0
    assert ds["spend"].values[a] == 1.0
    # responses in [5, 15]: 10 + 2; the ts=50 event is out of window
    assert ds["spend_after"].values[a] == 12.0


def test_conditional_first_vs_last_match():
    pre, post = _features()
    r_last = _reader(use_first=False, response_window=100.0)
    ds = r_last.generate_dataset([pre, post])
    c = r_last.row_keys().index("c")
    # cutoff at the LAST buy (ts=9): predictor side sums ts=3 event
    assert ds["spend"].values[c] == 5.0
    assert ds["spend_after"].values[c] == 6.0


def test_conditional_key_converting_at_first_event_has_null_predictors():
    pre, post = _features()
    r = _reader(response_window=100.0)
    ds = r.generate_dataset([pre, post])
    c = r.row_keys().index("c")
    assert not ds["spend"].mask[c]  # nothing strictly before the cutoff
    assert ds["spend_after"].values[c] == 11.0  # both buys in window


# --- joins -----------------------------------------------------------------


def _join_readers():
    sends = [
        {"u": "a", "ts": 1.0, "n": 1.0},
        {"u": "a", "ts": 2.0, "n": 1.0},
        {"u": "b", "ts": 1.5, "n": 1.0},
    ]
    clicks = [
        {"u": "a", "ts": 1.1, "c": 1.0},
        {"u": "z", "ts": 1.2, "c": 1.0},  # clicker with no sends
    ]
    l = AggregateReader(sends, key_fn=lambda r: r["u"], time_fn=lambda r: r["ts"])
    r = AggregateReader(clicks, key_fn=lambda r: r["u"], time_fn=lambda r: r["ts"])
    n = FeatureBuilder(ft.Real, "n").extract(lambda rec: rec.get("n")).as_predictor()
    c = FeatureBuilder(ft.Real, "c").extract(lambda rec: rec.get("c")).as_predictor()
    return l, r, n, c


@pytest.mark.parametrize("join_type,expect_pattern", [
    # (n present, c present) per joined row, as a sorted multiset:
    ("inner", [(True, True)]),                           # a only
    ("left", [(True, False), (True, True)]),             # a, b
    ("outer", [(False, True), (True, False), (True, True)]),  # a, b, z
])
def test_join_types_null_patterns(join_type, expect_pattern):
    l, r, n, c = _join_readers()
    jr = JoinedReader(l, r, left_key="u", join_type=join_type)
    ds = jr.generate_dataset([n, c])
    n_col, c_col = ds["n"], ds["c"]
    pattern = sorted(zip(n_col.mask.tolist(), c_col.mask.tolist()))
    assert pattern == sorted(expect_pattern)


def test_left_join_nulls_for_unmatched_right():
    l, r, n, c = _join_readers()
    jr = JoinedReader(l, r, left_key="u", join_type="left")
    ds = jr.generate_dataset([n, c])
    n_col, c_col = ds["n"], ds["c"]
    vals = sorted(zip(n_col.mask.tolist(), c_col.mask.tolist()))
    # user b: has sends (n=1), no clicks -> c null
    assert (True, False) in vals
    # user a: both sides present
    assert (True, True) in vals
