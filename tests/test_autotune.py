"""Cost-model-driven autotuning drills (ISSUE 13): successive-halving
selection parity + floor, cost-model training/persistence from the obs
plane, knob proposals and A/B probes, runner/CLI wiring."""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu.autotune import (
    AutotuneConfig,
    CostModel,
    KnobDecision,
    KnobTuner,
    candidate_features,
    key_for_fit,
    microbatch_candidates,
    params_hash,
    propose_bucket_edges,
    propose_pipeline_knobs,
    report_from_path,
)
from transmogrifai_tpu.evaluators.binary import (
    OpBinaryClassificationEvaluator,
)
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.trees import OpRandomForestClassifier
from transmogrifai_tpu.obs import trace as obs_trace
from transmogrifai_tpu.obs.metrics import (
    metrics_registry,
    prometheus_text_from_json,
)
from transmogrifai_tpu.selector.validator import OpCrossValidation


# ---------------------------------------------------------------------------
# shared fixtures
# ---------------------------------------------------------------------------
def _binary_arrays(n=40_000, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    beta = np.linspace(1.5, -1.5, d)
    y = (rng.rand(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(float)
    return X, y


def _models():
    lr_grid = [{"reg_param": r, "elastic_net_param": e}
               for r in (0.001, 0.1) for e in (0.1, 0.5)]
    rf_grid = [{"min_info_gain": g} for g in (0.001, 0.01, 0.1)]
    return [
        (OpLogisticRegression(), lr_grid),
        (OpRandomForestClassifier(num_trees=4, max_depth=3), rf_grid),
    ]


def _warm_cost_model(cm, families, d=8):
    """Synthetic multi-scale observations: what a production deployment
    accumulates across runs (walls scale with rows)."""
    for fam, base_ms in families:
        for rows in (4_000, 8_000, 20_000, 40_000):
            cm.observe(
                key_for_fit(fam),
                candidate_features(rows, d, {}, 0.5, folds=1.0),
                base_ms * rows / 40_000,
            )


def _warmed_config(**kw):
    cm = CostModel()
    _warm_cost_model(cm, [("OpLogisticRegression", 60.0),
                          ("OpRandomForestClassifier", 400.0)])
    kw.setdefault("rung_rows", 8_000)
    kw.setdefault("min_rows", 10_000)
    return AutotuneConfig(cost_model=cm, **kw)


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------
def test_cost_model_learns_row_scaling_and_roundtrips(tmp_path):
    cm = CostModel()
    key = key_for_fit("OpLogisticRegression")
    assert cm.predict_wall_ms(key, candidate_features(1000, 8)) is None
    for rows in (1_000, 4_000, 16_000, 64_000, 256_000):
        cm.observe(key, candidate_features(rows, 8), 0.01 * rows)
    lo = cm.predict_wall_ms(key, candidate_features(2_000, 8))
    hi = cm.predict_wall_ms(key, candidate_features(128_000, 8))
    assert lo is not None and hi is not None and hi > lo > 0
    p = str(tmp_path / "autotune.json")
    cm.save(p)
    cm2 = CostModel.load(p)
    assert cm2.load_error is None
    assert cm2.n_observations(key) == cm.n_observations(key)
    assert cm2.predict_wall_ms(
        key, candidate_features(128_000, 8)) == pytest.approx(hi)


def test_cost_model_load_tolerates_missing_and_torn(tmp_path):
    cold = CostModel.load(str(tmp_path / "missing.json"))
    assert cold.n_observations() == 0 and cold.load_error is None
    torn = tmp_path / "torn.json"
    torn.write_text('{"version": 1, "keys": {"fit:')
    cm = CostModel.load(str(torn))
    assert cm.n_observations() == 0
    assert cm.load_error and "Error" in cm.load_error
    # version mismatch: cold + named, never mis-predicting
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({"version": 999, "keys": {}}))
    cm3 = CostModel.load(str(stale))
    assert cm3.load_error == "version_mismatch"
    assert cm3.n_observations() == 0


def test_cost_model_trains_from_tagged_validator_spans():
    """Satellite 1: the spans OpValidator tags (family, params_hash,
    fold, n_rows, n_features) are sufficient to train the cost model
    from any exported ring - and re-ingesting the same ring dedupes."""
    obs_trace.reset_tracer()
    X, y = _binary_arrays(n=6_000)
    cv = OpCrossValidation(
        num_folds=2, evaluator=OpBinaryClassificationEvaluator(),
        seed=7, stratify=True)
    cv.validate(_models(), X, y)
    records = obs_trace.tracer().spans()
    fit_spans = [r for r in records if r["name"].startswith("cv.fit")]
    assert fit_spans, "validator did not tag fit spans"
    for r in fit_spans:
        attrs = r["attrs"]
        assert attrs["family"] in ("OpLogisticRegression",
                                   "OpRandomForestClassifier")
        assert attrs["n_rows"] > 0 and attrs["n_features"] == 8
        if r["name"] != "cv.fit_batch":
            assert "params_hash" in attrs
        if r["name"] == "cv.fit":
            assert "fold" in attrs
    cm = CostModel(min_obs=2)
    added = cm.ingest_spans(records)
    # rung-fit spans are deliberately NOT ingested (the validator
    # observes those fits directly; re-ingesting would double-count)
    assert added == len([
        r for r in records
        if r["name"] in ("cv.fit", "cv.fit_folds", "cv.fit_batch",
                         "serve.batch")
    ])
    assert cm.ingest_spans(records) == 0  # dedupe on re-ingest
    assert cm.n_observations(key_for_fit("OpRandomForestClassifier")) > 0


def test_params_hash_stable_and_order_free():
    a = params_hash({"x": 1, "y": 2.0})
    b = params_hash({"y": 2.0, "x": 1})
    assert a == b and len(a) == 12
    assert params_hash({"x": 2, "y": 2.0}) != a


# ---------------------------------------------------------------------------
# successive-halving selection
# ---------------------------------------------------------------------------
def test_cold_start_degrades_to_exhaustive_with_reason():
    """Satellite drill: first run (no observations) must take the
    exhaustive path, record why, and return the identical result."""
    X, y = _binary_arrays()
    ev = OpBinaryClassificationEvaluator()
    res_ex = OpCrossValidation(
        num_folds=3, evaluator=ev, seed=7, stratify=True,
    ).validate(_models(), X, y)
    cfg = AutotuneConfig(cost_model=CostModel(), rung_rows=8_000,
                         min_rows=10_000)
    cv = OpCrossValidation(num_folds=3, evaluator=ev, seed=7,
                           stratify=True, autotune=cfg)
    res = cv.validate(_models(), X, y)
    rep = cv.last_autotune_report
    assert rep["mode"] == "exhaustive"
    assert rep["reason"].startswith("cost_model_cold:")
    assert "OpLogisticRegression" in rep["reason"]
    assert rep["fits"]["total"] == rep["fits"]["exhaustive"]
    assert res.best_params == res_ex.best_params
    assert res.best_metric == res_ex.best_metric
    assert res.autotune is rep


def test_pruned_selection_parity_and_floor():
    """The selection-parity drill at tier-1 scale (the 2M version is
    the AUTOTUNE_BENCH acceptance artifact): pruning enabled must
    return the same winner family/params and AUROC within 1e-9 of the
    exhaustive sweep, while never evaluating more candidate-fold fits
    than the exhaustive count (the floor)."""
    X, y = _binary_arrays()
    ev = OpBinaryClassificationEvaluator()
    res_ex = OpCrossValidation(
        num_folds=3, evaluator=ev, seed=7, stratify=True,
    ).validate(_models(), X, y)
    cfg = _warmed_config()
    cv = OpCrossValidation(num_folds=3, evaluator=ev, seed=7,
                           stratify=True, autotune=cfg)
    res = cv.validate(_models(), X, y)
    rep = cv.last_autotune_report
    assert rep["mode"] == "pruned"
    assert rep["candidates_pruned"] > 0
    # the tier-1 FLOOR: pruned total fits never exceed exhaustive
    assert rep["fits"]["total"] <= rep["fits"]["exhaustive"]
    assert rep["fits"]["total"] == (
        rep["fits"]["rung"] + rep["fits"]["full"])
    # parity: winner family + params identical, AUROC within 1e-9
    assert (res.best_estimator.model_type
            == res_ex.best_estimator.model_type)
    assert res.best_params == res_ex.best_params
    assert abs(res.best_metric - res_ex.best_metric) <= 1e-9
    # the decision trail carries predicted-vs-actual evidence
    assert rep["predicted_speedup"] is not None
    assert rep["actual_full_ms_by_family"]
    for c in rep["rungs"]:
        assert c["rung_wall_ms"] is not None
        assert c["predicted_fit_ms"] is not None
        assert c["params_hash"]
    # pruned candidates visible (flagged) in all_results, never winners
    pruned = [r for r in res.all_results if r.get("pruned")]
    assert len(pruned) == rep["candidates_pruned"]
    assert all(r["metric_kind"] == "rung" for r in pruned)


def test_pruned_selection_visible_in_obs_plane():
    """Acceptance: pruning decisions scrape via the metrics registry
    (tx_autotune_*) and the decision event rides the trace."""
    obs_trace.reset_tracer()
    X, y = _binary_arrays(n=20_000)
    cfg = _warmed_config()
    cv = OpCrossValidation(
        num_folds=3, evaluator=OpBinaryClassificationEvaluator(),
        seed=7, stratify=True, autotune=cfg)
    cv.validate(_models(), X, y)
    doc = metrics_registry().to_json()
    assert doc["series"]["autotune.selections"]["value"] >= 1
    assert doc["series"]["autotune.candidates_pruned"]["value"] > 0
    text = prometheus_text_from_json(doc)
    assert "tx_autotune_selections" in text
    assert "tx_autotune_candidates_pruned" in text
    names = {s["name"] for s in obs_trace.tracer().spans()}
    assert "autotune.decision" in names
    assert "autotune.rung_fit" in names


def test_winner_ties_break_identically_with_autotune_on_and_off():
    """RandomParamBuilder satellite: duplicated grid points produce
    exact metric ties; the FIRST candidate must win in both modes
    (survivors keep original grid order, rank ties break by index)."""
    X, y = _binary_arrays(n=20_000)
    p = {"reg_param": 0.01, "elastic_net_param": 0.1}
    grid = [dict(p), dict(p), {"reg_param": 0.2, "elastic_net_param": 0.5}]
    models = [(OpLogisticRegression(), grid)]
    ev = OpBinaryClassificationEvaluator()
    res_off = OpCrossValidation(
        num_folds=2, evaluator=ev, seed=7, stratify=True,
    ).validate(models, X, y)
    cm = CostModel()
    _warm_cost_model(cm, [("OpLogisticRegression", 60.0)])
    cfg = AutotuneConfig(cost_model=cm, rung_rows=6_000,
                         min_rows=10_000, min_keep=2)
    res_on = OpCrossValidation(
        num_folds=2, evaluator=ev, seed=7, stratify=True, autotune=cfg,
    ).validate(models, X, y)
    assert res_off.best_params == res_on.best_params == p


def test_random_param_builder_same_seed_same_order():
    from transmogrifai_tpu.selector.random_param_builder import (
        RandomParamBuilder,
    )

    def build(n):
        return (
            RandomParamBuilder(seed=11)
            .log_uniform("reg_param", 1e-4, 1.0)
            .choice("elastic_net_param", [0.1, 0.5])
            .int_uniform("max_depth", 2, 12)
            .build(n)
        )

    assert build(6) == build(6)
    # grid identity is call-history-free: a builder that already drew a
    # DIFFERENT count still reproduces the same next-call stream
    b1 = (RandomParamBuilder(seed=11)
          .log_uniform("reg_param", 1e-4, 1.0))
    b1.build(9)
    b2 = (RandomParamBuilder(seed=11)
          .log_uniform("reg_param", 1e-4, 1.0))
    b2.build(2)
    assert b1.build(3) == b2.build(3)


def test_tiny_grid_degrades_rather_than_undercut_min_keep():
    """g=2, k=3: the fits-floor clamp allows only 1 survivor, below
    min_keep=2 - the plan must degrade to exhaustive, never keep
    fewer survivors than the contract promises."""
    X, y = _binary_arrays(n=20_000)
    cm = CostModel()
    _warm_cost_model(cm, [("OpLogisticRegression", 60.0)])
    cfg = AutotuneConfig(cost_model=cm, rung_rows=6_000, min_rows=10_000)
    grid = [{"reg_param": r, "elastic_net_param": 0.1}
            for r in (0.001, 0.1)]
    cv = OpCrossValidation(
        num_folds=3, evaluator=OpBinaryClassificationEvaluator(),
        seed=7, stratify=True, autotune=cfg)
    cv.validate([(OpLogisticRegression(), grid)], X, y)
    rep = cv.last_autotune_report
    assert rep["mode"] == "exhaustive"
    assert rep["reason"] == "no_fit_budget"
    assert rep["fits"]["total"] == rep["fits"]["exhaustive"]


def test_single_fold_validator_never_prunes():
    """k=1 has no fit budget for a rung (g + s*1 can never undercut
    g*1): the plan must degrade, keeping the floor invariant."""
    from transmogrifai_tpu.selector.validator import (
        OpTrainValidationSplit,
    )

    X, y = _binary_arrays(n=20_000)
    cfg = _warmed_config()
    tv = OpTrainValidationSplit(
        evaluator=OpBinaryClassificationEvaluator(), seed=7,
        stratify=True, autotune=cfg)
    tv.validate(_models(), X, y)
    rep = tv.last_autotune_report
    assert rep["mode"] == "exhaustive"
    assert rep["reason"] == "too_few_folds"
    assert rep["fits"]["total"] == rep["fits"]["exhaustive"]


# ---------------------------------------------------------------------------
# knob tuning
# ---------------------------------------------------------------------------
def test_ab_probe_keeps_baseline_on_tie_and_picks_clear_winner():
    tuner = KnobTuner(margin=0.05, repeats=1)
    base = {"max_batch_size": 128, "max_wait_us": 2000}
    better = {"max_batch_size": 256, "max_wait_us": 1000}
    worse = {"max_batch_size": 64, "max_wait_us": 4000}

    def measure_tied(knobs):
        return 1000.0  # identical everywhere: hand-set default holds

    d = tuner.ab_probe("s", base, [better, worse], measure_tied)
    assert isinstance(d, KnobDecision)
    assert not d.tuned and d.winner == base

    def measure(knobs):
        return 2000.0 if knobs == better else 1000.0

    d2 = tuner.ab_probe("s", base, [better, worse], measure)
    assert d2.tuned and d2.winner == better
    assert len(d2.probes) == 3
    assert [p["is_baseline"] for p in d2.probes] == [True, False, False]
    # a candidate whose probe raises is recorded, never crashes the run
    def measure_err(knobs):
        if knobs == worse:
            raise RuntimeError("bad knobs")
        return 1000.0

    d3 = tuner.ab_probe("s", base, [better, worse], measure_err)
    assert d3.probes[2]["error"] and not d3.tuned
    # an arm that errors on a LATER repeat is disqualified even though
    # an earlier repeat measured well - flaky configs never win
    calls = {"n": 0}

    def measure_flaky(knobs):
        if knobs == better:
            calls["n"] += 1
            if calls["n"] > 1:
                raise RuntimeError("intermittent")
            return 9999.0
        return 1000.0

    d4 = KnobTuner(margin=0.05, repeats=2).ab_probe(
        "s", base, [better], measure_flaky)
    assert not d4.tuned and d4.winner == base


def test_ab_probe_records_obs_gauges():
    tuner = KnobTuner(margin=0.01, repeats=1)
    base = {"max_wait_us": 2000}
    d = tuner.ab_probe(
        "unit.scope", base, [{"max_wait_us": 500}],
        lambda k: 1.0 / (1 + k["max_wait_us"]))
    assert d.tuned
    doc = metrics_registry().to_json()
    assert doc["series"]["autotune.knob.unit.scope.max_wait_us"][
        "value"] == 500.0
    assert doc["series"]["autotune.knob.unit.scope.tuned"]["value"] == 1.0


def test_microbatch_candidates_surround_defaults():
    base = {"max_batch_size": 128, "max_wait_us": 2000}
    cands = microbatch_candidates(base)
    assert base not in cands and cands
    sizes = {c["max_batch_size"] for c in cands}
    assert sizes <= {64, 128, 256}
    assert all(c["max_wait_us"] in (1000, 2000, 4000) for c in cands)


def test_propose_bucket_edges_covers_observed_spread():
    edges = propose_bucket_edges([3, 7, 20, 90, 110])
    assert edges[0] == 1 and edges[-1] >= 110
    assert list(edges) == sorted(set(edges))
    assert all(e & (e - 1) == 0 for e in edges)  # powers of two
    assert propose_bucket_edges([]) == (1, 8, 32, 128)
    assert len(propose_bucket_edges(range(1, 3000), max_buckets=5)) <= 5
    # the TOP edge survives overflow trimming (review repro): dropping
    # it would re-pad exactly the large batches the spread came from
    wide = propose_bucket_edges([2, 5, 17, 65, 257, 1000], max_buckets=5)
    assert len(wide) <= 5 and wide[0] == 1 and wide[-1] >= 1000
    assert propose_bucket_edges(range(1, 3000), max_buckets=5)[-1] >= 2999
    # observed sizes past the cap clamp to it instead of crashing
    assert propose_bucket_edges([5000])[-1] == 4096


def test_propose_pipeline_knobs_follows_stall_signals():
    cur = {"workers": 4, "buffer_chunks": 8}
    # consumer starved -> more parsers + deeper buffer
    starved = {"producer_busy_s": 10.0, "producer_stall_s": 0.1,
               "consumer_stall_s": 5.0}
    prop = propose_pipeline_knobs(starved, cur)
    assert prop["workers"] == 8 and prop["buffer_chunks"] == 16
    # producers blocked on a full buffer -> fewer parsers
    blocked = {"producer_busy_s": 10.0, "producer_stall_s": 6.0,
               "consumer_stall_s": 0.1}
    prop2 = propose_pipeline_knobs(blocked, cur)
    assert prop2["workers"] == 2
    # balanced -> keep hands off
    balanced = {"producer_busy_s": 10.0, "producer_stall_s": 0.2,
                "consumer_stall_s": 0.2}
    assert propose_pipeline_knobs(balanced, cur) == cur


def test_scheduler_retune_applies_live_and_lands_in_telemetry(rng):
    from transmogrifai_tpu.serving import MicroBatchScheduler

    class _Endpoint:
        batch_buckets = (1, 8, 32, 128)

        def __init__(self):
            from transmogrifai_tpu.serving import ServingTelemetry

            self.telemetry = ServingTelemetry()

        def score_batch(self, records):
            return [dict(r) for r in records]

    ep = _Endpoint()
    sched = MicroBatchScheduler(ep, max_wait_us=2000, start=False)
    assert sched.knobs() == {"max_batch_size": 128, "max_wait_us": 2000}
    applied = sched.retune(max_batch_size=256, max_wait_us=500)
    assert applied == {"max_batch_size": 256, "max_wait_us": 500}
    assert sched.max_batch_size == 256
    snap = ep.telemetry.snapshot()
    assert snap["tuned_knobs"]["max_batch_size"] == 256.0
    assert snap["knob_source"] == "autotune"
    with pytest.raises(ValueError):
        sched.retune(max_batch_size=0)
    sched.close()


def test_pipeline_stats_snapshot_carries_knobs(tmp_path):
    from transmogrifai_tpu.readers import pipeline as txpipe
    from transmogrifai_tpu.types import feature_types as ft

    p = tmp_path / "s.csv"
    p.write_text("a,b\n" + "\n".join(
        f"{i},{i * 2}" for i in range(50)) + "\n")
    pipe = txpipe.InputPipeline(
        txpipe.shard([str(p)]), {"a": ft.Real, "b": ft.Real},
        workers=1, buffer_chunks=3,
    )
    rows = sum(pc.n_rows for pc in pipe.chunks())
    assert rows == 50
    snap = pipe.stats.snapshot()
    assert snap["knobs"] == {"workers": 1, "buffer_chunks": 3}
    doc = metrics_registry().to_json()
    assert doc["series"]["pipeline.workers"]["value"] == 1.0
    assert doc["series"]["pipeline.buffer_chunks"]["value"] == 3.0


# ---------------------------------------------------------------------------
# runner + CLI wiring
# ---------------------------------------------------------------------------
def _selector_workflow(rng, n=1200):
    import transmogrifai_tpu.dsl  # noqa: F401
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector.factories import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils.uid import reset_uids

    reset_uids()
    a_v = rng.randn(n)
    b_v = rng.randn(n)
    data = {
        "y": ((a_v - b_v + 0.3 * rng.randn(n)) > 0).astype(float).tolist(),
        "a": a_v.tolist(),
        "b": b_v.tolist(),
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([a, b])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2,
        models_and_parameters=[
            (OpLogisticRegression(),
             [{"reg_param": r, "elastic_net_param": 0.1}
              for r in (0.001, 0.01, 0.1, 0.2)]),
        ],
        splitter=None,
    )
    pred = selector.set_input(y, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    return wf


def test_runner_train_autotune_cold_start_report_and_artifact(
        tmp_path, rng):
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    wf = _selector_workflow(rng)
    runner = OpWorkflowRunner(wf)
    loc = str(tmp_path / "model")
    params = OpParams(model_location=loc,
                      custom_params={"autotune": True,
                                     "autotune_rung_rows": 400,
                                     "autotune_min_rows": 200})
    r = runner.run("train", params)
    # the cold-start contract end to end: reason recorded in the run
    # summary's selection metadata, cost model persisted NEXT TO the
    # model as a versioned artifact
    md = next(
        s["metadata"]["model_selector_summary"]
        for s in r.summary["stages"]
        if "model_selector_summary" in s.get("metadata", {})
    )
    assert md["autotune"]["mode"] == "exhaustive"
    assert md["autotune"]["reason"].startswith("cost_model_cold")
    assert r.summary["autotune"]["cost_model"]["observations"] > 0
    at_path = os.path.join(loc, "autotune.json")
    assert os.path.exists(at_path)
    assert CostModel.load(at_path).n_observations() > 0
    with open(os.path.join(loc, "summary.json")) as f:
        saved = json.load(f)
    assert saved["autotune"]["cost_model"]["path"] == at_path
    # the CLI report renders the model-dir trail
    report = report_from_path(loc)
    assert report["selection"][0]["autotune"]["mode"] == "exhaustive"
    assert report["cost_model"]["observations"] > 0


def test_runner_serve_autotune_probes_and_records_decision(
        tmp_path, rng):
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    wf = _selector_workflow(rng, n=400)
    runner = OpWorkflowRunner(wf)
    loc = str(tmp_path / "model")
    runner.run("train", OpParams(model_location=loc))
    wf2 = _selector_workflow(rng, n=400)
    runner2 = OpWorkflowRunner(wf2)
    r = runner2.run("serve", OpParams(
        model_location=loc,
        custom_params={
            "serving_autotune": True,
            "autotune_probe_rows": 64,
            "autotune_probe_repeats": 1,
        },
    ))
    dec = r.metrics["autotune"]
    assert dec["scope"] == "serving.microbatch"
    assert dec["baseline"] == {"max_batch_size": 128,
                               "max_wait_us": 2000}
    assert dec["winner"]["max_batch_size"] >= 1
    assert any(p["is_winner"] for p in dec["probes"])
    # tuned values visible in serving telemetry (obs acceptance)
    assert "max_batch_size" in r.metrics["tuned_knobs"]


def test_cli_autotune_report(tmp_path, rng, capsys):
    from transmogrifai_tpu.cli import main as cli_main
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    wf = _selector_workflow(rng, n=400)
    loc = str(tmp_path / "model")
    OpWorkflowRunner(wf).run("train", OpParams(
        model_location=loc,
        custom_params={"autotune": True, "autotune_min_rows": 200},
    ))
    rc = cli_main(["autotune", "report", "--path", loc])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cost_model"]["observations"] > 0
    assert doc["selection"]
    rc2 = cli_main(["autotune", "report", "--path",
                    str(tmp_path / "nowhere")])
    assert rc2 == 2


def test_profiler_observations_export():
    from transmogrifai_tpu.obs.profiler import SpanProfiler

    prof = SpanProfiler()
    for ms in (1.0, 2.0, 3.0):
        prof.observe("stage.fit", ms)
    rows = prof.observations()
    row = next(r for r in rows if r["name"] == "stage.fit")
    assert row["count"] == 3 and row["ewma_ms"] is not None
    cm = CostModel(min_obs=1)
    assert cm.ingest_profiler(prof.snapshot()) >= 1
    assert cm.n_observations("span:stage.fit") == 1
