"""Whole-workflow fuzz: random schemas through the full AutoML path.

The contract harness stresses stages in isolation; this suite stresses
their COMPOSITION the way the reference's integration tests do
(OpWorkflowTest + the helloworld apps): a random feature set covering
every major type family -> transmogrify -> sanity check -> selector ->
score -> save/load -> bit-identical rescore, across seeds and null
densities.
"""
from __future__ import annotations

import datetime as _dt
import os

import numpy as np
import pytest

# TX_FUZZ_SEED_OFFSET shifts every generator seed (the contract-harness
# sweep trick): CI runs offset 0; ad-hoc sweeps explore fresh draws
_OFF = int(os.environ.get("TX_FUZZ_SEED_OFFSET", "0"))


def _rs(seed: int) -> np.random.RandomState:
    return np.random.RandomState(seed + _OFF)

from transmogrifai_tpu import dsl  # noqa: F401 - activates feature DSL
from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.features.feature_builder import FeatureBuilder
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.selector.model_selector import ModelSelector
from transmogrifai_tpu.selector.validator import OpTrainValidationSplit
from transmogrifai_tpu.serialization.model_io import load_model
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow.workflow import OpWorkflow

_MS0 = _dt.datetime(2021, 1, 1, tzinfo=_dt.timezone.utc).timestamp() * 1000.0
_DAY_MS = 86400_000.0


def _random_data(rng: np.random.RandomState, n: int, p_null: float):
    """One row dict per raw feature name, covering the type families."""
    def maybe(v):
        return None if rng.rand() < p_null else v

    colors = ["red", "green", "blue", "teal"]
    words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]
    data = {
        "amount": [maybe(float(rng.randn() * 10 + 50)) for _ in range(n)],
        "count": [maybe(int(rng.randint(0, 9))) for _ in range(n)],
        "flag": [maybe(bool(rng.rand() < 0.5)) for _ in range(n)],
        "color": [maybe(colors[rng.randint(len(colors))]) for _ in range(n)],
        "note": [
            maybe(" ".join(words[rng.randint(len(words))] for _ in range(4)))
            for _ in range(n)
        ],
        "joined": [
            maybe(_MS0 + float(rng.randint(0, 400)) * _DAY_MS)
            for _ in range(n)
        ],
        "visits": [
            maybe([_MS0 + float(rng.randint(0, 100)) * _DAY_MS
                   for _ in range(rng.randint(0, 4))])
            for _ in range(n)
        ],
        "site": [
            maybe((float(rng.uniform(-60, 60)),
                   float(rng.uniform(-179, 179)), 1.0))
            for _ in range(n)
        ],
        "attrs": [
            {k: float(rng.randn())
             for k in ("height", "width") if rng.rand() > p_null}
            for _ in range(n)
        ],
        "tags": [
            maybe(frozenset(colors[rng.randint(len(colors))]
                            for _ in range(rng.randint(0, 3))))
            for _ in range(n)
        ],
    }
    # a learnable label: depends on amount + flag
    amounts = [v if v is not None else 50.0 for v in data["amount"]]
    flags = [1.0 if v else 0.0 for v in data["flag"]]
    z = np.asarray(amounts) / 20.0 + np.asarray(flags) - 3.0
    data["label"] = (1 / (1 + np.exp(-z)) > rng.rand(n)).astype(float).tolist()
    return data


def _features():
    return [
        FeatureBuilder(ft.Real, "amount").as_predictor(),
        FeatureBuilder(ft.Integral, "count").as_predictor(),
        FeatureBuilder(ft.Binary, "flag").as_predictor(),
        FeatureBuilder(ft.PickList, "color").as_predictor(),
        FeatureBuilder(ft.Text, "note").as_predictor(),
        FeatureBuilder(ft.Date, "joined").as_predictor(),
        FeatureBuilder(ft.DateList, "visits").as_predictor(),
        FeatureBuilder(ft.Geolocation, "site").as_predictor(),
        FeatureBuilder(ft.RealMap, "attrs").as_predictor(),
        FeatureBuilder(ft.MultiPickList, "tags").as_predictor(),
    ]


@pytest.mark.parametrize("seed,p_null", [(1, 0.1), (2, 0.35), (3, 0.02)])
def test_full_pipeline_fuzz(tmp_path, seed, p_null):
    rng = _rs(seed)
    n = 120
    data = _random_data(rng, n, p_null)

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        checked = label.sanity_check(vec, remove_bad_features=True)
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75,
                evaluator=OpBinaryClassificationEvaluator(),
            ),
            models=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
        )
        pred = selector.set_input(label, checked).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    model = wf.set_input_dataset(data).train()
    scored = model.score(data)[pred.name].to_list()
    assert len(scored) == n
    probs = [r["probability_1"] for r in scored]
    assert all(0.0 <= p <= 1.0 for p in probs)
    # the label depends on amount+flag: the fit must beat chance in-sample
    m = model.evaluate(OpBinaryClassificationEvaluator())
    assert float(m.AuROC) > 0.55, float(m.AuROC)

    # save / load into a freshly built identical workflow -> bit-identical
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    rescored = m2.score(data)[pred2.name].to_list()
    assert rescored == scored

    # unseen data with fresh nulls scores without error, identical between
    # the original and the loaded model
    unseen = _random_data(_rs(seed + 100), 40, p_null)
    a = model.score(unseen)[pred.name].to_list()
    b = m2.score(unseen)[pred2.name].to_list()
    assert a == b


def test_multiclass_pipeline_fuzz(tmp_path):
    """Same random schema, 3-class label through the multiclass selector
    (stratified CV + DataCutter + softmax LR)."""
    from transmogrifai_tpu.evaluators.multiclass import (
        OpMultiClassificationEvaluator,
    )
    from transmogrifai_tpu.selector.factories import (
        MultiClassificationModelSelector,
    )

    rng = _rs(7)
    n = 150
    data = _random_data(rng, n, 0.1)
    amounts = np.asarray(
        [v if v is not None else 50.0 for v in data["amount"]]
    )
    data["label"] = np.digitize(amounts, [45.0, 55.0]).astype(float).tolist()

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        selector = MultiClassificationModelSelector.with_cross_validation(
            num_folds=2,
            models_and_parameters=[
                (OpLogisticRegression(), [{"reg_param": 0.01}]),
            ],
        )
        pred = selector.set_input(label, vec).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    model = wf.set_input_dataset(data).train()
    scored = model.score(data)[pred.name].to_list()
    # jointly-normalized class probabilities (multinomial family)
    for r in scored[:20]:
        ps = [v for k, v in r.items() if k.startswith("probability_")]
        assert len(ps) == 3
        assert abs(sum(ps) - 1.0) < 1e-6
    m = model.evaluate(OpMultiClassificationEvaluator())
    assert float(m.F1) > 0.5
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored


def test_workflow_cv_and_rff_compose_on_fuzz_schema(tmp_path):
    """The auxiliary systems compose over the random schema: a
    RawFeatureFilter gate (with a drifted scoring set), workflow-level CV
    (SanityChecker refit inside each fold), save/load, and the engine-free
    row scorer - all on one pipeline."""
    from transmogrifai_tpu.filters.raw_feature_filter import RawFeatureFilter

    rng = _rs(21)
    n = 140
    data = _random_data(rng, n, 0.15)
    # a drifted scoring set: 'count' becomes mostly-null so the filter
    # flags its fill difference
    scoring = _random_data(_rs(22), 90, 0.15)
    scoring["count"] = [None] * 85 + scoring["count"][85:]

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        checked = label.sanity_check(vec, remove_bad_features=True)
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75,
                evaluator=OpBinaryClassificationEvaluator(),
            ),
            models=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
        )
        pred = selector.set_input(label, checked).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    from transmogrifai_tpu.types.dataset import Dataset as _DS
    from transmogrifai_tpu.types.columns import column_from_list

    scoring_ds = _DS({
        f.name: column_from_list(scoring[f.name], f.ftype)
        for f in _features()
    })
    wf = wf.with_raw_feature_filter(
        RawFeatureFilter(scoring_data=scoring_ds, max_fill_difference=0.3)
    ).with_workflow_cv()
    model = wf.set_input_dataset(data).train()
    # the drifted feature was filtered out of the raw set
    dropped = {f.name for f in wf.blacklisted_features}
    assert "count" in dropped
    # ...and stays out of the interpretability lineage too
    ins = model.model_insights()
    assert ins.selected_model_type is not None
    assert not any("count" in fi.pretty_name for fi in ins.feature_insights)
    assert len(ins.pretty()) > 100
    scored = model.score(data)[pred.name].to_list()
    probs = [r["probability_1"] for r in scored]
    assert all(0.0 <= p <= 1.0 for p in probs)
    # engine-free row scorer parity on the full fuzz schema (maps,
    # datelists, geo, multipicklists all ride transform_columns); the
    # row path predicts in f64 numpy vs the batch path's device f32, so
    # probabilities agree to f32 resolution, not bitwise
    row_fn = model.score_function()
    for i in (0, 3, 17):
        row = {k: data[k][i] for k in data}
        got = row_fn(row)[pred.name]
        assert got["prediction"] == scored[i]["prediction"]
        for k in got:
            assert got[k] == pytest.approx(scored[i][k], rel=2e-5, abs=1e-6)
    # save/load round-trip with the filtered DAG
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    wf2 = wf2.with_raw_feature_filter(
        RawFeatureFilter(scoring_data=scoring_ds, max_fill_difference=0.3)
    ).with_workflow_cv()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored


def test_streaming_and_loco_on_fuzz_schema():
    """Streaming micro-batches score identically to one batch; LOCO
    explanations stay finite and name real vector columns - both over the
    full 10-type random schema."""
    from transmogrifai_tpu.insights.loco import RecordInsightsLOCO

    rng = _rs(31)
    n = 100
    data = _random_data(rng, n, 0.12)
    feats = _features()
    label = FeatureBuilder(ft.RealNN, "label").as_response()
    vec = transmogrify(feats)
    selector = ModelSelector(
        validator=OpTrainValidationSplit(
            train_ratio=0.75, evaluator=OpBinaryClassificationEvaluator()
        ),
        models=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
    )
    pred = selector.set_input(label, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred)
        .set_input_dataset(data).train()
    )
    scored_ds = model.score(data)
    scored = scored_ds[pred.name].to_list()
    # streaming path: odd batch size forces a ragged final micro-batch
    scorer = model.score_function()
    rows = [{k: data[k][i] for k in data} for i in range(n)]
    streamed = list(scorer.score_stream(rows, batch_size=7))
    assert len(streamed) == n
    for i in (0, 6, 7, 99):
        assert streamed[i][pred.name]["prediction"] == scored[i]["prediction"]
        assert streamed[i][pred.name]["probability_1"] == pytest.approx(
            scored[i]["probability_1"], rel=2e-5, abs=1e-6
        )
    # LOCO over the fitted selector's model on the combined vector
    from transmogrifai_tpu.selector.model_selector import SelectedModel

    sel_stage = next(
        s for layer in model._dag() for s in layer
        if isinstance(s, SelectedModel)
    )
    loco = RecordInsightsLOCO(sel_stage, top_k=5).set_input(vec)
    out = loco.transform(scored_ds)
    vals = out[loco.output_name].to_list()
    col_names = set(scored_ds[vec.name].metadata.column_names())
    for row in vals[:10]:
        assert 0 < len(row) <= 5
        for colname, delta in row.items():
            assert colname in col_names
            assert np.isfinite(delta)


def test_warm_start_skips_refit_on_fuzz_schema():
    """with_model_stages: a second train on the same workflow skips
    refitting warm stages and reproduces identical scores."""
    rng = _rs(41)
    n = 90
    data = _random_data(rng, n, 0.1)
    feats = _features()
    label = FeatureBuilder(ft.RealNN, "label").as_response()
    vec = transmogrify(feats)
    selector = ModelSelector(
        validator=OpTrainValidationSplit(
            train_ratio=0.75, evaluator=OpBinaryClassificationEvaluator()
        ),
        models=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
    )
    pred = selector.set_input(label, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    model = wf.train()
    scored = model.score(data)[pred.name].to_list()
    model2 = wf.with_model_stages(model).train()
    assert model2.score(data)[pred.name].to_list() == scored

    def fit_uids(m):
        return {
            s["stage_uid"] for s in m.app_metrics.to_json()["stages"]
            if s["phase"] == "fit"
        }

    # the warm stages must NOT have refit (score equality alone would
    # also pass for a silent full refit on fixed-seed data)
    assert not (fit_uids(model2) & fit_uids(model))


def test_data_cutter_drops_rare_class_fuzz():
    """Multiclass with a 3%-frequency class under DataCutter
    min_label_fraction: the rare label is cut before CV, the summary
    names it, and the fitted model never predicts it."""
    from transmogrifai_tpu.evaluators.multiclass import (
        OpMultiClassificationEvaluator,
    )
    from transmogrifai_tpu.selector.factories import (
        MultiClassificationModelSelector,
    )
    from transmogrifai_tpu.selector.splitters import DataCutter

    rng = _rs(95)
    n = 160
    data = _random_data(rng, n, 0.1)
    amounts = np.asarray(
        [v if v is not None else 50.0 for v in data["amount"]]
    )
    labels = np.digitize(amounts, [48.0]).astype(float)  # classes 0/1
    rare = rng.choice(n, size=4, replace=False)
    labels[rare] = 2.0  # ~3% class
    data["label"] = labels.tolist()

    feats = _features()
    label = FeatureBuilder(ft.RealNN, "label").as_response()
    vec = transmogrify(feats)
    selector = MultiClassificationModelSelector.with_cross_validation(
        num_folds=2,
        models_and_parameters=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
        splitter=DataCutter(min_label_fraction=0.1,
                            reserve_test_fraction=0.1),
    )
    pred = selector.set_input(label, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred)
        .set_input_dataset(data).train()
    )
    sel_summary = next(
        st["metadata"]["model_selector_summary"]
        for st in model.summary_json()["stages"]
        if "model_selector_summary" in st.get("metadata", {})
    )
    sp = sel_summary["splitter_summary"]
    assert sp["splitter"] == "DataCutter"
    assert 2.0 in sp["labelsDropped"]
    assert sp["rowsDropped"] == 4
    scored = model.score(data)[pred.name].to_list()
    preds = {r["prediction"] for r in scored}
    assert preds <= {0.0, 1.0}  # the cut class can never be predicted
    m = model.evaluate(OpMultiClassificationEvaluator())
    assert float(m.F1) > 0.5


def test_data_balancer_pipeline_fuzz(tmp_path):
    """A ~7%-positive label through the selector with DataBalancer: the
    minority up-weighting rides the CV weight vectors (no data copies),
    the splitter summary lands in metadata, and save/load holds."""
    from transmogrifai_tpu.selector.splitters import DataBalancer

    rng = _rs(85)
    n = 160
    data = _random_data(rng, n, 0.1)
    amounts = np.asarray(
        [v if v is not None else 50.0 for v in data["amount"]]
    )
    data["label"] = (amounts > np.percentile(amounts, 93)).astype(
        float
    ).tolist()

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75,
                evaluator=OpBinaryClassificationEvaluator(),
            ),
            models=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
            splitter=DataBalancer(sample_fraction=0.3),
        )
        pred = selector.set_input(label, vec).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    model = wf.set_input_dataset(data).train()
    summary = model.summary_json()
    sel_summary = next(
        st["metadata"]["model_selector_summary"]
        for st in summary["stages"]
        if "model_selector_summary" in st.get("metadata", {})
    )
    sp = sel_summary["splitter_summary"]
    assert sp["splitter"] == "DataBalancer" and sp["upSampled"]
    assert sp["minorityWeight"] > 1.0
    m = model.evaluate(OpBinaryClassificationEvaluator())
    assert float(m.AuROC) > 0.7  # amount drives the label outright
    scored = model.score(data)[pred.name].to_list()
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored


def test_nb_and_mlp_pipeline_fuzz(tmp_path):
    """NaiveBayes + MLP (the remaining classifier families) through the
    composition with save/load parity."""
    from transmogrifai_tpu.models.mlp import OpMultilayerPerceptronClassifier
    from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes

    rng = _rs(75)
    n = 130
    data = _random_data(rng, n, 0.1)

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75,
                evaluator=OpBinaryClassificationEvaluator(),
            ),
            models=[
                (OpNaiveBayes(), [{}]),
                (OpMultilayerPerceptronClassifier(
                    hidden_layers=(8,), max_iter=40), [{}]),
            ],
        )
        pred = selector.set_input(label, vec).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    model = wf.set_input_dataset(data).train()
    scored = model.score(data)[pred.name].to_list()
    probs = [r["probability_1"] for r in scored]
    assert all(np.isfinite(p) and 0.0 <= p <= 1.0 for p in probs)
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored


def test_glm_poisson_pipeline_fuzz(tmp_path):
    """A Poisson GLM through the regression composition: count-like label
    from the fuzz schema, finite coefficients, save/load parity."""
    from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
    from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression

    rng = _rs(65)
    n = 140
    data = _random_data(rng, n, 0.1)
    amounts = np.asarray(
        [v if v is not None else 50.0 for v in data["amount"]]
    )
    lam = np.exp((amounts - 50.0) / 25.0)
    data["label"] = rng.poisson(lam).astype(float).tolist()

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75, evaluator=OpRegressionEvaluator()
            ),
            models=[
                (OpGeneralizedLinearRegression(family="poisson"), [{}]),
            ],
        )
        pred = selector.set_input(label, vec).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    model = wf.set_input_dataset(data).train()
    scored = model.score(data)[pred.name].to_list()
    preds = np.asarray([r["prediction"] for r in scored])
    assert np.isfinite(preds).all() and (preds >= 0).all()
    # the log-link fit must recover the amount signal direction
    assert np.corrcoef(preds, np.asarray(data["label"]))[0, 1] > 0.3
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored


def test_tree_families_pipeline_fuzz(tmp_path):
    """RF + GBT ride the same composition (fold/grid-batched tree CV over
    the transmogrified fuzz matrix), save/load bit-parity included."""
    from transmogrifai_tpu.models.trees import (
        OpGBTClassifier,
        OpRandomForestClassifier,
    )

    rng = _rs(55)
    n = 130
    data = _random_data(rng, n, 0.1)

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75,
                evaluator=OpBinaryClassificationEvaluator(),
            ),
            models=[
                (OpRandomForestClassifier(num_trees=8, max_depth=4), [{}]),
                (OpGBTClassifier(num_trees=6, max_depth=3), [{}]),
            ],
        )
        pred = selector.set_input(label, vec).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    model = wf.set_input_dataset(data).train()
    scored = model.score(data)[pred.name].to_list()
    m = model.evaluate(OpBinaryClassificationEvaluator())
    assert float(m.AuROC) > 0.6
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored


def test_runner_five_run_types_on_fuzz_schema(tmp_path):
    """All five reference run types (Train/Score/Evaluate/Features/
    StreamingScore, OpWorkflowRunner.scala:296-313) execute over the
    10-type random schema, with avro score output."""
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    rng = _rs(91)
    data = _random_data(rng, 100, 0.1)

    class ListReader:
        def generate_dataset(self, raw_features, params):
            from transmogrifai_tpu.types.columns import column_from_list
            from transmogrifai_tpu.types.dataset import Dataset as _DS

            return _DS({
                f.name: column_from_list(data[f.name], f.ftype)
                for f in raw_features
            })

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75,
                evaluator=OpBinaryClassificationEvaluator(),
            ),
            models=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
        )
        pred = selector.set_input(label, vec).get_output()
        wf = OpWorkflow().set_result_features(pred).set_reader(ListReader())
        return wf, pred

    params = OpParams(
        model_location=str(tmp_path / "model"),
        write_location=str(tmp_path / "scores"),
        metrics_location=str(tmp_path / "metrics"),
        write_format="avro",
    )
    wf, pred = build()
    runner = OpWorkflowRunner(wf, evaluator=OpBinaryClassificationEvaluator())
    r = runner.run("train", params)
    assert r.model is not None

    wf2, pred2 = build()
    r2 = OpWorkflowRunner(
        wf2, evaluator=OpBinaryClassificationEvaluator()
    ).run("score", params)
    assert r2.scores is not None and pred2.name in r2.scores
    import glob as _glob

    avro_written = _glob.glob(str(tmp_path / "scores" / "*.avro"))
    assert avro_written, "write_format=avro must write an OCF"
    from transmogrifai_tpu.readers.avro_reader import read_avro_records

    _, recs = read_avro_records(avro_written[0])
    assert len(recs) == 100

    wf3, _ = build()
    r3 = OpWorkflowRunner(
        wf3, evaluator=OpBinaryClassificationEvaluator()
    ).run("evaluate", params)
    assert "AuROC" in r3.metrics

    wf4, _ = build()
    r4 = OpWorkflowRunner(wf4).run("features", params)
    assert r4.scores is not None  # the vectorized frame

    wf5, pred5 = build()
    runner5 = OpWorkflowRunner(wf5, evaluator=OpBinaryClassificationEvaluator())
    batches = [
        {k: v[i:i + 40] for k, v in data.items()} for i in (0, 40, 80)
    ]
    outs = list(runner5.streaming_score(batches, params))
    assert len(outs) == 3
    assert sum(len(o[pred5.name]) for o in outs) == 100


@pytest.mark.parametrize("corr_type,exclusion", [
    ("pearson", "none"),
    ("spearman", "none"),
    ("pearson", "hashed_text"),
    ("spearman", "hashed_text"),
])
def test_sanity_checker_option_matrix_on_fuzz_schema(corr_type, exclusion,
                                                     tmp_path):
    """Every correlation-type x exclusion combination trains, drops a
    planted leaker, keeps the hash block when excluded, and survives
    save/load with identical vector slicing."""
    from transmogrifai_tpu.preparators.sanity_checker import SanityChecker

    rng = _rs(61)
    n = 130
    data = _random_data(rng, n, 0.1)
    # planted label-leaker: an exact copy of the label
    data["leak"] = list(data["label"])

    def build():
        feats = _features() + [FeatureBuilder(ft.Real, "leak").as_predictor()]
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        checker = SanityChecker(
            remove_bad_features=True,
            correlation_type=corr_type,
            correlation_exclusion=exclusion,
            max_correlation=0.9,
        )
        checked = checker.set_input(label, vec).get_output()
        selector = ModelSelector(
            validator=OpTrainValidationSplit(
                train_ratio=0.75,
                evaluator=OpBinaryClassificationEvaluator(),
            ),
            models=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
        )
        pred = selector.set_input(label, checked).get_output()
        return OpWorkflow().set_result_features(pred), pred, checked

    wf, pred, checked = build()
    model = wf.set_input_dataset(data).train()
    out = model.score(data)
    kept = out[checked.name].metadata.columns
    kept_parents = {c.parent_feature_name for c in kept}
    assert "leak" not in kept_parents  # the leaker was dropped
    assert len(kept) > 0
    scored = out[pred.name].to_list()
    model.save(str(tmp_path / "m"))
    wf2, pred2, _ = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored


def test_multiclass_wide_matrix_stress():
    """K=4 over a ~1.1k-wide design (K*d+K ~ 4.4k Hessian): the
    dimension-aware ridge must keep the softmax Cholesky finite well past
    the 1.6k dim where the flat ridge froze (kernel-level stress of the
    fuzz-caught failure)."""
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )

    rng = _rs(3)
    n, d_dense = 220, 24
    Xd = rng.randn(n, d_dense)
    # one-hot blocks + sparse hashed-ish columns mimic transmogrified
    # structure (collinear groups, mostly-zero columns)
    groups = []
    for g in range(40):
        onehot = np.zeros((n, 8))
        onehot[np.arange(n), rng.randint(0, 8, n)] = 1.0
        groups.append(onehot)
    sparse = (rng.rand(n, 760) < 0.02) * rng.rand(n, 760)
    X = np.concatenate([Xd] + groups + [sparse], axis=1)
    y = np.digitize(Xd[:, 0] + 0.5 * Xd[:, 1], [-1.0, 0.0, 1.0]).astype(float)
    # family='auto' would take the large-K*d OVR fallback here; force the
    # softmax kernel - the stress target is ITS Cholesky at dim ~ 4.4k
    lr = OpLogisticRegression(reg_param=0.01, family="multinomial")
    params = lr.fit_arrays(X, y, np.ones(n))
    assert params["family"] == "multinomial"
    assert np.abs(params["betas"]).max() > 0.01  # did not freeze
    pred, _, prob = lr.predict_arrays_np(params, X)
    assert float((pred == y).mean()) > 0.8
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, atol=1e-6)


def test_regression_pipeline_fuzz(tmp_path):
    """Continuous label through the regression selector (no balancing,
    DataSplitter prep) - regression CV must stay on the batched path."""
    from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
    from transmogrifai_tpu.models.linear_regression import OpLinearRegression
    from transmogrifai_tpu.selector.factories import RegressionModelSelector

    rng = _rs(11)
    n = 150
    data = _random_data(rng, n, 0.1)
    amounts = np.asarray(
        [v if v is not None else 50.0 for v in data["amount"]]
    )
    flags = np.asarray([1.0 if v else 0.0 for v in data["flag"]])
    data["label"] = (
        2.0 * amounts + 5.0 * flags + rng.randn(n)
    ).tolist()

    def build():
        feats = _features()
        label = FeatureBuilder(ft.RealNN, "label").as_response()
        vec = transmogrify(feats)
        selector = RegressionModelSelector.with_cross_validation(
            num_folds=2,
            models_and_parameters=[
                (OpLinearRegression(), [{"reg_param": 0.01}]),
            ],
        )
        pred = selector.set_input(label, vec).get_output()
        return OpWorkflow().set_result_features(pred), pred

    wf, pred = build()
    model = wf.set_input_dataset(data).train()
    m = model.evaluate(OpRegressionEvaluator())
    assert float(m.R2) > 0.9  # amount is in the design matrix
    scored = model.score(data)[pred.name].to_list()
    model.save(str(tmp_path / "m"))
    wf2, pred2 = build()
    m2 = load_model(str(tmp_path / "m"), wf2.set_input_dataset(data))
    assert m2.score(data)[pred2.name].to_list() == scored
