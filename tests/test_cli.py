"""CLI project generator test (reference: cli/src/test/.../CliFullCycleTest
- generate then actually run the generated project)."""
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu.cli import generate


@pytest.fixture
def csv_file(tmp_path, rng):
    n = 200
    path = tmp_path / "data.csv"
    with open(path, "w") as f:
        f.write("y,x1,x2,cat\n")
        for i in range(n):
            x1, x2 = rng.randn(), rng.randn()
            y = int(x1 + 0.5 * x2 + 0.3 * rng.randn() > 0)
            cat = "a" if rng.rand() > 0.5 else "b"
            f.write(f"{y},{x1:.4f},{x2:.4f},{cat}\n")
    return str(path)


def test_generate_and_run_project(tmp_path, csv_file):
    out = tmp_path / "proj"
    main_py = generate(csv_file, response="y", name="TestApp", output=str(out))
    assert os.path.exists(main_py)
    assert os.path.exists(out / "README.md")
    src = open(main_py).read()
    assert "BinaryClassificationModelSelector" in src
    assert "as_response()" in src

    env = dict(os.environ)
    env.update({
        "PYTHONPATH": "/root/repo",
        "JAX_PLATFORMS": "cpu",
    })
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, main_py], capture_output=True, text=True,
        timeout=500, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "Selected model" in proc.stdout
    assert "model saved" in proc.stdout

    # the scaffold's scorer loads the saved model and scores the CSV
    proc2 = subprocess.run(
        [sys.executable, str(out / "score.py")], capture_output=True,
        text=True, timeout=500, env=env, cwd=str(out),
    )
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    assert "scored 200 rows" in proc2.stdout


def test_generate_multiclass_text_labels(tmp_path, rng):
    """A string-labeled response infers multiclass + label indexing
    (ProblemKind semantics)."""
    n = 150
    path = tmp_path / "iris_like.csv"
    with open(path, "w") as f:
        f.write("species,a,b\n")
        for i in range(n):
            k = i % 3
            f.write(
                f"{['setosa', 'versicolor', 'virginica'][k]},"
                f"{rng.randn() + k:.4f},{rng.randn() - k:.4f}\n"
            )
    out = tmp_path / "proj_mc"
    main_py = generate(str(path), response="species", name="McApp",
                       output=str(out))
    src = open(main_py).read()
    assert "MultiClassificationModelSelector" in src
    assert "LABELS = ['setosa', 'versicolor', 'virginica']" in src
    assert "map_values" in src

    env = dict(os.environ)
    env.update({"PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu"})
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, main_py], capture_output=True, text=True,
        timeout=500, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "Selected model" in proc.stdout


def test_generate_overrides_idcol_and_type_refinement(tmp_path, rng):
    n = 120
    path = tmp_path / "refine.csv"
    with open(path, "w") as f:
        f.write("rowid,y,email,freeform,x\n")
        for i in range(n):
            f.write(
                f"{i},{i % 2},user{i}@example.com,"
                f"word{i % 50} text {i},{rng.randn():.4f}\n"
            )
    out = tmp_path / "proj_ref"
    main_py = generate(
        str(path), response="y", name="RefApp", output=str(out),
        overrides={"freeform": __import__(
            "transmogrifai_tpu.types.feature_types", fromlist=["TextArea"]
        ).TextArea},
        id_col="rowid",
    )
    src = open(main_py).read()
    assert "ft.Email, 'email'" in src
    assert "ft.TextArea, 'freeform'" in src
    assert "rowid" not in src.split("def build_workflow")[0].replace(
        "# --", "")


def test_infer_problem_kind():
    from transmogrifai_tpu.cli import infer_problem_kind

    assert infer_problem_kind([0, 1, 1, 0]) == ("binary", [])
    assert infer_problem_kind([0.0, 1.0, 2.0] * 10) == ("multiclass", [])
    assert infer_problem_kind([1.5, 2.7, 3.14, 9.9]) == ("regression", [])
    assert infer_problem_kind(["yes", "no"]) == ("binary", ["no", "yes"])
    k, labels = infer_problem_kind(["a", "b", "c", None])
    assert k == "multiclass" and labels == ["a", "b", "c"]
    # non-canonical numeric classes must be re-indexed, not fed raw
    assert infer_problem_kind([1, 2, 1, 2]) == ("binary", [1.0, 2.0])
    assert infer_problem_kind([1, 3, 7] * 5) == ("multiclass",
                                                 [1.0, 3.0, 7.0])
    # textual nan placeholders count as missing, not as a class
    assert infer_problem_kind(["0", "1", "nan", "0"]) == ("binary", [])
    # an all-missing response cannot infer anything
    with pytest.raises(ValueError, match="no usable values"):
        infer_problem_kind(["nan", "nan"])


def test_generate_rejects_dirty_response_and_bad_idcol(tmp_path, rng):
    path = tmp_path / "dirty.csv"
    with open(path, "w") as f:
        f.write("y,x\nnan,1.0\n1,2.0\n0,3.0\n")
    with pytest.raises(ValueError, match="missing/non-finite"):
        generate(str(path), response="y", name="X",
                 output=str(tmp_path / "p1"))

    clean = tmp_path / "clean.csv"
    with open(clean, "w") as f:
        f.write("y,x\n1,2.0\n0,3.0\n")
    with pytest.raises(ValueError, match="cannot be the same"):
        generate(str(clean), response="y", name="X",
                 output=str(tmp_path / "p2"), id_col="y")

    # unicode header that is alnum but not identifier-legal still compiles
    uni = tmp_path / "uni.csv"
    with open(uni, "w", encoding="utf-8") as f:
        f.write("y,x²\n1,2.0\n0,3.0\n1,4.0\n0,5.0\n")
    main_py = generate(str(uni), response="y", name="U",
                       output=str(tmp_path / "p3"))
    compile(open(main_py, encoding="utf-8").read(), main_py, "exec")


def test_generate_handles_label_column_and_nonidentifiers(tmp_path, rng):
    """Response named 'label' (template-local collision), a column that
    sanitizes to a non-identifier, numeric {1,2} classes, and a bad
    --id-col must all be handled."""
    n = 120
    path = tmp_path / "tricky.csv"
    with open(path, "w") as f:
        f.write("label,1st col,x\n")
        for i in range(n):
            f.write(f"{1 + (i % 2)},{rng.randn():.4f},{rng.randn():.4f}\n")
    out = tmp_path / "proj_tricky"
    main_py = generate(str(path), response="label", name="TrickyApp",
                       output=str(out))
    src = open(main_py).read()
    assert "LABELS = [1.0, 2.0]" in src  # {1,2} re-indexed to 0/1
    compile(src, main_py, "exec")  # sanitized names must be valid python

    env = dict(os.environ)
    env.update({"PYTHONPATH": "/root/repo", "JAX_PLATFORMS": "cpu"})
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, main_py], capture_output=True, text=True,
        timeout=500, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "Selected model" in proc.stdout

    with pytest.raises(KeyError, match="id column"):
        generate(str(path), response="label", name="X",
                 output=str(tmp_path / "nope"), id_col="typo")


def test_ask_accepts_index_alias_and_reprompts():
    from transmogrifai_tpu.cli import ask

    opts = [("binary", ["binary", "yes"]), ("regression", ["regression"])]
    feed = iter(["bogus", "1"])  # invalid input re-prompts
    assert ask("Kind?", opts, input_fn=lambda q: next(feed)) == "regression"
    assert ask("Kind?", opts, input_fn=lambda q: "YES") == "binary"
    assert ask("Kind?", opts, input_fn=lambda q: "0") == "binary"


def test_ask_answers_map_short_circuits_stdin():
    from transmogrifai_tpu.cli import ask

    def explode(q):  # stdin must never be touched
        raise AssertionError("stdin used despite answers map")

    got = ask(
        "Problem kind for response 'y'",
        [("binary", ["binary"]), ("multiclass", ["multiclass"])],
        answers={"problem kind": "multiclass"},
        input_fn=explode,
    )
    assert got == "multiclass"


def test_generate_interactive_dialogue(tmp_path, csv_file):
    """Scripted interactive run (reference: op gen question dialogue,
    cli/gen/Ops.scala UserIO): confirm the inferred kind, pick no id."""
    out = tmp_path / "proj_interactive"
    feed = iter(["yes", "none"])
    main_py = generate(
        csv_file, response="y", name="InteractiveApp", output=str(out),
        interactive=True, input_fn=lambda q: next(feed),
    )
    src = open(main_py).read()
    assert "BinaryClassificationModelSelector" in src


def test_generate_with_answers_file(tmp_path, csv_file):
    """--answers scripts the dialogue without stdin (reference:
    CliParameters.answersFile, 'prefix => answer' lines)."""
    from transmogrifai_tpu.cli import load_answers, main

    answers = tmp_path / "answers.txt"
    answers.write_text(
        "problem kind => binary\nwhich column is the row id => cat\n"
    )
    amap = load_answers(str(answers))
    assert amap == {
        "problem kind": "binary", "which column is the row id": "cat",
    }
    out = tmp_path / "proj_answers"
    rc = main([
        "gen", "--input", csv_file, "--response", "y",
        "--name", "AnswersApp", "--output", str(out),
        "--answers", str(answers),
    ])
    assert rc == 0
    src = open(out / "main.py").read()
    assert "BinaryClassificationModelSelector" in src
    # 'cat' picked as the id column -> no predictor FeatureBuilder for it
    assert not re.search(r'FeatureBuilder\([^)]*"cat"\)[^\n]*as_predictor', src)


def test_ask_strict_and_layered_answers():
    """Scripted (strict) runs fail fast on missing/invalid answers; layered
    prefix files let a later, more specific prefix supply the answer; and
    non-strict (interactive + partial answers) falls through to the
    prompt (advisor r4 + review r5)."""
    import pytest

    from transmogrifai_tpu.cli import ask

    opts = [("a", ["colA"]), ("b", ["colB"])]
    with pytest.raises(ValueError, match="no entry"):
        ask("Which id column?", opts, answers={"unrelated": "colA"},
            strict=True)
    with pytest.raises(ValueError, match="invalid answer"):
        ask("Which id column?", opts, answers={"which id": "nope"},
            strict=True)
    assert ask("Which id column?", opts,
               answers={"which": "nope", "which id": "colB"},
               strict=True) == "b"
    assert ask("Which id column?", opts, answers={"unrelated": "colA"},
               strict=False, input_fn=lambda q: "colB") == "b"


def test_load_answers_rejects_malformed_lines(tmp_path):
    """A malformed answers line must raise, not silently vanish (a dropped
    entry turns a scripted run interactive) - review r5.  Blank lines and
    #-comments stay legal; '=>' without surrounding spaces parses."""
    import pytest

    from transmogrifai_tpu.cli import load_answers

    good = tmp_path / "good.txt"
    good.write_text("# comment\n\nwhich id=>colB\nproblem kind => binary\n")
    assert load_answers(str(good)) == {
        "which id": "colB", "problem kind": "binary",
    }
    bad = tmp_path / "bad.txt"
    bad.write_text("which id colB\n")
    with pytest.raises(ValueError, match="expected 'prefix => answer'"):
        load_answers(str(bad))


def test_ask_empty_answers_dict_is_still_strict():
    """answers={} (empty/malformed file) with strict must fail fast, not
    fall through to a blocking stdin prompt - review r5."""
    import pytest

    from transmogrifai_tpu.cli import ask

    with pytest.raises(ValueError, match="no entry"):
        ask("Which id column?", [("a", ["colA"])], answers={}, strict=True)
