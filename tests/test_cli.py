"""CLI project generator test (reference: cli/src/test/.../CliFullCycleTest
- generate then actually run the generated project)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu.cli import generate


@pytest.fixture
def csv_file(tmp_path, rng):
    n = 200
    path = tmp_path / "data.csv"
    with open(path, "w") as f:
        f.write("y,x1,x2,cat\n")
        for i in range(n):
            x1, x2 = rng.randn(), rng.randn()
            y = int(x1 + 0.5 * x2 + 0.3 * rng.randn() > 0)
            cat = "a" if rng.rand() > 0.5 else "b"
            f.write(f"{y},{x1:.4f},{x2:.4f},{cat}\n")
    return str(path)


def test_generate_and_run_project(tmp_path, csv_file):
    out = tmp_path / "proj"
    main_py = generate(csv_file, response="y", name="TestApp", output=str(out))
    assert os.path.exists(main_py)
    assert os.path.exists(out / "README.md")
    src = open(main_py).read()
    assert "BinaryClassificationModelSelector" in src
    assert "as_response()" in src

    env = dict(os.environ)
    env.update({
        "PYTHONPATH": "/root/repo",
        "JAX_PLATFORMS": "cpu",
    })
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, main_py], capture_output=True, text=True,
        timeout=500, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "Selected model" in proc.stdout
