"""SanityChecker correlation options (reference: SanityChecker.scala:633-637
CorrelationType.{Pearson,Spearman} -> Statistics.corr) and the host rank
transform behind the Spearman path."""
import numpy as np
import pytest
import scipy.stats

from transmogrifai_tpu.preparators.sanity_checker import SanityChecker
from transmogrifai_tpu.types.columns import NumericColumn, VectorColumn
from transmogrifai_tpu.types.dataset import Dataset
from transmogrifai_tpu.types.feature_types import RealNN
from transmogrifai_tpu.types.vector_metadata import (
    VectorColumnMeta,
    VectorMetadata,
)
from transmogrifai_tpu.utils.stats import average_ranks


def test_average_ranks_match_scipy(rng):
    v = rng.randn(500)
    v[:50] = np.round(v[:50], 1)  # force ties
    np.testing.assert_allclose(
        average_ranks(v), scipy.stats.rankdata(v, method="average")
    )
    M = rng.randn(200, 4)
    M[:, 2] = np.round(M[:, 2])  # heavy ties in one column
    got = average_ranks(M)
    for j in range(4):
        np.testing.assert_allclose(
            got[:, j], scipy.stats.rankdata(M[:, j], method="average")
        )


def _fit_summary(X, y, **kw):
    n, d = X.shape
    meta = VectorMetadata(
        "features", tuple(VectorColumnMeta(f"f{j}", "Real") for j in range(d))
    ).reindexed()
    label = NumericColumn(y, np.ones(n, bool), RealNN)
    vec = VectorColumn(X, meta)
    ds = Dataset({"label": label, "features": vec})
    sc = SanityChecker(remove_bad_features=False, **kw)
    sc.fit_model([label, vec], ds)
    return sc.metadata["sanity_checker_summary"]


def test_sanity_checker_spearman_matches_scipy(rng):
    n, d = 600, 5
    X = rng.randn(n, d)
    X[:, 1] = np.exp(X[:, 1])          # monotone-transformed signal
    X[:, 3] = np.round(X[:, 3], 1)     # ties
    y = (X[:, 1] > np.median(X[:, 1])).astype(np.float64)
    s = _fit_summary(X, y, correlation_type="spearman")
    for j, c in enumerate(s["column_stats"]):
        want = scipy.stats.spearmanr(X[:, j], y).statistic
        np.testing.assert_allclose(c["corr_label"], want, rtol=1e-4, atol=1e-4)


def test_sanity_checker_spearman_invariant_to_monotone_transform(rng):
    """The defining property Pearson lacks: rank correlation is identical
    under strictly monotone feature transforms."""
    n = 400
    base = rng.randn(n)
    y = (base + 0.5 * rng.randn(n) > 0).astype(np.float64)
    X1 = np.stack([base, rng.randn(n)], axis=1)
    X2 = np.stack([np.exp(2.0 * base), rng.randn(n)], axis=1)
    X2[:, 1] = X1[:, 1]
    s1 = _fit_summary(X1, y, correlation_type="spearman")
    s2 = _fit_summary(X2, y, correlation_type="spearman")
    np.testing.assert_allclose(
        s1["column_stats"][0]["corr_label"],
        s2["column_stats"][0]["corr_label"],
        rtol=1e-5,
    )


def test_sanity_checker_rejects_unknown_correlation_type():
    with pytest.raises(ValueError, match="correlation_type"):
        SanityChecker(correlation_type="kendall")


def test_correlation_exclusion_hashed_text(rng):
    """correlation_exclusion='hashed_text' skips label correlation for
    hashed text dims (no grouping/indicator, Text-family parent) so
    max-corr dropping cannot fire on them, while pivoted/numeric columns
    keep their correlations (reference: SanityChecker.scala:595)."""
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.ops.text import SmartTextVectorizer
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.ops.combiner import VectorsCombiner
    from transmogrifai_tpu.preparators.sanity_checker import SanityChecker
    from transmogrifai_tpu.types import feature_types as ft

    n = 120
    words = ["alpha", "beta", "gamma", "delta", "epsi", "zeta"]
    texts = [" ".join(rng.choice(words, 3)) for _ in range(n)]
    x = rng.randn(n)
    y = (x + 0.2 * rng.randn(n) > 0).astype(float)
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    ftxt = FeatureBuilder(ft.Text, "t").as_predictor()
    fx = FeatureBuilder(ft.Real, "x").as_predictor()
    tvec = SmartTextVectorizer(max_cardinality=2, hash_dims=8,
                               track_nulls=False).set_input(ftxt).get_output()
    xvec = RealVectorizer(track_nulls=False).set_input(fx).get_output()
    vec = VectorsCombiner().set_input(tvec, xvec).get_output()
    checked = SanityChecker(
        remove_bad_features=False, correlation_exclusion="hashed_text"
    ).set_input(fy, vec).get_output()
    wf = OpWorkflow().set_result_features(checked).set_input_dataset(
        {"y": y.tolist(), "t": texts, "x": x.tolist()})
    model = wf.train()
    summary = next(
        s.metadata["sanity_checker_summary"] for s in model.stages
        if "sanity_checker_summary" in s.metadata
    )
    assert summary["correlation_excluded_columns"] == 8
    stats = summary["column_stats"]
    hashed = [c for c in stats if "hash" in c["name"]]
    assert len(hashed) == 8
    assert all(c["corr_label"] is None for c in hashed)
    numeric = [c for c in stats if c["parent"] == "x"]
    assert any(c["corr_label"] is not None for c in numeric)
    # default: no exclusion recorded, hashed columns DO get correlations
    with_corr = SanityChecker(remove_bad_features=False).set_input(
        fy, vec).get_output()
    wf2 = OpWorkflow().set_result_features(with_corr).set_input_dataset(
        {"y": y.tolist(), "t": texts, "x": x.tolist()})
    m2 = wf2.train()
    summary2 = next(
        s.metadata["sanity_checker_summary"] for s in m2.stages
        if "sanity_checker_summary" in s.metadata
    )
    assert summary2["correlation_excluded_columns"] == 0


def test_pmi_and_rule_confidence_hand_examples():
    """Hand-computed PMI / association-rule values (reference:
    OpStatistics.contingencyStats PMI + maxConfidences)."""
    from transmogrifai_tpu.utils.stats import (
        max_rule_confidences,
        pointwise_mutual_info,
    )

    # perfect association: diagonal cells carry ALL their row/col mass
    perfect = np.array([[50.0, 0.0], [0.0, 50.0]])
    pmi = pointwise_mutual_info(perfect)
    assert pmi[0, 0] == pytest.approx(1.0)   # log2(0.5 / 0.25)
    assert pmi[1, 1] == pytest.approx(1.0)
    assert pmi[0, 1] == 0.0 and pmi[1, 0] == 0.0  # zero cells -> 0
    conf, supp = max_rule_confidences(perfect)
    assert conf.tolist() == [1.0, 1.0]
    assert supp.tolist() == [0.5, 0.5]

    # independence: every pmi exactly 0
    ind = np.array([[20.0, 30.0], [40.0, 60.0]])
    np.testing.assert_allclose(pointwise_mutual_info(ind), 0.0, atol=1e-12)

    # asymmetric case, verified by hand: n=100
    # col 0: 30+10=40, max 30 -> conf .75, support .4
    # col 1: 20+40=60, max 40 -> conf 2/3, support .6
    c = np.array([[30.0, 20.0], [10.0, 40.0]])
    conf, supp = max_rule_confidences(c)
    assert conf[0] == pytest.approx(0.75)
    assert conf[1] == pytest.approx(2 / 3)
    assert supp.tolist() == [0.4, 0.6]
    pmi = pointwise_mutual_info(c)
    # pmi[0,0] = log2( .3 / (.5 * .4) ) = log2(1.5)
    assert pmi[0, 0] == pytest.approx(np.log2(1.5))
    assert pmi[1, 1] == pytest.approx(np.log2(0.4 / (0.5 * 0.6)))

    # degenerate: all-zero table and an empty column
    assert pointwise_mutual_info(np.zeros((2, 2))).tolist() == [[0, 0], [0, 0]]
    conf0, supp0 = max_rule_confidences(np.array([[5.0, 0.0], [5.0, 0.0]]))
    assert conf0[1] == 0.0 and supp0[1] == 0.0


def test_cramers_v_edge_cases():
    """Reference parity for the association statistic's edge behavior
    (OpStatistics.cramersV; SURVEY §4 names these cases): perfect
    association = 1, independence = 0, empty rows/cols filtered before
    the test, degenerate 1xk tables = 0, empty = 0."""
    import numpy as np

    from transmogrifai_tpu.utils.stats import cramers_v

    # perfect association (diagonal)
    assert cramers_v(np.array([[50, 0], [0, 50]])) == pytest.approx(1.0)
    assert cramers_v(np.array([[30, 0, 0], [0, 30, 0], [0, 0, 30]])) == (
        pytest.approx(1.0)
    )
    # exact independence (outer product of margins)
    ind = np.outer([40, 60], [30, 70]) / 100.0
    assert cramers_v(ind) == pytest.approx(0.0, abs=1e-12)
    # empty row AND empty column are filtered, not counted in dof
    with_empty = np.array([[50, 0, 0], [0, 50, 0], [0, 0, 0]])
    assert cramers_v(with_empty) == pytest.approx(1.0)
    # degenerate shapes
    assert cramers_v(np.array([[10, 20, 30]])) == 0.0  # 1 x k
    assert cramers_v(np.array([[10], [20]])) == 0.0    # k x 1
    assert cramers_v(np.zeros((3, 3))) == 0.0
    assert cramers_v(np.zeros((0, 0))) == 0.0
    # V is symmetric in table transpose
    t = np.array([[12, 7, 3], [5, 22, 9]])
    assert cramers_v(t) == pytest.approx(cramers_v(t.T))
    # bounded in [0, 1] on random tables
    rng = np.random.default_rng(0)
    for _ in range(20):
        tbl = rng.integers(0, 50, size=(3, 4))
        v = cramers_v(tbl)
        assert 0.0 <= v <= 1.0 + 1e-12
