"""Pallas TPU kernels: interpret-mode parity against the jnp reference
paths (tests run on the virtual CPU mesh, so pallas executes interpreted;
on real TPU the same kernels compile via Mosaic).

Reference semantics covered: SanityChecker's colStats+corr single pass
(SanityChecker.scala:575,633-637) and hist-tree bin assignment
(Spark findSplitsBySorting / xgboost sketch).
"""
import numpy as np
import pytest

from transmogrifai_tpu.parallel import pallas_kernels as pk

pytestmark = pytest.mark.skipif(
    not pk.HAS_PALLAS, reason="pallas unavailable"
)


def _moments_ref(x, y):
    x = x.astype(np.float64)
    y = y.astype(np.float64)
    return (
        x.sum(0), (x * x).sum(0), (x * y[:, None]).sum(0),
        y.sum(), (y * y).sum(), x.min(0), x.max(0),
    )


@pytest.mark.parametrize("n,d", [(100, 7), (512, 128), (1000, 37), (513, 129)])
def test_fused_moments_parity(n, d):
    """Unaligned shapes exercise partial row tiles and partial lane blocks."""
    rng = np.random.RandomState(0)
    x = rng.randn(n, d).astype(np.float32) * 3.0
    y = rng.rand(n).astype(np.float32)
    want = _moments_ref(x, y)
    got = pk.fused_moments(x, y, force_pallas=True)
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b, dtype=np.float64),
            rtol=3e-5, atol=3e-3,
        )


def test_fused_moments_chunked_combine(monkeypatch):
    """Above _CHUNK_ROWS the pass splits and partials combine in float64
    (the 2^24 float32 exactness cliff must not corrupt 10M-row stats);
    exercised here by shrinking the chunk threshold."""
    monkeypatch.setattr(pk, "_CHUNK_ROWS", 257)
    rng = np.random.RandomState(3)
    x = rng.randn(1000, 13).astype(np.float32) * 2.0
    y = rng.rand(1000).astype(np.float32)
    want = _moments_ref(x, y)
    got = pk.fused_moments(x, y, force_pallas=False)
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b, dtype=np.float64),
            rtol=3e-5, atol=3e-3,
        )


def test_fused_moments_jnp_fallback_matches():
    rng = np.random.RandomState(1)
    x = rng.randn(300, 20).astype(np.float32)
    y = rng.rand(300).astype(np.float32)
    a = pk.fused_moments(x, y, force_pallas=True)
    b = pk.fused_moments(x, y, force_pallas=False)
    for u, v in zip(a, b):
        np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                   rtol=3e-5, atol=3e-3)


@pytest.mark.parametrize("n,d", [(200, 9), (600, 19)])
def test_bin_matrix_matches_searchsorted(n, d):
    from transmogrifai_tpu.models.tree_kernel import quantile_bin_edges

    rng = np.random.RandomState(2)
    X = rng.randn(n, d).astype(np.float32)
    X[::13, d // 2] = np.nan  # NaN rows must match numpy's total order
    edges = quantile_bin_edges(X, 16)
    want = np.empty((n, d), np.int32)
    for j in range(d):
        want[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    got = np.asarray(pk.bin_matrix(X, edges, force_pallas=True))
    np.testing.assert_array_equal(got, want)
    got_jnp = np.asarray(pk.bin_matrix(X, edges, force_pallas=False))
    np.testing.assert_array_equal(got_jnp, want)


def test_sanity_checker_uses_fused_moments():
    """End-to-end: the checker's stats are unchanged by the kernel swap."""
    from transmogrifai_tpu.preparators.sanity_checker import SanityChecker
    rng = np.random.RandomState(3)
    n = 400
    x = np.stack([rng.randn(n), rng.randn(n) * 2 + 1, rng.rand(n)], axis=1)
    y = (x[:, 0] + 0.1 * rng.randn(n) > 0).astype(np.float64)
    # direct moment check through the dispatcher
    xs, xss, xys, ys, yss, xmin, xmax = (
        np.asarray(v) for v in pk.fused_moments(
            x.astype(np.float32), y.astype(np.float32)
        )
    )
    np.testing.assert_allclose(xs, x.sum(0), rtol=1e-4)
    np.testing.assert_allclose(float(ys), y.sum(), rtol=1e-5)
    corr = (n * xys[0] - xs[0] * ys) / (
        np.sqrt(n * xss[0] - xs[0] ** 2) * np.sqrt(n * yss - ys**2)
    )
    assert corr > 0.5  # x0 drives the label


def test_masked_rank_metrics_matches_host():
    """Device rank-sum AuROC / step AuPR vs the host threshold-grouping
    implementation (evaluators/binary._roc_pr_areas) on tie-free scores."""
    from transmogrifai_tpu.evaluators.binary import (
        _roc_pr_areas,
        masked_rank_metrics,
    )

    rng = np.random.RandomState(0)
    n, B = 500, 6
    y = (rng.rand(n) < 0.4).astype(np.float64)
    # scores on exact bin centers -> the device 1024-bin quantization is
    # lossless and its tie-grouping equals the host threshold grouping
    scores = rng.randint(0, 1024, size=(B, n)).astype(np.float64) / 1023.0
    scores[:, 0] = 0.0   # pin min/max so the affine bin map hits centers
    scores[:, 1] = 1.0
    vmask = rng.rand(B, n) < 0.5
    vmask[:, :2] = True
    auroc, aupr = masked_rank_metrics(scores, y, vmask)
    for b in range(B):
        m = vmask[b]
        want_roc, want_pr = _roc_pr_areas(y[m], scores[b][m])
        np.testing.assert_allclose(auroc[b], want_roc, atol=1e-4)
        np.testing.assert_allclose(aupr[b], want_pr, atol=1e-4)


def test_masked_rank_metrics_continuous_close():
    """Continuous scores: 1024-bin metrics within O(1/nbins) of exact."""
    from transmogrifai_tpu.evaluators.binary import (
        _roc_pr_areas,
        masked_rank_metrics,
    )

    rng = np.random.RandomState(1)
    n = 4000
    y = (rng.rand(n) < 0.35).astype(np.float64)
    scores = (rng.randn(1, n) + y[None, :] * 1.2)
    vmask = np.ones((1, n), dtype=bool)
    auroc, aupr = masked_rank_metrics(scores, y, vmask)
    want_roc, want_pr = _roc_pr_areas(y, scores[0])
    assert abs(auroc[0] - want_roc) < 5e-3
    assert abs(aupr[0] - want_pr) < 5e-3


def test_bin_matrix_jnp_fallback_chunked_parity(rng):
    """The row-chunked jnp fallback (the one-shot [n, d, E] comparison
    broadcast OOMed a 16 GB v5e at 1M x 512 x 63) must agree with the
    unchunked broadcast on a shape that forces multiple blocks."""
    import numpy as np

    from transmogrifai_tpu.parallel.pallas_kernels import bin_matrix

    n, d, E = 10_000, 512, 63  # block cap 2^27/(512*63) ~= 4161 -> 3 blocks
    x = rng.standard_normal((n, d)).astype(np.float32) \
        if hasattr(rng, "standard_normal") else rng.randn(n, d).astype(np.float32)
    x[::97, 3] = np.nan
    edges = np.sort(rng.randn(d, E), axis=1).astype(np.float32)
    got = np.asarray(bin_matrix(x, edges, False))
    lt = (edges[None, :, :] < x[:, :, None]).sum(-1)
    nan_e = (~np.isnan(edges)).sum(1)
    ref = np.where(np.isnan(x), nan_e[None, :], lt)
    assert got.shape == ref.shape
    np.testing.assert_array_equal(got, ref)
