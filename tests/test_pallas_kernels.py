"""Pallas TPU kernels: interpret-mode parity against the jnp reference
paths (tests run on the virtual CPU mesh, so pallas executes interpreted;
on real TPU the same kernels compile via Mosaic).

Reference semantics covered: SanityChecker's colStats+corr single pass
(SanityChecker.scala:575,633-637) and hist-tree bin assignment
(Spark findSplitsBySorting / xgboost sketch).
"""
import numpy as np
import pytest

from transmogrifai_tpu.parallel import pallas_kernels as pk

pytestmark = pytest.mark.skipif(
    not pk.HAS_PALLAS, reason="pallas unavailable"
)


def _moments_ref(x, y):
    x = x.astype(np.float64)
    y = y.astype(np.float64)
    return (
        x.sum(0), (x * x).sum(0), (x * y[:, None]).sum(0),
        y.sum(), (y * y).sum(), x.min(0), x.max(0),
    )


@pytest.mark.parametrize("n,d", [(100, 7), (512, 128), (1000, 37), (513, 129)])
def test_fused_moments_parity(n, d):
    """Unaligned shapes exercise partial row tiles and partial lane blocks."""
    rng = np.random.RandomState(0)
    x = rng.randn(n, d).astype(np.float32) * 3.0
    y = rng.rand(n).astype(np.float32)
    want = _moments_ref(x, y)
    got = pk.fused_moments(x, y, force_pallas=True)
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b, dtype=np.float64),
            rtol=3e-5, atol=3e-3,
        )


def test_fused_moments_jnp_fallback_matches():
    rng = np.random.RandomState(1)
    x = rng.randn(300, 20).astype(np.float32)
    y = rng.rand(300).astype(np.float32)
    a = pk.fused_moments(x, y, force_pallas=True)
    b = pk.fused_moments(x, y, force_pallas=False)
    for u, v in zip(a, b):
        np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                   rtol=3e-5, atol=3e-3)


@pytest.mark.parametrize("n,d", [(200, 9), (600, 19)])
def test_bin_matrix_matches_searchsorted(n, d):
    from transmogrifai_tpu.models.tree_kernel import quantile_bin_edges

    rng = np.random.RandomState(2)
    X = rng.randn(n, d).astype(np.float32)
    X[::13, d // 2] = np.nan  # NaN rows must match numpy's total order
    edges = quantile_bin_edges(X, 16)
    want = np.empty((n, d), np.int32)
    for j in range(d):
        want[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    got = np.asarray(pk.bin_matrix(X, edges, force_pallas=True))
    np.testing.assert_array_equal(got, want)
    got_jnp = np.asarray(pk.bin_matrix(X, edges, force_pallas=False))
    np.testing.assert_array_equal(got_jnp, want)


def test_sanity_checker_uses_fused_moments():
    """End-to-end: the checker's stats are unchanged by the kernel swap."""
    from transmogrifai_tpu.preparators.sanity_checker import SanityChecker
    rng = np.random.RandomState(3)
    n = 400
    x = np.stack([rng.randn(n), rng.randn(n) * 2 + 1, rng.rand(n)], axis=1)
    y = (x[:, 0] + 0.1 * rng.randn(n) > 0).astype(np.float64)
    # direct moment check through the dispatcher
    xs, xss, xys, ys, yss, xmin, xmax = (
        np.asarray(v) for v in pk.fused_moments(
            x.astype(np.float32), y.astype(np.float32)
        )
    )
    np.testing.assert_allclose(xs, x.sum(0), rtol=1e-4)
    np.testing.assert_allclose(float(ys), y.sum(), rtol=1e-5)
    corr = (n * xys[0] - xs[0] * ys) / (
        np.sqrt(n * xss[0] - xs[0] ** 2) * np.sqrt(n * yss - ys**2)
    )
    assert corr > 0.5  # x0 drives the label
