"""Registry drills (ISSUE 5): versioned store, hot-swap, canary, rollback.

Covers the model-lifecycle control loop end to end: content-addressed
publish over crash-consistent artifacts, the checksummed registry index
surviving crash/corruption via ``.last-good``, the stage machine
(candidate → canary → stable → rolled_back), zero-downtime hot-swap
under concurrent scoring threads (no dropped / duplicated /
mixed-generation batch), deterministic hash canary splits, shadow
scoring, and signal-driven automatic rollback with recorded evidence —
plus the ``registry.publish_crash`` / ``registry.swap_crash`` /
``canary.regression`` / ``canary.latency`` fault points that drill each
window.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from transmogrifai_tpu import cli
from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.faults.injection import InjectedFault
from transmogrifai_tpu.registry import (
    DeploymentController,
    ModelRegistry,
    RegistryError,
    RegistryIntegrityError,
    RollbackPolicy,
)
from transmogrifai_tpu.serving import RowScoringError, ServingTelemetry
from transmogrifai_tpu.testkit.drills import (
    REGISTRY_CRASH_PUBLISHER_TEMPLATE,
    drill_env,
    tiny_drill_pipeline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm():
    faults.reset()
    yield
    faults.reset()


def _trained():
    wf, data, records, name = tiny_drill_pipeline()
    return wf.train(), records, name


def _trained_variant(seed: int = 1):
    """A second model whose FEATURE NAMES match the first pipeline's
    (uids reset, so the result feature carries the same suffix — the
    registry serves versions of ONE workflow definition, not arbitrary
    foreign models)."""
    from transmogrifai_tpu.utils.uid import reset_uids

    reset_uids()
    return tiny_drill_pipeline(seed=seed)[0].train()


def _fresh_workflow():
    from transmogrifai_tpu.utils.uid import reset_uids

    reset_uids()
    return tiny_drill_pipeline()[0]


# ---------------------------------------------------------------------------
# ModelRegistry: publish / index / verify
# ---------------------------------------------------------------------------
def test_publish_records_content_address_and_lineage(tmp_path):
    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    v1 = reg.publish(model, metrics={"auroc": 0.91})
    assert v1.version == "v1" and v1.stage == "candidate"
    assert len(v1.manifest_sha256) == 64
    assert v1.schema_sha256 is not None  # the tiny pipeline has a contract
    assert v1.metrics == {"auroc": 0.91}
    assert v1.parent is None
    reg.promote("v1", to="stable")
    # the second publish records the current stable as its parent
    v2 = reg.publish(model)
    assert v2.version == "v2" and v2.parent == "v1"
    events = [e["event"] for e in reg.lineage()]
    assert events == ["publish", "promote", "publish"]
    listed = reg.versions()
    assert [v.version for v in listed] == ["v1", "v2"]
    with pytest.raises(RegistryError, match="v9"):
        reg.get("v9")


def test_registry_index_recovers_from_last_good(tmp_path):
    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    reg.promote("v1", to="stable")  # second commit: last-good now exists
    index = os.path.join(root, "registry.json")
    with open(index, "r+b") as f:
        f.seek(10)
        f.write(b"XXXX")  # bit-flip the primary
    reg2 = ModelRegistry(root, create=False)
    report = reg2.verify()
    assert report["recovered_from_last_good"]
    # a registry serving from last-good is one commit stale: verify must
    # FAIL loudly even though it stays operable
    assert not report["index_ok"] and not report["ok"]
    # last-good predates the promote; the version itself must be there
    assert "v1" in {v.version for v in reg2.versions()}
    # the next commit must NOT snapshot the corrupt primary over the
    # only good last-good copy (that would brick the registry if the
    # commit then crashed); after it, both copies verify again
    reg2.publish(model)
    report = reg2.verify()
    assert report["index_ok"] and report["ok"]
    assert "v2" in {v.version for v in reg2.versions()}


def test_registry_index_both_damaged_is_loud(tmp_path):
    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    reg.promote("v1", to="stable")
    for name in ("registry.json", "registry.json.last-good"):
        with open(os.path.join(root, name), "w") as f:
            f.write("{not json")
    with pytest.raises(RegistryIntegrityError, match="last-good"):
        ModelRegistry(root, create=False).versions()


def test_verify_reports_tamper_and_orphans(tmp_path):
    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    # orphan: an artifact directory the index never committed (the
    # publish crash window)
    os.makedirs(os.path.join(root, "versions", "v99", "junk"))
    npz = os.path.join(root, "versions", "v1", "arrays.npz")
    with open(npz, "r+b") as f:
        f.seek(30)
        f.write(b"\x00\x00\x00\x00")
    report = reg.verify()
    assert not report["ok"]
    assert "checksum" in report["versions"]["v1"]
    assert os.path.join("versions", "v99") in report["orphans"]


def test_load_verifies_the_registered_content_address(tmp_path):
    model, records, name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    wf2 = _fresh_workflow()
    loaded = reg.load("v1", wf2)
    assert loaded.schema_contract is not None
    scored = loaded.score_function()(dict(records[0]))
    assert loaded.result_features[0].name in scored
    # replace the artifact OUTSIDE the registry: content address breaks
    # even though the artifact itself is internally consistent
    from transmogrifai_tpu.serialization.model_io import save_model

    model2 = _trained_variant()
    save_model(model2, os.path.join(root, "versions", "v1"))
    with pytest.raises(RegistryIntegrityError, match="manifest"):
        reg.load("v1", _fresh_workflow())


# ---------------------------------------------------------------------------
# stage machine
# ---------------------------------------------------------------------------
def test_stage_machine_and_invalid_transitions(tmp_path):
    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(model)
    reg.publish(model)
    reg.promote("v1", to="stable")
    reg.promote("v2", to="canary")
    assert reg.stable == "v1" and reg.canary == "v2"
    # a second canary cannot evict the live one silently
    reg.publish(model)
    with pytest.raises(RegistryError, match="canary slot"):
        reg.promote("v3", to="canary")
    # canary graduates: stable advances, old stable retires
    reg.promote("v2", to="stable")
    assert reg.stable == "v2" and reg.canary is None
    assert reg.get("v1").stage == "retired"
    # a retired version cannot be re-promoted without re-publishing
    with pytest.raises(RegistryError, match="retired"):
        reg.promote("v1", to="stable")


def test_rollback_stable_reverts_to_parent(tmp_path):
    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(model)
    reg.promote("v1", to="stable")
    reg.publish(model)  # parent = v1
    reg.promote("v2", to="stable")
    event = reg.rollback(reason="bad release")
    assert event["version"] == "v2"
    assert event["stable_reverted_to"] == "v1"
    assert reg.stable == "v1"
    assert reg.get("v2").stage == "rolled_back"
    assert reg.get("v1").stage == "stable"
    # nothing left to revert to: v1 has no parent
    with pytest.raises(RegistryError, match="no parent"):
        reg.rollback()


def test_publish_directly_into_a_stage(tmp_path):
    """publish(stage=...) promotes after the index commit — it must not
    deadlock on the cross-process registry lock it already holds (the
    flock is per-fd, not per-process)."""
    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    v1 = reg.publish(model, stage="stable")
    assert v1.stage == "stable" and reg.stable == "v1"
    v2 = reg.publish(model, stage="canary")
    assert v2.stage == "canary" and reg.canary == "v2"
    with pytest.raises(RegistryError, match="retired"):
        reg.publish(model, stage="retired")


def test_orphaned_version_ids_are_never_reissued(tmp_path):
    """A version directory without an index entry (mid-publish crash, or
    a concurrent publisher's reservation) consumes its id: the next
    publish must skip it, not overwrite it."""
    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    os.makedirs(os.path.join(root, "versions", "v2"))  # crash orphan
    v3 = reg.publish(model)
    assert v3.version == "v3"
    assert os.path.join("versions", "v2") in reg.verify()["orphans"]


def test_rollback_never_reinstates_a_rolled_back_parent(tmp_path):
    """A parent the operator explicitly demoted must not silently
    become the serving stable again when its child rolls back."""
    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(model)
    reg.promote("v1", to="stable")
    reg.publish(model)
    reg.promote("v2", to="stable")  # v1 -> retired
    reg.rollback(version="v1", reason="v1 is bad too")  # -> rolled_back
    with pytest.raises(RegistryError, match="rolled_back"):
        reg.rollback(reason="v2 regressed")
    assert reg.stable == "v2"  # nothing silently reverted


def test_versions_listing_tolerates_non_canonical_ids(tmp_path):
    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    # hand-migrated id the next-version logic already warns about:
    # the listing (and so `tx registry list`) must not crash on it
    with reg._exclusive():
        doc = reg._read()
        entry = dict(doc["versions"]["v1"], version="legacy-2024")
        doc["versions"]["legacy-2024"] = entry
        reg._commit(doc)
    listed = reg.versions()
    assert [v.version for v in listed] == ["v1", "legacy-2024"]
    v2 = reg.publish(model)  # canonical numbering continues from v1
    assert v2.version == "v2"


def test_publish_attributes_process_telemetry(tmp_path):
    from transmogrifai_tpu.parallel.resilience import mesh_telemetry
    from transmogrifai_tpu.schema import data_telemetry

    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    v1 = reg.publish(model)
    # the process-wide training-side accumulators now name the version
    # their metrics produced
    assert data_telemetry().snapshot()["model_version"] == v1.version
    assert mesh_telemetry().snapshot()["model_version"] == v1.version


def test_rollback_empty_registry_is_loud(tmp_path):
    reg = ModelRegistry(str(tmp_path / "reg"))
    with pytest.raises(RegistryError, match="nothing to roll back"):
        reg.rollback()


def test_release_canary_frees_the_slot_without_judgement(tmp_path):
    """Ending an observation window undecided returns the version to
    candidate (re-promotable), unlike a rollback."""
    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(model, stage="stable")
    reg.publish(model, stage="canary")
    event = reg.release_canary(reason="run ended")
    assert event["version"] == "v2"
    assert reg.canary is None
    assert reg.get("v2").stage == "candidate"
    assert reg.lineage()[-1]["event"] == "canary_release"
    # undecided, not condemned: the same version can canary again
    reg.promote("v2", to="canary")
    assert reg.canary == "v2"
    # nothing to release is a no-op, not an error
    reg.release_canary()
    assert reg.release_canary() is None


def test_describe_is_one_consistent_view(tmp_path):
    model, _records, _name = _trained()
    reg = ModelRegistry(str(tmp_path / "reg"))
    reg.publish(model, stage="stable")
    doc = reg.describe(lineage=True)
    assert doc["stable"] == "v1" and doc["canary"] is None
    assert [v["version"] for v in doc["versions"]] == ["v1"]
    assert [e["event"] for e in doc["lineage"]] == ["publish", "promote"]


# ---------------------------------------------------------------------------
# publish crash window (registry.publish_crash)
# ---------------------------------------------------------------------------
def test_publish_crash_leaves_registry_at_prior_version(tmp_path):
    root = str(tmp_path / "reg")
    script = tmp_path / "publisher.py"
    script.write_text(REGISTRY_CRASH_PUBLISHER_TEMPLATE.format(
        repo=REPO, root=root, fault="registry.publish_crash:on=1"))
    proc = subprocess.run([sys.executable, str(script)], env=drill_env(),
                          timeout=300)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT  # really crashed
    reg = ModelRegistry(root, create=False)
    # the index never saw v2: the registry is loadable at v1
    assert [v.version for v in reg.versions()] == ["v1"]
    assert reg.stable == "v1"
    report = reg.verify()
    assert report["versions"]["v1"] is None  # prior version intact
    # the half-published v2 artifact is an orphan, reported not trusted
    assert any("v2" in o for o in report["orphans"])
    loaded = reg.load_stable(_fresh_workflow())
    assert loaded.schema_contract is not None


def test_publish_crash_cli_verify_reports_prior_intact(tmp_path, capsys):
    root = str(tmp_path / "reg")
    script = tmp_path / "publisher.py"
    script.write_text(REGISTRY_CRASH_PUBLISHER_TEMPLATE.format(
        repo=REPO, root=root, fault="registry.publish_crash:on=1"))
    proc = subprocess.run([sys.executable, str(script)], env=drill_env(),
                          timeout=300)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT
    rc = cli.main(["registry", "verify", "--root", root])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["ok"]
    assert report["versions"]["v1"] is None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def test_cli_list_promote_rollback_roundtrip(tmp_path, capsys):
    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    reg.publish(model)
    assert cli.main(["registry", "promote", "--root", root,
                     "--version", "v1"]) == 0
    capsys.readouterr()
    assert cli.main(["registry", "promote", "--root", root,
                     "--version", "v2", "--to", "canary"]) == 0
    capsys.readouterr()
    assert cli.main(["registry", "list", "--root", root,
                     "--lineage"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["stable"] == "v1" and doc["canary"] == "v2"
    assert [e["event"] for e in doc["lineage"]][:2] == [
        "publish", "publish"]
    assert cli.main(["registry", "rollback", "--root", root,
                     "--reason", "drill"]) == 0
    event = json.loads(capsys.readouterr().out)
    assert event["version"] == "v2" and event["reason"] == "drill"
    # invalid transitions surface as JSON errors + exit 2, not tracebacks
    assert cli.main(["registry", "promote", "--root", root,
                     "--version", "v2"]) == 2
    assert "error" in json.loads(capsys.readouterr().out)


def test_cli_verify_exits_nonzero_on_damage(tmp_path, capsys):
    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    npz = os.path.join(root, "versions", "v1", "arrays.npz")
    with open(npz, "r+b") as f:
        f.seek(40)
        f.write(b"\xff\xff")
    assert cli.main(["registry", "verify", "--root", root]) == 1
    report = json.loads(capsys.readouterr().out)
    assert not report["ok"]
    # a missing registry is exit 2 (operational error, not damage)
    assert cli.main(["registry", "list", "--root",
                     str(tmp_path / "nope")]) == 2


# ---------------------------------------------------------------------------
# DeploymentController: hot-swap
# ---------------------------------------------------------------------------
def test_hot_swap_serves_without_interruption(tmp_path):
    model, records, name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8))
    ctl.deploy(model, version="v1")
    out1 = ctl.score_batch(records[:8])
    assert len(out1) == 8 and all(name in r for r in out1)
    model2 = _trained_variant()
    gen2 = ctl.deploy(model2, version="v2")
    assert gen2.generation == 2
    out2 = ctl.score_batch(records[:8])
    assert len(out2) == 8 and all(name in r for r in out2)
    # the swap is in the lifecycle log with its latency evidence
    swaps = [e for e in ctl.events() if e["event"] == "swap"]
    assert len(swaps) == 2
    assert swaps[1]["from_version"] == "v1"
    assert swaps[1]["flip_us"] < 1e6  # the flip is a pointer write
    # per-generation telemetry attribution (satellite: shared field)
    snap = gen2.endpoint.telemetry.snapshot()
    assert snap["model_version"] == "v2" and snap["generation"] == 2


def test_swap_crash_leaves_old_generation_serving(tmp_path):
    model, records, name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8))
    ctl.deploy(model, version="v1")
    model2 = _trained_variant()
    faults.configure("registry.swap_crash:on=1")
    with pytest.raises(InjectedFault):
        ctl.deploy(model2, version="v2")
    faults.reset()
    gen = ctl.stable_generation
    assert gen.version == "v1" and gen.generation == 1
    out = ctl.score_batch(records[:4])
    assert all(name in r for r in out)
    # the failed deploy left no half-registered generation behind: the
    # next deploy gets a clean consecutive id
    gen2 = ctl.deploy(model2, version="v2")
    assert gen2.generation == 2


def test_concurrent_scoring_through_hot_swaps_drops_nothing(tmp_path):
    """Threads score continuously while the main thread hot-swaps twice:
    every submitted batch returns exactly its own results (no drop, no
    duplicate, no error), and each call observes exactly ONE stable
    generation (never a half-swapped mix)."""
    model, records, name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8))
    generations = [ctl.deploy(model, version="v1")]
    stop = threading.Event()
    failures: list[str] = []
    counts = {"batches": 0, "rows": 0}
    lock = threading.Lock()

    def pump(tid: int):
        i = 0
        while not stop.is_set():
            batch = [dict(records[(i + j + tid) % len(records)])
                     for j in range(4)]
            try:
                out, info = ctl.score_batch_with_info(batch)
            except Exception as e:  # noqa: BLE001 - the invariant under test
                failures.append(f"t{tid}: {type(e).__name__}: {e}")
                return
            if len(out) != len(batch):
                failures.append(f"t{tid}: {len(out)} results for "
                                f"{len(batch)} rows")
                return
            bad = [r for r in out
                   if isinstance(r, RowScoringError) or name not in r]
            if bad:
                failures.append(f"t{tid}: bad rows during swap: {bad[:2]}")
                return
            if info["stable_generation"] not in (1, 2, 3):
                failures.append(f"t{tid}: unknown generation {info}")
                return
            with lock:
                counts["batches"] += 1
                counts["rows"] += len(out)
            i += 4

    threads = [threading.Thread(target=pump, args=(t,)) for t in range(3)]
    for t in threads:
        t.start()
    try:
        for seed, version in ((1, "v2"), (2, "v3")):
            time.sleep(0.15)
            m = _trained_variant(seed=seed)
            generations.append(ctl.deploy(m, version=version))
    finally:
        time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join(30)
    assert not failures, failures[:3]
    assert counts["batches"] > 0
    assert len([e for e in ctl.events() if e["event"] == "swap"]) == 3
    # conservation: every submitted row landed in exactly one
    # generation's request accounting (none dropped, none double-counted)
    telem_rows = sum(
        g.endpoint.telemetry.snapshot()["rows_scored"]
        for g in generations
    )
    assert telem_rows == counts["rows"]


def test_canary_arm_failure_never_fails_stable_rows(tmp_path):
    """A canary defect that raises out of its endpoint (e.g. a stricter
    contract under drift_policy='raise') must not take down the
    stable-routed share of the batch: its rows re-score on stable and
    the failure lands in the CANARY's telemetry for the policy."""
    model, records, name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8), canary_fraction=0.5,
                               check_every_batches=1000)
    ctl.deploy(model, version="v1")
    canary_gen = ctl.start_canary(_trained_variant(), version="v2")

    def boom(records):
        raise RuntimeError("canary endpoint defect")

    canary_gen.endpoint.score_batch = boom
    out, info = ctl.score_batch_with_info(records[:16])
    assert len(out) == 16
    assert not any(isinstance(r, RowScoringError) for r in out)
    assert info["canary_rows"] > 0
    snap = canary_gen.endpoint.telemetry.snapshot()
    assert snap["rows_failed"] == info["canary_rows"]


def test_deploy_version_rejects_ineligible_stage_without_swapping(tmp_path):
    model, records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    reg.promote("v1", to="stable")
    reg.publish(model)
    reg.promote("v2", to="stable")  # v1 is now retired
    ctl = DeploymentController(registry=reg, batch_buckets=(1, 8))
    ctl.deploy_version("v2", _fresh_workflow())
    # redeploying the retired v1 must fail FAST: live pointer and
    # registry both untouched (revert goes through registry.rollback)
    with pytest.raises(RegistryError, match="retired"):
        ctl.deploy_version("v1", _fresh_workflow())
    assert ctl.stable_generation.version == "v2"
    assert reg.stable == "v2"


def test_start_canary_validates_before_building(tmp_path):
    model, _records, _name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8))
    ctl.deploy(model, version="v1")
    with pytest.raises(ValueError, match="fraction"):
        ctl.start_canary(model, version="v2", fraction=1.5)
    # the bad input cost no generation id and left no canary behind
    assert ctl.canary_generation is None
    gen = ctl.start_canary(model, version="v2", fraction=0.5)
    assert gen.generation == 2


# ---------------------------------------------------------------------------
# canary routing + shadow + rollback
# ---------------------------------------------------------------------------
def test_canary_split_is_deterministic_and_proportional(tmp_path):
    model, records, _name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8), canary_fraction=0.3)
    ctl.deploy(model, version="v1")
    routes = [ctl.routes_to_canary(r) for r in records]
    # deterministic: the same records route identically on every call
    assert routes == [ctl.routes_to_canary(r) for r in records]
    frac = sum(routes) / len(routes)
    assert 0.05 < frac < 0.6  # 120 hashed records around 0.3
    # fraction 0 and 1 are exact
    assert not any(ctl.routes_to_canary(r, fraction=0.0) for r in records)
    assert all(ctl.routes_to_canary(r, fraction=1.0) for r in records)


def test_canary_scores_its_share_and_promotes(tmp_path):
    model, records, name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8), canary_fraction=0.5,
                               check_every_batches=1000)
    ctl.deploy(model, version="v1")
    model2 = _trained_variant()
    ctl.start_canary(model2, version="v2")
    out, info = ctl.score_batch_with_info(records[:32])
    assert len(out) == 32 and all(name in r for r in out)
    assert 0 < info["canary_rows"] < 32
    c_snap = ctl.canary_generation.endpoint.telemetry.snapshot()
    assert c_snap["rows_scored"] == info["canary_rows"]
    assert c_snap["model_version"] == "v2"
    promoted = ctl.promote_canary()
    assert ctl.stable_generation is promoted
    assert ctl.canary_generation is None


def test_shadow_scoring_never_touches_responses(tmp_path):
    model, records, name = _trained()
    ctl = DeploymentController(batch_buckets=(1, 8),
                               check_every_batches=1000)
    ctl.deploy(model, version="v1")
    baseline = ctl.score_batch(records[:16])
    model2 = _trained_variant()
    ctl.start_canary(model2, version="v2", shadow=True)
    shadowed, info = ctl.score_batch_with_info(records[:16])
    # responses are stable's, bit-identical to the pre-canary scores
    assert shadowed == baseline
    assert info["shadow_rows"] == 16
    stats = ctl.shadow_stats()
    assert stats["rows"] == 16
    # two differently-seeded models disagree somewhere
    assert stats["rows_differed"] > 0
    assert stats["max_abs_delta"] > 0


def test_canary_regression_fault_triggers_auto_rollback(tmp_path):
    model, records, _name = _trained()
    ctl = DeploymentController(
        batch_buckets=(1, 8), canary_fraction=0.5,
        policy=RollbackPolicy(min_canary_rows=8), check_every_batches=1,
    )
    ctl.deploy(model, version="v1")
    model2 = _trained_variant()
    canary_gen = ctl.start_canary(model2, version="v2")
    faults.configure("canary.regression:every=1")
    try:
        for _ in range(8):
            ctl.score_batch(records[:16])
            if ctl.canary_generation is None:
                break
    finally:
        faults.reset()
    assert ctl.canary_generation is None  # demoted automatically
    rollbacks = [e for e in ctl.events() if e["event"] == "rollback"]
    assert len(rollbacks) == 1
    reasons = {r["signal"] for r in rollbacks[0]["reasons"]}
    assert "nonfinite_rows" in reasons  # the guard saw the poison
    # evidence names both arms with their live numbers
    assert rollbacks[0]["evidence"]["canary"]["breaker"][
        "rows_nonfinite"] > 0
    # the decision also landed in the demoted generation's telemetry
    snap = canary_gen.endpoint.telemetry.snapshot()
    assert any(e["event"] == "rollback" for e in snap["lifecycle"])
    # stable keeps serving untouched
    out = ctl.score_batch(records[:8])
    assert not any(isinstance(r, RowScoringError) for r in out)


def test_canary_latency_fault_trips_the_latency_slo(tmp_path):
    model, records, _name = _trained()
    ctl = DeploymentController(
        batch_buckets=(1, 8), canary_fraction=0.5,
        policy=RollbackPolicy(min_canary_rows=8, max_latency_ratio=3.0,
                              max_breaker_opens=None,
                              max_nonfinite_rows=None,
                              max_failed_ratio=None),
        check_every_batches=1,
    )
    ctl.deploy(model, version="v1")
    model2 = _trained_variant()
    ctl.start_canary(model2, version="v2")
    # warm both arms' latency samples before arming the slowdown
    for _ in range(4):
        ctl.score_batch(records[:16])
    assert ctl.canary_generation is not None  # healthy so far
    faults.configure("canary.latency:every=1:delay=0.25")
    try:
        for _ in range(10):
            ctl.score_batch(records[:16])
            if ctl.canary_generation is None:
                break
    finally:
        faults.reset()
    assert ctl.canary_generation is None
    rollbacks = [e for e in ctl.events() if e["event"] == "rollback"]
    assert {r["signal"] for r in rollbacks[0]["reasons"]} == {
        "p99_latency_ratio"}
    assert rollbacks[0]["reasons"][0]["value"] > 3.0


def test_manual_rollback_and_registry_lineage(tmp_path):
    model, records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model)
    reg.promote("v1", to="stable")
    model2 = _trained_variant()
    reg.publish(model2)
    ctl = DeploymentController(registry=reg, batch_buckets=(1, 8))
    wf_a, wf_b = _fresh_workflow(), _fresh_workflow()
    ctl.deploy_version("v1", wf_a)
    ctl.start_canary_version("v2", wf_b, fraction=0.5)
    assert reg.get("v2").stage == "canary"
    ctl.score_batch(records[:16])
    event = ctl.rollback_canary(reason="operator said so")
    assert event["reason"] == "operator said so"
    assert reg.get("v2").stage == "rolled_back"
    assert reg.canary is None
    tail = reg.lineage()[-1]
    assert tail["event"] == "rollback" and tail["version"] == "v2"


# ---------------------------------------------------------------------------
# shared telemetry attribution field (satellite)
# ---------------------------------------------------------------------------
def test_all_three_telemetry_tiers_carry_model_version():
    from transmogrifai_tpu.parallel.resilience import MeshTelemetry
    from transmogrifai_tpu.schema import DataTelemetry

    for cls in (ServingTelemetry, DataTelemetry, MeshTelemetry):
        t = cls()
        snap = t.snapshot()
        assert snap["model_version"] is None and snap["generation"] is None
        t.set_model_version("v7", generation=3)
        snap = t.snapshot()
        assert snap["model_version"] == "v7", cls.__name__
        assert snap["generation"] == 3, cls.__name__


# ---------------------------------------------------------------------------
# runner deploy run type
# ---------------------------------------------------------------------------
def test_runner_deploy_run_publishes_and_serves(tmp_path):
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    wf, _data, _records, _name = tiny_drill_pipeline()
    model = wf.train()
    model_dir = str(tmp_path / "model")
    model.save(model_dir)
    root = str(tmp_path / "reg")
    runner = OpWorkflowRunner(tiny_drill_pipeline()[0])
    params = OpParams(
        model_location=model_dir,
        metrics_location=str(tmp_path / "metrics"),
        custom_params={"registry_root": root, "deploy_batch_rows": 32},
    )
    res = runner.run("deploy", params)
    m = res.metrics
    assert m["rows_submitted"] == 120 and m["rows_failed"] == 0
    assert m["published_version"] == "v1"
    assert m["deployed_version"] == "v1"
    assert m["stable"]["telemetry"]["model_version"] == "v1"
    exported = json.load(
        open(os.path.join(str(tmp_path / "metrics"),
                          "deploy_metrics.json")))
    assert exported["deployed_version"] == "v1"
    # the registry now records v1 as stable
    assert ModelRegistry(root, create=False).stable == "v1"


def test_runner_deploy_releases_an_undecided_canary(tmp_path):
    """A deploy run ending with its canary neither promoted nor rolled
    back must free the registry's canary slot (back to candidate), so a
    later run's canary never serves while the registry points at a
    stale one.  Each registry load gets a FRESH workflow via the
    factory — two versions with different blacklists cannot share one
    workflow object."""
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    model, _records, _name = _trained()
    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    reg.publish(model, stage="stable")
    reg.publish(_trained_variant())
    runner = OpWorkflowRunner(_fresh_workflow(),
                              workflow_factory=_fresh_workflow)
    params = OpParams(custom_params={
        "registry_root": root, "canary_version": "v2",
        "canary_fraction": 0.3, "deploy_batch_rows": 32,
    })
    res = runner.run("deploy", params)
    m = res.metrics
    assert m["rows_failed"] == 0
    assert m["canary_released"] is not None
    reg2 = ModelRegistry(root, create=False)
    assert reg2.canary is None
    assert reg2.get("v2").stage == "candidate"  # undecided, not condemned
    assert reg2.lineage()[-1]["event"] == "canary_release"
