"""Test configuration: fake 8-device CPU mesh.

The reference tests against local[2] Spark (reference: utils/.../test/
TestSparkContext.scala:33-76); the analogous strategy here is CPU jax with
8 virtual host devices so sharding/collective code paths run in-process.
Must run before jax initializes.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin registers itself via sitecustomize in every python
# process.  Unit tests must run on the virtual CPU mesh and never block on
# the TPU tunnel, so drop the axon backend factory before jax initializes.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # pallas must import while "tpu" is still a known platform (its TPU
    # lowering registrations reject unknown platforms), so pull it in
    # before the factory purge below
    try:
        from jax.experimental import pallas as _pl  # noqa: F401
        from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
    except Exception:
        pass
    from jax._src import xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from transmogrifai_tpu.utils.uid import reset_uids  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_uids():
    reset_uids()
    yield


@pytest.fixture
def rng():
    return np.random.RandomState(42)
