"""Test configuration: fake 8-device CPU mesh.

The reference tests against local[2] Spark (reference: utils/.../test/
TestSparkContext.scala:33-76); the analogous strategy here is CPU jax with
8 virtual host devices so sharding/collective code paths run in-process.
Must run before jax initializes.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from transmogrifai_tpu.utils.uid import reset_uids  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_uids():
    reset_uids()
    yield


@pytest.fixture
def rng():
    return np.random.RandomState(42)
