"""Test configuration: fake 8-device CPU mesh.

The reference tests against local[2] Spark (reference: utils/.../test/
TestSparkContext.scala:33-76); the analogous strategy here is CPU jax with
8 virtual host devices so sharding/collective code paths run in-process.
Must run before jax initializes.
"""
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin registers itself via sitecustomize in every python
# process.  Unit tests must run on the virtual CPU mesh and never block on
# the TPU tunnel.  Set the env unconditionally (hang-proof even if the
# shared guard module were missing), then let the guard purge the non-cpu
# backend factories before jax initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
).strip()
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8"
).strip()
try:
    from _backend_guard import ensure_cpu_mesh

    _mesh_ok = ensure_cpu_mesh(8)  # not inside assert: -O must still purge
    assert _mesh_ok, "cannot provision the 8-device CPU test mesh"
except ImportError:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from transmogrifai_tpu.utils.uid import reset_uids  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_uids():
    reset_uids()
    yield


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture
def subprocess_env():
    """Environment for tests that spawn python subprocesses: the repo on
    PYTHONPATH (the package is not pip-installed), CPU jax, and the axon
    plugin neutralized so a wedged tunnel cannot hang the child."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env
