"""CLI project-generator fuzz: messy CSVs through `gen` + the generated
project's training script.

The fixed-CSV CLI tests pin the happy paths; this drives type inference
over adversarial columns - unicode headers, numeric-looking strings,
all-null columns, constant columns, mixed-type cells - and then RUNS the
generated train script to prove the scaffold survives its own data.
"""
from __future__ import annotations

import csv
import os
import subprocess
import sys

import numpy as np
import pytest

from transmogrifai_tpu.cli import generate

_WORDS = ("lorem ipsum dolor sit amet consectetur adipiscing elit sed do "
          "eiusmod tempor incididunt labore magna aliqua enim minim veniam "
          "quis nostrud exercitation").split()


def _messy_csv(path, rng, n=80):
    cols = {
        "id": [f"row-{i}" for i in range(n)],
        # numeric-looking strings with junk in a few cells
        "amount": [
            ("" if rng.rand() < 0.1 else
             ("N/A" if rng.rand() < 0.05 else f"{rng.randn() * 10 + 50:.3f}"))
            for _ in range(n)
        ],
        # unicode header + categorical values with spaces
        "catégorie": [
            ["rouge", "vert", "bleu", " vert "][rng.randint(4)]
            for _ in range(n)
        ],
        "all_null": ["" for _ in range(n)],
        "constant": ["same" for _ in range(n)],
        "freetext": [
            " ".join(_WORDS[rng.randint(len(_WORDS))]
                     for _ in range(rng.randint(2, 7)))
            for _ in range(n)
        ],
        "email": [
            (f"user{i}@example.com" if rng.rand() > 0.2 else "")
            for i in range(n)
        ],
    }
    label = (rng.rand(n) > 0.5).astype(int)
    cols["target"] = [str(v) for v in label]
    with open(path, "w", newline="", encoding="utf-8") as f:
        w = csv.writer(f)
        w.writerow(cols.keys())
        for i in range(n):
            w.writerow([cols[k][i] for k in cols])
    return path


@pytest.mark.parametrize("seed", [71, 72])
def test_generate_on_messy_csv_and_run_training(tmp_path, seed, subprocess_env):
    rng = np.random.RandomState(seed)
    csv_path = _messy_csv(str(tmp_path / "messy.csv"), rng)
    out_dir = str(tmp_path / "proj")
    generate(
        input_path=csv_path, response="target", name="MessyApp",
        output=out_dir, id_col="id",
    )
    main_py = os.path.join(out_dir, "main.py")
    assert os.path.exists(main_py)
    env = subprocess_env
    r = subprocess.run(
        [sys.executable, main_py], capture_output=True, text=True,
        timeout=420, cwd=out_dir, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert os.path.isdir(os.path.join(out_dir, "model"))
    # the batch scorer script runs against the SAME csv (label-free path)
    r2 = subprocess.run(
        [sys.executable, os.path.join(out_dir, "score.py"), csv_path],
        capture_output=True, text=True, timeout=300, cwd=out_dir, env=env,
    )
    assert r2.returncode == 0, r2.stderr[-2000:]
