"""Cross-process observability drills (ISSUE 11: obs/fleet.py +
obs/slo.py).

Pins the acceptance criteria:
* trace-context export/adopt through the ``TX_OBS_TRACE_CONTEXT`` env
  seam, threaded through the supervisor's child dispatch - a supervised
  run spawning >=2 child processes (re-dispatch + deploy grandchild)
  produces ONE merged trace tree whose root trace id appears in spans
  from every pid;
* trace ids stay collision-free across 10k ids minted in 4 concurrent
  processes (and span ids stay linkable across a merged fleet);
* >=3 concurrent shippers into one aggregation dir with one SIGKILLed
  mid-write: the aggregator never surfaces a torn read, and the dead
  process ages out via heartbeat staleness;
* one Prometheus scrape carries series from every live process under
  distinct ``instance`` labels plus fleet-level sums/maxes;
* an SLO burn-rate alert fires while ``serving.nan_scores`` is armed
  and clears after recovery, and a firing alert is a hard
  RollbackPolicy signal;
* the fleet shipper stays within the tier-1 CPU floor (shipper-on
  <= 1.25x obs-off; bench.py --obs-fleet proves the tight numbers).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.obs import (
    TRACE_CONTEXT_ENV,
    FleetAggregator,
    ObsShipper,
    SLObjective,
    SLOEngine,
    load_slo_config,
    metrics_registry,
    process_instance,
    read_json_torn_safe,
    reset_metrics_registry,
    reset_tracer,
    set_enabled,
    set_process_instance,
    ship_now,
    tracer,
)
from transmogrifai_tpu.obs.fleet import SHARD_SUFFIX
from transmogrifai_tpu.obs.trace import Tracer, parse_context

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_obs():
    reset_metrics_registry()
    reset_tracer()
    faults.reset()
    yield
    faults.reset()
    reset_metrics_registry()
    reset_tracer()


def _child_env() -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(TRACE_CONTEXT_ENV, None)
    env.pop("TX_FAULTS", None)
    return env


# ---------------------------------------------------------------------------
# trace-context propagation
# ---------------------------------------------------------------------------
def test_context_export_adopt_roundtrip(monkeypatch):
    """A tracer constructed under TX_OBS_TRACE_CONTEXT roots every span
    it mints into the exported trace, parented to the exporting span."""
    tr = tracer()
    with tr.span("parent.root") as root:
        ctx = tr.current_context()
        assert ctx == f"{root.trace_id}:{root.span_id}"
        assert parse_context(ctx) == (root.trace_id, root.span_id)
        monkeypatch.setenv(TRACE_CONTEXT_ENV, ctx)
        child_tr = Tracer()  # the child process's construction path
    assert child_tr.contexts_adopted == 1
    with child_tr.span("child.root") as c:
        assert c.trace_id == root.trace_id
        assert c.parent_id == root.span_id
    # nested child spans still parent locally
    with child_tr.span("child.a") as a:
        with child_tr.span("child.b") as b:
            assert b.parent_id == a.span_id
            assert b.trace_id == root.trace_id
    # a middle process with no span open relays the ADOPTED context on
    assert child_tr.current_context() == ctx
    # malformed contexts degrade to fresh local traces, never raise
    assert parse_context("garbage") == (None, None)
    assert parse_context("") == (None, None)
    assert parse_context("t:not-an-int") == (None, None)


def test_child_env_sets_and_strips_context(monkeypatch):
    from transmogrifai_tpu.obs import child_env

    tr = tracer()
    with tr.span("spawner"):
        env = child_env({"KEEP": "1"})
        assert env["KEEP"] == "1"
        assert TRACE_CONTEXT_ENV in env
    # no ambient span + nothing adopted: a stale inherited context is
    # STRIPPED, not forwarded
    monkeypatch.delenv(TRACE_CONTEXT_ENV, raising=False)
    env = child_env({TRACE_CONTEXT_ENV: "stale:1", "KEEP": "1"})
    assert TRACE_CONTEXT_ENV not in env and env["KEEP"] == "1"


_ID_MINTER_CHILD = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from transmogrifai_tpu.obs.trace import Tracer
tr = Tracer(capacity=8)
with open({out!r}, "w") as f:
    for _ in range({n}):
        s = tr.span("mint")
        f.write(s.trace_id + " " + str(s.span_id) + "\\n")
"""


def test_trace_and_span_ids_collision_safe_4_processes_10k(tmp_path):
    """Acceptance: 10k trace ids minted in each of 4 CONCURRENT
    processes collide nowhere (the seed scheme's pid+4-byte prefix is
    widened to pid+8-byte start nonce), and span ids are globally
    unique too - they are the join keys of the merged fleet tree."""
    n = 10_000
    outs = [str(tmp_path / f"ids-{i}.txt") for i in range(4)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c",
             _ID_MINTER_CHILD.format(repo=REPO, out=out, n=n)],
            env=_child_env(),
        )
        for out in outs
    ]
    for p in procs:
        p.wait(timeout=180)
        assert p.returncode == 0
    trace_ids: set = set()
    span_ids: set = set()
    total = 0
    for out in outs:
        with open(out) as f:
            for line in f:
                t, _, s = line.strip().partition(" ")
                trace_ids.add(t)
                span_ids.add(int(s))
                total += 1
    assert total == 4 * n
    assert len(trace_ids) == total, "trace-id collision across processes"
    assert len(span_ids) == total, "span-id collision across processes"


# ---------------------------------------------------------------------------
# fleet aggregation
# ---------------------------------------------------------------------------
def test_aggregator_merges_instances_sums_and_maxes(tmp_path):
    """Shards from several (simulated) processes merge into one scrape:
    per-process samples under distinct instance labels, fleet rollup
    sums counters and maxes gauges."""
    agg_dir = str(tmp_path / "agg")
    reg = metrics_registry()
    c = reg.counter("work.rows")
    g = reg.gauge("work.depth")
    try:
        for inst, rows, depth in (("r1", 10, 3.0), ("r2", 32, 7.0)):
            # same registry re-shipped under two identities: the values
            # differ per ship, exactly like two replicas at different
            # points in their run
            c.inc(rows - c.value)
            g.set(depth)
            set_process_instance(inst)
            ship_now(agg_dir)
    finally:
        set_process_instance(None)
    agg = FleetAggregator(agg_dir, stale_after_s=300.0)
    text = agg.prometheus_text()
    assert 'tx_work_rows{instance="r1"} 10' in text
    assert 'tx_work_rows{instance="r2"} 32' in text
    assert 'tx_work_rows{instance="fleet",agg="sum"} 42' in text
    assert 'tx_work_rows{instance="fleet",agg="max"} 32' in text
    assert 'tx_work_depth{instance="fleet",agg="max"} 7' in text
    assert agg.last_report["instances"] == ["r1", "r2"]
    # the whole-fleet JSON document names both processes
    doc = agg.to_json()
    assert set(doc["processes"]) == {"r1", "r2"}
    assert doc["fleet"]["sum"]["tx_work_rows"] == 42


def test_aggregator_skips_torn_and_ages_out_dead(tmp_path):
    agg_dir = str(tmp_path / "agg")
    os.makedirs(agg_dir)
    try:
        set_process_instance("live")
        ship_now(agg_dir)
    finally:
        set_process_instance(None)
    # a torn shard: a writer killed mid-write on a rename-less fs
    torn = os.path.join(agg_dir, "torn" + SHARD_SUFFIX)
    with open(torn, "w") as f:
        f.write('{"instance": "torn", "metrics": {"ser')
    # a dead process: valid shard, stale heartbeat
    dead = os.path.join(agg_dir, "dead" + SHARD_SUFFIX)
    with open(dead, "w") as f:
        json.dump({"instance": "dead", "pid": 1, "metrics": {},
                   "spans": []}, f)
    old = time.time() - 3600.0
    os.utime(dead, (old, old))
    assert read_json_torn_safe(torn) is None
    agg = FleetAggregator(agg_dir, stale_after_s=5.0)
    shards = agg.shards()
    assert [d["instance"] for d in shards] == ["live"]
    assert agg.last_report["shards_torn"] == 1
    assert agg.last_report["shards_stale"] == 1
    # the scrape renders without the dead/torn processes and without
    # raising
    text = agg.prometheus_text()
    assert 'instance="live"' in text
    assert "torn" not in text and '"dead"' not in text


def test_concurrent_shippers_sigkill_one_mid_write(tmp_path):
    """Acceptance satellite: >=3 processes export into one aggregation
    dir while the parent aggregates concurrently; one child is
    SIGKILLed mid-loop.  The aggregator never surfaces a torn read, and
    the killed process ages out via heartbeat staleness while the
    survivors stay in the scrape."""
    agg_dir = str(tmp_path / "agg")
    from transmogrifai_tpu.testkit.drills import (
        FLEET_SHIPPER_CHILD_TEMPLATE,
    )

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", FLEET_SHIPPER_CHILD_TEMPLATE.format(
                repo=REPO, agg_dir=agg_dir, interval=0.01, duration=30.0)],
            env=_child_env(), stdout=subprocess.PIPE, text=True,
        )
        for _ in range(3)
    ]
    try:
        pids = []
        for p in procs:
            line = p.stdout.readline()  # SHIPPER_READY <pid>
            assert line.startswith("SHIPPER_READY"), line
            pids.append(int(line.split()[1]))
        agg = FleetAggregator(agg_dir, stale_after_s=1.0)
        # all three appear once each has shipped at least once
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if agg.last_report.get("shards_live", 0) >= 3:
                break
            agg.shards()
            time.sleep(0.02)
        assert agg.last_report["shards_live"] == 3, agg.last_report
        victim = procs[0]
        os.kill(victim.pid, signal.SIGKILL)
        # hammer the aggregator THROUGH the kill window: any torn read
        # would raise out of shards()/prometheus_text() right here
        deadline = time.monotonic() + 3.0
        saw_two = False
        while time.monotonic() < deadline:
            shards = agg.shards()
            text = agg.prometheus_text()
            assert agg.last_report["shards_torn"] == 0, agg.last_report
            live = set(agg.last_report["instances"])
            if len(shards) == 2:
                saw_two = True
                assert not any(
                    i.startswith(f"{victim.pid}-") for i in live), live
                for pid in pids[1:]:
                    assert any(i.startswith(f"{pid}-") for i in live), (
                        pid, live)
                    assert f'instance="{pid}-' in text
                break
            time.sleep(0.05)
        assert saw_two, "killed shipper never aged out of the scrape"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)
            p.stdout.close()


# ---------------------------------------------------------------------------
# acceptance: supervised multi-process run -> one merged trace + scrape
# ---------------------------------------------------------------------------
def test_e2e_supervised_fleet_drill(tmp_path):
    """A supervised run that spawns >=2 child processes (supervisor
    re-dispatch after a die-once exit + a deploy grandchild per
    attempt) produces ONE merged trace tree whose root trace id appears
    in spans from every pid, and one Prometheus scrape with series from
    every live process under distinct instance labels."""
    from transmogrifai_tpu.testkit.drills import (
        FLEET_DEPLOY_CHILD_TEMPLATE,
        FLEET_DRILL_CHILD_TEMPLATE,
        drill_env,
    )
    from transmogrifai_tpu.workflow.supervisor import supervise

    agg_dir = str(tmp_path / "agg")
    heartbeat = str(tmp_path / "beat")
    marker = str(tmp_path / "marker")
    grand_src = FLEET_DEPLOY_CHILD_TEMPLATE.format(
        repo=REPO, agg_dir=agg_dir)
    child_src = FLEET_DRILL_CHILD_TEMPLATE.format(
        repo=REPO, agg_dir=agg_dir, heartbeat=heartbeat, marker=marker,
        first_exit=7, grand=grand_src)
    tr = tracer()
    with tr.span("fleet.drill.root") as root:
        result = supervise(
            [sys.executable, "-c", child_src],
            heartbeat_path=heartbeat,
            stale_after_s=120.0,
            max_restarts=2,
            poll_s=0.1,
            env=drill_env(),
            backoff_base_s=0.05,
            backoff_seed=0,
        )
    assert result.returncode == 0
    assert result.attempts == 2  # die-once: one re-dispatch happened
    ship_now(agg_dir)  # the parent's own shard (root span included)

    agg = FleetAggregator(agg_dir, stale_after_s=300.0)
    spans = agg.merged_spans()
    ours = [r for r in spans if r["trace"] == root.trace_id]
    pids_in_trace = {r["pid"] for r in ours}
    # parent + two dispatch attempts + their grandchildren = >=5 pids,
    # and at the very least the required parent/child/grandchild hop
    assert len(pids_in_trace) >= 4, pids_in_trace
    assert os.getpid() in pids_in_trace

    trees = [t for t in agg.span_trees()
             if t["trace"] == root.trace_id]
    assert len(trees) == 1, [t["name"] for t in trees]
    tree = trees[0]
    assert tree["name"] == "fleet.drill.root"

    def walk(node):
        yield node
        for c in node.get("children", ()):
            yield from walk(c)

    nodes = list(walk(tree))
    names = [nd["name"] for nd in nodes]
    assert names.count("supervisor.dispatch") == 2
    assert names.count("child.work") == 2
    assert names.count("deploy.child") == 2
    # every node of the merged tree shares the ONE root trace id
    assert {nd["trace"] for nd in nodes} == {root.trace_id}
    # child.work parents under a dispatch attempt, deploy.child under
    # child.work: the tree reflects the PROCESS topology
    for nd in nodes:
        if nd["name"] == "child.work":
            assert any(c["name"] == "deploy.child"
                       for c in nd["children"])

    # one scrape, every live process, distinct instance labels
    text = agg.prometheus_text()
    instances = agg.last_report["instances"]
    assert len(instances) == len(set(instances)) >= 5
    for inst in instances:
        assert f'tx_obs_tracer_spans_recorded{{instance="{inst}"' in text


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------
def test_slo_ratio_burn_fires_and_clears_synthetic():
    """Deterministic state machine: burn over threshold in BOTH windows
    fires; short-window recovery clears."""
    reg = metrics_registry()
    bad = reg.counter("drill.bad")
    total = reg.counter("drill.total")
    obj = SLObjective(
        name="bad-ratio", kind="ratio",
        numerator="drill.bad", denominator="drill.total",
        objective=0.01, windows_s=(0.8, 0.15), burn_threshold=1.0)
    eng = SLOEngine([obj], register=False)
    eng.observe()  # baseline
    deadline = time.monotonic() + 3.0
    fired = False
    while time.monotonic() < deadline and not fired:
        bad.inc(10)
        total.inc(10)  # 100% failure, objective 1%
        rep = eng.observe()
        fired = rep["objectives"]["bad-ratio"]["state"] == "firing"
        time.sleep(0.02)
    assert fired, "alert never fired under sustained burn"
    assert [a["name"] for a in eng.firing()] == ["bad-ratio"]
    # recovery: clean traffic only; the short window clears it
    deadline = time.monotonic() + 3.0
    cleared = False
    while time.monotonic() < deadline and not cleared:
        total.inc(50)
        rep = eng.observe()
        cleared = rep["objectives"]["bad-ratio"]["state"] == "ok"
        time.sleep(0.05)
    assert cleared, "alert never cleared after recovery"
    events = eng.report()["events"]
    assert [e["transition"] for e in events] == ["fired", "cleared"]
    # no traffic burns no budget: more evaluations stay ok
    for _ in range(3):
        rep = eng.observe()
    assert rep["objectives"]["bad-ratio"]["state"] == "ok"


def test_slo_alert_fires_on_nan_scores_and_clears_after_recovery():
    """Acceptance: arm ``serving.nan_scores`` -> the NaN-guard refusals
    burn the nonfinite-rows budget and the alert FIRES; disarm -> clean
    traffic rolls the short window and it CLEARS."""
    from transmogrifai_tpu.serving import compile_endpoint
    from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline

    wf, _data, records, _name = tiny_drill_pipeline()
    model = wf.train()
    # breaker threshold high: this drill measures the SLO plane, not
    # the breaker (whose own opens are a different objective)
    endpoint = compile_endpoint(model, batch_buckets=(32,),
                                breaker_threshold=10_000)
    endpoint.score_batch(records[:32])  # warm, clean baseline traffic
    # denominator = clean batch-path rows + refused rows: the direct
    # score_batch path counts successes in rows_batched (rows_scored /
    # rows_failed belong to the scheduler's request accounting)
    obj = SLObjective(
        name="serving-nonfinite", kind="ratio",
        numerator="serving.breaker.rows_nonfinite",
        denominator=("serving.rows_batched",
                     "serving.breaker.rows_nonfinite"),
        objective=0.05, windows_s=(0.8, 0.15), burn_threshold=1.0)
    eng = SLOEngine([obj], register=False)
    eng.observe()
    faults.configure("serving.nan_scores:every=1")
    try:
        deadline = time.monotonic() + 5.0
        fired = False
        while time.monotonic() < deadline and not fired:
            endpoint.score_batch(records[:32])  # poisoned -> refused
            fired = bool(eng.observe()["firing"])
            time.sleep(0.02)
        assert fired, "SLO alert never fired while nan_scores armed"
    finally:
        faults.reset()
    # recovery: the same endpoint serves clean traffic again
    deadline = time.monotonic() + 5.0
    cleared = False
    while time.monotonic() < deadline and not cleared:
        out = endpoint.score_batch(records[:32])
        assert len(out) == 32
        cleared = not eng.observe()["firing"]
        time.sleep(0.05)
    assert cleared, "SLO alert never cleared after recovery"
    events = eng.report()["events"]
    assert [e["transition"] for e in events] == ["fired", "cleared"]


def test_firing_slo_is_a_hard_rollback_signal():
    """RollbackPolicy.slo_engine: a firing burn-rate alert becomes a
    hard rollback reason (``slo:<name>``) with the report in the
    evidence, regardless of canary sample size."""
    from transmogrifai_tpu.registry.rollback import RollbackPolicy

    reg = metrics_registry()
    bad = reg.counter("fleet.bad")
    total = reg.counter("fleet.total")
    obj = SLObjective(
        name="fleet-errors", kind="ratio",
        numerator="fleet.bad", denominator="fleet.total",
        objective=0.01, windows_s=(0.5, 0.05), burn_threshold=1.0)
    eng = SLOEngine([obj], register=False)
    eng.observe()
    policy = RollbackPolicy(slo_engine=eng)
    time.sleep(0.06)
    bad.inc(100)
    total.inc(100)
    eng.observe()
    time.sleep(0.06)
    bad.inc(100)
    total.inc(100)
    # evaluate() re-observes the engine itself, then reads alerts
    decision = policy.evaluate({"rows_scored": 0}, {"rows_scored": 0})
    signals = [r["signal"] for r in decision.reasons]
    assert "slo:fleet-errors" in signals
    assert decision.rollback
    assert decision.evidence["slo"]["firing"] == ["fleet-errors"]
    # a clean engine contributes nothing
    policy2 = RollbackPolicy()
    d2 = policy2.evaluate({"rows_scored": 0}, {"rows_scored": 0})
    assert not d2.rollback


def test_slo_config_load_validates(tmp_path):
    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({"slos": [
        {"name": "p99", "kind": "threshold",
         "metric": "serving.latency_ms.p99", "objective": 100.0},
        {"name": "errs", "kind": "ratio",
         "numerator": "serving.rows_failed",
         "denominator": ["serving.rows_scored", "serving.rows_failed"],
         "objective": 0.05},
    ]}))
    objs = load_slo_config(str(cfg))
    assert [o.name for o in objs] == ["p99", "errs"]
    # unknown keys fail loudly (a typo must not silently disable a knob)
    cfg.write_text(json.dumps({"slos": [
        {"name": "x", "kind": "ratio", "numerator": "a",
         "denominator": "b", "objectve": 0.1}]}))
    with pytest.raises(ValueError, match="objectve"):
        load_slo_config(str(cfg))
    with pytest.raises(ValueError):
        SLObjective(name="w", kind="nope")
    with pytest.raises(ValueError):  # (long, short) ordering enforced
        SLObjective(name="w", kind="rate", numerator="a",
                    windows_s=(1.0, 2.0))


def test_slo_cli_over_export_and_agg_dir(tmp_path, capsys):
    """tx obs slo: exit 1 when an objective's lifetime totals blow the
    budget, 0 when clean; works over a saved export and over a fleet
    aggregation dir."""
    from transmogrifai_tpu import cli
    from transmogrifai_tpu.obs import export_obs

    reg = metrics_registry()
    reg.counter("jobs.bad").inc(50)
    reg.counter("jobs.total").inc(100)
    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({"slos": [
        {"name": "bad-jobs", "kind": "ratio", "numerator": "jobs.bad",
         "denominator": "jobs.total", "objective": 0.01,
         "windows_s": [300.0, 60.0]}]}))
    out_dir = str(tmp_path / "export")
    export_obs(out_dir)
    rc = cli.main(["obs", "slo", "--path", out_dir,
                   "--config", str(cfg)])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["firing"] == ["bad-jobs"]
    assert report["objectives"]["bad-jobs"]["ratio"] == 0.5
    # fleet aggregation dir: the same config over shipped shards
    agg_dir = str(tmp_path / "agg")
    ship_now(agg_dir)
    rc = cli.main(["obs", "slo", "--path", agg_dir,
                   "--config", str(cfg)])
    assert rc == 1
    assert json.loads(capsys.readouterr().out)["firing"] == ["bad-jobs"]
    # a clean objective exits 0
    cfg.write_text(json.dumps({"slos": [
        {"name": "bad-jobs", "kind": "ratio", "numerator": "jobs.bad",
         "denominator": "jobs.total", "objective": 0.9}]}))
    rc = cli.main(["obs", "slo", "--path", out_dir,
                   "--config", str(cfg)])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["firing"] == []


def test_trace_cli_merges_fleet_shards(tmp_path, capsys):
    """tx obs trace over an aggregation dir merges every live shard's
    spans into one forest and reports fleet membership."""
    from transmogrifai_tpu import cli

    agg_dir = str(tmp_path / "agg")
    tr = tracer()
    with tr.span("merge.root"):
        with tr.span("merge.child"):
            pass
    ship_now(agg_dir)
    rc = cli.main(["obs", "trace", "--path", agg_dir])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["fleet"]["shards_live"] == 1
    roots = [t["name"] for t in out["trees"]]
    assert "merge.root" in roots
    root = next(t for t in out["trees"] if t["name"] == "merge.root")
    assert [c["name"] for c in root["children"]] == ["merge.child"]


def test_runner_slo_path_knob_exports_report(tmp_path):
    """The slo_path runner knob evaluates the config after any run and
    writes slo_report.json next to the obs export."""
    from tests.test_obs import _small_csv, _small_workflow
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    cfg = tmp_path / "slo.json"
    cfg.write_text(json.dumps({"slos": [
        {"name": "spans-flowing", "kind": "threshold",
         "metric": "obs_tracer.spans_recorded", "objective": 1e9}]}))
    wf = _small_workflow(_small_csv(tmp_path))
    runner = OpWorkflowRunner(wf)
    out_dir = str(tmp_path / "obs_out")
    runner.run("train", OpParams(
        model_location=str(tmp_path / "model"),
        custom_params={"metrics_path": out_dir,
                       "slo_path": str(cfg)},
    ))
    with open(os.path.join(out_dir, "slo_report.json")) as f:
        report = json.load(f)
    assert report["firing"] == []
    obj = report["objectives"]["spans-flowing"]
    assert obj["state"] == "ok" and obj["value"] > 0
    # the engine registered as a view: the scrape carries alert gauges
    with open(os.path.join(out_dir, "metrics.prom")) as f:
        assert "tx_slo_alerts_firing" in f.read()


# ---------------------------------------------------------------------------
# tier-1 floor: shipper overhead (bench.py --obs-fleet proves the
# tight numbers; this is the loose CI-stable version)
# ---------------------------------------------------------------------------
def test_fleet_shipper_within_cpu_floor_of_obs_off(tmp_path):
    """Serving with the obs plane ON and a live ObsShipper beating must
    stay within 1.25x the CPU time of the plane OFF entirely
    (min-of-3, interleaved arms)."""
    from transmogrifai_tpu.serving import compile_endpoint
    from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline

    wf, _data, records, _name = tiny_drill_pipeline(n=240)
    model = wf.train()
    endpoint = compile_endpoint(model, batch_buckets=(1, 8, 32, 128))
    endpoint.score_batch(records)  # warm both arms' caches
    ship_dir = str(tmp_path / "agg")

    def cpu_pass() -> float:
        t0 = time.process_time()
        for _ in range(4):
            out = endpoint.score_batch(records)
        assert len(out) == len(records)
        return max(time.process_time() - t0, 1e-9)

    on_c = off_c = float("inf")
    for _ in range(3):
        set_enabled(True)
        with ObsShipper(ship_dir, interval_s=0.25):
            on_c = min(on_c, cpu_pass())
        set_enabled(False)
        off_c = min(off_c, cpu_pass())
    set_enabled(True)
    assert on_c <= off_c * 1.25 + 0.01, (
        f"fleet shipper overhead too high: on={on_c:.4f}s "
        f"off={off_c:.4f}s cpu"
    )
    # and the shipper actually shipped a readable shard
    agg = FleetAggregator(ship_dir, stale_after_s=300.0)
    assert agg.shards(), "shipper never produced a shard"
    assert any(i == process_instance()
               for i in agg.last_report["instances"])


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------
def test_rollup_sums_multiple_views_of_one_kind(tmp_path):
    """A process holding TWO views of one kind (a deploy's stable +
    canary ServingTelemetry) contributes BOTH to the fleet rollup -
    last-one-wins would silently drop an arm from the sums."""
    from transmogrifai_tpu.serving.telemetry import ServingTelemetry

    stable = ServingTelemetry()
    canary = ServingTelemetry()
    for _ in range(10):
        stable.record_request(0.001, "ok")
    for _ in range(3):
        canary.record_request(0.001, "ok")
    agg_dir = str(tmp_path / "agg")
    try:
        set_process_instance("deployer")
        ship_now(agg_dir)
    finally:
        set_process_instance(None)
    agg = FleetAggregator(agg_dir, stale_after_s=300.0)
    rollup = agg.fleet_rollup()
    assert rollup["sum"]["tx_serving_rows_scored"] == 13
    assert rollup["max"]["tx_serving_rows_scored"] == 10


def test_threshold_spike_outside_windows_does_not_hold_alert():
    """A threshold breach sampled BEFORE both windows is delta-baseline
    data, not a live reading: once fresh in-window samples are healthy
    the alert must clear (and an unobserved gap must not re-fire it)."""
    reg = metrics_registry()
    g = reg.gauge("probe.p99")
    obj = SLObjective(name="p99", kind="threshold", metric="probe.p99",
                      objective=10.0, windows_s=(0.3, 0.1),
                      burn_threshold=1.0)
    eng = SLOEngine([obj], register=False)
    g.set(1000.0)
    rep = eng.observe()  # spike in both windows: fires
    assert rep["objectives"]["p99"]["state"] == "firing"
    g.set(1.0)
    time.sleep(0.35)  # the spike ages past BOTH windows
    rep = eng.observe()
    assert rep["objectives"]["p99"]["state"] == "ok", rep
    # and with no fresh samples at all in-window, nothing fires
    time.sleep(0.35)
    st = eng._alerts["p99"]
    burn, _info = eng._burn(obj, st.samples, time.perf_counter(), 0.1)
    assert burn == 0.0


def test_instance_identity_sanitized_for_labels_and_filenames(tmp_path):
    """A hostile/typoed instance name cannot inject Prometheus label
    syntax or escape the aggregation dir through the shard filename."""
    agg_dir = str(tmp_path / "agg")
    path = ship_now(agg_dir, instance='evil"name/../../x')
    assert os.path.dirname(path) == agg_dir
    assert "/" not in os.path.basename(path)[: -len(SHARD_SUFFIX)]
    agg = FleetAggregator(agg_dir, stale_after_s=300.0)
    text = agg.prometheus_text()
    assert '"evil' not in text.replace('="evil', "")  # no stray quotes
    assert 'instance="evil_name_.._.._x"' in text
    try:
        set_process_instance('rep"lica\n2')
        assert process_instance() == "rep_lica_2"
    finally:
        set_process_instance(None)
