"""MLP + GLM model tests (reference: OpMultilayerPerceptronClassifierTest,
OpGeneralizedLinearRegressionTest)."""
import numpy as np
import pytest

from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
from transmogrifai_tpu.models.mlp import OpMultilayerPerceptronClassifier
from transmogrifai_tpu.selector.random_param_builder import RandomParamBuilder


def test_mlp_learns_xor(rng):
    n = 400
    X = rng.randn(n, 2)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(float)
    est = OpMultilayerPerceptronClassifier(hidden_layers=(16, 16), max_iter=400)
    params = est.fit_arrays(X, y)
    pred, raw, prob = est.predict_arrays(params, X)
    assert (pred == y).mean() > 0.9  # linear models cannot do XOR
    assert prob.shape == (n, 2)


def test_glm_poisson(rng):
    n = 800
    X = rng.randn(n, 3)
    beta = np.array([0.5, -0.3, 0.2])
    lam = np.exp(X @ beta + 1.0)
    y = rng.poisson(lam).astype(float)
    est = OpGeneralizedLinearRegression(family="poisson")
    params = est.fit_arrays(X, y)
    assert np.allclose(params["beta"], beta, atol=0.1)
    assert abs(params["intercept"] - 1.0) < 0.1
    pred, _, _ = est.predict_arrays(params, X)
    assert pred.min() >= 0


def test_glm_gaussian_matches_linreg(rng):
    n = 300
    X = rng.randn(n, 2)
    y = X @ np.array([2.0, -1.0]) + 0.5 + 0.01 * rng.randn(n)
    est = OpGeneralizedLinearRegression(family="gaussian")
    params = est.fit_arrays(X, y)
    assert np.allclose(params["beta"], [2.0, -1.0], atol=0.02)


def test_random_param_builder_deterministic():
    b = (
        RandomParamBuilder(seed=3)
        .log_uniform("reg_param", 1e-4, 1e-1)
        .choice("elastic_net_param", [0.0, 0.5])
        .int_uniform("max_depth", 3, 12)
    )
    g1 = b.build(10)
    g2 = (
        RandomParamBuilder(seed=3)
        .log_uniform("reg_param", 1e-4, 1e-1)
        .choice("elastic_net_param", [0.0, 0.5])
        .int_uniform("max_depth", 3, 12)
    ).build(10)
    assert g1 == g2
    assert all(1e-4 <= p["reg_param"] <= 1e-1 for p in g1)
    assert all(3 <= p["max_depth"] <= 12 for p in g1)
