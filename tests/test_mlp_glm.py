"""MLP + GLM model tests (reference: OpMultilayerPerceptronClassifierTest,
OpGeneralizedLinearRegressionTest)."""
import numpy as np
import pytest

from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
from transmogrifai_tpu.models.mlp import OpMultilayerPerceptronClassifier
from transmogrifai_tpu.selector.random_param_builder import RandomParamBuilder


def test_mlp_learns_xor(rng):
    n = 400
    X = rng.randn(n, 2)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(float)
    est = OpMultilayerPerceptronClassifier(hidden_layers=(16, 16), max_iter=400)
    params = est.fit_arrays(X, y)
    pred, raw, prob = est.predict_arrays(params, X)
    assert (pred == y).mean() > 0.9  # linear models cannot do XOR
    assert prob.shape == (n, 2)


def test_glm_poisson(rng):
    n = 800
    X = rng.randn(n, 3)
    beta = np.array([0.5, -0.3, 0.2])
    lam = np.exp(X @ beta + 1.0)
    y = rng.poisson(lam).astype(float)
    est = OpGeneralizedLinearRegression(family="poisson")
    params = est.fit_arrays(X, y)
    assert np.allclose(params["beta"], beta, atol=0.1)
    assert abs(params["intercept"] - 1.0) < 0.1
    pred, _, _ = est.predict_arrays(params, X)
    assert pred.min() >= 0


def test_glm_gaussian_matches_linreg(rng):
    n = 300
    X = rng.randn(n, 2)
    y = X @ np.array([2.0, -1.0]) + 0.5 + 0.01 * rng.randn(n)
    est = OpGeneralizedLinearRegression(family="gaussian")
    params = est.fit_arrays(X, y)
    assert np.allclose(params["beta"], [2.0, -1.0], atol=0.02)


def test_random_param_builder_deterministic():
    b = (
        RandomParamBuilder(seed=3)
        .log_uniform("reg_param", 1e-4, 1e-1)
        .choice("elastic_net_param", [0.0, 0.5])
        .int_uniform("max_depth", 3, 12)
    )
    g1 = b.build(10)
    g2 = (
        RandomParamBuilder(seed=3)
        .log_uniform("reg_param", 1e-4, 1e-1)
        .choice("elastic_net_param", [0.0, 0.5])
        .int_uniform("max_depth", 3, 12)
    ).build(10)
    assert g1 == g2
    assert all(1e-4 <= p["reg_param"] <= 1e-1 for p in g1)
    assert all(3 <= p["max_depth"] <= 12 for p in g1)


def test_glm_gamma_matches_independent_mle(rng):
    """Round-5 fix: the gamma score was (mu - y) - the POISSON estimating
    equation - instead of (mu - y)/mu; coefficients were systematically
    off whenever the model wasn't exact.  Pinned against an independent
    scipy minimization of the gamma log-link NLL."""
    import jax.numpy as jnp
    from scipy.optimize import minimize

    from transmogrifai_tpu.models.glm import _glm_fit_kernel

    n, d = 2000, 4
    X = rng.randn(n, d)
    beta_t = np.array([0.5, -0.3, 0.2, 0.0])
    mu_true = np.exp(X @ beta_t + 0.4)
    y = rng.gamma(shape=2.0, scale=mu_true / 2.0)
    b, b0 = _glm_fit_kernel(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(np.ones(n)),
        jnp.asarray(0.0), family="gamma", iters=30,
    )
    b, b0 = np.asarray(b), float(b0)

    def nll(theta):
        eta = X @ theta[:d] + theta[d]
        mu = np.exp(np.clip(eta, -30, 30))
        return np.sum(y / mu + np.log(mu))

    res = minimize(nll, np.zeros(d + 1), method="L-BFGS-B",
                   options={"maxiter": 5000, "ftol": 1e-15})
    np.testing.assert_allclose(b, res.x[:d], atol=2e-3)
    assert abs(b0 - res.x[d]) < 2e-3


def test_glm_tweedie_family(rng):
    """Tweedie (log link, variance_power p): endpoints must coincide with
    poisson (p=1) and gamma (p=2) fixed points, p=1.5 must sit between,
    and the estimator surface must fit/predict/round-trip."""
    import jax.numpy as jnp

    from transmogrifai_tpu.models.glm import (
        OpGeneralizedLinearRegression,
        _glm_fit_kernel,
    )

    n, d = 1500, 3
    X = rng.randn(n, d)
    mu_true = np.exp(X @ np.array([0.4, -0.2, 0.1]) + 0.3)
    y = rng.gamma(shape=2.0, scale=mu_true / 2.0)
    w = jnp.asarray(np.ones(n))
    Xj, yj, r0 = jnp.asarray(X), jnp.asarray(y), jnp.asarray(0.0)
    bp, _ = _glm_fit_kernel(Xj, yj, w, r0, family="poisson", iters=30)
    bg, _ = _glm_fit_kernel(Xj, yj, w, r0, family="gamma", iters=30)
    bt1, _ = _glm_fit_kernel(Xj, yj, w, r0, family="tweedie", iters=30,
                             var_power=jnp.asarray(1.0))
    bt2, _ = _glm_fit_kernel(Xj, yj, w, r0, family="tweedie", iters=30,
                             var_power=jnp.asarray(2.0))
    np.testing.assert_allclose(np.asarray(bt1), np.asarray(bp), atol=1e-4)
    np.testing.assert_allclose(np.asarray(bt2), np.asarray(bg), atol=1e-4)

    est = OpGeneralizedLinearRegression(family="tweedie",
                                        variance_power=1.5)
    params = est.fit_arrays(X, y)
    pred, _, _ = est.predict_arrays(params, X)
    assert (pred > 0).all()  # log link: strictly positive means
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.3
    with pytest.raises(ValueError, match="unknown GLM family"):
        OpGeneralizedLinearRegression(family="tweedy")


def test_glm_family_validated_at_consumption(rng):
    """with_params()/grid-set families bypass __init__: a typo must raise
    at fit time, not silently fit the gaussian branch; tweedie variance
    powers in (0, 1) (no such distribution) are rejected too."""
    from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression

    X = rng.randn(50, 2)
    y = np.abs(rng.randn(50)) + 0.1
    est = OpGeneralizedLinearRegression().with_params(family="Tweedy")
    with pytest.raises(ValueError, match="unknown GLM family"):
        est.fit_arrays(X, y)
    # miscased-but-valid families normalize instead of raising
    ok = OpGeneralizedLinearRegression().with_params(family="Poisson")
    ok.fit_arrays(X, y)
    with pytest.raises(ValueError, match="variance_power"):
        OpGeneralizedLinearRegression(family="tweedie", variance_power=0.5)
    bad = OpGeneralizedLinearRegression(family="tweedie").with_params(
        variance_power=0.5
    )
    with pytest.raises(ValueError, match="variance_power"):
        bad.fit_arrays(X, y)


def test_glm_tweedie_power_link(rng):
    """link_power closes the documented log-link divergence: Spark GLR's
    default tweedie link is the power link lp = 1 - variancePower; both
    links must fit finite positive means, and the tweedie log-link
    endpoints must be unchanged by the lax.cond refactor."""
    import jax.numpy as jnp

    from transmogrifai_tpu.models.glm import (
        OpGeneralizedLinearRegression,
        _glm_fit_kernel,
    )

    n, d = 1500, 3
    X = rng.randn(n, d)
    # log-link data for lp=0; POWER-LINK data for lp=-0.5 (eta kept in
    # the link's positive domain - fitting a power link to log-link data
    # is misspecified and extreme rows legitimately clamp)
    mu_log = np.exp(X @ np.array([0.4, -0.2, 0.1]) + 0.6)
    eta_pow = X @ np.array([0.1, -0.05, 0.02]) + 2.0
    mu_pow = np.maximum(eta_pow, 0.3) ** (1.0 / -0.5)
    for lp, mu_true in ((0.0, mu_log), (-0.5, mu_pow)):
        y = rng.gamma(2.0, mu_true / 2.0)
        est = OpGeneralizedLinearRegression(
            family="tweedie", variance_power=1.5, link_power=lp
        )
        params = est.fit_arrays(X, y)
        assert params["link_power"] == lp
        pred, _, _ = est.predict_arrays(params, X)
        assert np.isfinite(pred).all() and (pred > 0).all()
        # assert against the TRUE means, not the noisy draws: gamma
        # shape-2 noise caps r2-vs-y near zero in low-signal regimes,
        # while recovery of mu is what the fit actually controls
        r2_mu = 1 - np.sum((pred - mu_true) ** 2) / np.sum(
            (mu_true - mu_true.mean()) ** 2
        )
        assert r2_mu > 0.7, (lp, r2_mu)
    # the log-link p=1/p=2 endpoints still coincide with poisson/gamma
    w = jnp.asarray(np.ones(n))
    Xj, yj, r0 = jnp.asarray(X), jnp.asarray(y), jnp.asarray(0.0)
    bp, _ = _glm_fit_kernel(Xj, yj, w, r0, family="poisson", iters=30)
    bt1, _ = _glm_fit_kernel(Xj, yj, w, r0, family="tweedie", iters=30,
                             var_power=jnp.asarray(1.0),
                             link_power=jnp.asarray(0.0))
    np.testing.assert_allclose(np.asarray(bt1), np.asarray(bp), atol=1e-4)


def test_glm_tweedie_power_link_save_load(tmp_path, rng):
    """The power-link tweedie model must survive the model writer with
    identical predictions (link_power rides the fitted params)."""
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

    n = 150
    a_vals = rng.rand(n) * 2 + 0.5
    eta = 0.3 * a_vals + 1.5
    mu = eta ** (1.0 / -0.5)
    data = {"y": rng.gamma(2.0, mu / 2.0).tolist(), "a": a_vals.tolist()}

    def build():
        fy = FeatureBuilder(ft.RealNN, "y").as_response()
        fa = FeatureBuilder(ft.Real, "a").as_predictor()
        vec = transmogrify([fa])
        pred = (
            OpGeneralizedLinearRegression(
                family="tweedie", variance_power=1.5, link_power=-0.5
            ).set_input(fy, vec).get_output()
        )
        return OpWorkflow().set_result_features(pred).set_input_dataset(data)

    m1 = build().train()
    assert m1.stages[-1].model_params["link_power"] == -0.5
    m1.save(str(tmp_path / "tw"))
    m2 = OpWorkflowModel.load(str(tmp_path / "tw"), build())
    p1 = [c for c in m1.score(data).columns().values()
          if hasattr(c, "prediction")][0]
    p2 = [c for c in m2.score(data).columns().values()
          if hasattr(c, "prediction")][0]
    np.testing.assert_array_equal(np.asarray(p1.prediction),
                                  np.asarray(p2.prediction))
