"""Warm start (reference: OpWorkflow.withModelStages:457): fitted stages
swap into an extended workflow so only NEW estimators train."""
import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.trees import OpRandomForestClassifier
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.types import feature_types as ft


def _fit_uids(model):
    return {
        m["stage_uid"] for m in model.app_metrics.to_json()["stages"]
        if m["phase"] == "fit"
    }


def test_warm_start_skips_already_fitted_stages(rng):
    n = 250
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "c": [("u", "v")[i % 2] for i in range(n)],
    }
    data["a"] = [ai + 2 * yi for ai, yi in zip(data["a"], data["y"])]
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a, c])
    checked = y.sanity_check(vec, remove_bad_features=False)
    lr_pred = (
        OpLogisticRegression(max_iter=8, reg_param=0.01).set_input(y, checked).get_output()
    )

    wf1 = OpWorkflow().set_result_features(lr_pred).set_input_dataset(data)
    m1 = wf1.train()
    fitted_once = _fit_uids(m1)
    assert fitted_once  # vectorizers + sanity checker + LR all fit

    # extend the SAME feature graph with a new estimator and warm start
    rf_pred = (
        OpRandomForestClassifier(num_trees=5, max_depth=3)
        .set_input(y, checked)
        .get_output()
    )
    wf2 = (
        OpWorkflow()
        .set_result_features(lr_pred, rf_pred)
        .set_input_dataset(data)
        .with_model_stages(m1)
    )
    m2 = wf2.train()
    refit = _fit_uids(m2)
    # every previously fitted stage was warm: ONLY the new RF fit
    assert not (refit & fitted_once), refit & fitted_once
    assert len(refit) == 1
    # warm LR predictions identical to the first training
    p1 = m1.score(data)[lr_pred.name].probability
    p2 = m2.score(data)[lr_pred.name].probability
    assert np.allclose(p1, p2)
    # and the new head actually works
    assert rf_pred.name in m2.score(data)


def test_warm_start_does_not_disable_workflow_cv_fold_refits(rng, monkeypatch):
    """Warm substitution must never bypass with_workflow_cv's leakage
    protection: the 'during' set comes from the FEATURE graph (original
    estimators), so label-aware stages still refit inside every fold even
    when their fitted counterparts were warmed into the main pass."""
    from transmogrifai_tpu.preparators import sanity_checker as sc_mod
    from transmogrifai_tpu.selector.factories import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.selector.splitters import DataSplitter

    n = 300
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "b": rng.randn(n).tolist(),
    }
    data["a"] = [ai + 2 * yi for ai, yi in zip(data["a"], data["y"])]
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([a, b])
    checked = y.sanity_check(vec, remove_bad_features=True)
    lr = OpLogisticRegression(reg_param=0.01).set_input(y, checked).get_output()
    m1 = OpWorkflow().set_result_features(lr).set_input_dataset(data).train()

    calls = {"n": 0}
    orig_fit = sc_mod.SanityChecker.fit_model

    def counting(self, cols, ds):
        calls["n"] += 1
        return orig_fit(self, cols, ds)

    monkeypatch.setattr(sc_mod.SanityChecker, "fit_model", counting)

    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
        splitter=DataSplitter(reserve_test_fraction=0.1),
    )
    pred = sel.set_input(y, checked).get_output()
    wf2 = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data)
        .with_workflow_cv().with_model_stages(m1)
    )
    wf2.train()
    assert calls["n"] == 3  # one leakage-free refit per fold
    assert sel.best_override is not None
