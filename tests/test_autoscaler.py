"""Elastic fleet autoscaling drills (ISSUE 19; fleet/autoscaler.py).

The acceptance matrix for the capacity control loop: ScaleGovernor
hysteresis units (streaks, cooldown suppression, a flap storm that
never triggers), the deterministic ``step()`` decision function over a
fake fleet (cost-model surge sizing - never "+1" - the at-max brownout
hold, replica-death replacement-capacity accounting, the A/B knob
retune riding the loop), and the live drills: a traffic ramp that
grows a real TCP fleet 2 -> >= 4 under load and shrinks it back idle
with ZERO dropped rows and exact double-entry row conservation, a
SIGKILL of a draining scale-down victim mid-drain (failover owns the
strands), the ``autoscaler.crash`` fault point (the control loop dies;
the data plane keeps serving; a restarted autoscaler ADOPTS the live
fleet), the worker ``retune`` verb + chunk cap, and the bulk job's
router re-resolution at shard boundaries (grow-mid-job).
"""
from __future__ import annotations

import os
import signal
import threading
import time

import pytest

from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.fleet import (
    AutoscaleDecision,
    FleetAutoscaler,
    FleetController,
    ScaleGovernor,
)
from transmogrifai_tpu.registry import ModelRegistry
from transmogrifai_tpu.testkit.drills import (
    tiny_drill_pipeline,
    write_shard_csv,
)

WORKFLOW_SPEC = "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline"

RAMP_DEADLINE_S = 180.0


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def fleet_registry(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("autoscale-registry"))
    wf, _data, records, pred_name = tiny_drill_pipeline()
    model = wf.train()
    reg = ModelRegistry(root)
    v1 = reg.publish(model, stage="stable")
    return {"root": root, "records": records, "pred_name": pred_name,
            "v1": v1.version, "model": model}


def _controller(fleet_registry, tmp_path, n_replicas, **kw):
    kw.setdefault("router_kw", {})
    kw["router_kw"].setdefault("max_in_flight_per_replica", 2)
    kw["router_kw"].setdefault("max_queue", 64)
    return FleetController(
        fleet_registry["root"], WORKFLOW_SPEC,
        n_replicas=n_replicas, work_dir=str(tmp_path / "fleet"),
        ship_interval_s=0.15, **kw,
    )


# ---------------------------------------------------------------------------
# ScaleGovernor: hysteresis units
# ---------------------------------------------------------------------------
def test_governor_streak_then_cooldown_suppression():
    g = ScaleGovernor(up_consecutive=2, down_consecutive=4, cooldown=2)
    assert g.observe_window("up") == "over"       # streak building
    assert g.observe_window("up") == "trigger"    # streak complete
    assert g.cooldown_left == 2
    assert g.observe_window("up") == "over"       # streaks reset
    assert g.observe_window("up") == "suppressed"  # complete but cooling
    assert g.observe_window("up") == "trigger"    # cooldown expired
    assert g.triggers == 2 and g.suppressed == 1


def test_governor_hold_resets_both_streaks():
    g = ScaleGovernor(up_consecutive=2, down_consecutive=2, cooldown=0)
    assert g.observe_window("up") == "over"
    assert g.observe_window("hold") == "clear"
    assert g.up_streak == 0 and g.down_streak == 0
    assert g.observe_window("up") == "over"       # starts from scratch
    assert g.observe_window("up") == "trigger"


def test_governor_down_needs_its_own_longer_streak():
    g = ScaleGovernor(up_consecutive=2, down_consecutive=4, cooldown=0)
    for _ in range(3):
        assert g.observe_window("down") == "over"
    assert g.observe_window("down") == "trigger"


def test_governor_flap_storm_never_triggers():
    g = ScaleGovernor(up_consecutive=2, down_consecutive=2, cooldown=2)
    for i in range(60):
        out = g.observe_window("up" if i % 2 == 0 else "down")
        assert out == "over"  # every flip resets the other streak
    assert g.triggers == 0 and g.windows == 60


def test_governor_rejects_unknown_direction():
    with pytest.raises(ValueError):
        ScaleGovernor().observe_window("sideways")


# ---------------------------------------------------------------------------
# step(): the deterministic decision function over a fake fleet
# ---------------------------------------------------------------------------
class _FakeHandle:
    def __init__(self, instance, svc_s=None, obs=None):
        self.instance = instance
        self.svc_s_ewma = svc_s
        self.obs = dict(obs or {})


class _FakeRouter:
    """Just the public seams ``step()`` reads: snapshot, live replicas,
    cost model, the retune broadcast."""

    def __init__(self, members, svc_s=0.01, snapshot=None):
        self.cost_model = None
        self._members = members  # shared list with the controller
        self.svc_s = svc_s
        self.snapshot_doc = dict(snapshot or {})
        self.broadcasts: list = []

    def snapshot(self):
        doc = {"rows_ok": 0, "requests_ok": 0, "queue_depth": 0,
               "healthy_replicas": len(self._members), "replicas": {}}
        doc.update(self.snapshot_doc)
        return doc

    def live_replicas(self):
        return [_FakeHandle(m, svc_s=self.svc_s) for m in self._members]

    def broadcast(self, cmd, args=None, timeout_s=30.0):
        self.broadcasts.append((cmd, dict(args or {})))
        return {m: {"ok": True} for m in self._members}


class _FakeSLO:
    def __init__(self):
        self.firing: list = []

    def observe(self):
        return {"objectives": {}, "firing": [{"name": n}
                                             for n in self.firing]}


class _FakeController:
    def __init__(self, n=2):
        self.members = [f"replica-{i}" for i in range(n)]
        self.router = _FakeRouter(self.members)
        self.slo_engine = _FakeSLO()
        self.gave_up: list = []
        self.autoscaler = None
        self.added: list = []
        self.removed: list = []

    def member_instances(self):
        return list(self.members)

    def gave_up_instances(self):
        return list(self.gave_up)

    def add_replica(self, probe_timeout_s=30.0):
        name = f"replica-{len(self.members)}"
        self.members.append(name)
        self.added.append(name)
        self.router._members = self.members
        return name

    def remove_replica(self, instance, drain_timeout_s=30.0):
        self.members.remove(instance)
        self.removed.append(instance)
        self.router._members = self.members
        return {"instance": instance, "drained": True, "drain_s": 0.0}


def _scaler(fc, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 8)
    kw.setdefault("up_consecutive", 2)
    kw.setdefault("down_consecutive", 2)
    kw.setdefault("cooldown_windows", 2)
    kw.setdefault("ref_batch_rows", 10)
    kw.setdefault("retune_enabled", False)
    return FleetAutoscaler(fc, **kw)


def test_step_sizes_surge_from_demand_not_plus_one():
    fc = _FakeController(n=2)
    # per-replica capacity 100 rows/s (svc EWMA 10ms/row); a backlog of
    # 100 in-flight rows + 30 queued requests x 10 rows/request over
    # the 1s up-window = 400 rows/s demand -> utilization 2.0
    fc.router.svc_s = 0.01
    fc.router.snapshot_doc = {
        "queue_depth": 30, "healthy_replicas": 2,
        "replicas": {"replica-0": {"in_flight_rows": 50},
                     "replica-1": {"in_flight_rows": 50}},
    }
    s = _scaler(fc, interval_s=0.5, target_utilization=0.7)
    d1 = s.step()
    assert d1.action == "hold" and d1.outcome == "over"
    assert d1.reason.startswith("overload:")
    d2 = s.step()
    # sized from demand: ceil(400 / (100 * 0.7)) = 6, NOT 2 + 1
    assert d2.action == "scale_up" and d2.target == 6
    assert fc.added == ["replica-2", "replica-3", "replica-4",
                        "replica-5"]
    assert d2.members_after == 6
    assert d2.evidence["capacity"]["source"] == "observed_ewma"
    assert d2.evidence["utilization"] >= 2.0
    assert d2.evidence["governor"]["triggers"] == 1


def test_step_at_max_defers_to_brownout():
    fc = _FakeController(n=2)
    fc.router.svc_s = 0.01
    fc.router.snapshot_doc = {
        "queue_depth": 30, "healthy_replicas": 2,
        "replicas": {"replica-0": {"in_flight_rows": 50}},
    }
    s = _scaler(fc, max_replicas=2)
    s.step()
    d = s.step()
    assert d.action == "hold" and d.outcome == "at_max"
    assert "brownout" in d.reason
    assert fc.added == []  # the quorum rule stays the last line


def test_step_replica_death_is_replacement_capacity():
    # 4 members but 2 gave up their restart budget: the survivors'
    # effective capacity halves, utilization crosses 1.0, and the
    # trigger sizes from DEMAND - not a blind 1:1 restart of the dead
    fc = _FakeController(n=4)
    fc.gave_up = ["replica-2", "replica-3"]
    fc.router.svc_s = 0.01
    fc.router.snapshot_doc = {
        "queue_depth": 0, "healthy_replicas": 2,
        "replicas": {"replica-0": {"in_flight_rows": 120},
                     "replica-1": {"in_flight_rows": 120}},
    }
    s = _scaler(fc, interval_s=0.5, target_utilization=0.7)
    s.step()
    d = s.step()
    assert d.action == "scale_up"
    # demand 240/1.0s = 240 rows/s over 200 effective -> util 1.2;
    # sized: ceil(240 / 70) = 4 serving replicas wanted
    assert d.target == 4
    assert d.evidence["gave_up"] == ["replica-2", "replica-3"]
    assert d.evidence["serving_n"] == 2


def test_step_scales_down_idle_fleet_youngest_first():
    fc = _FakeController(n=4)
    fc.router.svc_s = 0.01
    fc.router.snapshot_doc = {"queue_depth": 0, "healthy_replicas": 4,
                              "replicas": {}}
    s = _scaler(fc, min_replicas=1, idle_utilization=0.3)
    d1 = s.step()
    assert d1.action == "hold" and d1.reason.startswith("idle:")
    d2 = s.step()
    assert d2.action == "scale_down" and d2.target == 1
    # the youngest members retire first; the longest-lived replica
    # keeps its warm caches
    assert fc.removed == ["replica-3", "replica-2", "replica-1"]
    assert fc.members == ["replica-0"]
    assert [r["instance"] for r in d2.evidence["retired"]] == fc.removed


def test_step_flap_storm_never_scales():
    fc = _FakeController(n=2)
    fc.router.svc_s = 0.01
    overload = {"queue_depth": 30, "healthy_replicas": 2,
                "replicas": {"replica-0": {"in_flight_rows": 100}}}
    idle = {"queue_depth": 0, "healthy_replicas": 2, "replicas": {}}
    s = _scaler(fc, min_replicas=1)
    for i in range(12):
        fc.router.snapshot_doc = overload if i % 2 == 0 else idle
        d = s.step()
        assert d is None or d.action == "hold"
    assert fc.added == [] and fc.removed == []
    assert s.governor.triggers == 0


def test_stale_burn_over_idle_fleet_still_scales_down():
    # a LATCHED burn (e.g. serving-drift-js is a running max that never
    # decays) over a fleet with no offered load is stale evidence: it
    # must not pin the direction "up" and deadlock scale-down forever
    fc = _FakeController(n=3)
    fc.router.svc_s = 0.01
    fc.router.snapshot_doc = {"queue_depth": 0, "healthy_replicas": 3,
                              "replicas": {}}
    fc.slo_engine.firing = ["serving-drift-js"]
    s = _scaler(fc, min_replicas=1, down_consecutive=2)
    decisions = [s.step() for _ in range(2)]
    trigger = decisions[-1]
    assert trigger is not None and trigger.action == "scale_down"
    assert trigger.reason.startswith("idle:")
    assert fc.removed and not fc.added
    fc = _FakeController(n=3)
    fc.router = None  # no data plane reads: adoption alone
    s = _scaler(fc, interval_s=0.05)
    s.start()
    try:
        time.sleep(0.2)
    finally:
        s.stop()
    decisions = s.decisions()
    assert decisions[0].action == "adopt"
    assert decisions[0].members_before == 3
    assert decisions[0].evidence["governor"]["up_streak"] == 0
    # a restarted autoscaler cannot justify a scale event it cannot
    # derive from fresh windows: nothing but the adoption is recorded
    assert [d.action for d in decisions] == ["adopt"]
    assert s.scale_ups == 0 and s.scale_downs == 0
    assert fc.autoscaler is s
    snap = s.snapshot()
    assert snap["crashed"] is False and snap["members"] == 3


def test_retune_rides_the_loop_and_never_regresses(fleet_registry):
    # latency burns but the capacity trigger has not fired: the loop
    # A/B-probes micro-batch knobs instead of scaling
    fc = _FakeController(n=2)
    fc.router.svc_s = 0.01
    # queue_depth 1: a burn only counts with offered load behind it
    # (a stale latched burn over an idle fleet must not pin "up")
    fc.router.snapshot_doc = {"queue_depth": 1, "healthy_replicas": 2,
                              "replicas": {}}
    fc.slo_engine.firing = ["serving-p99-latency"]

    def fast_big_batches(knobs):
        return 100.0 + float(knobs["max_batch_size"])

    s = _scaler(fc, retune_enabled=True, ref_batch_rows=16,
                measure_fn=fast_big_batches, retune_margin=0.03)
    d = s.step()  # window 1: direction up, streak building -> retune
    assert d.action == "retune" and d.outcome == "tuned"
    assert d.evidence["knob_decision"]["tuned"] is True
    cmd, args = fc.router.broadcasts[-1]
    assert cmd == "retune" and args["source"] == "autotune"
    assert args["max_batch_size"] == 32  # the winning candidate
    assert s.retunes == 1

    # a baseline win RESTORES the hand-set default: tuned knobs never
    # regress past it (ties and margins keep the baseline)
    fc2 = _FakeController(n=2)
    fc2.router.svc_s = 0.01
    fc2.router.snapshot_doc = dict(fc.router.snapshot_doc)
    fc2.slo_engine.firing = ["serving-p99-latency"]
    s2 = _scaler(fc2, retune_enabled=True, ref_batch_rows=16,
                 measure_fn=lambda k: 100.0, retune_margin=0.03)
    d2 = s2.step()
    assert d2.action == "retune" and d2.outcome == "baseline_held"
    cmd2, args2 = fc2.router.broadcasts[-1]
    assert cmd2 == "retune" and args2["source"] == "hand_set"
    assert args2["max_batch_size"] == 0  # resets the worker cap

    # the retune cooldown holds: the very next burning window must not
    # probe again
    assert s2._retune_cooldown_left > 0
    d3 = s2.step()
    assert d3.action in ("hold", "scale_up")


def test_decision_to_json_round_trips():
    d = AutoscaleDecision(action="scale_up", outcome="trigger",
                          reason="r", members_before=2,
                          members_after=4, target=4,
                          evidence={"utilization": 2.0})
    doc = d.to_json()
    assert doc["action"] == "scale_up" and doc["target"] == 4
    assert doc["evidence"] == {"utilization": 2.0}
    assert doc["t"] <= time.time()


def test_autoscaler_validates_bounds():
    with pytest.raises(ValueError):
        FleetAutoscaler(_FakeController(), min_replicas=0)
    with pytest.raises(ValueError):
        FleetAutoscaler(_FakeController(), min_replicas=4,
                        max_replicas=2)


# ---------------------------------------------------------------------------
# worker retune verb + chunk cap (live, 1 replica)
# ---------------------------------------------------------------------------
def test_worker_retune_verb_applies_chunk_cap(fleet_registry, tmp_path):
    records = fleet_registry["records"]
    with _controller(fleet_registry, tmp_path, 1) as fc:
        out = fc.router.score_batch(records[:30], timeout_s=60.0)
        assert len(out) == 30
        doc = fc.router.control("replica-0", "retune",
                                {"max_batch_size": 8}, timeout_s=30.0)
        assert doc["ok"] and doc["applied"]["max_batch_size"] == 8
        assert doc["knobs"]["source"] == "autotune"
        # scoring still conserves rows: 30 rows through 8-row chunks
        out = fc.router.score_batch(records[:30], timeout_s=60.0)
        assert len(out) == 30
        info = fc.router.control("replica-0", "status", timeout_s=30.0)
        assert info["knobs"]["max_batch_size"] == 8
        # <= 0 resets to the hand-set default
        doc = fc.router.control(
            "replica-0", "retune",
            {"max_batch_size": 0, "source": "hand_set"}, timeout_s=30.0)
        assert doc["knobs"] == {"max_batch_size": None,
                                "max_wait_us": None,
                                "source": "hand_set"}


# ---------------------------------------------------------------------------
# acceptance drill 1: the traffic ramp - grow under load, shrink idle,
# zero drops, exact conservation, every decision under ONE trace id
# ---------------------------------------------------------------------------
def test_traffic_ramp_grows_and_shrinks_without_dropping_rows(
        fleet_registry, tmp_path):
    from transmogrifai_tpu.obs.trace import tracer

    records = fleet_registry["records"]
    batch = records[:24]
    t_start = time.monotonic()
    with _controller(
        fleet_registry, tmp_path, 2, transport="tcp", max_restarts=0,
        worker_env={"TX_FAULTS": "serving.slow_batch:every=1:delay=0.03"},
    ) as fc:
        fc.router.score_batch(batch, timeout_s=60.0)  # warm
        delivered: list = []
        errors: list = []
        stop_pump = threading.Event()

        def pump() -> None:
            while not stop_pump.is_set():
                try:
                    res = fc.router.submit(records=batch).wait(120.0)
                    delivered.append(res.n_rows)
                except Exception as e:  # noqa: BLE001 - ledger counts
                    errors.append(repr(e))

        threads = [threading.Thread(target=pump) for _ in range(6)]
        with tracer().span("autoscale-ramp-drill") as ramp_span:
            scaler = FleetAutoscaler(
                fc, min_replicas=2, max_replicas=4, interval_s=0.25,
                up_consecutive=2, down_consecutive=3,
                cooldown_windows=2, retune_enabled=False,
                probe_timeout_s=120.0, drain_timeout_s=60.0)
            scaler.start()
            try:
                for t in threads:
                    t.start()
                # surge: the backlog pushes utilization over 1.0, the
                # governor streak completes, and the cost-model sizing
                # grows the fleet 2 -> >= 4 (probe-gated admission)
                deadline = time.monotonic() + RAMP_DEADLINE_S
                while time.monotonic() < deadline:
                    if len(fc.member_instances()) >= 4:
                        break
                    time.sleep(0.1)
                grown = len(fc.member_instances())
                stop_pump.set()
                for t in threads:
                    t.join(timeout=120.0)
                assert grown >= 4, \
                    f"fleet never grew under load: {grown} members"
                # idle: served EWMA decays, the down streak completes,
                # and the fleet drains back to min_replicas
                while time.monotonic() < deadline:
                    if len(fc.member_instances()) <= 2:
                        break
                    time.sleep(0.1)
                assert len(fc.member_instances()) == 2, \
                    "fleet never shrank back after load stopped"
            finally:
                stop_pump.set()
                scaler.stop()

        # ZERO dropped rows, exact double-entry conservation across
        # every transition (grow, serve, drain, retire)
        assert errors == []
        snap = fc.router.snapshot()
        assert snap["rows_ok"] == (len(delivered) + 1) * len(batch)
        assert sum(delivered) == len(delivered) * len(batch)
        assert snap["requests_failed"] == 0

        # the decision trail: a recorded scale_up AND scale_down, each
        # carrying its evidence, all under the ONE ramp trace id
        actions = [d.action for d in scaler.decisions()]
        assert "adopt" == actions[0]
        assert "scale_up" in actions and "scale_down" in actions
        up = next(d for d in scaler.decisions()
                  if d.action == "scale_up")
        assert up.evidence["capacity"]["per_replica_rows_s"] > 0
        assert up.evidence["governor"]["triggers"] >= 1
        assert up.members_after > up.members_before
        down = next(d for d in scaler.decisions()
                    if d.action == "scale_down")
        assert down.members_after < down.members_before
        assert all(r.get("drained") for r in down.evidence["retired"])
        decision_spans = [
            s for s in tracer().spans(ramp_span.trace_id)
            if s["name"] == "autoscaler.decision"]
        assert len(decision_spans) >= 3  # adopt + up + down at least
        assert {s["trace"] for s in decision_spans} \
            == {ramp_span.trace_id}

        # the status document carries the autoscaler columns
        status = fc.status()
        assert status["autoscaler"]["scale_ups"] >= 1
        assert status["autoscaler"]["scale_downs"] >= 1
        assert status["autoscaler"]["replicas_added"] >= 2
    assert time.monotonic() - t_start < RAMP_DEADLINE_S + 60.0


# ---------------------------------------------------------------------------
# acceptance drill 2: SIGKILL of a draining scale-down victim - the
# router's failover owns the strands, conservation holds
# ---------------------------------------------------------------------------
def test_scale_down_victim_sigkilled_mid_drain_conserves_rows(
        fleet_registry, tmp_path):
    records = fleet_registry["records"]
    batch = records[:24]
    with _controller(
        fleet_registry, tmp_path, 3, max_restarts=0,
        worker_env={"TX_FAULTS": "serving.slow_batch:every=1:delay=0.15"},
    ) as fc:
        fc.router.score_batch(batch, timeout_s=60.0)  # warm
        victim_pid = fc._replicas["replica-2"].proc.pid
        delivered: list = []
        errors: list = []
        submitted = 36

        def pump(k: int) -> None:
            for _ in range(k):
                try:
                    res = fc.router.submit(records=batch).wait(120.0)
                    delivered.append(res.n_rows)
                except Exception as e:  # noqa: BLE001 - ledger counts
                    errors.append(repr(e))

        threads = [threading.Thread(target=pump, args=(submitted // 4,))
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # saturate: the victim holds in-flight work
        report: dict = {}

        def retire() -> None:
            report.update(fc.remove_replica("replica-2",
                                            drain_timeout_s=60.0))

        retirer = threading.Thread(target=retire)
        retirer.start()
        time.sleep(0.1)  # the drain is underway, batches in flight
        os.kill(victim_pid, signal.SIGKILL)
        retirer.join(timeout=120.0)
        assert not retirer.is_alive(), "removal hung on a dead victim"
        for t in threads:
            t.join(timeout=120.0)

        # EXACT conservation: everything the dead victim stranded was
        # re-dispatched to survivors - nothing lost, nothing doubled
        assert errors == []
        assert len(delivered) == submitted
        assert sum(delivered) == submitted * len(batch)
        snap = fc.router.snapshot()
        assert snap["rows_ok"] == (submitted + 1) * len(batch)
        assert report["instance"] == "replica-2"
        assert sorted(fc.member_instances()) \
            == ["replica-0", "replica-1"]
        live = {h.instance for h in fc.router.live_replicas()}
        assert live == {"replica-0", "replica-1"}
        post = fc.router.score_batch(batch, timeout_s=60.0)
        assert len(post) == len(batch)


# ---------------------------------------------------------------------------
# acceptance drill 3: autoscaler.crash - the control plane dies, the
# data plane keeps serving, a restarted autoscaler adopts
# ---------------------------------------------------------------------------
def test_autoscaler_crash_leaves_the_data_plane_serving(
        fleet_registry, tmp_path):
    records = fleet_registry["records"]
    batch = records[:16]
    with _controller(fleet_registry, tmp_path, 2,
                     max_restarts=0) as fc:
        fc.router.score_batch(batch, timeout_s=60.0)  # warm
        faults.configure("autoscaler.crash:on=2")
        scaler = FleetAutoscaler(fc, min_replicas=2, max_replicas=4,
                                 interval_s=0.1, retune_enabled=False)
        scaler.start()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and scaler.alive():
            time.sleep(0.05)
        faults.reset()
        assert not scaler.alive() and scaler.crashed
        assert scaler.snapshot()["crashed"] is True

        # the data plane never noticed: replicas, router, supervision
        # all keep serving through the control-plane death
        out = fc.router.score_batch(batch, timeout_s=60.0)
        assert len(out) == len(batch)
        assert sorted(fc.member_instances()) \
            == ["replica-0", "replica-1"]

        # a restarted autoscaler ADOPTS the live fleet: its first
        # decision is the adoption, and with the fleet steady it
        # cannot justify any scale event from fresh evidence
        scaler2 = FleetAutoscaler(fc, min_replicas=2, max_replicas=4,
                                  interval_s=0.1,
                                  retune_enabled=False)
        scaler2.start()
        try:
            time.sleep(0.6)
        finally:
            scaler2.stop()
        decisions = scaler2.decisions()
        assert decisions[0].action == "adopt"
        assert scaler2.scale_ups == 0 and scaler2.scale_downs == 0
        assert all(d.action in ("adopt", "hold") for d in decisions)
        assert fc.status()["autoscaler"]["crashed"] is False


# ---------------------------------------------------------------------------
# satellite: the bulk job re-resolves its router at shard boundaries
# ---------------------------------------------------------------------------
def test_bulk_job_re_resolves_router_when_fleet_grows_mid_job(
        fleet_registry, tmp_path):
    from transmogrifai_tpu.bulk import BulkScoringJob

    wf, data, _records, _pred = tiny_drill_pipeline(n=120, seed=0)
    model = wf.train()
    rows = [{"y": data["y"][i], "a": data["a"][i], "c": data["c"][i]}
            for i in range(120)]
    shards = []
    for k in range(3):
        p = str(tmp_path / f"in-{k}.csv")
        write_shard_csv(p, rows[k * 40:(k + 1) * 40])
        shards.append(p)
    reg_root = str(tmp_path / "bulk-registry")
    ModelRegistry(reg_root).publish(model, stage="stable")
    with FleetController(
        reg_root, WORKFLOW_SPEC, n_replicas=2,
        work_dir=str(tmp_path / "fleet"), ship_interval_s=0.15,
        max_restarts=0,
        router_kw={"max_in_flight_per_replica": 2, "max_queue": 64},
    ) as fc:
        resolutions = [0]

        def live_router():
            resolutions[0] += 1
            if resolutions[0] == 2:
                # the fleet grows AT the first shard boundary - the
                # job must pick up the new replica set, not a pinned
                # snapshot from planning time
                fc.add_replica(probe_timeout_s=120.0)
            return fc.router

        jd = str(tmp_path / "job")
        s = BulkScoringJob(model, jd, shards, router=live_router,
                           chunk_rows=16, max_in_flight=4).run()
        led = s["ledger"]
        assert led["balanced"] and led["rows_in"] == 120
        # one resolution at construction + one per shard boundary
        assert resolutions[0] == 1 + 3
        live = {h.instance for h in fc.router.live_replicas()}
        assert "replica-2" in live
        assert len(fc.member_instances()) == 3

        # a CONTROLLER source re-resolves the same way (the live
        # ``controller.router`` attribute each boundary)
        jd2 = str(tmp_path / "job2")
        s2 = BulkScoringJob(model, jd2, shards, router=fc,
                            chunk_rows=16, max_in_flight=4).run()
        assert s2["ledger"]["balanced"]


# ---------------------------------------------------------------------------
# satellite: the health eject/readmit knobs ride the controller seam
# ---------------------------------------------------------------------------
def test_health_knobs_flow_through_controller(fleet_registry, tmp_path):
    with _controller(fleet_registry, tmp_path, 1, eject_after=5,
                     probe_interval_s=0.25,
                     probe_timeout_s=1.25) as fc:
        assert fc.router.eject_after == 5
        assert fc.router.probe_interval_s == 0.25
        assert fc.router.probe_timeout_s == 1.25
        assert fc.router.handle("replica-0").health.eject_after == 5
