"""Async sharded input pipeline drills (readers/pipeline.py, ISSUE 10).

Covers the determinism seam (serial-vs-pipelined identical datasets,
selection, and planted coefficients), exact quarantine accounting under
worker concurrency (including armed fault points), clean shutdown on
producer crash with the shard + file named, the workflow streaming
ingest mode's partial-fit parity, and the tier-1 4-worker throughput
floor (mechanism asserted before the ratio, mirroring the fused-serving
floor pattern).
"""
import io
import os
import time

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.readers import fast_csv
from transmogrifai_tpu.readers.csv_reader import CSVReader
from transmogrifai_tpu.readers.pipeline import (
    InputPipeline,
    PipelinedCSVReader,
    ShardIngestError,
    pipelined_columns,
    pipelined_design_matrix,
    shard,
    stack_chunk_columns,
)
from transmogrifai_tpu.schema.quarantine import (
    MalformedRowError,
    QuarantineBuffer,
)
from transmogrifai_tpu.selector.validator import (
    OpCrossValidation,
    StreamingFoldBuilder,
    stratified_kfold_masks,
)
from transmogrifai_tpu.testkit.random_data import write_corrupted_csv
from transmogrifai_tpu.types import feature_types as ft

pytestmark = pytest.mark.skipif(
    not fast_csv.fast_path_available(),
    reason="native CSV kernels unavailable",
)

rng = np.random.RandomState(0)


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    yield
    faults.reset()


def _write_shards(tmp_path, nshards=4, rows=2_000, d=3, seed=0,
                  prefix="s"):
    r = np.random.RandomState(seed)
    paths = []
    for s in range(nshards):
        M = r.randn(rows, d)
        buf = io.StringIO()
        np.savetxt(buf, M, delimiter=",", fmt="%.6f")
        p = str(tmp_path / f"{prefix}{s}.csv")
        with open(p, "w") as f:
            f.write(",".join(f"x{i}" for i in range(d)) + "\n")
            f.write(buf.getvalue())
        paths.append(p)
    return paths


def _schema(d=3):
    return {f"x{i}": ft.Real for i in range(d)}


# -- determinism seam --------------------------------------------------------

def test_pipelined_columns_identical_to_serial(tmp_path):
    paths = _write_shards(tmp_path)
    schema = _schema()
    serial = [fast_csv.read_csv_columnar(p, schema) for p in paths]
    pipe = InputPipeline(shard(paths), schema, workers=4,
                         chunk_bytes=1 << 15)
    cols = pipelined_columns(pipe)
    for name in schema:
        want = np.concatenate([c[name].values for c in serial])
        assert np.array_equal(cols[name].values, want)
        wmask = np.concatenate([c[name].mask for c in serial])
        assert np.array_equal(cols[name].mask, wmask)


def test_chunks_carry_shard_and_chunk_ids_and_ordered_mode(tmp_path):
    paths = _write_shards(tmp_path, nshards=5)
    # an empty shard (header only) must not wedge ordered reassembly
    empty = str(tmp_path / "empty.csv")
    with open(empty, "w") as f:
        f.write("x0,x1,x2\n")
    paths.insert(2, empty)
    pipe = InputPipeline(shard(paths), _schema(), workers=3,
                         chunk_bytes=1 << 14, ordered=True)
    keys = [pc.order_key for pc in pipe.chunks()]
    assert keys == sorted(keys)
    assert len({k[0] for k in keys}) == 5  # every non-empty shard
    assert all(k[0] != 2 for k in keys)  # the empty shard has no chunks


def test_design_matrix_deterministic_any_arrival_order(tmp_path):
    paths = _write_shards(tmp_path, nshards=6, rows=1_500)
    schema = _schema()
    cols = list(schema)
    ref = None
    for workers in (1, 4):
        pipe = InputPipeline(shard(paths), schema, workers=workers,
                             chunk_bytes=1 << 14)
        X, M, n = pipelined_design_matrix(pipe, cols)
        assert n == 9_000
        if ref is None:
            ref = X
        else:
            assert np.array_equal(ref, X)


def test_serial_vs_pipelined_model_parity_planted(tmp_path):
    """The ISSUE determinism pin: the same model fit from serial and
    pipelined ingest — identical selection and planted-coefficient
    parity."""
    d, rows, nshards = 4, 3_000, 4
    r = np.random.RandomState(3)
    beta = np.array([1.0, -0.5, 0.25, 0.0])
    paths = []
    for s in range(nshards):
        M = r.randn(rows, d)
        y = (M @ beta + 0.5 * r.randn(rows) > 0).astype(float)
        buf = io.StringIO()
        np.savetxt(buf, np.column_stack([y, M]), delimiter=",",
                   fmt="%.6f")
        p = str(tmp_path / f"pl{s}.csv")
        with open(p, "w") as f:
            f.write("y," + ",".join(f"x{i}" for i in range(d)) + "\n")
            f.write(buf.getvalue())
        paths.append(p)
    schema = {"y": ft.Real, **{f"x{i}": ft.Real for i in range(d)}}
    cols = ["y"] + [f"x{i}" for i in range(d)]
    # serial arm
    serial = [fast_csv.read_csv_columnar(p, schema) for p in paths]
    Xs = np.column_stack([
        np.concatenate([c[x].values for c in serial]) for x in cols[1:]
    ]).astype(np.float32)
    ys = np.concatenate([c["y"].values for c in serial])
    # pipelined arm
    pipe = InputPipeline(shard(paths), schema, workers=4,
                         chunk_bytes=1 << 14)
    Xp_full, _, _ = pipelined_design_matrix(pipe, cols)
    Xp, yp = Xp_full[:, 1:], Xp_full[:, 0].astype(np.float64)
    assert np.array_equal(Xs, Xp) and np.array_equal(ys, yp)
    # identical CV selection (streamed fold construction vs batch)
    grid = [{"reg_param": 1e-3}, {"reg_param": 1e-1}]
    cv = OpCrossValidation(num_folds=3, stratify=True)
    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )

    cv.evaluator = OpBinaryClassificationEvaluator()
    lr = OpLogisticRegression(max_iter=15)
    res_serial = cv.validate([(lr, grid)], Xs, ys)

    def _chunks():
        step = 2_000
        for i, at in enumerate(range(0, len(yp), step)):
            yield (0, i), Xp[at:at + step], yp[at:at + step]

    res_stream = cv.validate_stream([(lr, grid)], _chunks())
    assert res_serial.best_params == res_stream.best_params
    assert res_serial.best_metric == pytest.approx(
        res_stream.best_metric, abs=1e-12)
    # planted parity: both ingest routes recover the same coefficients
    p_s = lr.fit_arrays(Xs, ys)
    p_p = lr.fit_arrays(Xp, yp)
    assert np.array_equal(p_s["beta"], p_p["beta"])
    assert np.sign(p_s["beta"][0]) > 0 and np.sign(p_s["beta"][1]) < 0


def test_streamed_fold_masks_bit_identical_shuffled_arrival():
    y = (np.random.RandomState(5).rand(10_000) > 0.6).astype(float)
    X = np.random.RandomState(6).randn(10_000, 3).astype(np.float32)
    want = stratified_kfold_masks(y, 4, seed=11, stratify=True)
    fb = StreamingFoldBuilder(4, seed=11, stratify=True)
    step = 1_000
    order = list(range(0, 10_000, step))
    np.random.RandomState(7).shuffle(order)  # arrival != source order
    for at in order:
        fb.observe((0, at // step), X[at:at + step], y[at:at + step])
    Xf, yf, masks = fb.finalize()
    assert np.array_equal(masks, want)
    assert np.array_equal(yf, y) and np.array_equal(Xf, X)


# -- quarantine under concurrency --------------------------------------------

def test_quarantine_counts_exact_multi_shard(tmp_path):
    nshards, rows, flips = 5, 400, 17
    paths, truths = [], []
    for s in range(nshards):
        p = str(tmp_path / f"bad{s}.csv")
        truths.append(write_corrupted_csv(
            p, n_rows=rows, n_type_flips=flips, n_truncated=0,
            seed=50 + s))
        paths.append(p)
    schema = {"y": ft.Real, "a": ft.Real, "c": ft.Text}
    pipe = InputPipeline(shard(paths), schema, workers=4,
                         errors="quarantine", chunk_bytes=1 << 13,
                         quarantine_max_rows=1 << 16)
    kept = sum(pc.n_rows for pc in pipe.chunks())
    merged = pipe.merged_quarantine()
    expected_rows = sorted(
        s * rows + r
        for s, t in enumerate(truths) for r in t["type_flip_rows"]
    )
    assert merged.total == nshards * flips
    assert kept == nshards * (rows - flips)
    assert sorted(r.row_index for r in merged.rows) == expected_rows
    assert merged.by_reason == {"type_flip": nshards * flips}
    # deterministic regardless of completion order: merge again equal
    merged2 = pipe.merged_quarantine()
    assert ([r.to_json() for r in merged2.rows]
            == [r.to_json() for r in merged.rows])


def test_quarantine_python_path_ragged_rows(tmp_path):
    """The python fallback shard reader owns ragged-row detection the
    native scanner cannot do — counts stay exact through the pipeline."""
    nshards, rows = 3, 300
    paths, truths = [], []
    for s in range(nshards):
        p = str(tmp_path / f"rag{s}.csv")
        truths.append(write_corrupted_csv(
            p, n_rows=rows, n_type_flips=4, n_truncated=6,
            seed=70 + s))
        paths.append(p)
    schema = {"y": ft.Real, "a": ft.Real, "c": ft.Text}
    pipe = InputPipeline(shard(paths), schema, workers=3,
                         errors="quarantine", chunk_rows=64,
                         use_native=False, quarantine_max_rows=1 << 16)
    kept = sum(pc.n_rows for pc in pipe.chunks())
    merged = pipe.merged_quarantine()
    assert merged.total == nshards * 10
    assert kept == nshards * (rows - 10)
    assert merged.by_reason["type_flip"] == nshards * 4
    assert merged.by_reason["truncated_row"] == nshards * 6


def test_fault_points_fire_inside_worker_shards(tmp_path):
    """reader.malformed_row / reader.type_flip armed while 4 workers
    parse concurrently: exact fire accounting (times=K bounds total
    fires across ALL workers), no hang, clean drain."""
    paths = _write_shards(tmp_path, nshards=4, rows=500)
    schema = _schema()
    faults.configure(
        "reader.type_flip:every=1:times=3 "
        "reader.malformed_row:every=1:times=2"
    )
    pipe = InputPipeline(shard(paths), schema, workers=4,
                         errors="quarantine", chunk_bytes=1 << 13,
                         quarantine_max_rows=1 << 16)
    t0 = time.perf_counter()
    kept = sum(pc.n_rows for pc in pipe.chunks())
    assert time.perf_counter() - t0 < 60
    merged = pipe.merged_quarantine()
    # the two points can co-fire on the same chunk's row 0 (one row,
    # one reason recorded) - total injected rows is between max and sum
    assert 3 <= merged.total <= 5
    assert kept == 2_000 - merged.total
    assert set(merged.by_reason) <= {"type_flip", "malformed_row"}


def test_strict_mode_error_names_shard_and_file(tmp_path):
    paths = _write_shards(tmp_path, nshards=3, rows=200)
    bad = str(tmp_path / "s1.csv")  # corrupt the middle shard
    with open(bad, "a") as f:
        f.write("junk_cell,1.0,2.0\n")
    pipe = InputPipeline(shard(paths), _schema(), workers=3,
                         errors="strict", chunk_bytes=1 << 13)
    with pytest.raises(ShardIngestError) as exc:
        for _ in pipe.chunks():
            pass
    assert exc.value.shard_id == 1
    assert exc.value.path == bad
    assert isinstance(exc.value.cause, MalformedRowError)
    # workers all joined: no leaked live threads
    assert all(not t.is_alive() for t in pipe._threads)


def test_producer_crash_drains_cleanly_no_hang(tmp_path):
    """A worker crash (unreadable shard) surfaces as ShardIngestError
    naming the shard + file; the bounded queue drains and every worker
    joins - the pipeline can never wedge the trainer."""
    paths = _write_shards(tmp_path, nshards=4, rows=800)
    paths[2] = str(tmp_path / "missing.csv")  # ENOENT mid-fleet
    pipe = InputPipeline(shard(paths), _schema(), workers=2,
                         buffer_chunks=1, chunk_bytes=1 << 12)
    t0 = time.perf_counter()
    with pytest.raises(ShardIngestError) as exc:
        for _ in pipe.chunks():
            pass
    assert time.perf_counter() - t0 < 60
    assert exc.value.shard_id == 2
    assert "missing.csv" in str(exc.value)
    assert all(not t.is_alive() for t in pipe._threads)
    assert pipe._queue.qsize() == 0  # drained


def test_consumer_abandonment_stops_workers(tmp_path):
    paths = _write_shards(tmp_path, nshards=4, rows=2_000)
    pipe = InputPipeline(shard(paths), _schema(), workers=4,
                         buffer_chunks=1, chunk_bytes=1 << 12)
    it = pipe.chunks()
    next(it)
    it.close()  # GeneratorExit mid-stream
    assert all(not t.is_alive() for t in pipe._threads)


def test_parquet_and_avro_shards_interleave(tmp_path):
    """The interleave stage speaks all three formats: a mixed
    CSV + Parquet + Avro shard list lands one consistent column set."""
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from transmogrifai_tpu.readers.avro_reader import save_dataset_avro
    from transmogrifai_tpu.types.columns import column_from_list
    from transmogrifai_tpu.types.dataset import Dataset

    r = np.random.RandomState(21)
    vals = {s: r.randn(500, 2) for s in range(3)}
    csv_p = str(tmp_path / "m0.csv")
    with open(csv_p, "w") as f:
        f.write("x0,x1\n")
        np.savetxt(f, vals[0], delimiter=",", fmt="%.6f")
    pq_p = str(tmp_path / "m1.parquet")
    pq.write_table(
        pa.table({"x0": vals[1][:, 0], "x1": vals[1][:, 1]}), pq_p)
    av_p = str(tmp_path / "m2.avro")
    save_dataset_avro(Dataset({
        "x0": column_from_list(vals[2][:, 0], ft.Real),
        "x1": column_from_list(vals[2][:, 1], ft.Real),
    }), av_p)
    schema = {"x0": ft.Real, "x1": ft.Real}
    pipe = InputPipeline(shard([csv_p, pq_p, av_p]), schema, workers=3,
                         chunk_rows=200)
    cols = pipelined_columns(pipe)
    want = np.concatenate([vals[s][:, 0] for s in range(3)])
    assert len(cols["x0"].values) == 1_500
    assert np.allclose(cols["x0"].values, want, atol=1e-5)


def test_avro_shard_checked_modes_match_serial_reader(tmp_path):
    """Avro shards through the pipeline must count type flips exactly
    like the serial avro route (strict raises, quarantine drops)."""
    from transmogrifai_tpu.readers.avro_reader import save_dataset_avro
    from transmogrifai_tpu.types.columns import column_from_list
    from transmogrifai_tpu.types.dataset import Dataset

    av = str(tmp_path / "flip.avro")
    save_dataset_avro(Dataset({
        "x0": column_from_list(
            ["1.5", "junk", "2.5", "alsojunk", "3.5"], ft.Text),
    }), av)
    schema = {"x0": ft.Real}
    pipe = InputPipeline(shard([av]), schema, workers=1,
                         errors="quarantine")
    kept = sum(pc.n_rows for pc in pipe.chunks())
    merged = pipe.merged_quarantine()
    assert kept == 3 and merged.total == 2
    assert merged.by_reason == {"type_flip": 2}
    assert sorted(r.row_index for r in merged.rows) == [1, 3]
    pipe2 = InputPipeline(shard([av]), schema, workers=1,
                          errors="strict")
    with pytest.raises(ShardIngestError) as exc:
        for _ in pipe2.chunks():
            pass
    assert isinstance(exc.value.cause, MalformedRowError)
    assert exc.value.cause.row_index == 1


# -- workflow streaming ingest ----------------------------------------------

def _csv_workflow_shards(tmp_path, nshards=3, rows=400):
    import csv as _csv

    r = np.random.RandomState(9)
    paths = []
    for s in range(nshards):
        p = str(tmp_path / f"wf{s}.csv")
        with open(p, "w", newline="") as f:
            w = _csv.writer(f)
            w.writerow(["y", "a", "cat"])
            for i in range(rows):
                a = r.randn()
                y = float(a + 0.3 * r.randn() > 0)
                w.writerow([
                    y, "" if i % 13 == 0 else f"{a:.6f}",
                    ("u", "v", "w")[int(r.randint(3))],
                ])
        paths.append(p)
    return paths


def _wf(reader):
    from transmogrifai_tpu.ops.categorical import StringIndexer
    from transmogrifai_tpu.ops.scalers import (
        FillMissingWithMean,
        OpScalarStandardScaler,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.utils.uid import reset_uids

    reset_uids()
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    cat = FeatureBuilder(ft.Text, "cat").as_predictor()
    am = FillMissingWithMean().set_input(a).get_output()
    asc = OpScalarStandardScaler().set_input(am).get_output()
    ci = StringIndexer().set_input(cat).get_output()
    vec = transmogrify([asc, ci])
    pred = OpLogisticRegression(reg_param=0.1).set_input(
        y, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_reader(reader)
    return wf, pred


def test_workflow_streaming_ingest_partial_fit_parity(tmp_path):
    """Streaming train (vectorizer stats accumulated while shards
    parse) must produce the same fitted stages and scores as the serial
    reader over the concatenated data."""
    paths = _csv_workflow_shards(tmp_path)
    concat = str(tmp_path / "all.csv")
    with open(concat, "w") as out:
        out.write("y,a,cat\n")
        for p in paths:
            with open(p) as f:
                next(f)
                out.write(f.read())
    schema = {"y": ft.RealNN, "a": ft.Real, "cat": ft.Text}
    wf_s, pred_s = _wf(CSVReader(concat, schema=schema))
    m_s = wf_s.train()
    wf_p, pred_p = _wf(PipelinedCSVReader(paths, workers=3,
                                          chunk_rows=128,
                                          chunk_bytes=1 << 12))
    m_p = wf_p.train()
    by_type_s = {type(s).__name__: s for s in m_s.stages}
    by_type_p = {type(s).__name__: s for s in m_p.stages}
    assert by_type_s["_FillMeanModel"].fill == pytest.approx(
        by_type_p["_FillMeanModel"].fill, rel=1e-12)
    assert by_type_s["_ScaleModel"].mean == pytest.approx(
        by_type_p["_ScaleModel"].mean, rel=1e-12)
    assert by_type_s["_ScaleModel"].std == pytest.approx(
        by_type_p["_ScaleModel"].std, rel=1e-9)
    assert (by_type_s["StringIndexerModel"].labels
            == by_type_p["StringIndexerModel"].labels)
    probe = {"y": [0.0, 1.0], "a": [0.5, -1.2], "cat": ["u", "w"]}
    s_s = m_s.score(data=probe)[pred_s.name]
    s_p = m_p.score(data=probe)[pred_p.name]
    assert np.allclose(s_s.probability, s_p.probability, atol=1e-9)


def test_partial_fit_stats_are_one_shot():
    """A fold refit after a streamed fit must re-observe its own data,
    never silently reuse full-data statistics (leakage guard)."""
    from transmogrifai_tpu.ops.scalers import FillMissingWithMean
    from transmogrifai_tpu.types.columns import NumericColumn
    from transmogrifai_tpu.types.dataset import Dataset

    est = FillMissingWithMean()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    est.set_input(a)
    est.accept_partial_fits([(2, 10.0), (2, 30.0)])
    ds = Dataset({"a": NumericColumn(np.array([1.0, 2.0]),
                                     np.array([True, True]), ft.Real)})
    m1 = est.fit(ds)
    assert m1.fill == pytest.approx(10.0)  # streamed (10+30)/4
    m2 = est.fit(ds)  # refit: stats consumed, falls back to the data
    assert m2.fill == pytest.approx(1.5)


def test_runner_pipelined_ingest_knob(tmp_path):
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    paths = _csv_workflow_shards(tmp_path, nshards=2, rows=200)
    wf, pred = _wf(None)
    runner = OpWorkflowRunner(wf)
    params = OpParams(custom_params={
        "ingest_shards": paths, "ingest_workers": 2,
    })
    res = runner.run("train", params)
    assert res.model is not None
    assert len(res.model._train_data_cache) == 400


# -- streamed sufficient-statistics fit --------------------------------------

def test_linreg_fit_from_stats_matches_batch_kernel():
    r = np.random.RandomState(13)
    n, d = 20_000, 6
    X = r.randn(n, d).astype(np.float32)
    beta = r.randn(d)
    y = X @ beta + 0.05 * r.randn(n)
    est = OpLinearRegression(reg_param=1e-3)
    batch = est.fit_arrays(X, y)
    stats = [
        OpLinearRegression.streaming_fit_stats(X[at:at + 2_500],
                                               y[at:at + 2_500])
        for at in range(0, n, 2_500)
    ]
    streamed = est.fit_from_stats(stats)
    assert np.allclose(batch["beta"], streamed["beta"], atol=1e-4)
    assert batch["intercept"] == pytest.approx(
        streamed["intercept"], abs=1e-4)
    assert np.abs(streamed["beta"] - beta).max() < 0.05


def test_stack_chunk_columns_matches_block(tmp_path):
    paths = _write_shards(tmp_path, nshards=1, rows=500)
    pipe = InputPipeline(shard(paths), _schema(), workers=1)
    cols = list(_schema())
    for pc in pipe.chunks():
        A = stack_chunk_columns(pc.payload, cols)
        block, _mask = fast_csv.chunk_to_block(pc.payload, cols)
        assert np.allclose(A.T, block, atol=1e-6)


# -- observability -----------------------------------------------------------

def test_ingest_shard_spans_join_ambient_trace(tmp_path):
    from transmogrifai_tpu.obs import trace as obs_trace

    paths = _write_shards(tmp_path, nshards=3, rows=300)
    tracer = obs_trace.reset_tracer()
    with obs_trace.span("test.root") as root:
        pipe = InputPipeline(shard(paths), _schema(), workers=3)
        for _ in pipe.chunks():
            pass
        trace_id = root.trace_id
    spans = tracer.spans(trace_id)
    shard_spans = [s for s in spans if s["name"] == "ingest.shard"]
    assert len(shard_spans) == 3
    assert {s["attrs"]["shard"] for s in shard_spans} == {0, 1, 2}
    for s in shard_spans:
        assert s["attrs"]["rows"] == 300
        assert s["attrs"]["quarantined"] == 0
    obs_trace.reset_tracer()


def test_pipeline_gauges_registered(tmp_path):
    from transmogrifai_tpu.obs.metrics import metrics_registry

    paths = _write_shards(tmp_path, nshards=2, rows=300)
    pipe = InputPipeline(shard(paths), _schema(), workers=2)
    for _ in pipe.chunks():
        pass
    doc = metrics_registry().to_json()["series"]
    assert "pipeline.buffer_depth" in doc
    assert doc["pipeline.chunks"]["value"] >= 2
    assert "pipeline.producer_stall_ms" in doc
    assert "pipeline.consumer_stall_ms" in doc


# -- tier-1 throughput floor -------------------------------------------------

def test_pipeline_4worker_throughput_floor(tmp_path):
    """Pipelined 4-worker ingest of a multi-shard CSV must sustain
    >= 1.5x the serial per-shard throughput on this host.  The test
    first asserts the pipeline actually ran workers CONCURRENTLY
    (producer busy time exceeding the wall = provable overlap) before
    reading the ratio, mirroring the fused-serving floor pattern; a
    failing ratio is re-measured before it fails the gate - a true
    regression to serial ingest fails every attempt."""
    if len(os.sched_getaffinity(0)) < 2:
        pytest.skip("throughput floor needs >=2 CPUs: 4 parse workers "
                    "cannot beat serial 1.5x on a single core")
    d, rows_per_shard, nshards = 8, 150_000, 8
    r = np.random.RandomState(1)
    buf = io.StringIO()
    np.savetxt(buf, r.randn(50_000, d), delimiter=",", fmt="%.5f")
    blk = buf.getvalue().encode() * (rows_per_shard // 50_000)
    hdr = (",".join(f"x{i}" for i in range(d)) + "\n").encode()
    paths = []
    for s in range(nshards):
        p = str(tmp_path / f"floor{s}.csv")
        with open(p, "wb") as f:
            f.write(hdr)
            f.write(blk)
        paths.append(p)
    for p in paths:  # page-cache warm so both arms measure parsing
        with open(p, "rb") as f:
            f.read()
    schema = {f"x{i}": ft.Real for i in range(d)}
    n_total = rows_per_shard * nshards

    def serial_wall():
        t0 = time.perf_counter()
        for p in paths:
            fast_csv.read_csv_columnar(p, schema)
        return time.perf_counter() - t0

    def pipelined_wall():
        pipe = InputPipeline(shard(paths), schema, workers=4)
        t0 = time.perf_counter()
        rows = sum(pc.n_rows for pc in pipe.chunks())
        wall = time.perf_counter() - t0
        assert rows == n_total
        return wall, pipe.stats.snapshot()

    ratio = None
    for _attempt in range(3):
        best_s = min(serial_wall(), serial_wall())
        wall_1, st_1 = pipelined_wall()
        wall_2, st_2 = pipelined_wall()
        best_p, st = ((wall_1, st_1) if wall_1 <= wall_2
                      else (wall_2, st_2))
        # mechanism first: workers provably ran concurrently (total
        # producer busy time well beyond one serial lane's wall)
        assert st["producer_busy_s"] > st["wall_s"] * 1.3, st
        assert st["overlap_fraction"] > 0.2, st
        ratio = best_s / best_p
        if ratio >= 1.5:
            break
    assert ratio >= 1.5, (
        f"pipelined 4-worker ingest only {ratio:.2f}x serial "
        f"({n_total / best_p:.0f} vs {n_total / best_s:.0f} rows/s) - "
        "the interleave stopped paying for itself"
    )
