"""OpWorkflowRunner run-type tests (reference: core/src/test/.../
OpWorkflowRunnerTest.scala - Train/Score/Features/Evaluate end-to-end)."""
import json
import os

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import column_from_list
from transmogrifai_tpu.types.dataset import Dataset
from transmogrifai_tpu.utils.uid import reset_uids
from transmogrifai_tpu.workflow.params import OpParams
from transmogrifai_tpu.workflow.runner import OpWorkflowRunner


class ListReader:
    """Minimal reader over in-memory rows."""

    def __init__(self, data: dict):
        self.data = data

    def generate_dataset(self, raw_features, params):
        return Dataset(
            {
                f.name: column_from_list(self.data[f.name], f.ftype)
                for f in raw_features
            }
        )


def _build(rng, n=200):
    reset_uids()
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "b": rng.randn(n).tolist(),
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([a, b])
    pred = OpLogisticRegression(reg_param=0.1).set_input(y, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_reader(ListReader(data))
    return wf, data, pred


def test_runner_train_score_evaluate(tmp_path, rng):
    wf, data, pred = _build(rng)
    runner = OpWorkflowRunner(wf, evaluator=OpBinaryClassificationEvaluator())
    params = OpParams(
        model_location=str(tmp_path / "model"),
        write_location=str(tmp_path / "scores"),
        metrics_location=str(tmp_path / "metrics"),
    )
    r1 = runner.run("train", params)
    assert r1.model is not None
    assert os.path.exists(tmp_path / "model" / "model.json")
    assert os.path.exists(tmp_path / "model" / "summary.json")

    # fresh workflow definition for load (same code, fresh uids)
    wf2, data2, pred2 = _build(rng)
    runner2 = OpWorkflowRunner(wf2, evaluator=OpBinaryClassificationEvaluator())
    r2 = runner2.run("score", params)
    assert r2.scores is not None and pred2.name in r2.scores
    with open(tmp_path / "scores" / "scores.json") as f:
        written = json.load(f)
    assert pred2.name in written and "y" in written

    wf3, _, _ = _build(rng)
    runner3 = OpWorkflowRunner(wf3, evaluator=OpBinaryClassificationEvaluator())
    r3 = runner3.run("evaluate", params)
    assert r3.metrics["AuROC"] > 0.4
    assert os.path.exists(tmp_path / "metrics" / "metrics.json")


def test_runner_features_and_param_injection(tmp_path, rng):
    wf, data, pred = _build(rng)
    runner = OpWorkflowRunner(wf)
    params = OpParams(
        write_location=str(tmp_path / "feat"),
        stage_params={"OpLogisticRegression": {"reg_param": 0.5}},
    )
    r = runner.run("features", params)
    assert set(r.scores.column_names()) == {"y", "a", "b"}
    # injection reached the stage
    stage = pred.origin_stage
    assert stage.params["reg_param"] == 0.5


def test_streaming_score(tmp_path, rng):
    wf, data, pred = _build(rng)
    runner = OpWorkflowRunner(wf)
    params = OpParams(model_location=str(tmp_path / "m"))
    runner.run("train", params)

    wf2, data2, _ = _build(rng)
    runner2 = OpWorkflowRunner(wf2)
    batches = [
        {k: v[i : i + 50] for k, v in data2.items()} for i in range(0, 200, 50)
    ]
    outs = list(runner2.streaming_score(batches, params))
    assert len(outs) == 4
    assert all(len(o) == 50 for o in outs)


def test_runner_avro_score_output(tmp_path, rng):
    """write_format='avro' saves scores as an Avro OCF (the reference's
    saveScores/saveAvro contract) that our own reader decodes back to the
    same prediction values."""
    from transmogrifai_tpu.readers.avro_reader import read_avro_records

    wf, data, pred = _build(rng)
    runner = OpWorkflowRunner(wf, evaluator=OpBinaryClassificationEvaluator())
    params = OpParams(
        model_location=str(tmp_path / "model"),
        write_location=str(tmp_path / "scores"),
        write_format="avro",
    )
    runner.run("train", params)
    result = runner.run("score", params)
    path = str(tmp_path / "scores" / "scores.avro")
    assert os.path.exists(path)
    schema, records = read_avro_records(path)
    assert len(records) == len(data["y"])
    # field names are sanitized to the avro name spec; the original
    # column name rides in the field doc
    field = next(
        f for f in schema["fields"] if f.get("doc") == pred.name
        or f["name"] == pred.name
    )
    import re
    assert re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", field["name"])
    scored_pred = result.scores[pred.name]
    for i in (0, 7, len(records) - 1):
        rec_map = records[i][field["name"]]
        assert rec_map["prediction"] == pytest.approx(
            float(scored_pred.prediction[i]))
