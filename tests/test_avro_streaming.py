"""Incremental avro OCF decode parity (readers/avro_reader.py, ISSUE 18).

``_iter_avro_chunks`` used to materialize the WHOLE shard's record list
before chunking (the documented memory limit); it now consumes
``AvroBlockStream`` block by block.  These drills pin the new path to
the old one: the pre-streaming whole-file decoder and the old
materialize-then-slice chunker are embedded here VERBATIM as oracles,
and the streaming route must match them bit for bit — record lists,
chunk boundaries, assembled column bytes, and exact quarantine
counts/indexes/excerpts under mid-file corruption and truncated tails.
Plus the point of the exercise: the read-ahead window must stay far
smaller than the file.
"""
from __future__ import annotations

import zlib

import numpy as np
import pytest

from transmogrifai_tpu.readers.avro_reader import (
    MAGIC,
    AvroBlockStream,
    _decode_value,
    _Decoder,
    read_avro_records,
    write_avro_records,
)
from transmogrifai_tpu.readers.pipeline import (
    CsvChunk,
    InputPipeline,
    _iter_avro_chunks,
    shard,
)
from transmogrifai_tpu.schema.quarantine import (
    MalformedRowError,
    QuarantineBuffer,
    coerce_numeric,
    excerpt_of,
)
from transmogrifai_tpu.types import feature_types as ft

SCHEMA = {
    "type": "record", "name": "R",
    "fields": [
        {"name": "x0", "type": ["null", "double"]},
        {"name": "x1", "type": ["null", "double", "string"]},
        {"name": "t", "type": ["null", "string"]},
    ],
}
PIPE_SCHEMA = {"x0": ft.Real, "x1": ft.Real, "t": ft.Text}
WANTED = ("x0", "x1", "t")


def _records(n, seed=0):
    r = np.random.RandomState(seed)
    out = []
    for i in range(n):
        out.append({
            "x0": None if i % 11 == 3 else float(r.randn()),
            "x1": float(r.randn()) * 100,
            "t": None if i % 7 == 5 else f"tok-{int(r.randint(50))}",
        })
    return out


def _write(path, records, codec="deflate", block_records=16):
    write_avro_records(str(path), SCHEMA, records, codec=codec,
                       block_records=block_records)
    return str(path)


def _sync_positions(path):
    """Byte offsets of every sync-marker occurrence (header's first)."""
    with open(path, "rb") as f:
        data = f.read()
    dec = _Decoder(data)
    assert dec.read(4) == MAGIC
    while True:
        n = dec.read_long()
        if n == 0:
            break
        for _ in range(abs(n)):
            dec.read_string()
            dec.read_bytes()
    sync = dec.read(16)
    positions, at = [], dec.pos - 16
    while at >= 0:
        positions.append(at)
        at = data.find(sync, at + 16)
    return positions, len(data)


def _flip_byte(path, offset):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)[0]
        f.seek(offset)
        f.write(bytes([b ^ 0xFF]))


def _truncate(path, size):
    with open(path, "r+b") as f:
        f.truncate(size)


# -- the PRE-STREAMING implementations, kept verbatim as parity oracles ------

def _oracle_read(path, errors="quarantine", quarantine=None):
    """The old whole-file read_avro_records (quarantine/coerce modes)."""
    with open(path, "rb") as f:
        data = f.read()
    dec = _Decoder(data)
    if dec.read(4) != MAGIC:
        raise ValueError(f"{path} is not an avro object container file")
    meta = {}
    while True:
        n = dec.read_long()
        if n == 0:
            break
        if n < 0:
            dec.read_long()
            n = -n
        for _ in range(n):
            key = dec.read_string()
            meta[key] = dec.read_bytes()
    sync = dec.read(16)
    schema = __import__("json").loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    records = []
    import struct as _struct
    while not dec.at_end():
        block_start = dec.pos
        n_before = len(records)
        try:
            count = dec.read_long()
            size = dec.read_long()
            block = dec.read(size)
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            bdec = _Decoder(block)
            for _ in range(count):
                records.append(_decode_value(bdec, schema))
            if dec.read(16) != sync:
                raise ValueError("bad sync marker (corrupt avro file)")
        except (EOFError, IndexError, ValueError, KeyError, zlib.error,
                _struct.error, UnicodeDecodeError) as e:
            if errors == "coerce":
                raise
            truncated = isinstance(e, (EOFError, IndexError, _struct.error))
            reason = "truncated_block" if truncated else "corrupt_block"
            del records[n_before:]
            nxt = data.find(sync, block_start)
            if nxt < 0:
                if quarantine is not None:
                    quarantine.add(
                        len(records), reason, None,
                        excerpt_of(f"{e}; no later sync marker - "
                                   f"{len(data) - block_start} trailing "
                                   "bytes undecodable"))
                break
            if quarantine is not None:
                quarantine.add(
                    len(records), reason, None,
                    excerpt_of(f"{e}; block dropped, resynced past "
                               f"{nxt + 16 - block_start} bytes"))
            dec.pos = nxt + 16
    return schema, records


def _oracle_chunks(records, chunk_rows, quarantine):
    """The old materialize-then-slice _iter_avro_chunks body (quarantine
    mode), operating on an already-decoded record list."""
    num_names = [n for n in WANTED if issubclass(PIPE_SCHEMA[n], ft.OPNumeric)]
    for start in range(0, len(records), chunk_rows):
        chunk = records[start:start + chunk_rows]
        keep = np.ones(len(chunk), bool)
        for i, r in enumerate(chunk):
            bad_reason = bad_col = bad_cell = None
            if not isinstance(r, dict):
                bad_reason, bad_cell = "malformed_record", r
            else:
                for n in num_names:
                    v = r.get(n)
                    if v is not None and coerce_numeric(v) is None:
                        bad_reason, bad_col, bad_cell = ("type_flip", n, v)
                        break
            if bad_reason is None:
                continue
            quarantine.add(start + i, bad_reason, bad_col,
                           excerpt_of(bad_cell))
            keep[i] = False
        if not keep.all():
            chunk = [r for r, k in zip(chunk, keep) if k]
        num = {}
        text = {}
        for n in WANTED:
            if n in num_names:
                vals = np.zeros(len(chunk))
                mask = np.zeros(len(chunk), bool)
                for i, r in enumerate(chunk):
                    v = r.get(n)
                    v = None if v is None else coerce_numeric(v)
                    if v is not None and v == v:
                        vals[i] = v
                        mask[i] = True
                num[n] = (vals, mask)
            else:
                out = np.empty(len(chunk), dtype=object)
                for i, r in enumerate(chunk):
                    v = r.get(n)
                    out[i] = None if v in (None, "") else str(v)
                text[n] = out
        yield CsvChunk(len(chunk), num, text, start)


def _buf_rows(buf):
    return [(r.row_index, r.reason, r.column, r.excerpt) for r in buf.rows]


def _assert_chunks_bit_identical(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert (g.n_rows, g.row_offset) == (w.n_rows, w.row_offset)
        assert set(g.numeric) == set(w.numeric)
        assert set(g.text) == set(w.text)
        for n in w.numeric:
            assert g.numeric[n][0].tobytes() == w.numeric[n][0].tobytes()
            assert g.numeric[n][1].tobytes() == w.numeric[n][1].tobytes()
        for n in w.text:
            assert list(g.text[n]) == list(w.text[n])


def _new_chunks(path, errors="quarantine"):
    buf = QuarantineBuffer(source=path)
    chunks = list(_iter_avro_chunks(
        path, PIPE_SCHEMA, WANTED, 10, errors, buf, None))
    return chunks, buf


# -- clean-file parity -------------------------------------------------------

@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_stream_matches_oracle_clean(tmp_path, codec):
    recs = _records(217, seed=3)
    p = _write(tmp_path / "clean.avro", recs, codec=codec)
    _, want = _oracle_read(p)
    stream = AvroBlockStream(p, errors="quarantine")
    got = [r for blk in stream.blocks() for r in blk]
    stream.close()
    assert got == want == recs
    assert stream.records_decoded == 217 and stream.damaged == 0
    # and through the public wrapper
    _, via_wrapper = read_avro_records(p, errors="quarantine",
                                       quarantine=QuarantineBuffer(source=p))
    assert via_wrapper == want


def test_chunks_bit_identical_clean(tmp_path):
    recs = _records(137, seed=4)
    p = _write(tmp_path / "clean.avro", recs)
    got, got_buf = _new_chunks(p)
    want_buf = QuarantineBuffer(source=p)
    want = list(_oracle_chunks(recs, 10, want_buf))
    _assert_chunks_bit_identical(got, want)
    assert got_buf.total == want_buf.total == 0


# -- damage parity: corrupt middle block, truncated tail ---------------------

def test_corrupt_middle_block_matches_oracle(tmp_path):
    recs = _records(160, seed=5)
    p = _write(tmp_path / "mid.avro", recs, block_records=32)
    syncs, _size = _sync_positions(p)
    assert len(syncs) >= 5  # header + >=4 block ends: damage mid-file
    # flip the SECOND block's trailing sync marker: raw deflate carries
    # no checksum, so a payload flip can corrupt silently - a marker
    # flip is a deterministic "bad sync marker" in both implementations.
    # Resync from the block head lands on the NEXT intact marker (the
    # third block's), so blocks 2 and 3 both roll back, no more.
    _flip_byte(p, syncs[2])
    want_buf = QuarantineBuffer(source=p)
    _, want = _oracle_read(p, quarantine=want_buf)
    got_buf = QuarantineBuffer(source=p)
    _, got = read_avro_records(p, errors="quarantine", quarantine=got_buf)
    assert got == want and len(got) == 160 - 64
    assert got_buf.total == want_buf.total == 1
    assert got_buf.by_reason == want_buf.by_reason == {"corrupt_block": 1}
    assert _buf_rows(got_buf) == _buf_rows(want_buf)
    assert "resynced past" in got_buf.rows[0].excerpt


def test_truncated_tail_matches_oracle(tmp_path):
    recs = _records(160, seed=6)
    p = _write(tmp_path / "tail.avro", recs, block_records=32)
    syncs, size = _sync_positions(p)
    _truncate(p, size - 21)  # mid final block: no later sync marker
    want_buf = QuarantineBuffer(source=p)
    _, want = _oracle_read(p, quarantine=want_buf)
    got_buf = QuarantineBuffer(source=p)
    _, got = read_avro_records(p, errors="quarantine", quarantine=got_buf)
    assert got == want and len(got) == 128
    assert got_buf.by_reason == want_buf.by_reason == {"truncated_block": 1}
    assert _buf_rows(got_buf) == _buf_rows(want_buf)
    assert "no later sync marker" in got_buf.rows[0].excerpt


def test_damaged_chunks_bit_identical_and_counts_pin(tmp_path):
    """The full satellite contract in one drill: a shard with BOTH a
    corrupt mid-file block and a type-flipped record chunks bit-identically
    to the old path, with equal quarantine accounting."""
    recs = _records(150, seed=7)
    recs[97]["x1"] = "definitely-not-a-number"
    p = _write(tmp_path / "both.avro", recs, block_records=25)
    syncs, _ = _sync_positions(p)
    _flip_byte(p, syncs[2])  # blocks 2+3 (records 25..74) roll back
    # oracle: old whole-file read, then old materialize-then-slice chunker
    want_buf = QuarantineBuffer(source=p)
    _, survivors = _oracle_read(p, quarantine=want_buf)
    want = list(_oracle_chunks(survivors, 10, want_buf))
    got, got_buf = _new_chunks(p)
    _assert_chunks_bit_identical(got, want)
    assert got_buf.total == want_buf.total == 2
    assert got_buf.by_reason == want_buf.by_reason == {
        "corrupt_block": 1, "type_flip": 1}
    assert _buf_rows(got_buf) == _buf_rows(want_buf)


def test_strict_mode_names_clean_record_index(tmp_path):
    recs = _records(96, seed=8)
    p = _write(tmp_path / "strict.avro", recs, block_records=32)
    syncs, _ = _sync_positions(p)
    _flip_byte(p, syncs[2])  # block 2's marker: 64 clean records first
    with pytest.raises(MalformedRowError) as exc:
        read_avro_records(p, errors="strict")
    assert exc.value.row_index == 64
    # coerce keeps legacy behavior: the raw error propagates
    with pytest.raises((EOFError, ValueError, zlib.error)):
        read_avro_records(p, errors="coerce")


# -- memory boundedness + pipeline integration -------------------------------

def test_window_stays_bounded(tmp_path):
    """The read-ahead window between blocks must hold ~one block, not
    the file: with 200 blocks the high-water mark stays a small
    fraction of the file size (the whole point of the streaming path)."""
    recs = [{"x0": float(i), "x1": float(i) * 2.0, "t": "pad" * 40}
            for i in range(3_200)]
    p = _write(tmp_path / "big.avro", recs, codec="null", block_records=16)
    size = __import__("os").path.getsize(p)
    stream = AvroBlockStream(p, errors="quarantine", read_bytes=1 << 12)
    high = 0
    for _ in stream.blocks():
        high = max(high, len(stream._win.buf))
    stream.close()
    assert stream.records_decoded == 3_200
    assert high < size // 10, (high, size)


def test_pipeline_avro_shard_streams_with_damage(tmp_path):
    """End to end through InputPipeline: a damaged avro shard still
    lands exact quarantine counts and the same kept rows as the serial
    oracle (the route bulk scoring rides)."""
    recs = _records(180, seed=9)
    p = _write(tmp_path / "pipe.avro", recs, block_records=30)
    syncs, _ = _sync_positions(p)
    _flip_byte(p, syncs[3])  # blocks 3+4 (records 60..119) roll back
    want_buf = QuarantineBuffer(source=p)
    _, survivors = _oracle_read(p, quarantine=want_buf)
    pipe = InputPipeline(shard([p]), PIPE_SCHEMA, wanted=WANTED, workers=1,
                         chunk_rows=16, errors="quarantine")
    kept = sum(pc.payload.n_rows for pc in pipe.chunks())
    merged = pipe.merged_quarantine()
    assert kept == len(survivors) == 120
    assert merged.total == want_buf.total == 1
    assert merged.by_reason == {"corrupt_block": 1}
