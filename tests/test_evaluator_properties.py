"""Property tests for the evaluator stack (reference test strategy,
SURVEY §4: the reference pins its metric implementations with exhaustive
identity checks - same style over random predictions here).

Invariants exercised per random seed:
- AuROC is invariant under strictly monotone score transforms and flips
  to 1-AuROC under score negation; perfect/anti-perfect/constant scores
  hit their closed-form values
- AuROC equals the Mann-Whitney U statistic (pair-counting definition,
  ties at half weight) on small samples
- confusion-matrix identities: TP+FN = positives, TN+FP = negatives,
  Error = (FP+FN)/n, F1 harmonic identity
- threshold curves: recall_by_threshold non-increasing in the threshold;
  endpoints recall(0)=1, and the curve lengths match num_thresholds+1
- the device-approximate masked rank metrics agree with the exact host
  AuROC/AuPR within histogram resolution
- multiclass: per-row probability rows sum to 1 -> top-1 threshold-0
  point equals plain accuracy; regression: RMSE/MAE/R2 identities
"""
import numpy as np
import pytest

from transmogrifai_tpu.evaluators.binary import (
    OpBinaryClassificationEvaluator,
    _roc_pr_areas,
    masked_rank_metrics,
)
from transmogrifai_tpu.evaluators.multiclass import (
    OpMultiClassificationEvaluator,
)
from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
from transmogrifai_tpu.types.columns import PredictionColumn


def _random_binary(rng, n=400):
    y = (rng.random(n) > 0.4).astype(np.float64)
    score = np.clip(
        0.3 * y + 0.5 + 0.25 * rng.standard_normal(n), 0.0, 1.0
    )
    return y, score


@pytest.mark.parametrize("seed", range(8))
def test_auroc_monotone_invariance_and_negation(seed):
    rng = np.random.default_rng(seed)
    y, score = _random_binary(rng)
    base, _ = _roc_pr_areas(y, score)
    for transform in (
        lambda s: 2.0 * s + 1.0,
        lambda s: np.exp(s),
        lambda s: s**3 + s,  # strictly increasing on [0, 1]
    ):
        got, _ = _roc_pr_areas(y, transform(score))
        assert abs(got - base) < 1e-12
    neg, _ = _roc_pr_areas(y, -score)
    assert abs((base + neg) - 1.0) < 1e-9


@pytest.mark.parametrize("seed", range(8))
def test_auroc_equals_pair_counting(seed):
    rng = np.random.default_rng(100 + seed)
    n = 60
    y = (rng.random(n) > 0.5).astype(np.float64)
    if y.sum() in (0, n):
        y[0] = 1.0 - y[0]
    score = np.round(rng.random(n), 2)  # coarse grid -> real ties
    auroc, _ = _roc_pr_areas(y, score)
    pos = score[y == 1]
    neg = score[y == 0]
    wins = (pos[:, None] > neg[None, :]).sum()
    ties = (pos[:, None] == neg[None, :]).sum()
    u = (wins + 0.5 * ties) / (len(pos) * len(neg))
    assert abs(auroc - u) < 1e-9, f"seed {seed}: {auroc} vs U {u}"


def test_auroc_closed_forms():
    y = np.array([0, 0, 1, 1], dtype=np.float64)
    assert _roc_pr_areas(y, np.array([0.1, 0.2, 0.8, 0.9]))[0] == 1.0
    assert _roc_pr_areas(y, np.array([0.9, 0.8, 0.2, 0.1]))[0] == 0.0
    auroc, _ = _roc_pr_areas(y, np.full(4, 0.5))
    assert abs(auroc - 0.5) < 1e-12  # all-tied = chance
    assert _roc_pr_areas(np.zeros(4), np.linspace(0, 1, 4)) == (0.0, 0.0)


@pytest.mark.parametrize("seed", range(6))
def test_confusion_identities_and_threshold_curves(seed):
    rng = np.random.default_rng(200 + seed)
    y, score = _random_binary(rng)
    pred = PredictionColumn(
        (score > 0.5).astype(np.float64),
        np.stack([-score, score], axis=1),
        np.stack([1 - score, score], axis=1),
    )
    ev = OpBinaryClassificationEvaluator()
    m = ev.evaluate_arrays(y, pred)
    n = len(y)
    assert m.TP + m.FN == y.sum()
    assert m.TN + m.FP == n - y.sum()
    assert abs(m.Error - (m.FP + m.FN) / n) < 1e-12
    if m.Precision + m.Recall > 0:
        f1 = 2 * m.Precision * m.Recall / (m.Precision + m.Recall)
        assert abs(m.F1 - f1) < 1e-12
    rec = m.recall_by_threshold
    assert len(rec) == ev.num_thresholds + 1
    assert len(m.precision_by_threshold) == ev.num_thresholds + 1
    assert abs(rec[0] - 1.0) < 1e-12  # threshold 0 catches everything
    assert all(a >= b - 1e-12 for a, b in zip(rec, rec[1:]))  # monotone


@pytest.mark.parametrize("seed", range(4))
def test_device_rank_metrics_match_host(seed):
    rng = np.random.default_rng(300 + seed)
    y, score = _random_binary(rng, n=2000)
    exact_auroc, exact_aupr = _roc_pr_areas(y, score)
    # one replica, full validation mask
    auroc_b, aupr_b = masked_rank_metrics(
        score[None, :], y, np.ones((1, len(y))))
    assert abs(float(auroc_b[0]) - exact_auroc) < 5e-3
    assert abs(float(aupr_b[0]) - exact_aupr) < 2e-2


@pytest.mark.parametrize("seed", range(4))
def test_multiclass_topk_and_accuracy(seed):
    rng = np.random.default_rng(400 + seed)
    n, k = 300, 4
    y = rng.integers(0, k, n).astype(np.float64)
    logits = rng.standard_normal((n, k)) + 1.5 * np.eye(k)[y.astype(int)]
    prob = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
    pred = PredictionColumn(prob.argmax(axis=1).astype(np.float64),
                            logits, prob)
    m = OpMultiClassificationEvaluator().evaluate_arrays(y, pred)
    acc = (prob.argmax(axis=1) == y).mean()
    assert abs(m.F1 - m.F1) == 0.0  # finite
    assert abs(m.Error - (1.0 - acc)) < 1e-12
    tm = m.threshold_metrics
    # top-1 at threshold 0 == plain accuracy; top-k correct rates are
    # non-decreasing in k at every threshold
    top1 = tm["correct_counts"]["1"][0] / max(n, 1)
    assert abs(top1 - acc) < 1e-12
    for t_idx in range(0, len(tm["thresholds"]), 25):
        counts = [tm["correct_counts"][str(topn)][t_idx]
                  for topn in sorted(int(s) for s in tm["correct_counts"])]
        assert counts == sorted(counts)


@pytest.mark.parametrize("seed", range(4))
def test_regression_metric_identities(seed):
    rng = np.random.default_rng(500 + seed)
    n = 250
    y = rng.standard_normal(n) * 3 + 1
    yhat = y + 0.5 * rng.standard_normal(n)
    m = OpRegressionEvaluator().evaluate_arrays(
        y, PredictionColumn(yhat))
    err = y - yhat
    assert abs(m.RootMeanSquaredError - np.sqrt((err**2).mean())) < 1e-9
    assert abs(m.MeanAbsoluteError - np.abs(err).mean()) < 1e-9
    ss_res = (err**2).sum()
    ss_tot = ((y - y.mean()) ** 2).sum()
    assert abs(m.R2 - (1 - ss_res / ss_tot)) < 1e-9
