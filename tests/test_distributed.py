"""Multi-host runtime helpers on the virtual 8-device CPU mesh
(reference: Spark's executor substrate, SURVEY §5.8; local[2]-style test
strategy per TestSparkContext.scala:33-76)."""
import numpy as np
import pytest

from transmogrifai_tpu.parallel import distributed as dist


def test_global_mesh_and_all_reduce():
    mesh = dist.global_mesh(("data",))
    assert mesh.devices.size >= 1
    X = np.arange(64, dtype=np.float32).reshape(16, 4)

    def moments(x):
        return x.sum(axis=0), (x * x).sum(axis=0)

    s, ss = dist.all_reduce_stats(moments, mesh, X)
    np.testing.assert_allclose(np.asarray(s), X.sum(0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ss), (X * X).sum(0), rtol=1e-6)


def test_host_local_to_global_single_process():
    mesh = dist.global_mesh(("data",))
    n = mesh.devices.size * 3
    X = np.random.RandomState(0).randn(n, 5).astype(np.float32)
    g = dist.host_local_to_global(X, mesh)
    assert g.shape == (n, 5)
    np.testing.assert_allclose(np.asarray(g), X, rtol=1e-6)


def test_initialize_noop_single_process():
    dist.initialize()  # must not raise or block on single-process setups
    assert dist._initialized is False  # a no-op must not latch


def test_all_reduce_rejects_mismatched_leading_axes():
    """ISSUE 3 satellite: shape disagreement fails up front with the
    offending array NAMED, not as an XLA error from inside jax.jit."""
    mesh = dist.global_mesh(("data",))
    n = mesh.devices.size * 2
    X = np.zeros((n, 3), np.float32)
    y = np.zeros((n + 1,), np.float32)
    with pytest.raises(dist.MeshShapeError, match=r"array 1 has"):
        dist.all_reduce_stats(lambda a, b: (a.sum(), b.sum()), mesh, X, y)


def test_all_reduce_rejects_indivisible_rows():
    mesh = dist.global_mesh(("data",))
    X = np.zeros((mesh.devices.size * 2 + 1, 3), np.float32)
    with pytest.raises(dist.MeshShapeError,
                       match=r"not divisible by mesh axis 'data'"):
        dist.all_reduce_stats(lambda a: a.sum(), mesh, X)


def test_all_reduce_rejects_scalar_and_bad_axis():
    mesh = dist.global_mesh(("data",))
    with pytest.raises(dist.MeshShapeError, match="0-d"):
        dist.all_reduce_stats(lambda a: a, mesh, np.float32(3.0))
    X = np.zeros((mesh.devices.size, 2), np.float32)
    with pytest.raises(dist.MeshShapeError, match="no axis 'rows'"):
        dist.all_reduce_stats(lambda a: a.sum(), mesh, X, axis="rows")


def test_host_local_to_global_rejects_indivisible_local_rows():
    mesh = dist.global_mesh(("data",))
    bad = np.zeros((mesh.devices.size + 1, 4), np.float32)
    with pytest.raises(dist.MeshShapeError, match="local_rows"):
        dist.host_local_to_global(bad, mesh)
    with pytest.raises(dist.MeshShapeError, match="0-d"):
        dist.host_local_to_global(np.float32(1.0), mesh)
