"""Multi-host runtime helpers on the virtual 8-device CPU mesh
(reference: Spark's executor substrate, SURVEY §5.8; local[2]-style test
strategy per TestSparkContext.scala:33-76)."""
import numpy as np

from transmogrifai_tpu.parallel import distributed as dist


def test_global_mesh_and_all_reduce():
    mesh = dist.global_mesh(("data",))
    assert mesh.devices.size >= 1
    X = np.arange(64, dtype=np.float32).reshape(16, 4)

    def moments(x):
        return x.sum(axis=0), (x * x).sum(axis=0)

    s, ss = dist.all_reduce_stats(moments, mesh, X)
    np.testing.assert_allclose(np.asarray(s), X.sum(0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ss), (X * X).sum(0), rtol=1e-6)


def test_host_local_to_global_single_process():
    mesh = dist.global_mesh(("data",))
    n = mesh.devices.size * 3
    X = np.random.RandomState(0).randn(n, 5).astype(np.float32)
    g = dist.host_local_to_global(X, mesh)
    assert g.shape == (n, 5)
    np.testing.assert_allclose(np.asarray(g), X, rtol=1e-6)


def test_initialize_noop_single_process():
    dist.initialize()  # must not raise or block on single-process setups
