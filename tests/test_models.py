"""Model-level tests on synthetic data (mirrors the reference's
classification/regression model specs, reference: core/src/test/.../impl/
classification + regression)."""
import numpy as np
import pytest

from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.linear_svc import OpLinearSVC
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
from transmogrifai_tpu.models.trees import (
    OpDecisionTreeClassifier,
    OpGBTClassifier,
    OpGBTRegressor,
    OpRandomForestClassifier,
    OpRandomForestRegressor,
)


@pytest.fixture
def binary_data(rng):
    n, d = 600, 8
    X = rng.randn(n, d)
    beta = 2.0 * np.array([2.0, -1.5, 1.0, 0.0, 0.0, 0.5, -0.5, 0.0])
    p = 1 / (1 + np.exp(-(X @ beta - 0.3)))
    y = (rng.rand(n) < p).astype(np.float64)
    return X, y


@pytest.fixture
def regression_data(rng):
    n, d = 500, 6
    X = rng.randn(n, d)
    beta = np.array([1.0, 2.0, 0.0, -1.0, 0.5, 0.0])
    y = X @ beta + 0.7 + 0.1 * rng.randn(n)
    return X, y


def _acc(est, X, y):
    params = est.fit_arrays(X, y)
    pred, raw, prob = est.predict_arrays(params, X)
    return float((pred == y).mean()), prob


def test_logistic_regression_learns(binary_data):
    X, y = binary_data
    acc, prob = _acc(OpLogisticRegression(reg_param=0.01), X, y)
    assert acc > 0.85
    assert prob.shape == (len(y), 2)
    assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)


def test_logistic_regression_batched_matches_single(binary_data):
    X, y = binary_data
    est = OpLogisticRegression()
    W = np.ones((3, len(y)))
    regs = np.array([0.001, 0.01, 0.1])
    ens = np.zeros(3)
    betas, b0s = est.fit_arrays_batched(X, y, W, regs, ens)
    est_single = OpLogisticRegression(reg_param=0.01)
    single = est_single.fit_arrays(X, y)
    assert np.allclose(betas[1], single["beta"], atol=1e-3)


def test_logistic_regression_sample_weights(binary_data):
    X, y = binary_data
    est = OpLogisticRegression(reg_param=0.01)
    w = np.zeros(len(y))
    w[:300] = 1.0
    params_w = est.fit_arrays(X, y, w)
    params_sub = est.fit_arrays(X[:300], y[:300])
    assert np.allclose(params_w["beta"], params_sub["beta"], atol=1e-4)


def test_linear_svc(binary_data):
    X, y = binary_data
    acc, _ = _acc(OpLinearSVC(reg_param=0.01), X, y)
    assert acc > 0.85


def test_naive_bayes(binary_data):
    X, y = binary_data
    acc, prob = _acc(OpNaiveBayes(), X, y)
    assert acc > 0.70
    assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)


def test_random_forest_classifier(binary_data):
    X, y = binary_data
    est = OpRandomForestClassifier(num_trees=20, max_depth=5)
    acc, prob = _acc(est, X, y)
    assert acc > 0.85
    assert prob.shape == (len(y), 2)


def test_decision_tree_classifier(binary_data):
    X, y = binary_data
    acc, _ = _acc(OpDecisionTreeClassifier(max_depth=5), X, y)
    assert acc > 0.80


def test_gbt_classifier(binary_data):
    X, y = binary_data
    acc, _ = _acc(OpGBTClassifier(num_trees=20, max_depth=3), X, y)
    assert acc > 0.88


def test_linear_regression(regression_data):
    X, y = regression_data
    est = OpLinearRegression(reg_param=0.001)
    params = est.fit_arrays(X, y)
    pred, _, _ = est.predict_arrays(params, X)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.2
    assert abs(params["intercept"] - 0.7) < 0.1


def test_random_forest_regressor(regression_data):
    X, y = regression_data
    est = OpRandomForestRegressor(num_trees=20, max_depth=6)
    params = est.fit_arrays(X, y)
    pred, _, _ = est.predict_arrays(params, X)
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.7


def test_gbt_regressor(regression_data):
    X, y = regression_data
    est = OpGBTRegressor(num_trees=30, max_depth=4)
    params = est.fit_arrays(X, y)
    pred, _, _ = est.predict_arrays(params, X)
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.8


def test_impurity_importances_concentrate_on_signal(rng):
    """Impurity-decrease importances (heap-recovered, Spark
    featureImportances contract) must rank the informative feature first
    and sum to 1; a pure-noise feature must score near zero."""
    n = 800
    X = rng.randn(n, 5)
    y = (X[:, 2] > 0).astype(np.float64)  # only feature 2 matters
    for est in (
        OpRandomForestClassifier(num_trees=10, max_depth=4, backend="jax"),
        OpGBTClassifier(num_trees=5, max_depth=3, backend="jax"),
    ):
        params = est.fit_arrays(X, y)
        imp = est.contributions(params)
        assert imp.shape == (5,)
        assert abs(imp.sum() - 1.0) < 1e-6
        assert int(np.argmax(imp)) == 2
        assert imp[2] > 0.5


def test_impurity_importances_backend_parity(rng):
    """Native C++ and JAX heaps must yield identical importances (same
    flat-heap layout feeds the same post-hoc recovery)."""
    from transmogrifai_tpu.models import native_trees

    if not native_trees.available():
        pytest.skip("native lib unavailable")
    n = 400
    X = rng.randn(n, 6)
    y = ((X[:, 1] + 0.5 * X[:, 4]) > 0).astype(np.float64)
    # "all" features per node: per-node random subsets draw from different
    # RNG streams per backend, so trees (hence importances) only match
    # when the subset sampling is off
    kw = dict(num_trees=5, max_depth=4, seed=7, feature_subset_strategy="all")
    p_jax = OpRandomForestClassifier(backend="jax", **kw).fit_arrays(X, y)
    p_nat = OpRandomForestClassifier(backend="native", **kw).fit_arrays(X, y)
    i_jax = OpRandomForestClassifier(backend="jax", **kw).contributions(p_jax)
    i_nat = OpRandomForestClassifier(backend="native", **kw).contributions(p_nat)
    np.testing.assert_allclose(i_jax, i_nat, rtol=1e-4, atol=1e-5)


def test_impurity_importances_ignore_shadow_splits():
    """An internal-marked node beneath a leaf (shadow child inheriting the
    parent's rows) is unreachable by prediction and must contribute zero
    importance."""
    from transmogrifai_tpu.models.tree_kernel import heap_impurity_importances

    M = 7  # depth-2 heap
    hf = np.zeros((1, M), np.int32)
    ht = np.full((1, M), 32, np.int32)
    hl = np.ones((1, M), bool)
    hv = np.zeros((1, M, 3), np.float32)
    # root is a LEAF; its shadow left child (node 1) is marked internal
    # with a genuine-looking gini decrease on feature 1
    hf[0, 1] = 1
    hl[0, 1] = False
    hv[0, 0] = [100.0, 50.0, 50.0]   # root: impure
    hv[0, 1] = [100.0, 50.0, 50.0]   # shadow child inherits parent stats
    hv[0, 3] = [50.0, 50.0, 0.0]     # its "children" look pure
    hv[0, 4] = [50.0, 0.0, 50.0]
    imp = heap_impurity_importances((hf, ht, hl, hv), 4, "gini")
    assert imp.sum() == 0.0  # nothing reachable splits -> no importance


def test_grid_batched_forest_matches_per_config(rng):
    """fit_arrays_folds_grid (one dispatch per static-shape group) must
    produce EXACTLY the trees the per-config fit_arrays_folds path grows -
    same seeds, same traced min_* scalars, just batched."""
    n, d = 300, 6
    X = rng.randn(n, d)
    y = ((X[:, 0] + X[:, 3]) > 0).astype(np.float64)
    W = np.stack([np.r_[np.ones(200), np.zeros(100)],
                  np.r_[np.zeros(100), np.ones(200)]])
    grid = [
        {"max_depth": 4, "min_info_gain": 0.0, "min_instances_per_node": 1},
        {"max_depth": 4, "min_info_gain": 0.05, "min_instances_per_node": 5},
        {"max_depth": 3, "min_info_gain": 0.0, "min_instances_per_node": 1},
    ]
    est = OpRandomForestClassifier(num_trees=4, backend="jax")
    batched = est.fit_arrays_folds_grid(X, y, W, grid)
    assert batched is not None and len(batched) == 3
    for j, pmap in enumerate(grid):
        cand = est.with_params(**pmap)
        single = cand.fit_arrays_folds(X, y, W)
        for f in range(2):
            for hb, hs in zip(batched[j][f]["heaps"], single[f]["heaps"]):
                np.testing.assert_array_equal(np.asarray(hb), np.asarray(hs))
            pb = batched[j][f]
            ps = single[f]
            predb = cand.predict_arrays(pb, X)[0]
            preds = cand.predict_arrays(ps, X)[0]
            np.testing.assert_array_equal(predb, preds)


def test_watchdog_chunked_dispatch_parity(rng, monkeypatch):
    """The host chunking that keeps each device program under the runtime
    watchdog (tree_kernel.fits_per_dispatch; the tunneled TPU runtime
    kills ~2-minute programs) must be bit-identical to one big dispatch:
    trees/grid points/folds are independent, and GBT chunks carry the
    boosting margin."""
    n, d = 240, 5
    X = rng.randn(n, d)
    y = ((X[:, 0] - X[:, 2]) > 0).astype(np.float64)
    W = np.stack([np.r_[np.ones(160), np.zeros(80)],
                  np.r_[np.zeros(80), np.ones(160)]]).astype(np.float32)
    grid = [
        {"min_info_gain": 0.0, "min_instances_per_node": 1},
        {"min_info_gain": 0.02, "min_instances_per_node": 4},
        {"min_info_gain": 0.1, "min_instances_per_node": 1},
    ]

    def run_all():
        rf = OpRandomForestClassifier(num_trees=5, max_depth=4, backend="jax")
        rf_grid = rf.fit_arrays_folds_grid(X, y, W, grid)
        rf_single = rf.fit_arrays(X, y)
        gbt = OpGBTClassifier(num_trees=6, max_depth=3, backend="jax")
        gbt_grid = gbt.fit_arrays_folds_grid(X, y, W, grid)
        gbt_single = gbt.fit_arrays(X, y)
        return rf_grid, rf_single, gbt_grid, gbt_single

    monkeypatch.setenv("TX_TREE_FITS_PER_DISPATCH", "100000")
    big = run_all()
    monkeypatch.setenv("TX_TREE_FITS_PER_DISPATCH", "3")
    small = run_all()

    for b, s in zip(big, small):
        if isinstance(b, dict):  # single-fit params
            for hb, hs in zip(b["heaps"], s["heaps"]):
                np.testing.assert_array_equal(np.asarray(hb), np.asarray(hs))
            if "f0" in b:
                assert b["f0"] == pytest.approx(s["f0"], abs=1e-7)
        else:  # grid results: [G][F] param dicts
            for cb, cs in zip(b, s):
                for fb, fs in zip(cb, cs):
                    for hb, hs in zip(fb["heaps"], fs["heaps"]):
                        np.testing.assert_array_equal(
                            np.asarray(hb), np.asarray(hs))


def test_row_chunked_histogram_parity(rng, monkeypatch):
    """The row-chunked level-histogram accumulation (tree_kernel._level_hist
    - avoids the [n, d, C] scatter broadcast that OOMs at 10M rows) must
    match the one-shot scatter: bit-identical on the classifier path
    (gini counts are exact integers), and identical tree STRUCTURE with
    summation-order-tolerant leaf stats on the variance (regression)
    path, whose wy/wyy float channels accumulate per block.
    The cap env var is read at trace time, hence the clear_caches."""
    import jax

    n, d = 501, 7  # deliberately non-round: exercises the padded tail
    X = rng.randn(n, d)
    y_cls = ((X[:, 1] + X[:, 4]) > 0).astype(np.float64)
    y_reg = (2.0 * X[:, 1] - X[:, 4] + 0.05 * rng.randn(n))

    def fit_cls():
        est = OpRandomForestClassifier(num_trees=3, max_depth=4,
                                       backend="jax")
        return est.fit_arrays(X, y_cls)

    def fit_reg():
        est = OpRandomForestRegressor(num_trees=3, max_depth=4,
                                      backend="jax")
        return est.fit_arrays(X, y_reg)

    big_c, big_r = fit_cls(), fit_reg()
    # force chunking (block of ~6 rows); fresh traces so the env is seen
    monkeypatch.setenv("TX_TREE_HIST_SCATTER_ELEMS", "128")
    jax.clear_caches()
    small_c, small_r = fit_cls(), fit_reg()
    monkeypatch.delenv("TX_TREE_HIST_SCATTER_ELEMS")
    jax.clear_caches()
    for hb, hs in zip(big_c["heaps"], small_c["heaps"]):
        np.testing.assert_array_equal(np.asarray(hb), np.asarray(hs))
    hf_b, ht_b, hl_b, hv_b = (np.asarray(h) for h in big_r["heaps"])
    hf_s, ht_s, hl_s, hv_s = (np.asarray(h) for h in small_r["heaps"])
    np.testing.assert_array_equal(hf_b, hf_s)
    np.testing.assert_array_equal(ht_b, ht_s)
    np.testing.assert_array_equal(hl_b, hl_s)
    np.testing.assert_allclose(hv_b, hv_s, rtol=1e-5, atol=1e-4)


def test_bf16_hessian_same_fixed_point(rng, monkeypatch):
    """The TPU-mode bf16 Hessian Gram (TX_LR_HESSIAN_BF16) must converge
    to the SAME optimum as the f32 path: the gradient stays f32, so
    approximate curvature changes the Newton path, not the fixed point."""
    import jax

    X = rng.randn(400, 8)
    beta_t = rng.randn(8)
    y = (X @ beta_t + 0.5 * rng.randn(400) > 0).astype(float)

    def fit(cls, **kw):
        return cls(**kw).fit_arrays(X, y)

    monkeypatch.setenv("TX_LR_HESSIAN_BF16", "1")
    jax.clear_caches()
    lr_b = fit(OpLogisticRegression, reg_param=0.01, max_iter=30)
    svc_b = fit(OpLinearSVC, reg_param=0.01, max_iter=30)
    monkeypatch.setenv("TX_LR_HESSIAN_BF16", "0")
    jax.clear_caches()
    lr_f = fit(OpLogisticRegression, reg_param=0.01, max_iter=30)
    svc_f = fit(OpLinearSVC, reg_param=0.01, max_iter=30)
    monkeypatch.delenv("TX_LR_HESSIAN_BF16")
    jax.clear_caches()
    for b, f in ((lr_b, lr_f), (svc_b, svc_f)):
        err = np.max(np.abs(b["beta"] - f["beta"])
                     / (np.abs(f["beta"]) + 1e-3))
        assert err < 5e-3, err


def test_fits_per_dispatch_work_model(monkeypatch):
    """The watchdog work model must shrink the per-program fit budget as
    trees get more expensive (deeper, wider, more rows) and respect the
    env overrides."""
    from transmogrifai_tpu.models.tree_kernel import fits_per_dispatch

    base = fits_per_dispatch(6, 10_000, 30, 32, 3)
    assert base >= 1
    assert fits_per_dispatch(12, 10_000, 30, 32, 3) < base      # deeper
    assert fits_per_dispatch(6, 10_000_000, 30, 32, 3) < base   # more rows
    assert fits_per_dispatch(6, 10_000, 500, 32, 3) < base      # wider
    monkeypatch.setenv("TX_TREE_FITS_PER_DISPATCH", "7")
    assert fits_per_dispatch(12, 10_000_000, 500, 32, 3) == 7
    monkeypatch.delenv("TX_TREE_FITS_PER_DISPATCH")
    monkeypatch.setenv("TX_TREE_DISPATCH_BUDGET_S", "60")
    doubled = fits_per_dispatch(6, 10_000, 30, 32, 3)
    assert abs(doubled - 2 * base) <= 2  # int truncation slack


def test_bench_scale_dispatch_plan_stays_under_watchdog():
    """BASELINE config-5 shapes (10M x 39, 64 bins): the r3 on-chip
    capture died with synth_rf_error because a dispatch outlived the
    ~2-minute runtime watchdog.  Pin the work-model plan at exactly the
    bench's RF (depth<=6, gini C=3) and GBT (depth<=4, C=4) shapes: one
    fit must never threaten the kill, and a full dispatch must stay at
    the ~30 s budget."""
    from transmogrifai_tpu.models.tree_kernel import (
        _tree_fit_work,
        fits_per_dispatch,
    )

    rate, watchdog_s = 2.0e9, 120.0
    for depth, n_stats in ((6, 3), (4, 4)):
        per_fit_s = _tree_fit_work(depth, 10_000_000, 39, 64, n_stats) / rate
        assert per_fit_s < watchdog_s / 3, per_fit_s
        k = fits_per_dispatch(depth, 10_000_000, 39, 64, n_stats)
        assert k >= 1
        assert k * per_fit_s <= 45.0, (k, per_fit_s)


def test_logistic_regression_multiclass_families():
    """>2 classes: family='auto' routes through the multinomial softmax
    Newton (reference OpLogisticRegression.scala:110-116 auto semantics -
    jointly normalized probabilities by construction); family='ovr' keeps
    the one-vs-rest route.  Both must recover a separable 3-class
    problem."""
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )

    rng = np.random.RandomState(4)
    n = 600
    centers = np.array([[2.0, 0.0], [-2.0, 1.5], [0.0, -2.5]])
    y = np.repeat(np.arange(3.0), n // 3)
    X = centers[y.astype(int)] + 0.5 * rng.randn(n, 2)
    for family, expect in (("auto", "multinomial"), ("ovr", "ovr"),
                           ("multinomial", "multinomial")):
        est = OpLogisticRegression(reg_param=0.01, max_iter=25,
                                   family=family)
        params = est.fit_arrays(X, y)
        assert set(params) >= {"betas", "intercepts", "classes"}
        assert params["family"] == expect, (family, params["family"])
        pred, raw, prob = est.predict_arrays(params, X)
        assert (pred == y).mean() > 0.97, family
        np.testing.assert_allclose(prob.sum(axis=1), 1.0, atol=1e-9)
        # engine-free path identical
        pred2, _, prob2 = est.predict_arrays_np(params, X)
        np.testing.assert_array_equal(pred, pred2)
        np.testing.assert_allclose(prob, prob2, atol=1e-12)
        assert est.contributions(params).shape == (2,)


def test_multinomial_softmax_matches_independent_reference():
    """The softmax Newton must land on the SAME penalized optimum as an
    independent scipy L-BFGS minimization of the multinomial NLL (same
    standardized-space objective): probability parity to f32 noise, and
    the constant column excluded with coefficient pinned to 0."""
    from scipy.optimize import minimize

    from transmogrifai_tpu.models.logistic_regression import (
        _softmax_fit_kernel,
    )
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    n, d, K = 600, 7, 4
    X = rng.randn(n, d).astype(np.float32)
    X[:, 2] = X[:, 2] * 30 + 100  # ill-conditioned scale/offset
    X[:, 5] = 3.0  # constant column
    Xz = (X - X.mean(0)) / np.where(X.std(0) > 0, X.std(0), 1.0)
    Bt = rng.randn(K, d) * 1.5
    z = Xz @ Bt.T
    P = np.exp(z - z.max(1, keepdims=True))
    P /= P.sum(1, keepdims=True)
    y = np.array([rng.choice(K, p=pp) for pp in P])
    w = (rng.rand(n) + 0.5).astype(np.float32)
    Yoh = np.zeros((n, K), np.float32)
    Yoh[np.arange(n), y] = 1.0
    reg = 0.05

    betas, b0 = _softmax_fit_kernel(
        jnp.asarray(X), jnp.asarray(Yoh), jnp.asarray(w),
        jnp.asarray(reg), jnp.asarray(0.0), iters=30,
    )
    betas = np.asarray(betas, np.float64)
    b0 = np.asarray(b0, np.float64)
    assert np.abs(betas[:, 5]).max() == 0.0  # excluded column pinned

    wsum = w.sum()
    mu = (w @ X) / wsum
    msq = (w @ (X * X)) / wsum
    var = msq - mu**2
    active = var > 1e-6 * msq + 1e-30
    sd = np.where(active, np.sqrt(np.maximum(var, 1e-12)), 1.0)
    Xs = (X - mu) / sd * active

    def nll(theta):
        B = theta[: K * d].reshape(K, d)
        zz = Xs @ B.T + theta[K * d:]
        zz = zz - zz.max(axis=1, keepdims=True)
        logp = zz - np.log(np.exp(zz).sum(axis=1, keepdims=True))
        return (
            -(w * logp[np.arange(n), y]).sum() / wsum
            + 0.5 * reg * (B**2).sum()
        )

    res = minimize(nll, np.zeros(K * d + K), method="L-BFGS-B",
                   options={"maxiter": 5000, "ftol": 1e-15, "gtol": 1e-11})
    beta_ref = res.x[: K * d].reshape(K, d) * active / sd
    b0_ref = res.x[K * d:] - beta_ref @ mu
    z1 = X @ betas.T + b0
    z2 = X @ beta_ref.T + b0_ref
    p1 = np.exp(z1 - z1.max(1, keepdims=True))
    p1 /= p1.sum(1, keepdims=True)
    p2 = np.exp(z2 - z2.max(1, keepdims=True))
    p2 /= p2.sum(1, keepdims=True)
    assert np.abs(p1 - p2).max() < 2e-3
    theta_newton = np.concatenate(
        [(betas * sd).reshape(K * d), b0 + betas @ mu]
    )
    assert nll(theta_newton) <= res.fun + 1e-6  # same penalized optimum


def test_multiclass_selector_default_includes_working_lr():
    """The default multiclass model set fields OpLogisticRegression
    (reference MultiClassificationModelSelector Defaults: LR + RF); its
    candidates must produce REAL metrics, not sigmoid-on-{0,1,2} garbage
    riding the binary batched path (pinned via the _binary_labels guard)."""
    from transmogrifai_tpu.evaluators.multiclass import (
        OpMultiClassificationEvaluator,
    )
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.selector.factories import lr_grid
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    rng = np.random.RandomState(11)
    n = 450
    centers = np.array([[2.5, 0.0], [-2.5, 1.0], [0.0, -3.0]])
    y = np.repeat(np.arange(3.0), n // 3)
    X = (centers[y.astype(int)] + 0.6 * rng.randn(n, 2)).astype(np.float64)
    cv = OpCrossValidation(
        num_folds=3, evaluator=OpMultiClassificationEvaluator(),
        stratify=True, seed=0,
    )
    res = cv.validate([(OpLogisticRegression(max_iter=15), lr_grid())], X, y)
    assert res.best_metric > 0.9, res.best_metric  # F1 on separable data


def test_linear_kernels_survive_high_mean_low_variance_columns():
    """f32 conditioning regression (round-4): columns whose |mean| >> std
    made the folded centered-Gram identity cancel catastrophically - the
    standardized Hessian went indefinite and the Newton solve NaN'd
    (found driving a softmax language-score map with 2 distinct rows
    through LR).  All linear kernels now pre-center globally and exclude
    near-constant-under-weights columns like Spark's std==0 handling."""
    import jax.numpy as jnp

    from transmogrifai_tpu.models.linear_regression import OpLinearRegression
    from transmogrifai_tpu.models.linear_svc import OpLinearSVC
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.models.packed_newton import (
        lr_fit_batched_packed,
    )

    # 2 distinct rows, 40 columns ~N(0.03, 1e-4): mean/std ~ 300
    row_a = 0.03 + 0.0003 * np.arange(40)
    row_b = row_a + 0.0005 * ((-1.0) ** np.arange(40))
    X = np.tile(np.stack([row_a, row_b]), (20, 1)).astype(np.float64)
    y = np.tile([0.0, 1.0], 20)

    lr = OpLogisticRegression(reg_param=0.01, max_iter=25)
    p = lr.fit_arrays(X, y)
    assert np.isfinite(p["beta"]).all() and np.isfinite(p["intercept"])
    pred, _, _ = lr.predict_arrays(p, X)
    assert (pred == y).mean() == 1.0

    svc = OpLinearSVC(reg_param=0.01, max_iter=20)
    ps = svc.fit_arrays(X, y)
    assert np.isfinite(ps["beta"]).all()
    preds, _, _ = svc.predict_arrays(ps, X)
    assert (preds == y).mean() == 1.0

    lin = OpLinearRegression(reg_param=0.01)
    pl = lin.fit_arrays(X, y.astype(np.float64))
    assert np.isfinite(pl["beta"]).all()
    yhat, _, _ = lin.predict_arrays(pl, X)
    assert np.corrcoef(yhat, y)[0, 1] > 0.99

    # GLM families on the same matrix
    from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression

    for fam in ("gaussian", "poisson", "binomial"):
        glm = OpGeneralizedLinearRegression(family=fam, reg_param=0.01)
        pg = glm.fit_arrays(X, y if fam != "poisson" else y + 1.0)
        assert np.isfinite(pg["beta"]).all(), fam
        assert np.isfinite(pg["intercept"]), fam

    # packed route too
    W = np.ones((3, len(y)), np.float32)
    bp, ip = lr_fit_batched_packed(
        jnp.asarray(X, jnp.float32), jnp.asarray(y, jnp.float32),
        jnp.asarray(W), jnp.asarray([0.01, 0.1, 0.01], jnp.float32),
        jnp.asarray([0.0, 0.0, 0.1], jnp.float32), iters=25,
        hess_bf16=False,
    )
    assert np.isfinite(np.asarray(bp)).all()
    assert np.isfinite(np.asarray(ip)).all()


def test_multinomial_survives_separable_and_zero_variance_columns():
    """The Iris failure shape (round 5): near-separable classes, zero
    regularization, and constant-zero null-indicator columns must yield
    finite, accurate coefficients.  Two guards are pinned: the relative
    (trace-scaled) ridge that keeps the f32 Cholesky conditioned along
    the softmax shift-invariance flat directions, and the eps curvature
    floor that bounds the Newton steps when saturated probabilities zero
    the Hessian."""
    import jax.numpy as jnp

    from transmogrifai_tpu.models.logistic_regression import (
        _softmax_fit_kernel,
    )

    rng = np.random.RandomState(5)
    n, K = 450, 3
    centers = np.array([[3.0, 0.0], [-3.0, 1.0], [0.0, -4.0]])
    y = np.repeat(np.arange(K), n // K)
    Xn = centers[y] + 0.1 * rng.randn(n, 2)
    # interleave constant-zero columns like transmogrified null trackers
    X = np.zeros((n, 6), np.float32)
    X[:, 0], X[:, 2] = Xn[:, 0], Xn[:, 1]
    Yoh = np.zeros((n, K), np.float32)
    Yoh[np.arange(n), y] = 1.0
    w = np.ones(n, np.float32)
    for reg in (0.0, 0.01):
        b, b0 = _softmax_fit_kernel(
            jnp.asarray(X), jnp.asarray(Yoh), jnp.asarray(w),
            jnp.asarray(reg), jnp.asarray(0.0), iters=20,
        )
        b, b0 = np.asarray(b), np.asarray(b0)
        assert np.isfinite(b).all() and np.isfinite(b0).all(), reg
        acc = ((X @ b.T + b0).argmax(1) == y).mean()
        assert acc > 0.97, (reg, acc)
        assert np.abs(b[:, [1, 3, 4, 5]]).max() == 0.0  # excluded cols


def test_logistic_family_contract():
    """Family validation (review r5): unknown family strings raise at
    construction; family='binomial' refuses >2 classes (MLlib contract);
    an explicit 'multinomial' is honored regardless of problem size."""
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )

    with pytest.raises(ValueError, match="unknown logistic family"):
        OpLogisticRegression(family="multinominal")

    rng = np.random.RandomState(0)
    X = rng.randn(90, 2)
    y3 = np.repeat(np.arange(3.0), 30)
    with pytest.raises(ValueError, match="at most 2 outcome classes"):
        OpLogisticRegression(family="binomial").fit_arrays(X, y3)

    # explicit multinomial bypasses the auto-route size heuristic
    est = OpLogisticRegression(family="multinomial")
    assert est._multiclass_family(K=3, d=1023) == "multinomial"
    assert (
        OpLogisticRegression(family="auto")._multiclass_family(3, 1023)
        == "ovr"
    )


def test_gbt_refuses_multiclass_labels():
    """Logistic-loss GBT is binary-only (Spark GBTClassifier contract):
    3-class labels previously fit sigmoid-on-{0,1,2} silently at chance
    accuracy - every fit entry point must raise instead (round 5)."""
    rng = np.random.RandomState(0)
    X = rng.randn(90, 3)
    y3 = np.repeat(np.arange(3.0), 30)
    est = OpGBTClassifier(num_trees=3, max_depth=3)
    W = np.ones((2, 90))
    with pytest.raises(ValueError, match="only binary"):
        est.fit_arrays(X, y3)
    with pytest.raises(ValueError, match="only binary"):
        est.fit_arrays_folds(X, y3, W)
    with pytest.raises(ValueError, match="only binary"):
        est.fit_arrays_folds_grid(X, y3, W, [{}])
    # regressors and binary labels stay unaffected
    from transmogrifai_tpu.models.trees import OpGBTRegressor

    OpGBTRegressor(num_trees=2, max_depth=2).fit_arrays(X, y3)
    est2 = OpGBTClassifier(num_trees=2, max_depth=2)
    est2.fit_arrays(X, (y3 > 0).astype(float))


def test_linear_svc_refuses_multiclass_labels():
    """Squared-hinge SVC is binary-only (Spark LinearSVC contract):
    3-class labels must raise at both fit entry points, and an MLP -
    the reference's multiclass-capable neural family - must actually
    learn the same 3-class problem."""
    from transmogrifai_tpu.models.mlp import (
        OpMultilayerPerceptronClassifier,
    )

    rng = np.random.RandomState(0)
    n = 300
    centers = np.array([[2.5, 0.0], [-2.5, 1.0], [0.0, -3.0]])
    y3 = np.repeat(np.arange(3.0), n // 3)
    X = centers[y3.astype(int)] + 0.5 * rng.randn(n, 2)
    with pytest.raises(ValueError, match="only binary"):
        OpLinearSVC().fit_arrays(X, y3)
    with pytest.raises(ValueError, match="only binary"):
        OpLinearSVC().fit_arrays_batched(
            X, y3, np.ones((2, n)), np.zeros(2), np.zeros(2)
        )
    mlp = OpMultilayerPerceptronClassifier(hidden_layers=(8,), max_iter=60)
    p = mlp.fit_arrays(X, y3)
    pred, _, prob = mlp.predict_arrays(p, X)
    assert (pred == y3).mean() > 0.95
    assert prob.shape == (n, 3)


def test_binary_guard_rejects_nonstandard_encodings():
    """Count-only checks miss y in {1,2} (both classes map to the
    positive hinge side); the shared base guard validates VALUES too,
    and skips device-resident labels (the validator pre-guards those) -
    review r5."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    X = rng.randn(60, 3)
    y12 = np.repeat([1.0, 2.0], 30)
    with pytest.raises(ValueError, match="labels in"):
        OpLinearSVC().fit_arrays(X, y12)
    with pytest.raises(ValueError, match="labels in"):
        OpGBTClassifier(num_trees=2, max_depth=2).fit_arrays(X, y12)
    # device-resident labels skip the host scan (pre-guarded callers)
    est = OpLinearSVC()
    est._check_binary_labels(jnp.asarray(y12))  # no raise by design


@pytest.mark.parametrize("seed,K", [(1, 3), (2, 4), (3, 5), (4, 3)])
def test_multinomial_property_sweep_vs_scipy(seed, K):
    """Seeded sweep of random multiclass problems: the softmax Newton's
    probabilities must match an independent scipy L-BFGS optimum of the
    same penalized objective (one fixed problem proves little; the sweep
    covers class counts and geometries)."""
    from scipy.optimize import minimize

    import jax.numpy as jnp

    from transmogrifai_tpu.models.logistic_regression import (
        _softmax_fit_kernel,
    )

    rng = np.random.RandomState(seed)
    n, d = 400, 6
    X = rng.randn(n, d).astype(np.float32)
    Bt = rng.randn(K, d) * 1.2
    z = X @ Bt.T
    P = np.exp(z - z.max(1, keepdims=True))
    P /= P.sum(1, keepdims=True)
    y = np.array([rng.choice(K, p=pp) for pp in P])
    if len(np.unique(y)) < K:
        pytest.skip("degenerate draw")
    Yoh = np.zeros((n, K), np.float32)
    Yoh[np.arange(n), y] = 1.0
    w = (rng.rand(n) + 0.5).astype(np.float32)
    reg = 0.03

    betas, b0 = _softmax_fit_kernel(
        jnp.asarray(X), jnp.asarray(Yoh), jnp.asarray(w),
        jnp.asarray(reg), jnp.asarray(0.0), iters=30,
    )
    betas, b0 = np.asarray(betas, np.float64), np.asarray(b0, np.float64)

    wsum = w.sum()
    mu = (w @ X) / wsum
    var = (w @ (X * X)) / wsum - mu**2
    sd = np.sqrt(np.maximum(var, 1e-12))
    Xs = (X - mu) / sd

    def nll(theta):
        B = theta[: K * d].reshape(K, d)
        zz = Xs @ B.T + theta[K * d:]
        zz = zz - zz.max(axis=1, keepdims=True)
        logp = zz - np.log(np.exp(zz).sum(axis=1, keepdims=True))
        return (
            -(w * logp[np.arange(n), y]).sum() / wsum
            + 0.5 * reg * (B**2).sum()
        )

    res = minimize(nll, np.zeros(K * d + K), method="L-BFGS-B",
                   options={"maxiter": 5000, "ftol": 1e-15, "gtol": 1e-11})
    beta_ref = res.x[: K * d].reshape(K, d) / sd
    b0_ref = res.x[K * d:] - beta_ref @ mu
    z1 = X @ betas.T + b0
    z2 = X @ beta_ref.T + b0_ref
    p1 = np.exp(z1 - z1.max(1, keepdims=True))
    p1 /= p1.sum(1, keepdims=True)
    p2 = np.exp(z2 - z2.max(1, keepdims=True))
    p2 /= p2.sum(1, keepdims=True)
    assert np.abs(p1 - p2).max() < 3e-3, np.abs(p1 - p2).max()


def test_multinomial_bf16_hessian_branch(monkeypatch):
    """The bf16-Hessian branch of the softmax kernel (the TPU default)
    must stay finite and accurate under ill-conditioned columns - on CPU
    it is only reachable via the env override, so pin it here rather
    than discover a broken trace on the chip."""
    import jax

    monkeypatch.setenv("TX_LR_HESSIAN_BF16", "1")
    jax.clear_caches()  # the env is read at trace time
    try:
        rng = np.random.RandomState(3)
        n = 450
        centers = np.array([[2.5, 0.0], [-2.5, 1.0], [0.0, -3.0]])
        y = np.repeat(np.arange(3.0), n // 3)
        X = centers[y.astype(int)] + 0.5 * rng.randn(n, 2)
        X[:, 0] = X[:, 0] * 20 + 100
        est = OpLogisticRegression(reg_param=0.01)
        params = est.fit_arrays(X, y)
        pred, _, prob = est.predict_arrays(params, X)
        assert params["family"] == "multinomial"
        assert np.isfinite(params["betas"]).all()
        assert (pred == y).mean() > 0.95
        assert np.isfinite(prob).all()
    finally:
        jax.clear_caches()  # don't leak bf16-traced kernels to others
