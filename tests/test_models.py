"""Model-level tests on synthetic data (mirrors the reference's
classification/regression model specs, reference: core/src/test/.../impl/
classification + regression)."""
import numpy as np
import pytest

from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.linear_svc import OpLinearSVC
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
from transmogrifai_tpu.models.trees import (
    OpDecisionTreeClassifier,
    OpGBTClassifier,
    OpGBTRegressor,
    OpRandomForestClassifier,
    OpRandomForestRegressor,
)


@pytest.fixture
def binary_data(rng):
    n, d = 600, 8
    X = rng.randn(n, d)
    beta = 2.0 * np.array([2.0, -1.5, 1.0, 0.0, 0.0, 0.5, -0.5, 0.0])
    p = 1 / (1 + np.exp(-(X @ beta - 0.3)))
    y = (rng.rand(n) < p).astype(np.float64)
    return X, y


@pytest.fixture
def regression_data(rng):
    n, d = 500, 6
    X = rng.randn(n, d)
    beta = np.array([1.0, 2.0, 0.0, -1.0, 0.5, 0.0])
    y = X @ beta + 0.7 + 0.1 * rng.randn(n)
    return X, y


def _acc(est, X, y):
    params = est.fit_arrays(X, y)
    pred, raw, prob = est.predict_arrays(params, X)
    return float((pred == y).mean()), prob


def test_logistic_regression_learns(binary_data):
    X, y = binary_data
    acc, prob = _acc(OpLogisticRegression(reg_param=0.01), X, y)
    assert acc > 0.85
    assert prob.shape == (len(y), 2)
    assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)


def test_logistic_regression_batched_matches_single(binary_data):
    X, y = binary_data
    est = OpLogisticRegression()
    W = np.ones((3, len(y)))
    regs = np.array([0.001, 0.01, 0.1])
    ens = np.zeros(3)
    betas, b0s = est.fit_arrays_batched(X, y, W, regs, ens)
    est_single = OpLogisticRegression(reg_param=0.01)
    single = est_single.fit_arrays(X, y)
    assert np.allclose(betas[1], single["beta"], atol=1e-3)


def test_logistic_regression_sample_weights(binary_data):
    X, y = binary_data
    est = OpLogisticRegression(reg_param=0.01)
    w = np.zeros(len(y))
    w[:300] = 1.0
    params_w = est.fit_arrays(X, y, w)
    params_sub = est.fit_arrays(X[:300], y[:300])
    assert np.allclose(params_w["beta"], params_sub["beta"], atol=1e-4)


def test_linear_svc(binary_data):
    X, y = binary_data
    acc, _ = _acc(OpLinearSVC(reg_param=0.01), X, y)
    assert acc > 0.85


def test_naive_bayes(binary_data):
    X, y = binary_data
    acc, prob = _acc(OpNaiveBayes(), X, y)
    assert acc > 0.70
    assert np.allclose(prob.sum(axis=1), 1.0, atol=1e-5)


def test_random_forest_classifier(binary_data):
    X, y = binary_data
    est = OpRandomForestClassifier(num_trees=20, max_depth=5)
    acc, prob = _acc(est, X, y)
    assert acc > 0.85
    assert prob.shape == (len(y), 2)


def test_decision_tree_classifier(binary_data):
    X, y = binary_data
    acc, _ = _acc(OpDecisionTreeClassifier(max_depth=5), X, y)
    assert acc > 0.80


def test_gbt_classifier(binary_data):
    X, y = binary_data
    acc, _ = _acc(OpGBTClassifier(num_trees=20, max_depth=3), X, y)
    assert acc > 0.88


def test_linear_regression(regression_data):
    X, y = regression_data
    est = OpLinearRegression(reg_param=0.001)
    params = est.fit_arrays(X, y)
    pred, _, _ = est.predict_arrays(params, X)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.2
    assert abs(params["intercept"] - 0.7) < 0.1


def test_random_forest_regressor(regression_data):
    X, y = regression_data
    est = OpRandomForestRegressor(num_trees=20, max_depth=6)
    params = est.fit_arrays(X, y)
    pred, _, _ = est.predict_arrays(params, X)
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.7


def test_gbt_regressor(regression_data):
    X, y = regression_data
    est = OpGBTRegressor(num_trees=30, max_depth=4)
    params = est.fit_arrays(X, y)
    pred, _, _ = est.predict_arrays(params, X)
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.8
