"""End-to-end Titanic slice (BASELINE.md configs 1-2 shape).

Mirrors the reference's workflow tests (reference: core/src/test/scala/com/
salesforce/op/OpWorkflowTest.scala) + the README quality bar: holdout AuROC
should approach the published 0.88 (we assert a conservative floor here;
bench.py tracks the exact number).
"""
import os

import numpy as np
import pytest

from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.examples.titanic import TITANIC_CSV, titanic_workflow

needs_data = pytest.mark.skipif(
    not os.path.exists(TITANIC_CSV), reason="titanic csv not available"
)


@needs_data
def test_titanic_lr_end_to_end():
    wf, survived, prediction = titanic_workflow(reserve_test_fraction=0.15)
    model = wf.train()

    # training metrics
    train_metrics = model.evaluate(OpBinaryClassificationEvaluator())
    assert train_metrics.AuROC > 0.83, train_metrics

    # holdout metrics
    holdout = model.evaluate_holdout(OpBinaryClassificationEvaluator())
    # plain LR floor; the README's 0.88 is the RF ModelSelector's number
    assert holdout.AuROC > 0.78, holdout

    # sanity checker kept a sensible number of columns and recorded summary
    summary = model.summary_json()
    sc = next(
        s for s in summary["stages"]
        if "sanity_checker_summary" in s.get("metadata", {})
    )
    scs = sc["metadata"]["sanity_checker_summary"]
    assert scs["n_kept"] > 10
    assert scs["n_features"] >= scs["n_kept"]

    # sex columns must carry the famous +-0.51 correlation (README.md:100-107)
    by_name = {c["pretty_name"]: c for c in scs["column_stats"]}
    female = next(
        (v for k, v in by_name.items() if "female" in k.lower()), None
    )
    assert female is not None and female["corr_label"] is not None
    assert 0.40 < female["corr_label"] < 0.62

    # row-level scorer parity with batch scoring
    fn = model.score_function()
    rec = {
        "pClass": "3", "name": "Braund, Mr. Owen Harris", "sex": "male",
        "age": 22.0, "sibSp": 1, "parCh": 0, "ticket": "A/5 21171",
        "fare": 7.25, "cabin": None, "embarked": "S", "survived": 0.0,
    }
    out = fn(rec)
    pred_val = out[prediction.name]
    assert set(pred_val) >= {"prediction", "probability_0", "probability_1"}


@needs_data
def test_titanic_scoring_roundtrip():
    wf, survived, prediction = titanic_workflow(reserve_test_fraction=0.0)
    model = wf.train()
    # rescore the raw reader data through the fitted DAG
    from transmogrifai_tpu.examples.titanic import titanic_reader

    raw = titanic_reader().generate_dataset(model.raw_features, {})
    scored = model.score(raw)
    assert prediction.name in scored
    assert len(scored) == len(raw)
    probs = scored[prediction.name].probability
    assert probs is not None and np.all(probs >= 0) and np.all(probs <= 1)


def test_score_and_evaluate_api(rng):
    """Reference parity: model.scoreAndEvaluate returns (scores, metrics)
    in one pass (OpTitanicSimple's final step)."""
    import transmogrifai_tpu.dsl  # noqa: F401
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    n = 200
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
    }
    data["a"] = [ai + 2 * yi for ai, yi in zip(data["a"], data["y"])]
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    pred = (
        OpLogisticRegression(max_iter=10)
        .set_input(y, transmogrify([a]))
        .get_output()
    )
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    scored, metrics = model.score_and_evaluate(
        OpBinaryClassificationEvaluator(), data=data
    )
    assert pred.name in scored and len(scored) == n
    assert float(metrics.AuROC) > 0.85
