"""Preemption re-dispatch harness (workflow/supervisor.py + validator
heartbeats).  SURVEY §5.3: detect a dead/hung CV step, restore from the
checkpoint, re-dispatch; the restarted run must reach the IDENTICAL final
selection an uninterrupted run reaches."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import json, os, sys, time
# hang mode wedges BEFORE the heavy imports, beating once first: the
# supervisor must detect it via heartbeat STALENESS (stale_after_s), so
# the test is immune to slow-import startup on a loaded host (the
# startup window is governed by grace_s, which the test sets generously)
if {mode!r} == "hang" and not os.path.exists({marker!r}):
    open({marker!r}, "w").close()
    hb = {ckpt!r} + ".heartbeat"
    with open(hb, "w") as f:
        f.write("beat")
    time.sleep(600)
sys.path.insert(0, {repo!r})
import _backend_guard
_backend_guard.ensure_cpu_mesh(1)
import numpy as np
from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
from transmogrifai_tpu.selector.validator import OpCrossValidation, OpValidator

ckpt = {ckpt!r}
marker = {marker!r}
mode = {mode!r}

class SlowGLM(OpGeneralizedLinearRegression):
    model_type = "SlowGLM"
    def fit_arrays_folds(self, X, y, W):
        time.sleep(0.05)
        return super().fit_arrays_folds(X, y, W)

if mode == "die" and not os.path.exists(marker):
    # first attempt: SIGKILL-style death after the 3rd checkpointed row
    open(marker, "w").close()
    orig = OpValidator._ckpt_save
    state = {{"n": 0}}
    def dying(self, done):
        orig(self, done)
        state["n"] += 1
        if state["n"] >= 3:
            os._exit(9)
    OpValidator._ckpt_save = dying
# (hang mode handled at the very top, before imports)

rng = np.random.RandomState(0)
n = 400
X = rng.randn(n, 5)
y = X @ np.linspace(1.0, -1.0, 5) + 0.3 * rng.randn(n)
grid = [{{"reg_param": r}} for r in (0.0, 0.001, 0.01, 0.1, 0.3, 1.0)]
cv = OpCrossValidation(num_folds=3, evaluator=OpRegressionEvaluator(),
                       seed=0, checkpoint_path=ckpt)
res = cv.validate([(SlowGLM(max_iter=8), grid)], X, y)
with open({out!r}, "w") as f:
    json.dump({{"best_params": res.best_params,
               "best_metric": res.best_metric,
               "all": [(r["params"], r["fold_metrics"])
                        for r in res.all_results]}}, f)
"""


def _write_worker(tmp_path, name, mode):
    ckpt = str(tmp_path / f"{name}.ckpt.json")
    marker = str(tmp_path / f"{name}.died")
    out = str(tmp_path / f"{name}.result.json")
    script = tmp_path / f"{name}.py"
    script.write_text(
        WORKER.format(repo=REPO, ckpt=ckpt, marker=marker, mode=mode,
                      out=out)
    )
    return str(script), ckpt, marker, out


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def test_supervisor_redispatches_after_mid_cv_death(tmp_path):
    from transmogrifai_tpu.workflow.supervisor import supervise

    # uninterrupted baseline
    script_b, _, _, out_b = _write_worker(tmp_path, "baseline", "never")
    subprocess.run([sys.executable, script_b], check=True, env=_env(),
                   timeout=300)
    baseline = json.load(open(out_b))

    # supervised run that dies after 3 checkpointed rows
    script, ckpt, marker, out = _write_worker(tmp_path, "dying", "die")
    res = supervise(
        [sys.executable, script],
        heartbeat_path=ckpt + ".heartbeat",
        stale_after_s=120.0,
        max_restarts=2,
        env=_env(),
    )
    assert res.returncode == 0
    assert res.attempts == 2, res.restarts  # died once, resumed once
    assert os.path.exists(marker)

    got = json.load(open(out))
    assert got["best_params"] == baseline["best_params"]
    assert got["best_metric"] == pytest.approx(baseline["best_metric"])
    for (p1, m1), (p2, m2) in zip(got["all"], baseline["all"]):
        assert p1 == p2
        assert np.allclose(m1, m2)

    # the resumed run restored rows 1-3 from the checkpoint (keys exist)
    done = json.load(open(ckpt))
    assert len(done) == 6


def test_supervisor_kills_hung_worker_and_redispatches(tmp_path):
    from transmogrifai_tpu.workflow.supervisor import supervise

    script, ckpt, marker, out = _write_worker(tmp_path, "hung", "hang")
    t0 = time.time()
    res = supervise(
        [sys.executable, script],
        heartbeat_path=ckpt + ".heartbeat",
        stale_after_s=8.0,
        grace_s=240.0,  # startup may be slow on a loaded host
        max_restarts=1,
        poll_s=0.2,
        env=_env(),
    )
    assert res.returncode == 0
    assert res.attempts == 2
    assert "stale" in res.restarts[0][1]
    assert time.time() - t0 < 560
    assert os.path.exists(out)


def test_supervisor_exhausts_restarts(tmp_path):
    from transmogrifai_tpu.workflow.supervisor import supervise

    hb = str(tmp_path / "hb")
    with pytest.raises(RuntimeError, match="after 2 attempts"):
        supervise(
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            heartbeat_path=hb, stale_after_s=30.0, max_restarts=1,
            poll_s=0.1, env=_env(),
        )


def test_staleness_clamps_future_mtimes_to_zero(tmp_path):
    """ISSUE 3 satellite regression: a heartbeat stamped in the FUTURE
    (clock skew across hosts, coarse-mtime filesystems) must read
    staleness 0.0, never negative - negative staleness poisons every
    ``staleness > threshold`` comparison downstream (supervise(), mesh
    PeerHealth), letting a hung child look alive for the whole skew
    window."""
    from transmogrifai_tpu.workflow.supervisor import beat, staleness

    hb = str(tmp_path / "hb")
    beat(hb)
    future = time.time() + 120.0
    os.utime(hb, (future, future))
    s = staleness(hb)
    assert s == 0.0
    assert staleness(str(tmp_path / "never-beat")) is None


def test_legacy_checkpoint_keys_migrate(tmp_path):
    """Pre-mode-suffix checkpoint files restore as ':exact' rows instead of
    silently retraining everything (advisor finding)."""
    from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    ckpt = tmp_path / "legacy.json"
    legacy_key = 'OpLinearRegression:{"reg_param": 0.1}'
    ckpt.write_text(json.dumps({legacy_key: [0.5, 0.6, 0.7]}))
    cv = OpCrossValidation(num_folds=3, evaluator=OpRegressionEvaluator(),
                           checkpoint_path=str(ckpt))
    done = cv._ckpt_load()
    assert legacy_key + ":exact" in done
    assert done[legacy_key + ":exact"] == [0.5, 0.6, 0.7]
