"""Type system + feature graph + DAG tests (mirrors the reference's
FeatureLike/OpPipelineStage specs, reference: features/src/test/)."""
import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder, from_schema
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import NumericColumn, TextColumn
from transmogrifai_tpu.workflow.dag import compute_dag, validate_dag
import transmogrifai_tpu.dsl  # noqa: F401  (patches Feature operators)


def test_type_lattice():
    assert issubclass(ft.RealNN, ft.Real)
    assert issubclass(ft.DateTime, ft.Date)
    assert issubclass(ft.Date, ft.Integral)
    assert issubclass(ft.PickList, ft.Text)
    assert ft.PickList.is_categorical
    assert ft.RealNN.non_nullable
    assert ft.TextMap.value_type is ft.Text
    assert len(ft.all_feature_types()) >= 45


def test_numeric_column_masks():
    c = NumericColumn.from_list([1.0, None, 3.0])
    assert c.mask.tolist() == [True, False, True]
    assert c.values[1] == 0.0
    assert c.to_list() == [1.0, None, 3.0]


def test_feature_builder_and_raw_features():
    age = FeatureBuilder(ft.Real, "age").as_predictor()
    label = FeatureBuilder(ft.RealNN, "y").as_response()
    assert age.is_raw() and not age.is_response
    assert label.is_response
    s = age + 1
    total = s * 2
    raws = total.raw_features()
    assert [f.name for f in raws] == ["age"]


def test_dag_layering():
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    c = a + b        # layer 0
    d = c * 2        # layer 1
    e = d + a        # layer 2
    dag = compute_dag([e])
    assert len(dag) == 3
    validate_dag(dag)
    # execution order: c's stage first, e's stage last
    assert dag[0][0] is c.origin_stage
    assert dag[-1][0] is e.origin_stage


def test_dag_dedup_shared_subgraph():
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    shared = a + 1
    x = shared * 2
    y = shared * 3
    dag = compute_dag([x, y])
    stages = [s for layer in dag for s in layer]
    assert len(stages) == 3  # shared counted once


def test_from_schema_sorted_and_typed():
    resp, preds = from_schema(
        {"y": ft.Integral, "b": ft.Text, "a": ft.Real}, response="y"
    )
    assert resp.ftype is ft.RealNN and resp.is_response
    assert [p.name for p in preds] == ["a", "b"]
    assert preds[0].ftype is ft.Real


def test_feature_math_transform():
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    out = (a + b) / 2
    ds = Dataset.from_pylists(
        {"a": [2.0, None, 4.0], "b": [4.0, 1.0, None]},
        {"a": ft.Real, "b": ft.Real},
    )
    from transmogrifai_tpu.workflow.workflow import fit_and_transform_dag

    dag = compute_dag([out])
    _, res, _ = fit_and_transform_dag(dag, ds)
    col = res[out.name]
    assert col.to_list() == [3.0, None, None]  # null propagation
