"""Boston regression + Iris multiclass end-to-end (BASELINE.md configs 3-4;
reference: helloworld OpBoston.scala / OpIris.scala)."""
import os

import numpy as np
import pytest

from transmogrifai_tpu.evaluators.multiclass import OpMultiClassificationEvaluator
from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
from transmogrifai_tpu.examples.boston import BOSTON_DATA, boston_workflow
from transmogrifai_tpu.examples.iris import IRIS_DATA, iris_workflow
from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
from transmogrifai_tpu.models.trees import (
    OpGBTRegressor,
    OpRandomForestClassifier,
)
from transmogrifai_tpu.selector.factories import (
    MultiClassificationModelSelector,
    RegressionModelSelector,
    linreg_grid,
)


@pytest.mark.skipif(not os.path.exists(BOSTON_DATA), reason="no boston data")
def test_boston_regression_end_to_end():
    selector = RegressionModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[
            (OpLinearRegression(), linreg_grid()[:4]),
            (OpGBTRegressor(num_trees=20, max_depth=4), [{}]),
        ],
    )
    wf, medv, prediction = boston_workflow(selector=selector)
    model = wf.train()
    metrics = model.evaluate(OpRegressionEvaluator())
    assert metrics.R2 > 0.6, metrics
    md = model.stages[-1].metadata["model_selector_summary"]
    assert md["best_model_type"] in ("OpLinearRegression", "OpGBTRegressor")
    holdout = model.evaluate_holdout(OpRegressionEvaluator())
    assert holdout.RootMeanSquaredError < 8.0, holdout


@pytest.mark.skipif(not os.path.exists(IRIS_DATA), reason="no iris data")
def test_iris_multiclass_end_to_end():
    selector = MultiClassificationModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[
            (OpRandomForestClassifier(num_trees=10, max_depth=4), [{}]),
            (OpNaiveBayes(), [{}]),
        ],
    )
    wf, label, prediction, deindexed, labels = iris_workflow(
        selector=selector
    )
    assert labels == ["Iris-setosa", "Iris-versicolor", "Iris-virginica"]
    model = wf.train()
    # no-argument evaluate must resolve the label to the model's own
    # (indexed) label input, not the raw STRING response column
    metrics = model.evaluate(OpMultiClassificationEvaluator())
    assert metrics.F1 > 0.90, metrics
    # threshold metrics present (reference: OpMultiClassificationEvaluator
    # ThresholdMetrics topN {1,3})
    tm = metrics.threshold_metrics
    assert tm["topns"] == [1, 3]
    assert len(tm["thresholds"]) == 101
    holdout = model.evaluate_holdout(OpMultiClassificationEvaluator())
    assert holdout.Error < 0.2, holdout
    # the de-indexed prediction round-trips numeric classes back to the
    # ORIGINAL label strings (reference OpIris deindexed flow)
    scored = model.score(wf.generate_raw_data())
    de = scored[deindexed.name].values
    raw = scored["irisClass"].values
    agree = sum(a == b for a, b in zip(de, raw)) / len(de)
    assert set(v for v in de if v is not None) <= set(labels)
    assert agree > 0.9, agree


@pytest.mark.skipif(not os.path.exists(IRIS_DATA), reason="no iris data")
def test_indexed_label_with_missing_value_fails_loudly():
    """A missing string label must not become a phantom class through the
    StringIndexer: the predictor fit gate rejects masked labels."""
    from transmogrifai_tpu import FeatureBuilder, OpWorkflow
    from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft
    import transmogrifai_tpu.dsl  # noqa: F401

    n = 60
    data = {
        "cls": [None if i == 5 else ("a" if i % 2 else "b")
                for i in range(n)],
        "x": [float(i % 7) for i in range(n)],
    }
    cls = FeatureBuilder(ft.PickList, "cls").as_response()
    x = FeatureBuilder(ft.Real, "x").as_predictor()
    label = cls.indexed()
    pred = (
        OpLogisticRegression(max_iter=3)
        .set_input(label, transmogrify([x]))
        .get_output()
    )
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    with pytest.raises(ValueError, match="missing values"):
        wf.train()
