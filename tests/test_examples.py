"""Boston regression + Iris multiclass end-to-end (BASELINE.md configs 3-4;
reference: helloworld OpBoston.scala / OpIris.scala)."""
import os

import numpy as np
import pytest

from transmogrifai_tpu.evaluators.multiclass import OpMultiClassificationEvaluator
from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator
from transmogrifai_tpu.examples.boston import BOSTON_DATA, boston_workflow
from transmogrifai_tpu.examples.iris import IRIS_DATA, iris_workflow
from transmogrifai_tpu.models.linear_regression import OpLinearRegression
from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
from transmogrifai_tpu.models.trees import (
    OpGBTRegressor,
    OpRandomForestClassifier,
)
from transmogrifai_tpu.selector.factories import (
    MultiClassificationModelSelector,
    RegressionModelSelector,
    linreg_grid,
)


@pytest.mark.skipif(not os.path.exists(BOSTON_DATA), reason="no boston data")
def test_boston_regression_end_to_end():
    selector = RegressionModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[
            (OpLinearRegression(), linreg_grid()[:4]),
            (OpGBTRegressor(num_trees=20, max_depth=4), [{}]),
        ],
    )
    wf, medv, prediction = boston_workflow(selector=selector)
    model = wf.train()
    metrics = model.evaluate(OpRegressionEvaluator())
    assert metrics.R2 > 0.6, metrics
    md = model.stages[-1].metadata["model_selector_summary"]
    assert md["best_model_type"] in ("OpLinearRegression", "OpGBTRegressor")
    holdout = model.evaluate_holdout(OpRegressionEvaluator())
    assert holdout.RootMeanSquaredError < 8.0, holdout


@pytest.mark.skipif(not os.path.exists(IRIS_DATA), reason="no iris data")
def test_iris_multiclass_end_to_end():
    selector = MultiClassificationModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[
            (OpRandomForestClassifier(num_trees=10, max_depth=4), [{}]),
            (OpNaiveBayes(), [{}]),
        ],
    )
    wf, label, prediction, labels = iris_workflow(selector=selector)
    assert labels == ["Iris-setosa", "Iris-versicolor", "Iris-virginica"]
    model = wf.train()
    metrics = model.evaluate(OpMultiClassificationEvaluator())
    assert metrics.F1 > 0.90, metrics
    # threshold metrics present (reference: OpMultiClassificationEvaluator
    # ThresholdMetrics topN {1,3})
    tm = metrics.threshold_metrics
    assert tm["topns"] == [1, 3]
    assert len(tm["thresholds"]) == 101
    holdout = model.evaluate_holdout(OpMultiClassificationEvaluator())
    assert holdout.Error < 0.2, holdout
