"""Network-fault envelope drills for the fleet transport (ISSUE 17).

The acceptance drills - a loopback-TCP fleet partitioned mid-serve
(ejection -> survivors absorb with an exact double-entry ledger ->
half-open probe readmission, all under one trace id), the half-open
(accept-but-never-respond) variant, and a reconnect-storm recovery -
plus the unit surface: TCP/unix address parsing, per-frame CRC32
integrity, OP_HELLO handshake failure modes, the ReplicaHealth state
machine, quorum brownout, remote deadline drops, and the
decode-error attribution satellite.

All drills are seeded: the fault specs (``on=``/``every=`` triggers,
``delay=`` impairment windows) pin every run to the same schedule, and
fault consumption only happens on data sends (see faults/injection.py)
so trigger counts are a deterministic function of traffic.
"""
from __future__ import annotations

import socket
import threading
import time

import pytest

from transmogrifai_tpu.faults import injection as _faults
from transmogrifai_tpu.fleet import (
    BrownoutShedError,
    FleetController,
    FleetDecodeError,
    FleetRouter,
    ReplicaHealth,
)
from transmogrifai_tpu.fleet.channel import (
    OP_HELLO,
    OP_SCORE,
    WIRE_MAGIC,
    ChannelClosedError,
    ChannelProtocolError,
    ChannelTimeoutError,
    accept,
    connect,
    listen,
    parse_address,
)
from transmogrifai_tpu.fleet.router import FleetResult
from transmogrifai_tpu.obs.trace import tracer
from transmogrifai_tpu.registry import ModelRegistry
from transmogrifai_tpu.serving import QueueFullError
from transmogrifai_tpu.serving.admission import DeadlineExceededError
from transmogrifai_tpu.testkit.drills import tiny_drill_pipeline

WORKFLOW_SPEC = "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline"


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every drill leaves the process fault-free (channel unit tests
    arm in-process; a leaked plan would corrupt later tests)."""
    yield
    _faults.reset()


# ---------------------------------------------------------------------------
# unit surface: addressing
# ---------------------------------------------------------------------------
def test_parse_address_tcp_vs_unix():
    assert parse_address("tcp://10.0.0.7:7001") == \
        ("tcp", ("10.0.0.7", 7001))
    assert parse_address("tcp://:7001") == ("tcp", ("127.0.0.1", 7001))
    assert parse_address("127.0.0.1:9000") == \
        ("tcp", ("127.0.0.1", 9000))
    # a path separator or a non-numeric port means unix, not TCP
    assert parse_address("/tmp/replica-0.sock") == \
        ("unix", "/tmp/replica-0.sock")
    assert parse_address("/tmp/odd:name.sock") == \
        ("unix", "/tmp/odd:name.sock")
    assert parse_address("replica:zero") == ("unix", "replica:zero")


# ---------------------------------------------------------------------------
# unit surface: TCP channel - roundtrip, CRC integrity, handshake
# ---------------------------------------------------------------------------
def _tcp_listener():
    lsock = listen("127.0.0.1:0")
    host, port = lsock.getsockname()[:2]
    return lsock, f"{host}:{port}"


def _recv_message(chan, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() <= deadline:
        msg = chan.recv()
        if msg is not None:
            return msg
    raise AssertionError("no message within the deadline")


def _handshake_server(lsock, accepts=1, magic=None, respond=True,
                      errors=None):
    """Accept ``accepts`` connections and answer each OP_HELLO (with an
    optionally-wrong magic, or silence) - the worker side of the
    handshake, small enough to drive every client failure mode."""

    def run():
        for _ in range(accepts):
            try:
                chan = accept(lsock, 10.0)
            except ChannelClosedError:
                return  # the test closed the listener: done
            if chan is None:
                return
            try:
                msg = _recv_message(chan)
                if respond:
                    meta = chan.hello_reply_meta()
                    if magic is not None:
                        meta["magic"] = magic
                    chan.send(OP_HELLO, msg[1], meta)
                # hold the channel open (silently when not responding -
                # the client must TIME OUT, not see a close) until the
                # peer hangs up or a bounded wait passes
                try:
                    _recv_message(chan, timeout_s=5.0)
                except AssertionError:
                    pass
            except (ChannelClosedError, ChannelProtocolError) as e:
                if errors is not None:
                    errors.append(e)
            finally:
                chan.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_tcp_roundtrip_and_crc_corruption_detected():
    lsock, address = _tcp_listener()
    server_chan = {}
    ready = threading.Event()

    def server():
        server_chan["c"] = accept(lsock, 10.0)
        ready.set()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    client = connect(address, timeout_s=10.0, handshake=False)
    assert ready.wait(10.0)
    srv = server_chan["c"]
    try:
        # clean frame: meta + payload survive the wire byte-exactly
        payload = b"\x00\x01" * 4096
        client.send(OP_SCORE, 7, {"n_rows": 3}, payload)
        op, rid, meta, got = _recv_message(srv)
        assert (op, rid, meta["n_rows"]) == (OP_SCORE, 7, 3)
        assert bytes(got) == payload

        # corrupt frame: flipped CRC -> ChannelProtocolError, counted,
        # never decoded into a batch; the stream is unsyncable -> closed
        _faults.configure("channel.corrupt_frame:on=1")
        client.send(OP_SCORE, 8, {"n_rows": 3}, payload)
        assert client.corrupt_injected == 1
        with pytest.raises(ChannelProtocolError, match="CRC"):
            _recv_message(srv)
        assert srv.protocol_errors == 1
        assert srv.closed
        assert srv.stats()["protocol_errors"] == 1
    finally:
        client.close()
        srv.close()
        lsock.close()


def test_handshake_rejects_cross_wired_magic():
    lsock, address = _tcp_listener()
    _handshake_server(lsock, magic="not-txfleet")
    try:
        with pytest.raises(ChannelProtocolError, match="cross-wired"):
            connect(address, timeout_s=5.0)
    finally:
        lsock.close()


def test_handshake_silence_times_out_bounded():
    lsock, address = _tcp_listener()
    _handshake_server(lsock, accepts=8, respond=False)
    t0 = time.monotonic()
    try:
        with pytest.raises(ChannelTimeoutError, match="handshake"):
            connect(address, timeout_s=0.6, handshake_timeout_s=0.2)
    finally:
        lsock.close()
    assert time.monotonic() - t0 < 5.0  # bounded, not the 30s default


def test_handshake_completes_and_records_peer():
    lsock, address = _tcp_listener()
    _handshake_server(lsock)
    try:
        chan = connect(address, timeout_s=5.0)
        assert chan.peer["magic"] == WIRE_MAGIC
        assert chan.peer["pid"] > 0
        chan.close()
    finally:
        lsock.close()


def test_reconnect_storm_drops_connections_then_recovers():
    lsock, address = _tcp_listener()
    errors: list = []
    _handshake_server(lsock, accepts=4, errors=errors)
    _faults.configure("fleet.reconnect_storm:every=1:times=2")
    try:
        for _ in range(2):
            with pytest.raises(ChannelProtocolError,
                               match="reconnect storm"):
                connect(address, timeout_s=5.0)
        # the storm budget (times=2) is spent: the next connect lands
        chan = connect(address, timeout_s=5.0)
        assert chan.peer["magic"] == WIRE_MAGIC
        chan.close()
    finally:
        lsock.close()


# ---------------------------------------------------------------------------
# unit surface: ReplicaHealth state machine
# ---------------------------------------------------------------------------
def test_replica_health_state_machine():
    h = ReplicaHealth(eject_after=2)
    assert h.state == "healthy" and h.snapshot()["state_code"] == 0

    # consecutive failures below the threshold do not eject
    assert h.record_failure("response timeout", 1.0) is False
    assert h.state == "healthy" and h.consecutive_failures == 1
    # a response of any kind resets the count while healthy
    h.record_success(2.5, 1.5)
    assert h.consecutive_failures == 0 and h.last_rtt_ms == 2.5
    # the threshold ejects exactly once
    assert h.record_failure("response timeout", 2.0) is False
    assert h.record_failure("response timeout", 2.1) is True
    assert h.state == "ejected" and h.ejections == 1
    assert h.ejected_at == 2.1
    # force_eject while already ejected does not double-count
    h.force_eject("channel dead", 2.2)
    assert h.ejections == 1

    # a straggler success while ejected is NOT readmission
    h.record_success(1.0, 2.3)
    assert h.state == "ejected"

    # probe -> probing; unanswered probe -> back to ejected
    h.begin_probe(3.0)
    assert h.state == "probing" and h.probes_sent == 1
    h.probe_failed("probe unanswered", 3.5)
    assert h.state == "ejected" and h.probes_failed == 1
    assert h.snapshot()["state_code"] == 2

    # probe pong readmits (exactly once) and clears the counters
    h.begin_probe(4.0)
    assert h.readmit(4.2) is True
    assert h.state == "healthy" and h.readmissions == 1
    assert h.consecutive_failures == 0 and h.readmitted_at == 4.2
    assert h.readmit(4.3) is False  # already healthy: no double-count
    assert h.readmissions == 1

    transitions = [t["to"] for t in h.transitions]
    assert transitions == ["ejected", "probing", "ejected", "probing",
                           "healthy"]
    with pytest.raises(ValueError):
        ReplicaHealth(eject_after=0)


# ---------------------------------------------------------------------------
# unit surface: quorum brownout sheds at the front door
# ---------------------------------------------------------------------------
def test_brownout_sheds_low_priority_below_quorum():
    router = FleetRouter(start=False, quorum=2,
                         tenant_priority={"vip": 5},
                         brownout_min_priority=1)
    try:
        # zero healthy replicas < quorum 2: anonymous + low-priority
        # tenants shed with the dedicated (QueueFullError) subclass
        with pytest.raises(BrownoutShedError, match="brownout"):
            router.submit(records=[{"r": 1}])
        with pytest.raises(QueueFullError):
            router.submit(records=[{"r": 1}], tenant="batch-job")
        # a tenant at/above the priority floor still admits
        req = router.submit(records=[{"r": 1}], tenant="vip")
        assert req is not None
        snap = router.snapshot()
        assert snap["shed_brownout"] == 2
        assert snap["healthy_replicas"] == 0 and snap["quorum"] == 2
        health = router.health_snapshot()
        assert health["shed_brownout"] == 2
    finally:
        router.close(timeout_s=5.0)


# ---------------------------------------------------------------------------
# satellite: decode failures are counted and attributed
# ---------------------------------------------------------------------------
def test_fleet_result_decode_error_names_request_and_replica():
    counted = []
    res = FleetResult(
        {"request_id": 41, "instance": "replica-3", "n_rows": 2},
        b"\x80\x05not-a-pickle",
        on_decode_error=lambda: counted.append(1))
    with pytest.raises(FleetDecodeError) as ei:
        _ = res.results
    msg = str(ei.value)
    assert "request 41" in msg and "replica-3" in msg
    assert counted == [1]
    # a decodable payload still round-trips
    from transmogrifai_tpu.fleet import encode_results

    ok = FleetResult({"n_rows": 1}, encode_results([{"p": 0.5}]))
    assert ok.results == [{"p": 0.5}]


def test_router_counts_decode_errors():
    router = FleetRouter(start=False)
    try:
        router._count_decode_error()
        assert router.snapshot()["decode_errors"] == 1
        assert router.health_snapshot()["decode_errors"] == 1
    finally:
        router.close(timeout_s=5.0)


# ---------------------------------------------------------------------------
# satellite: the fleet_health metrics view rides the obs plane
# ---------------------------------------------------------------------------
def test_health_views_extracts_fleet_health_from_metrics_doc():
    from transmogrifai_tpu.obs.fleet import health_views

    doc = {"views": {
        "fleet_router/1": {"rows_ok": 5},
        "fleet_health/1": {"ejections": 2,
                           "replicas": {"replica-0": {"state": "healthy"}}},
        "fleet_health": {"ejections": 0, "replicas": {}},
    }}
    got = dict(health_views(doc))
    assert set(got) == {"fleet_health/1", "fleet_health"}
    assert got["fleet_health/1"]["ejections"] == 2
    assert dict(health_views({"views": {"serving/1": {}}})) == {}
    assert dict(health_views({})) == {}


# ---------------------------------------------------------------------------
# shared registry for the integration drills (one tiny trained model)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def fleet_registry(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("fleet-faults-registry"))
    wf, _data, records, pred_name = tiny_drill_pipeline()
    model = wf.train()
    reg = ModelRegistry(root)
    v1 = reg.publish(model, stage="stable")
    return {"root": root, "records": records, "pred_name": pred_name,
            "v1": v1.version}


def _tcp_controller(fleet_registry, tmp_path, n_replicas, **kw):
    kw.setdefault("router_kw", {})
    kw["router_kw"].setdefault("max_in_flight_per_replica", 2)
    kw["router_kw"].setdefault("max_queue", 64)
    kw.setdefault("transport", "tcp")
    kw.setdefault("max_restarts", 0)
    return FleetController(
        fleet_registry["root"], WORKFLOW_SPEC,
        n_replicas=n_replicas, work_dir=str(tmp_path / "fleet"),
        ship_interval_s=0.15, **kw,
    )


def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() <= deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _run_impairment_drill(fleet_registry, tmp_path, fault_spec):
    """The shared body of the partition and half-open acceptance
    drills: a two-replica loopback-TCP fleet, replica-1 impaired
    mid-serve by ``fault_spec``, pumped traffic throughout.  Asserts
    ejection -> survivors absorb with an exact double-entry ledger ->
    probe readmission, all under ONE trace id; returns the final router
    snapshot + replica-1's worker-side status doc for drill-specific
    asserts."""
    records = fleet_registry["records"]
    batch = records[:24]
    with tracer().span("fleet.fault_drill") as root:
        with _tcp_controller(
            fleet_registry, tmp_path, 2,
            worker_env_overrides={"replica-1": {"TX_FAULTS": fault_spec}},
            router_kw={
                "response_timeout_s": 1.5,
                "eject_after": 1,
                "probe_interval_s": 0.4,
                "probe_timeout_s": 0.8,
            },
        ) as fc:
            assert all(h.transport == "tcp"
                       for h in fc.router.replicas())
            fc.router.score_batch(batch, timeout_s=60.0)  # warm
            delivered: list = []
            errors: list = []
            submitted = [0]
            stop_pump = threading.Event()

            def pump() -> None:
                while not stop_pump.is_set():
                    submitted[0] += 1
                    try:
                        res = fc.router.submit(records=batch).wait(60.0)
                        delivered.append(res.n_rows)
                    except Exception as e:  # noqa: BLE001 - the drill counts
                        errors.append(repr(e))

            threads = [threading.Thread(target=pump) for _ in range(4)]
            for t in threads:
                t.start()
            try:
                # the impairment window opens on replica-1's Nth data
                # send; the router must EJECT it within the silence
                # ceiling while survivors keep serving
                _wait_for(
                    lambda: fc.router.snapshot()["ejections"] >= 1,
                    timeout_s=20.0, what="ejection")
                assert fc.router.handle("replica-1").health.state \
                    != "healthy"
                assert fc.router.score_batch(batch, timeout_s=60.0) \
                    and True  # survivors serve DURING the outage
            finally:
                stop_pump.set()
                for t in threads:
                    t.join(timeout=120.0)

            # heal: the window expires, a probe pong readmits
            _wait_for(
                lambda: fc.router.snapshot()["readmissions"] >= 1
                and fc.router.handle("replica-1").health.state
                == "healthy",
                timeout_s=20.0, what="readmission")

            # EXACT double-entry ledger: every accepted request was
            # answered exactly once - nothing lost, nothing duplicated
            assert errors == []
            assert len(delivered) == submitted[0]
            assert sum(delivered) == submitted[0] * len(batch)
            snap = fc.router.snapshot()
            # +2: the warm batch and the mid-outage survivor batch
            assert snap["rows_ok"] == (submitted[0] + 2) * len(batch)
            assert snap["response_timeouts"] >= 1
            assert snap["ejections"] >= 1
            assert snap["readmissions"] >= 1
            assert snap["probes_sent"] >= 1
            assert snap["requests_failed"] == 0

            # the survivor carried load the whole way through
            assert snap["replicas"]["replica-0"]["rows_ok"] > 0
            assert snap["replicas"]["replica-0"]["health"]["state"] \
                == "healthy"

            # the readmitted replica serves again (post-heal traffic
            # reaches it once its health is green)
            post = fc.router.score_batch(batch, timeout_s=60.0)
            assert len(post) == len(batch)

            # the controller's status doc carries the health columns
            status = fc.status()
            rep1 = status["replicas"]["replica-1"]
            assert rep1["transport"] == "tcp"
            assert rep1["health"] == "healthy"
            assert rep1["ejections"] >= 1 and rep1["readmissions"] >= 1

            # worker-side wire ledger (the impairment happened in the
            # replica's channel): read it over the control plane
            worker_doc = fc.router.control("replica-1", "status",
                                           timeout_s=30.0)
            health_snap = fc.router.health_snapshot()
    # ONE trace id: ejection and readmission events from the router's
    # health/receive threads ride the drill's ambient trace
    events = [r for r in tracer().spans(root.trace_id)
              if r["name"] in ("fleet.ejection", "fleet.readmission")]
    names = {r["name"] for r in events}
    assert names == {"fleet.ejection", "fleet.readmission"}
    assert all(r["trace"] == root.trace_id for r in events)
    return snap, worker_doc, health_snap


# ---------------------------------------------------------------------------
# acceptance drill: partition -> ejection -> heal -> readmission
# ---------------------------------------------------------------------------
def test_tcp_partition_ejects_heals_and_readmits(fleet_registry,
                                                 tmp_path):
    snap, worker_doc, health_snap = _run_impairment_drill(
        fleet_registry, tmp_path,
        "fleet.partition:every=6:times=1:delay=4.0")
    wire = worker_doc["wire"]
    assert wire["partitions"] >= 1
    assert wire["frames_dropped"] >= 1  # frames vanished into the dark
    assert health_snap["replicas"]["replica-1"]["state"] == "healthy"
    assert health_snap["replicas"]["replica-1"]["ejections"] >= 1


# ---------------------------------------------------------------------------
# acceptance drill: half-open (accepts work, never responds)
# ---------------------------------------------------------------------------
def test_tcp_half_open_peer_ejects_heals_and_readmits(fleet_registry,
                                                      tmp_path):
    snap, worker_doc, _health = _run_impairment_drill(
        fleet_registry, tmp_path,
        "fleet.half_open:every=6:times=1:delay=4.0")
    wire = worker_doc["wire"]
    assert wire["half_opens"] >= 1
    assert wire["frames_dropped"] >= 1
    # half-open keeps READING: probes reached the worker but the pongs
    # were eaten, so at least one probe went unanswered before the heal
    assert snap["probes_failed"] >= 1


# ---------------------------------------------------------------------------
# drill: corrupt frame kills the channel; the readmission probe rides
# out a reconnect storm (rate-bounded) and recovers the replica
# ---------------------------------------------------------------------------
def test_corrupt_frame_then_reconnect_storm_recovers(fleet_registry,
                                                     tmp_path):
    records = fleet_registry["records"]
    batch = records[:24]
    with _tcp_controller(
        fleet_registry, tmp_path, 1,
        # the worker corrupts its 3rd data send: the router's receiver
        # raises ChannelProtocolError and force-ejects the replica
        worker_env_overrides={
            "replica-0": {"TX_FAULTS": "channel.corrupt_frame:on=3"}},
        router_kw={
            "response_timeout_s": 5.0,
            "probe_interval_s": 0.4,
            "probe_timeout_s": 2.0,
        },
    ) as fc:
        # sends 1-2 deliver cleanly; the 3rd comes back corrupt
        for _ in range(2):
            assert len(fc.router.score_batch(batch, timeout_s=60.0)) \
                == len(batch)
        # the router-side storm eats the probe's first two reconnects
        _faults.configure("fleet.reconnect_storm:every=1:times=2")
        t0 = time.monotonic()
        recovered = False
        while time.monotonic() - t0 < 60.0 and not recovered:
            try:
                res = fc.router.submit(records=batch).wait(30.0)
                recovered = len(res.results) == len(batch)
            except Exception:  # noqa: BLE001 - outage window: retry
                time.sleep(0.2)
        assert recovered, ("fleet never recovered from the "
                           "corrupt-frame + storm outage")
        snap = fc.router.snapshot()
        assert snap["protocol_errors"] >= 1   # the corrupt frame
        assert snap["replica_deaths"] >= 1    # channel force-eject
        assert snap["probes_failed"] >= 2     # the storm's two drops
        assert snap["readmissions"] >= 1      # and the recovery
        h = fc.router.handle("replica-0")
        assert h.health.state == "healthy"
        # reconnect probing is RATE-BOUNDED: two storm-dropped attempts
        # plus the landing one cannot complete faster than the interval
        assert time.monotonic() - t0 >= 2 * 0.4
        # the replaced channel's wire counters were folded, not zeroed
        assert h.wire_stats()["protocol_errors"] >= 1


# ---------------------------------------------------------------------------
# drill: deadlines ride the wire; a slow peer drops abandoned work
# ---------------------------------------------------------------------------
def test_deadline_rides_wire_and_slow_peer_drops_late_work(
        fleet_registry, tmp_path):
    records = fleet_registry["records"]
    batch = records[:8]
    with _tcp_controller(
        fleet_registry, tmp_path, 1,
        worker_env_overrides={
            "replica-0": {"TX_FAULTS": "fleet.slow_peer:every=1:delay=0.5"}},
    ) as fc:
        fc.router.score_batch(batch, timeout_s=60.0)  # warm (slow)
        # r1 holds the (serial) worker for ~0.5s; r2's 200ms budget is
        # spent in the socket before the worker ever reads it
        r1 = fc.router.submit(records=batch)
        r2 = fc.router.submit(records=batch, deadline_ms=200.0)
        assert len(r1.wait(60.0).results) == len(batch)
        with pytest.raises(DeadlineExceededError, match="replica-0"):
            r2.wait(60.0)
        snap = fc.router.snapshot()
        assert snap["deadline_dropped_remote"] == 1
        assert snap["shed_deadline"] >= 1
        # a deadline drop is evidence of transport LIFE, not a failure:
        # the replica stays healthy and serves on
        h = fc.router.handle("replica-0")
        assert h.health.state == "healthy"
        assert h.health.consecutive_failures == 0
        worker_doc = fc.router.control("replica-0", "status",
                                       timeout_s=30.0)
        assert worker_doc["deadline_dropped"] == 1
        post = fc.router.score_batch(batch, timeout_s=60.0)
        assert len(post) == len(batch)
