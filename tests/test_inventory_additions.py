"""PredictionDeIndexer, vector column history, and the train-time
serializability gate (reference: impl/preparators/PredictionDeIndexer.scala,
OpVectorColumnHistory, ClosureUtils.checkSerializable at OpWorkflow:265-272).
"""
import numpy as np
import pytest

from transmogrifai_tpu.preparators.deindexer import PredictionDeIndexer
from transmogrifai_tpu.types.columns import PredictionColumn, TextColumn
from transmogrifai_tpu.types.dataset import Dataset
from transmogrifai_tpu.types.vector_metadata import VectorColumnMeta, VectorMetadata


def test_prediction_deindexer_roundtrip():
    labels = np.array(["setosa", "versicolor", "setosa", "virginica",
                       "setosa", "versicolor"], dtype=object)
    ds = Dataset({"label": TextColumn(labels, None)})
    # indexed by frequency desc, value asc: setosa=0, versicolor=1, virginica=2
    pred = PredictionColumn(np.array([0.0, 1.0, 2.0, 0.0, 5.0, 1.0]), None, None)
    est = PredictionDeIndexer()
    model = est.fit_model([ds["label"], pred], ds)
    out = model.transform_columns([ds["label"], pred], ds)
    assert list(out.values[:4]) == ["setosa", "versicolor", "virginica", "setosa"]
    assert out.values[4] is None  # unseen index -> None (NoFilter semantics)


def test_vector_column_history():
    meta = VectorMetadata("features", (
        VectorColumnMeta("sex", "PickList", grouping="sex", indicator_value="female"),
        VectorColumnMeta("age", "Real"),
    )).reindexed()
    hist = meta.column_history()
    assert hist[0]["indicatorValue"] == "female"
    assert hist[0]["index"] == 0 and hist[1]["parentFeatureName"] == "age"

    class FakeFeature:
        def history(self):
            return {"originFeatures": ["sex"], "stages": ["OneHot_0"]}

    hist = meta.column_history({"sex": FakeFeature()})
    assert hist[0]["stages"] == ["OneHot_0"]


def test_serializability_gate_rejects_bad_stage():
    from transmogrifai_tpu.workflow.dag import validate_dag
    from transmogrifai_tpu.stages.base import Transformer

    class BadStage(Transformer):
        def __init__(self):
            super().__init__()
            self.bad_state = object()  # not encodable by the model writer

    from types import SimpleNamespace

    s = BadStage()
    s._output = SimpleNamespace(name="bad_out")
    with pytest.raises(ValueError, match="cannot serialize|holds state"):
        validate_dag([[s]])


def test_serializability_gate_rejects_bad_params_and_metadata():
    """save_model also encodes stage.params and stage.metadata, so the
    train-time gate must dry-run those too (a stage passing validate_dag
    must never fail later at save() time)."""
    from types import SimpleNamespace

    from transmogrifai_tpu.stages.base import Transformer
    from transmogrifai_tpu.workflow.dag import validate_dag

    class ParamStage(Transformer):
        pass

    s = ParamStage()
    s._output = SimpleNamespace(name="p_out")
    s.params["callback"] = lambda v: v  # not encodable
    with pytest.raises(ValueError, match="cannot serialize|holds state"):
        validate_dag([[s]])

    s2 = ParamStage()
    s2._output = SimpleNamespace(name="m_out")
    s2.metadata["handle"] = object()
    with pytest.raises(ValueError, match="cannot serialize|holds state"):
        validate_dag([[s2]])


def test_smart_text_map_hashing_dispatch(rng):
    """SmartTextMapVectorizer semantics: high-cardinality free-text map
    keys hash into a shared per-feature space (key-salted tokens) while
    low-cardinality keys still pivot; PickListMap never hashes."""
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.ops.maps import MapVectorizer
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.types.columns import MapColumn
    from transmogrifai_tpu.types.dataset import Dataset

    n = 120
    rows = []
    for i in range(n):
        rows.append({
            "freeform": f"unique text value number {i} with words",
            "status": ("open", "closed")[i % 2],
        })
    ds = Dataset({"m": MapColumn(rows, ft.TextMap)})
    f = FeatureBuilder(ft.TextMap, "m").as_predictor()
    stage = MapVectorizer(max_cardinality=10, hash_dims=16, min_support=1,
                          track_nulls=True).set_input(f)
    model = stage.fit(ds)
    out = model.transform(ds)[stage.output_name]
    # 'freeform' (120 distinct) -> 16 hash dims; 'status' (2) -> pivot
    hash_cols = [c for c in out.metadata.columns
                 if c.descriptor_value and c.descriptor_value.startswith("hash_")]
    assert len(hash_cols) == 16
    assert any(c.grouping == "status" and c.indicator_value == "open"
               for c in out.metadata.columns)
    # hashed block carries signal (non-zero TF counts)
    hash_idx = [i for i, c in enumerate(out.metadata.columns)
                if c.descriptor_value and c.descriptor_value.startswith("hash_")]
    assert np.asarray(out.values[:, hash_idx]).sum() > 0
    # key salting: the SAME word hashed under two different fit-time keys
    # must land on different slots of the shared space
    srows = [{"k1": "signalword", "k2": "other stuff"}
             if i % 2 else {"k1": "filler text", "k2": "signalword"}
             for i in range(100)]
    # force both keys past max_cardinality so both hash
    for i, r in enumerate(srows):
        for k in r:
            r[k] = r[k] + f" unique{i}"
    sds = Dataset({"m": MapColumn(srows, ft.TextMap)})
    sstage = MapVectorizer(max_cardinality=10, hash_dims=32,
                           min_support=1).set_input(f)
    sout = sstage.fit(sds).transform(sds)[sstage.output_name]
    sh_idx = [i for i, c in enumerate(sout.metadata.columns)
              if c.descriptor_value and c.descriptor_value.startswith("hash_")]
    row_k1 = np.asarray(sout.values[0, sh_idx])   # signalword under k1
    row_k2 = np.asarray(sout.values[1, sh_idx])   # signalword under k2
    # without salting, 'signalword' would activate the SAME slot in both
    # rows; with key-salted tokens the activated slots differ
    both_active = (row_k1 > 0) & (row_k2 > 0)
    assert not both_active.any() or not np.array_equal(
        np.nonzero(row_k1)[0].tolist(), np.nonzero(row_k2)[0].tolist()
    )

    # categorical map values never hash, regardless of cardinality
    prows = [{"k": f"cat{i}"} for i in range(n)]
    pds = Dataset({"p": MapColumn(prows, ft.PickListMap)})
    pf = FeatureBuilder(ft.PickListMap, "p").as_predictor()
    pstage = MapVectorizer(max_cardinality=10, min_support=1).set_input(pf)
    pout = pstage.fit(pds).transform(pds)[pstage.output_name]
    assert not any(c.descriptor_value and c.descriptor_value.startswith("hash_")
                   for c in pout.metadata.columns)
