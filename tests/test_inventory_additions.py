"""PredictionDeIndexer, vector column history, and the train-time
serializability gate (reference: impl/preparators/PredictionDeIndexer.scala,
OpVectorColumnHistory, ClosureUtils.checkSerializable at OpWorkflow:265-272).
"""
import numpy as np
import pytest

from transmogrifai_tpu.preparators.deindexer import PredictionDeIndexer
from transmogrifai_tpu.types.columns import PredictionColumn, TextColumn
from transmogrifai_tpu.types.dataset import Dataset
from transmogrifai_tpu.types.vector_metadata import VectorColumnMeta, VectorMetadata


def test_prediction_deindexer_roundtrip():
    labels = np.array(["setosa", "versicolor", "setosa", "virginica",
                       "setosa", "versicolor"], dtype=object)
    ds = Dataset({"label": TextColumn(labels, None)})
    # indexed by frequency desc, value asc: setosa=0, versicolor=1, virginica=2
    pred = PredictionColumn(np.array([0.0, 1.0, 2.0, 0.0, 5.0, 1.0]), None, None)
    est = PredictionDeIndexer()
    model = est.fit_model([ds["label"], pred], ds)
    out = model.transform_columns([ds["label"], pred], ds)
    assert list(out.values[:4]) == ["setosa", "versicolor", "virginica", "setosa"]
    assert out.values[4] is None  # unseen index -> None (NoFilter semantics)


def test_vector_column_history():
    meta = VectorMetadata("features", (
        VectorColumnMeta("sex", "PickList", grouping="sex", indicator_value="female"),
        VectorColumnMeta("age", "Real"),
    )).reindexed()
    hist = meta.column_history()
    assert hist[0]["indicatorValue"] == "female"
    assert hist[0]["index"] == 0 and hist[1]["parentFeatureName"] == "age"

    class FakeFeature:
        def history(self):
            return {"originFeatures": ["sex"], "stages": ["OneHot_0"]}

    hist = meta.column_history({"sex": FakeFeature()})
    assert hist[0]["stages"] == ["OneHot_0"]


def test_serializability_gate_rejects_bad_stage():
    from transmogrifai_tpu.workflow.dag import validate_dag
    from transmogrifai_tpu.stages.base import Transformer

    class BadStage(Transformer):
        def __init__(self):
            super().__init__()
            self.bad_state = object()  # not encodable by the model writer

    from types import SimpleNamespace

    s = BadStage()
    s._output = SimpleNamespace(name="bad_out")
    with pytest.raises(ValueError, match="cannot serialize|holds state"):
        validate_dag([[s]])


def test_serializability_gate_rejects_bad_params_and_metadata():
    """save_model also encodes stage.params and stage.metadata, so the
    train-time gate must dry-run those too (a stage passing validate_dag
    must never fail later at save() time)."""
    from types import SimpleNamespace

    from transmogrifai_tpu.stages.base import Transformer
    from transmogrifai_tpu.workflow.dag import validate_dag

    class ParamStage(Transformer):
        pass

    s = ParamStage()
    s._output = SimpleNamespace(name="p_out")
    s.params["callback"] = lambda v: v  # not encodable
    with pytest.raises(ValueError, match="cannot serialize|holds state"):
        validate_dag([[s]])

    s2 = ParamStage()
    s2._output = SimpleNamespace(name="m_out")
    s2.metadata["handle"] = object()
    with pytest.raises(ValueError, match="cannot serialize|holds state"):
        validate_dag([[s2]])
