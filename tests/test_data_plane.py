"""Data-plane robustness drills (ISSUE 4 tentpole).

Quarantine-mode ingestion across the readers (csv python path, fast_csv
native path, avro, parquet/arrow), strict-mode named errors citing row
indices, the schema contract's capture / artifact round-trip / serve-
time enforcement (SchemaDriftError + drift_policy raise|warn|shed),
distribution-drift scoring, the local-scorer/endpoint empty-batch
parity pin, and the ``reader.*`` / ``serving.schema_drift`` fault
points.
"""
import json
import os

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.readers.avro_reader import (
    AvroReader,
    read_avro_records,
    write_avro_records,
)
from transmogrifai_tpu.readers.csv_reader import CSVReader
from transmogrifai_tpu.readers.fast_csv import (
    fast_path_available,
    read_csv_columnar,
)
from transmogrifai_tpu.schema import (
    DataTelemetry,
    MalformedRowError,
    QuarantineBuffer,
    SchemaContract,
    SchemaDriftError,
    reset_data_telemetry,
)
from transmogrifai_tpu.serialization.model_io import (
    LAST_GOOD_SUFFIX,
    SCHEMA_JSON,
    load_model,
    save_model,
    verify_artifact,
)
from transmogrifai_tpu.serving import (
    RowScoringError,
    ServingTelemetry,
    compile_endpoint,
)
from transmogrifai_tpu.testkit.drills import (
    corrupted_csv_drill,
    tiny_drill_pipeline,
)
from transmogrifai_tpu.testkit.random_data import (
    shift_records,
    write_corrupted_csv,
)
from transmogrifai_tpu.types import feature_types as ft


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.reset()
    reset_data_telemetry()
    yield
    faults.reset()


def _features():
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    return [y, a, c]


# -- CSV quarantine / strict / coerce (tier-1 regression: exact counts) -----

def test_csv_quarantine_counts_are_exact_and_deterministic(tmp_path):
    """Acceptance: quarantine-mode ingest of a corrupted file completes
    without raising and yields exact good/quarantined row counts, twice
    over (deterministic)."""
    path, feats, truth = corrupted_csv_drill(str(tmp_path))
    for _ in range(2):
        reader = CSVReader(path, errors="quarantine")
        ds = reader.generate_dataset(feats)
        assert len(ds) == truth["good_rows"]
        assert reader.quarantine.total == len(truth["bad_rows"])
        assert sorted(q.row_index for q in reader.quarantine.rows) \
            == truth["bad_rows"]
        by_reason = reader.quarantine.by_reason
        assert by_reason["type_flip"] == len(truth["type_flip_rows"])
        assert by_reason["truncated_row"] == len(truth["truncated_rows"])
        # every quarantined row names its reason + a payload excerpt
        for q in reader.quarantine.rows:
            assert q.reason in ("type_flip", "truncated_row")
            assert q.excerpt


def test_csv_strict_raises_naming_first_bad_row(tmp_path):
    path, feats, truth = corrupted_csv_drill(str(tmp_path))
    with pytest.raises(MalformedRowError) as exc:
        CSVReader(path, errors="strict").generate_dataset(feats)
    e = exc.value
    assert e.row_index == truth["bad_rows"][0]
    assert str(e.row_index) in str(e)
    assert e.reason in ("type_flip", "truncated_row")


def test_csv_coerce_mode_is_legacy_unchanged(tmp_path):
    """The default mode must keep every row and silently null junk -
    bit-compatible with the pre-quarantine reader."""
    path, feats, truth = corrupted_csv_drill(str(tmp_path))
    ds = CSVReader(path).generate_dataset(feats)
    assert len(ds) == truth["n_rows"]
    a = ds["a"].to_list()
    for i in truth["type_flip_rows"]:
        assert a[i] is None


def test_csv_quarantine_telemetry_counts_and_export(tmp_path):
    path, feats, truth = corrupted_csv_drill(str(tmp_path))
    tel = DataTelemetry()
    reader = CSVReader(path, errors="quarantine", telemetry=tel)
    reader.generate_dataset(feats)
    snap = tel.snapshot()
    assert snap["rows_read"] == truth["n_rows"]
    assert snap["rows_quarantined"] == len(truth["bad_rows"])
    assert snap["quarantined_by_reason"]["type_flip"] \
        == len(truth["type_flip_rows"])
    out = tel.export(str(tmp_path / "data_metrics.json"))
    assert out["rows_kept"] == truth["good_rows"]
    with open(tmp_path / "data_metrics.json") as f:
        assert json.load(f)["rows_read"] == truth["n_rows"]


def test_quarantine_buffer_is_bounded_but_counts_stay_exact(tmp_path):
    path = str(tmp_path / "many.csv")
    truth = write_corrupted_csv(path, n_rows=300, n_type_flips=120,
                                n_truncated=0, seed=3)
    buf = QuarantineBuffer(max_rows=16, source=path)
    reader = CSVReader(path, errors="quarantine", quarantine=buf)
    ds = reader.generate_dataset(_features())
    assert buf.total == 120          # exact count past the cap
    assert len(buf.rows) == 16       # bounded detail
    assert buf.truncated == 104
    assert len(ds) == truth["good_rows"]


@pytest.mark.skipif(not fast_path_available(),
                    reason="native CSV kernels unavailable")
def test_fast_csv_quarantine_and_strict(tmp_path):
    """The native scanner's own checked path: type flips quarantined at
    chunk speed with global row indices; strict raises named."""
    path = str(tmp_path / "n.csv")
    truth = write_corrupted_csv(path, n_rows=400, n_type_flips=6,
                                n_truncated=0, seed=11)
    schema = {"y": ft.Real, "a": ft.Real}
    buf = QuarantineBuffer(source=path)
    cols = read_csv_columnar(path, schema, errors="quarantine",
                             quarantine=buf)
    assert len(cols["a"].values) == truth["good_rows"]
    assert buf.total == len(truth["type_flip_rows"])
    assert sorted(q.row_index for q in buf.rows) == truth["type_flip_rows"]
    assert all(q.reason == "type_flip" and q.column == "a"
               for q in buf.rows)
    with pytest.raises(MalformedRowError) as exc:
        read_csv_columnar(path, schema, errors="strict")
    assert exc.value.row_index == truth["type_flip_rows"][0]
    # coerce unchanged: junk -> masked missing, all rows present
    legacy = read_csv_columnar(path, schema)
    assert len(legacy["a"].values) == truth["n_rows"]
    assert not legacy["a"].mask[truth["type_flip_rows"]].any()


@pytest.mark.skipif(not fast_path_available(),
                    reason="native CSV kernels unavailable")
def test_device_csv_ingest_quarantine(tmp_path):
    from transmogrifai_tpu.readers.fast_csv import DeviceCSVIngest

    path = str(tmp_path / "d.csv")
    truth = write_corrupted_csv(path, n_rows=200, n_type_flips=4,
                                n_truncated=0, seed=5)
    schema = {"y": ft.Real, "a": ft.Real}
    ing = DeviceCSVIngest(path, ["y", "a"], schema, errors="quarantine")
    X, mask, rows = ing.to_device()
    assert rows == truth["good_rows"]
    assert X.shape == (truth["good_rows"], 2)
    assert ing.quarantine.total == len(truth["type_flip_rows"])
    tel = DataTelemetry()
    with pytest.raises(MalformedRowError):
        DeviceCSVIngest(path, ["y", "a"], schema, errors="strict",
                        telemetry=tel).to_device()
    # strict failures count in the CALLER's accumulator, like every
    # other strict reader path
    assert tel.snapshot()["strict_errors"] == 1


# -- avro quarantine ---------------------------------------------------------

def _avro_file(tmp_path, records):
    schema = {
        "type": "record", "name": "Row",
        "fields": [
            {"name": "y", "type": ["null", "double"]},
            {"name": "a", "type": ["null", "string"]},
            {"name": "c", "type": ["null", "string"]},
        ],
    }
    path = str(tmp_path / "r.avro")
    write_avro_records(path, schema, records, codec="null")
    return path


def test_avro_quarantine_isolates_type_flips(tmp_path):
    recs = [{"y": float(i % 2), "a": str(i * 0.5), "c": "u"}
            for i in range(10)]
    recs[3]["a"] = "garbage!"
    recs[7]["a"] = "also-bad"
    path = _avro_file(tmp_path, recs)
    reader = AvroReader(path, errors="quarantine")
    ds = reader.generate_dataset(_features())
    assert len(ds) == 8
    assert reader.quarantine.total == 2
    assert sorted(q.row_index for q in reader.quarantine.rows) == [3, 7]
    assert all(q.reason == "type_flip" and q.column == "a"
               for q in reader.quarantine.rows)
    # strict names the first offender
    with pytest.raises(MalformedRowError) as exc:
        AvroReader(path, errors="strict").generate_dataset(_features())
    assert exc.value.row_index == 3
    # coerce keeps all rows, junk nulled (legacy)
    ds0 = AvroReader(path).generate_dataset(_features())
    assert len(ds0) == 10
    assert ds0["a"].to_list()[3] is None


def test_avro_truncated_file_quarantines_tail_strict_raises(tmp_path):
    recs = [{"y": 1.0, "a": "1.5", "c": "u"} for _ in range(50)]
    schema = {
        "type": "record", "name": "Row",
        "fields": [
            {"name": "y", "type": ["null", "double"]},
            {"name": "a", "type": ["null", "string"]},
            {"name": "c", "type": ["null", "string"]},
        ],
    }
    path = str(tmp_path / "blocks.avro")
    # small blocks so a chopped tail still leaves clean whole blocks
    write_avro_records(path, schema, recs, codec="null", block_records=16)
    with open(path, "rb") as f:
        data = f.read()
    cut = str(tmp_path / "cut.avro")
    with open(cut, "wb") as f:
        f.write(data[: len(data) - 40])  # chop mid final block
    # coerce (legacy): raw truncation error
    with pytest.raises((EOFError, IndexError, ValueError)):
        read_avro_records(cut)
    # strict: named error
    with pytest.raises(MalformedRowError):
        read_avro_records(cut, errors="strict")
    # quarantine: clean prefix + recorded damage
    buf = QuarantineBuffer(source=cut)
    _schema, recs2 = read_avro_records(cut, errors="quarantine",
                                       quarantine=buf)
    assert 0 < len(recs2) < 50
    assert buf.total == 1
    assert buf.rows[0].reason in ("truncated_block", "corrupt_block")
    # telemetry stays internally consistent through the reader: the
    # lost block counts as read-and-quarantined, and repeated
    # generate_dataset calls must NOT double any count (memoized)
    tel = DataTelemetry()
    reader = AvroReader(cut, errors="quarantine", telemetry=tel)
    ds1 = reader.generate_dataset(_features())
    ds2 = reader.generate_dataset(_features())
    assert len(ds1) == len(ds2) == len(recs2)
    assert reader.quarantine.total == 1
    snap = tel.snapshot()
    assert snap["rows_read"] - snap["rows_kept"] == snap["rows_quarantined"]
    assert snap["rows_quarantined"] \
        == sum(snap["quarantined_by_reason"].values())
    assert snap["reads"] == 1  # second call served from the memo


def test_avro_midfile_corrupt_block_resyncs_to_later_blocks(tmp_path):
    """A bit flip in a MIDDLE block must cost only that block: the
    reader resyncs on the sync marker and keeps every later record —
    not (the pre-review behavior) silently discarding 70% of the file
    while reporting one quarantined row."""
    recs = [{"y": float(i % 2), "a": str(i * 0.5), "c": "u"}
            for i in range(80)]
    schema = {
        "type": "record", "name": "Row",
        "fields": [
            {"name": "y", "type": ["null", "double"]},
            {"name": "a", "type": ["null", "string"]},
            {"name": "c", "type": ["null", "string"]},
        ],
    }
    path = str(tmp_path / "mid.avro")
    write_avro_records(path, schema, recs, codec="deflate",
                       block_records=16)  # 5 blocks
    clean_schema, clean = read_avro_records(path)
    assert len(clean) == 80
    with open(path, "rb") as f:
        data = bytearray(f.read())
    # flip a byte in the middle of the file's payload region
    data[len(data) // 2] ^= 0xFF
    bad_path = str(tmp_path / "mid_bad.avro")
    with open(bad_path, "wb") as f:
        f.write(bytes(data))
    buf = QuarantineBuffer(source=bad_path)
    _s, recovered = read_avro_records(bad_path, errors="quarantine",
                                      quarantine=buf)
    # most of the file survives: only the damaged block's 16 records
    # (plus possibly its neighbor at the resync point) are lost
    assert len(recovered) >= 80 - 32, len(recovered)
    assert buf.total >= 1
    assert all(q.reason in ("corrupt_block", "truncated_block")
               for q in buf.rows)
    # ROLLBACK guarantee: no garbage record decoded off misaligned
    # bytes survives — every recovered record is a well-formed row
    for r in recovered:
        assert set(r) == {"y", "a", "c"}
        assert r["a"] is None or float(r["a"]) >= 0.0


def test_avro_unsupported_codec_is_loud_in_every_mode(tmp_path):
    """An unsupported codec is a configuration error, not block damage:
    quarantine mode must refuse loudly, never resync a valid file into
    zero records."""
    schema = {"type": "record", "name": "R",
              "fields": [{"name": "y", "type": ["null", "double"]}]}
    from transmogrifai_tpu.readers.avro_reader import MAGIC, _Encoder

    head = _Encoder()
    head.write(MAGIC)
    head.write_long(2)
    head.write_string("avro.schema")
    head.write_bytes(json.dumps(schema).encode())
    head.write_string("avro.codec")
    head.write_bytes(b"snappy")
    head.write_long(0)
    head.write(b"S" * 16)
    path = str(tmp_path / "snappy.avro")
    with open(path, "wb") as f:
        f.write(head.getvalue() + b"\x02\x02\x00" + b"S" * 16)
    for mode in ("coerce", "strict", "quarantine"):
        with pytest.raises(ValueError, match="unsupported avro codec"):
            read_avro_records(path, errors=mode,
                              quarantine=QuarantineBuffer(source=path))


def test_parquet_quarantine_string_typed_numeric(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    from transmogrifai_tpu.readers.avro_reader import ParquetReader

    path = str(tmp_path / "p.parquet")
    tbl = pa.table({
        "y": [1.0, 0.0, 1.0, 0.0],
        "a": ["1.5", "junk", "2.5", None],   # string-typed numeric
        "c": ["u", "v", "w", "u"],
    })
    pq.write_table(tbl, path)
    reader = ParquetReader(path, errors="quarantine")
    ds = reader.generate_dataset(_features())
    assert len(ds) == 3
    assert reader.quarantine.total == 1
    assert reader.quarantine.rows[0].row_index == 1
    assert reader.quarantine.rows[0].column == "a"
    with pytest.raises(MalformedRowError) as exc:
        ParquetReader(path, errors="strict").generate_dataset(_features())
    assert exc.value.row_index == 1


def test_arrow_device_ingest_quarantine(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    from transmogrifai_tpu.readers.arrow_ingest import DeviceParquetIngest

    path = str(tmp_path / "d.parquet")
    tbl = pa.table({
        "x0": [1.0, 2.0, 3.0, 4.0],
        "x1": ["0.5", "nope", "1.5", "2.5"],
    })
    pq.write_table(tbl, path)
    ing = DeviceParquetIngest(path, ["x0", "x1"], errors="quarantine")
    X, mask, rows = ing.to_device()
    assert rows == 3
    assert ing.quarantine.total == 1
    assert ing.quarantine.rows[0].column == "x1"
    with pytest.raises(MalformedRowError):
        DeviceParquetIngest(path, ["x0", "x1"],
                            errors="strict").to_device()


def test_bad_errors_mode_is_loud():
    with pytest.raises(ValueError, match="errors must be one of"):
        CSVReader("nope.csv", errors="ignore")


# -- reader fault points -----------------------------------------------------

def test_reader_fault_points_drill_quarantine_path(tmp_path):
    path = str(tmp_path / "clean.csv")
    write_corrupted_csv(path, n_rows=50, n_type_flips=0, n_truncated=0,
                        seed=1)
    faults.configure("reader.malformed_row:on=5 reader.type_flip:on=9")
    reader = CSVReader(path, errors="quarantine")
    ds = reader.generate_dataset(_features())
    assert reader.quarantine.total == 2
    assert len(ds) == 48
    reasons = {q.reason for q in reader.quarantine.rows}
    assert "truncated_row" in reasons  # malformed_row chops a field
    assert "type_flip" in reasons
    faults.reset()
    # strict mode: injected corruption raises named
    faults.configure("reader.type_flip:on=1")
    with pytest.raises(MalformedRowError):
        CSVReader(path, errors="strict").generate_dataset(_features())


# -- schema contract: capture + artifact round-trip --------------------------

@pytest.fixture(scope="module")
def trained():
    wf, data, records, pred_name = tiny_drill_pipeline(n=160)
    model = wf.train()
    return model, data, records, pred_name


def test_contract_captured_at_fit(trained):
    model, _data, _records, _ = trained
    c = model.schema_contract
    assert c is not None
    assert set(c.names) == {"y", "a", "c"}
    spec = c.feature("a")
    assert spec.kind == "numeric" and not spec.is_response
    assert c.feature("y").is_response
    # distributions captured with pinned numeric ranges
    assert c.distributions["a"].value_range is not None
    assert c.distributions["a"].count == 160


def test_contract_roundtrips_in_manifest(tmp_path, trained):
    model, _data, _records, _ = trained
    path = str(tmp_path / "m")
    save_model(model, path)
    # schema.json exists AND is checksummed by the manifest
    assert os.path.exists(os.path.join(path, SCHEMA_JSON))
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    assert SCHEMA_JSON in manifest["files"]
    assert verify_artifact(path) is None
    wf2, _, _, _ = tiny_drill_pipeline(n=160)
    m2 = load_model(path, wf2)
    c2 = m2.schema_contract
    assert c2 is not None
    assert set(c2.names) == {"y", "a", "c"}
    assert c2.distributions["a"].value_range \
        == model.schema_contract.distributions["a"].value_range
    assert np.array_equal(c2.distributions["a"].histogram,
                          model.schema_contract.distributions["a"].histogram)


def test_contract_corruption_fails_checksum_and_recovers(tmp_path,
                                                         trained):
    """Acceptance: the contract survives the last-good recovery path -
    a bit-flipped schema.json fails verification and load falls back."""
    model, _data, _records, _ = trained
    path = str(tmp_path / "m")
    save_model(model, path)
    save_model(model, path)  # second save -> last-good exists
    sp = os.path.join(path, SCHEMA_JSON)
    with open(sp, "r+b") as f:
        f.seek(10)
        f.write(b"X")
    damage = verify_artifact(path)
    assert damage is not None and SCHEMA_JSON in damage
    wf2, _, _, _ = tiny_drill_pipeline(n=160)
    m2 = load_model(path, wf2)  # recovered from last-good
    assert m2.schema_contract is not None
    assert os.path.isdir(path + LAST_GOOD_SUFFIX)


def test_contract_opt_out_and_legacy_artifact(tmp_path):
    wf, _data, _records, _ = tiny_drill_pipeline(n=60)
    wf.set_parameters(schema_contract=False)
    model = wf.train()
    assert model.schema_contract is None
    path = str(tmp_path / "m")
    save_model(model, path)
    assert not os.path.exists(os.path.join(path, SCHEMA_JSON))
    wf2, _data2, _records2, _ = tiny_drill_pipeline(n=60)
    m2 = load_model(path, wf2)
    assert m2.schema_contract is None
    # contract-less models serve with guards disabled, no error
    ep = compile_endpoint(m2, batch_buckets=(4,), drift_policy="raise")
    out = ep.score_batch(_records2[:2])
    assert not any(isinstance(r, RowScoringError) for r in out)


# -- serve-time enforcement ---------------------------------------------------

def test_renamed_column_raises_named_drift_error(trained):
    model, _data, records, _ = trained
    ep = compile_endpoint(model, batch_buckets=(4,), drift_policy="raise")
    bad = [{"a_renamed": r["a"], "c": r["c"]} for r in records[:4]]
    with pytest.raises(SchemaDriftError) as exc:
        ep.score_batch(bad)
    msg = str(exc.value)
    assert "a" in [v["feature"] for v in exc.value.violations]
    assert "missing_column" in msg and "a_renamed" in msg


def test_retyped_column_raises_naming_feature(trained):
    model, _data, records, _ = trained
    ep = compile_endpoint(model, batch_buckets=(4,), drift_policy="raise")
    bad = [dict(records[0], a="a-string-now")]
    with pytest.raises(SchemaDriftError) as exc:
        ep.score_batch(bad)
    v = exc.value.violations[0]
    assert v["kind"] == "type_flip" and v["feature"] == "a"


def test_warn_policy_serves_and_counts(trained):
    model, _data, records, _ = trained
    tel = ServingTelemetry()
    ep = compile_endpoint(model, batch_buckets=(4,), telemetry=tel,
                          drift_policy="warn")
    bad = [{"a_renamed": r["a"], "c": r["c"]} for r in records[:4]]
    out = ep.score_batch(bad)
    assert len(out) == 4  # served anyway ('a' scores as missing)
    snap = tel.snapshot()["data_contract"]
    assert snap["schema_drift_batches"] == 1
    assert snap["violations_by_kind"]["missing_column"] == 1


def test_shed_policy_sheds_without_wedging(trained):
    model, _data, records, _ = trained
    tel = ServingTelemetry()
    ep = compile_endpoint(model, batch_buckets=(4,), telemetry=tel,
                          drift_policy="shed")
    bad = [{"a_renamed": r["a"], "c": r["c"]} for r in records[:4]]
    shed = ep.score_batch(bad)
    assert all(isinstance(r, RowScoringError) and r.shed
               and r.shed_reason == "schema" for r in shed)
    # endpoint is NOT wedged: conformant traffic serves immediately
    ok = ep.score_batch(records[:4])
    assert not any(isinstance(r, RowScoringError) for r in ok)
    snap = tel.snapshot()["data_contract"]
    assert snap["rows_shed_schema"] == 4
    # the breaker is untouched: schema sheds are caller-data problems
    assert ep.breaker.state == "closed"


def test_scheduler_relays_schema_shed_as_drift_error(trained):
    from transmogrifai_tpu.serving import MicroBatchScheduler

    model, _data, records, _ = trained
    tel = ServingTelemetry()
    ep = compile_endpoint(model, batch_buckets=(4,), telemetry=tel,
                          drift_policy="shed")
    with MicroBatchScheduler(ep, start=False, telemetry=tel) as sched:
        req = sched.submit({"a_renamed": 1.0, "c": "u"})
        sched.run_once(wait_timeout_s=0.5)
        with pytest.raises(SchemaDriftError):
            req.wait(1.0)
    assert tel.snapshot()["data_contract"]["shed_schema"] == 1


def test_distribution_shift_yields_nonzero_drift_score(trained):
    """Acceptance: a schema-valid but distribution-shifted batch
    surfaces a nonzero per-feature drift score in the snapshot."""
    model, _data, records, _ = trained
    ep = compile_endpoint(model, batch_buckets=(32,))
    ep.score_batch(records[:96])
    base = ep.drift_scores()["a"]
    ep.score_batch(shift_records(records[:96], "a", delta=30.0))
    snap = ep.telemetry.snapshot()["data_contract"]
    assert snap["drift_js"]["a"]["last"] > base
    assert snap["drift_js"]["a"]["last"] > 0.1
    assert snap["drift_js_max"] >= snap["drift_js"]["a"]["last"]


def test_serving_schema_drift_fault_point(trained):
    model, _data, records, _ = trained
    ep = compile_endpoint(model, batch_buckets=(4,), drift_policy="raise")
    faults.configure("serving.schema_drift:on=1")
    with pytest.raises(SchemaDriftError, match="injected"):
        ep.score_batch(records[:2])
    # burned: next batch clean
    out = ep.score_batch(records[:2])
    assert not any(isinstance(r, RowScoringError) for r in out)


def test_local_scorer_raise_policy_and_default_warn(trained):
    from transmogrifai_tpu.local.scorer import LocalScorer

    model, _data, records, _ = trained
    strict = LocalScorer(model, drift_policy="raise")
    with pytest.raises(SchemaDriftError):
        strict.score_batch([{"a_renamed": 1.0, "c": "u"}])
    # default (warn) still scores
    lenient = LocalScorer(model)
    out = lenient.score_batch([{"a": 0.5, "c": "u"}])
    assert len(out) == 1


def test_empty_batch_parity_endpoint_vs_scorer(trained):
    """Satellite bugfix pin: empty (all-rows-quarantined) input returns
    an empty result + a telemetry count from BOTH serve surfaces, never
    an exception."""
    model, _data, _records, _ = trained
    scorer = model.score_function()
    assert scorer.score_batch([]) == []
    tel = ServingTelemetry()
    ep = compile_endpoint(model, batch_buckets=(4,), telemetry=tel)
    assert ep.score_batch([]) == []
    assert tel.snapshot()["data_contract"]["empty_batches"] == 1
