"""Degraded-mode distributed training drills (ISSUE 3 tentpole).

The collective watchdog + peer health + shrink-to-survivors recovery in
parallel/resilience.py, exercised end to end against the named fault
points (SURVEY §5.3/§5.8 - the one subsystem that previously had zero
failure handling):

* ``collective.delay``  -> straggler: ONE retry with an extended deadline
* ``mesh.peer_hang``    -> the retry stalls too: escalate to shrink
* ``mesh.peer_die``     -> dead peer: no retry, survivor recompute,
                           result parity with the uninterrupted run
* ``mesh.init_no_coordinator`` / a genuinely unreachable address ->
  ``initialize()`` raises MeshBootstrapError within
  TX_MESH_INIT_TIMEOUT_S, never hangs (armed in-process AND via the
  TX_FAULTS env in a child, proving the zero-code-change drill path)

plus the file-based PeerHealth hang-once / die-once child drills
(testkit/drills.py templates) and the telemetry surfacing contracts.
Collective drills run on the in-process 8-device CPU mesh (conftest), so
nothing here needs cross-process collectives; the child drills are
jax-free on purpose.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.parallel import distributed as dist
from transmogrifai_tpu.parallel import resilience
from transmogrifai_tpu.parallel.resilience import (
    CollectiveStallError,
    CollectiveWatchdog,
    DeadlinePolicy,
    MeshTelemetry,
    PeerHealth,
)
from transmogrifai_tpu.testkit.drills import (
    MESH_BOOTSTRAP_CHILD_TEMPLATE,
    MESH_PEER_CHILD_TEMPLATE,
    drill_env,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm(monkeypatch):
    """Every drill arms injection explicitly; none may leak - and the
    process-global telemetry/watchdog must not carry events across
    tests (summary_json would surface them elsewhere)."""
    monkeypatch.delenv("TX_MESH_WATCHDOG", raising=False)
    faults.reset()
    resilience.reset_mesh_telemetry()
    yield
    faults.reset()
    resilience.reset_mesh_telemetry()


def _moments(x):
    return x.sum(axis=0), (x * x).sum(axis=0)


@pytest.fixture
def mesh_setup(rng):
    mesh = dist.global_mesh(("data",))
    n = 16 * mesh.devices.size
    X = rng.randn(n, 5).astype(np.float32)
    tel = MeshTelemetry()
    wd = CollectiveWatchdog(
        telemetry=tel,
        policy=DeadlinePolicy(floor_s=0.05, ceiling_s=30.0, factor=4.0),
    )
    step = lambda: dist.all_reduce_stats(_moments, mesh, X)  # noqa: E731
    shrink = lambda: dist.all_reduce_stats(  # noqa: E731
        _moments, resilience.survivor_mesh(("data",)), X)
    baseline = tuple(np.asarray(v) for v in step())
    return wd, tel, step, shrink, baseline


def _parity(result, baseline):
    for got, want in zip(result, baseline):
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


# -- deadline policy ---------------------------------------------------------

def test_deadline_policy_clamps_and_tracks_p99():
    p = DeadlinePolicy(floor_s=1.0, ceiling_s=10.0, factor=4.0)
    # no observations yet: a cold compile must never be killed early
    assert p.deadline_s() == 10.0
    for _ in range(50):
        p.observe(0.1)
    assert p.deadline_s() == pytest.approx(1.0)  # 0.4 clamped to floor
    for _ in range(50):
        p.observe(1.0)
    assert 3.9 <= p.deadline_s() <= 4.1  # p99*4
    for _ in range(50):
        p.observe(100.0)
    assert p.deadline_s() == 10.0  # ceiling


# -- the watchdog state machine ----------------------------------------------

def test_healthy_step_is_transparent_and_observed(mesh_setup):
    wd, tel, step, _shrink, baseline = mesh_setup
    out = wd.run("drill.moments", step)
    _parity(out, baseline)
    snap = tel.snapshot()
    assert snap["collectives_ok"] == 1
    assert snap["detections"] == 0 and snap["shrinks"] == 0
    assert snap["step_ms"]["p99"] is not None


def test_straggler_gets_one_extended_retry(mesh_setup):
    wd, tel, step, shrink, baseline = mesh_setup
    wd.run("drill.moments", step)  # warm the jit cache: retry is fast
    faults.configure("collective.delay:on=1:delay=0.5")
    out = wd.run("drill.moments", step, shrink_fn=shrink, deadline_s=0.15)
    _parity(out, baseline)
    snap = tel.snapshot()
    assert snap["detections"] == 1
    assert snap["straggler_retries"] == 1 and snap["retries_ok"] == 1
    assert snap["shrinks"] == 0  # the retry recovered: no shrink needed
    detect = [e for e in snap["events"] if e["event"] == "detect"][0]
    assert detect["classification"] == "straggler"
    retry = [e for e in snap["events"] if e["event"] == "retry"][0]
    assert retry["ok"] and retry["deadline_s"] == pytest.approx(0.3)


def test_peer_hang_escalates_past_retry_to_shrink(mesh_setup):
    wd, tel, step, shrink, baseline = mesh_setup
    wd.run("drill.moments", step)  # warm
    # every armed call stalls: the straggler retry stalls too
    faults.configure("mesh.peer_hang:every=1:times=2:delay=1.5")
    t0 = time.perf_counter()
    out = wd.run("drill.moments", step, shrink_fn=shrink, deadline_s=0.1)
    wall = time.perf_counter() - t0
    _parity(out, baseline)
    snap = tel.snapshot()
    assert snap["detections"] == 1
    assert snap["straggler_retries"] == 1 and snap["retries_ok"] == 0
    assert snap["shrinks"] == 1
    assert wall < 5.0  # bounded: deadline + retry + recompute, not 2x1.5s


def test_peer_die_shrinks_without_retry(mesh_setup):
    wd, tel, step, shrink, baseline = mesh_setup
    # the dying peer marks itself then stalls briefly: detection is
    # driven by the death, not by deadline tuning (deadline stays huge)
    faults.configure("mesh.peer_die:on=1:delay=0.05")
    out = wd.run("drill.moments", step, shrink_fn=shrink, deadline_s=30.0)
    _parity(out, baseline)
    snap = tel.snapshot()
    assert snap["detections"] == 1
    assert snap["straggler_retries"] == 0  # dead peer: no pointless retry
    assert snap["shrinks"] == 1
    detect = [e for e in snap["events"] if e["event"] == "detect"][0]
    assert detect["classification"] == "dead_peer"
    assert detect["dead_peers"] == ["injected"]
    assert snap["shrink_recompute_ms"]["p99"] is not None


def test_stall_without_shrink_path_raises_named_error(mesh_setup):
    wd, tel, step, _shrink, _baseline = mesh_setup
    faults.configure("mesh.peer_die:on=1:delay=0.05")
    with pytest.raises(CollectiveStallError, match="dead_peer"):
        wd.run("drill.moments", step, deadline_s=30.0)
    assert tel.snapshot()["shrink_failures"] == 1


def test_wedged_survivor_route_fails_loudly_not_hangs():
    """'Never wedge the caller' must hold even when the survivor
    recompute ITSELF is broken: the shrink runs in a bounded worker
    (ceiling deadline) and a stalled one raises, never hangs."""
    tel = MeshTelemetry()
    wd = CollectiveWatchdog(telemetry=tel, policy=DeadlinePolicy(
        floor_s=0.05, ceiling_s=0.3, factor=4.0))
    faults.configure("mesh.peer_die:on=1:delay=0.05")

    def wedged_shrink():
        time.sleep(5.0)
        return 1

    t0 = time.perf_counter()
    with pytest.raises(CollectiveStallError, match="survivor recompute"):
        wd.run("drill.sum", lambda: 1, shrink_fn=wedged_shrink,
               deadline_s=30.0)
    assert time.perf_counter() - t0 < 3.0  # bounded by the 0.3s ceiling
    assert tel.snapshot()["shrink_failures"] == 1


def test_nested_guards_run_inline():
    """A guarded fit inside a guarded validator step must not stack a
    second watchdog thread/deadline (one deadline per collective)."""
    tel = MeshTelemetry()
    wd = CollectiveWatchdog(telemetry=tel, policy=DeadlinePolicy(
        floor_s=0.05, ceiling_s=30.0, factor=4.0))

    def outer():
        return resilience.guarded_collective(
            "inner", lambda: 42, watchdog=wd)

    assert wd.run("outer", outer) == 42
    assert tel.snapshot()["collectives_ok"] == 1  # outer only


# -- peer health: hang-once / die-once child drills --------------------------

def _spawn_peer(tmp_path, mode: str, beats: int = 3, interval: float = 0.1,
                exit_code: int = 9):
    hb_dir = str(tmp_path / "hb")
    script = tmp_path / f"peer_{mode}.py"
    script.write_text(MESH_PEER_CHILD_TEMPLATE.format(
        repo=REPO, hb_dir=hb_dir, peer_id=1, beats=beats,
        interval=interval, mode=mode, exit_code=exit_code,
    ))
    proc = subprocess.Popen([sys.executable, str(script)], env=drill_env())
    return hb_dir, proc


def _wait_for_dead(ph: PeerHealth, timeout_s: float = 30.0) -> list:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        dead = ph.dead_peers()
        if dead:
            return dead
        time.sleep(0.05)
    return []


def test_peer_health_detects_die_once_child(tmp_path):
    hb_dir, proc = _spawn_peer(tmp_path, "die")
    try:
        ph = PeerHealth(hb_dir, process_id=0, stale_after_s=0.6)
        ph.beat()
        proc.wait(timeout=60)
        assert proc.returncode == 9  # really died
        assert _wait_for_dead(ph) == [1]
        assert ph.survivors() == [0]
        assert 1 in ph.peers()  # the corpse's last beat is still visible
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def test_peer_health_detects_hang_once_child(tmp_path):
    """A hung peer (alive, beatless) is as dead as a dead one: it will
    never finish the collective."""
    hb_dir, proc = _spawn_peer(tmp_path, "hang", beats=2)
    try:
        ph = PeerHealth(hb_dir, process_id=0, stale_after_s=0.6)
        ph.beat()
        assert _wait_for_dead(ph) == [1]
        assert proc.poll() is None  # hung, not dead - same classification
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def test_watchdog_classifies_stale_heartbeat_as_dead_peer(tmp_path, rng):
    """With PeerHealth attached, a stall plus a stale peer heartbeat
    skips the straggler retry and goes straight to the survivor
    recompute."""
    hb_dir = str(tmp_path / "hb")
    ph = PeerHealth(hb_dir, process_id=0, stale_after_s=5.0)
    ph.beat()
    # peer 1 last beat 100s ago: stale long before any drill timing
    stale_path = ph.path_for(1)
    with open(stale_path, "w"):
        pass
    past = time.time() - 100.0
    os.utime(stale_path, (past, past))
    tel = MeshTelemetry()
    wd = CollectiveWatchdog(telemetry=tel, peer_health=ph)
    faults.configure("mesh.peer_hang:on=1:delay=1.0")
    out = wd.run("drill.sum", lambda: 7, shrink_fn=lambda: 7,
                 deadline_s=0.1)
    assert out == 7
    snap = tel.snapshot()
    assert snap["straggler_retries"] == 0
    detect = [e for e in snap["events"] if e["event"] == "detect"][0]
    assert detect["classification"] == "dead_peer"
    assert detect["dead_peers"] == [1]
    shrink = [e for e in snap["events"] if e["event"] == "shrink"][0]
    assert shrink["survivors"] == 1  # only this process still beats


def test_peer_health_clamps_skewed_clocks():
    """A peer heartbeat stamped in the future must read staleness 0, not
    negative (supervisor.staleness clamp) - never 'fresher than now'."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ph = PeerHealth(td, process_id=0)
        ph.beat()
        future = time.time() + 120.0
        os.utime(ph.path_for(0), (future, future))
        s = ph.staleness_by_peer()[0]
        assert s == 0.0


# -- the validator's guarded CV-fold collective ------------------------------

def test_validator_mesh_fit_shrinks_to_survivor_parity(rng, monkeypatch):
    """The CV fold x grid fit over the 8-device mesh, with the peer dying
    mid-collective: the watchdog (auto-armed by the mesh.* fault point)
    must shrink to the single-host recompute and reach the SAME
    selection as an undisturbed unsharded run."""
    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.selector.factories import lr_grid
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    n, d = 1999, 12
    X = rng.randn(n, d).astype(np.float32)
    beta = rng.randn(d)
    y = (rng.rand(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(np.float64)
    ev = OpBinaryClassificationEvaluator()

    def run():
        cv = OpCrossValidation(num_folds=3, evaluator=ev, stratify=True)
        return cv.validate([(OpLogisticRegression(), lr_grid())], X, y)

    monkeypatch.setenv("TX_PRODUCT_MESH", "0")
    res_single = run()
    monkeypatch.setenv("TX_PRODUCT_MESH", "1")
    # the dying peer marks itself and stalls briefly; the huge default
    # deadline never fires early, so this is timing-insensitive
    faults.configure("mesh.peer_die:on=1:delay=0.1")
    res_shrunk = run()
    snap = resilience.mesh_telemetry().snapshot()
    assert snap["shrinks"] == 1, snap["events"]
    assert res_shrunk.best_params == res_single.best_params
    np.testing.assert_allclose(
        res_shrunk.best_metric, res_single.best_metric, rtol=1e-5
    )
    for a, b in zip(res_shrunk.all_results, res_single.all_results):
        np.testing.assert_allclose(
            a["fold_metrics"], b["fold_metrics"], rtol=1e-5, atol=1e-7
        )


# -- bootstrap deadline ------------------------------------------------------

def test_initialize_bootstrap_deadline_with_injected_absent_coordinator(
        monkeypatch):
    monkeypatch.setenv("TX_MESH_INIT_TIMEOUT_S", "0.4")
    faults.configure("mesh.init_no_coordinator:on=1:delay=60")
    t0 = time.time()
    with pytest.raises(dist.MeshBootstrapError, match="coordinator"):
        dist.initialize(coordinator_address="203.0.113.1:65000",
                        num_processes=2, process_id=0)
    assert time.time() - t0 < 10.0  # bounded, nowhere near the 60s hang
    assert dist._initialized is False  # failure must not latch
    # recorded as a bootstrap event in the global telemetry
    snap = resilience.mesh_telemetry().snapshot()
    assert snap["bootstrap_timeouts"] == 1


def _run_bootstrap_child(tmp_path, addr: str, env_extra: dict,
                         timeout: int = 180):
    script = tmp_path / "bootstrap.py"
    script.write_text(
        MESH_BOOTSTRAP_CHILD_TEMPLATE.format(repo=REPO, addr=addr))
    env = dict(drill_env(), **env_extra)
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, timeout=timeout,
        capture_output=True, text=True,
    )
    return proc


def test_initialize_bootstrap_deadline_env_armed_child(tmp_path):
    """TX_FAULTS in the child env arms the drill with zero code changes
    (the injection framework's import-time arming contract)."""
    proc = _run_bootstrap_child(
        tmp_path, "203.0.113.1:65000",
        {"TX_FAULTS": "mesh.init_no_coordinator:on=1:delay=600",
         "TX_MESH_INIT_TIMEOUT_S": "2"},
    )
    assert proc.returncode == 42, proc.stdout + proc.stderr
    assert "MESH_BOOTSTRAP_ERROR" in proc.stdout


def test_initialize_unreachable_coordinator_never_hangs(tmp_path):
    """A genuinely bogus coordinator address (TEST-NET-3, blackholed on
    most networks) must fail loudly within the deadline: either the
    named MeshBootstrapError (dial hangs -> deadline) or the backend's
    own immediate connection error (dial refused) - NEVER an indefinite
    hang (the subprocess timeout is the hang detector)."""
    proc = _run_bootstrap_child(
        tmp_path, "203.0.113.1:65000", {"TX_MESH_INIT_TIMEOUT_S": "3"},
    )
    assert proc.returncode in (42, 43), proc.stdout + proc.stderr


# -- telemetry surfacing -----------------------------------------------------

def test_mesh_events_surface_in_stage_metrics_and_export(tmp_path):
    from transmogrifai_tpu.utils.tracing import AppMetrics

    run_metrics = AppMetrics()  # the run the degradation happens in
    tel = resilience.mesh_telemetry()
    wd = CollectiveWatchdog(telemetry=tel)
    faults.configure("mesh.peer_die:on=1:delay=0.05")
    wd.run("drill.sum", lambda: 1, shrink_fn=lambda: 1, deadline_s=30.0)
    # AppMetrics.to_json (what model.summary_json embeds) carries the
    # events of ITS OWN window...
    mj = run_metrics.to_json()
    assert [e["event"] for e in mj["mesh_resilience_events"]] == [
        "detect", "shrink"]
    # ...while a LATER run in the same process must not inherit another
    # run's degradation report (per-run scoping)
    time.sleep(0.01)
    assert "mesh_resilience_events" not in AppMetrics().to_json()
    # and the JSON artifact export has the ServingTelemetry-style shape
    out = tel.export(str(tmp_path / "mesh.json"), extra={"drill": True})
    assert out["shrinks"] == 1 and out["drill"] is True
    import json

    on_disk = json.load(open(tmp_path / "mesh.json"))
    assert on_disk["detections"] == 1
    assert set(on_disk["step_ms"]) == {"p50", "p95", "p99"}
