"""GENUINE multi-process distributed-runtime test.

VERDICT r2 weak #7: the multi-host path had only ever run as
single-process no-ops.  Here TWO separate processes (2 virtual CPU
devices each -> a 4-device global mesh, Gloo collectives) exercise the
real contracts:

* parallel.distributed.initialize with explicit coordinator args,
* global_mesh spanning both processes,
* host_local_to_global: per-process row blocks -> one globally-sharded
  array (jax.make_array_from_process_local_data),
* all_reduce_stats: cross-process psum-lowered reductions match the
  full-data answer,
* fused_moments_sharded on a device-resident global array matches
  single-process moments, and its host-resident-input guard raises,
* forest fold fits over cross-process row shards are bit-identical to
  the single-process heaps,
* (round 5) the MXU-packed shard_map Gram runs with 'data' spanning the
  process boundary - its psum crosses hosts over Gloo - and matches the
  single-process vmap route's coefficients,
* (this round, VERDICT r5 next #9) FOUR processes of one device each form
  a 2x2 ('data', 'replica') mesh - coordinator address via the
  JAX_COORDINATOR_ADDRESS env half of the bootstrap - and the packed
  Gram + GBT fold fits match the single-process answers.

Hosts whose jax CPU backend lacks cross-process collectives ("Multiprocess
computations aren't implemented on the CPU backend") skip rather than
fail: the contract is exercised wherever the runtime supports it.
"""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = '''
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, {repo!r})

from transmogrifai_tpu.parallel.distributed import (
    all_reduce_stats, global_mesh, host_local_to_global, initialize)

initialize(coordinator_address=f"localhost:{{port}}", num_processes=2,
           process_id=pid)

import jax
import jax.numpy as jnp
import numpy as np

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()

mesh = global_mesh(("data",))
assert mesh.devices.size == 4

# deterministic full dataset known to BOTH processes; each contributes
# its own half through the reader hand-off
rng = np.random.RandomState(0)
X_full = rng.randn(40, 5).astype(np.float32)
y_full = (rng.rand(40) > 0.5).astype(np.float32)
lo, hi = (0, 20) if pid == 0 else (20, 40)
Xg = host_local_to_global(X_full[lo:hi], mesh)
yg = host_local_to_global(y_full[lo:hi], mesh)
assert Xg.shape == (40, 5)  # global shape, process-local shards

# cross-process reduction == full-data answer
col_sums = all_reduce_stats(lambda a: a.sum(axis=0), mesh, X_full)
assert np.allclose(np.asarray(col_sums), X_full.sum(axis=0), atol=1e-4)

# the SanityChecker moments kernel over the global mesh
from transmogrifai_tpu.parallel.pallas_kernels import (
    fused_moments, fused_moments_sharded)

got = fused_moments_sharded(Xg, yg, mesh)
want = fused_moments(jnp.asarray(X_full), jnp.asarray(y_full))
for g, w in zip(got, want):
    assert np.allclose(np.asarray(g), np.asarray(w), atol=1e-4), (g, w)

# host-resident input on a multi-process runtime must raise loudly
try:
    fused_moments_sharded(X_full[lo:hi], y_full[lo:hi], mesh)
    raise AssertionError("host-resident guard did not fire")
except ValueError as e:
    assert "multi-process" in str(e)

# ---- tree fold fits over the cross-process mesh ------------------------
# the forest CV kernel must give the single-process answer when its
# row-sharded inputs span both processes (gini count channels are small
# integers, so the sharded segment-sums are exact -> heaps bit-identical)
from jax.sharding import NamedSharding, PartitionSpec as P
from transmogrifai_tpu.models.tree_kernel import (
    bin_data, fit_forest_folds, quantile_bin_edges)

edges = quantile_bin_edges(X_full, 8)
bins_full = bin_data(X_full, edges)
classes = np.array([0.0, 1.0])
onehot = (y_full[:, None] == classes[None, :]).astype(np.float32)
stats_full = np.concatenate([np.ones((40, 1), np.float32), onehot], axis=1)
W_full = np.stack([
    np.r_[np.ones(30, np.float32), np.zeros(10, np.float32)],
    np.r_[np.zeros(10, np.float32), np.ones(30, np.float32)],
])
T, d = 3, X_full.shape[1]
boot_full = np.ones((T, 40), np.float32)
feat_masks = jnp.ones((T, d), dtype=bool)
keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(T))

def to_global(local, spec, m=None):
    return jax.make_array_from_process_local_data(
        NamedSharding(m or mesh, P(*spec)), local)

heaps_g = fit_forest_folds(
    to_global(bins_full[lo:hi], ("data", None)),
    to_global(stats_full[lo:hi], ("data", None)),
    to_global(W_full[:, lo:hi], (None, "data")),
    to_global(boot_full[:, lo:hi], (None, "data")),
    feat_masks, keys,
    max_depth=3, max_bins=8, impurity_kind="gini", n_stats=3,
    min_instances_per_node=1.0, min_info_gain=0.0,
)
heaps_l = fit_forest_folds(
    jnp.asarray(bins_full), jnp.asarray(stats_full), jnp.asarray(W_full),
    jnp.asarray(boot_full), feat_masks, keys,
    max_depth=3, max_bins=8, impurity_kind="gini", n_stats=3,
    min_instances_per_node=1.0, min_info_gain=0.0,
)
for hg, hl in zip(heaps_g, heaps_l):
    # replicate the (possibly sharded) global output so every process can
    # materialize it: jitted identity with replicated out_shardings
    rep = jax.jit(
        lambda a: a, out_shardings=NamedSharding(mesh, P())
    )(hg)
    assert np.array_equal(np.asarray(rep), np.asarray(hl)), \
        "sharded tree heaps differ"

# ---- round-5 packed shard_map Gram spanning BOTH processes -------------
# the MXU-packed CV route's psum('data') must cross the process boundary
# (Gloo) and agree with the single-process vmap route
from transmogrifai_tpu.models.logistic_regression import _lr_fit_batched
from transmogrifai_tpu.models.packed_newton import lr_fit_batched_packed

# axis order ("data", "replica"): jax.devices() lists process 0's
# devices first, so the LEADING mesh axis is the process boundary -
# 'data' must sit there (rows split across hosts, DCN psum) while
# 'replica' stays within each host (ICI).  Each process then supplies
# its devices' shards: its own row block, ALL replica rows of W for
# those rows, and the full replica-sharded scalars.
mesh_rd = global_mesh(("data", "replica"), shape=(2, 2))
B = 4
# DISTINCT weight masks and regs per replica: identical replicas could
# not detect a replica-shard permutation (review r5)
W_lr_full = np.stack([
    np.r_[np.ones(30, np.float32), np.zeros(10, np.float32)],
    np.r_[np.zeros(10, np.float32), np.ones(30, np.float32)],
    np.r_[np.ones(20, np.float32), np.zeros(20, np.float32)],
    np.ones(40, np.float32),
])
regs_full = np.asarray([0.003, 0.01, 0.03, 0.1], np.float32)
ens_full = np.asarray([0.0, 0.2, 0.0, 0.5], np.float32)
Xp = to_global(X_full[lo:hi], ("data", None), mesh_rd)
yp = to_global(y_full[lo:hi], ("data",), mesh_rd)
Wp = to_global(W_lr_full[:, lo:hi], ("replica", "data"), mesh_rd)
rp = to_global(regs_full, ("replica",), mesh_rd)
ep = to_global(ens_full, ("replica",), mesh_rd)
bp, ip = lr_fit_batched_packed(
    Xp, yp, Wp, rp, ep, iters=6, hess_bf16=False, mesh=mesh_rd,
)
bv, iv = _lr_fit_batched(
    jnp.asarray(X_full), jnp.asarray(y_full), jnp.asarray(W_lr_full),
    jnp.asarray(regs_full), jnp.asarray(ens_full), iters=6,
)
rep_b = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh_rd, P()))(bp)
rep_i = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh_rd, P()))(ip)
assert np.allclose(np.asarray(rep_b), np.asarray(bv), atol=5e-4), \
    np.abs(np.asarray(rep_b) - np.asarray(bv)).max()
assert np.allclose(np.asarray(rep_i), np.asarray(iv), atol=5e-4)

print(f"proc {{pid}} OK", flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


_NO_MULTIPROC = "Multiprocess computations aren't implemented"


def _run_workers(tmp_path, worker_src: str, n_procs: int,
                 timeout: int = 280) -> list[str]:
    """Spawn n worker processes; returns their outputs.  Skips the test
    when the host's jax CPU backend cannot run cross-process collectives
    (environment capability, not a code defect)."""
    script = tmp_path / "worker.py"
    script.write_text(worker_src.format(repo=REPO))
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:  # a wedged worker must not outlive the test
            if p.poll() is None:
                p.kill()
                p.wait()
    if any(_NO_MULTIPROC in out for out in outs):
        pytest.skip("jax CPU backend lacks multiprocess collectives here")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK" in out
    return outs


def test_two_process_mesh_and_moments(tmp_path):
    _run_workers(tmp_path, WORKER, 2)


WORKER4 = '''
import os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
# the env half of the bootstrap contract: the coordinator address rides
# JAX_COORDINATOR_ADDRESS (what a pod launcher exports); process count/id
# stay explicit because this jax version auto-detects them only from
# cluster schedulers (SLURM/OMPI), not generic env vars
os.environ["JAX_COORDINATOR_ADDRESS"] = f"localhost:{{port}}"
sys.path.insert(0, {repo!r})

from transmogrifai_tpu.parallel.distributed import global_mesh, initialize

initialize(num_processes=4, process_id=pid)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

assert jax.process_count() == 4, jax.process_count()
assert jax.device_count() == 4, jax.device_count()

# 2x2 ('data', 'replica') mesh: jax.devices() orders by process, so the
# LEADING axis pairs processes {{0,1}} vs {{2,3}} - 'data' must span that
# boundary (row psums cross it) while 'replica' splits within each pair
mesh = global_mesh(("data", "replica"), shape=(2, 2))
assert mesh.devices.shape == (2, 2)
data_idx, replica_idx = pid // 2, pid % 2
lo, hi = (0, 20) if data_idx == 0 else (20, 40)

rng = np.random.RandomState(0)
X_full = rng.randn(40, 6).astype(np.float32)
y_full = (rng.rand(40) > 0.5).astype(np.float32)


def to_global(local, spec, m=None):
    return jax.make_array_from_process_local_data(
        NamedSharding(m or mesh, P(*spec)), local)


def replicated(a, m=None):
    return np.asarray(jax.jit(
        lambda x: x, out_shardings=NamedSharding(m or mesh, P())
    )(a))


# ---- MXU-packed Gram over the 2x2 mesh, psum crossing 4 processes ------
from transmogrifai_tpu.models.logistic_regression import _lr_fit_batched
from transmogrifai_tpu.models.packed_newton import lr_fit_batched_packed

# DISTINCT weights/regs per replica so a replica-shard permutation or a
# dropped psum contribution cannot cancel out
W_lr_full = np.stack([
    np.r_[np.ones(30, np.float32), np.zeros(10, np.float32)],
    np.r_[np.zeros(10, np.float32), np.ones(30, np.float32)],
    np.r_[np.ones(20, np.float32), np.zeros(20, np.float32)],
    np.ones(40, np.float32),
])
regs_full = np.asarray([0.003, 0.01, 0.03, 0.1], np.float32)
ens_full = np.asarray([0.0, 0.2, 0.0, 0.5], np.float32)
r0 = 2 * replica_idx  # this process's replica rows [r0, r0+2)
Xp = to_global(X_full[lo:hi], ("data", None))
yp = to_global(y_full[lo:hi], ("data",))
Wp = to_global(W_lr_full[r0:r0 + 2, lo:hi], ("replica", "data"))
rp = to_global(regs_full[r0:r0 + 2], ("replica",))
ep = to_global(ens_full[r0:r0 + 2], ("replica",))
bp, ip = lr_fit_batched_packed(
    Xp, yp, Wp, rp, ep, iters=6, hess_bf16=False, mesh=mesh,
)
bv, iv = _lr_fit_batched(
    jnp.asarray(X_full), jnp.asarray(y_full), jnp.asarray(W_lr_full),
    jnp.asarray(regs_full), jnp.asarray(ens_full), iters=6,
)
assert np.allclose(replicated(bp), np.asarray(bv), atol=5e-4), \\
    np.abs(replicated(bp) - np.asarray(bv)).max()
assert np.allclose(replicated(ip), np.asarray(iv), atol=5e-4)

# ---- GBT fold fits row-sharded over all four processes -----------------
# the boosting scan's level-histogram segment sums psum over 'data'; the
# chunked margin carry must survive 4-way Gloo sharding bit-compatibly
from transmogrifai_tpu.models.tree_kernel import (
    bin_data, fit_gbt_folds, quantile_bin_edges)

mesh_d = global_mesh(("data",))
qlo, qhi = pid * 10, (pid + 1) * 10  # row quarter per process
edges = quantile_bin_edges(X_full, 8)
bins_full = bin_data(X_full, edges)
W_full = np.stack([
    np.r_[np.ones(30, np.float32), np.zeros(10, np.float32)],
    np.r_[np.zeros(10, np.float32), np.ones(30, np.float32)],
])
kw = dict(num_trees=4, max_depth=3, max_bins=8, is_classification=True,
          step_size=jnp.asarray(0.3),
          min_instances_per_node=jnp.asarray(1.0),
          min_info_gain=jnp.asarray(0.0))
f0_g, heaps_g = fit_gbt_folds(
    to_global(bins_full[qlo:qhi], ("data", None), mesh_d),
    to_global(y_full[qlo:qhi], ("data",), mesh_d),
    to_global(W_full[:, qlo:qhi], (None, "data"), mesh_d),
    **kw,
)
f0_l, heaps_l = fit_gbt_folds(
    jnp.asarray(bins_full), jnp.asarray(y_full), jnp.asarray(W_full), **kw,
)
assert np.allclose(replicated(f0_g, mesh_d), np.asarray(f0_l), atol=1e-5)
for k, (hg, hl) in enumerate(zip(heaps_g, heaps_l)):
    rep = replicated(hg, mesh_d)
    want = np.asarray(hl)
    if want.dtype.kind in "ib":  # tree structure: bit parity
        assert np.array_equal(rep, want), f"gbt heap {{k}} differs"
    else:  # float leaf stats: psum ordering tolerance
        assert np.allclose(rep, want, atol=2e-4), \\
            np.abs(rep - want).max()

print(f"proc {{pid}} OK", flush=True)
'''


def test_four_process_2x2_mesh_packed_gram_and_gbt(tmp_path):
    """VERDICT r5 next #9: the multi-host bootstrap at FOUR Gloo
    processes - the last untested seam in parallel/distributed.py."""
    _run_workers(tmp_path, WORKER4, 4, timeout=300)
