"""Fold/grid-batched CV fits must agree with per-fold/per-config fits.

The reference trains every (model, paramMap, fold) concurrently on a JVM
Future pool (reference: core/.../impl/tuning/OpValidator.scala:289-306);
here that fan-out is an array axis.  These tests pin exact/numeric parity
between the batched dispatches and the straightforward loops for every
family that gained a batched path: GBT (fold + whole-grid), LinearSVC
(batched), NaiveBayes / GLM / MLP (fold-batched).
"""
import numpy as np
import pytest

from transmogrifai_tpu.selector.validator import stratified_kfold_masks


def _data(rng, n=400, d=6):
    X = rng.randn(n, d)
    z = X @ np.linspace(1.0, -1.0, d) + 0.5 * rng.randn(n)
    y = (z > 0).astype(float)
    return X, y, z


def _fold_weights(y, k=3):
    return stratified_kfold_masks(y, k, seed=0, stratify=True).astype(
        np.float64
    )


def test_gbt_folds_matches_per_fold(rng):
    from transmogrifai_tpu.models.trees import OpGBTClassifier

    X, y, _ = _data(rng)
    W = _fold_weights(y)
    est = OpGBTClassifier(num_trees=5, max_depth=3, backend="jax")
    batched = est.fit_arrays_folds(X, y, W)
    for f in range(len(W)):
        single = est.fit_arrays(X, y, W[f])
        _, _, prob_b = est.predict_arrays(batched[f], X)
        _, _, prob_s = est.predict_arrays(single, X)
        assert np.allclose(prob_b, prob_s, atol=1e-5)


def test_gbt_grid_matches_per_config(rng):
    from transmogrifai_tpu.models.trees import OpGBTRegressor

    X, y, z = _data(rng)
    W = _fold_weights(y)
    grid = [
        {"min_info_gain": 0.001, "step_size": 0.1},
        {"min_info_gain": 0.1, "step_size": 0.1},
        {"min_info_gain": 0.001, "step_size": 0.3},
        {"max_depth": 2, "min_info_gain": 0.01},
    ]
    est = OpGBTRegressor(num_trees=4, max_depth=3, backend="jax")
    by_grid = est.fit_arrays_folds_grid(X, z, W, grid)
    assert by_grid is not None and len(by_grid) == len(grid)
    for j, pmap in enumerate(grid):
        cand = est.with_params(**pmap)
        per_fold = cand.fit_arrays_folds(X, z, W)
        for f in range(len(W)):
            pred_g, _, _ = cand.predict_arrays(by_grid[j][f], X)
            pred_s, _, _ = cand.predict_arrays(per_fold[f], X)
            assert np.allclose(pred_g, pred_s, atol=1e-4), (j, f)


def test_gbt_native_folds_share_binning(rng):
    """Native host backend keeps parity through the shared-binning loop."""
    from transmogrifai_tpu.models import native_trees
    from transmogrifai_tpu.models.trees import OpGBTClassifier

    if not native_trees.available():
        pytest.skip("native learner unavailable")
    X, y, _ = _data(rng)
    W = _fold_weights(y)
    est = OpGBTClassifier(num_trees=5, max_depth=3, backend="native")
    batched = est.fit_arrays_folds(X, y, W)
    for f in range(len(W)):
        single = est.fit_arrays(X, y, W[f])
        _, _, prob_b = est.predict_arrays(batched[f], X)
        _, _, prob_s = est.predict_arrays(single, X)
        assert np.allclose(prob_b, prob_s, atol=1e-5)


def test_svc_batched_matches_single(rng):
    from transmogrifai_tpu.models.linear_svc import OpLinearSVC

    X, y, _ = _data(rng)
    W = _fold_weights(y)
    regs = np.array([0.001, 0.01, 0.1])
    est = OpLinearSVC()
    betas, b0s = est.fit_arrays_batched(X, y, W, regs, np.zeros(3))
    for f in range(len(W)):
        est_f = OpLinearSVC(reg_param=float(regs[f]))
        single = est_f.fit_arrays(X, y, W[f])
        assert np.allclose(betas[f], single["beta"], atol=1e-4)
        assert np.isclose(b0s[f], single["intercept"], atol=1e-4)


def test_nb_folds_matches_per_fold(rng):
    from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes

    X, y, _ = _data(rng)
    X = np.abs(X)  # multinomial counts
    W = _fold_weights(y)
    est = OpNaiveBayes()
    batched = est.fit_arrays_folds(X, y, W)
    for f in range(len(W)):
        single = est.fit_arrays(X, y, W[f])
        assert np.allclose(batched[f]["theta"], single["theta"], atol=1e-8)
        assert np.allclose(batched[f]["prior"], single["prior"], atol=1e-8)


@pytest.mark.parametrize("family", ["gaussian", "poisson", "binomial"])
def test_glm_folds_matches_per_fold(rng, family):
    from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression

    X, y, z = _data(rng)
    target = {"gaussian": z, "poisson": np.exp(np.clip(z, -2, 2)),
              "binomial": y}[family]
    W = _fold_weights(y)
    est = OpGeneralizedLinearRegression(family=family, reg_param=0.01,
                                        max_iter=10)
    batched = est.fit_arrays_folds(X, target, W)
    for f in range(len(W)):
        single = est.fit_arrays(X, target, W[f])
        assert np.allclose(batched[f]["beta"], single["beta"], atol=1e-5)
        assert np.isclose(batched[f]["intercept"], single["intercept"],
                          atol=1e-5)


def test_mlp_folds_matches_per_fold(rng):
    from transmogrifai_tpu.models.mlp import OpMultilayerPerceptronClassifier

    X, y, _ = _data(rng, n=200, d=4)
    W = _fold_weights(y)
    est = OpMultilayerPerceptronClassifier(hidden_layers=(5,), max_iter=30)
    batched = est.fit_arrays_folds(X, y, W)
    for f in range(len(W)):
        single = est.fit_arrays(X, y, W[f])
        for (Wb, bb), (Ws, bs) in zip(batched[f]["layers"],
                                      single["layers"]):
            assert np.allclose(Wb, Ws, atol=1e-4)
            assert np.allclose(bb, bs, atol=1e-4)


def test_validator_default_binary_families_no_per_config_loop(rng):
    """Every default binary-selector family must take a batched path: the
    generic per-(fold, config) fit_arrays loop is only legal for estimators
    with no batched implementation at all."""
    from transmogrifai_tpu.models.linear_svc import OpLinearSVC
    from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
    from transmogrifai_tpu.models.naive_bayes import OpNaiveBayes
    from transmogrifai_tpu.models.trees import (
        OpGBTClassifier,
        OpRandomForestClassifier,
    )

    assert hasattr(OpLogisticRegression(), "fit_arrays_batched")
    assert hasattr(OpLinearSVC(), "fit_arrays_batched")
    assert hasattr(OpRandomForestClassifier(), "fit_arrays_folds_grid")
    assert hasattr(OpGBTClassifier(), "fit_arrays_folds_grid")
    assert hasattr(OpNaiveBayes(), "fit_arrays_folds")


def test_validator_gbt_grid_end_to_end(rng):
    """OpCrossValidation over a GBT grid through the batched path agrees
    with metrics recomputed from independent per-config fits."""
    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )
    from transmogrifai_tpu.models.trees import OpGBTClassifier
    from transmogrifai_tpu.selector.validator import OpCrossValidation
    from transmogrifai_tpu.types.columns import PredictionColumn

    X, y, _ = _data(rng, n=300)
    grid = [{"min_info_gain": 0.001}, {"min_info_gain": 0.1}]
    est = OpGBTClassifier(num_trees=4, max_depth=3, backend="jax")
    ev = OpBinaryClassificationEvaluator()
    cv = OpCrossValidation(num_folds=3, evaluator=ev, seed=0, stratify=True)
    res = cv.validate([(est, grid)], X, y)
    assert len(res.all_results) == 2

    masks = stratified_kfold_masks(y, 3, seed=cv.seed, stratify=True)
    W = masks.astype(np.float64)
    for j, pmap in enumerate(grid):
        cand = est.with_params(**pmap)
        fold_params = cand.fit_arrays_folds(X, y, W)
        expect = []
        for f in range(3):
            val = ~masks[f]
            pred, raw, prob = cand.predict_arrays(fold_params[f], X[val])
            m = ev.evaluate_arrays(y[val], PredictionColumn(pred, raw, prob))
            expect.append(ev.default_metric(m))
        got = res.all_results[j]["fold_metrics"]
        assert np.allclose(got, expect, atol=1e-9)

def test_regression_cv_stays_on_batched_path(rng, monkeypatch):
    """Continuous labels must NOT knock OpLinearRegression off the batched
    route: the kernel is squared-loss, label cardinality is irrelevant
    (regression: advisor r4 — the binary-label gate silently demoted every
    regression CV/grid fit to per-candidate fit_arrays loops)."""
    from transmogrifai_tpu.evaluators.regression import (
        OpRegressionEvaluator,
    )
    from transmogrifai_tpu.models.linear_regression import OpLinearRegression
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    X, _, z = _data(rng, n=300)
    grid = [{"reg_param": 0.01}, {"reg_param": 0.1}, {"reg_param": 0.2}]
    est = OpLinearRegression()
    assert est.batched_needs_binary_y is False

    calls = {"single": 0}
    orig = OpLinearRegression.fit_arrays

    def counting_fit(self, Xa, ya, w=None):
        calls["single"] += 1
        return orig(self, Xa, ya, w)

    monkeypatch.setattr(OpLinearRegression, "fit_arrays", counting_fit)
    cv = OpCrossValidation(
        num_folds=3, evaluator=OpRegressionEvaluator(), seed=0,
        stratify=False,
    )
    res = cv.validate([(est, grid)], X, z)
    assert len(res.all_results) == 3
    # the batched branch never touches per-candidate fit_arrays; a demotion
    # to the generic loop would call it k*g = 9 times
    assert calls["single"] == 0


def test_multiclass_labels_never_ride_binary_batched_kernel(
    rng, monkeypatch
):
    """3-class y through OpLogisticRegression must never reach the binary
    fit_arrays_batched kernel (sigmoid on {0,1,2} garbage); it rides the
    fold-vmapped multinomial route instead - one fit_arrays_folds call
    per grid config, zero per-(fold, config) fit_arrays calls."""
    from transmogrifai_tpu.evaluators.multiclass import (
        OpMultiClassificationEvaluator,
    )
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    X, _, z = _data(rng, n=240)
    y3 = np.digitize(z, np.quantile(z, [1 / 3, 2 / 3])).astype(float)
    est = OpLogisticRegression()
    assert est.batched_needs_binary_y is True

    calls = {"single": 0, "batched": 0, "folds": 0}
    orig_single = OpLogisticRegression.fit_arrays
    orig_batched = OpLogisticRegression.fit_arrays_batched
    orig_folds = OpLogisticRegression.fit_arrays_folds

    def c_single(self, Xa, ya, w=None):
        calls["single"] += 1
        return orig_single(self, Xa, ya, w)

    def c_batched(self, *a, **k):
        calls["batched"] += 1
        return orig_batched(self, *a, **k)

    def c_folds(self, *a, **k):
        calls["folds"] += 1
        return orig_folds(self, *a, **k)

    monkeypatch.setattr(OpLogisticRegression, "fit_arrays", c_single)
    monkeypatch.setattr(OpLogisticRegression, "fit_arrays_batched", c_batched)
    monkeypatch.setattr(OpLogisticRegression, "fit_arrays_folds", c_folds)
    cv = OpCrossValidation(
        num_folds=3, evaluator=OpMultiClassificationEvaluator(), seed=0,
        stratify=True,
    )
    res = cv.validate([(est, [{"reg_param": 0.01}, {"reg_param": 0.1}])], X, y3)
    best = res.best_params
    assert calls["batched"] == 0  # the binary kernel is never touched
    assert calls["folds"] == 2  # one fold-vmapped dispatch per config
    assert calls["single"] == 0  # no per-(fold, config) demotion left
    assert res.best_metric > 0.5  # real 3-class models
    assert best["reg_param"] in (0.01, 0.1)


def test_lr_fit_arrays_folds_matches_per_fold(rng):
    """Fold-vmapped LR fits (binary AND multinomial) must agree with
    independent per-fold fit_arrays calls."""
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )

    X, y, z = _data(rng, n=300)
    W = _fold_weights(y)
    est = OpLogisticRegression(reg_param=0.01)
    batched = est.fit_arrays_folds(X, y, W)
    for f in range(W.shape[0]):
        single = est.fit_arrays(X, y, W[f])
        np.testing.assert_allclose(batched[f]["beta"], single["beta"],
                                   atol=1e-6)
        np.testing.assert_allclose(batched[f]["intercept"],
                                   single["intercept"], atol=1e-6)

    y3 = np.digitize(z, np.quantile(z, [1 / 3, 2 / 3])).astype(float)
    W3 = stratified_kfold_masks(y3, 3, seed=0, stratify=True).astype(
        np.float64
    )
    b3 = est.fit_arrays_folds(X, y3, W3)
    for f in range(3):
        single = est.fit_arrays(X, y3, W3[f])
        assert b3[f]["family"] == single["family"] == "multinomial"
        np.testing.assert_allclose(b3[f]["betas"], single["betas"],
                                   atol=1e-5)
        # intercepts are unregularized, so each solve may drift along
        # the softmax shift-invariance direction (adding a constant to
        # every class changes nothing): compare shift-invariantly
        ib = np.asarray(b3[f]["intercepts"])
        isg = np.asarray(single["intercepts"])
        np.testing.assert_allclose(ib - ib.mean(), isg - isg.mean(),
                                   atol=1e-5)


def test_lr_folds_memory_budget_fallback(rng, monkeypatch):
    """Past the TX_LR_FOLDS_ELEMS budget the multinomial fold vmap falls
    back to a per-fold host loop; results must be identical either way
    (the budget is a memory decision, not a numerics one)."""
    from transmogrifai_tpu.models.logistic_regression import (
        OpLogisticRegression,
    )

    X, y, z = _data(rng, n=240)
    y3 = np.digitize(z, np.quantile(z, [1 / 3, 2 / 3])).astype(float)
    W = stratified_kfold_masks(y3, 3, seed=0, stratify=True).astype(
        np.float64
    )
    est = OpLogisticRegression(reg_param=0.01)
    vmapped = est.fit_arrays_folds(X, y3, W)
    monkeypatch.setenv("TX_LR_FOLDS_ELEMS", "10")  # force the fallback
    looped = est.fit_arrays_folds(X, y3, W)
    for f in range(3):
        assert looped[f]["family"] == vmapped[f]["family"] == "multinomial"
        np.testing.assert_allclose(looped[f]["betas"],
                                   vmapped[f]["betas"], atol=1e-5)
        iv = np.asarray(vmapped[f]["intercepts"])
        il = np.asarray(looped[f]["intercepts"])
        np.testing.assert_allclose(il - il.mean(), iv - iv.mean(),
                                   atol=1e-5)
