"""Product-path mesh integration: the train/validate/SanityChecker paths
must actually shard over the 8-device CPU test mesh AND produce the same
results as unsharded execution (reference semantics being proven: Spark's
treeAggregate / Future-pool fan-out == mesh collectives, SURVEY §2.9;
VERDICT r1 weak #5 - mesh modules must not be shelf-ware)."""
import os

import jax
import numpy as np
import pytest

from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.parallel.mesh import cv_mesh_or_none, data_mesh_or_none
from transmogrifai_tpu.selector.factories import lr_grid
from transmogrifai_tpu.selector.validator import OpCrossValidation


@pytest.fixture
def cv_data(rng):
    n, d = 1999, 12  # n % data-axis != 0: exercises the zero-weight padding
    X = rng.randn(n, d).astype(np.float32)
    beta = rng.randn(d)
    y = (rng.rand(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(np.float64)
    return X, y


def test_mesh_helpers_shape():
    assert len(jax.devices()) == 8  # conftest provisioned the test mesh
    m = data_mesh_or_none()
    assert m is not None and m.shape == {"data": 8}
    m2 = cv_mesh_or_none(24)  # 3 folds x 8 grid
    assert m2 is not None
    assert m2.shape["replica"] == 2 and m2.shape["data"] == 4
    m3 = cv_mesh_or_none(3)  # replica must divide B
    assert m3.shape["replica"] == 1 and m3.shape["data"] == 8


def test_cv_sharded_matches_unsharded(cv_data, monkeypatch):
    X, y = cv_data
    ev = OpBinaryClassificationEvaluator()

    def run():
        cv = OpCrossValidation(num_folds=3, evaluator=ev, stratify=True)
        return cv.validate([(OpLogisticRegression(), lr_grid())], X, y)

    res_sharded = run()  # 8-device mesh active
    monkeypatch.setenv("TX_PRODUCT_MESH", "0")
    res_single = run()
    assert res_sharded.best_params == res_single.best_params
    np.testing.assert_allclose(
        res_sharded.best_metric, res_single.best_metric, rtol=1e-5
    )
    for a, b in zip(res_sharded.all_results, res_single.all_results):
        np.testing.assert_allclose(
            a["fold_metrics"], b["fold_metrics"], rtol=1e-5, atol=1e-7
        )


def test_sanity_checker_sharded_matches_unsharded(rng, monkeypatch):
    from transmogrifai_tpu.preparators.sanity_checker import SanityChecker
    from transmogrifai_tpu.types.columns import NumericColumn, VectorColumn
    from transmogrifai_tpu.types.dataset import Dataset
    from transmogrifai_tpu.types.feature_types import RealNN
    from transmogrifai_tpu.types.vector_metadata import (
        VectorColumnMeta,
        VectorMetadata,
    )

    n, d = 999, 7  # odd n: uneven shards must still reduce exactly
    X = rng.randn(n, d)
    y = (X[:, 0] > 0).astype(np.float64)
    meta = VectorMetadata(
        "features", tuple(VectorColumnMeta(f"f{j}", "Real") for j in range(d))
    ).reindexed()
    label = NumericColumn(y, np.ones(n, bool), RealNN)
    vec = VectorColumn(X, meta)
    ds = Dataset({"label": label, "features": vec})

    def summaries():
        sc = SanityChecker(remove_bad_features=False)
        sc.fit_model([label, vec], ds)
        return sc.metadata["sanity_checker_summary"]

    s_sharded = summaries()
    monkeypatch.setenv("TX_PRODUCT_MESH", "0")
    s_single = summaries()
    for a, b in zip(s_sharded["column_stats"], s_single["column_stats"]):
        np.testing.assert_allclose(a["mean"], b["mean"], rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(
            a["variance"], b["variance"], rtol=1e-4, atol=1e-7
        )
        np.testing.assert_allclose(
            a["corr_label"], b["corr_label"], rtol=1e-4, atol=1e-6
        )


def test_sanity_checker_accepts_device_resident_vector(rng):
    """A design matrix already living in HBM (e.g. the on-device synthetic
    generator) must be consumed in place - no host round-trip."""
    import jax.numpy as jnp

    from transmogrifai_tpu.preparators.sanity_checker import SanityChecker
    from transmogrifai_tpu.types.columns import NumericColumn, VectorColumn
    from transmogrifai_tpu.types.dataset import Dataset
    from transmogrifai_tpu.types.feature_types import RealNN
    from transmogrifai_tpu.types.vector_metadata import (
        VectorColumnMeta,
        VectorMetadata,
    )

    n, d = 512, 5
    Xh = rng.randn(n, d).astype(np.float32)
    y = (Xh[:, 1] > 0).astype(np.float64)
    meta = VectorMetadata(
        "features", tuple(VectorColumnMeta(f"f{j}", "Real") for j in range(d))
    ).reindexed()
    label = NumericColumn(y, np.ones(n, bool), RealNN)
    vec_dev = VectorColumn(jnp.asarray(Xh), meta)
    ds = Dataset({"label": label, "features": vec_dev})
    sc = SanityChecker(remove_bad_features=False)
    sc.fit_model([label, vec_dev], ds)
    stats = sc.metadata["sanity_checker_summary"]["column_stats"]
    want_mean = Xh.mean(axis=0)
    for j, c in enumerate(stats):
        np.testing.assert_allclose(c["mean"], want_mean[j], rtol=1e-4, atol=1e-5)


def test_tree_fold_fits_sharded_equals_unsharded(rng, monkeypatch):
    """The tree CV fan-out now rides the product 'data' mesh: row-sharded
    fold fits (8-device CPU mesh, zero-weight row padding) must reproduce
    the unsharded fits exactly for both forests and GBT."""
    import numpy as np

    from transmogrifai_tpu.models.trees import (
        OpGBTClassifier,
        OpRandomForestClassifier,
    )
    from transmogrifai_tpu.selector.validator import stratified_kfold_masks

    n = 403  # deliberately NOT a multiple of 8: padding path must engage
    X = rng.randn(n, 6)
    y = (X @ np.linspace(1, -1, 6) + 0.4 * rng.randn(n) > 0).astype(float)
    W = stratified_kfold_masks(y, 3, seed=0, stratify=True).astype(float)

    for est in (
        OpRandomForestClassifier(num_trees=5, max_depth=3, backend="jax"),
        OpGBTClassifier(num_trees=4, max_depth=3, backend="jax"),
    ):
        monkeypatch.setenv("TX_PRODUCT_MESH", "1")
        sharded = est.fit_arrays_folds(X, y, W)
        monkeypatch.setenv("TX_PRODUCT_MESH", "0")
        plain = est.fit_arrays_folds(X, y, W)
        for f in range(len(W)):
            _, _, prob_s = est.predict_arrays(sharded[f], X)
            _, _, prob_p = est.predict_arrays(plain[f], X)
            assert np.allclose(prob_s, prob_p, atol=1e-5), (
                type(est).__name__, f)

    # whole-grid batching too
    est = OpRandomForestClassifier(num_trees=4, max_depth=3, backend="jax")
    grid = [{"min_info_gain": 0.0}, {"min_info_gain": 0.1}]
    monkeypatch.setenv("TX_PRODUCT_MESH", "1")
    g_sh = est.fit_arrays_folds_grid(X, y, W, grid)
    monkeypatch.setenv("TX_PRODUCT_MESH", "0")
    g_pl = est.fit_arrays_folds_grid(X, y, W, grid)
    for j in range(len(grid)):
        cand = est.with_params(**grid[j])
        for f in range(len(W)):
            _, _, ps = cand.predict_arrays(g_sh[j][f], X)
            _, _, pp = cand.predict_arrays(g_pl[j][f], X)
            assert np.allclose(ps, pp, atol=1e-5), (j, f)
