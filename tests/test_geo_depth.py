"""Geolocation vectorizer depth: geographic-centroid fill semantics.

The reference imputes missing triples with the GeolocationMidpoint
monoid's 3D unit-vector mean, not an arithmetic lat/lon mean (reference:
GeolocationVectorizer.scala:70-93 'Only supports filling with geographic
centroid'), and offers constant fill (fillWithConstant, default
Geolocation(0, 0, Unknown), Transmogrifier.scala:77).
"""
from __future__ import annotations

import numpy as np
import pytest

from transmogrifai_tpu.features.feature_builder import FeatureBuilder
from transmogrifai_tpu.ops.geo import GeolocationVectorizer, geographic_midpoint
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow.workflow import OpWorkflow


def _fit(values, **kw):
    f = FeatureBuilder(ft.Geolocation, "loc").as_predictor()
    vec = GeolocationVectorizer(**kw).set_input(f).get_output()
    data = {"loc": values}
    model = (
        OpWorkflow().set_result_features(vec).set_input_dataset(data).train()
    )
    return np.asarray(model.score(data)[vec.name].to_list(), dtype=float)


def test_fill_uses_geographic_centroid_across_dateline():
    """Points at +179 and -179 longitude must fill near 180, not 0 —
    the arithmetic mean lands on the wrong side of the planet."""
    vals = [(10.0, 179.0, 1.0), (10.0, -179.0, 3.0), None]
    out = _fit(vals, track_nulls=True)
    assert out.shape == (3, 4)
    fill_lat, fill_lon = out[2, 0], out[2, 1]
    assert abs(abs(fill_lon) - 180.0) < 1e-6
    assert fill_lat == pytest.approx(10.0, abs=0.1)
    assert out[2, 2] == pytest.approx(2.0)  # accuracy averages plainly
    assert out[2, 3] == 1.0  # null indicator
    assert out[0, 3] == 0.0


def test_constant_fill():
    vals = [(40.0, -75.0, 1.0), None]
    out = _fit(vals, fill_with_constant=True, fill_value=(37.0, -122.0, 5.0))
    assert out[1, :3].tolist() == [37.0, -122.0, 5.0]
    out0 = _fit(vals, fill_with_constant=True)
    assert out0[1, :3].tolist() == [0.0, 0.0, 0.0]  # DefaultGeolocation


def test_midpoint_helper_matches_aggregator_single_point():
    mid = geographic_midpoint(np.array([[48.85, 2.35, 2.0]]))
    assert mid[0] == pytest.approx(48.85, abs=1e-9)
    assert mid[1] == pytest.approx(2.35, abs=1e-9)
    assert mid[2] == pytest.approx(2.0)


def test_midpoint_helper_matches_monoid_aggregator(rng=np.random.RandomState(7)):
    """The vectorized fit-path helper and the event-aggregation monoid are
    the same math — pin them against each other on random points."""
    from transmogrifai_tpu.features.aggregators import GeolocationMidpoint

    pts = np.column_stack([
        rng.uniform(-80, 80, 50), rng.uniform(-180, 180, 50),
        rng.uniform(0, 10, 50),
    ])
    fast = geographic_midpoint(pts)
    slow = GeolocationMidpoint().aggregate([list(p) for p in pts])
    np.testing.assert_allclose(fast, slow, atol=1e-9)


def test_bad_constant_fill_fails_fast():
    with pytest.raises(ValueError, match="lat, lon, accuracy"):
        GeolocationVectorizer(fill_with_constant=True,
                              fill_value=(37.0, -122.0))


def test_geo_map_key_fill_uses_centroid():
    from transmogrifai_tpu.ops.maps import MapVectorizer

    f = FeatureBuilder(ft.GeolocationMap, "g").as_predictor()
    vec = MapVectorizer().set_input(f).get_output()
    data = {"g": [
        {"home": (0.0, 179.0, 1.0)},
        {"home": (0.0, -179.0, 1.0)},
        {},
    ]}
    model = (
        OpWorkflow().set_result_features(vec).set_input_dataset(data).train()
    )
    col = model.score(data)[vec.name]
    out = np.asarray(col.to_list(), dtype=float)
    lon_idx = next(
        j for j, c in enumerate(col.metadata.columns)
        if c.descriptor_value == "lon"
    )
    assert abs(abs(out[2, lon_idx]) - 180.0) < 1e-6
