"""Chaos-composition drill (ISSUE 4 satellite, extended by ISSUEs 5,
16, 17 and 18): ONE seeded, randomized schedule arming faults from
eight different subsystems — ``reader.*`` (data plane),
``serving.batch`` (serving), ``io.save_model.crash`` (serialization),
``supervisor.child_kill`` (supervision), ``registry.publish_crash`` +
``canary.regression`` (model lifecycle), ``continuous.refit_crash`` +
``drift.false_positive`` (continuous training), ``fleet.partition`` +
``channel.corrupt_frame`` + ``fleet.reconnect_storm`` (fleet
transport, over a live loopback-TCP fleet), and ``bulk.output_crash``
+ ``bulk.replica_die_midshard`` (exactly-once bulk scoring) — across a
single end-to-end workflow run (corrupted-CSV quarantine ingest →
train → save/load → serve → supervise → registry publish/canary →
drift-triggered refit → fleet serve under network faults → a bulk job
killed between output write and journal commit, then resumed, then
re-run over a fleet losing a replica mid-shard), asserting the GLOBAL
invariants:

* no corrupt artifact is ever loadable (checksums verify at each step,
  including the registry index after a crashed publish);
* no phase hangs past its deadline;
* every injected event is accounted for in telemetry — quarantine
  counts, fallback rows, breaker transitions, supervisor restarts,
  canary NaN-guard refusals and the rollback decision they trigger,
  partition windows and corrupt frames in the fleet wire ledgers with
  the fleet's row ledger EXACT (nothing lost, nothing duplicated), and
  the bulk job's double-entry ledger EXACT after a kill + resume
  (``rows_in == rows_out + rows_quarantined``, output bytes identical
  to an uninterrupted run).

The schedule is randomized per TX_CHAOS_SEED but deterministic for a
given seed, so a failing composition replays exactly.
"""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.models.logistic_regression import (
    OpLogisticRegression,
)
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.readers.csv_reader import CSVReader
from transmogrifai_tpu.schema import reset_data_telemetry
from transmogrifai_tpu.serialization.model_io import (
    load_model,
    verify_artifact,
)
from transmogrifai_tpu.serving import (
    CircuitBreaker,
    RowScoringError,
    ServingTelemetry,
    compile_endpoint,
)
from transmogrifai_tpu.testkit.drills import (
    CRASH_SAVER_TEMPLATE,
    drill_env,
    tiny_drill_pipeline,
)
from transmogrifai_tpu.testkit.random_data import write_corrupted_csv
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow.supervisor import supervise

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: per-phase wall-clock ceilings (generous: these catch HANGS, not
#: slowness — a wedged collective/reader/endpoint blows way past them)
INGEST_TRAIN_DEADLINE_S = 120.0
CRASH_SAVE_DEADLINE_S = 300.0
SERVE_DEADLINE_S = 60.0
SUPERVISE_DEADLINE_S = 60.0
FLEET_DEADLINE_S = 180.0
BULK_DEADLINE_S = 180.0


@pytest.fixture(autouse=True)
def _disarm():
    faults.reset()
    reset_data_telemetry()
    yield
    faults.reset()


def _reader_workflow(path, reader_errors="quarantine", quarantine=None):
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a, c])
    pred = OpLogisticRegression(reg_param=0.01).set_input(y, vec).get_output()
    reader = CSVReader(path, errors=reader_errors, quarantine=quarantine)
    wf = OpWorkflow().set_result_features(pred).set_reader(reader)
    return wf, reader, pred.name


def test_chaos_composition_end_to_end(tmp_path):
    seed = int(os.environ.get("TX_CHAOS_SEED", "1234"))
    rng = np.random.RandomState(seed)
    # ---- the seeded, randomized schedule -------------------------------
    n_rows = 400
    n_flips = int(rng.randint(3, 9))
    n_trunc = int(rng.randint(2, 6))
    malformed_on = int(rng.randint(1, 50))      # rows 0..48
    flip_on = int(rng.randint(50, 100))         # rows 49..98, disjoint
    serving_failures = int(rng.randint(2, 5))
    canary_regression_on = int(rng.randint(1, 4))  # Nth canary batch
    events = {"armed_points": [
        "reader.malformed_row", "reader.type_flip", "serving.batch",
        "io.save_model.crash", "supervisor.child_kill",
        "registry.publish_crash", "canary.regression",
        "continuous.refit_crash", "drift.false_positive",
        "fleet.partition", "channel.corrupt_frame",
        "fleet.reconnect_storm",
        "bulk.output_crash", "bulk.replica_die_midshard",
        "autoscaler.crash",
    ]}
    bulk_kill_shard = int(rng.randint(1, 4))    # which shard's window
    autoscaler_crash_on = int(rng.randint(2, 6))  # Nth control tick

    # ---- phase 1: quarantine ingest (real corruption + injected) → train
    csv_path = str(tmp_path / "chaos.csv")
    truth = write_corrupted_csv(csv_path, n_rows=n_rows,
                                n_type_flips=n_flips,
                                n_truncated=n_trunc, seed=seed)
    wf, reader, pred_name = _reader_workflow(csv_path)
    faults.configure(
        f"reader.malformed_row:on={malformed_on} "
        f"reader.type_flip:on={flip_on}"
    )
    t0 = time.monotonic()
    model = wf.train()
    t_train = time.monotonic() - t0
    faults.reset()
    assert t_train < INGEST_TRAIN_DEADLINE_S, "ingest+train hang"
    injected_rows = {malformed_on - 1, flip_on - 1}
    expected_quarantined = len(set(truth["bad_rows"]) | injected_rows)
    # invariant: every injected + real bad row accounted, exactly once
    assert reader.quarantine.total == expected_quarantined
    events["quarantined"] = reader.quarantine.total
    assert model.schema_contract is not None
    # the contract saw only the CLEANED rows
    assert model.schema_contract.n_rows == n_rows - expected_quarantined

    # clean save of the chaos-trained model: artifact verifies
    model_path = str(tmp_path / "chaos_model")
    model.save(model_path)
    assert verify_artifact(model_path) is None

    # ---- phase 2: crash mid-save in a child → artifact invariant -------
    crash_path = str(tmp_path / "crash_model")
    script = tmp_path / "saver.py"
    script.write_text(CRASH_SAVER_TEMPLATE.format(
        repo=REPO, path=crash_path, fault="io.save_model.crash:on=1"))
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, str(script)], env=drill_env(),
                          timeout=CRASH_SAVE_DEADLINE_S)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT  # really crashed
    events["crash_save_exit"] = proc.returncode
    # invariant: the pre-crash artifact is intact and loadable, with its
    # schema contract
    assert verify_artifact(crash_path) is None
    wf2, _data, records, _name = tiny_drill_pipeline()
    recovered = load_model(crash_path, wf2)
    assert recovered.schema_contract is not None

    # ---- phase 3: serving under injected batch failures ----------------
    telemetry = ServingTelemetry()
    breaker = CircuitBreaker(failure_threshold=serving_failures,
                             cooldown_s=60.0)
    endpoint = compile_endpoint(recovered, batch_buckets=(4,),
                                telemetry=telemetry, breaker=breaker)
    faults.configure(
        f"serving.batch:every=1:times={serving_failures}")
    t0 = time.monotonic()
    for _ in range(serving_failures):
        out = endpoint.score_batch(records[:2])
        # degraded, not dead: rows still score through the fallback
        assert not any(isinstance(r, RowScoringError) for r in out)
    assert breaker.state == "open"
    shed = endpoint.score_batch(records[:3])
    assert all(isinstance(r, RowScoringError) and r.shed for r in shed)
    t_serve = time.monotonic() - t0
    faults.reset()
    assert t_serve < SERVE_DEADLINE_S, "serving hang"
    snap = telemetry.snapshot()
    # invariant: every injected batch failure accounted in telemetry
    assert snap["rows_fallback"] == 2 * serving_failures
    assert snap["breaker"]["opens"] == 1
    assert snap["breaker"]["rows_shed"] == 3
    events["serving_failures"] = serving_failures

    # ---- phase 4: supervised child killed by injection -----------------
    faults.configure("supervisor.child_kill:on=1")
    t0 = time.monotonic()
    res = supervise(
        [sys.executable, "-c", "import time; time.sleep(0.4)"],
        heartbeat_path=str(tmp_path / "hb"),
        stale_after_s=60.0, grace_s=60.0, max_restarts=1, poll_s=0.05,
        env=drill_env(), backoff_base_s=0.05, backoff_jitter=0.0,
    )
    t_sup = time.monotonic() - t0
    faults.reset()
    assert t_sup < SUPERVISE_DEADLINE_S, "supervision hang"
    # invariant: the injected kill is accounted in the restart log
    assert res.returncode == 0 and res.attempts == 2
    assert "injected child kill" in res.restarts[0][1]
    events["supervisor_restarts"] = len(res.restarts)

    # ---- phase 5: model lifecycle under injected faults ----------------
    # (ISSUE 5 satellite) a crashed registry publish in a child leaves
    # the registry loadable at the prior version, and a poisoned canary
    # auto-rolls-back with the injection accounted in telemetry
    from transmogrifai_tpu.registry import (
        DeploymentController,
        ModelRegistry,
        RollbackPolicy,
    )
    from transmogrifai_tpu.testkit.drills import (
        REGISTRY_CRASH_PUBLISHER_TEMPLATE,
    )

    reg_root = str(tmp_path / "registry")
    reg_script = tmp_path / "publisher.py"
    reg_script.write_text(REGISTRY_CRASH_PUBLISHER_TEMPLATE.format(
        repo=REPO, root=reg_root, fault="registry.publish_crash:on=1"))
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, str(reg_script)],
                          env=drill_env(), timeout=CRASH_SAVE_DEADLINE_S)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT  # really crashed
    events["registry_crash_exit"] = proc.returncode
    registry = ModelRegistry(reg_root, create=False)
    # invariant: the index never saw the crashed publish — prior version
    # intact, the half-published artifact reported as an orphan
    report = registry.verify()
    assert report["ok"] and report["versions"]["v1"] is None
    assert report["orphans"], "crashed publish left no orphan to report"
    wf5 = tiny_drill_pipeline()[0]
    stable_model = registry.load_stable(wf5)
    controller = DeploymentController(
        registry=registry, canary_fraction=0.5,
        policy=RollbackPolicy(min_canary_rows=4),
        check_every_batches=1, batch_buckets=(4,),
    )
    controller.deploy(stable_model, version="v1")
    # publish the canary candidate THROUGH the registry (v2, parent v1)
    v2 = registry.publish(stable_model, metrics={"drill": True})
    wf6 = tiny_drill_pipeline()[0]
    canary_gen = controller.start_canary_version(v2.version, wf6)
    assert registry.canary == v2.version
    faults.configure(f"canary.regression:on={canary_regression_on}")
    t0 = time.monotonic()
    rolled_back_after = None
    for i in range(canary_regression_on + 3):
        controller.score_batch([dict(r) for r in records[:8]])
        if controller.canary_generation is None:
            rolled_back_after = i + 1
            break
    t_canary = time.monotonic() - t0
    faults.reset()
    assert t_canary < SERVE_DEADLINE_S, "canary control loop hang"
    # invariant: the injected regression is accounted — NaN-guard hits
    # in the canary's telemetry, a rollback event with evidence on the
    # controller, and the demotion in the registry lineage
    assert rolled_back_after is not None
    c_snap = canary_gen.endpoint.telemetry.snapshot()
    assert c_snap["breaker"]["rows_nonfinite"] > 0
    rollbacks = [e for e in controller.events()
                 if e["event"] == "rollback"]
    assert len(rollbacks) == 1
    assert any(r["signal"] == "nonfinite_rows"
               for r in rollbacks[0]["reasons"])
    assert any(e["event"] == "rollback" for e in registry.lineage())
    events["canary_rolled_back_after_batches"] = rolled_back_after

    # ---- phase 6: continuous loop under injected faults ----------------
    # (ISSUE 16 satellite) the drift-triggered refit controller takes
    # over the SAME registry the lifecycle drill just exercised: a refit
    # crashed between train and publish leaves the fleet on the old
    # stable and the next cycle recovers organically; then a forced
    # drift false-positive on a healthy window promotes a healthy refit
    # instead of wedging the loop
    from transmogrifai_tpu.continuous import ContinuousTrainer
    from transmogrifai_tpu.testkit.drills import (
        CONTINUOUS_REFIT_CRASH_TEMPLATE,
        continuous_shard_rows,
        write_shard_csv,
    )

    tiny_factory = (
        "transmogrifai_tpu.testkit.drills:continuous_tiny_factory")
    watch = str(tmp_path / "continuous_watch")
    os.makedirs(watch)
    stable_before = registry.stable
    write_shard_csv(os.path.join(watch, "s0000.csv"),
                    continuous_shard_rows(64, seed=seed, shift=3.0))
    crash_script = tmp_path / "refit_crasher.py"
    crash_script.write_text(CONTINUOUS_REFIT_CRASH_TEMPLATE.format(
        repo=REPO, watch=watch, root=reg_root,
        fault="continuous.refit_crash:on=1"))
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, str(crash_script)],
                          env=drill_env(), timeout=CRASH_SAVE_DEADLINE_S)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT  # really died
    events["refit_crash_exit"] = proc.returncode
    # invariant: the registry never saw the crashed refit
    registry = ModelRegistry(reg_root, create=False)
    assert registry.stable == stable_before
    assert registry.verify()["ok"]
    # next cycle (fresh daemon, same watch dir) recovers end to end:
    # the follower re-offers the shard, detect → refit → promote
    trainer = ContinuousTrainer(
        watch, reg_root, tiny_factory,
        drift_threshold=0.3, consecutive_windows=1, cooldown_windows=0,
        min_window_rows=8, refit_rows=256, train_fused=False)
    cyc = trainer.run_cycle()
    t_cont = time.monotonic() - t0
    assert t_cont < INGEST_TRAIN_DEADLINE_S, "continuous recovery hang"
    assert cyc["verdict"] == "trigger" and cyc["outcome"] == "promote"
    assert registry.stable == cyc["published"] != stable_before
    events["continuous_recovered_version"] = cyc["published"]
    # forced false positive: hysteresis tuned so an organic trigger is
    # impossible (threshold 0.9, three consecutive windows) — only the
    # injected flag fires, and the healthy refit is judged on merit
    forced_trainer = ContinuousTrainer(
        watch, reg_root, tiny_factory,
        drift_threshold=0.9, consecutive_windows=3, cooldown_windows=1,
        min_window_rows=8, refit_rows=256, train_fused=False)
    write_shard_csv(os.path.join(watch, "s0001.csv"),
                    continuous_shard_rows(64, seed=seed + 1, shift=3.0))
    faults.configure("drift.false_positive:on=1")
    cyc2 = forced_trainer.run_cycle()
    faults.reset()
    # invariant: the window itself was healthy — only the forced flag
    # triggered, and it is accounted on the trainer
    assert cyc2["forced"] is True and cyc2["max_js"] < 0.9
    assert cyc2["verdict"] == "trigger" and cyc2["outcome"] == "promote"
    assert forced_trainer.forced_triggers == 1
    assert registry.stable == cyc2["published"] != cyc["published"]
    events["forced_trigger_promoted"] = cyc2["published"]

    # ---- phase 7: fleet transport under network faults -----------------
    # (ISSUE 17) a live loopback-TCP fleet rides out a partition on one
    # replica (silence-detection ejection → failover → probe
    # readmission), then a router-side corrupt frame kills the other
    # replica's channel and the readmission probe rides out a reconnect
    # storm — traffic pumped throughout, the row ledger EXACT
    from transmogrifai_tpu.fleet import FleetController
    from transmogrifai_tpu.registry import ModelRegistry as _Reg

    fleet_reg_root = str(tmp_path / "fleet_registry")
    _Reg(fleet_reg_root).publish(recovered, stage="stable")
    t0 = time.monotonic()
    batch = records[:16]
    with FleetController(
        fleet_reg_root,
        "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline",
        n_replicas=2, transport="tcp", max_restarts=0,
        work_dir=str(tmp_path / "fleet"), ship_interval_s=0.2,
        worker_env_overrides={"replica-1": {
            "TX_FAULTS": "fleet.partition:every=4:times=1:delay=2.5"}},
        router_kw={"max_in_flight_per_replica": 2, "max_queue": 64,
                   "response_timeout_s": 1.5, "eject_after": 1,
                   "probe_interval_s": 0.4, "probe_timeout_s": 0.8},
    ) as fc:
        def _fleet_wait(pred, timeout_s, what):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() <= deadline:
                if pred():
                    return
                time.sleep(0.05)
            raise AssertionError(f"fleet phase hang: {what}")

        fc.router.score_batch(batch, timeout_s=60.0)  # warm
        delivered, fleet_errors, submitted = [], [], [0]
        stop_pump = threading.Event()

        def _pump():
            while not stop_pump.is_set():
                submitted[0] += 1
                try:
                    res = fc.router.submit(records=batch).wait(60.0)
                    delivered.append(res.n_rows)
                except Exception as e:  # noqa: BLE001 - the ledger counts
                    fleet_errors.append(repr(e))

        pumps = [threading.Thread(target=_pump) for _ in range(3)]
        for t in pumps:
            t.start()
        try:
            # replica-1's 4th data send opens the partition window: the
            # router must eject it on response silence while replica-0
            # absorbs the failovers
            _fleet_wait(lambda: fc.router.snapshot()["ejections"] >= 1,
                        30.0, "partition ejection")
        finally:
            stop_pump.set()
            for t in pumps:
                t.join(timeout=120.0)
        _fleet_wait(
            lambda: fc.router.snapshot()["readmissions"] >= 1
            and fc.router.handle("replica-1").health.state == "healthy",
            30.0, "partition readmission")

        # router-side: the NEXT outbound frame goes out corrupt (the
        # worker's CRC check kills the channel), and the readmission
        # probe's first reconnect is storm-dropped
        faults.configure("channel.corrupt_frame:on=1 "
                         "fleet.reconnect_storm:every=1:times=1")
        res = fc.router.submit(records=batch).wait(60.0)
        assert res.n_rows == len(batch)  # failed over, delivered ONCE
        _fleet_wait(lambda: fc.router.snapshot()["readmissions"] >= 2,
                    30.0, "post-storm readmission")
        faults.reset()

        post = fc.router.score_batch(batch, timeout_s=60.0)
        assert len(post) == len(batch)
        snap = fc.router.snapshot()

        # row ledger EXACT: every accepted request answered exactly once
        assert fleet_errors == []
        assert len(delivered) == submitted[0]
        assert sum(delivered) == submitted[0] * len(batch)
        assert snap["rows_ok"] == (submitted[0] + 3) * len(batch)
        assert snap["requests_failed"] == 0

        # every injection accounted in the wire/health ledgers
        corrupted = [h for h in fc.router.replicas()
                     if h.wire_stats()["corrupt_injected"] >= 1]
        assert len(corrupted) == 1  # exactly one frame went out corrupt
        victim = corrupted[0]
        assert victim.health.state == "healthy"  # readmitted post-storm
        vdoc = fc.router.control(victim.instance, "status",
                                 timeout_s=30.0)
        assert vdoc["wire"]["protocol_errors"] >= 1  # worker CAUGHT it
        w1 = fc.router.control("replica-1", "status", timeout_s=30.0)
        assert w1["wire"]["partitions"] >= 1
        assert w1["wire"]["frames_dropped"] >= 1
        assert snap["response_timeouts"] >= 1  # partition detection
        assert snap["ejections"] >= 2 and snap["readmissions"] >= 2
        assert snap["replica_deaths"] >= 1     # corrupt-frame channel kill
        assert snap["probes_failed"] >= 1      # the storm's dropped dial
        events["fleet_ejections"] = snap["ejections"]
        events["fleet_readmissions"] = snap["readmissions"]
        events["fleet_rows_ok"] = snap["rows_ok"]
    t_fleet = time.monotonic() - t0
    assert t_fleet < FLEET_DEADLINE_S, "fleet transport hang"

    # ---- phase 8: exactly-once bulk scoring under kills ----------------
    # (ISSUE 18) a checkpointed bulk job over three shards of the tiny
    # drill schema is SIGKILLed in the seeded shard's "output durable,
    # receipt lost" window (between the output-shard write and its
    # ``scored`` journal commit), resumed in THIS process, and must
    # come out byte-identical to an uninterrupted run with the
    # double-entry ledger exact; then the SAME shards run over a fresh
    # fleet whose replica-1 dies mid-shard - at-least-once failover
    # duplicates WORK, the journal keeps the OUTPUT exactly-once
    from transmogrifai_tpu.bulk import (
        BulkJournal,
        BulkScoringJob,
        concatenated_output,
    )
    from transmogrifai_tpu.testkit.drills import BULK_KILL_CHILD_TEMPLATE
    from transmogrifai_tpu.utils.uid import reset_uids

    # byte-identity with the killed CHILD requires matching stage uids
    # (the scored rows' column names embed them): rewind the counters
    # to where a fresh process starts before building the oracle
    reset_uids()
    wf8, data8, _rec8, _name8 = tiny_drill_pipeline()
    bulk_rows = [{"y": data8["y"][i], "a": data8["a"][i],
                  "c": data8["c"][i]} for i in range(len(data8["y"]))]
    bulk_shards = []
    for k in range(3):
        p = str(tmp_path / f"bulk-in-{k}.csv")
        write_shard_csv(p, bulk_rows[k * 40:(k + 1) * 40])
        bulk_shards.append(p)
    t0 = time.monotonic()
    # train the oracle model EXACTLY as the killed child will (the
    # save/load roundtrip `recovered` went through perturbs low-order
    # weight bits, and byte-identity is the whole point here)
    bulk_model = wf8.train()
    bulk_ref_dir = str(tmp_path / "bulk_ref")
    BulkScoringJob(bulk_model, bulk_ref_dir, bulk_shards,
                   chunk_rows=16).run()
    bulk_ref = concatenated_output(bulk_ref_dir)
    # kill between write and commit on the seeded shard's window
    bulk_dir = str(tmp_path / "bulk_job")
    bulk_script = tmp_path / "bulk_killer.py"
    bulk_script.write_text(BULK_KILL_CHILD_TEMPLATE.format(
        repo=REPO, fault=f"bulk.output_crash:on={bulk_kill_shard}",
        n=120, job_dir=bulk_dir, shards=bulk_shards, chunk=16))
    proc = subprocess.run([sys.executable, str(bulk_script)],
                          env=drill_env(), timeout=CRASH_SAVE_DEADLINE_S)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT  # really killed
    events["bulk_kill_exit"] = proc.returncode
    assert BulkJournal.load(bulk_dir).states()["committed"] < 3
    bulk_summary = BulkScoringJob(bulk_model, bulk_dir).run()
    # invariant: zero duplicated, zero lost rows - bytes identical,
    # ledger balanced, the killed shard's re-score accounted
    assert bulk_summary["resumed"] is True
    assert concatenated_output(bulk_dir) == bulk_ref
    bulk_led = bulk_summary["ledger"]
    assert bulk_led["balanced"] and bulk_led["rows_in"] == 120
    assert bulk_led["rows_in"] == (bulk_led["rows_out"]
                                   + bulk_led["rows_quarantined"])
    (bulk_resume,) = bulk_summary["resumes"]
    assert bulk_kill_shard - 1 in bulk_resume["rescored_shards"]
    events["bulk_rescored_shards"] = bulk_resume["rescored_shards"]
    # replica death mid-shard: a fresh 2-replica fleet over the same
    # registry; replica-1 dies on its first bulk chunk
    with FleetController(
        fleet_reg_root,
        "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline",
        n_replicas=2, transport="tcp", max_restarts=0,
        work_dir=str(tmp_path / "bulk_fleet"), ship_interval_s=0.2,
        worker_env_overrides={"replica-1": {
            "TX_FAULTS": "bulk.replica_die_midshard:on=1"}},
        router_kw={"max_in_flight_per_replica": 2, "max_queue": 64},
    ) as bfc:
        fleet_bulk_dir = str(tmp_path / "bulk_fleet_job")
        fleet_summary = BulkScoringJob(
            bulk_model, fleet_bulk_dir, bulk_shards, router=bfc.router,
            chunk_rows=16, max_in_flight=4).run()
        fleet_led = fleet_summary["ledger"]
        assert fleet_led["balanced"] and fleet_led["rows_in"] == 120
        bsnap = bfc.router.snapshot()
        assert bsnap["replica_deaths"] == 1
        assert bsnap["retries"] >= 1  # the victim died holding a chunk
        assert len(concatenated_output(fleet_bulk_dir).splitlines()) \
            == fleet_led["rows_out"]
        events["bulk_fleet_replica_deaths"] = bsnap["replica_deaths"]
    t_bulk = time.monotonic() - t0
    assert t_bulk < BULK_DEADLINE_S, "bulk scoring hang"

    # ---- phase 9: elastic capacity under chaos -------------------------
    # (ISSUE 19) a fresh TCP fleet rides a load surge while the
    # autoscaler's OWN control loop is killed on the seeded tick
    # (``autoscaler.crash``): the data plane keeps serving through the
    # control-plane death, a restarted autoscaler adopts the live
    # fleet and grows it under the still-burning load, then the drain
    # (load stops) shrinks it back - the row ledger EXACT throughout
    from transmogrifai_tpu.fleet import FleetAutoscaler

    t0 = time.monotonic()
    with FleetController(
        fleet_reg_root,
        "transmogrifai_tpu.testkit.drills:tiny_drill_pipeline",
        n_replicas=2, transport="tcp", max_restarts=0,
        work_dir=str(tmp_path / "autoscale_fleet"), ship_interval_s=0.2,
        worker_env={"TX_FAULTS": "serving.slow_batch:every=1:delay=0.03"},
        router_kw={"max_in_flight_per_replica": 2, "max_queue": 64},
    ) as afc:
        abatch = records[:24]
        afc.router.score_batch(abatch, timeout_s=60.0)  # warm
        adelivered, aerrors = [], []
        stop_surge = threading.Event()

        def _surge():
            while not stop_surge.is_set():
                try:
                    res = afc.router.submit(records=abatch).wait(120.0)
                    adelivered.append(res.n_rows)
                except Exception as e:  # noqa: BLE001 - ledger counts
                    aerrors.append(repr(e))

        surges = [threading.Thread(target=_surge) for _ in range(6)]
        for t in surges:
            t.start()
        try:
            faults.configure(
                f"autoscaler.crash:on={autoscaler_crash_on}")
            doomed = FleetAutoscaler(
                afc, min_replicas=2, max_replicas=3, interval_s=0.15,
                up_consecutive=2, down_consecutive=2,
                cooldown_windows=1, retune_enabled=False,
                probe_timeout_s=120.0, drain_timeout_s=60.0)
            doomed.start()
            _fleet_wait(lambda: not doomed.alive(), 60.0,
                        "autoscaler crash")
            faults.reset()
            assert doomed.crashed  # the fault, not a clean stop
            # the data plane never noticed the control plane die
            assert len(afc.router.score_batch(
                abatch, timeout_s=60.0)) == len(abatch)
            # a restarted autoscaler adopts the live fleet and grows
            # it under the still-burning surge
            scaler = FleetAutoscaler(
                afc, min_replicas=2, max_replicas=3, interval_s=0.15,
                up_consecutive=2, down_consecutive=2,
                cooldown_windows=1, retune_enabled=False,
                probe_timeout_s=120.0, drain_timeout_s=60.0)
            scaler.start()
            _fleet_wait(lambda: len(afc.member_instances()) >= 3,
                        FLEET_DEADLINE_S, "surge scale-up")
        finally:
            stop_surge.set()
            for t in surges:
                t.join(timeout=120.0)
        try:
            # the drain: load gone, the fleet shrinks back to min
            _fleet_wait(lambda: len(afc.member_instances()) <= 2,
                        FLEET_DEADLINE_S, "idle scale-down")
        finally:
            scaler.stop()
        assert scaler.decisions()[0].action == "adopt"
        # the crash tick is randomized, so the surge scale-up may land
        # on either side of the crash: assert it over the COMBINED
        # decision history, and that the adopter never repeated it
        # blindly (at most one scale-up total for one sustained surge)
        actions = [d.action for d in doomed.decisions()] \
            + [d.action for d in scaler.decisions()]
        assert "scale_up" in actions and "scale_down" in actions
        assert actions.count("scale_up") == 1
        # row ledger EXACT across crash + grow + drain: every accepted
        # request answered exactly once
        assert aerrors == []
        asnap = afc.router.snapshot()
        assert asnap["rows_ok"] == (len(adelivered) + 2) * len(abatch)
        assert asnap["requests_failed"] == 0
        events["autoscaler_crash_tick"] = autoscaler_crash_on
        events["autoscale_decisions"] = len(scaler.decisions())
        events["autoscale_rows_ok"] = asnap["rows_ok"]
    t_autoscale = time.monotonic() - t0
    assert t_autoscale < FLEET_DEADLINE_S, "autoscale phase hang"

    # ---- global: nothing leaked, everything accounted ------------------
    assert not faults.active()
    assert events["quarantined"] == expected_quarantined
    assert verify_artifact(model_path) is None
    assert verify_artifact(crash_path) is None
    assert registry.verify()["ok"]
