"""Event readers + aggregators + testkit tests (reference: readers/src/test/
DataReaderTest / JoinedDataReaderDataGenerationTest; testkit specs)."""
import numpy as np
import pytest

from transmogrifai_tpu.features.aggregators import (
    CutOffTime,
    Event,
    FeatureAggregator,
    GeolocationMidpoint,
    default_aggregator,
)
from transmogrifai_tpu.features.feature_builder import FeatureBuilder
from transmogrifai_tpu.readers.events import (
    AggregateReader,
    ConditionalReader,
    JoinedReader,
    SimpleReader,
    StreamingReader,
)
from transmogrifai_tpu.testkit.random_data import (
    RandomBinary,
    RandomReal,
    RandomText,
    random_dataset,
)
from transmogrifai_tpu.types import feature_types as ft


def test_default_aggregators_per_type():
    assert default_aggregator(ft.Real).aggregate([1.0, 2.0, None]) == 3.0
    assert default_aggregator(ft.Percent).aggregate([0.2, 0.4]) == pytest.approx(0.3)
    assert default_aggregator(ft.Binary).aggregate([False, True]) is True
    assert default_aggregator(ft.Date).aggregate([5, 9, 7]) == 9
    assert default_aggregator(ft.PickList).aggregate(["a", "b", "a"]) == "a"
    assert default_aggregator(ft.Text).aggregate(["x", "y"]) == "x y"
    assert default_aggregator(ft.MultiPickList).aggregate(
        [frozenset({"a"}), frozenset({"b"})]
    ) == {"a", "b"}
    assert default_aggregator(ft.RealMap).aggregate(
        [{"k": 1.0}, {"k": 2.0, "j": 5.0}]
    ) == {"k": 3.0, "j": 5.0}


def test_geolocation_midpoint():
    mid = GeolocationMidpoint().aggregate([[0.0, 0.0, 1.0], [0.0, 90.0, 1.0]])
    assert abs(mid[0]) < 1e-6 and abs(mid[1] - 45.0) < 1e-6


def test_aggregate_reader_cutoff_semantics():
    records = [
        {"id": "u1", "t": 1.0, "amount": 10.0, "label": 0.0},
        {"id": "u1", "t": 2.0, "amount": 5.0, "label": 1.0},
        {"id": "u1", "t": 9.0, "amount": 100.0, "label": 1.0},
        {"id": "u2", "t": 1.5, "amount": 7.0, "label": 0.0},
    ]
    amount = FeatureBuilder(ft.Real, "amount").as_predictor()
    label = FeatureBuilder(ft.Binary, "label").as_response()
    reader = AggregateReader(
        records, key_fn=lambda r: r["id"], time_fn=lambda r: r["t"],
        cutoff=CutOffTime(5.0),
    )
    ds = reader.generate_dataset([amount, label])
    # predictors: events strictly before 5 summed; responses: events >= 5
    # or'd (reference comparison FeatureAggregator.scala:114-123)
    assert ds["amount"].to_list() == [15.0, 7.0]
    assert ds["label"].to_list() == [1.0, None]


def test_conditional_reader_per_key_cutoff():
    records = [
        {"id": "a", "t": 1.0, "spend": 3.0, "visit": False, "converted": 0.0},
        {"id": "a", "t": 2.0, "spend": 4.0, "visit": True, "converted": 0.0},
        {"id": "a", "t": 3.0, "spend": 9.0, "visit": False, "converted": 1.0},
        {"id": "b", "t": 1.0, "spend": 2.0, "visit": False, "converted": 0.0},
    ]
    spend = FeatureBuilder(ft.Real, "spend").as_predictor()
    conv = FeatureBuilder(ft.Binary, "converted").as_response()
    reader = ConditionalReader(
        records,
        key_fn=lambda r: r["id"],
        time_fn=lambda r: r["t"],
        target_condition=lambda r: r["visit"],
        response_window=5.0,
    )
    ds = reader.generate_dataset([spend, conv])
    # only key 'a' has the condition; spend aggregates events STRICTLY
    # before t(visit)=2 - the visit that set the cutoff is response-side
    # (reference FeatureAggregator.scala:119: date < cutoff)
    assert len(ds) == 1
    assert ds["spend"].to_list() == [3.0]
    assert ds["converted"].to_list() == [1.0]


def test_joined_reader_left_join():
    left = SimpleReader(
        [{"k": "1", "x": 1.0}, {"k": "2", "x": 2.0}]
    )
    right = SimpleReader([{"k": "1", "z": "hi"}])
    fx = FeatureBuilder(ft.Real, "x").as_predictor()
    fk = FeatureBuilder(ft.ID, "k").as_predictor()
    fz = FeatureBuilder(ft.Text, "z").as_predictor()
    joined = JoinedReader(left, right, left_key="k")
    ds = joined.generate_dataset([fk, fx, fz])
    assert ds["z"].to_list() == ["hi", None]


def test_streaming_reader_batches():
    recs = ({"a": float(i)} for i in range(25))
    fa = FeatureBuilder(ft.Real, "a").as_predictor()
    batches = list(StreamingReader(recs, batch_size=10).stream([fa]))
    assert [len(b) for b in batches] == [10, 10, 5]


def test_testkit_generators_deterministic():
    r1 = RandomReal.normal(1.0, 2.0, seed=7).limit(100)
    r2 = RandomReal.normal(1.0, 2.0, seed=7).limit(100)
    assert r1 == r2
    sparse = RandomReal.uniform(seed=1).with_probability_of_empty(0.5).limit(1000)
    assert 300 < sum(v is None for v in sparse) < 700
    picks = RandomText.picklists(["a", "b"], seed=3).limit(50)
    assert set(picks) <= {"a", "b"}
    ds = random_dataset(
        {
            "x": (RandomReal.normal(seed=1), ft.Real),
            "b": (RandomBinary(0.3, seed=2), ft.Binary),
            "t": (RandomText.words(seed=3), ft.Text),
        },
        n=50,
    )
    assert len(ds) == 50 and set(ds.column_names()) == {"x", "b", "t"}
