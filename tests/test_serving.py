"""Serving subsystem: bucketed endpoint, micro-batcher, admission control.

Covers the serving contracts (ISSUE: serving test coverage):
* batch-of-1 vs batch-of-N prediction parity across LR/RF/GBT winners
* deadline-shed + queue-overflow admission behavior
* shape-miss fallback correctness (bad rows isolated, peers still score)
* deterministic batch-fill scheduling (run_once, no worker thread)
* per-request timeout surface
* the RF-winner throughput regression floor (bench-host tier-1 gate)
* the `serve` run type end-to-end with telemetry JSON export
"""
import json
import os
import time

import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.trees import (
    OpGBTClassifier,
    OpRandomForestClassifier,
)
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.serving import (
    DeadlineExceededError,
    MicroBatchScheduler,
    QueueFullError,
    RequestTimeoutError,
    RowScoringError,
    ServingTelemetry,
    compile_endpoint,
)
from transmogrifai_tpu.types import feature_types as ft


def _mixed_pipeline(est, n=240, seed=0):
    """Small full pipeline (numeric + picklist through transmogrify) with
    ``est`` as the predictor; returns (model, records, prediction_name)."""
    rng = np.random.RandomState(seed)
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "b": rng.uniform(0, 10, n).round(3).tolist(),
        "c": [("u", "v", "w")[i % 3] for i in range(n)],
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    b = FeatureBuilder(ft.Real, "b").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a, b, c])
    pred = est.set_input(y, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    records = [
        {"a": data["a"][i], "b": data["b"][i], "c": data["c"][i]}
        for i in range(n)
    ]
    return model, records, pred.name


WINNERS = [
    ("lr", lambda: OpLogisticRegression(reg_param=0.01)),
    ("rf", lambda: OpRandomForestClassifier(num_trees=10, max_depth=4)),
    ("gbt", lambda: OpGBTClassifier(num_trees=8, max_depth=3)),
]


@pytest.mark.parametrize("name,make", WINNERS, ids=[w[0] for w in WINNERS])
def test_batch_of_1_vs_batch_of_n_parity(name, make):
    """Every request must score identically whether it rides alone
    (bucket 1/pad) or inside a full batch - the bucket padding must be
    invisible."""
    model, records, pred_name = _mixed_pipeline(make())
    endpoint = compile_endpoint(model, batch_buckets=(1, 4, 16, 64))
    records = records[:50]
    batched = endpoint.score_batch(records)
    assert not any(isinstance(r, RowScoringError) for r in batched)
    singles = [endpoint(r) for r in records]
    for one, many in zip(singles, batched):
        po, pm = one[pred_name], many[pred_name]
        assert po["prediction"] == pm["prediction"]
        for k in po:
            if k.startswith("probability"):
                assert abs(po[k] - pm[k]) < 1e-9, (name, k)


def test_endpoint_warmup_primes_every_bucket():
    model, _, _ = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model, batch_buckets=(2, 8))
    assert endpoint.warmed_buckets == (2, 8)
    assert endpoint.warm_error is None


def test_oversized_batch_chunks_at_largest_bucket():
    model, records, pred_name = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model, batch_buckets=(1, 8))
    out = endpoint.score_batch(records[:20])  # 20 > bucket max 8
    assert len(out) == 20
    ref = compile_endpoint(model, batch_buckets=(32,)).score_batch(
        records[:20]
    )
    for a, b in zip(out, ref):
        assert a[pred_name]["prediction"] == b[pred_name]["prediction"]


def test_shape_miss_fallback_isolates_bad_rows():
    """A malformed record must degrade ITS batch to the row path and come
    back as RowScoringError without failing its batch peers."""
    model, records, pred_name = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    good = records[:3]
    bad = {"a": object(), "b": 1.0, "c": "u"}  # unparseable numeric cell
    out = endpoint.score_batch([good[0], bad, good[1], good[2]])
    assert endpoint.shape_misses == 1
    assert isinstance(out[1], RowScoringError)
    clean = endpoint.score_batch(good)
    for got, want in zip([out[0], out[2], out[3]], clean):
        assert got[pred_name]["prediction"] == want[pred_name]["prediction"]
    assert endpoint.telemetry.snapshot()["rows_fallback"] == 4


def test_queue_overflow_sheds_at_the_front_door():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    sched = MicroBatchScheduler(
        endpoint, max_queue=4, max_wait_us=0, start=False
    )
    for i in range(4):
        sched.submit(records[i])
    with pytest.raises(QueueFullError):
        sched.submit(records[4])
    assert endpoint.telemetry.snapshot()["shed_queue_full"] == 1
    assert sched.run_once() == 4  # the queue drains and recovers
    sched.submit(records[4])
    sched.close()


def test_deadline_shed_never_scores_dead_requests():
    """Requests whose deadline passed in the queue resolve with
    DeadlineExceededError at batch formation and never reach the model."""
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    fake_now = [100.0]
    sched = MicroBatchScheduler(
        endpoint, max_wait_us=0, start=False, clock=lambda: fake_now[0]
    )
    dead = sched.submit(records[0], deadline_ms=50.0)
    live = sched.submit(records[1], deadline_ms=10_000.0)
    fake_now[0] += 1.0  # 1s later: first deadline (50ms) long gone
    assert sched.run_once() == 1
    with pytest.raises(DeadlineExceededError):
        dead.wait(0)
    assert live.wait(0) is not None
    snap = endpoint.telemetry.snapshot()
    assert snap["shed_deadline"] == 1
    assert snap["rows_scored"] == 1
    sched.close()


def test_per_request_timeout_surface():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    sched = MicroBatchScheduler(endpoint, start=False)  # nobody drains
    with pytest.raises(RequestTimeoutError):
        sched.score(records[0], timeout_s=0.01)
    assert endpoint.telemetry.snapshot()["request_timeouts"] == 1
    sched.close()


def test_deterministic_batch_fill():
    """run_once with no worker thread: batch formation is exact - fills
    to max_batch_size, then drains the remainder as a partial batch."""
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    tel = ServingTelemetry()
    endpoint = compile_endpoint(
        model, batch_buckets=(1, 8), telemetry=tel
    )
    sched = MicroBatchScheduler(
        endpoint, max_batch_size=8, max_wait_us=0, start=False,
        telemetry=tel,
    )
    for r in records[:20]:
        sched.submit(r)
    sizes = []
    while True:
        n = sched.run_once()
        if n == 0:
            break
        sizes.append(n)
    assert sizes == [8, 8, 4]
    snap = tel.snapshot()
    assert snap["batches"] >= 3  # warm-up batches may add to the count
    assert snap["rows_scored"] == 20
    hist = snap["batch_fill_histogram"]
    assert hist["75-100%"] >= 2  # the two full batches
    sched.close()


def test_scheduler_results_match_direct_scoring():
    """Through-the-batcher results must equal direct endpoint scoring,
    in submission order, with a live worker thread."""
    model, records, pred_name = _mixed_pipeline(
        OpRandomForestClassifier(num_trees=10, max_depth=4)
    )
    endpoint = compile_endpoint(model)
    direct = endpoint.score_batch(records)
    with MicroBatchScheduler(endpoint, max_wait_us=1000) as sched:
        served = list(sched.score_stream(iter(records), window=64))
    assert len(served) == len(records)
    for s, d in zip(served, direct):
        assert not isinstance(s, RowScoringError)
        assert s[pred_name]["prediction"] == d[pred_name]["prediction"]


def test_score_stream_backpressures_instead_of_dying_on_full_queue():
    """A window larger than the admission bound must not kill the stream
    with QueueFullError - the stream waits on its own oldest request."""
    model, records, pred_name = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    with MicroBatchScheduler(
        endpoint, max_queue=8, max_wait_us=200
    ) as sched:
        out = list(sched.score_stream(iter(records[:100]), window=64))
    assert len(out) == 100
    assert not any(isinstance(r, RowScoringError) for r in out)


def test_score_stream_sheds_row_when_queue_full_of_foreign_requests():
    """With zero in-flight requests of its own and the queue full of
    other callers' work, the stream sheds the row as RowScoringError
    rather than raising."""
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    sched = MicroBatchScheduler(
        endpoint, max_queue=2, max_wait_us=0, start=False
    )
    sched.submit(records[0])  # foreign requests hog the queue
    sched.submit(records[1])
    out = list(sched.score_stream([records[2]]))
    assert len(out) == 1
    assert isinstance(out[0], RowScoringError)
    assert "QueueFullError" in out[0].error
    sched.close()


def test_submit_after_close_raises_immediately():
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    sched = MicroBatchScheduler(endpoint, start=False)
    sched.close()
    with pytest.raises(RuntimeError, match="closed"):
        sched.submit(records[0])
    # the admission-side gate holds even if the scheduler flag is missed
    # (the close()/submit() race goes through the queue lock)
    with pytest.raises(RuntimeError, match="closed"):
        sched.admission.admit(records[0])


def test_abandoned_request_not_double_counted():
    """A request whose caller timed out must count once (timeout), not
    again as a delivered 'ok' when the batch loop later scores it."""
    model, records, _ = _mixed_pipeline(OpLogisticRegression())
    endpoint = compile_endpoint(model)
    sched = MicroBatchScheduler(endpoint, max_wait_us=0, start=False)
    with pytest.raises(RequestTimeoutError):
        sched.score(records[0], timeout_s=0.01)
    assert sched.run_once() == 1  # the row still scores...
    snap = endpoint.telemetry.snapshot()
    assert snap["request_timeouts"] == 1
    assert snap["rows_scored"] == 0  # ...but is not re-counted
    sched.close()


def test_unscoreable_pad_record_does_not_degrade_batches():
    """A pipeline that cannot score the all-None pad row (warm_error set)
    must still serve partial batches through the BATCH path - unpadded -
    not silently fall back to per-row scoring."""
    rng = np.random.RandomState(1)
    n = 120
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    # a map stage that chokes on None: the pad record is unscoreable
    a2 = a.map_values(lambda v: v * 2.0, ft.Real)
    vec = transmogrify([a2])
    pred = OpLogisticRegression().set_input(y, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    endpoint = compile_endpoint(model, batch_buckets=(1, 32))
    assert endpoint.warm_error is not None
    records = [{"a": data["a"][i]} for i in range(5)]
    out = endpoint.score_batch(records)  # 5 < bucket 32: would need pads
    assert len(out) == 5
    assert not any(isinstance(r, RowScoringError) for r in out)
    assert endpoint.shape_misses == 0  # batch path, not row fallback
    assert endpoint.telemetry.snapshot()["rows_fallback"] == 0


def test_empty_telemetry_snapshot_is_strict_json():
    """Zero-traffic snapshots must export valid RFC 8259 JSON: the
    empty-sample percentiles serialize as null, never a bare NaN token."""
    snap = ServingTelemetry().snapshot()
    text = json.dumps(snap)
    assert "NaN" not in text
    assert json.loads(text)["latency_ms"]["p50"] is None


def test_rf_winner_batch_throughput_floor():
    """Tier-1 serving regression gate (ISSUE acceptance: RF-winner >= 1000
    rows/s through the serving endpoint).  The floor is far below the
    measured ~15k rows/s (SERVING_BENCH.json) so only a real regression -
    e.g. the per-tree python predict loop coming back - trips it."""
    est = OpRandomForestClassifier(num_trees=50, max_depth=12)
    model, records, _ = _mixed_pipeline(est, n=400)
    endpoint = compile_endpoint(model)
    requests = (records * 3)[:1000]
    t0 = time.perf_counter()
    out = endpoint.score_batch(requests)
    wall = time.perf_counter() - t0
    assert len(out) == 1000
    rows_per_s = len(out) / wall
    assert rows_per_s >= 1000, (
        f"RF-winner serving throughput regressed: {rows_per_s:.0f} rows/s"
    )


def test_rf_batch_of_1_flat_heap_predict_is_fast():
    """The VERDICT r5 Weak #4 root cause must stay fixed: batch-of-1
    through the flat-heap predict is microseconds, not milliseconds (the
    old per-tree python loop cost ~6 ms/row on 50 trees)."""
    rng = np.random.RandomState(0)
    X = rng.randn(500, 12)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    est = OpRandomForestClassifier(num_trees=50, max_depth=12)
    params = est.fit_arrays(X, y)
    x1 = X[:1]
    est.predict_arrays_np(params, x1)  # warm
    t0 = time.perf_counter()
    n = 100
    for _ in range(n):
        est.predict_arrays_np(params, x1)
    per_call_ms = (time.perf_counter() - t0) / n * 1e3
    assert per_call_ms < 2.0, f"batch-of-1 predict {per_call_ms:.2f} ms"


def test_serve_run_type_exports_telemetry(tmp_path):
    """OpWorkflowRunner 'serve': load model, pump reader rows through the
    micro-batcher, export serving_metrics.json."""
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    rng = np.random.RandomState(3)
    n = 120
    data = {
        "y": (rng.rand(n) > 0.5).astype(float).tolist(),
        "a": rng.randn(n).tolist(),
        "c": [("u", "v")[i % 2] for i in range(n)],
    }
    y = FeatureBuilder(ft.RealNN, "y").as_response()
    a = FeatureBuilder(ft.Real, "a").as_predictor()
    c = FeatureBuilder(ft.PickList, "c").as_predictor()
    vec = transmogrify([a, c])
    pred = (
        OpRandomForestClassifier(num_trees=8, max_depth=3)
        .set_input(y, vec)
        .get_output()
    )
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    model = wf.train()
    model_dir = str(tmp_path / "model")
    model.save(model_dir)

    params = OpParams(
        model_location=model_dir,
        metrics_location=str(tmp_path / "metrics"),
        write_location=str(tmp_path / "scores"),
        custom_params={"serving_max_wait_us": 500, "serving_window": 32},
    )
    runner = OpWorkflowRunner(wf)
    result = runner.run("serve", params)
    assert result.run_type == "serve"
    assert result.metrics["rows_scored"] == n
    assert result.metrics["rows_failed"] == 0
    for k in ("p50", "p95", "p99"):
        assert result.metrics["latency_ms"][k] >= 0.0
    with open(tmp_path / "metrics" / "serving_metrics.json") as f:
        exported = json.load(f)
    assert exported["rows_submitted"] == n
    with open(tmp_path / "scores" / "scores.json") as f:
        rows = json.load(f)
    assert len(rows) == n
    assert all("error" not in r for r in rows)


def test_cli_generated_project_has_serve_template(tmp_path):
    """The project generator must emit serve.py wired to the serving
    subsystem (parses, imports the right surface)."""
    import ast

    from transmogrifai_tpu.cli import generate

    csv = tmp_path / "d.csv"
    rows = ["y,a,c"] + [
        f"{i % 2},{i * 0.1:.1f},{('u', 'v')[i % 2]}" for i in range(40)
    ]
    csv.write_text("\n".join(rows) + "\n")
    out = tmp_path / "proj"
    generate(str(csv), "y", "App", str(out))
    serve_py = out / "serve.py"
    assert serve_py.exists()
    src = serve_py.read_text()
    ast.parse(src)
    assert "MicroBatchScheduler" in src
    assert "compile_endpoint" in src
