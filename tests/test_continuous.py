"""Continuous-training drills (ISSUE 16).

The drift-triggered refit controller that closes the
data→drift→refit→canary→promote loop: the RefitGovernor hysteresis/
cooldown machine, the ShardDirectoryFollower tail mode on the PR-8
pipeline, the DriftMonitor windowed-reset seam (with the cumulative-
merge dilution bias pinned), the direct-promote loop + status file +
``tx continuous status`` + ``continuous`` run type + ``tx_continuous_*``
scrape, the ``continuous.refit_crash`` / ``drift.false_positive`` fault
drills, and the e2e acceptance drill: a mid-stream distribution shift
on a live 2-replica fleet is detected, refit WARM from the PR-15
``train_xla_cache/`` seeded by a different process, canaried and
auto-promoted — old model serving throughout, zero dropped rows, the
whole cycle under ONE trace id.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from transmogrifai_tpu.continuous import (
    STATUS_FILENAME,
    ContinuousError,
    ContinuousTrainer,
    RefitGovernor,
)
from transmogrifai_tpu.faults import injection as faults
from transmogrifai_tpu.readers.pipeline import (
    ShardDirectoryFollower,
    pipelined_columns,
)
from transmogrifai_tpu.registry import ModelRegistry
from transmogrifai_tpu.schema.drift import DriftMonitor
from transmogrifai_tpu.testkit.drills import (
    CONTINUOUS_REFIT_CRASH_TEMPLATE,
    CONTINUOUS_SEED_TRAINER_TEMPLATE,
    continuous_shard_rows,
    continuous_tiny_factory,
    drill_env,
    tiny_drill_pipeline,
    write_shard_csv,
)
from transmogrifai_tpu.testkit.random_data import shift_records

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY_FACTORY = "transmogrifai_tpu.testkit.drills:continuous_tiny_factory"
DRILL_FACTORY = (
    "transmogrifai_tpu.testkit.drills:continuous_drill_workflow")


@pytest.fixture(autouse=True)
def _disarm():
    faults.reset()
    yield
    faults.reset()


def _tiny_trainer(tmp_path, **kw):
    """A bootstrapped direct-mode trainer over the tiny (no-selector)
    pipeline - the fast fixture for loop-policy drills."""
    watch = str(tmp_path / "watch")
    os.makedirs(watch, exist_ok=True)
    kw.setdefault("drift_threshold", 0.35)
    kw.setdefault("consecutive_windows", 2)
    kw.setdefault("cooldown_windows", 1)
    kw.setdefault("min_window_rows", 32)
    kw.setdefault("refit_rows", 256)
    kw.setdefault("train_fused", False)
    kw.setdefault("bootstrap", True)
    return ContinuousTrainer(
        watch, str(tmp_path / "registry"), TINY_FACTORY,
        status_dir=str(tmp_path), **kw)


# ---------------------------------------------------------------------------
# RefitGovernor: hysteresis + cooldown + forced semantics
# ---------------------------------------------------------------------------
def test_governor_hysteresis_needs_consecutive_over_windows():
    gov = RefitGovernor(threshold=0.5, consecutive=3, cooldown=2)
    # a broken streak never triggers
    assert gov.observe_window(0.6) == "over"
    assert gov.observe_window(0.6) == "over"
    assert gov.observe_window(0.4) == "clear"
    assert gov.over_streak == 0 and gov.triggers == 0
    # three in a row does
    assert gov.observe_window(0.6) == "over"
    assert gov.observe_window(0.9) == "over"
    assert gov.observe_window(0.7) == "trigger"
    assert gov.triggers == 1 and gov.cooldown_left == 2


def test_governor_cooldown_suppresses_and_surfaces():
    gov = RefitGovernor(threshold=0.5, consecutive=1, cooldown=2)
    assert gov.observe_window(0.9) == "trigger"
    # the two cooldown windows cannot re-trigger, however hot
    assert gov.observe_window(0.99) == "suppressed"
    assert gov.observe_window(0.2) == "clear"  # burns cooldown quietly
    assert gov.suppressed == 1 and gov.triggers == 1
    # cooldown over: the next hot window triggers again
    assert gov.observe_window(0.9) == "trigger"
    snap = gov.snapshot()
    assert snap["triggers"] == 2 and snap["windows"] == 4


def test_governor_forced_bypasses_hysteresis_not_cooldown():
    gov = RefitGovernor(threshold=0.5, consecutive=3, cooldown=2)
    # forced trigger on a stone-cold window, streak irrelevant
    assert gov.observe_window(0.0, forced=True) == "trigger"
    # forced during cooldown is suppressed like any other window
    assert gov.observe_window(0.0, forced=True) == "suppressed"
    assert gov.suppressed == 1


def test_governor_rejects_nonsense_knobs():
    with pytest.raises(ValueError):
        RefitGovernor(consecutive=0)
    with pytest.raises(ValueError):
        RefitGovernor(cooldown=-1)


# ---------------------------------------------------------------------------
# ShardDirectoryFollower: the tail mode on the PR-8 pipeline
# ---------------------------------------------------------------------------
def test_follower_monotonic_ids_and_exactly_once(tmp_path):
    watch = tmp_path / "watch"
    follower = ShardDirectoryFollower(str(watch))
    # missing dir = nothing yet, not an error
    assert follower.poll() == []
    watch.mkdir()
    assert follower.poll() == []
    write_shard_csv(str(watch / "s0001.csv"),
                    continuous_shard_rows(4, seed=1))
    (watch / "notes.txt").write_text("not a shard")
    (watch / "subdir").mkdir()
    specs = follower.poll()
    assert [s.shard_id for s in specs] == [0]
    assert specs[0].path.endswith("s0001.csv") and specs[0].fmt == "csv"
    # consumed exactly once; an in-place overwrite is NOT re-read
    write_shard_csv(str(watch / "s0001.csv"),
                    continuous_shard_rows(4, seed=2))
    assert follower.poll() == []
    # new names keep the ids growing monotonically, in name order
    write_shard_csv(str(watch / "s0003.csv"),
                    continuous_shard_rows(4, seed=3))
    write_shard_csv(str(watch / "s0002.csv"),
                    continuous_shard_rows(4, seed=4))
    specs = follower.poll()
    assert [(s.shard_id, os.path.basename(s.path)) for s in specs] == [
        (1, "s0002.csv"), (2, "s0003.csv")]
    assert follower.shards_seen == 3


def test_follower_pinned_fmt_accepts_any_extension(tmp_path):
    follower = ShardDirectoryFollower(str(tmp_path), fmt="csv")
    write_shard_csv(str(tmp_path / "rows.dat"),
                    continuous_shard_rows(4, seed=1))
    specs = follower.poll()
    assert len(specs) == 1 and specs[0].fmt == "csv"


def test_follower_rides_the_pipeline_round_trip(tmp_path):
    """One poll's shards read through the real interleave/prefetch
    pipeline land as the exact rows the producer published."""
    import transmogrifai_tpu.dsl  # noqa: F401 - feature operators
    from transmogrifai_tpu.types import feature_types as ft

    rows = continuous_shard_rows(12, seed=5)
    write_shard_csv(str(tmp_path / "a.csv"), rows[:6])
    write_shard_csv(str(tmp_path / "b.csv"), rows[6:])
    follower = ShardDirectoryFollower(str(tmp_path))
    schema = {"y": ft.RealNN, "a": ft.Real, "c": ft.PickList}
    pipe = follower.pipeline(follower.poll(), schema, workers=2)
    cols = {n: c.to_list() for n, c in pipelined_columns(pipe).items()}
    assert len(cols["a"]) == 12
    assert cols["c"] == [r["c"] for r in rows]
    assert cols["a"] == pytest.approx([r["a"] for r in rows])
    # an empty poll yields no pipeline, not an empty-shard crash
    assert follower.pipeline([], schema) is None


# ---------------------------------------------------------------------------
# the windowed-merge seam: cumulative dilution bias, pinned
# ---------------------------------------------------------------------------
def test_cumulative_merge_dilutes_late_shift_windowed_reset_catches_it():
    """The satellite pin behind DriftMonitor.reset(): after enough
    baseline traffic the cumulative monoid merge waters a full
    distribution shift down below the warn threshold, while the same
    monitor reset() at the window boundary scores it at saturation -
    the bias that forces the continuous loop to be windowed."""
    wf, _data, records, _name = tiny_drill_pipeline(n=120, seed=0)
    model = wf.train()
    mon = DriftMonitor(model.schema_contract)
    base = records[:64]
    shifted = shift_records(base, "a", delta=30.0)
    for _ in range(19):
        mon.observe(base)
    mon.observe(shifted)  # a FULLY disjoint window, 5% of the mass
    diluted = mon.scores()["a"]
    assert diluted < mon.warn_threshold, (
        "the drill premise broke: the cumulative score noticed")
    # the windowed view of the exact same monitor: reset + one window
    mon.reset()
    assert mon.rows_observed("a") == 0 and mon.batches_observed == 0
    mon.observe(shifted)
    windowed = mon.scores()["a"]
    assert windowed > 0.9  # disjoint support: JS ~ 1.0
    assert windowed > 5 * diluted
    # reset clears the warned-once latch too: a fresh window re-alarms
    assert mon.reset() is mon


# ---------------------------------------------------------------------------
# direct-mode loop: detect -> refit -> publish -> stable pointer flip
# ---------------------------------------------------------------------------
def test_direct_mode_detects_shift_refits_and_promotes(tmp_path, capsys):
    trainer = _tiny_trainer(tmp_path)
    v1 = trainer.version
    assert v1 is not None and trainer.registry.stable == v1
    watch = trainer.watch_dir

    # idle poll: no shards, no governor window consumed
    c = trainer.run_cycle()
    assert c["verdict"] == "idle" and trainer.governor.windows == 0
    # a thin window judges nothing either way
    write_shard_csv(os.path.join(watch, "s0000.csv"),
                    continuous_shard_rows(8, seed=20))
    c = trainer.run_cycle()
    assert c["verdict"] == "thin" and trainer.governor.windows == 0
    # healthy window: clear
    write_shard_csv(os.path.join(watch, "s0001.csv"),
                    continuous_shard_rows(64, seed=21))
    c = trainer.run_cycle()
    assert c["verdict"] == "clear" and c["max_js"] < 0.35
    # two consecutive shifted windows: over, then trigger -> promote
    write_shard_csv(os.path.join(watch, "s0002.csv"),
                    continuous_shard_rows(64, seed=22, shift=3.0))
    assert trainer.run_cycle()["verdict"] == "over"
    write_shard_csv(os.path.join(watch, "s0003.csv"),
                    continuous_shard_rows(64, seed=23, shift=3.0))
    c = trainer.run_cycle()
    assert c["verdict"] == "trigger" and c["outcome"] == "promote"
    v2 = c["published"]
    assert v2 != v1
    assert trainer.registry.stable == v2 == trainer.version
    assert trainer.refits == 1 and trainer.promotes == 1
    # the refit became the drift baseline: its contract watches now
    assert trainer.model.schema_contract is not None
    assert trainer.monitor.contract is trainer.model.schema_contract

    # the whole trigger cycle rode ONE trace id
    from transmogrifai_tpu.obs import tracer

    names = {s["name"] for s in tracer().spans()
             if s.get("trace") == c["trace"]
             and str(s["name"]).startswith("continuous.")}
    assert {"continuous.cycle", "continuous.detect",
            "continuous.refit", "continuous.publish",
            "continuous.verdict"} <= names

    # the continuous view rides the obs scrape
    from transmogrifai_tpu.obs import (
        metrics_registry,
        prometheus_text_from_json,
    )

    text = prometheus_text_from_json(metrics_registry().to_json())
    assert "tx_continuous_cycles" in text
    assert "tx_continuous_refit_cache_hits" in text

    # the status file is the one consistent loop document ...
    doc = json.load(open(os.path.join(str(tmp_path), STATUS_FILENAME)))
    assert doc["mode"] == "direct"
    assert doc["stable_version"] == v2
    assert doc["counters"]["refits"] == 1
    assert doc["counters"]["promotes"] == 1
    assert doc["governor"]["triggers"] == 1
    assert doc["last_cycle"]["verdict"] == "trigger"
    assert doc["last_trace"] == c["trace"]
    # ... and `tx continuous status` renders it (dir or file path)
    from transmogrifai_tpu.cli import main as cli_main

    assert cli_main(["continuous", "status",
                     "--path", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["status"]["counters"]["refits"] == 1
    assert out["source"].endswith(STATUS_FILENAME)
    assert cli_main(["continuous", "status", "--path",
                     os.path.join(str(tmp_path), STATUS_FILENAME)]) == 0


def test_trainer_without_stable_requires_bootstrap(tmp_path):
    os.makedirs(tmp_path / "watch")
    with pytest.raises(ContinuousError, match="no stable"):
        ContinuousTrainer(str(tmp_path / "watch"),
                          str(tmp_path / "registry"), TINY_FACTORY)


def test_run_loop_exits_on_idle_and_max_cycles(tmp_path):
    trainer = _tiny_trainer(tmp_path)
    cycles = trainer.run(max_cycles=5, idle_exit=2, poll_interval_s=0.01)
    assert len(cycles) == 2  # two consecutive empty polls
    assert all(c["verdict"] == "idle" for c in cycles)
    write_shard_csv(os.path.join(trainer.watch_dir, "s0.csv"),
                    continuous_shard_rows(64, seed=30))
    cycles = trainer.run(max_cycles=1, poll_interval_s=0.01)
    assert len(cycles) == 1 and cycles[0]["rows"] == 64


# ---------------------------------------------------------------------------
# drift.false_positive: a forced trigger on a healthy stream
# ---------------------------------------------------------------------------
def test_false_positive_trigger_promotes_healthy_refit(tmp_path):
    """A spurious detection (operator page, broken alert) must not
    wedge or degrade anything: the forced refit is judged on its own
    merit - here (direct mode, healthy data) it simply promotes."""
    trainer = _tiny_trainer(tmp_path, drift_threshold=0.9,
                            consecutive_windows=3)
    v1 = trainer.version
    write_shard_csv(os.path.join(trainer.watch_dir, "s0.csv"),
                    continuous_shard_rows(64, seed=40))
    assert trainer.run_cycle()["verdict"] == "clear"
    faults.configure("drift.false_positive:on=1")
    write_shard_csv(os.path.join(trainer.watch_dir, "s1.csv"),
                    continuous_shard_rows(64, seed=41))
    c = trainer.run_cycle()
    faults.reset()
    # the window itself was healthy - only the forced flag triggered
    assert c["forced"] is True and c["max_js"] < 0.9
    assert c["verdict"] == "trigger" and c["outcome"] == "promote"
    assert trainer.forced_triggers == 1
    assert trainer.registry.stable == c["published"] != v1
    # burned: the next window is judged normally again
    write_shard_csv(os.path.join(trainer.watch_dir, "s2.csv"),
                    continuous_shard_rows(64, seed=42))
    c = trainer.run_cycle()
    assert c["forced"] is False and c["verdict"] in (
        "clear", "suppressed")


# ---------------------------------------------------------------------------
# continuous.refit_crash: kill between refit and publish
# ---------------------------------------------------------------------------
def test_refit_crash_leaves_old_stable_and_next_cycle_recovers(
        tmp_path):
    reg_root = str(tmp_path / "registry")
    watch = str(tmp_path / "watch")
    os.makedirs(watch)
    model = continuous_tiny_factory().train()
    v1 = ModelRegistry(reg_root).publish(model, stage="stable").version
    write_shard_csv(os.path.join(watch, "s0.csv"),
                    continuous_shard_rows(64, seed=50, shift=3.0))
    script = tmp_path / "crasher.py"
    script.write_text(CONTINUOUS_REFIT_CRASH_TEMPLATE.format(
        repo=REPO, watch=watch, root=reg_root,
        fault="continuous.refit_crash:on=1"))
    proc = subprocess.run([sys.executable, str(script)],
                          env=drill_env(), timeout=300)
    assert proc.returncode == faults.DEFAULT_KILL_EXIT  # really died
    # the refit died BEFORE publish: the registry never saw it
    reg = ModelRegistry(reg_root, create=False)
    assert reg.stable == v1
    assert reg.verify()["ok"]
    # next cycle (fresh daemon, same watch dir) recovers end to end:
    # the follower re-offers the shard, the refit completes, promotes
    trainer = ContinuousTrainer(
        watch, reg_root, TINY_FACTORY,
        drift_threshold=0.3, consecutive_windows=1, cooldown_windows=0,
        min_window_rows=8, refit_rows=256, train_fused=False)
    c = trainer.run_cycle()
    assert c["verdict"] == "trigger" and c["outcome"] == "promote"
    assert trainer.registry.stable == c["published"] != v1


# ---------------------------------------------------------------------------
# the `continuous` run type on the workflow runner
# ---------------------------------------------------------------------------
def test_runner_continuous_run_type_bootstraps_and_reports(tmp_path):
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    watch = str(tmp_path / "watch")
    os.makedirs(watch)
    write_shard_csv(os.path.join(watch, "s0.csv"),
                    continuous_shard_rows(64, seed=60, shift=3.0))
    wf = continuous_tiny_factory()
    r = OpWorkflowRunner(wf).run("continuous", OpParams(
        model_location=str(tmp_path / "model"),
        metrics_location=str(tmp_path / "metrics"),
        custom_params={
            "watch_dir": watch,
            "drift_threshold": 0.3,
            "drift_consecutive": 1,
            "drift_cooldown": 0,
            "continuous_window_rows": 32,
            "continuous_refit_rows": 256,
            "continuous_max_cycles": 3,
            "continuous_idle_exit": 1,
            "continuous_poll_s": 0.01,
            "train_fused": False,
        }))
    assert r.run_type == "continuous"
    m = r.metrics
    assert m["run_type"] == "continuous" and m["mode"] == "direct"
    # bootstrap published v1 from the runner's workflow, then the
    # shifted shard refit-promoted on top of it
    assert m["counters"]["refits"] >= 1
    assert m["counters"]["promotes"] >= 1
    reg = ModelRegistry(os.path.join(str(tmp_path / "model"),
                                     "registry"), create=False)
    assert reg.stable is not None and reg.verify()["ok"]
    saved = json.load(open(os.path.join(
        str(tmp_path / "metrics"), "continuous_metrics.json")))
    assert saved["counters"]["cycles"] == m["counters"]["cycles"]
    # the status file landed in metrics_location (the runner default)
    assert os.path.exists(os.path.join(str(tmp_path / "metrics"),
                                       STATUS_FILENAME))


def test_runner_continuous_requires_watch_dir():
    from transmogrifai_tpu.workflow.params import OpParams
    from transmogrifai_tpu.workflow.runner import OpWorkflowRunner

    with pytest.raises(ValueError, match="watch_dir"):
        OpWorkflowRunner(continuous_tiny_factory()).run(
            "continuous", OpParams(model_location="/tmp/x"))


# ---------------------------------------------------------------------------
# E2E acceptance: shift -> detect -> WARM refit -> canary -> promote on
# a live fleet, old model serving throughout, zero dropped rows
# ---------------------------------------------------------------------------
def test_continuous_e2e_fleet_shift_warm_refit_canary_promote(
        tmp_path, monkeypatch):
    from transmogrifai_tpu.fleet import FleetController
    from transmogrifai_tpu.obs.slo import SLObjective

    # the conftest provisions an 8-device CPU mesh; with the CV product
    # mesh live the fused-train gate defers to it (reason "mesh"), so
    # pin the single-process fused path the same way test_fused_train
    # does - the drill is about the WARM cache, not mesh scheduling
    monkeypatch.setenv("TX_PRODUCT_MESH", "0")

    reg_root = str(tmp_path / "registry")
    cache = str(tmp_path / "train_xla_cache")
    watch = str(tmp_path / "watch")
    os.makedirs(watch)
    n_train = 256

    # seed v1 COLD in a child process: this process's in-process fused
    # program registry stays empty, so the daemon's refit below can
    # only be warm via DISK rehydration from train_xla_cache/
    seed_src = CONTINUOUS_SEED_TRAINER_TEMPLATE.format(
        repo=REPO, n=n_train, seed=0, cache_dir=cache, root=reg_root)
    proc = subprocess.run(
        [sys.executable, "-c", seed_src], env=drill_env(),
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    seeded = [ln for ln in proc.stdout.splitlines()
              if ln.startswith("SEEDED")][0].split(" ", 2)
    v1, seed_trail = seeded[1], json.loads(seeded[2])
    seed_fam = seed_trail["families"]["OpLogisticRegression"]
    assert seed_fam["cache"] == "miss" and seed_fam["compile_ms"] > 0
    assert os.listdir(cache), "the seed left no AOT cache to rehydrate"

    batch_base = [{k: r[k] for k in ("a", "c")}
                  for r in continuous_shard_rows(40, seed=99)]
    batch_shifted = [{k: r[k] for k in ("a", "c")}
                     for r in continuous_shard_rows(40, seed=98,
                                                    shift=3.0)]
    # the pump serves whatever the stream currently looks like: the
    # mid-stream shift moves the LIVE traffic too (that is the drill -
    # the canary is judged on the shifted traffic it will actually see)
    current = {"batch": batch_base}
    results: list = []
    errors: list = []
    stop = threading.Event()
    # the fleet SLO wired into the rollback policy is a HEALTH signal
    # (NaN-guard refusals), not the default drift SLO: during a genuine
    # distribution shift the fleet-wide drift objective fires BECAUSE
    # the stable arm is drowning in the new traffic - the very signal
    # that triggered the refit - and would veto its own correction
    # (docs/continuous.md documents the scoping rule)
    health_slo = SLObjective(
        name="fleet-nonfinite", kind="threshold",
        metric="serving.breaker.rows_nonfinite", objective=0.5,
        windows_s=(30.0, 5.0))
    with FleetController(
        reg_root, DRILL_FACTORY, n_replicas=2,
        work_dir=str(tmp_path / "fleet"), ship_interval_s=0.15,
        slo_objectives=[health_slo],
        router_kw={"max_in_flight_per_replica": 2, "max_queue": 64},
    ) as fc:
        fc.router.score_batch(batch_base, timeout_s=120.0)  # warm

        def pump() -> None:
            while not stop.is_set():
                try:
                    results.append(fc.router.submit(
                        records=current["batch"]).wait(120.0))
                except Exception as e:  # noqa: BLE001 - the drill counts
                    errors.append(repr(e))

        threads = [threading.Thread(target=pump) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            trainer = ContinuousTrainer(
                watch, reg_root, DRILL_FACTORY, fleet=fc,
                status_dir=str(tmp_path),
                drift_threshold=0.4, consecutive_windows=4,
                cooldown_windows=2, min_window_rows=64,
                refit_rows=n_train, train_fused=True,
                train_cache_dir=cache, canary_fraction=0.5,
                canary_min_rows=48, canary_timeout_s=120.0)
            assert trainer.version == v1
            # window 1: the stream looks like training - clear
            write_shard_csv(os.path.join(watch, "s0000.csv"),
                            continuous_shard_rows(64, seed=10))
            c = trainer.run_cycle()
            assert c["verdict"] == "clear", c
            # the distribution SHIFTS mid-stream - shards AND live
            # traffic; the hysteresis holds for three over-threshold
            # windows, then trips on the fourth.  By then the bounded
            # buffer holds the last n_train rows = ALL shifted, the
            # seed's exact shape bucket, so the refit both rehydrates
            # the seeded executables and models the traffic its canary
            # is about to be judged on.
            current["batch"] = batch_shifted
            for i in range(1, 4):
                write_shard_csv(
                    os.path.join(watch, f"s{i:04d}.csv"),
                    continuous_shard_rows(64, seed=10 + i, shift=3.0))
                c = trainer.run_cycle()
                assert c["verdict"] == "over", c
            write_shard_csv(os.path.join(watch, "s0004.csv"),
                            continuous_shard_rows(64, seed=14,
                                                  shift=3.0))
            c = trainer.run_cycle()
            assert c["verdict"] == "trigger", c
            assert c["outcome"] == "promote", c
            time.sleep(0.4)  # let the promoted arm serve some batches
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=120.0)

        v2 = c["published"]
        assert v2 != v1 and fc.registry.stable == v2
        assert trainer.version == v2

        # WARM refit: executables rehydrated from the child-seeded
        # disk cache - zero compile, nonzero load, same shape bucket
        fam = c["refit"]["train_fused"]["families"][
            "OpLogisticRegression"]
        assert fam["cache"] == "hit", fam
        assert fam["load_ms"] > 0 and fam["compile_ms"] == 0, fam
        assert fam["bucket"] == seed_fam["bucket"]
        assert trainer.refit_cache["hits"] >= 1
        assert c["refit"]["rows"] == n_train

        # zero dropped rows; exact conservation, double-entry
        assert errors == []
        assert all(res.n_rows == len(batch_base) for res in results)
        snap = fc.router.snapshot()
        assert snap["rows_ok"] == (sum(r.n_rows for r in results)
                                   + len(batch_base))  # + warm batch

        # the superseded model served THROUGHOUT: every response names
        # a version, v1 until the pointer flip, v2 after
        versions = {res.version for res in results}
        assert None not in versions
        assert versions <= {v1, v2}
        assert v1 in versions
        # the canary actually scored shadow traffic before the verdict
        assert c["canary_rows"] >= 48

    # ONE trace id spans the whole promoted cycle: detect, refit,
    # publish, canary, verdict
    from transmogrifai_tpu.obs import tracer

    names = {s["name"] for s in tracer().spans()
             if s.get("trace") == c["trace"]
             and str(s["name"]).startswith("continuous.")}
    assert {"continuous.cycle", "continuous.detect",
            "continuous.refit", "continuous.publish",
            "continuous.canary", "continuous.verdict"} <= names
    # and the status file carries the same story
    doc = json.load(open(os.path.join(str(tmp_path), STATUS_FILENAME)))
    assert doc["mode"] == "fleet"
    assert doc["counters"]["promotes"] == 1
    assert doc["counters"]["refit_cache_hits"] == 1
    assert doc["last_trace"] == c["trace"]
