"""ModelSelector / validator / splitter tests (mirrors reference:
core/src/test/.../impl/selector/ModelSelectorTest.scala, tuning/*Test)."""
import numpy as np
import pytest

from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.models.trees import OpRandomForestClassifier
from transmogrifai_tpu.selector.factories import (
    BinaryClassificationModelSelector,
    lr_grid,
)
from transmogrifai_tpu.selector.splitters import DataBalancer, DataCutter
from transmogrifai_tpu.selector.validator import (
    OpCrossValidation,
    stratified_kfold_masks,
)
from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import NumericColumn, VectorColumn
from transmogrifai_tpu.types.vector_metadata import VectorColumnMeta, VectorMetadata


def test_stratified_folds_cover_and_balance(rng):
    y = (rng.rand(300) < 0.2).astype(float)
    masks = stratified_kfold_masks(y, 3, seed=1, stratify=True)
    assert masks.shape == (3, 300)
    # every row is in exactly 2 of 3 train splits
    assert (masks.sum(axis=0) == 2).all()
    for f in range(3):
        val = ~masks[f]
        frac = y[val].mean()
        assert abs(frac - 0.2) < 0.07


def test_data_balancer_weights(rng):
    y = (rng.rand(1000) < 0.05).astype(float)
    prep = DataBalancer(sample_fraction=0.3).prepare(y)
    w = prep.weights
    pos_frac = (w * (y == 1)).sum() / w.sum()
    assert abs(pos_frac - 0.3) < 0.01
    assert prep.summary["upSampled"]


def test_data_cutter_drops_rare_labels(rng):
    y = np.concatenate([np.zeros(500), np.ones(480), np.full(20, 2.0)])
    prep = DataCutter(min_label_fraction=0.05).prepare(y)
    assert prep.keep_mask is not None
    assert set(np.unique(y[prep.keep_mask])) == {0.0, 1.0}
    assert prep.summary["labelsDropped"] == [2.0]


def _binary_vec_dataset(rng, n=400, d=6):
    X = rng.randn(n, d)
    beta = np.linspace(2, -2, d)
    y = (rng.rand(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(float)
    meta = VectorMetadata(
        "features",
        tuple(
            VectorColumnMeta(parent_feature_name=f"x{i}", parent_feature_type="Real")
            for i in range(d)
        ),
    ).reindexed()
    label_f = FeatureBuilder(ft.RealNN, "label").as_response()
    ds = Dataset(
        {
            "label": NumericColumn(y, np.ones(n, dtype=bool), ft.RealNN),
            "features": VectorColumn(X, meta),
        }
    )
    vec_f = FeatureBuilder(ft.OPVector, "features").as_predictor()
    return ds, label_f, vec_f, y


def test_cross_validation_picks_best_and_writes_summary(rng):
    ds, label_f, vec_f, y = _binary_vec_dataset(rng)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[
            (OpLogisticRegression(), lr_grid()[:4]),
            (OpRandomForestClassifier(num_trees=5, max_depth=3), [{}]),
        ],
    )
    selector.set_input(label_f, vec_f)
    model = selector.fit(ds)
    md = model.metadata["model_selector_summary"]
    assert md["best_model_type"] in ("OpLogisticRegression", "OpRandomForestClassifier")
    # linear data -> LR should win
    assert md["best_model_type"] == "OpLogisticRegression"
    assert len(md["validation_results"]) == 5
    assert md["validation_metric"]["name"] == "AuROC"
    assert md["validation_metric"]["value"] > 0.85
    out = model.transform(ds)
    pc = out[model.output_name]
    assert pc.probability is not None

    # holdout evaluation path (has_test_eval)
    metrics = model.evaluate_model(ds.take(np.arange(50)))
    assert "OpBinaryClassificationEvaluator" in metrics


def test_batched_cv_matches_loop_cv(rng):
    """The vmapped fold x grid fan-out must agree with per-candidate loops."""
    ds, label_f, vec_f, y = _binary_vec_dataset(rng, n=300, d=4)
    X = np.asarray(ds["features"].values, dtype=np.float64)
    grid = [{"reg_param": r, "elastic_net_param": 0.0} for r in (0.001, 0.1)]
    ev = OpBinaryClassificationEvaluator()
    cv = OpCrossValidation(num_folds=3, evaluator=ev, seed=7, stratify=True)
    res_batched = cv.validate([(OpLogisticRegression(), grid)], X, y)

    class NoBatch(OpLogisticRegression):
        fit_arrays_batched = property()  # hide the batched path

    cv2 = OpCrossValidation(num_folds=3, evaluator=ev, seed=7, stratify=True)
    res_loop = cv2.validate([(NoBatch(), grid)], X, y)
    for a, b in zip(res_batched.all_results, res_loop.all_results):
        assert a["metric"] == pytest.approx(b["metric"], abs=2e-3)


def test_databalancer_weight_algebra_properties():
    """DataBalancer edge cases (reference DataBalancer.scala:45-90; the
    TPU redesign expresses resampling as sample weights): the reweighted
    positive fraction hits the target exactly; already-balanced and
    degenerate label sets pass through; the size cap uniformly
    down-weights."""
    import numpy as np

    from transmogrifai_tpu.selector.splitters import DataBalancer

    # 2% positives, target 10%: weighted positive fraction == target
    rng = np.random.RandomState(0)
    y = (rng.rand(5000) < 0.02).astype(float)
    prep = DataBalancer(sample_fraction=0.1).prepare(y)
    w = prep.weights
    wp = w[y == 1].sum() / w.sum()
    assert abs(wp - 0.1) < 1e-9
    assert prep.summary["upSampled"] and not prep.summary["downSampled"]

    # already above the target: untouched
    y2 = (rng.rand(1000) < 0.4).astype(float)
    prep2 = DataBalancer(sample_fraction=0.1).prepare(y2)
    assert (prep2.weights == 1.0).all()
    assert not prep2.summary["upSampled"]

    # single-class labels: no reweighting, no NaN
    prep3 = DataBalancer(sample_fraction=0.1).prepare(np.ones(50))
    assert np.isfinite(prep3.weights).all() and (prep3.weights == 1.0).all()

    # size cap: effective sample (sum of weights) respects the maximum
    prep4 = DataBalancer(
        sample_fraction=0.1, max_training_sample=100
    ).prepare((rng.rand(1000) < 0.3).astype(float))
    assert prep4.weights.sum() <= 100 + 1e-9
    assert prep4.summary["downSampled"]


def test_datacutter_label_curation_properties():
    """DataCutter edge cases (reference DataCutter.scala:48-141): the
    min-fraction floor and the top-K cap compose; kept+dropped partition
    the label set; the keep mask matches the summary counts."""
    import numpy as np

    from transmogrifai_tpu.selector.splitters import DataCutter

    y = np.array([0.0] * 500 + [1.0] * 300 + [2.0] * 150 + [3.0] * 45
                 + [4.0] * 5)
    prep = DataCutter(min_label_fraction=0.02).prepare(y)
    assert prep.summary["labelsDropped"] == [4.0]  # 0.5% < 2%
    assert prep.keep_mask.sum() == len(y) - 5

    prep2 = DataCutter(max_label_categories=2).prepare(y)
    assert prep2.summary["labelsKept"] == [0.0, 1.0]
    assert prep2.summary["rowsDropped"] == 200

    # kept + dropped partition the distinct labels
    all_labels = {0.0, 1.0, 2.0, 3.0, 4.0}
    for p in (prep, prep2):
        kept = set(p.summary["labelsKept"])
        dropped = set(p.summary["labelsDropped"])
        assert kept | dropped == all_labels and not kept & dropped
