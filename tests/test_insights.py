"""ModelInsights + LOCO + correlation record insights tests (reference:
ModelInsightsTest, RecordInsightsLOCOTest)."""
import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401
from transmogrifai_tpu import Dataset, FeatureBuilder, OpWorkflow
from transmogrifai_tpu.insights.loco import RecordInsightsCorr, RecordInsightsLOCO
from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.types import feature_types as ft


@pytest.fixture
def fitted(rng):
    n = 300
    data = {
        "y": [],
        "strong": [],
        "weak": [],
    }
    strong = rng.randn(n)
    weak = rng.randn(n)
    y = (strong + 0.1 * weak + 0.3 * rng.randn(n) > 0).astype(float)
    data = {"y": y.tolist(), "strong": strong.tolist(), "weak": weak.tolist()}
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fs = FeatureBuilder(ft.Real, "strong").as_predictor()
    fw = FeatureBuilder(ft.Real, "weak").as_predictor()
    vec = transmogrify([fs, fw])
    pred = OpLogisticRegression(reg_param=0.01).set_input(fy, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    model = wf.train()
    return model, vec, pred


def test_model_insights_pretty_and_json(fitted):
    model, vec, pred = fitted
    ins = model.model_insights()
    j = ins.to_json()
    assert j["feature_insights"] == []  # no sanity checker in this flow
    text = ins.pretty()
    assert isinstance(text, str)


def test_loco_ranks_strong_feature(fitted):
    model, vec, pred = fitted
    predictor_model = next(
        s for s in model.stages if hasattr(s, "model_params")
    )
    scored = model.score()
    loco = RecordInsightsLOCO(predictor_model, top_k=4).set_input(vec)
    out = loco.transform(scored)[loco.output_name]
    row = out.values[0]
    # the 'strong' value column should dominate |delta| for most rows
    n_dominant = 0
    for r in out.values:
        top_name = max(r, key=lambda k: abs(r[k]))
        if "strong" in top_name:
            n_dominant += 1
    assert n_dominant > len(out.values) * 0.7


def test_corr_insights_agree_with_loco_direction(fitted):
    model, vec, pred = fitted
    predictor_model = next(
        s for s in model.stages if hasattr(s, "model_params")
    )
    scored = model.score()
    corr = RecordInsightsCorr(predictor_model, top_k=4).set_input(vec)
    out = corr.transform(scored)[corr.output_name]
    n_dominant = sum(
        1
        for r in out.values
        if "strong" in max(r, key=lambda k: abs(r[k]))
    )
    assert n_dominant > len(out.values) * 0.7


def test_loco_detailed_format_round_trips(fitted):
    """detailed=True emits the reference's serialized insight map
    ({column-history-json: [[pred_idx, delta]] json}, RecordInsightsParser
    contract) and parse_insights recovers structure + values."""
    from transmogrifai_tpu.insights.loco import parse_insights

    model, vec, pred = fitted
    pred_stage = next(
        s for s in model.stages if hasattr(s, "model_params")
    )
    scored = model.score()
    loco_plain = RecordInsightsLOCO(pred_stage, top_k=3).set_input(vec)
    loco_det = RecordInsightsLOCO(pred_stage, top_k=3,
                                  detailed=True).set_input(vec)
    plain = loco_plain.transform(scored)[loco_plain.output_name].values
    det = loco_det.transform(scored)[loco_det.output_name].values
    for row_plain, row_det in zip(plain, det):
        parsed = parse_insights(row_det)
        assert len(parsed) == len(row_plain)
        for history, scores in parsed:
            assert "columnName" in history
            # the full per-class diff vector rides along (binary -> 2)
            assert [c for c, _ in scores] == [0, 1]
            # class-1 delta == the plain format's value; class 0 mirrors
            assert scores[1][1] == pytest.approx(
                row_plain[history["columnName"]])
            assert scores[0][1] == pytest.approx(-scores[1][1], abs=1e-5)


@pytest.mark.parametrize("family", ["auto", "ovr"])
def test_loco_on_multiclass_lr(rng, family):
    """Record insights over multiclass LR - family='auto' exercises the
    round-5 multinomial softmax model (jointly-normalized per-class
    probabilities), 'ovr' the one-vs-rest route: LOCO deltas must exist,
    rank the informative feature first, and the detailed per-class format
    must carry one delta per class (RecordInsightsLOCO.scala per-class
    score diffs)."""
    from transmogrifai_tpu.insights.loco import parse_insights

    n = 300
    centers = np.array([[2.5, 0.0], [-2.5, 1.0], [0.0, -3.0]])
    yv = np.repeat(np.arange(3.0), n // 3)
    strong = centers[yv.astype(int), 0] + 0.4 * rng.randn(n)
    weak = rng.randn(n)
    data = {"y": yv.tolist(), "strong": strong.tolist(),
            "weak": weak.tolist()}
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fs = FeatureBuilder(ft.Real, "strong").as_predictor()
    fw = FeatureBuilder(ft.Real, "weak").as_predictor()
    vec = transmogrify([fs, fw])
    pred = OpLogisticRegression(
        reg_param=0.01, family=family
    ).set_input(fy, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    model = wf.train()
    predictor_model = model.stages[-1]
    expect_family = "multinomial" if family == "auto" else "ovr"
    assert predictor_model.model_params["family"] == expect_family

    scored = model.score(data)
    loco = RecordInsightsLOCO(predictor_model, top_k=4).set_input(vec)
    out = loco.transform(scored)[loco.output_name]
    # the strong feature's column dominates in most rows
    top_hits = 0
    for row in out.values[:50]:
        top_col = max(row, key=lambda k: abs(row[k]))
        if "strong" in top_col:
            top_hits += 1
    assert top_hits > 35, top_hits

    detailed = RecordInsightsLOCO(
        predictor_model, top_k=4, detailed=True
    ).set_input(vec)
    dout = detailed.transform(scored)[detailed.output_name]
    parsed = parse_insights(dout.values[0])
    # per-class deltas: 3 classes -> 3 (prediction_index, delta) pairs
    assert all(len(deltas) == 3 for _, deltas in parsed)


def test_model_insights_label_summary_and_stage_info(rng):
    """Round-5 parity fields (ModelInsights.scala:72-79, 291-323): the
    label's own summary (name, lineage, sample size, Discrete/Continuous
    distribution) and per-stage settings keyed by uid."""
    n = 120
    yv = np.repeat([0.0, 1.0], n // 2)
    data = {"y": yv.tolist(), "a": rng.randn(n).tolist()}
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fa = FeatureBuilder(ft.Real, "a").as_predictor()
    vec = transmogrify([fa])
    pred = OpLogisticRegression(reg_param=0.01).set_input(fy, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    ins = model.model_insights()

    ls = ins.label_summary
    assert ls["label_name"] == "y"
    assert "y" in ls["raw_feature_names"]
    assert ls["sample_size"] == n
    assert ls["distribution"]["type"] == "discrete"
    assert ls["distribution"]["domain"] == ["0.0", "1.0"]
    assert ls["distribution"]["prob"] == pytest.approx([0.5, 0.5])

    si = ins.stage_info
    assert len(si) >= 2  # vectorizer + predictor at minimum
    pred_uid = model.stages[-1].uid
    assert si[pred_uid]["class"] == "OpLogisticRegression"
    assert si[pred_uid]["params"]["reg_param"] == 0.01
    assert "y" in si[pred_uid]["inputs"]
    # the new fields survive the JSON report
    j = ins.to_json()
    assert j["label_summary"]["label_name"] == "y"
    assert pred_uid in j["stage_info"]


def test_model_insights_continuous_label_distribution(rng):
    """A regression label with >30 unique values reports the Continuous
    shape (min/max/mean/variance)."""
    n = 150
    yv = rng.randn(n) * 2.0 + 1.0
    data = {"y": yv.tolist(), "a": rng.randn(n).tolist()}
    fy = FeatureBuilder(ft.RealNN, "y").as_response()
    fa = FeatureBuilder(ft.Real, "a").as_predictor()
    vec = transmogrify([fa])
    from transmogrifai_tpu.models.linear_regression import OpLinearRegression

    pred = OpLinearRegression(reg_param=0.01).set_input(fy, vec).get_output()
    model = (
        OpWorkflow().set_result_features(pred).set_input_dataset(data).train()
    )
    d = model.model_insights().label_summary["distribution"]
    assert d["type"] == "continuous"
    assert d["min"] == pytest.approx(yv.min())
    assert d["mean"] == pytest.approx(yv.mean(), abs=1e-9)


def test_model_insights_loaded_model_label_stats_honest(tmp_path, rng):
    """A model restored via load_model has no training cache: the label
    summary keeps name/lineage but marks the distribution unavailable
    instead of pretending (review r5)."""
    from transmogrifai_tpu.workflow.workflow import OpWorkflowModel

    n = 100
    yv = np.repeat([0.0, 1.0], n // 2)
    data = {"y": yv.tolist(), "a": rng.randn(n).tolist()}

    def build():
        fy = FeatureBuilder(ft.RealNN, "y").as_response()
        fa = FeatureBuilder(ft.Real, "a").as_predictor()
        vec = transmogrify([fa])
        pred = (
            OpLogisticRegression(reg_param=0.01)
            .set_input(fy, vec).get_output()
        )
        return OpWorkflow().set_result_features(pred).set_input_dataset(data)

    m1 = build().train()
    m1.save(str(tmp_path / "m"))
    m2 = OpWorkflowModel.load(str(tmp_path / "m"), build())
    ls = m2.model_insights().label_summary
    assert ls["label_name"] == "y"
    assert "distribution" not in ls
    assert "distribution_unavailable" in ls
