"""Avro OCF round-trip fuzz over the full 10-type random schema.

The golden Avro tests pin fixed fixtures; this drives the writer/reader
pair (schema_for_dataset -> write -> read_avro_records) through random
nullable data covering maps, ragged date lists, geolocations, and
multipicklists - the union-branching surface where a decoder bug
corrupts silently.
"""
from __future__ import annotations

import numpy as np
import pytest

from transmogrifai_tpu.readers.avro_reader import (
    read_avro_records,
    save_dataset_avro,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import column_from_list
from transmogrifai_tpu.types.dataset import Dataset

from tests.test_workflow_fuzz import _features, _random_data


@pytest.mark.parametrize("seed,p_null", [(51, 0.1), (52, 0.4)])
def test_avro_roundtrip_fuzz(tmp_path, seed, p_null):
    rng = np.random.RandomState(seed)
    n = 60
    data = _random_data(rng, n, p_null)
    ds = Dataset({
        f.name: column_from_list(data[f.name], f.ftype) for f in _features()
    })
    path = str(tmp_path / "fuzz.avro")
    count = save_dataset_avro(ds, path)
    assert count == n
    _, records = read_avro_records(path)
    assert len(records) == n
    cols = {name: ds[name].to_list() for name in ds.column_names()}
    for i, rec in enumerate(records):
        for name in cols:
            want = cols[name][i]
            got = rec.get(name)
            if want is None or (isinstance(want, (list, dict, set))
                                and not want):
                assert got in (None, [], {}), (name, i, got)
                continue
            if name == "site":  # geo triple
                assert got is not None
                np.testing.assert_allclose(
                    np.asarray(got, dtype=float),
                    np.asarray(want, dtype=float), rtol=1e-9)
            elif name == "attrs":  # real map
                assert got is not None
                assert set(got) == set(want)
                for k in want:
                    assert got[k] == pytest.approx(want[k])
            elif name == "tags":  # multipicklist -> list on disk
                assert sorted(got) == sorted(want)
            elif name == "visits":  # ragged ms list
                np.testing.assert_allclose(
                    np.asarray(got, dtype=float),
                    np.asarray(want, dtype=float), rtol=0, atol=0.5)
            elif isinstance(want, float):
                assert got == pytest.approx(want)
            else:
                assert got == want, (name, i, got, want)
