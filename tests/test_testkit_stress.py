"""Testkit-driven stress of the vectorizer library.

The reference uses its testkit to pound every vectorizer with controlled
nulls (testkit/.../RandomData.scala consumers); VERDICT r2 flagged that
our generators existed but barely exercised the library.  This sweep runs
every transmogrify-able feature type x probability_of_empty in
{0, 0.3, 0.9, 1.0} through the full transmogrify -> train -> score path
and asserts structural invariants:

* output is a finite [n, d] vector with coherent metadata,
* null-indicator columns (track_nulls) count EXACTLY the generated Nones,
* all-empty columns still fit and score (no NaNs, no crashes),
* scoring unseen testkit data keeps width and finiteness.
"""
import numpy as np
import pytest

import transmogrifai_tpu.dsl  # noqa: F401
from transmogrifai_tpu import FeatureBuilder, OpWorkflow
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.testkit.random_data import (
    InfiniteStream,
    default_generator,
    random_dataset,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import VectorColumn

N = 120

# every type the Transmogrifier dispatches (one representative per branch)
STRESS_TYPES = [
    ft.Real, ft.Integral, ft.Binary, ft.Date, ft.PickList, ft.Text,
    ft.Email, ft.MultiPickList, ft.Geolocation, ft.TextList,
    ft.RealMap, ft.PickListMap, ft.BinaryMap,
]


@pytest.mark.parametrize("p_empty", [0.0, 0.3, 0.9, 1.0])
@pytest.mark.parametrize("t", STRESS_TYPES, ids=lambda t: t.__name__)
def test_vectorizer_survives_null_sweep(t, p_empty):
    gen = default_generator(t, seed=11, probability_of_empty=p_empty)
    values = gen.limit(N)
    n_none = sum(v is None for v in values)
    data = {"x": values}
    f = FeatureBuilder(t, "x").as_predictor()
    vec = transmogrify([f])
    wf = OpWorkflow().set_result_features(vec).set_input_dataset(data)
    model = wf.train()
    col = model.score(data)[vec.name]
    assert isinstance(col, VectorColumn)
    assert len(col) == N
    if t.kind == "map" and p_empty == 1.0:
        # all-empty maps have no keys to expand: a 0-width vector is the
        # correct degenerate output (same as the reference's key pivot)
        assert col.width == 0
        return
    assert col.width > 0
    assert col.metadata.size == col.width
    assert np.isfinite(col.values).all(), (
        f"{t.__name__} p_empty={p_empty} produced non-finite outputs"
    )
    # track-null contract: a whole-feature null-indicator column must count
    # exactly the generated Nones (maps track per-key, so exempt)
    if t.kind != "map":
        null_cols = [
            i for i, c in enumerate(col.metadata.columns)
            if c.is_null_indicator and (c.grouping in (None, "x"))
        ]
        if null_cols and t.kind in ("numeric", "text"):
            counted = int(col.values[:, null_cols].sum())
            assert counted == n_none, (
                f"{t.__name__} p_empty={p_empty}: null indicator counted "
                f"{counted}, generated {n_none}"
            )
    # scoring UNSEEN testkit data keeps the fitted width
    data2 = {"x": default_generator(t, seed=99,
                                    probability_of_empty=0.5).limit(N)}
    col2 = model.score(data2)[vec.name]
    assert col2.width == col.width
    assert np.isfinite(col2.values).all()


def test_selector_on_testkit_mixed_dataset(rng):
    """Full AutoML path on a testkit-joined mixed-type dataset with nulls:
    transmogrify 6 typed features -> sanity check -> LR selection."""
    from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression
    from transmogrifai_tpu.selector.factories import (
        BinaryClassificationModelSelector,
    )

    n = 300
    gens = {
        "r": (default_generator(ft.Real, 1, 0.2), ft.Real),
        "i": (default_generator(ft.Integral, 2, 0.2), ft.Integral),
        "p": (default_generator(ft.PickList, 3, 0.2), ft.PickList),
        "t": (default_generator(ft.Text, 4, 0.2), ft.Text),
        "m": (default_generator(ft.RealMap, 5, 0.2), ft.RealMap),
        "g": (default_generator(ft.Geolocation, 6, 0.2), ft.Geolocation),
    }
    ds = random_dataset(gens, n)
    r_col = ds["r"]
    y = ((np.asarray(r_col.values) > 0) & np.asarray(r_col.mask)).astype(
        float
    )
    data = {name: ds[name].to_list() for name in ds}
    data["y"] = y.tolist()

    yf = FeatureBuilder(ft.RealNN, "y").as_response()
    feats = [FeatureBuilder(t, name).as_predictor()
             for name, (_, t) in gens.items()]
    vec = transmogrify(feats)
    checked = yf.sanity_check(vec, remove_bad_features=True)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[(OpLogisticRegression(), [{"reg_param": 0.01}])],
    )
    pred = sel.set_input(yf, checked).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    model = wf.train()
    scored = model.score(data)
    prob = scored[pred.name].probability
    assert np.isfinite(prob).all()
    md = model.stages[-1].metadata["model_selector_summary"]
    # r drives the label, so the fit must separate well despite 20% nulls
    assert md["validation_metric"]["value"] > 0.8


def test_infinite_stream_feeds_streaming_scorer(rng):
    """InfiniteStream batches drive the streaming-score run type."""
    from transmogrifai_tpu.models.logistic_regression import OpLogisticRegression

    n = 200
    gens = {
        "a": (default_generator(ft.Real, 7), ft.Real),
        "b": (default_generator(ft.Real, 8), ft.Real),
    }
    ds = random_dataset(gens, n)
    y = (np.asarray(ds["a"].values) + np.asarray(ds["b"].values) > 0).astype(
        float
    )
    data = {"a": ds["a"].to_list(), "b": ds["b"].to_list(),
            "y": y.tolist()}
    yf = FeatureBuilder(ft.RealNN, "y").as_response()
    af = FeatureBuilder(ft.Real, "a").as_predictor()
    bf = FeatureBuilder(ft.Real, "b").as_predictor()
    vec = transmogrify([af, bf])
    pred = OpLogisticRegression(max_iter=5).set_input(yf, vec).get_output()
    wf = OpWorkflow().set_result_features(pred).set_input_dataset(data)
    model = wf.train()

    stream = InfiniteStream(
        {**gens, "y": (default_generator(ft.Binary, 9), ft.RealNN)},
        batch_size=50,
    )
    total = 0
    for batch in stream.take(4):
        out = model.score({name: batch[name].to_list() for name in batch})
        total += len(out[pred.name])
    assert total == 200
    # determinism: a fresh identically-seeded stream yields the same batches
    stream2 = InfiniteStream(
        {
            "a": (default_generator(ft.Real, 7), ft.Real),
            "b": (default_generator(ft.Real, 8), ft.Real),
            "y": (default_generator(ft.Binary, 9), ft.RealNN),
        },
        batch_size=50,
    )
    b1 = stream2.next_batch()
    assert np.allclose(
        b1["a"].values,
        np.asarray(default_generator(ft.Real, 7).limit(50), float),
    )
